"""Native C++ broker tests: exact semantic parity with the Python broker
(the Transport contract the PS protocol relies on), plus GIL-free blocking
behavior. Mirrors TestInProc in test_transport.py — same contract, other
implementation (SURVEY.md §2 comp. 1: the reference's native binding had no
tests at all; its TPU equivalent does)."""

import threading
import time

import numpy as np
import pytest

import mpit_tpu.native as native
from mpit_tpu.transport import ANY_SOURCE, ANY_TAG, RecvTimeout

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="no C++ toolchain and no prebuilt lib"
)


@pytest.fixture
def b3():
    broker = native.NativeBroker(3)
    yield broker
    broker.close()


class TestNativeBrokerParity:
    def test_send_recv_roundtrip(self, b3):
        tps = b3.transports()
        payload = np.arange(5.0)
        tps[0].send(1, tag=7, payload=payload)
        msg = tps[1].recv(src=0, tag=7, timeout=1)
        np.testing.assert_array_equal(msg.payload, payload)
        assert msg.src == 0 and msg.tag == 7 and msg.dst == 1

    def test_per_src_tag_fifo_order(self, b3):
        tps = b3.transports()
        for i in range(50):
            tps[0].send(1, tag=3, payload=i)
        got = [tps[1].recv(0, 3, timeout=1).payload for _ in range(50)]
        assert got == list(range(50))

    def test_any_source_any_tag(self, b3):
        tps = b3.transports()
        tps[0].send(2, tag=1, payload="from0")
        tps[1].send(2, tag=9, payload="from1")
        first = tps[2].recv(ANY_SOURCE, ANY_TAG, timeout=1)
        second = tps[2].recv(ANY_SOURCE, ANY_TAG, timeout=1)
        assert {first.payload, second.payload} == {"from0", "from1"}

    def test_tag_selective_recv_leaves_others_queued(self, b3):
        tps = b3.transports()
        tps[0].send(1, tag=1, payload="a")
        tps[0].send(1, tag=2, payload="b")
        assert tps[1].recv(ANY_SOURCE, 2, timeout=1).payload == "b"
        assert tps[1].recv(ANY_SOURCE, 1, timeout=1).payload == "a"

    def test_probe(self, b3):
        tps = b3.transports()
        assert not tps[1].probe()
        tps[0].send(1, tag=4, payload=None)
        assert tps[1].probe(src=0, tag=4)
        assert not tps[1].probe(src=0, tag=5)

    def test_recv_timeout_raises(self, b3):
        with pytest.raises(RecvTimeout):
            b3.transports()[1].recv(timeout=0.05)

    def test_blocking_recv_wakes_on_send(self, b3):
        tps = b3.transports()
        out = {}

        def receiver():
            out["msg"] = tps[1].recv(timeout=5)

        t = threading.Thread(target=receiver)
        t.start()
        time.sleep(0.05)
        tps[0].send(1, tag=0, payload="wake")
        t.join(timeout=5)
        assert out["msg"].payload == "wake"

    def test_isend_irecv_wait(self, b3):
        tps = b3.transports()
        h = tps[0].isend(1, tag=1, payload=123)
        h.wait(timeout=1)
        r = tps[1].irecv(src=0, tag=1)
        assert r.wait(timeout=1).payload == 123

    def test_bad_dst_raises(self, b3):
        with pytest.raises(ValueError, match="out of range"):
            b3.transports()[0].send(5, tag=0, payload=None)

    def test_none_payload(self, b3):
        tps = b3.transports()
        tps[0].send(1, tag=2, payload=None)
        assert tps[1].recv(0, 2, timeout=1).payload is None

    def test_large_payload(self, b3):
        tps = b3.transports()
        payload = np.random.default_rng(0).random(1_000_000)
        tps[0].send(1, tag=1, payload=payload)
        np.testing.assert_array_equal(
            tps[1].recv(0, 1, timeout=5).payload, payload
        )


class TestNativeConcurrency:
    def test_selective_recvs_dont_steal(self, b3):
        """Two receivers blocked on different tags; a send must wake the
        matching one only (the C side uses notify_all + per-filter match)."""
        tps = b3.transports()
        out = {}

        def rx(tag):
            out[tag] = tps[2].recv(ANY_SOURCE, tag, timeout=5).payload

        t1 = threading.Thread(target=rx, args=(1,))
        t2 = threading.Thread(target=rx, args=(2,))
        t1.start(), t2.start()
        time.sleep(0.05)
        tps[0].send(2, tag=2, payload="two")
        tps[0].send(2, tag=1, payload="one")
        t1.join(5), t2.join(5)
        assert out == {1: "one", 2: "two"}

    def test_blocking_recv_releases_gil(self, b3):
        """A thread parked in native recv must not stall Python threads —
        the whole point of the C++ broker (ctypes drops the GIL)."""
        tps = b3.transports()
        done = threading.Event()

        def blocked():
            try:
                tps[1].recv(timeout=2)
            except RecvTimeout:
                pass
            done.set()

        t = threading.Thread(target=blocked)
        t.start()
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < 0.3:
            n += 1  # pure-Python progress while the other thread blocks
        assert n > 10_000  # would be ~0 if recv held the GIL
        tps[0].send(1, tag=0, payload="unblock")
        t.join(5)
        assert done.is_set()


class TestNativeShutdown:
    def test_close_with_blocked_receiver_is_safe(self):
        """close() while a thread is parked in recv must wake it with an
        error — not delete the condvar under the waiter (use-after-free
        regression)."""
        broker = native.NativeBroker(2)
        tps = broker.transports()
        outcome = {}

        def blocked():
            try:
                tps[1].recv(timeout=30)
                outcome["r"] = "message"
            except RuntimeError as e:
                outcome["r"] = str(e)
            except RecvTimeout:
                outcome["r"] = "timeout"

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.1)
        broker.close()
        t.join(timeout=5)
        assert not t.is_alive()
        assert "closed" in outcome["r"]

    def test_send_after_close_raises(self):
        broker = native.NativeBroker(2)
        tps = broker.transports()
        broker.close()
        with pytest.raises(RuntimeError):
            tps[0].send(1, tag=0, payload="x")


class TestNativePSTrainer:
    def test_async_ps_on_native_transport(self):
        import jax.numpy as jnp
        import optax

        from mpit_tpu.data.synthetic import synthetic_image_classification
        from mpit_tpu.models import MLP
        from mpit_tpu.parallel import AsyncPSTrainer

        x, y, xt, yt = synthetic_image_classification(
            512, 128, (8, 8, 1), 10, seed=0
        )
        tr = AsyncPSTrainer(
            MLP(hidden=(16,), compute_dtype=jnp.float32),
            optax.sgd(0.1),
            num_clients=2, num_servers=2, tau=4, transport="native",
        )
        center, stats = tr.train(x, y, steps=16, batch_size=32)
        assert stats["server_counts"][0]["push_easgd"] == 2 * (16 // 4)
        acc = tr.evaluate(center, xt, yt)
        assert 0.0 <= acc <= 1.0


def test_native_blocking_probe(b3):
    """C-side probe_wait: parks off-GIL until a match arrives, without
    consuming it; times out to False."""
    import threading
    import time

    tps = b3.transports()
    assert tps[1].probe(timeout=0.05) is False

    def later():
        time.sleep(0.15)
        tps[0].send(1, tag=5, payload=b"x")

    threading.Thread(target=later, daemon=True).start()
    t0 = time.monotonic()
    assert tps[1].probe(src=0, tag=5, timeout=5) is True
    assert time.monotonic() - t0 < 4
    assert tps[1].recv(0, 5, timeout=1).payload == b"x"
