"""Elastic membership: JOIN/REJOIN/REPLACE/LEAVE + shard snapshot recovery.

The membership layer (`parallel/elastic.py`, docs/ROBUSTNESS.md "Elastic
membership") replaces the seed-era "declared dead stays dead forever"
model: a replacement client announces itself with JOIN and gets a fresh
fetch plus a fresh-epoch dedup slot, a preempted client can rejoin
without being mistaken for a replay, and a killed server restores its
shard snapshot (center + version + dedup + membership as one consistent
cut) so acked pushes are never double-applied across a restart. These
tests pin each transition at the unit level, over the wire, and through
the save → kill → restore round trip; the process-level churn soak is
`scripts/elastic_soak.sh`."""

import shutil
import threading
import time

import numpy as np
import pytest

from mpit_tpu.parallel.elastic import ElasticMembership
from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_HEARTBEAT,
    TAG_PUSH_EASGD,
    TAG_STOP,
    PServer,
    spawn_server_thread,
)
from mpit_tpu.transport import Broker, ChaosConfig, ChaosTransport

DIM = 16


# ------------------------------------------------------- membership unit


class TestMembershipView:
    def test_register_kinds(self):
        m = ElasticMembership(2, [1, 2])
        assert m.register(1, epoch=111) == "join"
        assert m.register(1, epoch=111) == "rejoin"  # same identity
        assert m.register(1, epoch=222) == "replace"  # fresh process
        assert m.epochs[1] == 222

    def test_replace_clears_every_terminal_state(self):
        """A rank that was declared dead (or even stopped — a respawn
        after a clean exit) owes a fresh STOP once it re-registers."""
        m = ElasticMembership(2, [1, 2])
        m.register(1, epoch=1)
        m.dead.add(1)
        m.stopped.add(2)
        assert m.register(1, epoch=2) == "replace"
        assert 1 not in m.dead
        m.register(2, epoch=3)
        assert 2 not in m.stopped
        assert not m.teardown_complete()

    def test_teardown_accounting(self):
        m = ElasticMembership(2, [1, 2])
        assert not m.teardown_complete()
        m.stopped.add(1)
        assert not m.teardown_complete()
        m.dead.add(2)
        assert m.teardown_complete()  # stopped|dead|left covers expected

    def test_leave_counts_toward_teardown(self):
        m = ElasticMembership(2, [1, 2])
        m.stopped.add(1)
        m.leave(2)
        assert m.teardown_complete()

    def test_unknown_rank_join_raises_the_bar(self):
        """A mid-run joiner becomes *expected*: teardown must now wait
        for its STOP too, never complete without it."""
        m = ElasticMembership(1, [1])
        m.stopped.add(1)
        assert m.teardown_complete()
        m.register(7, epoch=9)
        assert 7 in m.expected
        assert not m.teardown_complete()
        m.leave(7)
        assert m.teardown_complete()

    def test_view_epoch_bumps_on_every_change(self):
        m = ElasticMembership(1, [1])
        v0 = m.view_epoch
        m.register(1, epoch=4)
        m.leave(1)
        assert m.view_epoch == v0 + 2

    def test_state_round_trip_preserves_set_identity(self):
        """load_state mutates in place: the server aliases
        ``dead_clients``/``_stopped`` to these sets, so a restore must
        never rebind them. 64-bit epochs (the client identity is 8
        random bytes) must survive the trip."""
        big = int.from_bytes(b"\xff" * 8, "big")
        src = ElasticMembership(2, [1, 2])
        src.register(1, epoch=big)
        src.dead.add(2)
        src.leave(1)

        dst = ElasticMembership(1, [1])
        dead_alias, stopped_alias = dst.dead, dst.stopped
        dst.load_state(src.state())
        assert dst.dead is dead_alias and dst.stopped is stopped_alias
        assert dst.state() == src.state()
        assert dst.epochs[1] == big


# ----------------------------------------------------- JOIN over the wire


def _world(num_clients: int, client_timeout=None, **server_kw):
    broker = Broker(1 + num_clients)
    tps = broker.transports()
    server = PServer(
        tps[0],
        np.zeros(DIM, np.float32),
        num_clients=num_clients,
        alpha=0.5,
        client_ranks=list(range(1, 1 + num_clients)),
        client_timeout=client_timeout,
        **server_kw,
    )
    thread = spawn_server_thread(server)
    return tps, server, thread


class TestJoinProtocol:
    def test_join_returns_fresh_fetch(self):
        tps, server, thread = _world(1)
        client = PClient(tps[1], [0], DIM)
        center = client.join()
        np.testing.assert_array_equal(center, np.zeros(DIM, np.float32))
        assert server.counts["join"] == 1
        assert server._membership.epochs[1] == client._epoch
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None

    def test_replacement_epoch_gets_fresh_dedup_slot(self):
        """The exactly-once half of membership: the predecessor consumed
        seq 1 under its epoch; the replacement's seq 1 (fresh epoch) must
        APPLY, while a replay under the predecessor's epoch must not."""
        tps, server, thread = _world(1)
        first = PClient(tps[1], [0], DIM)
        first.join()
        first.push_easgd(np.ones(DIM, np.float32))

        # replacement process on the same rank: new PClient = new epoch
        second = PClient(tps[1], [0], DIM)
        assert second._epoch != first._epoch
        second.join()
        assert server._membership.epochs[1] == second._epoch
        second.push_easgd(np.ones(DIM, np.float32))  # seq 1 again

        # a chaos-style replay of the PREDECESSOR's push: same (epoch, 1)
        tps[1].send(
            0, TAG_PUSH_EASGD, (first._epoch, 1, np.ones(DIM, np.float32))
        )
        second.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None
        assert server.counts["push_easgd"] == 2  # both seq-1 pushes landed
        assert server.counts["dup_dropped"] == 1  # the replay did not
        assert server.counts["join"] == 2

    def test_leave_releases_teardown_without_stop(self):
        tps, server, thread = _world(2)
        a = PClient(tps[1], [0], DIM)
        b = PClient(tps[2], [0], DIM)
        a.join()
        b.join()
        a.stop()
        b.leave()  # planned departure: no STOP ever sent
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None
        assert server.counts["leave"] == 1
        assert server._membership.left == {2}

    def test_rejoined_client_keeps_its_dedup_window(self):
        """Same epoch re-registering (a preempted client whose process
        survived): its already-admitted seqs must STAY admitted — a
        retransmit from before the partition is still a replay."""
        tps, server, thread = _world(1)
        client = PClient(tps[1], [0], DIM)
        client.join()
        client.push_easgd(np.ones(DIM, np.float32))
        client.join()  # rejoin: same object, same epoch
        tps[1].send(
            0, TAG_PUSH_EASGD, (client._epoch, 1, np.ones(DIM, np.float32))
        )
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None
        assert server.counts["push_easgd"] == 1
        assert server.counts["dup_dropped"] == 1


# ------------------------------------------------- shard snapshot recovery


class TestShardSnapshot:
    def test_kill_restore_round_trip_preserves_exactly_once(self, tmp_path):
        """Save under load, 'kill' the server, restore a new one on the
        same path: version counter continues, gen bumps, and a replayed
        (epoch, seq) push from before the kill is still rejected —
        the dedup window rode the snapshot with the center."""
        path = str(tmp_path / "shard_0.msgpack")
        killed = str(tmp_path / "shard_0.killed.msgpack")
        tps, server, thread = _world(1, ckpt_path=path, ckpt_every=1)
        client = PClient(tps[1], [0], DIM)
        client.join()
        client.push_easgd(np.ones(DIM, np.float32))
        client.push_easgd(np.full(DIM, 2.0, np.float32))
        deadline = time.monotonic() + 5
        while server.version < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.version == 2
        want_center = server.snapshot()
        # "kill": freeze the snapshot as persisted after push 2, BEFORE
        # the clean stop below rewrites it with rank 1 marked stopped —
        # a preempted server never got to record that stop
        shutil.copy(path, killed)
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None

        # the restored server is a NEW process: fresh transports too
        tps2, revived, thread2 = _world(1, ckpt_path=killed, ckpt_every=1)
        assert revived.restored
        assert revived.version == 2  # counter continuity
        assert revived.gen == 1  # restore = new generation
        np.testing.assert_array_equal(revived.snapshot(), want_center)

        # replay an acked pre-kill push: must be a dup, not a re-apply
        tps2[1].send(
            0, TAG_PUSH_EASGD,
            (client._epoch, 2, np.full(DIM, 2.0, np.float32)),
        )
        tps2[1].send(0, TAG_STOP, None)
        thread2.join(timeout=5)
        assert not thread2.is_alive() and revived.error is None
        assert revived.counts["dup_dropped"] == 1
        assert revived.counts["push_easgd"] == 0
        assert revived.version == 2  # untouched by the replay
        np.testing.assert_array_equal(revived.snapshot(), want_center)

    def test_restored_membership_remembers_stopped_ranks(self, tmp_path):
        """A server killed AFTER a client stopped must not wait for that
        client again on restore — its STOP rode the snapshot."""
        path = str(tmp_path / "shard_0.msgpack")
        tps, server, thread = _world(2, ckpt_path=path, ckpt_every=1)
        a = PClient(tps[1], [0], DIM)
        a.join()
        a.push_easgd(np.ones(DIM, np.float32))  # triggers a snapshot...
        a.stop()
        deadline = time.monotonic() + 5
        while 1 not in server._stopped and time.monotonic() < deadline:
            time.sleep(0.01)
        a.push_easgd(np.ones(DIM, np.float32))  # ...and this one persists
        # the stop (stop() keeps the dedup epoch, so seq 2 still admits)
        deadline = time.monotonic() + 5
        while server.counts["push_easgd"] < 2 and (
            time.monotonic() < deadline
        ):
            time.sleep(0.01)

        tps2, revived, thread2 = _world(2, ckpt_path=path, ckpt_every=1)
        assert revived.restored
        assert revived._membership.stopped == {1}
        tps2[2].send(0, TAG_STOP, None)  # only rank 2 still owes a stop
        thread2.join(timeout=5)
        assert not thread2.is_alive() and revived.error is None

    def test_shape_mismatch_fails_loudly(self, tmp_path):
        path = str(tmp_path / "shard_0.msgpack")
        tps, server, thread = _world(1, ckpt_path=path, ckpt_every=1)
        client = PClient(tps[1], [0], DIM)
        client.push_easgd(np.ones(DIM, np.float32))
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive()
        with pytest.raises(ValueError, match="shape"):
            PServer(
                Broker(2).transports()[0],
                np.zeros(DIM + 1, np.float32),
                num_clients=1,
                ckpt_path=path,
            )


# --------------------------------------- revival + heartbeat thread hygiene


class TestRevivalUnderChaos:
    def test_blackholed_heartbeats_then_release_revives(self):
        """Scripted drops swallow the client's first heartbeats (a grey
        link), the watchdog declares it dead, the hole ends, the next
        heartbeat revives it, and its push still applies — recovery, not
        just detection."""
        hole = 40  # 40 * 0.05 s = 2 s of dropped heartbeats vs 0.5 s timeout
        broker = Broker(3)
        tps = broker.transports()
        # a second, healthy client keeps the run alive: with a lone
        # client, declaring it dead would complete teardown and end the
        # serve loop before any revival could happen
        server = PServer(
            tps[0], np.zeros(DIM, np.float32), num_clients=2, alpha=0.5,
            client_ranks=[1, 2], client_timeout=0.5,
        )
        thread = spawn_server_thread(server)
        chaos = ChaosTransport(
            tps[1],
            ChaosConfig(
                seed=0,
                scripted={
                    (1, 0, TAG_HEARTBEAT, n): "drop" for n in range(hole)
                },
            ),
        )
        grey = PClient(chaos, [0], DIM, heartbeat_interval=0.05)
        healthy = PClient(tps[2], [0], DIM, heartbeat_interval=0.05)
        deadline = time.monotonic() + 10
        while 1 not in server.dead_clients and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in server.dead_clients  # the hole outlasted the watchdog
        deadline = time.monotonic() + 10
        while 1 in server.dead_clients and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 not in server.dead_clients  # first delivered beat revived
        grey.push_easgd(np.ones(DIM, np.float32))
        grey.stop()
        healthy.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None
        assert server.counts["push_easgd"] == 1
        assert server.dead_clients == set()


class TestHeartbeatShutdown:
    @staticmethod
    def _hb_threads():
        return [
            t for t in threading.enumerate()
            if t.name == "mpit-pclient-heartbeat" and t.is_alive()
        ]

    def test_stop_joins_heartbeat_thread(self):
        tps, server, thread = _world(1)
        before = len(self._hb_threads())
        client = PClient(tps[1], [0], DIM, heartbeat_interval=0.05)
        assert len(self._hb_threads()) == before + 1
        client.stop()
        assert client._hb_thread is None
        assert len(self._hb_threads()) == before  # joined, not leaked
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None

    def test_double_stop_is_idempotent(self):
        tps, server, thread = _world(1)
        client = PClient(tps[1], [0], DIM, heartbeat_interval=0.05)
        client.stop()
        client.stop()  # second stop: no error, no hang
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None
