"""Carry-decode LSTM serving: exactness of the prefill+tick recipe.

Mirrors the transformer sampling pins (tests/test_generate.py): the
carry-decode fast path must equal the full-forward slow reference
token for token (greedy and sampled), batched rows must equal solo
calls at ``fold_in(rng, n)`` across mixed prompt lengths, and the
shared conventions (filters, eos truncation, validation, bf16 weight
serving) must behave identically to the transformer path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import generate_rnn
from mpit_tpu.models.lstm import LSTMLM
from mpit_tpu.models.sampling import _filter_logits

V = 23


def _model_params():
    model = LSTMLM(
        vocab_size=V, embed_dim=16, hidden=32, num_layers=2,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _slow(model, params, prompt, steps, temperature=0.0, rng=None,
          top_k=None, top_p=None):
    """Full forward on the growing sequence — the exact reference."""
    toks = list(prompt)
    keys = (
        jax.random.split(rng, steps) if rng is not None else [None] * steps
    )
    for j in range(steps):
        logits = model.apply(
            {"params": params}, jnp.asarray(toks, jnp.int32)[None]
        )[0, -1]
        if temperature > 0:
            scaled = _filter_logits(logits / temperature, top_k, top_p)
            toks.append(int(jax.random.categorical(keys[j], scaled)))
        else:
            toks.append(int(jnp.argmax(logits)))
    return toks


def test_greedy_matches_full_forward(topo8):
    model, params = _model_params()
    for prompt, steps in [([3, 1, 4, 1, 5], 8), ([2], 1), ([7, 7], 15)]:
        assert generate_rnn(model, params, prompt, steps) == _slow(
            model, params, prompt, steps
        ), (prompt, steps)


def test_sampled_matches_full_forward(topo8):
    model, params = _model_params()
    rng = jax.random.key(9)
    got = generate_rnn(
        model, params, [3, 1, 4], 6, temperature=0.8, top_k=5, rng=rng
    )
    want = _slow(
        model, params, [3, 1, 4], 6, temperature=0.8, rng=rng, top_k=5
    )
    assert got == want
    other = generate_rnn(
        model, params, [3, 1, 4], 6, temperature=0.8, top_k=5,
        rng=jax.random.key(10),
    )
    assert got != other  # overwhelmingly likely from a random model


def test_batch_rows_equal_solo_mixed_lengths(topo8):
    """Per-row seq_lengths prefill: every row of a mixed-length batch
    (N=3 pads to 4) equals its solo call — greedy and sampled."""
    model, params = _model_params()
    prompts = [[3, 1, 4, 1, 5], [2], [7, 7, 7]]
    rows = generate_rnn(model, params, prompts, 5)
    for i, q in enumerate(prompts):
        assert rows[i] == generate_rnn(model, params, q, 5), i
    rng = jax.random.key(4)
    rows = generate_rnn(
        model, params, prompts, 5, temperature=0.9, top_p=0.9, rng=rng
    )
    for i, q in enumerate(prompts):
        want = generate_rnn(
            model, params, q, 5, temperature=0.9, top_p=0.9,
            rng=jax.random.fold_in(rng, i),
        )
        assert rows[i] == want, i


def test_bf16_default_model_fast_equals_slow(topo8):
    """The DEFAULT bf16-compute LSTM must also match the full-forward
    reference exactly: head_logits quantizes the bias to compute dtype
    exactly like flax Dense does, so prefill logits == tick logits ==
    full-forward logits bit for bit (a f32 bias in the prefill head
    would flip near-tie argmaxes)."""
    model = LSTMLM(vocab_size=V, embed_dim=16, hidden=32, num_layers=2)
    params = model.init(
        jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    for prompt in ([3, 1, 4, 1, 5], [2, 6]):
        assert generate_rnn(model, params, prompt, 10) == _slow(
            model, params, prompt, 10
        ), prompt


def test_generation_has_no_length_cap(topo8):
    """An RNN carry has no positional horizon: generation runs far past
    any training sequence length (the transformer path would reject
    this at max_len)."""
    model, params = _model_params()
    out = generate_rnn(model, params, [1, 2], 100)
    assert len(out) == 102
    assert all(0 <= t < V for t in out)


def test_eos_truncation_and_weights_dtype(topo8):
    model, params = _model_params()
    probe = generate_rnn(model, params, [3, 1, 4], 8)
    eos = probe[4]  # second generated token (may also appear earlier —
    # greedy RNNs repeat; expect the SHARED truncation rule's result)
    first = next(i for i in range(3, len(probe)) if probe[i] == eos)
    got = generate_rnn(model, params, [3, 1, 4], 8, eos_id=eos)
    assert got == probe[: first + 1] and got[-1] == eos
    # bf16 weights serve end-to-end (values may differ — only shape and
    # validity are pinned here; the bf16-compute default model is the
    # numerically-meaningful case)
    out = generate_rnn(
        model, params, [3, 1, 4], 4, weights_dtype=jnp.bfloat16
    )
    assert len(out) == 7 and all(0 <= t < V for t in out)


def test_validation_shared_with_transformer_path(topo8):
    model, params = _model_params()
    with pytest.raises(ValueError, match="vocab_size"):
        generate_rnn(model, params, [999], 2)
    with pytest.raises(ValueError, match="temperature"):
        generate_rnn(model, params, [1], 2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        generate_rnn(model, params, [1], 2, temperature=0.5, top_k=0)
    with pytest.raises(ValueError, match="eos_id"):
        generate_rnn(model, params, [1], 2, eos_id=99)
    assert generate_rnn(model, params, [1, 2], 0) == [1, 2]
    # a flat empty sequence is a solo 0-token prompt — the shared
    # validator rejects it instead of silently returning []
    with pytest.raises(ValueError, match="prompt of 0 tokens"):
        generate_rnn(model, params, [], 3)


def test_min_p_batch_rows_equal_solo(topo8):
    """min_p on the RNN path: batch row n equals its solo call at
    fold_in(rng, n) — the same contract as every other rule knob."""
    model, params = _model_params()
    rng = jax.random.key(9)
    prompts = [[1, 2], [3], [4, 5, 6]]
    rows = generate_rnn(
        model, params, prompts, 5, temperature=0.8, min_p=0.3, rng=rng
    )
    for i, q in enumerate(prompts):
        want = generate_rnn(
            model, params, q, 5, temperature=0.8, min_p=0.3,
            rng=jax.random.fold_in(rng, i),
        )
        assert rows[i] == want, i
    with pytest.raises(ValueError, match="min_p"):
        generate_rnn(model, params, [1], 2, temperature=0.8, min_p=2.0)


def test_batch_bucketing_shares_programs(topo8):
    """Row counts and lengths bucket: N=3 shares the N=4 program."""
    from mpit_tpu.models import rnn_sampling

    model, params = _model_params()
    generate_rnn(model, params, [[1, 2]] * 4, steps=4)
    n0 = rnn_sampling._rnn_prefill_decode_scan._cache_size()
    out = generate_rnn(model, params, [[1], [2, 3], [4]], steps=4)
    assert rnn_sampling._rnn_prefill_decode_scan._cache_size() == n0
    assert len(out) == 3


def test_training_params_serve_directly(topo8):
    """The decode clone's param tree IS the training tree: a few
    training steps, then serving from the trained params — no
    conversion."""
    import optax

    import mpit_tpu
    from mpit_tpu.parallel import DataParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(num_workers=1)
    model, _ = _model_params()
    tr = DataParallelTrainer(
        model, optax.adam(1e-2), topo, donate_state=False
    )
    rngs = np.random.default_rng(0)
    x = rngs.integers(0, V, (8, 12)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(1), x[:1])
    for _ in range(3):
        state, m = tr.step(state, x, y)
    out = generate_rnn(model, state.params, [1, 2, 3], 5)
    assert out == _slow(model, state.params, [1, 2, 3], 5)
    mpit_tpu.finalize()


def test_empty_tuple_is_explicit_empty_batch(topo8):
    """prompts=() is the one unambiguous empty-batch spelling and maps
    to [] (mirroring generate_batch's []->[] on the transformer path);
    the empty LIST stays rejected as a solo empty prompt."""
    model, params = _model_params()
    assert generate_rnn(model, params, (), 3) == []
    with pytest.raises(ValueError, match="prompt of 0 tokens"):
        generate_rnn(model, params, [], 3)
