"""Tensor parallelism (GSPMD annotations): tp-invariance on the 8-device mesh.

The trainer writes NO collectives — correctness is entirely "annotate the
Megatron shardings, let the partitioner insert psums". The checks: weights
really land sharded, the loss/param trajectory is invariant across
(dp, tp) factorizations, and it matches the explicit-collective
DataParallelTrainer at tp=1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.models.transformer import TransformerLM
from mpit_tpu.parallel import DataParallelTrainer, TensorParallelTrainer

V, B, T = 29, 8, 32


def _model():
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=8, max_len=T,
        compute_dtype=jnp.float32,
    )


def _data(seed=0, n=B):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, V, (n, T)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


def _run_tp(mesh_shape, steps=3):
    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=mesh_shape)
    tr = TensorParallelTrainer(
        _model(), optax.sgd(0.1, momentum=0.9), topo, donate_state=False
    )
    x, y = _data()
    state = tr.init_state(jax.random.key(0), x[:2])
    losses = []
    for _ in range(steps):
        state, m = tr.step(state, x, y)
        losses.append(float(m["loss"]))
    params = jax.tree.map(np.asarray, jax.device_get(state.params))
    ev = tr.evaluate(state, x, y)
    mpit_tpu.finalize()
    return losses, params, ev


class TestTensorParallel:
    def test_weights_actually_sharded(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
        tr = TensorParallelTrainer(
            _model(), optax.sgd(0.1), topo, donate_state=False
        )
        x, _ = _data()
        state = tr.init_state(jax.random.key(0), x[:2])
        qkv = state.params["Block_0"]["Dense_0"]["kernel"]
        down = state.params["Block_0"]["Dense_3"]["kernel"]
        # column-sharded qkv: each device holds 1/tp of the output dim
        assert qkv.sharding.spec == ("tp",) or qkv.sharding.spec[-1] == "tp"
        assert down.sharding.spec[0] == "tp"
        # and the embedding stays replicated
        emb = state.params["Embed_0"]["embedding"]
        assert all(s is None for s in emb.sharding.spec)
        mpit_tpu.finalize()

    @pytest.mark.slow
    def test_tp_factorizations_match_each_other_and_dp(self):
        ref_losses, ref_params, ref_ev = _run_tp((8, 1))
        for shape in ((2, 4), (1, 8)):
            losses, params, ev = _run_tp(shape)
            np.testing.assert_allclose(
                losses, ref_losses, rtol=1e-4, atol=1e-5,
                err_msg=f"losses diverged for mesh {shape}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-4, atol=2e-4
                ),
                params, ref_params,
            )
            assert ev[0] == pytest.approx(ref_ev[0], abs=1e-6)
        # cross-check against the explicit-collective DP trainer (same
        # math, hand-written psum) on the plain 1-D mesh
        mpit_tpu.finalize()
        topo = mpit_tpu.init(num_workers=8)
        dp = DataParallelTrainer(
            _model(), optax.sgd(0.1, momentum=0.9), topo,
            donate_state=False,
        )
        x, y = _data()
        state = dp.init_state(jax.random.key(0), x[:1])
        dp_losses = []
        for _ in range(3):
            state, m = dp.step(state, x, y)
            dp_losses.append(float(m["loss"]))
        np.testing.assert_allclose(dp_losses, ref_losses, rtol=1e-4,
                                   atol=1e-5)
        mpit_tpu.finalize()

    def test_rule_drift_raises_instead_of_replicating(self):
        """A Dense kernel the rule table doesn't know (renamed/added
        layer) and a rule that matches nothing both hard-fail —
        the failure mode used to be silent replication."""
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
        tr = TensorParallelTrainer(
            _model(), optax.sgd(0.1), topo, donate_state=False
        )
        arr = jnp.zeros((8, 8))
        with pytest.raises(ValueError, match="matched no rule"):
            tr.state_sharding(
                {"params": {"Block_0": {"Dense_9": {"kernel": arr}}}}
            )
        # all-LayerNorm tree: every rule goes unmatched
        with pytest.raises(ValueError, match="matched no parameter"):
            tr.state_sharding(
                {"params": {"Block_0": {"LayerNorm_0": {"scale": arr}}}}
            )
        mpit_tpu.finalize()

    def test_moe_model_rejected(self):
        """moe_* leaves match no tp rule; the constructor refuses the
        model instead of silently replicating every expert."""
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
        moe = TransformerLM(
            vocab_size=V, num_layers=2, d_model=32, num_heads=8,
            max_len=T, moe_experts=8,
        )
        with pytest.raises(ValueError, match="MoEParallelTrainer"):
            TensorParallelTrainer(moe, optax.sgd(0.1), topo)
        mpit_tpu.finalize()

    def test_validation(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        with pytest.raises(ValueError, match="second axis is 'tp'"):
            TensorParallelTrainer(_model(), optax.sgd(0.1), topo)
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(1, 8))
        with pytest.raises(ValueError, match="not divisible by tp"):
            TensorParallelTrainer(
                _model().clone(num_heads=2), optax.sgd(0.1), topo
            )
        with pytest.raises(ValueError, match="dense-attention"):
            TensorParallelTrainer(
                _model().clone(seq_axis="sp"), optax.sgd(0.1), topo
            )
        mpit_tpu.finalize()
