"""Keep the driver entry points green: entry() compiles, dryrun runs."""

import jax
import pytest

# integration tier — excluded from the smoke run (driver entry dryruns (3+ min each))
pytestmark = pytest.mark.slow


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_dryrun_multichip_2():
    import __graft_entry__ as g

    g.dryrun_multichip(2)
