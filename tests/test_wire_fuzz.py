"""Differential codec fuzz harness (mpit_tpu.transport.fuzz).

The gate itself is stdlib-random (seeded, replayable — lint gate 9);
this file pins its contracts:

- determinism: the same seed produces the same report, and corpus
  regeneration is byte-identical to the checked-in corpus;
- the oracle: deep_equal's bit-exact semantics (NaN, signed zero, f32
  quant scales, dtype-sensitive arrays) — a sloppier oracle would wave
  wrong decodes through;
- the mutation contract: structured corruptions land on WireDecodeError
  or the original value, and a frame that decodes to a DIFFERENT value
  is classified "wrong" (the failure class the gate exists to catch);
- the corpus: the checked-in file replays clean, end to end.

An optional hypothesis layer re-states the roundtrip/differential
properties generatively where hypothesis is installed (it is not a
dependency of the gate).
"""

from __future__ import annotations

import random
from pathlib import Path

import numpy as np
import pytest

from mpit_tpu.quant import quantize
from mpit_tpu.transport import fuzz, wire

CORPUS = (
    Path(__file__).resolve().parent / "fixtures" / "wire_corpus"
    / "corpus.jsonl"
)


# -------------------------------------------------------------- determinism


def test_run_fuzz_is_deterministic():
    a = fuzz.run_fuzz(seed=7, examples=200)
    b = fuzz.run_fuzz(seed=7, examples=200)
    assert a.to_json() == b.to_json()
    assert not a.failures, a.failures[:3]
    assert a.roundtrip_ok == a.differential_ok == 200


def test_generator_is_seed_sensitive():
    a = fuzz.run_fuzz(seed=1, examples=50)
    b = fuzz.run_fuzz(seed=2, examples=50)
    assert a.to_json() != b.to_json()


def test_corpus_regenerates_byte_identical(tmp_path):
    """The corpus is a FUNCTION of the codec + seed: regeneration must
    reproduce the checked-in bytes exactly, or the codec changed and
    the corpus (and lockfile thinking) must be refreshed consciously."""
    out = tmp_path / "corpus.jsonl"
    n = fuzz.write_corpus(out, seed=0)
    assert n == len(CORPUS.read_text().splitlines())
    assert out.read_bytes() == CORPUS.read_bytes()


def test_checked_in_corpus_replays_clean():
    report = fuzz.replay_corpus(CORPUS)
    assert not report.failures, report.failures[:5]
    assert report.corpus_clean >= 40
    assert report.corpus_mutations >= 9 * report.corpus_clean


# ------------------------------------------------------------------ oracle


def test_deep_equal_bit_exact_floats():
    nan = float("nan")
    assert fuzz.deep_equal(nan, nan)
    assert fuzz.deep_equal((1, nan), (1, nan))
    assert not fuzz.deep_equal(0.0, -0.0)  # distinct IEEE bit patterns
    assert not fuzz.deep_equal(1, 1.0)  # type-sensitive
    assert not fuzz.deep_equal(True, 1)  # bool is not int on the wire
    assert not fuzz.deep_equal((1,), [1])


def test_deep_equal_arrays_and_quant():
    a = np.arange(4, dtype=np.int32)
    assert fuzz.deep_equal(a, a.copy())
    assert not fuzz.deep_equal(a, a.astype(np.int64))  # dtype-sensitive
    assert not fuzz.deep_equal(a, a.reshape(2, 2))  # shape-sensitive
    q = quantize(np.arange(8, dtype=np.float32), "int8")
    r = quantize(np.arange(8, dtype=np.float32), "int8")
    assert fuzz.deep_equal(q, r)
    assert not fuzz.deep_equal(
        q, quantize(np.arange(8, dtype=np.float32), "bf16")
    )


def test_empty_multidim_array_roundtrips():
    """Regression: zero-in-shape arrays crashed encode_frame
    (memoryview.cast rejects views with zeros in shape)."""
    for shape in ((0,), (2, 0), (2, 0, 3)):
        payload = np.zeros(shape, dtype=np.float32)
        data = fuzz.frame_bytes(3, 4, payload)
        assert data is not None
        _, _, out = fuzz.decode_bytes(data)
        assert fuzz.deep_equal(out, payload)


# --------------------------------------------------------------- mutations


def _frame():
    payload = (7, 3, 1, np.arange(5, dtype=np.float32))
    return fuzz.frame_bytes(2, 2, payload), 2, 2, payload


@pytest.mark.parametrize("name,op", fuzz.MUTATIONS)
def test_every_mutation_op_is_error_or_benign(name, op):
    data, src, tag, payload = _frame()
    rng = random.Random(0)
    for _ in range(50):
        outcome, detail = fuzz.classify_mutation(
            op(data, rng), src, tag, payload
        )
        assert outcome in ("error", "ok"), (name, outcome, detail)


def test_crc_corruption_always_errors():
    data, src, tag, payload = _frame()
    rng = random.Random(0)
    for _ in range(20):
        outcome, _ = fuzz.classify_mutation(
            fuzz._mut_crc_xor(data, rng), src, tag, payload
        )
        assert outcome == "error"


def test_future_version_always_errors():
    data, src, tag, payload = _frame()
    rng = random.Random(0)
    for _ in range(20):
        outcome, _ = fuzz.classify_mutation(
            fuzz._mut_version_bump(data, rng), src, tag, payload
        )
        assert outcome == "error"


def test_wrong_value_is_classified_wrong():
    """The failure class the gate exists to catch: a frame that decodes
    CLEANLY to a different value must come back 'wrong', not 'ok'."""
    other = fuzz.frame_bytes(
        2, 2, (7, 3, 2, np.arange(5, dtype=np.float32))
    )
    _, src, tag, payload = _frame()
    outcome, detail = fuzz.classify_mutation(other, src, tag, payload)
    assert outcome == "wrong", (outcome, detail)


def test_short_and_empty_frames_error():
    for blob in (b"", b"M", b"MW\x01\x00"):
        with pytest.raises(wire.WireDecodeError):
            fuzz.decode_bytes(blob)


# ------------------------------------------- optional hypothesis property


try:  # hypothesis is optional — the stdlib tests above always run
    import hypothesis
    from hypothesis import strategies as st
except ImportError:  # pragma: no cover
    hypothesis = None

if hypothesis is not None:

    @hypothesis.given(st.integers())
    @hypothesis.settings(deadline=None, max_examples=50)
    def test_property_roundtrip_any_int(n):
        data = fuzz.frame_bytes(0, 1, n)
        assert data is not None
        _, _, out = fuzz.decode_bytes(data)
        assert out == n and type(out) is int

    @hypothesis.given(st.text())
    @hypothesis.settings(deadline=None, max_examples=50)
    def test_property_roundtrip_text(s):
        try:
            s.encode("utf-8")
        except UnicodeEncodeError:
            hypothesis.assume(False)  # lone surrogates: not encodable
        data = fuzz.frame_bytes(0, 1, s)
        assert data is not None
        _, _, out = fuzz.decode_bytes(data)
        assert out == s
