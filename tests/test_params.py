"""Flatten/unflatten round-trip tests (SURVEY.md §4: "param flatten/unflatten
round-trip" is a required unit test the reference lacked)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.utils.params import flatten_params, unflatten_params


def _tree():
    return {
        "conv": {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4), "b": jnp.ones((4,))},
        "dense": (jnp.full((2, 2), 2.0), jnp.zeros((2,))),
    }


def test_round_trip_exact():
    tree = _tree()
    flat, spec = flatten_params(tree)
    assert flat.ndim == 1 and flat.size == 12 + 4 + 4 + 2
    back = unflatten_params(spec, flat)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), tree, back)


def test_flat_edit_propagates():
    tree = _tree()
    flat, spec = flatten_params(tree)
    back = unflatten_params(spec, flat * 2)
    np.testing.assert_allclose(back["dense"][0], np.full((2, 2), 4.0))


def test_shape_mismatch_raises():
    _, spec = flatten_params(_tree())
    with pytest.raises(ValueError):
        unflatten_params(spec, jnp.zeros((3,)))


def test_flatten_under_jit():
    tree = _tree()
    _, spec = flatten_params(tree)

    @jax.jit
    def step(t):
        flat, s = flatten_params(t)
        return unflatten_params(s, flat + 1.0)

    out = step(tree)
    np.testing.assert_allclose(out["conv"]["b"], np.full((4,), 2.0))
