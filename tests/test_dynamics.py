"""Training-dynamics plane tests (docs/OBSERVABILITY.md, "dynamics").

Layers under test: the versioned PARAM/push protocol (server version
counter, client basis echo, per-source staleness attribution under a
seeded chaos delay), the journal reducer (``mpit_tpu.obs.dynamics``)
and its gate/CLI exit codes, conformance rule TC204 on the checked-in
golden journals (green) and a mutated copy (red), the divergence and
staleness-runaway alert rules — fired from a real unstable-alpha run's
trajectory and quiet on the golden fixture — the Perfetto counter
tracks, the faulthandler forensics knob, bench_gate's dynamics
comparison, and the obs-off zero-cost guard in the client loop.
"""

import importlib.util
import json
import os
import shutil
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpit_tpu.data.datasets import load_mnist
from mpit_tpu.models.mlp import MLP
from mpit_tpu.obs import ObsConfig
from mpit_tpu.obs.__main__ import main as obs_main
from mpit_tpu.obs.alerts import AlertConfig, AlertEngine
from mpit_tpu.obs.core import _parse_faulthandler, arm_faulthandler, \
    config_from_env, disarm_faulthandler
from mpit_tpu.obs.dynamics import (
    aggregate_dynamics,
    check_dynamics_gate,
    diverging,
    load_gate,
)
from mpit_tpu.obs.live import M_ELASTIC_DIST, M_STALENESS, MetricsRegistry
from mpit_tpu.obs.merge import merge_to_chrome_trace, read_journal
from mpit_tpu.parallel import ps_roles
from mpit_tpu.parallel.ps_trainer import AsyncPSTrainer
from mpit_tpu.parallel.pserver import TAG_PUSH_EASGD
from mpit_tpu.transport.chaos import ChaosConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dynamics", "good_run")
SMOKE_GATE = os.path.join(REPO, "scripts", "dynamics_smoke.json")


def _mnist():
    x, y, _, _ = load_mnist(synthetic_train=1024, synthetic_test=256)
    return x, y


def _trainer(tmp_path, **kw):
    kw.setdefault("num_clients", 2)
    kw.setdefault("obs", ObsConfig(dir=str(tmp_path)))
    return AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_servers=1,
        algo="easgd",
        tau=4,
        transport="inproc",
        max_exchange_failures=5,
        fetch_timeout=5.0,
        fetch_retries=3,
        **kw,
    )


def _stamped(reg, t, seq, interval_s=0.1):
    snap = reg.snapshot()
    snap["seq"] = seq
    snap["interval_s"] = interval_s
    snap["t"] = t
    return snap


# ------------------------------------------------- aggregation + gate


class TestAggregateFixture:
    def test_golden_report_shape(self):
        report = aggregate_dynamics([FIXTURE])
        run = report["run"]
        assert run is not None
        assert run["clients"] == 2 and run["servers"] == 1
        assert run["versions_monotonic"] is True
        assert run["diverging"] is False
        assert run["staleness_p99"] >= 0
        assert run["elastic_dist_final"] > 0
        assert 0 < run["norm_ratio"] < 1
        for rank in (1, 2):
            row = report["clients"][rank]
            assert row["algo"] == "easgd" and row["rounds"] == 6
            assert row["elastic"]["final"] > 0
            assert not row["diverging"]
            assert len(row["trajectory"]) == 6
            st = report["staleness"][rank]
            assert st["pushes"] == 6
            assert st["p50"] <= st["p99"] <= st["max"]
        srv = report["servers"][0]
        assert srv["monotonic"] and srv["param_replies"] > 0
        assert srv["first_version"] <= srv["final_version"]

    def test_smoke_gate_passes_and_tight_gate_fails(self):
        report = aggregate_dynamics([FIXTURE])
        assert check_dynamics_gate(report, load_gate(SMOKE_GATE)) == []
        viol = check_dynamics_gate(report, {"elastic_dist_final_max": 0.0})
        assert len(viol) == 1 and "elastic_dist_final" in viol[0]

    def test_gated_metric_absent_is_a_violation(self):
        # journals with no staleness records but a staleness gate: the
        # instrumentation regressed — exactly what the gate must catch
        report = {"run": {"elastic_dist_final": 1.0}, "clients": {}}
        viol = check_dynamics_gate(report, {"staleness_p99_max": 5})
        assert viol and "absent" in viol[0]

    def test_load_gate_rejects_typos_and_types(self, tmp_path):
        p = tmp_path / "gate.json"
        p.write_text('{"stalness_p99_max": 1}')
        with pytest.raises(ValueError, match="unknown"):
            load_gate(str(p))
        p.write_text('{"staleness_p99_max": true}')
        with pytest.raises(ValueError, match="expected"):
            load_gate(str(p))
        p.write_text('{"allow_diverging": 1}')
        with pytest.raises(ValueError, match="expected"):
            load_gate(str(p))
        p.write_text('[1]')
        with pytest.raises(ValueError, match="object"):
            load_gate(str(p))

    def test_diverging_verdict(self):
        assert diverging([1.0, 2.0, 4.0, 8.0])
        assert not diverging([1.0, 2.0, 4.0])  # too short
        assert not diverging([8.0, 1.0, 2.0, 4.0, 3.9])  # not monotone
        assert not diverging([1.0, 1.1, 1.2, 1.3])  # grows < factor
        assert not diverging([0.0, 1.0, 2.0, 3.0])  # zero base


class TestDynamicsCLI:
    def test_exit_codes(self, tmp_path, capsys):
        assert obs_main(["dynamics", FIXTURE]) == 0
        assert obs_main(
            ["dynamics", FIXTURE, "--gate", SMOKE_GATE]
        ) == 0
        tight = tmp_path / "tight.json"
        tight.write_text('{"staleness_p99_max": 0}')
        assert obs_main(
            ["dynamics", FIXTURE, "--gate", str(tight)]
        ) == 1
        assert "DYNAMICS VIOLATION" in capsys.readouterr().out
        typo = tmp_path / "typo.json"
        typo.write_text('{"nope": 1}')
        assert obs_main(["dynamics", FIXTURE, "--gate", str(typo)]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert obs_main(["dynamics", str(empty)]) == 2

    def test_json_output_carries_violations(self, tmp_path, capsys):
        tight = tmp_path / "tight.json"
        tight.write_text('{"norm_ratio_max": 0.0}')
        assert obs_main(
            ["dynamics", FIXTURE, "--json", "--gate", str(tight)]
        ) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["run"]["clients"] == 2
        assert len(doc["violations"]) == 1


# ------------------------------------------------------ conformance


def _project():
    from mpit_tpu.analysis import lint

    modules = []
    pkg = os.path.join(REPO, "mpit_tpu")
    for ap, rel in lint.collect_files([pkg]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    return lint.Project(modules=modules, config=lint.Config())


class TestTC204:
    def test_golden_run_is_monotonic(self):
        from mpit_tpu.analysis import conformance

        report = conformance.check_conformance(FIXTURE, _project())
        assert report.ok, [str(v) for v in report.violations]

    def test_version_regression_is_flagged(self, tmp_path):
        from mpit_tpu.analysis import conformance

        for name in os.listdir(FIXTURE):
            shutil.copy(os.path.join(FIXTURE, name), tmp_path / name)
        # rewind the version in the server's LAST param_version record:
        # a counter that went backwards, invisible to TC201-203
        path = tmp_path / "obs_rank0.jsonl"
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        pv = [i for i, r in enumerate(recs) if r.get("ev") == "param_version"]
        assert len(pv) >= 2
        recs[pv[-1]]["version"] = recs[pv[0]]["version"] - 1 \
            if recs[pv[0]]["version"] > 0 else -1
        path.write_text("".join(json.dumps(r) + "\n" for r in recs))

        report = conformance.check_conformance(str(tmp_path), _project())
        rules = sorted({v.rule for v in report.violations})
        assert rules == ["TC204"], [str(v) for v in report.violations]
        # the post-mortem reducer reaches the same verdict
        agg = aggregate_dynamics([str(tmp_path)])
        assert agg["servers"][0]["monotonic"] is False
        assert agg["run"]["versions_monotonic"] is False


# ------------------------------------------- staleness attribution


class TestStalenessAttribution:
    def test_chaos_delayed_client_owns_the_staleness(self, tmp_path):
        """3-rank run where client rank 1's EASGD *pushes* (tag 2 only
        — fetches stay fast, so its basis stays old) go through a
        400 ms chaos delay, probability 1 so the seed is irrelevant.
        Each delayed push lands after the undelayed client has moved
        the center — the per-source staleness accounting must
        attribute the gap to rank 1, in the journals AND the stats.
        Staleness here comes from message *ordering* (old basis held
        across other ranks' applied pushes), not from racing the
        round time, so the assertion is load-tolerant."""
        x, y = _mnist()
        trainer = _trainer(
            tmp_path,
            chaos=ChaosConfig(
                delay=1.0,
                delay_s=0.4,
                edges=((1, 0),),
                tags=(TAG_PUSH_EASGD,),
            ),
        )
        _, stats = trainer.train(x, y, steps=24, batch_size=32, seed=0)

        by_src = stats["staleness_by_src"][0]
        assert set(by_src) == {1, 2}
        assert by_src[1]["pushes"] == by_src[2]["pushes"] == 6
        # the delayed client's window spans several center updates
        assert by_src[1]["max"] >= 2
        assert by_src[1]["sum"] > by_src[2]["sum"]

        report = aggregate_dynamics([str(tmp_path)])
        st = report["staleness"]
        assert st[1]["pushes"] == 6 and st[2]["pushes"] == 6
        assert st[1]["mean"] > st[2]["mean"]
        assert st[1]["max"] == by_src[1]["max"]
        assert report["servers"][0]["monotonic"]
        # versions: one bump per applied push
        assert stats["server_versions"] == [12]

    def test_clean_run_carries_dynamics_in_stats(self, tmp_path):
        x, y = _mnist()
        trainer = _trainer(tmp_path)
        _, stats = trainer.train(x, y, steps=8, batch_size=32, seed=0)
        assert stats["server_versions"] == [4]
        by_src = stats["staleness_by_src"][0]
        assert sum(s["pushes"] for s in by_src.values()) == 4


# ------------------------------------------------------- divergence


class TestDivergence:
    def test_unstable_alpha_fires_alert_and_verdict(self, tmp_path):
        """alpha=1.9 makes the elastic map amplify the worker-center
        gap ~2.8x per exchange — elastic distance grows strictly. The
        reducer must say diverging, the default gate must flag it, and
        replaying the trajectory through the AlertEngine as live
        snapshots must fire `divergence` exactly once (then dedup)."""
        x, y = _mnist()
        trainer = _trainer(tmp_path, num_clients=1, alpha=1.9)
        trainer.train(x, y, steps=24, batch_size=32, seed=0)

        report = aggregate_dynamics([str(tmp_path)])
        row = report["clients"][1]
        assert row["diverging"] and report["run"]["diverging"]
        traj = row["trajectory"]
        assert traj[-1] / traj[0] > 10  # the ~2.8x/exchange amplifier
        viol = check_dynamics_gate(report, load_gate(SMOKE_GATE))
        assert any("diverging" in v for v in viol)
        assert check_dynamics_gate(
            report, {"allow_diverging": True}
        ) == []

        engine = AlertEngine(None, AlertConfig())
        fired = []
        for i, v in enumerate(traj):
            reg = MetricsRegistry(1)
            reg.set_gauge(M_ELASTIC_DIST, v)
            fired += engine.evaluate(
                {1: _stamped(reg, t=100.0 + i, seq=i + 1)}
            )
        kinds = [(f["kind"], f["rank"]) for f in fired]
        assert ("divergence", 1) in kinds
        assert kinds.count(("divergence", 1)) == 1  # dedup held
        div = next(f for f in fired if f["kind"] == "divergence")
        assert div["detail"]["growth"] > 2.0

    def test_golden_trajectories_stay_quiet(self):
        """The checked-in healthy run replayed through the engine: no
        divergence, no staleness_runaway — the default thresholds must
        not cry wolf on an equilibrating EASGD run."""
        report = aggregate_dynamics([FIXTURE])
        engine = AlertEngine(None, AlertConfig())
        fired = []
        for rank, row in report["clients"].items():
            for i, v in enumerate(row["trajectory"]):
                reg = MetricsRegistry(rank)
                reg.set_gauge(M_ELASTIC_DIST, v)
                fired += engine.evaluate(
                    {rank: _stamped(reg, t=100.0 + i, seq=i + 1)}
                )
        assert fired == []


class TestStalenessRunaway:
    def test_spike_over_own_baseline_fires_once(self):
        engine = AlertEngine(None, AlertConfig())
        fired = []
        for i, s in enumerate((1.0, 1.0, 1.0, 8.0)):
            reg = MetricsRegistry(0)
            reg.observe(M_STALENESS, s)
            fired += engine.evaluate(
                {0: _stamped(reg, t=100.0 + i, seq=i + 1)}
            )
        kinds = [(f["kind"], f["rank"]) for f in fired]
        assert kinds == [("staleness_runaway", 0)]
        detail = fired[0]["detail"]
        assert detail["staleness_p99"] > 3 * detail["baseline"]
        # unchanged snapshot seq: histories must not advance, the
        # active alert must stay suppressed
        reg = MetricsRegistry(0)
        reg.observe(M_STALENESS, 8.0)
        snap = _stamped(reg, t=104.0, seq=4)
        assert engine.evaluate({0: snap}) == []
        assert engine.evaluate({0: snap}) == []

    def test_steady_staleness_is_quiet(self):
        engine = AlertEngine(None, AlertConfig())
        fired = []
        for i in range(6):
            reg = MetricsRegistry(0)
            reg.observe(M_STALENESS, 2.0)
            fired += engine.evaluate(
                {0: _stamped(reg, t=100.0 + i, seq=i + 1)}
            )
        assert fired == []


# ------------------------------------------------- counter tracks


class TestMergeCounters:
    def test_perfetto_counter_tracks_from_golden(self):
        trace = merge_to_chrome_trace([FIXTURE])
        counters = [
            e for e in trace["traceEvents"] if e.get("ph") == "C"
        ]
        names = {e["name"] for e in counters}
        assert "elastic_dist" in names
        assert {"staleness src 1", "staleness src 2"} <= names
        for e in counters:
            assert "value" in e["args"] and e["tid"] == 0


# --------------------------------------------------- faulthandler


class TestFaulthandler:
    def test_knob_parse(self):
        assert _parse_faulthandler(None) == 0.0
        assert _parse_faulthandler("0") == 0.0
        assert _parse_faulthandler("false") == 0.0
        assert _parse_faulthandler("1") == 300.0
        assert _parse_faulthandler("true") == 300.0
        assert _parse_faulthandler("2.5") == 2.5
        with pytest.raises(ValueError):
            _parse_faulthandler("soon")
        cfg = config_from_env(
            {"MPIT_OBS_DIR": "/x", "MPIT_OBS_FAULTHANDLER": "1"}
        )
        assert cfg.faulthandler == 300.0
        with pytest.raises(ValueError):
            ObsConfig(faulthandler=-1.0)

    def test_disabled_config_never_arms(self, tmp_path):
        assert arm_faulthandler(None, "t") is None
        assert arm_faulthandler(
            ObsConfig(dir=str(tmp_path)), "t"
        ) is None
        assert not os.listdir(tmp_path)

    def test_armed_dump_lands_in_stacks_file(self, tmp_path):
        """A sub-interval hang leaves all-thread stacks on disk — the
        forensics a wedged run is killed without. Process-global: the
        first armed file serves every later arm in this process."""
        cfg = ObsConfig(dir=str(tmp_path), faulthandler=0.05)
        path = arm_faulthandler(cfg, "t")
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if path and os.path.getsize(path) > 0:
                    break
                time.sleep(0.05)
        finally:
            disarm_faulthandler()
        text = open(path).read()
        assert "Thread" in text and "test_dynamics" in text


# ---------------------------------------------- bench_gate dynamics


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "scripts", "bench_gate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_round(d, n, parsed):
    with open(os.path.join(str(d), f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": "",
                   "parsed": parsed}, f)


class TestBenchGateDynamics:
    BASE = {
        "metric": "ps_mnist_throughput", "value": 100.0,
        "platform": "cpu",
        "dynamics": {"staleness_p99": 2, "elastic_dist_final": 1.0,
                     "norm_ratio": 0.02},
    }

    def test_quality_regressions_flagged(self, tmp_path, capsys):
        bg = _bench_gate()
        _bench_round(tmp_path, 1, self.BASE)
        _bench_round(tmp_path, 2, {
            **self.BASE,
            "dynamics": {"staleness_p99": 4, "elastic_dist_final": 2.0,
                         "norm_ratio": 0.01},
        })
        assert bg.main([str(tmp_path)]) == 0  # warn-only default
        out = capsys.readouterr().out
        assert "dynamics.staleness_p99 2 -> 4" in out
        assert "dynamics.elastic_dist_final" in out
        assert "dynamics.norm_ratio" in out and "drift" in out
        assert bg.main(["--strict", str(tmp_path)]) == 1

    def test_zero_baseline_appearance_warns(self, tmp_path, capsys):
        bg = _bench_gate()
        _bench_round(tmp_path, 1, {
            **self.BASE, "dynamics": {"staleness_p99": 0},
        })
        _bench_round(tmp_path, 2, {
            **self.BASE, "dynamics": {"staleness_p99": 3},
        })
        bg.main([str(tmp_path)])
        assert "zero baseline" in capsys.readouterr().out

    def test_within_threshold_and_platform_change_quiet(
        self, tmp_path, capsys
    ):
        bg = _bench_gate()
        _bench_round(tmp_path, 1, self.BASE)
        _bench_round(tmp_path, 2, {
            **self.BASE,
            "dynamics": {"staleness_p99": 2, "elastic_dist_final": 1.05,
                         "norm_ratio": 0.021},
        })
        assert bg.main(["--strict", str(tmp_path)]) == 0
        _bench_round(tmp_path, 3, {
            **self.BASE, "platform_note": "tunnel dead",
            "dynamics": {"staleness_p99": 50},
        })
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out


# ------------------------------------------------ obs-off zero cost


class TestObsOffGuard:
    def test_record_dynamics_never_called_without_obs(
        self, tmp_path, monkeypatch
    ):
        """The dynamics norms are guarded by the transport's obs_tracer:
        with obs off the helper must never run (no extra O(n) norms on
        the exchange path), while the protocol's version ints still
        flow (they are O(1) and always on)."""

        def boom(*a, **k):  # pragma: no cover - the assertion IS no call
            raise AssertionError("_record_dynamics ran with obs off")

        monkeypatch.setattr(ps_roles, "_record_dynamics", boom)
        x, y = _mnist()
        trainer = _trainer(tmp_path, obs=None)
        _, stats = trainer.train(x, y, steps=8, batch_size=32, seed=0)
        assert "telemetry" not in stats
        assert stats["server_versions"] == [4]
