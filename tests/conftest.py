"""Test scaffolding: simulate an 8-device TPU-like mesh on CPU.

The reference's test strategy was "multi-process single-node MPI simulates the
cluster" (SURVEY.md §4). The TPU-native equivalent: force the XLA host
platform to expose 8 virtual CPU devices and run the real shard_map/pjit code
paths against them. Must run before jax initializes its backends, hence the
env mutation at module import time.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

# The tests hard-assume 8 workers; the re-pin recipe (flag scrub + config-API
# platform update before backend init) lives in one place: utils/vmesh.py.
from mpit_tpu.utils.vmesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import pytest  # noqa: E402

import mpit_tpu  # noqa: E402

# Deterministic hypothesis profile for CI: derandomize pins every
# property test to one reproducible example stream (no flaky shrink
# sessions in the gate), deadline=None tolerates first-call jit/XLA
# compile stalls, print_blob makes any failure replayable verbatim.
# hypothesis is an OPTIONAL dev dependency — the suite (and the fuzz
# gate, which is stdlib-random) must run without it.
try:  # pragma: no cover - exercised only where hypothesis is installed
    from hypothesis import settings as _hyp_settings

    _hyp_settings.register_profile(
        "mpit-ci",
        derandomize=True,
        deadline=None,
        print_blob=True,
    )
    if os.environ.get("CI"):
        _hyp_settings.load_profile("mpit-ci")
except ImportError:
    pass


@pytest.fixture(autouse=True)
def _fresh_topology():
    """Each test starts with an uninitialized world (mpiT.Finalize parity)."""
    mpit_tpu.finalize()
    yield
    mpit_tpu.finalize()


@pytest.fixture(autouse=True, scope="module")
def _bounded_jit_cache():
    """Drop jax's compiled-program caches after each test module.

    The full suite compiles hundreds of XLA:CPU programs in one
    process; past ~330 tests the LLVM JIT segfaults NONDETERMINISTICALLY
    inside ``backend_compile_and_load`` (observed twice on 2026-08-01,
    at two unrelated tests — not OOM: 120+ GB free). Bounding the live
    executable count at module boundaries keeps the gate out of the
    crash window. Within-module compile-count pins are unaffected (the
    clear runs between modules); the cost is cross-module recompiles of
    the few shared small kernels."""
    yield
    import jax

    jax.clear_caches()


@pytest.fixture
def topo8():
    return mpit_tpu.init()


def pytest_report_header(config):
    import jax

    return f"mpit_tpu test mesh: {jax.device_count()} virtual CPU devices"


# -- shared MoE test helpers (used by test_moe.py and test_properties.py) ----

def run_moe_sharded(topo, params, h, capacity_factor, top_k=1):
    """moe_ffn under shard_map on ``topo``: experts sharded, router
    replicated, batch sharded on the worker axis."""
    import jax
    from jax.sharding import PartitionSpec as P

    from mpit_tpu.ops import moe_ffn

    axis = topo.worker_axis
    spec = {k: (P() if k == "router" else P(axis)) for k in params}
    fn = jax.jit(jax.shard_map(
        lambda p, x: moe_ffn(
            p, x, axis=axis, capacity_factor=capacity_factor, top_k=top_k
        ),
        mesh=topo.mesh, in_specs=(spec, P(axis)), out_specs=P(axis),
        check_vma=False,
    ))
    import numpy as np

    return np.asarray(fn(params, h))


def moe_dense_per_shard(params, h, capacity_factor, ep, top_k=1):
    """The dense reference applied shard-by-shard with the same local
    token count — the ONE definition of the per-shard overflow contract."""
    import jax.numpy as jnp
    import numpy as np

    from mpit_tpu.ops import moe_ffn_dense_reference

    per = len(h) // ep
    return np.concatenate([
        np.asarray(moe_ffn_dense_reference(
            params, jnp.asarray(h[i * per : (i + 1) * per]),
            capacity_factor=capacity_factor, top_k=top_k,
        ))
        for i in range(ep)
    ])
