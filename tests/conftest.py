"""Test scaffolding: simulate an 8-device TPU-like mesh on CPU.

The reference's test strategy was "multi-process single-node MPI simulates the
cluster" (SURVEY.md §4). The TPU-native equivalent: force the XLA host
platform to expose 8 virtual CPU devices and run the real shard_map/pjit code
paths against them. Must run before jax initializes its backends, hence the
env mutation at module import time.
"""

import os

import re

_FLAG = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
# Replace any pre-existing device-count flag (CI images sometimes set one);
# the tests hard-assume 8 workers.
_cleaned = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _existing)
os.environ["XLA_FLAGS"] = (_cleaned + " " + _FLAG).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402
import jax  # noqa: E402

# Some images register a hardware backend from sitecustomize at interpreter
# startup (before this conftest runs), which pins jax's platform despite the
# env var above. Re-pin to CPU through the config API — effective as long as
# no computation has run yet.
jax.config.update("jax_platforms", "cpu")

import mpit_tpu  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_topology():
    """Each test starts with an uninitialized world (mpiT.Finalize parity)."""
    mpit_tpu.finalize()
    yield
    mpit_tpu.finalize()


@pytest.fixture
def topo8():
    return mpit_tpu.init()


def pytest_report_header(config):
    return f"mpit_tpu test mesh: {jax.device_count()} virtual CPU devices"
