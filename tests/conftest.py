"""Test scaffolding: simulate an 8-device TPU-like mesh on CPU.

The reference's test strategy was "multi-process single-node MPI simulates the
cluster" (SURVEY.md §4). The TPU-native equivalent: force the XLA host
platform to expose 8 virtual CPU devices and run the real shard_map/pjit code
paths against them. Must run before jax initializes its backends, hence the
env mutation at module import time.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

# The tests hard-assume 8 workers; the re-pin recipe (flag scrub + config-API
# platform update before backend init) lives in one place: utils/vmesh.py.
from mpit_tpu.utils.vmesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import pytest  # noqa: E402

import mpit_tpu  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_topology():
    """Each test starts with an uninitialized world (mpiT.Finalize parity)."""
    mpit_tpu.finalize()
    yield
    mpit_tpu.finalize()


@pytest.fixture
def topo8():
    return mpit_tpu.init()


def pytest_report_header(config):
    import jax

    return f"mpit_tpu test mesh: {jax.device_count()} virtual CPU devices"
