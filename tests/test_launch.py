"""Launcher integration: the reference's `mpirun -n N` shape as real OS
processes over TCP (SURVEY.md §4: 'multi-node without a real cluster =
multi-process single-node MPI' — this is that test, which the reference
itself never had)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(n, script_args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.launch", "-n", str(n),
         os.path.join(REPO, "examples", "ptest_proc.py"), *script_args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )


def test_three_process_ps_easgd_trains():
    r = _launch(3, ["--model", "mlp", "--steps", "12", "--train-size", "512",
                    "--algo", "ps-easgd"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "test acc=" in r.stdout
    assert "pserver rank 0" in r.stdout
    assert "dead_clients=[]" in r.stdout
    # 2 clients, tau=4 (default), 12 steps -> 3 pushes each
    assert "'push_easgd': 6" in r.stdout


def test_failed_rank_terminates_world():
    """A rank exiting non-zero must bring the job down (not hang) — the
    launcher-level half of the failure-detection story."""
    r = _launch(2, ["--model", "mlp", "--steps", "4", "--servers", "2"],
                timeout=120)
    # 2 ranks, 2 servers -> no clients: every rank exits with SystemExit
    assert r.returncode != 0
    assert "leaves no clients" in r.stdout + r.stderr
