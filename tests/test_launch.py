"""Launcher integration: the reference's `mpirun -n N` shape as real OS
processes over TCP (SURVEY.md §4: 'multi-node without a real cluster =
multi-process single-node MPI' — this is that test, which the reference
itself never had)."""

import os
import subprocess
import sys
import pytest

# integration tier — excluded from the smoke run (real OS-process worlds + jax.distributed)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch_script(script, n, script_args, timeout=240, jax_distributed=False):
    """One launcher-invocation helper for every integration test (env
    hygiene and timeout policy live here only)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    cmd = [sys.executable, "-m", "mpit_tpu.launch", "-n", str(n)]
    if jax_distributed:
        cmd.append("--jax-distributed")
    cmd += [os.path.join(REPO, "examples", script), *script_args]
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout,
    )


def _launch(n, script_args, timeout=240):
    return _launch_script("ptest_proc.py", n, script_args, timeout=timeout)


def test_three_process_ps_easgd_trains():
    r = _launch(3, ["--model", "mlp", "--steps", "12", "--train-size", "512",
                    "--algo", "ps-easgd"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "test acc=" in r.stdout
    assert "pserver rank 0" in r.stdout
    assert "dead_clients=[]" in r.stdout
    # 2 clients, tau=4 (default), 12 steps -> 3 pushes each
    assert "'push_easgd': 6" in r.stdout


def test_failed_rank_terminates_world():
    """A rank exiting non-zero must bring the job down (not hang) — the
    launcher-level half of the failure-detection story."""
    r = _launch(2, ["--model", "mlp", "--steps", "4", "--servers", "2"],
                timeout=120)
    # 2 ranks, 2 servers -> no clients: every rank exits with SystemExit
    assert r.returncode != 0
    assert "leaves no clients" in r.stdout + r.stderr


def test_jax_distributed_global_mesh(tmp_path):
    """--jax-distributed: 2 OS processes x 2 local CPU devices form ONE
    global 4-worker mesh; the step's pmean crosses process boundaries and
    both ranks see identical, decreasing loss (the multi-host bootstrap,
    SURVEY.md §5 backend row, driven for real)."""
    import json

    out = str(tmp_path / "mh")
    r = _launch_script(
        "multihost_sync.py", 2,
        ["--local-devices", "2", "--steps", "25", "--out", out],
        jax_distributed=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    metrics = [
        json.load(open(f"{out}.rank{i}.json")) for i in range(2)
    ]
    for m in metrics:
        assert m["process_count"] == 2
        assert m["num_workers"] == 4
        assert m["last_loss"] < m["first_loss"] * 0.5
    # the mesh is ONE world: the replicated state must agree bit-for-bit
    assert metrics[0]["last_loss"] == metrics[1]["last_loss"]


def test_jax_distributed_easgd_round(tmp_path):
    """EASGD's whole tau-round (worker-sharded state, replicated center,
    elastic psum) runs over the cross-process mesh too."""
    import json

    out = str(tmp_path / "mh_easgd")
    r = _launch_script(
        "multihost_sync.py", 2,
        ["--algo", "easgd", "--local-devices", "2", "--steps", "20",
         "--out", out],
        jax_distributed=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    metrics = [json.load(open(f"{out}.rank{i}.json")) for i in range(2)]
    for m in metrics:
        assert m["num_workers"] == 4
        assert m["last_loss"] < m["first_loss"]
    assert metrics[0]["last_loss"] == metrics[1]["last_loss"]


def test_jax_distributed_zero_shards_and_checkpoint(tmp_path):
    """ZeRO-1 across OS processes: each rank's optimizer chunks are
    non-addressable to the others, the psum_scatter/all_gather pair
    crosses the process boundary, and the end-of-run checkpoint drives
    the process_allgather save path for genuinely distributed Adam
    state."""
    import json

    out = str(tmp_path / "mh_zero")
    r = _launch_script(
        "multihost_sync.py", 2,
        ["--algo", "zero", "--local-devices", "2", "--steps", "20",
         "--ckpt-dir", str(tmp_path / "ckpt"), "--out", out],
        timeout=300, jax_distributed=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    metrics = [json.load(open(f"{out}.rank{i}.json")) for i in range(2)]
    for m in metrics:
        assert m["num_workers"] == 4
        assert m["last_loss"] < m["first_loss"]
        assert m["ckpt_roundtrip"] is True
    assert metrics[0]["last_loss"] == metrics[1]["last_loss"]


def test_jax_distributed_checkpoint_roundtrip(tmp_path):
    """Multi-process checkpointing: worker-sharded EASGD leaves are
    genuinely non-addressable per process here, so this drives the
    process_allgather save path and the save-visibility barrier (a rank
    restoring immediately after save must find the file — the race the
    barrier exists to close)."""
    import json

    out = str(tmp_path / "mh_ck")
    r = _launch_script(
        "multihost_sync.py", 2,
        ["--algo", "easgd", "--local-devices", "2", "--steps", "8",
         "--ckpt-dir", str(tmp_path / "ckpt"), "--out", out],
        timeout=300, jax_distributed=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    for i in range(2):
        m = json.load(open(f"{out}.rank{i}.json"))
        assert m["ckpt_roundtrip"] is True
