"""Wire-schema inference (mpit_tpu.analysis.schema) and the lockfile gate.

Four layers:

- the MODEL: per-tag sender/receiver schemas inferred over the real
  package — every canonical TAG_* must come out with BOTH halves
  populated, and the envelope tags must carry their known shapes;
- the RULES going QUIET: each seeded MPT016/017/018 fixture, with its
  one bug fixed, lints clean (tests/test_analysis.py pins the firing
  direction; this file pins the silence direction);
- the CLI: ``schema --json`` emits the full 10-tag table, ``--check``
  is clean against the checked-in wire-schema.lock.json and exits 1
  the moment the lock is mutated out from under it (the undeclared-
  protocol-drift gate, pinned by mutate-and-rescan);
- the LOCKFILE itself: committed, current, and regenerated verbatim by
  ``--update-lock``.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from mpit_tpu.analysis import lint
from mpit_tpu.analysis import schema as schema_mod

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"
LOCK = REPO / "wire-schema.lock.json"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"


def _project(paths):
    modules = []
    for ap, rel in lint.collect_files(paths):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    return lint.Project(modules=modules, config=lint.Config())


@pytest.fixture(scope="module")
def package_schema():
    return _project([PKG]).schema


# ------------------------------------------------------------------ model


def test_all_fifteen_tags_have_both_halves(package_schema):
    doc = package_schema.to_json()
    assert sorted(doc["tags"], key=int) == [str(t) for t in range(1, 16)]
    for tag, entry in doc["tags"].items():
        assert entry["sender"], f"tag {tag} has no sender schema"
        assert entry["receiver"], f"tag {tag} has no receiver schema"


def test_push_envelope_shape(package_schema):
    doc = package_schema.to_json()
    by_name = {e["name"]: e for e in doc["tags"].values()}
    # the EASGD/delta push envelope: (epoch, seq, basis, chunk) where
    # the chunk is a raw array, its quantized form, or — sharded — the
    # per-shard parts list (docs/WIRE.md "Sharded-PS envelopes"); the
    # `?` is the coalesced-chunk build the classifier can't resolve
    for name in ("TAG_PUSH_EASGD", "TAG_PUSH_DELTA"):
        assert by_name[name]["sender"] == [
            "(int, int, int, ?|ndarray|quant)",
            "(int, int, int, list)",
        ], by_name[name]
    # control tags carry None and the receiver ignores the payload
    for name in ("TAG_STOP", "TAG_HEARTBEAT", "TAG_LEAVE"):
        assert by_name[name]["sender"] == ["none"], by_name[name]
        assert by_name[name]["receiver"] == ["ignored"], by_name[name]
    assert by_name["TAG_JOIN"]["sender"] == ["(int, int)"]


def test_snapshot_schema_is_closed(package_schema):
    doc = package_schema.to_json()
    assert doc["snapshot"]["writes"] == doc["snapshot"]["reads"]
    assert "center" in doc["snapshot"]["writes"]


def test_model_json_is_serializable(package_schema):
    json.dumps(package_schema.to_json())


# ------------------------------------------------- rules go quiet when fixed

_FIXES = {
    "fixture_mpt016": (
        "client.py",
        "        # BUG: drops the epoch stamp — a 2-tuple where the server\n"
        "        # unpacks three fields\n"
        "        transport.send(0, TAG_DATA, (seq, chunk))\n",
        "        transport.send(0, TAG_DATA, (epoch, seq, chunk))\n",
    ),
    "fixture_mpt017.py": (
        None,
        "    # BUG: dict payload — unencodable by the structural wire codec\n"
        '    transport.send(0, TAG_EVENT, {"step": step, "loss": loss})\n',
        "    transport.send(0, TAG_EVENT, (step, loss))\n",
    ),
    "fixture_mpt018.py": (
        None,
        "    # BUG: no save_shard_state writer packs 'gen' any more\n"
        '    gen = state.get("gen", 0)\n'
        "    return center, version, gen\n",
        "    return center, version\n",
    ),
}


@pytest.mark.parametrize("fixture", sorted(_FIXES))
def test_fixture_goes_quiet_when_fixed(fixture, tmp_path):
    """The other half of the fires-exactly-once contract: applying the
    obvious fix silences the rule (no residual finding survives)."""
    target, bug, fix = _FIXES[fixture]
    if target is None:
        dst = tmp_path / fixture
        shutil.copy(FIXTURES / fixture, dst)
        f = dst
    else:
        dst = tmp_path / fixture
        shutil.copytree(FIXTURES / fixture, dst)
        f = dst / target
    src = f.read_text()
    assert bug in src, "fixture drifted from the test's patch"
    f.write_text(src.replace(bug, fix))
    findings = lint.run_lint([dst], lint.Config(hot_all=True))
    assert findings == [], [x.format() for x in findings]


# -------------------------------------------------------------------- CLI


def _cli(*args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        **kw,
    )


def test_cli_schema_json_emits_all_tags():
    r = _cli("schema", "--json")
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["version"] == schema_mod.SCHEMA_LOCK_VERSION
    assert sorted(doc["tags"], key=int) == [str(t) for t in range(1, 16)]
    for entry in doc["tags"].values():
        assert entry["sender"] and entry["receiver"]


def test_cli_schema_check_clean_against_committed_lock():
    r = _cli("schema", "--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "15 tag(s) match" in r.stdout


def test_cli_schema_check_fails_on_undeclared_drift(tmp_path):
    """Mutate-and-rescan: an edited lock (i.e. the inferred schema
    moving away from the committed one) must exit 1 and name the tag."""
    mutated = json.loads(LOCK.read_text())
    mutated["tags"]["2"]["sender"] = ["(int, ndarray)"]
    alt = tmp_path / "wire-schema.lock.json"
    alt.write_text(json.dumps(mutated, indent=2, sort_keys=True))
    r = _cli("schema", "--check", "--lock", str(alt))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "TAG_PUSH_EASGD" in r.stdout
    assert "--update-lock" in r.stdout  # the remediation hint


def test_cli_schema_check_missing_lock_is_usage_error(tmp_path):
    r = _cli("schema", "--check", "--lock", str(tmp_path / "nope.json"))
    assert r.returncode == 2


# --------------------------------------------------------------- lockfile


def test_lockfile_is_committed_and_current(tmp_path):
    """--update-lock regenerates the committed file verbatim: the lock
    can never silently lag the code it describes."""
    assert LOCK.exists(), "wire-schema.lock.json must be checked in"
    regen = tmp_path / "regen.json"
    r = _cli("schema", "--update-lock", "--lock", str(regen))
    assert r.returncode == 0, r.stderr
    assert regen.read_text() == LOCK.read_text()
