"""Model zoo shape/registry tests (one forward per model, float32 on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import get_model


@pytest.mark.parametrize(
    "name,kwargs,x_shape,out_shape",
    [
        ("lenet", {}, (2, 28, 28, 1), (2, 10)),
        ("mlp", {}, (2, 28, 28, 1), (2, 10)),
        ("vgg_small", {}, (2, 32, 32, 3), (2, 10)),
        ("alexnet", {"num_classes": 100}, (2, 224, 224, 3), (2, 100)),
        ("lstm", {"vocab_size": 50, "embed_dim": 8, "hidden": 16}, None, None),
    ],
)
def test_forward_shapes(name, kwargs, x_shape, out_shape):
    model = get_model(name, compute_dtype=jnp.float32, **kwargs)
    if name == "lstm":
        x = np.zeros((2, 12), np.int32)
        out_shape = (2, 12, 50)
    else:
        x = np.zeros(x_shape, np.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == out_shape
    assert out.dtype == jnp.float32


def test_resnet50_forward_and_param_count():
    model = get_model("resnet50", num_classes=10, compute_dtype=jnp.float32)
    x = np.zeros((1, 64, 64, 3), np.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == (1, 10)
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"])
    )
    # ResNet-50 trunk ~23.5M params (without the 1000-class head)
    assert 20e6 < n_params < 30e6, n_params


def test_unknown_model_raises():
    with pytest.raises(ValueError, match="unknown model"):
        get_model("transformer9000")
