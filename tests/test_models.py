"""Model zoo shape/registry tests (one forward per model, float32 on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import get_model


@pytest.mark.parametrize(
    "name,kwargs,x_shape,out_shape",
    [
        ("lenet", {}, (2, 28, 28, 1), (2, 10)),
        ("mlp", {}, (2, 28, 28, 1), (2, 10)),
        ("vgg_small", {}, (2, 32, 32, 3), (2, 10)),
        ("alexnet", {"num_classes": 100}, (2, 224, 224, 3), (2, 100)),
        ("lstm", {"vocab_size": 50, "embed_dim": 8, "hidden": 16}, None, None),
    ],
)
def test_forward_shapes(name, kwargs, x_shape, out_shape):
    model = get_model(name, compute_dtype=jnp.float32, **kwargs)
    if name == "lstm":
        x = np.zeros((2, 12), np.int32)
        out_shape = (2, 12, 50)
    else:
        x = np.zeros(x_shape, np.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == out_shape
    assert out.dtype == jnp.float32


@pytest.mark.slow
def test_resnet50_forward_and_param_count():
    model = get_model("resnet50", num_classes=10, compute_dtype=jnp.float32)
    x = np.zeros((1, 64, 64, 3), np.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == (1, 10)
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"])
    )
    # ResNet-50 trunk ~23.5M params (without the 1000-class head)
    assert 20e6 < n_params < 30e6, n_params


def test_unknown_model_raises():
    with pytest.raises(ValueError, match="unknown model"):
        get_model("transformer9000")


@pytest.mark.slow
class TestSpaceToDepthStem:
    """The MLPerf-style stem reformulation must compute EXACTLY the
    textbook 7x7/2 conv (same kernel, float32)."""

    def test_matches_conv_stem_bitwise_math(self):
        import jax
        import jax.numpy as jnp

        from mpit_tpu.models.resnet import space_to_depth_stem

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 16, 20, 3)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(7, 7, 3, 8)), jnp.float32)
        ref = jax.lax.conv_general_dilated(
            x, k, window_strides=(2, 2), padding=((3, 3), (3, 3)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        got = space_to_depth_stem(x, k, jnp.float32)
        assert got.shape == ref.shape == (2, 8, 10, 8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_odd_spatial_dims_rejected(self):
        import jax.numpy as jnp
        import pytest

        from mpit_tpu.models.resnet import space_to_depth_stem

        with pytest.raises(ValueError, match="divisible"):
            space_to_depth_stem(
                jnp.zeros((1, 15, 16, 3)), jnp.zeros((7, 7, 3, 8)),
                jnp.float32,
            )

    def test_alexnet_stem_matches_strided_conv(self):
        """The general s2d-conv on AlexNet's 11x11/4 p=2 stem — including
        the output-slice case (s2d grid has one extra position when the
        stride does not divide H+2p-k)."""
        import jax
        import jax.numpy as jnp

        from mpit_tpu.ops.stem import space_to_depth_conv

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(2, 32, 36, 3)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(11, 11, 3, 8)), jnp.float32)
        ref = jax.lax.conv_general_dilated(
            x, k, window_strides=(4, 4), padding=((2, 2), (2, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        got = space_to_depth_conv(x, k, stride=4, padding=2, dt=jnp.float32)
        assert got.shape == ref.shape == (2, 7, 8, 8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_alexnet_s2d_model_trains(self):
        import jax
        import jax.numpy as jnp

        from mpit_tpu.models.alexnet import AlexNet

        model = AlexNet(
            num_classes=10, stem="space_to_depth",
            compute_dtype=jnp.float32,
        )
        x = jnp.ones((2, 64, 64, 3))
        params = model.init(jax.random.key(0), x)["params"]
        assert params["stem_kernel"].shape == (11, 11, 3, 64)
        def loss(p):
            return model.apply({"params": p}, x).sum()

        out = model.apply({"params": params}, x)
        assert out.shape == (2, 10) and np.isfinite(np.asarray(out)).all()
        grads = jax.grad(loss)(params)
        gk = np.asarray(grads["stem_kernel"])
        gb = np.asarray(grads["stem_bias"])
        assert np.isfinite(gk).all() and np.abs(gk).sum() > 0
        assert np.isfinite(gb).all() and np.abs(gb).sum() > 0

    def test_resnet50_s2d_stem_trains(self):
        import jax
        import jax.numpy as jnp
        import optax

        from mpit_tpu.models.resnet import ResNet50

        model = ResNet50(
            num_classes=10, stage_sizes=(1, 1), stem="space_to_depth",
            compute_dtype=jnp.float32,
        )
        x = jnp.ones((2, 32, 32, 3))
        params = model.init(jax.random.key(0), x)["params"]
        assert params["stem_kernel"].shape == (7, 7, 3, 64)

        def loss(p):
            return model.apply({"params": p}, x).sum()

        grads = jax.grad(loss)(params)
        assert np.isfinite(
            float(jnp.sum(jnp.abs(grads["stem_kernel"])))
        )

    def test_unknown_stem_raises(self):
        import jax
        import jax.numpy as jnp
        import pytest

        from mpit_tpu.models.resnet import ResNet50

        with pytest.raises(ValueError, match="stem"):
            ResNet50(stem="nope").init(
                jax.random.key(0), jnp.ones((1, 32, 32, 3))
            )


@pytest.mark.slow
class TestRemat:
    def test_transformer_remat_same_function(self):
        import jax.numpy as jnp

        from mpit_tpu.models.transformer import TransformerLM

        x = np.random.default_rng(0).integers(0, 31, (2, 16)).astype(np.int32)
        base = TransformerLM(vocab_size=31, max_len=16, num_layers=2,
                             d_model=32, num_heads=2,
                             compute_dtype=jnp.float32)
        rem = base.clone(remat=True)
        params = base.init(jax.random.key(0), x)["params"]
        # nn.remat preserves the param tree: same params drive both
        y0 = base.apply({"params": params}, x)
        y1 = rem.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                                   rtol=1e-6, atol=1e-6)
        # and gradients agree (remat only changes WHEN activations are
        # recomputed, never what is computed)
        def loss(m):
            def f(p):
                out = m.apply({"params": p}, x)
                return (out.astype(jnp.float32) ** 2).mean()
            return f
        g0 = jax.grad(loss(base))(params)
        g1 = jax.grad(loss(rem))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g0, g1,
        )

    def test_resnet_remat_same_function(self):
        import jax.numpy as jnp

        from mpit_tpu.models.resnet import ResNet50

        x = np.random.default_rng(1).uniform(0, 1, (2, 32, 32, 3)).astype(
            np.float32
        )
        base = ResNet50(num_classes=7, stage_sizes=(1, 1),
                        compute_dtype=jnp.float32)
        rem = base.clone(remat=True)
        params = base.init(jax.random.key(0), x)["params"]
        y0 = base.apply({"params": params}, x)
        y1 = rem.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                                   rtol=1e-5, atol=1e-5)
