"""Serving fleet: router policies, lifecycle audit, control plane
(docs/SERVING.md "The serving fleet").

Layers under test: the pure dispatch policies (seeded p2c replay,
least-loaded tie-breaking), the in-process fleet harness against real
Servers (all-finish + zero-lost audits, the replica-kill redispatch
leg, rolling weight refreshes with monotone versions), the controller
policy core and its spawn-replacement path, the pooled replica-side
latency aggregation, and the fleet-route model checker (MPT019 clean on
the shipped semantics, witnessed under single-bit mutations).
"""

import dataclasses
import glob
import json
import os
import random

import pytest

from mpit_tpu.fleet import (
    FleetHarness,
    Router,
    StaticWeightSource,
    audit_lifecycle,
    choose_replica,
    decide,
)
from mpit_tpu.loadgen import Request, ServeChaos, pooled_latencies

V, T = 17, 64


def _journals(d):
    return sorted(glob.glob(os.path.join(str(d), "obs_rank*.jsonl")))


def _model_params():
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _immediate_requests(n, seed=0, max_new=(3, 7)):
    """All-at-once arrivals: the router submits every request in its
    first loop iteration, which makes routing decisions a pure function
    of (policy, seed) — the replay tests depend on that."""
    rng = random.Random(seed)
    lo, hi = max_new
    return [
        Request(
            arrival_s=0.0,
            prompt=tuple(rng.randrange(1, V) for _ in range(
                rng.randrange(1, 7)
            )),
            max_new=rng.randrange(lo, hi),
            slo_ms=60_000.0,
        )
        for _ in range(n)
    ]


def _factory(model, params, out=None):
    from mpit_tpu.models import Server
    from mpit_tpu.obs.core import ObsConfig

    def factory(rank):
        obs = (
            ObsConfig(dir=os.path.join(str(out), f"rep{rank}"))
            if out is not None else None
        )
        return Server(model, params, max_batch=2, segment=4, obs=obs)

    return factory


def _routes(obs_dir):
    """[(rid, replica), ...] in journal order — the routing decisions."""
    out = []
    for path in _journals(obs_dir):
        for line in open(path):
            rec = json.loads(line)
            if rec.get("ev") == "req_route":
                out.append((rec["rid"], rec["replica"]))
    return out


# ------------------------------------------------------ dispatch policies


class TestChooseReplica:
    def test_least_loaded_ties_by_rank(self):
        assert choose_replica("least", 0, 0, {3: 1, 1: 2, 2: 1}) == 2
        assert choose_replica("least", 0, 0, {3: 0, 1: 0, 2: 0}) == 1
        # pure: the seed/rid inputs don't perturb least-loaded
        assert choose_replica("least", 9, 5, {3: 1, 1: 2, 2: 1}) == 2

    def test_p2c_deterministic_and_seeded(self):
        loads = {1: 0, 2: 0, 3: 0}
        a = [choose_replica("p2c", 7, rid, loads) for rid in range(64)]
        b = [choose_replica("p2c", 7, rid, loads) for rid in range(64)]
        assert a == b  # same seed, same draws — the replay contract
        c = [choose_replica("p2c", 8, rid, loads) for rid in range(64)]
        assert a != c  # the seed actually feeds the draw
        assert set(a) == {1, 2, 3}  # and both probes move around

    def test_p2c_prefers_less_loaded_candidate(self):
        # replica 2 is drowning: p2c may still pick it (both probes can
        # land there) but must pick it strictly less often than 1
        picks = [
            choose_replica("p2c", 3, rid, {1: 0, 2: 100})
            for rid in range(200)
        ]
        assert picks.count(2) < picks.count(1)
        assert picks.count(2) == sum(
            1 for rid in range(200)
            if choose_replica("p2c", 3, rid, {1: 0, 2: 0, 3: 0}) is not None
            and picks[rid] == 2
        )  # deterministic count, not a flaky sample

    def test_rejects_unknown_policy_and_empty_loads(self):
        with pytest.raises(ValueError, match="policy"):
            choose_replica("random", 0, 0, {1: 0})
        with pytest.raises(ValueError, match="alive"):
            choose_replica("p2c", 0, 0, {})


# ----------------------------------------------------- the fleet harness


class TestFleetRuns:
    def test_all_finish_and_audit_ok(self, tmp_path):
        model, params = _model_params()
        reqs = _immediate_requests(9)
        rep = FleetHarness(
            _factory(model, params), reqs, n_replicas=3, seed=0,
            obs_dir=str(tmp_path),
        ).run()
        assert len(rep.results) == 9 and rep.shed == 0
        audit = audit_lifecycle([str(tmp_path)])
        assert audit["ok"], audit
        assert audit["admitted"] == audit["finished"] == 9
        assert audit["lost"] == [] and audit["unrouted"] == []
        # every reply names its replica + the weights version served
        for res in rep.results.values():
            assert res["replica"] in (1, 2, 3)
            assert res["serving_weights_version"] == 0  # no publisher

    def test_same_seed_routes_identically(self, tmp_path):
        """Seeded p2c replay at the run level: two runs of the same
        workload+seed make identical routing decisions."""
        model, params = _model_params()
        dirs = []
        for leg in ("a", "b"):
            out = tmp_path / leg
            rep = FleetHarness(
                _factory(model, params), _immediate_requests(8),
                n_replicas=3, policy="p2c", seed=5, obs_dir=str(out),
            ).run()
            assert len(rep.results) == 8
            dirs.append(out)
        ra, rb = _routes(dirs[0]), _routes(dirs[1])
        assert ra == rb and len(ra) == 8

    def test_kill_redispatches_orphans_zero_lost(self, tmp_path):
        """THE fleet guarantee, journal-verified: killing 1 of 3
        replicas mid-run loses no admitted request — the dead replica's
        orphans carry explicit req_redispatch records to their finish."""
        model, params = _model_params()
        rep = FleetHarness(
            _factory(model, params), _immediate_requests(12),
            n_replicas=3, seed=1, obs_dir=str(tmp_path),
            chaos=ServeChaos(seed=1, kill_after=1), kill_rank=1,
        ).run()
        assert rep.killed_ranks == [1]
        assert rep.redispatched > 0  # the kill actually orphaned work
        assert len(rep.results) == 12
        audit = audit_lifecycle([str(tmp_path)])
        assert audit["ok"], audit
        assert audit["lost"] == []
        assert audit["dead_replicas"] == [1]
        assert audit["redispatched"] == rep.redispatched
        # no finish credited to the dead replica after redispatch took
        # its work: survivors finished everything they were handed
        assert 1 not in audit["replicas_finished"] or (
            audit["replicas_finished"][1] + rep.redispatched >= 1
        )

    def test_rolling_refresh_versions_monotonic(self, tmp_path):
        model, params = _model_params()
        import jax

        source = StaticWeightSource(params, version=1)
        rep = FleetHarness(
            _factory(model, params), _immediate_requests(10),
            n_replicas=2, seed=2, obs_dir=str(tmp_path),
            source=source, refresh_boundaries=(1,),
            refresh_params_fn=lambda v: jax.tree_util.tree_map(
                lambda a: a + 1e-3 * v, params
            ),
        ).run()
        assert len(rep.results) == 10
        assert source.version == 2  # the bump fired
        assert rep.weights_pushed == {1: 2, 2: 2}  # rolled to the fleet
        audit = audit_lifecycle([str(tmp_path)])
        assert audit["ok"] and audit["versions_monotonic"], audit
        # every reply is stamped and none serves ahead of the source; 0
        # is legitimate (a route framed before the startup push lands).
        # Which requests land on v2 is a scheduling fact — the
        # queue-ordered guarantee is pinned in test_refresh_before_route
        versions = [
            res["serving_weights_version"] for res in rep.results.values()
        ]
        assert set(versions) <= {0, 1, 2}

    def test_refresh_before_route_serves_new_version(self, tmp_path):
        """Queue-order determinism, no wall clock: a WEIGHT_PUSH framed
        before a ROUTE is installed before that request is served, so
        the reply MUST carry the refreshed version."""
        import threading

        import jax

        from mpit_tpu.fleet.replica import ReplicaServer
        from mpit_tpu.fleet.weights import WeightPublisher
        from mpit_tpu.transport.inproc import Broker

        model, params = _model_params()
        transports = Broker(2).transports()
        rep = ReplicaServer(
            _factory(model, params)(1), transports[1], router_rank=0,
        )
        t = threading.Thread(target=rep.run, daemon=True)
        t.start()
        router = Router(transports[0], [1], obs_dir=str(tmp_path))
        source = StaticWeightSource(params, version=1)
        publisher = WeightPublisher(transports[0], source)
        source.bump(jax.tree_util.tree_map(lambda a: a + 1e-3, params))
        publisher.publish_to(1)  # framed FIRST...
        rid = router.submit([1, 2, 3], 3)  # ...so the route serves v2
        assert router.poll(timeout=60.0) == rid
        assert router.results[rid]["serving_weights_version"] == 2
        router.stop()
        t.join(timeout=30.0)
        assert not t.is_alive()
        router.close()

    def test_controller_spawns_spare_not_dead_rank(self, tmp_path):
        """The acceptance claim: a dead_rank alert makes the controller
        retire the corpse and spawn the SPARE rank — never the dead
        rank's slot (its transport may hold undelivered traffic)."""
        model, params = _model_params()
        rep = FleetHarness(
            _factory(model, params), _immediate_requests(12),
            n_replicas=3, spares=1, seed=3, obs_dir=str(tmp_path),
            chaos=ServeChaos(seed=3, kill_after=1), kill_rank=1,
            use_controller=True,
        ).run()
        assert rep.killed_ranks == [1]
        assert rep.spawned_ranks == [4]
        acts = [(a.kind, a.rank, a.reason) for a in rep.controller_log]
        assert ("retire", 1, "dead_rank") in acts
        assert ("spawn", 4, "dead_rank") in acts
        assert len(rep.results) == 12
        assert audit_lifecycle([str(tmp_path)])["ok"]


# ---------------------------------------------------- admission shedding


def test_shed_at_admission_is_refusal_not_loss(tmp_path):
    from mpit_tpu.transport.inproc import Broker

    broker = Broker(2)
    transports = broker.transports()
    router = Router(
        transports[0], [1], max_outstanding=1, obs_dir=str(tmp_path),
    )
    assert router.submit([1, 2], 3) == 0
    assert router.submit([3], 2) is None  # saturated: shed, not queued
    assert router.shed == 1 and router.outstanding == 1
    router.close()
    audit = audit_lifecycle([str(tmp_path)])
    assert audit["shed"] == 1 and audit["admitted"] == 1
    # the admitted-but-unserved request is named, the shed one is not
    assert audit["lost"] == [0] and not audit["ok"]


# ------------------------------------------------------- controller core


class TestDecide:
    def test_dead_rank_retires_and_spawns_avoiding_dead(self):
        acts = decide(
            [{"kind": "dead_rank", "rank": 1}],
            alive={1, 2, 3}, all_ranks=[1, 2, 3, 4], max_replicas=3,
        )
        assert [(a.kind, a.rank) for a in acts] == [
            ("retire", 1), ("spawn", 4)
        ]
        # rank 1's slot is dead — even with no spare the policy must not
        # respawn into it
        acts = decide(
            [{"kind": "dead_rank", "rank": 1}],
            alive={1, 2, 3}, all_ranks=[1, 2, 3], max_replicas=3,
        )
        assert [(a.kind, a.rank) for a in acts] == [("retire", 1)]

    def test_slo_burn_spawns_then_sheds_at_capacity(self):
        burn = [{"kind": "slo_burn", "rank": -1}]
        acts = decide(burn, alive={1, 2}, all_ranks=[1, 2, 3],
                      max_replicas=3)
        assert [(a.kind, a.rank) for a in acts] == [("spawn", 3)]
        acts = decide(burn, alive={1, 2, 3}, all_ranks=[1, 2, 3],
                      max_replicas=3)
        assert [a.kind for a in acts] == ["shed"]

    def test_straggler_sheds_only_when_sole_replica(self):
        strag = [{"kind": "straggler", "rank": 1}]
        assert decide(strag, alive={1, 2}, all_ranks=[1, 2],
                      max_replicas=2) == []
        acts = decide(strag, alive={1}, all_ranks=[1], max_replicas=1)
        assert [a.kind for a in acts] == ["shed"]

    def test_idle_unshed(self):
        acts = decide([], alive={1}, all_ranks=[1], max_replicas=1,
                      outstanding=1, max_outstanding=8)
        assert [a.kind for a in acts] == ["unshed"]
        assert decide([], alive={1}, all_ranks=[1], max_replicas=1,
                      outstanding=7, max_outstanding=8) == []

    def test_pure(self):
        args = ([{"kind": "dead_rank", "rank": 2}], {1, 2}, [1, 2, 3], 2)
        assert decide(*args) == decide(*args)


# ------------------------------------------- pooled replica-side latency


def test_pooled_latencies_keeps_colliding_rids_apart(tmp_path):
    """Two replicas both journal rid 0 — pooling must count BOTH ttft
    samples (one aggregator would fold them into one request)."""
    for rep, (t0, t1) in (("a", (1.0, 1.5)), ("b", (2.0, 2.25))):
        d = tmp_path / rep
        d.mkdir()
        (d / "obs_rank0.jsonl").write_text(
            json.dumps({"ev": "req_enqueue", "rid": 0, "t": t0}) + "\n"
            + json.dumps({"ev": "req_first_token", "rid": 0, "t": t1})
            + "\n"
        )
    lat = pooled_latencies(
        [_journals(tmp_path / "a"), _journals(tmp_path / "b")],
        names=("ttft",),
    )
    assert lat["ttft"]["count"] == 2
    # pooled percentiles span both groups' samples (~500ms and ~250ms)
    assert 200 <= lat["ttft"]["p50_ms"] <= 300
    assert 450 <= lat["ttft"]["p99_ms"] <= 600


# ------------------------------------------------ fleet-route model check


def _analysis_project():
    from pathlib import Path

    from mpit_tpu.analysis import lint

    pkg = Path(__file__).resolve().parent.parent / "mpit_tpu"
    modules = []
    for ap, rel in lint.collect_files([pkg]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    return lint.Project(modules=modules, config=lint.Config())


@pytest.fixture(scope="module")
def fleet_sem():
    from mpit_tpu.analysis import protocol

    fsem = protocol.extract_fleet_semantics(_analysis_project())
    assert fsem is not None
    return fsem


def test_shipped_fleet_semantics_extracted_exactly(fleet_sem):
    from mpit_tpu.fleet.replica import (
        TAG_FLEET_STOP, TAG_REPLY, TAG_ROUTE,
    )

    assert fleet_sem.router_role == "serving_router"
    assert fleet_sem.replica_role == "serving_replica"
    assert fleet_sem.route_tag == TAG_ROUTE
    assert fleet_sem.reply_tag == TAG_REPLY
    assert fleet_sem.stop_tag == TAG_FLEET_STOP
    assert fleet_sem.redispatch_on_death  # Router.redispatch exists
    assert fleet_sem.reply_recv_timeout  # poll() recv carries timeout
    assert fleet_sem.route_send is not None
    assert fleet_sem.route_send.rel.endswith("fleet/router.py")


def test_shipped_fleet_model_is_clean(fleet_sem):
    from mpit_tpu.analysis import mcheck

    r = mcheck.check_fleet(mcheck.fleet_from_protocol(fleet_sem))
    assert r.ok, r.violations
    assert not r.truncated
    assert r.states > 100  # a real exploration, not a handful of steps
    assert r.fault_points > 0  # the kill fault contributed schedules


@pytest.mark.parametrize(
    "mutation",
    [
        # router never redispatches a dead replica's orphans
        {"redispatch_on_death": False},
        # reply wait can block forever: death is never even noticed
        {"reply_timeout": False},
    ],
)
def test_fleet_mutations_witness_mpt019(fleet_sem, mutation):
    from mpit_tpu.analysis import mcheck

    bad = dataclasses.replace(
        mcheck.fleet_from_protocol(fleet_sem), **mutation
    )
    r = mcheck.check_fleet(bad, mcheck.fleet_config(quick=True))
    assert "MPT019" in r.violations, (mutation, r.violations)
    assert "lost" in r.violations["MPT019"]
