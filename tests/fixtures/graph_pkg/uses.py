"""Every alias spelling the graph must follow, plus a stacked partial."""

import functools

import graph_pkg.consts as cc
from graph_pkg import consts
from graph_pkg.consts import BASE as RENAMED
from graph_pkg.funcs import bound as rebound
from graph_pkg.funcs import passthrough as forwarded

double = functools.partial(rebound, 3)
