"""Assignment cycle: resolution must terminate at MAX_DEPTH, not hang.

(Would NameError at import time — this package is only ever parsed.)
"""

A = B  # noqa: F821
B = A
