"""Constant registry: literals, folded unary/binary, an assign chain."""

BASE = 7
DERIVED = BASE  # assign chain, resolves to 7
NEG = -1  # UnaryOp(USub) folding
SHIFTED = BASE + 1  # BinOp over a cross-name operand, folds to 8
MASK = (1 << 4) | 2  # pure-literal arithmetic, folds to 18
WIRE = "obs" + "1"  # the one string fold: concatenation
