"""Constant registry: literals, folded unary, an assign chain."""

BASE = 7
DERIVED = BASE  # assign chain, resolves to 7
NEG = -1  # UnaryOp(USub) folding
