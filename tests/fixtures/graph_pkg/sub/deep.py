"""Relative imports across package levels."""

from ..consts import BASE as UP
from ..funcs import inner as up_inner
from .sibling import NEAR
