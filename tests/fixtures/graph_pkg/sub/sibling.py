"""Same-level relative-import target."""

NEAR = 21
