"""Subpackage, for relative-import resolution."""
