"""Fixture package for analysis/graph.py unit tests.

Exercises every resolution shape the module graph supports: import
aliasing (``import x as y``, ``from x import y as z``, relative imports),
constant/assign chains, ``functools.partial`` accumulation, pass-through
wrappers, star-import refusal, and the cycle guard. Parsed by the tests,
never imported.
"""
