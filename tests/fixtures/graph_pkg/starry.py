"""Star import: names it COULD provide must be refused, never guessed."""

from graph_pkg.consts import *

LOCAL = 3
