"""Callable-chain bottom: a real def, a partial, a pass-through wrapper."""

import functools


def inner(a, b, c):
    return a


def passthrough(*args, **kwargs):
    return inner(*args, **kwargs)


bound = functools.partial(inner, 1, b=2)
