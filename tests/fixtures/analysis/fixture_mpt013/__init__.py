"""Seeded MPT013 package: cross-thread shared state with no common lock.

``worker.py`` spawns a drainer thread that pops ``pending`` under the
instance lock while ``submit()`` (main thread) appends to it with no lock
at all — the canonical empty-lockset-intersection race. Parsed by the
linter tests, never imported.
"""
