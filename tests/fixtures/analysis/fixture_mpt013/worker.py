"""Seeded MPT013: ``pending`` is written from two thread roots, and the
submitting side holds no lock. Parsed by the linter tests, never
imported or executed."""

import threading


class JobPump:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        while True:
            with self._lock:
                if self.pending:
                    self.pending.pop()

    def submit(self, job):
        self.pending.append(job)  # BUG: no lock — races with _drain
