"""Seeded MPT014 package: a static lock-order cycle.

``deadlock.py`` runs two threads over the same pair of locks in opposite
nesting order — each path is deadlock-free alone, together they can
deadlock; only the cross-path cycle check sees it. Parsed by the linter
tests, never imported.
"""
