"""Seeded MPT014: ``_a_lock``/``_b_lock`` acquired in opposite orders on
two thread roots. Parsed by the linter tests, never imported or
executed."""

import threading


class Shuttle:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self.hops = 0
        threading.Thread(target=self._forward, daemon=True).start()
        threading.Thread(target=self._backward, daemon=True).start()

    def _forward(self):
        with self._a_lock:
            with self._b_lock:
                self.hops += 1

    def _backward(self):
        with self._b_lock:  # BUG: opposite order — cycle with _forward
            with self._a_lock:
                self.hops += 1
