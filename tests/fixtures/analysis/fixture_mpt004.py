"""Seeded MPT004: jit static_argnums drifted off the wrapped signature.

The c166392 failure class: the function lost parameters but the wrapper
still pins index 7. This file is parsed by the linter tests, never
imported or executed.
"""

import functools

import jax


@functools.partial(jax.jit, static_argnums=(0, 7))
def step(model, batch):
    return model, batch
