"""Seeded MPT021: a lossy push with no error-feedback fold.

The delta is quantized and its codes reach the wire, but the residual
``delta - dequantize(q)`` is never computed, so the compression error
is dropped every round instead of being re-injected on the next push —
a biased compressor on a training path. The numerics rule must flag the
quantize site (MPT021) and nothing else; folding the residual (or an
explicit ``# mpit-analysis: ef-off[...]`` marker) silences it. Parsed
by the linter tests, never imported.
"""

from mpit_tpu.quant import quantize

TAG_GRAD_PUSH = 32


def push_update(transport, rank, delta):
    # BUG: codes reach the wire, residual never folded into EF state
    q = quantize(delta, "int8")
    transport.send(rank, TAG_GRAD_PUSH, q)
