"""Seeded MPT005: host-device sync inside a loop (linted as hot path).

This file is parsed by the linter tests (with ``Config(hot_all=True)``),
never imported or executed.
"""


def train(step_fn, batches):
    total = 0.0
    for batch in batches:
        loss = step_fn(batch)
        total += loss.item()  # device->host round-trip every iteration
    return total
