"""Seeded MPT004 wrapper-chain package.

``top.py`` jits a callable reached through a 3-link chain (import alias →
``functools.partial`` → assignment) whose ``static_argnums`` is out of
range for the EFFECTIVE signature (the partial consumed one leading
positional). Parsed by the linter tests, never imported.
"""
