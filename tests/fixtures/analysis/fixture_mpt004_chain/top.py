"""Top of the chain: the jit site whose static_argnums drifted.

``bound_step`` is effectively ``base_step(batch, extra)`` — two positional
parameters — so index 4 is out of range.
"""

import jax

from fixture_mpt004_chain.mid import bound_step

fast_step = jax.jit(bound_step, static_argnums=(4,))
