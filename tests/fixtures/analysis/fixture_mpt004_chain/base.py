"""Bottom of the wrapper chain: the real def."""


def base_step(model, batch, extra):
    return batch
