"""Middle links: import alias + partial binding the leading positional."""

import functools

from fixture_mpt004_chain.base import base_step as aliased_step

bound_step = functools.partial(aliased_step, None)
