"""Seeded MPT001: collective with a literal axis name, no binding context.

This file is parsed by the linter tests, never imported or executed.
"""

from jax import lax


def bad_mean(x):
    # "rows" is never bound by any shard_map/Mesh/P spec in this module
    return lax.psum(x, "rows")
