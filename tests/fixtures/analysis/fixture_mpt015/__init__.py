"""Seeded MPT015 package: blocking I/O under a lock held by a CALLER.

``flusher.py``'s leaf helper looks innocent in isolation (MPT006 stays
silent by design — the ``with`` is a frame above); only the call-graph
lockset walk sees the socket write inside the critical section. Parsed
by the linter tests, never imported.
"""
