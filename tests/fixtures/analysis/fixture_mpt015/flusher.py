"""Seeded MPT015: the blocking ``sendall`` sits one call-frame below the
``with self._lock:`` that covers it. Parsed by the linter tests, never
imported or executed."""

import threading


class Flusher:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._sock = sock
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self._flush()  # BUG: the lock spans the blocking write below

    def _flush(self):
        self._sock.sendall(b"x")
