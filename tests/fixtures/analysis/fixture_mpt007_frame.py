"""Seeded MPT007 (frame-version variant): a frame writer at a marked
wire boundary that hard-codes ``version=`` instead of naming the
canonical ``WIRE_FORMAT_VERSION`` constant from ``transport/wire.py``.
A literal that equals the canonical value TODAY is still drift waiting
to happen — a bump of the constant would silently strand this site.
This file is parsed by the linter tests, never imported or executed.
"""

from mpit_tpu.transport import wire

# mpit-analysis: wire-boundary


def frame(payload):
    return wire.encode_frame(0, 2, payload, version=1)  # not by name
