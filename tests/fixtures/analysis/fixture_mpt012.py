"""Seeded MPT012: a typo'd live-metric name published to the registry.

The module is in the live plane's import closure (it imports
``live_registry``), so every ``inc``/``set_gauge``/``observe`` first
argument must be an ``M_*`` constant from ``mpit_tpu.obs.live``. The one
publish below uses a string literal with a transposition
(``train.ronuds``) — exactly the defect the rule exists for: the series
forks silently and the dashboard's rounds column flatlines. The clean
publish next to it pins the other direction (a namespace constant
resolves and is NOT flagged). Parsed by the linter tests, never
imported or executed.
"""

from mpit_tpu.obs.live import M_SAMPLES, live_registry


def train_round(client, k, batch_size):
    reg = live_registry(client.transport)
    reg.inc(M_SAMPLES, k * batch_size)
    reg.inc("train.ronuds")  # transposed "train.rounds" — forked series
