"""Seeded MPT018: restore reads a snapshot field no writer packs.

The save path stopped writing ``gen``; the restore path still reads it
with a default — every recovery silently restarts generation counting
from zero. The schema rule must flag the orphaned read (MPT018) and
nothing else. Parsed by the linter tests, never imported.
"""


def save(state_io, path, center, version):
    state_io.save_shard_state(path, {"center": center, "version": version})


def restore(state_io, path):
    state = state_io.load_shard_state(path)
    center = state["center"]
    version = state["version"]
    # BUG: no save_shard_state writer packs 'gen' any more
    gen = state.get("gen", 0)
    return center, version, gen
