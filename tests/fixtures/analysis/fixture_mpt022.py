"""Seeded MPT022: codes dequantized with the wrong mode (and no scale).

The rows are quantized as int8 (codes + per-row absmax scales) but the
reconstruction declares bf16 — the int8 codes are reinterpreted as
bf16 bit halves and the scales are dropped on the floor, so the
"reconstruction" is numerically unrelated to the input. The quantize is
paired (MPT021 quiet) and nothing reduces codes (MPT020 quiet): the
numerics rule must flag the dequantize site (MPT022) and nothing else.
Parsed by the linter tests, never imported.
"""

from mpit_tpu.quant import dequantize_rows_jnp, quantize_rows_jnp


def roundtrip(rows):
    codes, scales = quantize_rows_jnp(rows, "int8")
    # BUG: int8 codes decoded as bf16, per-row scales dropped
    deq = dequantize_rows_jnp(codes, None, "bf16")
    residual = rows - deq
    return residual, scales
