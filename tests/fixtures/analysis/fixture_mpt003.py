"""Seeded MPT003: TAG_* constant colliding with the canonical registry.

TAG_FETCH = 1 in mpit_tpu/parallel/pserver.py owns this value; a second
module claiming it corrupts the fetch mailbox the moment they share a
broker. This file is parsed by the linter tests, never imported.
"""

TAG_CLASH = 1
