"""Client role: fetch with timeout but NO attempt-id comparison."""

from fixture_mpt011.tags import TAG_PUSH, TAG_REQ, TAG_REP, TAG_STOP

# mpit-analysis: protocol-role[client->server]


def fetch(transport, rank, attempt, deadline):
    transport.send(rank, TAG_REQ, attempt)
    # the seeded defect: the reply carries the echoed attempt id, but
    # whatever arrives first is returned — a reply delayed past an
    # earlier deadline is assembled into this newer fetch
    got = transport.recv(rank, TAG_REP, timeout=deadline)
    return got[1]


def push(transport, rank, epoch, seq, delta):
    transport.send(rank, TAG_PUSH, (epoch, seq, delta))


def stop(transport, rank):
    transport.send(rank, TAG_STOP, None)
