"""Server role: correct dispatch loop and dedup window."""

from fixture_mpt011.tags import TAG_PUSH, TAG_REQ, TAG_REP, TAG_STOP

# mpit-analysis: protocol-role[server->client]


class DedupWindow:
    def __init__(self, size=4):
        self.size = size
        self.high = 0
        self.seen = set()

    def admit(self, seq):
        if seq <= self.high - self.size:
            return False
        if seq in self.seen:
            return False
        self.seen.add(seq)
        if seq > self.high:
            self.high = seq
            if len(self.seen) > self.size:
                self.seen = {s for s in self.seen if s > seq - self.size}
        return True


def serve(transport, center, window, stopped, world):
    while len(stopped) < world:
        msg = transport.recv(-1, -1)
        if msg.tag == TAG_REQ:
            transport.send(msg.src, TAG_REP, (msg.payload, center))
        elif msg.tag == TAG_PUSH:
            if window.admit(msg.payload[1]):
                center = center + msg.payload[2]
        elif msg.tag == TAG_STOP:
            stopped.add(msg.src)
