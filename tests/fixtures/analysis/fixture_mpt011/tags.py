"""Tag registry for the seeded missing-attempt-check protocol."""

TAG_REQ = 21
TAG_REP = 22
TAG_PUSH = 23
TAG_STOP = 24
