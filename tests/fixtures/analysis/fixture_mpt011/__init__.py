"""Seeded MPT011 package: attempt ids echoed but never checked.

The mirror image of ``fixture_mpt009``: the dedup window is correct
(``<=`` boundary), the server dutifully echoes the request's attempt id
in its reply — but the client assembles whatever reply arrives first
into its live fetch without comparing ids, so a reply delayed past a
timeout lands in the NEXT attempt's slot. The model checker must find
the stale-assembly schedule (MPT011) and nothing else. Parsed by the
linter tests, never imported.
"""
