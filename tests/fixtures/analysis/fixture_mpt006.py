"""Seeded MPT006: indefinitely-blocking socket call under a held lock.

This file is parsed by the linter tests, never imported or executed.
"""


class Sender:
    def __init__(self, sock, lock):
        self.sock = sock
        self._send_lock = lock

    def flush(self, frame):
        with self._send_lock:
            self.sock.sendall(frame)  # one slow peer stalls every sender
