"""Tag registry for the seeded arity-divergence protocol."""

TAG_DATA = 26
