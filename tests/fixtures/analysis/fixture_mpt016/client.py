"""Client role: streams epoch-stamped chunks to the server."""

from fixture_mpt016.tags import TAG_DATA

# mpit-analysis: protocol-role[client->server]


def push_chunks(transport, epoch, chunks):
    for seq, chunk in enumerate(chunks):
        # BUG: drops the epoch stamp — a 2-tuple where the server
        # unpacks three fields
        transport.send(0, TAG_DATA, (seq, chunk))
