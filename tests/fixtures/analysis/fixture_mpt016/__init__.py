"""Seeded MPT016 package: a sender/receiver payload-arity divergence.

A miniature streaming pair: the client pushes chunk envelopes, the
server destructures them. The only defect is the envelope arity — the
client packs ``(seq, chunk)`` where the server unpacks
``epoch, seq, chunk``: every message mis-unpacks at dispatch. The
schema rule must flag the send site (MPT016) and nothing else. Parsed
by the linter tests, never imported.
"""
