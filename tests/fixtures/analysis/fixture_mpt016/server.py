"""Server role: dispatch loop unpacking the three-field data envelope."""

from fixture_mpt016.tags import TAG_DATA

# mpit-analysis: protocol-role[server->client]


def serve(transport, sink):
    while True:
        msg = transport.recv(-1, -1)
        if msg.tag == TAG_DATA:
            epoch, seq, chunk = msg.payload
            sink.append((epoch, seq, chunk))
