"""Seeded MPT020: a reduction over quantized codes.

The block-quantized rows are summed in their wire representation —
unscaled int8 integers — instead of the f32 reconstruction, so the
accumulator is garbage whenever rows carry different absmax scales.
The error-feedback fold is present (the quantize is paired), so MPT021
must stay quiet: the numerics rule must flag the ``jnp.sum`` site
(MPT020) and nothing else. Parsed by the linter tests, never imported.
"""

import jax.numpy as jnp

from mpit_tpu.quant import dequantize_rows_jnp, quantize_rows_jnp


def reduce_blocks(rows, mode):
    codes, scales = quantize_rows_jnp(rows, mode)
    deq = dequantize_rows_jnp(codes, scales, mode)
    residual = rows - deq  # error feedback: the quantize is paired
    # BUG: accumulates the wire codes, not the f32 reconstruction
    total = jnp.sum(codes, axis=0)
    return total, residual
