"""Seeded MPT002: hard-coded literal tag at a transport send site.

This file is parsed by the linter tests, never imported or executed.
"""


def push_update(transport, payload):
    transport.send(0, 42, payload)  # 42 claims a tag outside the registry
