"""Seeded MPT017: a telemetry send whose payload is a dict literal.

Dicts are not in the structural wire grammar, so the whole message
falls off ``encode_frame`` onto the per-message pickle fallback —
silently, and on every step. The schema rule must flag the send site
(MPT017) and nothing else. Parsed by the linter tests, never imported.
"""

TAG_EVENT = 31


def report(transport, step, loss):
    # BUG: dict payload — unencodable by the structural wire codec
    transport.send(0, TAG_EVENT, {"step": step, "loss": loss})
