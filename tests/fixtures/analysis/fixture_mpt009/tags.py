"""Tag registry for the seeded dedup-off-by-one protocol."""

TAG_REQ = 21
TAG_REP = 22
TAG_PUSH = 23
TAG_STOP = 24
