"""Client role: attempt-id'd fetch (correct), push, stop."""

from fixture_mpt009.tags import TAG_PUSH, TAG_REQ, TAG_REP, TAG_STOP

# mpit-analysis: protocol-role[client->server]


def fetch(transport, rank, attempt, deadline):
    transport.send(rank, TAG_REQ, attempt)
    while True:
        got, payload = transport.recv(rank, TAG_REP, timeout=deadline)
        if got != attempt:
            continue  # stale reply from a timed-out earlier attempt
        return payload


def push(transport, rank, epoch, seq, delta):
    transport.send(rank, TAG_PUSH, (epoch, seq, delta))


def stop(transport, rank):
    transport.send(rank, TAG_STOP, None)
