"""Seeded MPT009 package: a dedup window with the classic off-by-one.

A complete miniature PS protocol pair — attempt-id echo and check,
reply-wait timeout, dispatch for REQ/PUSH/STOP — whose only defect is
the admit boundary: ``seq < high - size`` where ``<=`` is required, so
a duplicated push delivered after the window slid past it is admitted
a second time. The model checker must find the violating fault
schedule (MPT009) and nothing else. Parsed by the linter tests, never
imported.
"""
