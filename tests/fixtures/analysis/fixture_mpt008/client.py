"""Client role: one paired exchange, plus the seeded orphan send."""

from fixture_mpt008.tags import TAG_ORPHAN, TAG_REP, TAG_REQ

# mpit-analysis: protocol-role[client->server]


def exchange(transport, rank, payload):
    transport.send(rank, TAG_REQ, payload)
    return transport.recv(rank, TAG_REP)


def leak(transport, rank, payload):
    # the seeded defect: no server dispatch branch handles ORPHAN, so this
    # message parks in the server mailbox forever
    transport.send(rank, TAG_ORPHAN, payload)
