"""Server role: wildcard-recv dispatch loop that handles REQ only."""

from fixture_mpt008.tags import TAG_REP, TAG_REQ

# mpit-analysis: protocol-role[server->client]


def serve(transport):
    while True:
        msg = transport.recv(-1, -1)
        if msg.tag == TAG_REQ:
            transport.send(msg.src, TAG_REP, "center")
