"""Seeded MPT008 package: a two-role protocol with one unpaired send.

``client.py`` and ``server.py`` carry the protocol-role markers;
``tags.py`` is their registry (values off the canonical 1-6 range so
MPT003 stays quiet). Parsed by the linter tests, never imported.
"""
