"""Tag registry for the seeded two-role protocol."""

TAG_REQ = 11
TAG_REP = 12
TAG_ORPHAN = 13
