"""Tag registry for the seeded two-role protocol."""

TAG_REQ = 11
TAG_REP = 12
TAG_ORPHAN = TAG_REP + 1  # derived tag: resolves to 13 only by folding
