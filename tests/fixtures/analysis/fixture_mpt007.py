"""Seeded MPT007: pickle-protocol drift at a marked wire boundary.

The module opts into the wire-format rule with the marker comment below;
its one ``dumps`` pins a protocol that drifted off the canonical
``WIRE_PICKLE_PROTOCOL`` contract. This file is parsed by the linter
tests, never imported or executed.
"""

import pickle

# mpit-analysis: wire-boundary


def frame(payload):
    return pickle.dumps(payload, protocol=4)  # drifted off the wire contract
