"""Dataset-loader tests on generated on-disk fixtures.

Real datasets are absent in this image (the loaders' synthetic fallbacks
cover the training tests); these tests prove the real-format parsers are
correct so that dropping the actual files under $MPIT_DATA_DIR just works
(round-1 verdict item 7)."""

import gzip
import os
import struct

import numpy as np
import pytest

from mpit_tpu.data import load_cifar10, load_mnist
from mpit_tpu.data.datasets import _read_cifar10_bin


def _write_cifar_bin(path, n, seed, gzipped=False):
    """Standard CIFAR-10 record: 1 label byte + 3072 channel-planar pixels."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    pixels = rng.integers(0, 256, (n, 3, 32, 32), dtype=np.uint8)
    rows = np.concatenate(
        [labels[:, None], pixels.reshape(n, -1)], axis=1
    ).astype(np.uint8)
    opener = gzip.open if gzipped else open
    with opener(path, "wb") as f:
        f.write(rows.tobytes())
    return labels, pixels


class TestCifarBin:
    def test_parse_values_and_layout(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin")
        labels, pixels = _write_cifar_bin(p, 7, seed=0)
        x, y = _read_cifar10_bin([p])
        assert x.shape == (7, 32, 32, 3) and x.dtype == np.float32
        np.testing.assert_array_equal(y, labels.astype(np.int32))
        # channel-planar source -> NHWC: pixel (n, c, h, w) lands at
        # x[n, h, w, c]
        np.testing.assert_allclose(
            x[3, 5, 9, 2], pixels[3, 2, 5, 9] / 255.0
        )
        assert x.min() >= 0.0 and x.max() <= 1.0

    def test_load_cifar10_from_dir(self, tmp_path, monkeypatch):
        sub = tmp_path / "cifar-10-batches-bin"
        sub.mkdir()
        for i in range(1, 6):
            _write_cifar_bin(str(sub / f"data_batch_{i}.bin"), 4, seed=i)
        te_labels, _ = _write_cifar_bin(str(sub / "test_batch.bin"), 3, seed=9)
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        x_tr, y_tr, x_te, y_te = load_cifar10()
        assert x_tr.shape == (20, 32, 32, 3)
        assert x_te.shape == (3, 32, 32, 3)
        np.testing.assert_array_equal(y_te, te_labels.astype(np.int32))

    def test_gzipped_batches(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin.gz")
        labels, _ = _write_cifar_bin(p, 5, seed=2, gzipped=True)
        x, y = _read_cifar10_bin([p])
        assert x.shape == (5, 32, 32, 3)
        np.testing.assert_array_equal(y, labels.astype(np.int32))

    def test_truncated_file_raises(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin")
        with open(p, "wb") as f:
            f.write(b"\x00" * 100)  # not a multiple of 3073
        with pytest.raises(ValueError, match="3073-byte"):
            _read_cifar10_bin([p])


def test_mnist_idx_roundtrip(tmp_path, monkeypatch):
    """The idx parser against generated standard-format files."""
    rng = np.random.default_rng(0)
    imgs_tr = rng.integers(0, 256, (6, 28, 28), dtype=np.uint8)
    lab_tr = rng.integers(0, 10, 6, dtype=np.uint8)
    imgs_te = rng.integers(0, 256, (2, 28, 28), dtype=np.uint8)
    lab_te = rng.integers(0, 10, 2, dtype=np.uint8)

    def write_idx(path, arr):
        with open(path, "wb") as f:
            f.write(struct.pack(">I", 0x800 + (0x100 * 0) + arr.ndim))
            for d in arr.shape:
                f.write(struct.pack(">I", d))
            f.write(arr.tobytes())

    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs_tr)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), lab_tr)
    write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), imgs_te)
    write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), lab_te)
    monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
    x_tr, y_tr, x_te, y_te = load_mnist()
    assert x_tr.shape == (6, 28, 28, 1)
    np.testing.assert_array_equal(y_tr, lab_tr.astype(np.int32))
    np.testing.assert_allclose(x_te[1, 3, 4, 0], imgs_te[1, 3, 4] / 255.0)


class TestImageFolder:
    """ImageNet-layout folder loader on generated JPEG/PNG fixtures."""

    def _write_tree(self, root, classes, per_class, size=(40, 32)):
        from PIL import Image

        rng = np.random.default_rng(7)
        for cls in classes:
            os.makedirs(os.path.join(root, cls), exist_ok=True)
            for i in range(per_class):
                arr = rng.integers(0, 256, (*size, 3), dtype=np.uint8)
                ext = "png" if i % 2 else "jpg"
                Image.fromarray(arr).save(
                    os.path.join(root, cls, f"img_{i}.{ext}")
                )

    def test_decode_resize_crop_and_labels(self, tmp_path):
        from mpit_tpu.data.datasets import _read_image_folder

        self._write_tree(str(tmp_path), ["n01", "n02", "n03"], 2)
        x, y, classes = _read_image_folder(str(tmp_path), image_size=24)
        assert x.shape == (6, 24, 24, 3) and x.dtype == np.float32
        assert classes == ["n01", "n02", "n03"]
        np.testing.assert_array_equal(y, [0, 0, 1, 1, 2, 2])
        assert 0.0 <= x.min() and x.max() <= 1.0
        # random uint8 pixels: a decoded crop can't be constant
        assert x.std() > 0.1

    def test_load_imagenet_like_uses_folder(self, tmp_path, monkeypatch):
        from mpit_tpu.data import load_imagenet_like

        self._write_tree(
            str(tmp_path / "imagenet" / "train"), ["a", "b"], 3
        )
        self._write_tree(str(tmp_path / "imagenet" / "val"), ["a", "b"], 1)
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        x_tr, y_tr, x_te, y_te = load_imagenet_like(image_size=16)
        assert x_tr.shape == (6, 16, 16, 3)
        assert x_te.shape == (2, 16, 16, 3)
        np.testing.assert_array_equal(y_te, [0, 1])

    def test_holdout_when_no_val_split(self, tmp_path, monkeypatch):
        from mpit_tpu.data import load_imagenet_like

        self._write_tree(
            str(tmp_path / "imagenet" / "train"), ["a", "b"], 5
        )
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        x_tr, y_tr, x_te, y_te = load_imagenet_like(image_size=16)
        assert len(x_tr) == 9 and len(x_te) == 1

    def test_limit_caps_ram_and_keeps_class_coverage(
        self, tmp_path, monkeypatch
    ):
        from mpit_tpu.data import load_imagenet_like

        self._write_tree(
            str(tmp_path / "imagenet" / "train"), ["a", "b"], 4
        )
        self._write_tree(str(tmp_path / "imagenet" / "val"), ["a", "b"], 1)
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        monkeypatch.setenv("MPIT_IMAGENET_LIMIT", "3")
        x_tr, y_tr, *_ = load_imagenet_like(image_size=16)
        assert len(x_tr) <= 3
        # the cap is spread per class, not first-classes-win
        assert set(y_tr.tolist()) == {0, 1}

    def test_limit_is_hard_even_below_class_count(self, tmp_path):
        """limit < number of classes: the RAM bound wins over coverage."""
        from mpit_tpu.data.datasets import _read_image_folder

        self._write_tree(str(tmp_path), ["a", "b", "c", "d"], 2)
        x, y, _ = _read_image_folder(str(tmp_path), image_size=16, limit=2)
        assert len(x) == 2

    def test_val_labels_use_train_mapping(self, tmp_path, monkeypatch):
        """A val split whose class set differs from train must error, not
        silently relabel (labels across splits share one mapping)."""
        from mpit_tpu.data import load_imagenet_like

        self._write_tree(
            str(tmp_path / "imagenet" / "train"), ["a", "b"], 2
        )
        self._write_tree(str(tmp_path / "imagenet" / "val"), ["zz"], 1)
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        with pytest.raises(ValueError, match="label mapping"):
            load_imagenet_like(image_size=16)

    def test_unsupported_extensions_give_clear_error(
        self, tmp_path, monkeypatch
    ):
        from mpit_tpu.data.datasets import _read_image_folder

        os.makedirs(str(tmp_path / "a"))
        (tmp_path / "a" / "x.webp").write_bytes(b"notanimage")
        with pytest.raises(ValueError, match="decodable"):
            _read_image_folder(str(tmp_path), image_size=16)


class TestPrefetch:
    """Device prefetch pipeline: staged batches arrive with the worker
    sharding, in order, exactly once — at every depth."""

    def _topo(self):
        import mpit_tpu

        mpit_tpu.finalize()
        return mpit_tpu.init()

    def test_order_count_and_sharding(self):
        import jax

        from mpit_tpu.data import prefetch_to_device

        topo = self._topo()
        items = [
            (np.full((8, 2), i, np.float32), np.full((8,), i, np.int32))
            for i in range(7)
        ]
        for depth in (0, 1, 3, 10):
            out = list(
                prefetch_to_device(iter(items), topo.worker_sharding(),
                                   depth=depth)
            )
            assert len(out) == 7
            for i, (x, y) in enumerate(out):
                assert isinstance(x, jax.Array)
                assert x.sharding.spec == topo.worker_sharding().spec
                np.testing.assert_array_equal(np.asarray(y), items[i][1])

    def test_negative_depth_rejected(self):
        import pytest as _pytest

        from mpit_tpu.data import prefetch_to_device

        topo = self._topo()
        with _pytest.raises(ValueError, match="depth"):
            list(prefetch_to_device([], topo.worker_sharding(), depth=-1))

    def test_device_batches_wraps_epochs(self):
        from mpit_tpu.data import Batches, DeviceBatches

        topo = self._topo()
        x = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
        y = np.arange(64, dtype=np.int32)
        db = DeviceBatches(
            Batches(x, y, global_batch=16), topo, depth=2,
            transform=lambda xb, yb: (xb * 2.0, yb),
        )
        assert db.steps_per_epoch() == 4
        got = list(db.epoch(0))
        assert len(got) == 4
        # the transform ran before staging
        first_x = np.asarray(got[0][0])
        assert (first_x % 2 == 0).all()


class TestInputDtype:
    def test_float_cast_and_int_passthrough(self):
        import ml_dtypes

        from mpit_tpu.data import cast_input_dtype

        x = np.random.default_rng(0).uniform(0, 1, (4, 3)).astype(np.float32)
        xb = cast_input_dtype(x, "bf16")
        assert xb.dtype == ml_dtypes.bfloat16
        # bf16 is a pure narrowing of the same values (round-to-nearest)
        np.testing.assert_allclose(
            xb.astype(np.float32), x, rtol=1e-2, atol=1e-2
        )
        tokens = np.arange(5, dtype=np.int32)
        assert cast_input_dtype(tokens, "bf16") is tokens
        assert cast_input_dtype(x, "float32") is x

    def test_unknown_name_raises(self):
        from mpit_tpu.data import cast_input_dtype

        with pytest.raises(ValueError, match="unknown input dtype"):
            cast_input_dtype(np.zeros(2, np.float32), "fp8")


class TestHasRealDataset:
    """has_real_dataset must agree with the loaders' own file checks —
    a partial file set (which the loader would silently replace with
    synthetic data) must NOT count as real."""

    def test_partial_ptb_is_not_real(self, tmp_path, monkeypatch):
        from mpit_tpu.data.datasets import has_real_dataset

        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        assert not has_real_dataset("ptb")
        (tmp_path / "ptb.train.txt").write_text("a b c\n")
        assert not has_real_dataset("ptb")  # valid split missing
        (tmp_path / "ptb.valid.txt").write_text("a b\n")
        assert has_real_dataset("ptb")

    def test_cifar_requires_all_batches_and_finds_subdir(
        self, tmp_path, monkeypatch
    ):
        from mpit_tpu.data.datasets import has_real_dataset

        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        sub = tmp_path / "cifar-10-batches-bin"
        sub.mkdir()
        for i in range(1, 5):  # batch 5 missing
            (sub / f"data_batch_{i}.bin").write_bytes(b"x")
        (sub / "test_batch.bin").write_bytes(b"x")
        assert not has_real_dataset("cifar10")
        (sub / "data_batch_5.bin").write_bytes(b"x")
        assert has_real_dataset("cifar10")  # tarball subdir layout

    def test_mnist_requires_all_four_files(self, tmp_path, monkeypatch):
        from mpit_tpu.data.datasets import has_real_dataset

        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        for n in (
            "train-images-idx3-ubyte", "train-labels-idx1-ubyte",
            "t10k-images-idx3-ubyte",
        ):
            (tmp_path / n).write_bytes(b"x")
        assert not has_real_dataset("mnist")  # test labels missing
        (tmp_path / "t10k-labels-idx1-ubyte").write_bytes(b"x")
        assert has_real_dataset("mnist")

    def test_unset_dir_and_unknown_name(self, monkeypatch):
        from mpit_tpu.data.datasets import has_real_dataset

        monkeypatch.delenv("MPIT_DATA_DIR", raising=False)
        assert not has_real_dataset("mnist")
        monkeypatch.setenv("MPIT_DATA_DIR", "/nonexistent-dir")
        with pytest.raises(ValueError, match="unknown dataset"):
            has_real_dataset("nope")
