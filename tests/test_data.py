"""Dataset-loader tests on generated on-disk fixtures.

Real datasets are absent in this image (the loaders' synthetic fallbacks
cover the training tests); these tests prove the real-format parsers are
correct so that dropping the actual files under $MPIT_DATA_DIR just works
(round-1 verdict item 7)."""

import gzip
import os
import struct

import numpy as np
import pytest

from mpit_tpu.data import load_cifar10, load_mnist
from mpit_tpu.data.datasets import _read_cifar10_bin


def _write_cifar_bin(path, n, seed, gzipped=False):
    """Standard CIFAR-10 record: 1 label byte + 3072 channel-planar pixels."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    pixels = rng.integers(0, 256, (n, 3, 32, 32), dtype=np.uint8)
    rows = np.concatenate(
        [labels[:, None], pixels.reshape(n, -1)], axis=1
    ).astype(np.uint8)
    opener = gzip.open if gzipped else open
    with opener(path, "wb") as f:
        f.write(rows.tobytes())
    return labels, pixels


class TestCifarBin:
    def test_parse_values_and_layout(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin")
        labels, pixels = _write_cifar_bin(p, 7, seed=0)
        x, y = _read_cifar10_bin([p])
        assert x.shape == (7, 32, 32, 3) and x.dtype == np.float32
        np.testing.assert_array_equal(y, labels.astype(np.int32))
        # channel-planar source -> NHWC: pixel (n, c, h, w) lands at
        # x[n, h, w, c]
        np.testing.assert_allclose(
            x[3, 5, 9, 2], pixels[3, 2, 5, 9] / 255.0
        )
        assert x.min() >= 0.0 and x.max() <= 1.0

    def test_load_cifar10_from_dir(self, tmp_path, monkeypatch):
        sub = tmp_path / "cifar-10-batches-bin"
        sub.mkdir()
        for i in range(1, 6):
            _write_cifar_bin(str(sub / f"data_batch_{i}.bin"), 4, seed=i)
        te_labels, _ = _write_cifar_bin(str(sub / "test_batch.bin"), 3, seed=9)
        monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
        x_tr, y_tr, x_te, y_te = load_cifar10()
        assert x_tr.shape == (20, 32, 32, 3)
        assert x_te.shape == (3, 32, 32, 3)
        np.testing.assert_array_equal(y_te, te_labels.astype(np.int32))

    def test_gzipped_batches(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin.gz")
        labels, _ = _write_cifar_bin(p, 5, seed=2, gzipped=True)
        x, y = _read_cifar10_bin([p])
        assert x.shape == (5, 32, 32, 3)
        np.testing.assert_array_equal(y, labels.astype(np.int32))

    def test_truncated_file_raises(self, tmp_path):
        p = str(tmp_path / "data_batch_1.bin")
        with open(p, "wb") as f:
            f.write(b"\x00" * 100)  # not a multiple of 3073
        with pytest.raises(ValueError, match="3073-byte"):
            _read_cifar10_bin([p])


def test_mnist_idx_roundtrip(tmp_path, monkeypatch):
    """The idx parser against generated standard-format files."""
    rng = np.random.default_rng(0)
    imgs_tr = rng.integers(0, 256, (6, 28, 28), dtype=np.uint8)
    lab_tr = rng.integers(0, 10, 6, dtype=np.uint8)
    imgs_te = rng.integers(0, 256, (2, 28, 28), dtype=np.uint8)
    lab_te = rng.integers(0, 10, 2, dtype=np.uint8)

    def write_idx(path, arr):
        with open(path, "wb") as f:
            f.write(struct.pack(">I", 0x800 + (0x100 * 0) + arr.ndim))
            for d in arr.shape:
                f.write(struct.pack(">I", d))
            f.write(arr.tobytes())

    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs_tr)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), lab_tr)
    write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), imgs_te)
    write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), lab_te)
    monkeypatch.setenv("MPIT_DATA_DIR", str(tmp_path))
    x_tr, y_tr, x_te, y_te = load_mnist()
    assert x_tr.shape == (6, 28, 28, 1)
    np.testing.assert_array_equal(y_tr, lab_tr.astype(np.int32))
    np.testing.assert_allclose(x_te[1, 3, 4, 0], imgs_te[1, 3, 4] / 255.0)
