"""The CI lint gate, exercised exactly the way CI runs it.

The acceptance contract for the analysis subsystem:

- ``python -m mpit_tpu.analysis --format json`` over the package exits 0
  with ZERO non-baseline findings (and the baseline itself stays small and
  reviewed);
- the whole-package scan is fast enough for a pre-commit hook;
- the scan IMPORTS NOTHING it analyzes — it must be safe on code that
  would crash, hang, or initialize a TPU backend at import time.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

from mpit_tpu.analysis import lint

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_gate_json_exits_clean_with_no_new_findings():
    proc = _cli("--format", "json", str(PKG))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert doc["baselined"] > 0  # the baseline is in use, not bypassed
    assert doc["total_scanned"] == doc["baselined"]


def test_gate_script_passes_within_wall_clock_bound():
    """The full default run — all ten gates — must stay green AND
    inside the 35 s budget the model checker and the fuzz gate were
    sized for (state space and example count are knobs; this test is
    the governor). Two gates get their own sub-budgets, asserted from
    the per-gate timing lines the script prints for exactly this
    purpose: wire-schema (the 10k-example fuzz run plus corpus replay
    and the lockfile check) under 20 s, and numerics (three fixture
    scans plus the RT104 smoke) under 8 s."""
    start = time.monotonic()
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint.sh")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 35.0, f"lint gate took {elapsed:.1f}s (budget 35s)"
    # all the gates actually ran: state counts + conformance tally +
    # the wire-schema trio (lock check, fixtures, fuzz) + numerics
    assert "states" in proc.stdout, proc.stdout
    assert "violation(s)" in proc.stdout, proc.stdout
    assert "15 tag(s) match" in proc.stdout, proc.stdout
    assert "fuzz gate ok" in proc.stdout, proc.stdout
    assert "RT104 smoke ok" in proc.stdout, proc.stdout
    # per-gate wall-clock lines are the budget ledger: parse them and
    # hold the two heaviest gates to their own sub-budgets
    timings = {}
    for line in proc.stdout.splitlines():
        if line.startswith("[lint] gate "):
            parts = line.split()
            timings[parts[2]] = float(parts[3].rstrip("s"))
    assert "wire-schema" in timings, sorted(timings)
    assert timings["wire-schema"] < 20.0, timings
    assert "numerics" in timings, sorted(timings)
    assert timings["numerics"] < 8.0, timings
    # ten numbered gates + the warn-only bench-trend tail
    assert len(timings) == 11, sorted(timings)


def test_gate_fails_on_a_new_finding(tmp_path):
    bad = tmp_path / "drifted.py"
    bad.write_text(
        "import pickle\n"
        "# mpit-analysis: wire-boundary\n"
        "def frame(x):\n"
        "    return pickle.dumps(x, protocol=4)\n"
    )
    proc = _cli("--format", "json", "--no-baseline", str(bad))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["MPT007"]


def test_whole_package_scan_is_fast():
    """< 5 s in-process for the full package, cross-module passes
    included — the pre-commit-hook budget from the acceptance bar."""
    start = time.monotonic()
    lint.run_lint([PKG])
    assert time.monotonic() - start < 5.0


def test_scan_never_imports_analyzed_code(tmp_path):
    """Linting a module whose import has a visible side effect must not
    trigger that side effect (and must not crash on its bare
    ``raise``)."""
    marker = tmp_path / "imported.marker"
    mod = tmp_path / "boobytrap.py"
    mod.write_text(
        f"open({str(marker)!r}, 'w').close()\n"
        "raise RuntimeError('imported, not parsed')\n"
    )
    lint.run_lint([mod])
    assert not marker.exists()
