"""Roofline attribution tests (ISSUE 6: device/wire/idle split).

Layers under test: the interval algebra and the per-rank join in
``mpit_tpu.obs.merge.roofline`` (synthetic journals with known answers),
the real AsyncPSTrainer integration (client compute spans, server idle,
fractions summing to ~1.0), the chaos acceptance criterion (seeded
injected delay must land in the WIRE phase, not compute), the CLI, and
bench.py's two reporting paths (``phase_source: "timed-leg"`` for the
collective legs, ``"obs"`` for the host-async PS preset) plus the probe
cache/env-knob satellite.
"""

import json
import os

import numpy as np
import pytest

from mpit_tpu.obs import ObsConfig, roofline
from mpit_tpu.obs.__main__ import main as obs_main
from mpit_tpu.obs.merge import _merge_intervals, _overlap


def _write_rank(tmp_path, rank, recs):
    """Hand-authored journal with CONTROLLED wall-clock: the Journal class
    stamps its own ``t``, so synthetic known-answer fixtures write the
    JSONL directly."""
    with open(os.path.join(str(tmp_path), f"obs_rank{rank}.jsonl"),
              "w") as f:
        for r in recs:
            f.write(json.dumps({"rank": rank, **r}) + "\n")


class TestIntervalAlgebra:
    def test_merge_intervals(self):
        assert _merge_intervals([]) == []
        assert _merge_intervals([(1, 2), (3, 4)]) == [(1, 2), (3, 4)]
        assert _merge_intervals([(3, 5), (1, 2), (2, 4)]) == [(1, 5)]
        assert _merge_intervals([(1, 1), (2, 3)]) == [(2, 3)]  # empty drop

    def test_overlap(self):
        merged = _merge_intervals([(1, 3), (5, 7)])
        assert _overlap(0, 10, merged) == 4
        assert _overlap(2, 6, merged) == 2
        assert _overlap(3, 5, merged) == 0
        assert _overlap(8, 9, merged) == 0


class TestRooflineSynthetic:
    def test_known_answer_attribution(self, tmp_path):
        """Client: 1.0 s compute span, 0.1 s send + 0.5 s in-exchange recv
        wait (wire), 0.3 s out-of-span wait (idle) over a 2.5 s window —
        overhead is the 0.6 s remainder. Server: span-less, so its waits
        are idle."""
        _write_rank(tmp_path, 1, [
            {"ev": "span_b", "t": 0.0, "name": "compute", "span": 1},
            {"ev": "span_e", "t": 1.0, "name": "compute", "span": 1},
            {"ev": "span_b", "t": 1.0, "name": "exchange", "span": 2},
            {"ev": "send", "t": 1.1, "dst": 0, "mtag": 1, "n": 0,
             "bytes": 10, "dur": 0.1},
            {"ev": "recv", "t": 1.8, "src": 0, "mtag": 4, "n": 0,
             "bytes": 20, "wait": 0.5},
            {"ev": "span_e", "t": 2.0, "name": "exchange", "span": 2},
            {"ev": "recv", "t": 2.5, "src": 0, "mtag": 4, "n": 1,
             "bytes": 20, "wait": 0.3},
        ])
        _write_rank(tmp_path, 0, [
            {"ev": "recv", "t": 1.0, "src": 1, "mtag": 1, "n": 0,
             "bytes": 10, "wait": 0.8},
            {"ev": "send", "t": 1.5, "dst": 1, "mtag": 4, "n": 0,
             "bytes": 20, "dur": 0.1},
        ])
        rep = roofline([str(tmp_path)])
        cli = rep["ranks"][1]
        assert cli["role"] == "client"
        assert cli["compute_s"] == pytest.approx(1.0)
        assert cli["wire_s"] == pytest.approx(0.6)
        assert cli["idle_s"] == pytest.approx(0.3)
        assert cli["overhead_s"] == pytest.approx(0.6)
        assert cli["window_s"] == pytest.approx(2.5)
        assert cli["phases"]["compute"] == pytest.approx(0.4)
        assert sum(cli["phases"].values()) == pytest.approx(1.0)
        assert cli["exchanges"] == 1
        assert cli["exchange_mean_s"] == pytest.approx(1.0)
        srv = rep["ranks"][0]
        assert srv["role"] == "server"
        assert srv["idle_s"] == pytest.approx(0.8)  # span-less wait
        assert srv["wire_s"] == pytest.approx(0.1)
        assert sum(srv["phases"].values()) == pytest.approx(1.0)
        assert rep["run"]["ranks"] == 2 and rep["run"]["clients"] == 1
        assert sum(rep["run"]["phases"].values()) == pytest.approx(1.0)
        assert rep["straggler"] is None  # one client: no comparison

    def test_straggler_flagged(self, tmp_path):
        for rank, dur in ((1, 1.0), (2, 2.0)):
            _write_rank(tmp_path, rank, [
                {"ev": "span_b", "t": 0.0, "name": "compute", "span": 1},
                {"ev": "span_e", "t": dur, "name": "compute", "span": 1},
            ])
        rep = roofline([str(tmp_path)])
        assert rep["straggler"] == 2

    def test_unclosed_span_and_empty(self, tmp_path):
        # a killed rank's dangling span_b must not crash or count
        _write_rank(tmp_path, 1, [
            {"ev": "span_b", "t": 0.0, "name": "compute", "span": 1},
            {"ev": "send", "t": 0.5, "dst": 0, "mtag": 1, "n": 0,
             "bytes": 1, "dur": 0.1},
        ])
        rep = roofline([str(tmp_path)])
        assert rep["ranks"][1]["compute_s"] == 0.0
        assert rep["ranks"][1]["role"] == "client"  # the span DID open
        assert roofline([]) == {
            "ranks": {}, "run": None, "straggler": None
        }


class TestRooflineCLI:
    def test_exit_codes_and_output(self, tmp_path, capsys):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert obs_main(["roofline", str(empty)]) == 2
        run = tmp_path / "run"
        run.mkdir()
        _write_rank(run, 0, [
            {"ev": "recv", "t": 0.0, "src": 1, "mtag": 1, "n": 0,
             "bytes": 1, "wait": 0.2},
            {"ev": "send", "t": 0.5, "dst": 1, "mtag": 4, "n": 0,
             "bytes": 1, "dur": 0.1},
        ])
        assert obs_main(["roofline", str(run)]) == 0
        out = capsys.readouterr().out
        assert "server" in out and "compute" in out
        assert obs_main(["roofline", str(run), "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert set(rep["ranks"]["0"]["phases"]) == {
            "compute", "wire", "idle", "overhead"
        }


def _trainer(tmp_path, chaos=None):
    import jax.numpy as jnp
    import optax

    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import AsyncPSTrainer

    return AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo="easgd",
        tau=4,
        transport="inproc",
        chaos=chaos,
        obs=ObsConfig(dir=str(tmp_path)),
        max_exchange_failures=5,
        fetch_timeout=2.0,
        fetch_retries=3,
    )


@pytest.fixture(scope="module")
def mnist():
    from mpit_tpu.data import load_mnist

    return load_mnist(synthetic_train=2048, synthetic_test=512)


class TestRooflineTrainerIntegration:
    def test_real_run_attribution(self, tmp_path, mnist):
        x_tr, y_tr, *_ = mnist
        trainer = _trainer(tmp_path)
        trainer.train(x_tr, y_tr, steps=16, batch_size=32)
        rep = roofline([str(tmp_path)])
        assert set(rep["ranks"]) == {0, 1, 2}
        srv, c1, c2 = rep["ranks"][0], rep["ranks"][1], rep["ranks"][2]
        assert srv["role"] == "server" and srv["idle_s"] > 0
        for c in (c1, c2):
            assert c["role"] == "client"
            assert c["compute_s"] > 0  # the ps_roles compute spans landed
            assert c["exchanges"] == 16 // 4
        for row in rep["ranks"].values():
            assert abs(sum(row["phases"].values()) - 1.0) <= 0.02
        assert abs(sum(rep["run"]["phases"].values()) - 1.0) <= 0.02
        # the proof-of-completion barrier makes compute the clients'
        # dominant measured phase on this CPU workload
        assert c1["phases"]["compute"] > c1["phases"]["wire"]

    def test_chaos_delay_lands_in_wire_not_compute(self, tmp_path, mnist):
        """The ISSUE acceptance criterion: a seeded ChaosTransport delay
        run must attribute the injected latency to the WIRE phase. The
        chaos sleep happens inside the send path, under the telemetry
        wrapper's timer — so send ``dur`` (wire) absorbs it while the
        compute spans stay clean."""
        from mpit_tpu.transport import ChaosConfig

        x_tr, y_tr, *_ = mnist
        clean_dir = tmp_path / "clean"
        chaos_dir = tmp_path / "chaos"
        clean_dir.mkdir(), chaos_dir.mkdir()
        _trainer(clean_dir).train(x_tr, y_tr, steps=16, batch_size=32)
        chaos = ChaosConfig(
            seed=7, delay=1.0, delay_s=0.05, tags=(1, 2, 4)
        )
        _trainer(chaos_dir, chaos=chaos).train(
            x_tr, y_tr, steps=16, batch_size=32
        )
        clean = roofline([str(clean_dir)])
        delayed = roofline([str(chaos_dir)])
        clean_wire = sum(
            r["wire_s"] for r in clean["ranks"].values()
        )
        delayed_wire = sum(
            r["wire_s"] for r in delayed["ranks"].values()
        )
        # every send on tags 1/2/4 sleeps U(0, 50 ms); across ~9 sends
        # per client plus the PARAM replies the injected total is far
        # above anything the clean inproc run can produce
        assert delayed_wire > max(2 * clean_wire, 0.05), (
            clean_wire, delayed_wire,
        )
        # compute is real device time in BOTH runs — the injected sleep
        # must not inflate it (generous 2.5x bound for CPU timing noise)
        clean_compute = sum(
            r["compute_s"] for r in clean["ranks"].values()
        )
        delayed_compute = sum(
            r["compute_s"] for r in delayed["ranks"].values()
        )
        assert delayed_compute < 2.5 * clean_compute
        for rep in (clean, delayed):
            for row in rep["ranks"].values():
                assert abs(sum(row["phases"].values()) - 1.0) <= 0.02


class TestBenchIntegration:
    def test_leg_phases_schema_and_sum(self):
        import bench

        ph = bench._leg_phases(2.0, 1.8)
        assert set(ph) == {"compute", "wire", "idle", "overhead"}
        assert ph["compute"] == pytest.approx(0.9)
        assert sum(ph.values()) == pytest.approx(1.0, abs=1e-3)
        # degenerate leg: all overhead, still sums to 1.0
        assert sum(bench._leg_phases(0.0, 0.0).values()) == pytest.approx(
            1.0
        )
        # correction can never manufacture compute > 1
        assert bench._leg_phases(1.0, 2.0)["compute"] == 1.0

    def test_bench_ps_literal_reports_obs_phases(self):
        """THE acceptance assertion: the CPU bench emits
        ``phases: {compute, wire, idle, overhead}`` summing to
        1.0 ± 0.02, measured from real obs journals."""
        import bench

        res = bench.bench_ps_literal(cpu_smoke=True)
        assert res["phase_source"] == "obs"
        ph = res["phases"]
        assert set(ph) == {"compute", "wire", "idle", "overhead"}
        assert abs(sum(ph.values()) - 1.0) <= 0.02
        assert ph["compute"] > 0

    def test_backend_probe_cached_and_env_knob(self, monkeypatch):
        import bench

        from mpit_tpu.utils import vmesh

        monkeypatch.setattr(bench, "_PROBE_CACHE", {})
        monkeypatch.setenv("MPIT_BENCH_PROBE_TIMEOUT", "7")
        monkeypatch.delenv("MPIT_BENCH_PROBE_SECONDS", raising=False)
        calls = []

        def fake_run_bounded(code, timeout=None, quiet=False):
            calls.append(timeout)
            return 1  # probe fails

        monkeypatch.setattr(vmesh, "run_bounded", fake_run_bounded)
        assert bench._backend_alive() is False
        assert calls == [7.0, 7.0]  # env knob honored, both attempts
        assert bench._backend_alive() is False
        assert calls == [7.0, 7.0]  # cached: no re-probe this process
        tag = bench._probe_tag()
        assert tag["probe_seconds"] >= 0.0

    def test_probe_seconds_survives_reexec_env(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_PROBE_CACHE", {})
        monkeypatch.setenv("MPIT_BENCH_PROBE_SECONDS", "361.2")
        assert bench._probe_tag() == {"probe_seconds": 361.2}
        monkeypatch.setenv("MPIT_BENCH_PROBE_SECONDS", "")
        assert bench._probe_tag() == {}
