"""Load harness + SLO gates (docs/SERVING.md).

Layers under test: seeded workload generation (replay contract),
the open-loop harness against real Server/RNNServer instances with
request-lifecycle journaling, the constant-memory SLO aggregation and
its gate files, the `obs slo` CLI exit-code contract (0/1/2), seeded
serving chaos (the p99-moves-p50-doesn't pin and kill -> unfinished
accounting), the obs-off null path's cost bound, the Perfetto merge's
request tracks, and scripts/bench_gate.py's trajectory warnings.
"""

import importlib.util
import json
import os
import time

import pytest

from mpit_tpu.loadgen import (
    LoadHarness,
    LoadSpec,
    Request,
    ServeChaos,
    aggregate_paths,
    evaluate_gate,
    make_workload,
    validate_gate,
)
from mpit_tpu.loadgen.slo import _Hist
from mpit_tpu.obs.__main__ import main as obs_main

V, T = 17, 64


def _journals(d):
    import glob

    return sorted(glob.glob(os.path.join(str(d), "obs_rank*.jsonl")))


# ---------------------------------------------------------------- workload


class TestWorkload:
    def test_same_seed_token_identical_schedule(self):
        spec = LoadSpec(requests=40, rate=50.0, seed=7, cancel_prob=0.3)
        a = make_workload(spec, 101, max_len=64)
        b = make_workload(spec, 101, max_len=64)
        assert a == b
        c = make_workload(
            LoadSpec(requests=40, rate=50.0, seed=8, cancel_prob=0.3),
            101, max_len=64,
        )
        assert a != c

    def test_arrivals_strictly_increase(self):
        work = make_workload(LoadSpec(requests=30, seed=1), 101)
        times = [r.arrival_s for r in work]
        assert times == sorted(times) and times[0] > 0

    def test_max_len_clamp_and_token_range(self):
        spec = LoadSpec(
            requests=50, seed=2,
            prompt_buckets=((1, 60, 1.0),),
            output_buckets=((1, 60, 1.0),),
        )
        for r in make_workload(spec, V, max_len=16):
            assert 1 <= len(r.prompt)
            assert 1 <= r.max_new
            assert len(r.prompt) + r.max_new <= 16
            assert all(1 <= t < V for t in r.prompt)

    def test_cancel_prob_extremes(self):
        none = make_workload(
            LoadSpec(requests=20, seed=3, cancel_prob=0.0), 101
        )
        assert all(r.cancel_after_s is None for r in none)
        every = make_workload(
            LoadSpec(requests=20, seed=3, cancel_prob=1.0), 101
        )
        assert all(r.cancel_after_s is not None for r in every)
        # the cancel knob must not perturb the rest of the schedule
        # (unconditional draws keep the stream aligned)
        assert [r.prompt for r in none] == [r.prompt for r in every]

    def test_slo_scales_with_budget(self):
        spec = LoadSpec(requests=10, seed=4, slo_base_ms=100.0,
                        slo_per_token_ms=10.0)
        for r in make_workload(spec, 101):
            assert r.slo_ms == 100.0 + 10.0 * r.max_new

    def test_validation(self):
        with pytest.raises(ValueError, match="requests"):
            LoadSpec(requests=0)
        with pytest.raises(ValueError, match="rate"):
            LoadSpec(rate=0)
        with pytest.raises(ValueError, match="prompt_buckets"):
            LoadSpec(prompt_buckets=())
        with pytest.raises(ValueError, match="lo < hi"):
            LoadSpec(output_buckets=((5, 5, 1.0),))
        with pytest.raises(ValueError, match="vocab_size"):
            make_workload(LoadSpec(), 1)


class TestServeChaos:
    def test_draws_are_pure_functions_of_seed_and_boundary(self):
        a = ServeChaos(seed=9, delay_p=0.5, delay_s=0.1)
        b = ServeChaos(seed=9, delay_p=0.5, delay_s=0.1)
        draws = [a.draw(i) for i in range(50)]
        assert draws == [b.draw(i) for i in range(50)]
        assert any(d is not None for d in draws)
        assert any(d is None for d in draws)
        for d in draws:
            if d is not None:
                kind, s = d
                assert kind == "delay"
                assert 0.05 <= s <= 0.15  # +-50% jitter around delay_s

    def test_kill_after(self):
        c = ServeChaos(seed=0, kill_after=3)
        assert c.draw(2) is None
        assert c.draw(3) == ("kill", 0.0)
        assert c.draw(7) == ("kill", 0.0)

    def test_validation(self):
        with pytest.raises(ValueError, match="delay_p"):
            ServeChaos(delay_p=1.5)
        with pytest.raises(ValueError, match="kill_after"):
            ServeChaos(kill_after=-1)


# ------------------------------------------------------------- aggregation


class TestHist:
    def test_percentiles_within_geometric_quantization(self):
        h = _Hist()
        for _ in range(90):
            h.add(0.001)
        for _ in range(10):
            h.add(1.0)
        s = h.summary()
        assert s["count"] == 100
        assert s["p50_ms"] <= 1.0 * 1.1  # ~1ms, one bucket of slack
        assert 900.0 <= s["p99_ms"] <= 1100.0
        assert s["mean_ms"] == pytest.approx(100.9, rel=0.01)

    def test_empty(self):
        assert _Hist().summary() == {"count": 0}
        assert _Hist().percentile_ms(0.99) is None


def _write_lifecycle_journal(d, rows):
    path = os.path.join(str(d), "obs_rank0.jsonl")
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return path


def _three_request_rows():
    """2 finishes (one in SLO, one out) + 1 cancel, with segment time."""
    return [
        {"ev": "req_enqueue", "rid": 0, "t": 0.0, "p_len": 4,
         "max_new": 5, "slo_ms": 500.0},
        {"ev": "req_enqueue", "rid": 1, "t": 0.01, "p_len": 2,
         "max_new": 3, "slo_ms": 100.0},
        {"ev": "req_enqueue", "rid": 2, "t": 0.02, "p_len": 2,
         "max_new": 3},
        {"ev": "segment", "t": 0.2, "seg": 0, "occupied": 2,
         "nslots": 2, "waiting": 1, "dur": 0.18},
        {"ev": "req_first_token", "rid": 0, "t": 0.1},
        {"ev": "req_first_token", "rid": 1, "t": 0.12},
        {"ev": "req_finish", "rid": 0, "t": 0.3, "gen": 5,
         "reason": "budget"},
        {"ev": "req_finish", "rid": 1, "t": 0.4, "gen": 3,
         "reason": "eos"},
        {"ev": "req_cancel", "rid": 2, "t": 0.41, "where": "queued"},
    ]


class TestAggregator:
    def test_lifecycle_reduction(self, tmp_path):
        path = _write_lifecycle_journal(tmp_path, _three_request_rows())
        rep = aggregate_paths([path])
        assert rep["requests"] == {
            "submitted": 3, "finished": 2, "cancelled": 1,
            "unfinished": 0,
        }
        assert rep["finish_reasons"] == {"budget": 1, "eos": 1}
        assert rep["ttft"]["count"] == 2
        # rid 0: e2e 300ms <= 500 SLO; rid 1: 390ms > 100 -> missed;
        # cancelled rid 2 leaves the denominator
        assert rep["goodput"] == 0.5
        assert rep["queue_depth"]["max"] == 1
        assert rep["occupancy"] == 1.0  # 2 occupied of 2 slots
        assert rep["tokens"] == 8
        assert rep["dropped_records"] == 0

    def test_no_slo_meets_vacuously_and_default_retrofits(self, tmp_path):
        rows = [
            {"ev": "req_enqueue", "rid": 0, "t": 0.0},
            {"ev": "req_first_token", "rid": 0, "t": 0.1},
            {"ev": "req_finish", "rid": 0, "t": 0.5, "gen": 2,
             "reason": "eos"},
        ]
        path = _write_lifecycle_journal(tmp_path, rows)
        assert aggregate_paths([path])["goodput"] == 1.0
        assert aggregate_paths(
            [path], default_slo_ms=100.0
        )["goodput"] == 0.0

    def test_unfinished_counts_against_goodput(self, tmp_path):
        rows = [
            {"ev": "req_enqueue", "rid": 0, "t": 0.0, "slo_ms": 500.0},
            {"ev": "req_enqueue", "rid": 1, "t": 0.0, "slo_ms": 500.0},
            {"ev": "req_first_token", "rid": 0, "t": 0.05},
            {"ev": "req_finish", "rid": 0, "t": 0.1, "gen": 2,
             "reason": "eos"},
            {"ev": "serve_fault", "t": 0.2, "kind": "kill",
             "boundary": 3},
        ]
        path = _write_lifecycle_journal(tmp_path, rows)
        rep = aggregate_paths([path])
        assert rep["requests"]["unfinished"] == 1
        assert rep["goodput"] == 0.5
        assert rep["faults"] == {"kill": 1}

    def test_torn_tail_skipped(self, tmp_path):
        path = _write_lifecycle_journal(tmp_path, _three_request_rows())
        with open(path, "a") as f:
            f.write('{"ev": "req_enq')  # a crashed writer's last line
        assert aggregate_paths([path])["requests"]["submitted"] == 3


class TestGateFiles:
    def test_unknown_key_and_bad_value_rejected(self):
        with pytest.raises(ValueError, match="unknown gate key"):
            validate_gate({"ttft_p98_ms": 5})
        with pytest.raises(ValueError, match="unknown gate key"):
            validate_gate({"goodput": 0.9})
        with pytest.raises(ValueError, match="must be a number"):
            validate_gate({"ttft_p99_ms": True})
        validate_gate({"ttft_p99_ms": 250, "goodput_min": 0.9,
                       "min_finished": 1, "max_unfinished": 0,
                       "max_dropped_records": 0})

    def test_evaluate_directions(self, tmp_path):
        path = _write_lifecycle_journal(tmp_path, _three_request_rows())
        rep = aggregate_paths([path])
        assert evaluate_gate(rep, {"e2e_p99_ms": 10_000}) == []
        assert evaluate_gate(rep, {"e2e_p99_ms": 1}) != []
        assert evaluate_gate(rep, {"goodput_min": 0.4}) == []
        assert evaluate_gate(rep, {"goodput_min": 0.9}) != []
        assert evaluate_gate(rep, {"min_finished": 3}) != []
        assert evaluate_gate(rep, {"max_unfinished": 0}) == []

    def test_gated_percentile_without_samples_violates(self):
        rep = {"requests": {"submitted": 1, "finished": 0,
                            "cancelled": 0, "unfinished": 1},
               "ttft": {"count": 0}, "tpot": {"count": 0},
               "e2e": {"count": 0}, "goodput": None,
               "dropped_records": 0}
        out = evaluate_gate(rep, {"ttft_p99_ms": 250})
        assert out and "no samples" in out[0]
        out = evaluate_gate(rep, {"goodput_min": 0.5})
        assert out and "no eligible" in out[0]


class TestSloCli:
    """The exit-code contract: 0 clean, 1 gate violation, 2 usage/empty."""

    def test_report_and_pass_gate(self, tmp_path, capsys):
        _write_lifecycle_journal(tmp_path, _three_request_rows())
        assert obs_main(["slo", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "requests: 3 submitted" in out and "goodput" in out
        gate = tmp_path / "gate.json"
        gate.write_text('{"e2e_p99_ms": 10000, "min_finished": 2}')
        assert obs_main(["slo", str(tmp_path), "--gate",
                         str(gate)]) == 0

    def test_violation_exits_1(self, tmp_path, capsys):
        _write_lifecycle_journal(tmp_path, _three_request_rows())
        gate = tmp_path / "gate.json"
        gate.write_text('{"ttft_p99_ms": 0.001}')
        assert obs_main(["slo", str(tmp_path), "--gate",
                         str(gate)]) == 1
        assert "SLO VIOLATION" in capsys.readouterr().out

    def test_empty_and_bad_gate_exit_2(self, tmp_path, capsys):
        assert obs_main(["slo", str(tmp_path)]) == 2  # no journals
        sub = tmp_path / "norequests"
        sub.mkdir()
        _write_lifecycle_journal(sub, [{"ev": "send", "t": 0.0, "n": 0}])
        assert obs_main(["slo", str(sub)]) == 2  # journals, no requests
        _write_lifecycle_journal(tmp_path, _three_request_rows())
        gate = tmp_path / "gate.json"
        gate.write_text('{"nope_p99_ms": 5}')
        assert obs_main(["slo", str(tmp_path), "--gate",
                         str(gate)]) == 2
        capsys.readouterr()

    def test_json_output_carries_violations(self, tmp_path, capsys):
        _write_lifecycle_journal(tmp_path, _three_request_rows())
        gate = tmp_path / "gate.json"
        gate.write_text('{"goodput_min": 0.9}')
        assert obs_main(["slo", str(tmp_path), "--gate", str(gate),
                         "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["goodput"] == 0.5
        assert payload["violations"]


# ------------------------------------------------- harness against servers


def _model_params():
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _immediate_requests(n, seed=0, max_new=(3, 8)):
    """All-at-once arrivals: the harness submits every request before
    the first step, which makes boundary counts deterministic (the
    chaos comparison tests need identical scheduling across runs)."""
    import random

    rng = random.Random(seed)
    lo, hi = max_new
    return [
        Request(
            arrival_s=0.0,
            prompt=tuple(rng.randrange(1, V) for _ in range(
                rng.randrange(1, 7)
            )),
            max_new=rng.randrange(lo, hi),
            slo_ms=60_000.0,
        )
        for _ in range(n)
    ]


def _server(model, params, tmp_path=None, **kw):
    from mpit_tpu.models import Server
    from mpit_tpu.obs.core import ObsConfig

    obs = ObsConfig(dir=str(tmp_path)) if tmp_path is not None else None
    return Server(model, params, max_batch=2, segment=4, obs=obs, **kw)


class TestHarness:
    def test_load_run_journals_full_lifecycle(self, topo8, tmp_path):
        model, params = _model_params()
        srv = _server(model, params, tmp_path)
        reqs = _immediate_requests(8)
        rep = LoadHarness(srv, reqs).run()
        assert rep.submitted == 8 and not rep.killed
        assert len(rep.results) == 8  # every request completed
        report = aggregate_paths(_journals(tmp_path))
        assert report["requests"] == {
            "submitted": 8, "finished": 8, "cancelled": 0,
            "unfinished": 0,
        }
        # every finished request produced a TTFT and an e2e sample
        assert report["ttft"]["count"] == 8
        assert report["e2e"]["count"] == 8
        assert report["goodput"] == 1.0  # 60s SLOs on a warm smoke run
        assert report["segments"] == rep.boundaries
        assert report["tokens"] == sum(
            len(rep.results[r.rid]) - len(r.prompt) for r in reqs
        )
        assert report["occupancy"] is not None
        # ordering sanity on one rid: enqueue < first_token < finish
        recs = [json.loads(l) for l in open(_journals(tmp_path)[0])]
        by_rid = [r for r in recs if r.get("rid") == reqs[0].rid]
        evs = [r["ev"] for r in by_rid]
        assert evs.index("req_enqueue") < evs.index("req_first_token")
        assert evs.index("req_first_token") <= evs.index("req_finish")

    def test_results_match_obs_off_run(self, topo8, tmp_path):
        """Journaling must not change a single token."""
        model, params = _model_params()
        reqs = _immediate_requests(6, seed=5)
        on = LoadHarness(
            _server(model, params, tmp_path), _immediate_requests(6, seed=5)
        ).run()
        off = LoadHarness(_server(model, params), reqs).run()
        assert [on.results[r.rid] for r in on.requests.values()] == [
            off.results[r.rid] for r in off.requests.values()
        ]

    def test_cancellations_journaled_and_leave_denominator(
        self, topo8, tmp_path
    ):
        model, params = _model_params()
        srv = _server(model, params, tmp_path)
        reqs = _immediate_requests(8, seed=1, max_new=(20, 30))
        for r in reqs[:3]:
            r.cancel_after_s = 0.0  # due immediately after submission
        rep = LoadHarness(srv, reqs).run()
        assert rep.cancelled == 3
        report = aggregate_paths(_journals(tmp_path))
        assert report["requests"]["cancelled"] == 3
        assert report["requests"]["finished"] == 5
        assert report["goodput"] == 1.0  # cancelled leave the denominator
        wheres = [
            json.loads(l).get("where")
            for l in open(_journals(tmp_path)[0])
            if '"req_cancel"' in l
        ]
        assert len(wheres) == 3 and all(
            w in ("queued", "slot") for w in wheres
        )

    def test_kill_leaves_unfinished_and_penalizes_goodput(
        self, topo8, tmp_path
    ):
        model, params = _model_params()
        srv = _server(model, params, tmp_path)
        rep = LoadHarness(
            srv, _immediate_requests(8, max_new=(10, 20)),
            chaos=ServeChaos(seed=0, kill_after=1),
        ).run()
        assert rep.killed and rep.boundaries == 1
        report = aggregate_paths(_journals(tmp_path))
        assert report["requests"]["unfinished"] > 0
        assert report["faults"] == {"kill": 1}
        assert report["goodput"] < 1.0
        assert evaluate_gate(report, {"max_unfinished": 0}) != []

    def test_injected_delay_moves_p99_not_p50(self, topo8, tmp_path):
        """THE chaos-closure pin: a rare seeded stall late in the run
        stretches the tail (the requests spanning it) while the median
        request never sees it."""
        model, params = _model_params()
        delay_s = 0.5
        # warm every bucket shape first: a mid-run XLA compile is a
        # stall too, and it must not masquerade as (or mask) the
        # injected one in either run's tail
        LoadHarness(
            _server(model, params),
            _immediate_requests(24, seed=2, max_new=(3, 6)),
        ).run()
        clean = LoadHarness(
            _server(model, params, tmp_path / "clean"),
            _immediate_requests(24, seed=2, max_new=(3, 6)),
        ).run()
        nb = clean.boundaries
        assert nb >= 8  # enough boundaries for "late" to mean something
        # find a seed whose ONE delay lands in the last quarter of the
        # boundary schedule — deterministic, and the draw is a pure
        # function of (seed, boundary) so the search result replays
        seed = next(
            s for s in range(500)
            if (hits := [
                b for b in range(nb)
                if ServeChaos(seed=s, delay_p=0.04,
                              delay_s=delay_s).draw(b) is not None
            ]) and len(hits) == 1 and hits[0] >= (3 * nb) // 4
        )
        chaotic = LoadHarness(
            _server(model, params, tmp_path / "chaos"),
            _immediate_requests(24, seed=2, max_new=(3, 6)),
            chaos=ServeChaos(seed=seed, delay_p=0.04, delay_s=delay_s),
        ).run()
        assert chaotic.boundaries == nb  # identical scheduling
        a = aggregate_paths(_journals(tmp_path / "clean"))
        b = aggregate_paths(_journals(tmp_path / "chaos"))
        assert b["faults"] == {"delay": 1}
        # jitter bounds the injected stall to [0.5, 1.5] * delay_s
        p99_shift = b["e2e"]["p99_ms"] - a["e2e"]["p99_ms"]
        p50_shift = abs(b["e2e"]["p50_ms"] - a["e2e"]["p50_ms"])
        assert p99_shift > 0.3 * delay_s * 1e3, (p99_shift, p50_shift)
        assert p50_shift < 0.25 * delay_s * 1e3, (p99_shift, p50_shift)

    def test_rnn_server_under_load(self, topo8, tmp_path):
        import jax
        import jax.numpy as jnp

        from mpit_tpu.models import RNNServer
        from mpit_tpu.models.lstm import LSTMLM
        from mpit_tpu.obs.core import ObsConfig

        model = LSTMLM(
            vocab_size=V, embed_dim=12, hidden=16, num_layers=2,
            compute_dtype=jnp.float32,
        )
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        srv = RNNServer(
            model, params, max_batch=2, segment=3,
            obs=ObsConfig(dir=str(tmp_path)),
        )
        # no horizon: max_len=None exercises the RNN budget path
        work = make_workload(
            LoadSpec(requests=6, rate=1e4, seed=6), V, max_len=None
        )
        rep = LoadHarness(srv, work).run()
        assert len(rep.results) == 6
        report = aggregate_paths(_journals(tmp_path))
        assert report["requests"]["finished"] == 6
        assert report["ttft"]["count"] == 6
        assert report["tpot"]["count"] >= 1

    def test_obs_off_is_the_null_path(self, topo8):
        """The 2% pin, analytically: servers default to _obs None, and
        (hook sites per drain) x (measured cost of one is-None check)
        must stay under 2% of the drain's wall-clock."""
        model, params = _model_params()
        srv = _server(model, params)
        assert srv._obs is None
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            if srv._obs is not None:  # the exact guard every hook uses
                raise AssertionError
        per_check = (time.perf_counter() - t0) / n
        reqs = _immediate_requests(6, seed=4)
        t0 = time.perf_counter()
        rep = LoadHarness(srv, reqs).run()
        wall = time.perf_counter() - t0
        # generous over-count of guard sites: submit + admission +
        # per-segment + per-retirement, x10 slack
        hooks = 10 * (rep.boundaries + len(reqs))
        assert hooks * per_check < 0.02 * wall, (
            f"{hooks} checks x {per_check*1e9:.0f}ns vs {wall:.3f}s drain"
        )

    def test_merge_renders_request_tracks(self, topo8, tmp_path):
        from mpit_tpu.obs import merge_to_chrome_trace

        model, params = _model_params()
        srv = _server(model, params, tmp_path)
        LoadHarness(
            srv, _immediate_requests(5, seed=8),
            chaos=ServeChaos(seed=1, delay_p=1.0, delay_s=0.001),
        ).run()
        trace = merge_to_chrome_trace(_journals(tmp_path))
        evs = trace["traceEvents"]
        serve = [e for e in evs if e.get("cat") == "serve"]
        assert any(e["name"].startswith("prefill") for e in serve)
        assert any(e["name"] == "segment" for e in serve)
        assert all(e["ph"] == "X" and e["dur"] >= 1.0 for e in serve)
        # every request opens and closes one async span on tid 2
        opens = {e["id"] for e in evs
                 if e.get("cat") == "request" and e["ph"] == "b"}
        closes = {e["id"] for e in evs
                  if e.get("cat") == "request" and e["ph"] == "e"}
        assert len(opens) == 5 and opens == closes
        faults = [e for e in evs if e.get("cat") == "chaos"]
        assert faults and all(
            e["name"] == "fault delay" for e in faults
        )
        # timestamps non-negative and sorted (the merger's contract)
        ts = [e.get("ts", 0.0) for e in evs]
        assert min(ts) >= 0.0 and ts == sorted(ts)


# ------------------------------------------------------------- bench_gate


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "bench_gate.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_round(d, n, parsed):
    with open(os.path.join(str(d), f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": "",
                   "parsed": parsed}, f)


class TestBenchGate:
    def test_throughput_drop_and_slo_rise_flagged(self, tmp_path, capsys):
        bg = _bench_gate()
        base = {"metric": "serve_load_tokens_per_sec", "value": 100.0,
                "platform": "tpu", "ttft_p99_ms": 50.0, "goodput": 1.0}
        _bench_round(tmp_path, 1, base)
        _bench_round(tmp_path, 2, {**base, "value": 80.0,
                                   "ttft_p99_ms": 60.0, "goodput": 0.8})
        assert bg.main([str(tmp_path)]) == 0  # warn-only by default
        out = capsys.readouterr().out
        assert out.count("WARNING") == 3  # value, ttft_p99_ms, goodput
        assert bg.main(["--strict", str(tmp_path)]) == 1
        capsys.readouterr()

    def test_within_threshold_ok(self, tmp_path, capsys):
        bg = _bench_gate()
        base = {"metric": "m", "value": 100.0, "platform": "tpu",
                "e2e_p99_ms": 100.0}
        _bench_round(tmp_path, 1, base)
        _bench_round(tmp_path, 2, {**base, "value": 95.0,
                                   "e2e_p99_ms": 105.0})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "OK" in capsys.readouterr().out

    def test_platform_change_not_comparable(self, tmp_path, capsys):
        bg = _bench_gate()
        _bench_round(tmp_path, 1, {"metric": "m", "value": 1000.0,
                                   "platform": "tpu"})
        _bench_round(tmp_path, 2, {"metric": "m", "value": 1.0,
                                   "platform": "cpu",
                                   "platform_note": "smoke"})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out

    def test_exchange_mode_change_not_comparable(self, tmp_path, capsys):
        """An int8 round must never be scored against a raw round (or a
        different bucket/overlap/codec config) — that's an A/B pair, not
        a trajectory; the trend series must also stop at the boundary."""
        bg = _bench_gate()
        base = {"metric": "sync_dp_exchange_throughput",
                "platform": "cpu", "dp_bucket_bytes": 65536,
                "dp_overlap": False}
        _bench_round(tmp_path, 1, {**base, "value": 200.0,
                                   "dp_quant": "off"})
        _bench_round(tmp_path, 2, {**base, "value": 100.0,
                                   "dp_quant": "int8"})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # wire-codec knobs gate the PS legs the same way
        _bench_round(tmp_path, 3, {"metric": "m", "value": 100.0,
                                   "platform": "cpu",
                                   "wire_format": "pickle"})
        _bench_round(tmp_path, 4, {"metric": "m", "value": 50.0,
                                   "platform": "cpu",
                                   "wire_format": "framed"})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # same mode on both sides still flags a real drop
        _bench_round(tmp_path, 5, {**base, "value": 100.0,
                                   "dp_quant": "int8"})
        _bench_round(tmp_path, 6, {**base, "value": 50.0,
                                   "dp_quant": "int8"})
        assert bg.main(["--strict", str(tmp_path)]) == 1
        assert "WARNING" in capsys.readouterr().out
        # the trend series stops at the exchange-mode boundary: rounds
        # 2/5/6 share int8 but round 2's predecessor is raw — series is
        # the int8 suffix only (5,6 + 2 is non-contiguous; suffix = 5,6)
        tflags, tlabel = bg.trend(bg._load_rounds(str(tmp_path)), 0.10)
        assert tlabel == "" or "int8" in tlabel

    def test_shard_topology_change_not_comparable(self, tmp_path, capsys):
        """A resharded round (different shard count, or a ring-version
        bump from churn) serves different slices from different servers
        — score it as a new series, not a regression of the old one."""
        bg = _bench_gate()
        base = {"metric": "ps_exchange_throughput", "platform": "cpu",
                "ps_shards": 8, "ring_version": 0}
        _bench_round(tmp_path, 1, {**base, "value": 200.0})
        _bench_round(tmp_path, 2, {**base, "value": 100.0,
                                   "ps_shards": 16})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # same shard count but the ring churned: also a boundary
        _bench_round(tmp_path, 3, {**base, "value": 100.0,
                                   "ring_version": 2})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # identical topology on both sides still flags a real drop
        _bench_round(tmp_path, 4, {**base, "value": 50.0,
                                   "ring_version": 2})
        assert bg.main(["--strict", str(tmp_path)]) == 1
        assert "WARNING" in capsys.readouterr().out

    def test_fleet_shape_change_not_comparable(self, tmp_path, capsys):
        """A 3-replica round must never be scored against a 1-replica
        round (per-replica goodput/latency scales with fleet size), nor
        p2c against least-loaded — different fleet, not a regression."""
        bg = _bench_gate()
        base = {"metric": "serve_load_tokens_per_sec", "platform": "cpu",
                "replica_count": 3, "router_policy": "p2c"}
        _bench_round(tmp_path, 1, {**base, "value": 200.0})
        _bench_round(tmp_path, 2, {**base, "value": 100.0,
                                   "replica_count": 1})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # same fleet size but the routing policy changed: also a boundary
        _bench_round(tmp_path, 3, {**base, "value": 100.0,
                                   "router_policy": "least"})
        assert bg.main(["--strict", str(tmp_path)]) == 0
        assert "not comparable" in capsys.readouterr().out
        # identical fleet shape on both sides still flags a real drop
        _bench_round(tmp_path, 4, {**base, "value": 50.0,
                                   "router_policy": "least"})
        assert bg.main(["--strict", str(tmp_path)]) == 1
        assert "WARNING" in capsys.readouterr().out

    def test_fewer_than_two_rounds_is_clean(self, tmp_path, capsys):
        bg = _bench_gate()
        assert bg.main([str(tmp_path)]) == 0
        _bench_round(tmp_path, 1, {"metric": "m", "value": 1.0})
        assert bg.main([str(tmp_path)]) == 0
        assert "nothing to compare" in capsys.readouterr().out


# ------------------------------------------------------------- slow soak


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_serve_soak(topo8, tmp_path, seed, capsys):
    """Multi-seed serving soak: every seeded load run (cancels + mild
    chaos) must pass the checked-in smoke gate. scripts/serve_soak.sh
    widens the seed space per round via MPIT_SERVE_SOAK_OFFSET."""
    from mpit_tpu.loadgen.__main__ import main as loadgen_main

    seed += 10 * int(os.environ.get("MPIT_SERVE_SOAK_OFFSET", "0"))
    out = str(tmp_path / f"soak_{seed}")
    assert loadgen_main([
        "--out", out, "--seed", str(seed), "--requests", "16",
        "--rate", "500", "--cancel-prob", "0.1",
        "--chaos-delay-p", "0.05",
    ]) == 0
    gate = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "slo_smoke.json")
    assert obs_main(["slo", out, "--gate", gate]) == 0
    capsys.readouterr()
