"""Ring attention ≡ dense attention, on the simulated 8-device mesh.

The op is exact (online-softmax accumulation, not an approximation), so the
sharded result must match dense attention over the gathered sequence to
float tolerance — causal and non-causal, fp32 and bf16, uneven head dims.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpit_tpu
from mpit_tpu.ops import dense_attention, make_ring_attention


@pytest.fixture(scope="module")
def topo():
    mpit_tpu.finalize()
    t = mpit_tpu.init(num_workers=8)
    yield t
    mpit_tpu.finalize()


def _qkv(b=2, t=64, h=2, d=16, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((b, t, h, d)).astype(dtype)
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_fp32(self, topo, causal):
        q, k, v = _qkv()
        ring = make_ring_attention(
            topo.mesh, topo.worker_axis, causal=causal
        )
        got = np.asarray(ring(q, k, v))
        want = np.asarray(dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
        ))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_matches_dense_bf16(self, topo):
        q, k, v = _qkv(dtype=np.float32)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        ring = make_ring_attention(topo.mesh, topo.worker_axis, causal=True)
        got = np.asarray(ring(qb, kb, vb), dtype=np.float32)
        want = np.asarray(
            dense_attention(qb, kb, vb, causal=True), dtype=np.float32
        )
        # both paths share the bf16-inputs/f32-accumulate recipe; the ring
        # only reorders the same block contributions
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)

    def test_causal_prefix_invariance(self, topo):
        """Causal attention at position t must not change when the suffix
        after t changes — the defining property of the causal mask,
        checked across shard boundaries."""
        q, k, v = _qkv(t=32)
        ring = make_ring_attention(topo.mesh, topo.worker_axis, causal=True)
        base = np.asarray(ring(q, k, v))
        q2, k2, v2 = (x.copy() for x in (q, k, v))
        k2[:, 20:], v2[:, 20:] = 7.0, -3.0  # clobber the suffix
        got = np.asarray(ring(q, k2, v2))
        np.testing.assert_allclose(got[:, :20], base[:, :20], rtol=1e-5,
                                   atol=1e-5)
        assert not np.allclose(got[:, 21:], base[:, 21:])

    def test_memory_shape_is_blockwise(self, topo):
        """The sharded op never builds the (T, T) score matrix: every
        intermediate in the jaxpr (including sub-jaxprs — shard_map body,
        fori_loop body) has trailing dims far below T×T."""
        t = 64

        def walk(jaxpr, found):
            for eqn in jaxpr.eqns:
                for ov in eqn.outvars:
                    shape = getattr(ov.aval, "shape", ())
                    if len(shape) >= 2 and shape[-1] * shape[-2] >= t * t:
                        found.append((eqn.primitive.name, shape))
                for val in eqn.params.values():
                    for sub in (
                        val if isinstance(val, (tuple, list)) else (val,)
                    ):
                        inner = getattr(sub, "jaxpr", sub)
                        if hasattr(inner, "eqns"):
                            walk(inner, found)

        q, k, v = _qkv(t=t)
        ring = make_ring_attention(
            topo.mesh, topo.worker_axis, causal=False, jit=False
        )
        jaxpr = jax.make_jaxpr(ring)(q, k, v)
        found = []
        walk(jaxpr.jaxpr, found)
        assert not found, f"dense-sized intermediates in ring jaxpr: {found}"

    def test_rejects_bad_rank(self, topo):
        ring = make_ring_attention(topo.mesh, topo.worker_axis)
        with pytest.raises(ValueError, match=r"\(B, T, H, D\)"):
            q = jnp.zeros((2, 64, 16))
            ring(q, q, q)
