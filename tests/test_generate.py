"""Autoregressive decoding: exactness of the fixed-buffer recipe.

The sampler's one nontrivial claim is that causal attention makes the
suffix garbage in the fixed (1, max_len) buffer irrelevant — pinned
directly — and that a model trained to memorize a periodic stream
actually reproduces it greedily.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpit_tpu
from mpit_tpu.models import generate
from mpit_tpu.models.transformer import TransformerLM

V, T = 17, 32


def _model():
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )


def test_suffix_garbage_cannot_leak(topo8):
    """Logits at every prompt position depend only on tokens [0, p]:
    buffers padded with DIFFERENT random suffixes must agree on the
    whole prompt's logits, and greedy decode must match the
    prompt-only forward."""
    model = _model()
    prompt = [3, 1, 4, 1, 5]
    p_len = len(prompt)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)
    heads = []
    for _ in range(3):  # three different garbage suffixes
        buf = rng.integers(0, V, (1, T)).astype(np.int32)
        buf[0, :p_len] = prompt
        logits = model.apply({"params": params}, jnp.asarray(buf))
        heads.append(np.asarray(logits[0, :p_len]))
    np.testing.assert_allclose(heads[0], heads[1], rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(heads[0], heads[2], rtol=1e-6, atol=1e-6)
    # and the sampler's first step equals the prompt-only forward
    a = generate(model, params, prompt, steps=6)
    assert a == generate(model, params, prompt, steps=6)
    ref = model.apply(
        {"params": params}, jnp.asarray(prompt, jnp.int32)[None]
    )[0, -1]
    assert a[p_len] == int(jnp.argmax(ref))


def test_memorized_stream_continues(topo8):
    """Train on a periodic token stream until near-memorized; greedy
    decode must continue the period."""
    import optax

    from mpit_tpu.parallel import DataParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(num_workers=1)
    model = _model()
    tr = DataParallelTrainer(
        model, optax.adam(3e-3), topo, donate_state=False
    )
    stream = np.arange(8 * T * 2, dtype=np.int32) % V
    x = stream.reshape(-1, T)[:8]
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(1), x[:1])
    for _ in range(150):
        state, m = tr.step(state, x, y)
    assert float(m["loss"]) < 0.2, "did not memorize; test setup broken"
    prompt = list(range(8))  # 0..7 -> expect 8, 9, 10, ...
    out = generate(model, state.params, prompt, steps=8)
    assert out[8:] == [(8 + i) % V for i in range(8)], out
    mpit_tpu.finalize()


def test_temperature_sampling_reproducible(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    a = generate(model, params, [1, 2], steps=5, temperature=1.0, seed=7)
    b = generate(model, params, [1, 2], steps=5, temperature=1.0, seed=7)
    c = generate(model, params, [1, 2], steps=5, temperature=1.0, seed=8)
    assert a == b
    assert a != c  # overwhelmingly likely at T=1 from a random model


def test_validation(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, list(range(T + 1)), steps=1)
    with pytest.raises(ValueError, match="temperature"):
        generate(model, params, [1], steps=1, temperature=-1.0)
    with pytest.raises(ValueError, match="vocab_size"):
        generate(model, params, [1, 999], steps=1)
    sharded = model.clone(seq_axis="sp")
    with pytest.raises(ValueError, match="dense"):
        generate(sharded, params, [1], steps=1)


def test_window_slides_past_max_len(topo8):
    """Generation longer than max_len keeps going (sliding window)."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    out = generate(model, params, list(range(10)), steps=T + 5)
    assert len(out) == 10 + T + 5
    assert all(0 <= t < V for t in out)


# ---------------------------------------------------------------- fast path


def test_fast_matches_slow_greedy(topo8):
    """The KV-cached scan recipe and the fixed-buffer recipe are the same
    sampler: greedy outputs identical across prompt lengths and step
    counts (including bucket-boundary lengths)."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast

    for prompt, steps in [([3, 1, 4, 1, 5], 8), ([2], 1), ([7, 7], 15)]:
        assert generate_fast(model, params, prompt, steps) == generate(
            model, params, prompt, steps
        ), (prompt, steps)


def test_fast_matches_slow_sampled(topo8):
    """Same seed -> same draws: both recipes index one key per generated
    token from the same split, so sampled streams agree exactly."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast

    a = generate(model, params, [1, 2], steps=6, temperature=0.8, seed=7)
    b = generate_fast(
        model, params, [1, 2], steps=6, temperature=0.8, seed=7
    )
    assert a == b
    c = generate_fast(
        model, params, [1, 2], steps=6, temperature=0.8, seed=8
    )
    assert b != c  # overwhelmingly likely from a random model


def test_fast_validation(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast

    with pytest.raises(ValueError, match="cannot slide"):
        generate_fast(model, params, list(range(10)), steps=T)
    with pytest.raises(ValueError, match="vocab_size"):
        generate_fast(model, params, [999], steps=1)
    assert generate_fast(model, params, [1, 2], steps=0) == [1, 2]


def test_decode_mode_rejects_parallel_configs(topo8):
    """decode=True is the single-device dense serving path: sharded or
    MoE configurations must raise, not silently mis-attend."""
    model = _model().clone(decode=True, seq_axis="sp")
    with pytest.raises(ValueError, match="seq_axis"):
        model.init(jax.random.key(0), jnp.zeros((1, 1), jnp.int32))
    moe = _model().clone(decode=True, moe_experts=2)
    with pytest.raises(ValueError, match="dense-FFN"):
        moe.init(jax.random.key(0), jnp.zeros((1, 1), jnp.int32))


# ------------------------------------------------------------ top-k / top-p


def test_filter_logits_unit(topo8):
    from mpit_tpu.models.sampling import _filter_logits

    logits = jnp.array([0.0, 1.0, 2.0, 3.0])
    out = np.asarray(_filter_logits(logits, 2, None))
    assert np.isneginf(out[[0, 1]]).all() and (out[[2, 3]] == [2, 3]).all()
    # nucleus: softmax([0,1,2,3]) ~ [.032,.087,.237,.644]. top_p=0.6:
    # token 3 alone crosses (its before-mass 0 < .6; token 2's before-
    # mass .644 >= .6 -> dropped)
    out = np.asarray(_filter_logits(logits, None, 0.6))
    assert np.isneginf(out[[0, 1, 2]]).all() and out[3] == 3.0
    # top_p=0.85: {3, 2} (token 1's before-mass .881 >= .85 -> dropped)
    out = np.asarray(_filter_logits(logits, None, 0.85))
    assert np.isneginf(out[[0, 1]]).all() and (out[[2, 3]] == [2, 3]).all()
    # ties at the k-th value all survive
    out = np.asarray(_filter_logits(jnp.array([1.0, 2.0, 2.0, 0.0]), 2, None))
    assert np.isneginf(out[[0, 3]]).all() and (out[[1, 2]] == 2.0).all()


def test_top_k_one_is_greedy(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast

    greedy = generate(model, params, [3, 1], steps=6)
    for fn in (generate, generate_fast):
        assert fn(
            model, params, [3, 1], steps=6, temperature=1.0, top_k=1,
            seed=9,
        ) == greedy, fn.__name__


def test_top_filters_match_across_recipes(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast

    for kw in ({"top_k": 3}, {"top_p": 0.8}, {"top_k": 5, "top_p": 0.9}):
        a = generate(
            model, params, [1, 2], steps=6, temperature=0.9, seed=4, **kw
        )
        b = generate_fast(
            model, params, [1, 2], steps=6, temperature=0.9, seed=4, **kw
        )
        assert a == b, kw


def test_top_k_restricts_support(topo8):
    """Every sampled token must be one of the k most likely at its
    step: check against the step-by-step argsort of the slow recipe."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    k = 2
    for seed in range(4):
        toks = generate(
            model, params, [5], steps=5, temperature=2.0, top_k=k,
            seed=seed,
        )
        # recompute each step's top-k set from the prefix
        for i in range(1, 6):
            prefix = toks[:i]
            logits = model.apply(
                {"params": params}, jnp.asarray(prefix, jnp.int32)[None]
            )[0, -1]
            allowed = set(np.argsort(np.asarray(logits))[-k:].tolist())
            assert toks[i] in allowed, (seed, i)


def test_top_filter_validation(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, [1], 2, temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, [1], 2, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="greedy"):
        generate(model, params, [1], 2, top_k=3)


def test_min_p_filter_unit(topo8):
    """min-p keeps tokens at least min_p times as probable as the best
    (logit-space: l >= l_max + log(min_p)); min_p -> 0 keeps all."""
    from mpit_tpu.models import sampling

    logits = jnp.asarray([0.0, -1.0, -3.0, -10.0])
    out = sampling._filter_logits(
        logits, None, None, jnp.asarray(0.2)
    )  # threshold log(0.2) ~ -1.609: keep 0.0 and -1.0 only
    assert bool(jnp.isfinite(out[0])) and bool(jnp.isfinite(out[1]))
    assert out[2] == -jnp.inf and out[3] == -jnp.inf
    out0 = sampling._filter_logits(logits, None, None, jnp.asarray(0.0))
    assert bool(jnp.all(jnp.isfinite(out0)))


def test_min_p_matches_across_recipes_and_batch(topo8):
    """min_p through the three recipe layers: fast == slow at a fixed
    seed (alone and composed with top_k), and each batch row equals its
    solo call."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch, generate_fast

    for kw in ({"min_p": 0.25}, {"min_p": 0.1, "top_k": 5}):
        a = generate(
            model, params, [1, 2], steps=6, temperature=0.9, seed=4, **kw
        )
        b = generate_fast(
            model, params, [1, 2], steps=6, temperature=0.9, seed=4, **kw
        )
        assert a == b, kw
    rng = jax.random.key(7)
    rows = generate_batch(
        model, params, [[1, 2], [3], [4, 5, 6]], 5,
        temperature=0.8, min_p=0.3, rng=rng,
    )
    for i, q in enumerate([[1, 2], [3], [4, 5, 6]]):
        want = generate_fast(
            model, params, q, 5, temperature=0.8, min_p=0.3,
            rng=jax.random.fold_in(rng, i),
        )
        assert rows[i] == want, i


def test_min_p_restricts_support(topo8):
    """Every sampled token's probability is at least min_p times the
    step's best — checked against the slow recipe's own prefix
    forwards."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    mp = 0.4
    for seed in range(3):
        toks = generate(
            model, params, [5], steps=5, temperature=1.5, min_p=mp,
            seed=seed,
        )
        for i in range(1, 6):
            logits = model.apply(
                {"params": params},
                jnp.asarray(toks[:i], jnp.int32)[None],
            )[0, -1] / 1.5
            probs = np.asarray(jax.nn.softmax(logits))
            assert probs[toks[i]] >= mp * probs.max() - 1e-7, (seed, i)


def test_min_p_validation(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="min_p"):
        generate(model, params, [1], 2, temperature=1.0, min_p=0.0)
    with pytest.raises(ValueError, match="min_p"):
        generate(model, params, [1], 2, temperature=1.0, min_p=1.5)
    with pytest.raises(ValueError, match="greedy"):
        generate(model, params, [1], 2, min_p=0.5)


def test_top_p_sweep_shares_one_program(topo8):
    """top_p and min_p are traced thresholds: sweeping their values
    must not recompile the decode scan (only top_k — and switching a
    filter on/off — changes the program)."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_fast, sampling

    generate_fast(model, params, [1], 8, temperature=1.0, top_p=0.5)
    # generate_fast routes through the chunked-prefill kernel (single
    # prompt == uniform length); count compiles there
    n0 = sampling._prefill_decode_scan._cache_size()
    for p in (0.6, 0.8, 0.9, 0.95):
        generate_fast(model, params, [1], 8, temperature=1.0, top_p=p)
    assert sampling._prefill_decode_scan._cache_size() == n0
    generate_fast(model, params, [1], 8, temperature=1.0, min_p=0.1)
    n1 = sampling._prefill_decode_scan._cache_size()
    for mp in (0.2, 0.3, 0.5):
        generate_fast(model, params, [1], 8, temperature=1.0, min_p=mp)
    assert sampling._prefill_decode_scan._cache_size() == n1


# --------------------------------------------------------------- beam search


def test_beam_one_is_greedy(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search, generate_fast

    seq, score = beam_search(model, params, [3, 1, 4], steps=6, beam_size=1)
    assert seq == generate_fast(model, params, [3, 1, 4], steps=6)
    assert np.isfinite(score)


@pytest.mark.slow
def test_beam_matches_brute_force(topo8):
    """With beam_size >= V^(steps-1) the search is exhaustive: its best
    sequence must equal the argmax over ALL V^steps continuations scored
    by the full forward."""
    import itertools

    model = TransformerLM(
        vocab_size=5, num_layers=1, d_model=16, num_heads=2, max_len=8,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(2), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search

    prompt, steps = [2, 0], 3
    seq, score = beam_search(
        model, params, prompt, steps=steps, beam_size=25
    )

    best_bf, best_score = None, -np.inf
    for cont in itertools.product(range(5), repeat=steps):
        toks = prompt + list(cont)
        logits = model.apply(
            {"params": params}, jnp.asarray(toks, jnp.int32)[None]
        )[0]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        s = sum(
            float(logp[len(prompt) - 1 + i, cont[i]]) for i in range(steps)
        )
        if s > best_score:
            best_bf, best_score = toks, s
    assert seq == best_bf, (seq, best_bf)
    assert score == pytest.approx(best_score, abs=1e-3)


def test_beam_finds_no_worse_than_greedy(topo8):
    model = _model()
    params = model.init(
        jax.random.key(1), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search

    _, s1 = beam_search(model, params, [5, 2], steps=8, beam_size=1)
    _, s4 = beam_search(model, params, [5, 2], steps=8, beam_size=4)
    assert s4 >= s1 - 1e-5


def _replay_logprob(model, params, seq, p_len):
    """Sum of log P(seq[i] | seq[:i]) over the generated positions —
    the score beam_search must report for the sequence it returns."""
    logits = model.apply(
        {"params": params}, jnp.asarray(seq, jnp.int32)[None]
    )[0]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return sum(
        float(logp[i - 1, seq[i]]) for i in range(p_len, len(seq))
    )


def test_beam_eos_truncates_and_freezes(topo8):
    """A beam that emits eos keeps its score frozen; the returned
    sequence is cut just past the first eos beyond the prompt, and the
    reported score equals the replayed log-prob of exactly the returned
    tokens (overrun/eos padding contributing would break this). The eos
    id is chosen as greedy's third token so it is certainly emitted."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search, generate_fast

    prompt = [1, 2]
    eos = generate_fast(model, params, prompt, steps=3)[4]
    seq, score = beam_search(
        model, params, prompt, steps=10, beam_size=4, eos_id=eos
    )
    body = seq[len(prompt):]
    assert eos in body, "setup broken: chosen eos never emitted"
    assert seq[-1] == eos and eos not in body[:-1]
    assert len(seq) < len(prompt) + 10
    assert score == pytest.approx(
        _replay_logprob(model, params, seq, len(prompt)), abs=1e-3
    )


@pytest.mark.slow
def test_beam_score_is_replayable_at_non_pow2_budget(topo8):
    """steps whose scan bucket overruns the budget (total-1 not a power
    of two) must still return a score equal to the replayed log-prob of
    the returned tokens — the overrun ticks are frozen, not expanded."""
    model = _model()
    params = model.init(
        jax.random.key(1), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search

    prompt = [4, 4]
    for steps in (4, 6, 9):  # total-1 = 5, 7, 10 -> buckets 8, 8, 16
        seq, score = beam_search(
            model, params, prompt, steps=steps, beam_size=3
        )
        assert len(seq) == len(prompt) + steps
        assert score == pytest.approx(
            _replay_logprob(model, params, seq, len(prompt)), abs=1e-3
        ), steps


def test_beam_validation(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import beam_search

    with pytest.raises(ValueError, match="beam_size"):
        beam_search(model, params, [1], 2, beam_size=0)
    with pytest.raises(ValueError, match="eos_id"):
        beam_search(model, params, [1], 2, eos_id=99)
    with pytest.raises(ValueError, match="cannot slide"):
        beam_search(model, params, list(range(10)), steps=T)


# ------------------------------------------------------------------ batched


def test_batch_rows_equal_single_row_fast(topo8):
    """Row n of generate_batch == generate_fast(prompt_n,
    rng=fold_in(rng, n)) — greedy and sampled with filters, across
    mixed prompt lengths."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch, generate_fast

    prompts = [[3, 1, 4, 1, 5], [2], [7, 7, 7]]
    got = generate_batch(model, params, prompts, steps=6)
    for i, p in enumerate(prompts):
        assert got[i] == generate_fast(model, params, p, steps=6), i

    rng = jax.random.key(42)
    got = generate_batch(
        model, params, prompts, steps=6, temperature=0.9, rng=rng,
        top_k=5,
    )
    for i, p in enumerate(prompts):
        want = generate_fast(
            model, params, p, steps=6, temperature=0.9,
            rng=jax.random.fold_in(rng, i), top_k=5,
        )
        assert got[i] == want, i


def test_batch_edge_cases(topo8):
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch

    assert generate_batch(model, params, [], steps=4) == []
    assert generate_batch(model, params, [[1, 2]], steps=0) == [[1, 2]]
    with pytest.raises(ValueError, match="cannot slide"):
        generate_batch(model, params, [[1], list(range(10))], steps=T)
    with pytest.raises(ValueError, match="vocab_size"):
        generate_batch(model, params, [[1], [999]], steps=2)


def test_batch_size_bucketing_shares_programs(topo8):
    """Row counts bucket to powers of two: N=3 and N=4 share one
    compiled program (pad rows are discarded) — and mixed-length
    batches run the SAME per-row-prefill kernel as uniform ones (no
    separate all-ticks program to compile)."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch, sampling

    generate_batch(model, params, [[1]] * 4, steps=4)
    n0 = sampling._prefill_decode_scan._cache_size()
    out3 = generate_batch(model, params, [[1], [2], [3]], steps=4)
    assert sampling._prefill_decode_scan._cache_size() == n0
    assert len(out3) == 3 and all(len(r) == 5 for r in out3)
    # mixed lengths share the kernel too: same buckets as a UNIFORM
    # batch at the longest prompt's bucket -> NO new compile
    generate_batch(model, params, [[1, 2]] * 4, steps=4)
    n1 = sampling._prefill_decode_scan._cache_size()
    generate_batch(model, params, [[1], [2, 3], [4], [5, 6]], steps=4)
    assert sampling._prefill_decode_scan._cache_size() == n1


def test_mixed_lengths_prefill_per_row(topo8):
    """Per-row cache clocks: every row of a mixed-length batch prefills
    its ENTIRE prompt in the dense pass and stays bit-equal to its solo
    generate_fast call — greedy and sampled with filters, including
    1-token prompts and bucket pad rows (N=3 pads to 4 with dummy rows
    at the shortest real length)."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch, generate_fast

    for prompts, steps in [
        ([[3, 1, 4, 1, 5], [2, 6], [7, 7, 7]], 6),   # mixed, N pads to 4
        ([[5], [2, 6, 3]], 4),                       # 1-token shortest
        ([[3, 1, 4, 1], [2, 6], [7, 7, 7]], 5),      # pad-row case
    ]:
        got = generate_batch(model, params, prompts, steps=steps)
        for i, p in enumerate(prompts):
            assert got[i] == generate_fast(model, params, p, steps), (
                prompts, i
            )

    rng = jax.random.key(7)
    prompts = [[3, 1, 4, 1, 5], [2, 6], [7, 7, 7]]
    got = generate_batch(
        model, params, prompts, steps=6, temperature=0.8, rng=rng,
        top_k=5,
    )
    for i, p in enumerate(prompts):
        want = generate_fast(
            model, params, p, steps=6, temperature=0.8,
            rng=jax.random.fold_in(rng, i), top_k=5,
        )
        assert got[i] == want, i


# --------------------------------------------------------- tensor-parallel


def test_tp_decode_matches_plain(topo8):
    """generate_tp under a (2,4) dp x tp mesh is token-identical to
    generate_batch on replicated params — greedy and sampled+filtered
    (same kernel, same key streams; GSPMD just partitions it)."""
    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
    from mpit_tpu.models import generate_batch, generate_tp

    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    prompts = [[3, 1, 4, 1, 5], [2], [7, 7, 7]]
    assert generate_tp(
        model, params, prompts, steps=6, topo=topo
    ) == generate_batch(model, params, prompts, steps=6)
    kw = dict(temperature=0.9, seed=3, top_k=5)
    assert generate_tp(
        model, params, prompts, steps=6, topo=topo, **kw
    ) == generate_batch(model, params, prompts, steps=6, **kw)
    mpit_tpu.finalize()


def test_tp_decode_serves_tp_trainer_state(topo8):
    """The end-to-end Megatron story: train with TensorParallelTrainer,
    decode from its sharded state.params directly."""
    import optax

    from mpit_tpu.models import generate_fast, generate_tp
    from mpit_tpu.parallel import TensorParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
    model = _model()
    tr = TensorParallelTrainer(
        model, optax.sgd(0.1), topo, donate_state=False
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, V, (8, T)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(0), x[:1])
    state, _ = tr.step(state, x, y)
    got = generate_tp(model, state.params, [[1, 2, 3]], steps=5, topo=topo)
    # reference: the same (gathered) params through the plain recipe
    host = jax.tree.map(lambda a: np.asarray(a), jax.device_get(state.params))
    want = generate_fast(model, host, [1, 2, 3], steps=5)
    assert got[0] == want
    mpit_tpu.finalize()


def test_tp_decode_validation(topo8):
    from mpit_tpu.models import generate_tp

    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    # topo8 is the 1-D worker mesh: no tp axis
    with pytest.raises(ValueError, match="tp"):
        generate_tp(model, params, [[1]], steps=2)
    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(1, 8))
    with pytest.raises(ValueError, match="divisible"):
        generate_tp(model, params, [[1]], steps=2, topo=topo)  # heads=4
    mpit_tpu.finalize()


def test_weights_dtype_serving(topo8):
    """bf16 weight serving: outputs stay faithful on a trained model
    (the memorized stream continues identically), and the bench flag's
    cast leaves int leaves alone."""
    import optax

    from mpit_tpu.models import generate_batch, generate_fast
    from mpit_tpu.models.sampling import cast_weights
    from mpit_tpu.parallel import DataParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(num_workers=1)
    model = _model()
    tr = DataParallelTrainer(model, optax.adam(3e-3), topo,
                             donate_state=False)
    stream = np.arange(8 * T * 2, dtype=np.int32) % V
    x = stream.reshape(-1, T)[:8]
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(1), x[:1])
    for _ in range(150):
        state, _ = tr.step(state, x, y)
    prompt = list(range(8))
    full = generate_fast(model, state.params, prompt, 8)
    half = generate_fast(model, state.params, prompt, 8,
                         weights_dtype=jnp.bfloat16)
    assert half == full  # a memorized stream survives bf16 weights
    outs = generate_batch(model, state.params, [prompt], 8,
                          weights_dtype=jnp.bfloat16)
    assert outs[0] == full
    cast = cast_weights(state.params, jnp.bfloat16)
    dtypes = {a.dtype for a in jax.tree.leaves(cast)}
    assert jnp.dtype(jnp.bfloat16) in dtypes
    assert jnp.dtype(jnp.float32) not in dtypes
    mpit_tpu.finalize()


def test_eos_truncation_on_serving_paths(topo8):
    """eos_id cuts each returned row just past the first eos beyond its
    prompt (beam_search's rule) on generate_fast and generate_batch,
    and validates its range."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    from mpit_tpu.models import generate_batch, generate_fast

    prompt = [1, 2]
    # pick the token greedy emits at step 3 so truncation provably fires
    eos = generate_fast(model, params, prompt, steps=3)[4]
    full = generate_fast(model, params, prompt, steps=8)
    cut = generate_fast(model, params, prompt, steps=8, eos_id=eos)
    assert cut == full[: full.index(eos, len(prompt)) + 1]
    assert cut[-1] == eos and eos not in cut[len(prompt):-1]
    rows = generate_batch(
        model, params, [prompt, [3]], steps=8, eos_id=eos
    )
    assert rows[0] == cut
    with pytest.raises(ValueError, match="eos_id"):
        generate_fast(model, params, prompt, steps=2, eos_id=V)


# ----------------------------------------------------------- property-based


try:
    from hypothesis import given, settings, strategies as st  # noqa: E402
except ImportError:  # container without the dev extra: ONLY the property
    # tests below skip (via pytest.importorskip's canonical path, same as
    # tests/test_properties.py) — a module-level importorskip would throw
    # away the ~700 lines of example tests above, so the guard is scoped
    # to the @given-decorated tests alone
    class _DummyStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _DummyStrategies()

    def settings(*a, **k):
        return lambda f: f

    def given(*a, **k):
        def _deco(f):
            def _skip(*args, **kwargs):
                pytest.importorskip(
                    "hypothesis", reason="property tier needs hypothesis"
                )
            return _skip

        return _deco

_PROP_MODEL = None
_PROP_PARAMS = None


def _prop_setup():
    """One model+params for every hypothesis example (init is the
    expensive part; the property varies the REQUEST, not the weights)."""
    global _PROP_MODEL, _PROP_PARAMS
    if _PROP_MODEL is None:
        _PROP_MODEL = _model()
        _PROP_PARAMS = _PROP_MODEL.init(
            jax.random.key(0), jnp.zeros((1, T), jnp.int32)
        )["params"]
    return _PROP_MODEL, _PROP_PARAMS


@settings(max_examples=12, deadline=None)
@given(
    prompt=st.lists(st.integers(0, V - 1), min_size=1, max_size=10),
    steps=st.integers(1, 12),
    temperature=st.sampled_from([0.0, 0.7, 1.3]),
    seed=st.integers(0, 3),
)
@pytest.mark.slow
def test_property_fast_equals_slow(prompt, steps, temperature, seed):
    """For ANY request in range (prompt x steps x temperature x seed,
    within max_len), the KV-cached scan and the fixed-buffer recipe
    produce the same tokens — the serving path is a pure optimization."""
    from hypothesis import assume

    from mpit_tpu.models import generate_fast

    assume(len(prompt) + steps <= T)
    model, params = _prop_setup()
    a = generate(model, params, prompt, steps,
                 temperature=temperature, seed=seed)
    b = generate_fast(model, params, prompt, steps,
                      temperature=temperature, seed=seed)
    assert a == b, (prompt, steps, temperature, seed)


def test_head_logits_matches_full_forward(topo8):
    """head=False hidden states projected through head_logits equal the
    full forward's logits at every position — pins the embed table's
    param path the helper reaches into."""
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32)
    )["params"]
    x = jnp.asarray(
        np.random.default_rng(1).integers(0, V, (2, 8)), jnp.int32
    )
    full = model.apply({"params": params}, x)
    hidden = model.clone(head=False).apply({"params": params}, x)
    for pos in (0, 3, 7):
        got = model.head_logits(params, hidden[:, pos])
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full[:, pos]),
            rtol=1e-6, atol=1e-6,
        )
