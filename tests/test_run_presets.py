"""End-to-end driver tests: every BASELINE workload preset trains through
``mpit_tpu.run.run()`` on the simulated 8-device mesh (tiny scales — these
pin the wiring, not convergence; convergence is covered per-trainer)."""

import dataclasses
import json
import os

import pytest

# integration tier — excluded from the smoke run (full driver runs over every preset)
pytestmark = pytest.mark.slow

from mpit_tpu.run import run
from mpit_tpu.utils.config import TrainConfig


def _cfg(preset: str, **over) -> TrainConfig:
    return dataclasses.replace(TrainConfig().apply_preset(preset), **over)


class TestPresets:
    def test_mnist_easgd(self):
        r = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1))
        assert r["trained_units"] == 1  # 4 steps / tau 4 = 1 round
        assert 0.0 <= r["accuracy"] <= 1.0
        assert r["samples"] == 256 and r["workers"] == 8

    def test_mnist_ps_literal_shape(self):
        r = run(_cfg("mnist-ps", train_size=256, steps=8, global_batch=32))
        assert r["clients"] == 2 and r["servers"] == 1
        counts = r["server_counts"][0]
        assert counts["push_easgd"] == 2 * (8 // 4)  # 2 clients, tau=4
        assert 0.0 <= r["accuracy"] <= 1.0

    def test_cifar_vgg_sync(self):
        r = run(_cfg("cifar-vgg-sync", train_size=128, global_batch=32,
                     epochs=1))
        assert r["trained_units"] == 4
        assert "eval_loss" in r

    def test_alexnet_downpour(self):
        r = run(_cfg("alexnet-downpour", train_size=64, global_batch=32,
                     image_size=64, tau=2, epochs=1))
        assert r["trained_units"] == 1
        assert r["samples"] == 64

    def test_resnet50_sync(self):
        r = run(_cfg("resnet50-sync", train_size=16, global_batch=8,
                     image_size=64, epochs=1))
        assert r["trained_units"] == 2

    def test_mnist_easgd_bf16_inputs(self):
        # bf16 input staging is a storage change, not a math change: the
        # model cast its inputs to bf16 on entry already, so the run must
        # train end-to-end identically in structure (run.py input_dtype)
        r = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, input_dtype="bf16"))
        assert r["trained_units"] == 1
        assert 0.0 <= r["accuracy"] <= 1.0

    def test_pp_sync_transformer(self):
        # pipeline-parallel transformer end to end through the driver:
        # dp x pp mesh, both schedules
        for sched in ("gpipe", "1f1b"):
            r = run(_cfg("ptb-transformer-pp", pp=4, layers=4, n_micro=2,
                         train_size=64, global_batch=16, seq_len=32,
                         epochs=1, pp_schedule=sched))
            assert r["trained_units"] == 4, sched
            assert 0.0 <= r["accuracy"] <= 1.0 and "eval_loss" in r
            # batch shards over dp=2 of the (2, 4) mesh
            assert r["workers"] == 2, sched

    def test_pp_sync_rejects_non_transformer(self):
        with pytest.raises(ValueError, match="transformer-only"):
            run(_cfg("ptb-transformer-pp", model="lenet", dataset="mnist",
                     train_size=32, global_batch=8, epochs=1))

    def test_moe_sync_transformer(self):
        # expert-parallel MoE LM end to end through the driver: experts
        # shard over the 8-device worker axis
        r = run(_cfg("ptb-transformer-seq", algo="moe-sync",
                     moe_experts=16, moe_capacity_factor=8.0,
                     train_size=32, global_batch=8, seq_len=32, epochs=1))
        assert r["trained_units"] == 4
        assert 0.0 <= r["accuracy"] <= 1.0 and "eval_loss" in r
        assert r["workers"] == 8

    def test_moe_sync_requires_experts(self):
        with pytest.raises(ValueError, match="moe-experts"):
            run(_cfg("ptb-transformer-seq", algo="moe-sync",
                     train_size=32, global_batch=8, seq_len=32, epochs=1))

    def test_remat_trains_and_warns_on_unsupported_model(self):
        r = run(_cfg("ptb-transformer-seq", train_size=32, global_batch=8,
                     seq_len=32, sp=2, epochs=1, remat=True))
        assert r["trained_units"] == 4 and "eval_loss" in r
        with pytest.warns(UserWarning, match="remat is implemented"):
            run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, remat=True))

    def test_unknown_input_dtype_raises(self):
        with pytest.raises(ValueError, match="unknown input dtype"):
            run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, input_dtype="fp8"))

    def test_ptb_lstm_easgd(self):
        r = run(_cfg("ptb-lstm-easgd", train_size=64, global_batch=16,
                     seq_len=16, tau=2, epochs=1))
        assert r["trained_units"] == 2
        # token-level accuracy, properly normalized to [0, 1]
        assert 0.0 <= r["accuracy"] <= 1.0

    def test_ptb_transformer_seq(self):
        # sp=4 on the 8-device mesh: a (2, 4) dp x sp world, ring attention
        # in the compiled step; afterwards a default-algo run must rebuild
        # the 1-D world transparently (_world_for)
        from mpit_tpu.comm.topology import topology as current_topology

        r = run(_cfg("ptb-transformer-seq", train_size=32, global_batch=8,
                     seq_len=32, sp=4, epochs=1))
        assert r["trained_units"] == 4
        assert 0.0 <= r["accuracy"] <= 1.0 and "eval_loss" in r
        assert r["workers"] == 2  # dp extent of the (2, 4) mesh
        topo = current_topology()
        assert dict(topo.mesh.shape) == {"dp": 2, "sp": 4}
        r2 = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                      epochs=1))
        assert r2["workers"] == 8  # world rebuilt to the 1-D mesh

    def test_ptb_transformer_large_dims_reach_the_model(self):
        # the MFU-ceiling preset's width knobs must actually build a wider
        # model (run at toy scale — d_model shrunk, depth/heads kept)
        from mpit_tpu.run import _build_model

        cfg = _cfg("ptb-transformer-large", d_model=48, seq_len=32,
                   train_size=32, global_batch=8, epochs=1)
        model = _build_model(cfg, {"vocab_size": 100})
        assert (model.d_model, model.num_heads, model.num_layers) == (
            48, 12, 6
        )
        assert model.d_ff == 0  # 0 -> 4x d_model inside the block
        r = run(cfg)
        assert r["trained_units"] == 4
        assert 0.0 <= r["accuracy"] <= 1.0 and "eval_loss" in r


class TestDriverPlumbing:
    def test_optimizer_mismatch_rejected_any_algo(self, tmp_path):
        """An sgd checkpoint resumed with adam must fail the layout guard
        with a clear message on EVERY algo (the opt_state structure
        differs), not die inside from_bytes — the guard is not
        pp-sync-only."""
        base = _cfg("mnist-easgd", train_size=256, global_batch=64,
                    epochs=1, ckpt_dir=str(tmp_path / "ck"))
        run(base)
        with pytest.raises(ValueError, match="optimizer"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, optimizer="adam"))
        # a SCHEDULE flips the opt_state between scale (empty) and
        # scale_by_schedule (count leaf); clip_norm None->value grows the
        # chain's state tuple — both must fail the guard, not from_bytes
        with pytest.raises(ValueError, match="layout mismatch"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, lr_schedule="cosine"))
        with pytest.raises(ValueError, match="layout mismatch"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, clip_norm=0.5))

    def test_metrics_and_checkpoint(self, tmp_path):
        cfg = _cfg(
            "mnist-easgd", train_size=512, global_batch=64, epochs=1,
            metrics_path=str(tmp_path / "m.jsonl"),
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=1, log_every=1,
        )
        r = run(cfg)
        assert r["trained_units"] == 2
        assert r["last_checkpoint"] == 2
        lines = [json.loads(l)
                 for l in open(tmp_path / "m.jsonl").read().splitlines()]
        assert [l["step"] for l in lines] == [1, 2]
        meta = json.load(open(tmp_path / "ck" / "ckpt_00000002.json"))
        assert json.loads(meta["config"])["preset"] == "mnist-easgd"

    def test_resume_continues_unit_count(self, tmp_path):
        cfg = _cfg(
            "mnist-easgd", train_size=512, global_batch=64, epochs=1,
            ckpt_dir=str(tmp_path / "ck"),
        )
        r1 = run(cfg)
        assert r1["last_checkpoint"] == 2
        # epochs is TOTAL: resuming a finished 1-epoch run with epochs=2
        # trains exactly the second epoch
        r2 = run(dataclasses.replace(cfg, resume=True, epochs=2))
        assert r2["resumed_from"] == 2
        assert r2["trained_units"] == 2
        assert r2["last_checkpoint"] == 4
        # resuming with nothing left to do is a no-op, not an error
        r3 = run(dataclasses.replace(cfg, resume=True, epochs=2))
        assert r3["trained_units"] == 0

    def test_resume_matches_uninterrupted_schedule(self, tmp_path):
        """Interrupted+resumed training is BIT-IDENTICAL to uninterrupted:
        the resumed run must re-enter the same per-epoch data permutations
        (regression: unit counters were once fed in as epoch indices)."""
        base = _cfg("mnist-easgd", train_size=512, global_batch=64)
        straight = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "a")))
        run(dataclasses.replace(base, epochs=1, ckpt_dir=str(tmp_path / "b")))
        resumed = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "b"), resume=True))
        assert straight["last_checkpoint"] == resumed["last_checkpoint"] == 4
        a = (tmp_path / "a" / "ckpt_00000004.msgpack").read_bytes()
        b = (tmp_path / "b" / "ckpt_00000004.msgpack").read_bytes()
        assert a == b, "resumed state diverged from uninterrupted state"

    def test_pp_sync_resume_matches_uninterrupted(self, tmp_path):
        """The pipeline trainer's dict state checkpoints and resumes
        bit-identically through the same driver path as TrainState
        trainers."""
        base = _cfg("ptb-transformer-pp", pp=4, layers=4, n_micro=2,
                    train_size=64, global_batch=16, seq_len=32)
        straight = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "a")))
        run(dataclasses.replace(base, epochs=1,
                                ckpt_dir=str(tmp_path / "b")))
        resumed = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "b"), resume=True))
        assert resumed["resumed_from"] == 4
        assert straight["last_checkpoint"] == resumed["last_checkpoint"]
        a = (tmp_path / "a" / "ckpt_00000008.msgpack").read_bytes()
        b = (tmp_path / "b" / "ckpt_00000008.msgpack").read_bytes()
        assert a == b, "resumed pipeline state diverged"

    def test_pp_sync_resume_layout_mismatch_rejected(self, tmp_path):
        """A checkpoint written under the interleaved (chunk-permuted)
        layout must refuse to load into a differently-laid-out trainer
        instead of silently training layers in the wrong order."""
        base = _cfg("ptb-transformer-pp", pp=4, layers=8, n_micro=2,
                    pp_schedule="interleaved", train_size=32,
                    global_batch=16, seq_len=32,
                    ckpt_dir=str(tmp_path / "ck"))
        run(dataclasses.replace(base, epochs=1))
        with pytest.raises(ValueError, match="layout mismatch"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, pp_schedule="1f1b"))
        with pytest.raises(ValueError, match="layout mismatch"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, pp_virtual=1))
        # a different optimizer changes the opt_state STRUCTURE (adam's
        # moments vs sgd's trace) — guard must catch it here, not let
        # from_bytes fail with an opaque structure error
        with pytest.raises(ValueError, match="layout mismatch"):
            run(dataclasses.replace(
                base, resume=True, epochs=2, optimizer="adam"))
        # the original config resumes fine
        r = run(dataclasses.replace(base, resume=True, epochs=2))
        assert r["resumed_from"] == 2

    def test_optimizer_and_schedule_flags(self):
        """--optimizer / --lr-schedule reach the update rule: adamw with
        warmup-cosine trains and diverges from the sgd default; unknown
        names fail fast."""
        base = _cfg("mnist-easgd", train_size=256, global_batch=64,
                    epochs=1)
        default = run(base)
        adamw = run(dataclasses.replace(
            base, optimizer="adamw", lr=1e-3,
            lr_schedule="warmup-cosine", warmup_steps=2))
        assert adamw["trained_units"] == default["trained_units"]
        assert adamw["final_loss"] != default["final_loss"]
        cosine = run(dataclasses.replace(base, lr_schedule="cosine"))
        assert cosine["final_loss"] != default["final_loss"]
        with pytest.raises(ValueError, match="unknown optimizer"):
            run(dataclasses.replace(base, optimizer="lion"))
        with pytest.raises(ValueError, match="unknown lr_schedule"):
            run(dataclasses.replace(base, lr_schedule="step"))

    def test_zero_sync_resume_matches_uninterrupted(self, tmp_path):
        """ZeRO's sharded optimizer leaves round-trip through the same
        checkpoint path: resumed training is bit-identical."""
        base = _cfg("mnist-easgd", algo="zero-sync", train_size=512,
                    global_batch=64)
        straight = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "a")))
        run(dataclasses.replace(base, epochs=1,
                                ckpt_dir=str(tmp_path / "b")))
        resumed = run(dataclasses.replace(
            base, epochs=2, ckpt_dir=str(tmp_path / "b"), resume=True))
        assert straight["last_checkpoint"] == resumed["last_checkpoint"]
        a = (tmp_path / "a" / "ckpt_00000016.msgpack").read_bytes()
        b = (tmp_path / "b" / "ckpt_00000016.msgpack").read_bytes()
        assert a == b, "resumed ZeRO state diverged"

    def test_pp_sync_gpipe_resume_allows_pp_change(self, tmp_path):
        """Identity-layout schedules store globally-ordered layers, so
        restoring onto a different pp extent just re-shards — the
        layout guard must not false-reject it."""
        base = _cfg("ptb-transformer-pp", pp=4, layers=8, n_micro=2,
                    train_size=32, global_batch=16, seq_len=32,
                    ckpt_dir=str(tmp_path / "ck"))
        run(dataclasses.replace(base, epochs=1))
        r = run(dataclasses.replace(base, resume=True, epochs=2, pp=2))
        assert r["resumed_from"] == 2 and r["trained_units"] == 2

    def test_profile_trace(self, tmp_path):
        cfg = _cfg("mnist-easgd", train_size=256, global_batch=64, epochs=1,
                   profile_dir=str(tmp_path / "tr"))
        run(cfg)
        assert os.listdir(tmp_path / "tr")

    def test_unknown_algo_raises(self):
        with pytest.raises(ValueError, match="unknown algo"):
            run(TrainConfig(algo="gossip", train_size=256))


class TestEAMSGDAlias:
    """The paper's momentum variant as a named algo (reference goptim had
    an explicit EAMSGD optimizer; here it is EASGD + momentum local
    optimizer, and the alias asserts the momentum is actually on)."""

    def test_eamsgd_trains(self):
        r = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, algo="eamsgd"))
        assert r["trained_units"] == 1

    def test_eamsgd_requires_momentum(self):
        with pytest.raises(ValueError, match="momentum"):
            run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, algo="eamsgd", momentum=0.0))

    def test_ps_eamsgd_maps_to_easgd_protocol(self):
        r = run(_cfg("mnist-ps", train_size=256, steps=8, global_batch=32,
                     algo="ps-eamsgd"))
        assert r["server_counts"][0]["push_easgd"] == 2 * (8 // 4)

    def test_ps_eamsgd_requires_momentum(self):
        with pytest.raises(ValueError, match="momentum"):
            run(_cfg("mnist-ps", train_size=256, steps=8, global_batch=32,
                     algo="ps-eamsgd", momentum=0.0))

    def test_resolved_algo_is_the_single_rule(self):
        """All entry points (run(), PS path, process examples) resolve
        through TrainConfig.resolved_algo."""
        assert _cfg("mnist-easgd", algo="eamsgd").resolved_algo() == "easgd"
        assert (_cfg("mnist-ps", algo="ps-eamsgd").resolved_algo()
                == "ps-easgd")
        assert _cfg("mnist-easgd").resolved_algo() == "easgd"
        with pytest.raises(ValueError, match="momentum"):
            _cfg("mnist-easgd", algo="eamsgd", momentum=0.0).resolved_algo()


class TestExchangeDtypeFlag:
    def test_bad_value_rejected_for_every_algo(self):
        for algo in ("easgd", "sync", "ps-easgd"):
            preset = "mnist-ps" if algo.startswith("ps-") else "mnist-easgd"
            with pytest.raises(ValueError, match="exchange_dtype"):
                run(_cfg(preset, train_size=256, global_batch=64, epochs=1,
                         steps=4, algo=algo, exchange_dtype="bf-16"))

    def test_non_easgd_algo_warns_not_silent(self):
        with pytest.warns(UserWarning, match="exchange_dtype"):
            run(_cfg("cifar-vgg-sync", train_size=64, global_batch=32,
                     epochs=1, image_size=32, exchange_dtype="bf16"))

    def test_bf16_exchange_trains(self):
        r = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, exchange_dtype="bf16"))
        assert r["trained_units"] == 1

    def test_clip_norm_through_the_driver(self):
        # chained path (sync) trains; device-varying paths (zero-sync,
        # moe-sync) construct with the trainer-side mesh-correct clip
        # instead of the rejected optax chain
        import optax

        from mpit_tpu.run import _build_model, build_optimizer, build_trainer
        from mpit_tpu.comm.topology import topology as current_topology

        r = run(_cfg("mnist-easgd", train_size=256, global_batch=64,
                     epochs=1, clip_norm=0.5))
        assert r["trained_units"] == 1

        cfg = _cfg("mnist-easgd", algo="zero-sync", clip_norm=0.5)
        topo = current_topology()
        opt = build_optimizer(cfg, 10)
        tr = build_trainer(cfg, _build_model(cfg, {}), opt, topo)
        assert tr.clip_norm == 0.5  # reached the trainer, not the chain

    def test_pp_sync_pre_optax_checkpoint_rejected(self, tmp_path):
        # a checkpoint holding the old built-in-SGD state layout
        # ({params, momentum, step}) must fail the resume guard with a
        # clear message, not a from_bytes structure error
        import jax
        import jax.numpy as jnp

        from mpit_tpu.utils.checkpoint import save_checkpoint

        base = _cfg("ptb-transformer-pp", pp=2, layers=2, n_micro=2,
                    train_size=32, global_batch=16, seq_len=32, epochs=2,
                    ckpt_dir=str(tmp_path / "ck"))
        fake = {
            "params": {"w": jnp.zeros((2,))},
            "momentum": {"w": jnp.zeros((2,))},
            "step": jnp.zeros((), jnp.int32),
        }
        save_checkpoint(str(tmp_path / "ck"), fake, 2,
                        metadata={"config": base.to_json()})
        with pytest.raises(ValueError, match="pre-optax"):
            run(dataclasses.replace(base, resume=True))
