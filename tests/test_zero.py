"""ZeRO-1 sharded-optimizer DP ≡ plain sync DP, with state truly sharded.

The chunked update is pure bookkeeping for elementwise optimizers: the
trajectory must match DataParallelTrainer exactly, while Adam's mu/nu
live 1/W per device instead of replicated.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.models import LeNet
from mpit_tpu.parallel import DataParallelTrainer, ZeroDataParallelTrainer


def _data(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(0, 1, (n, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int32)
    return x, y


class TestZero:
    def test_matches_plain_dp_trajectory(self, topo8):
        """Adam through the chunked update equals replicated Adam."""
        model = LeNet(compute_dtype=jnp.float32)
        opt = optax.adam(1e-3)
        x, y = _data()
        results = {}
        for cls in (DataParallelTrainer, ZeroDataParallelTrainer):
            tr = cls(model, opt, topo8, donate_state=False)
            st = tr.init_state(jax.random.key(0), x[:2])
            losses = []
            for _ in range(3):
                st, m = tr.step(st, x, y)
                losses.append(float(m["loss"]))
            results[cls.__name__] = (
                losses,
                jax.tree.map(np.asarray, jax.device_get(st.params)),
                tr.evaluate(st, x, y),
            )
        a = results["DataParallelTrainer"]
        b = results["ZeroDataParallelTrainer"]
        np.testing.assert_allclose(b[0], a[0], rtol=1e-5)
        jax.tree.map(
            lambda p, q: np.testing.assert_allclose(p, q, atol=2e-5),
            b[1], a[1],
        )
        assert b[2][0] == pytest.approx(a[2][0], abs=1e-6)

    def test_optimizer_state_actually_sharded(self, topo8):
        """The point of ZeRO: Adam's mu/nu land P(worker-axis), 1/W per
        device, while params stay replicated."""
        model = LeNet(compute_dtype=jnp.float32)
        tr = ZeroDataParallelTrainer(
            model, optax.adam(1e-3), topo8, donate_state=False
        )
        x, y = _data()
        st = tr.init_state(jax.random.key(0), x[:2])
        axis = topo8.worker_axis
        flat_leaves = [
            a for a in jax.tree.leaves(st.opt_state)
            if getattr(a, "ndim", 0) == 1 and a.size >= 8
        ]
        assert flat_leaves, "no parameter-sized optimizer leaves found"
        for leaf in flat_leaves:
            assert leaf.sharding.spec[0] == axis, leaf.sharding
        # params replicated
        k = jax.tree.leaves(st.params)[0]
        assert all(s is None for s in (k.sharding.spec or [None]))
        # and the sharding survives a step
        st, _ = tr.step(st, x, y)
        mu = [
            a for a in jax.tree.leaves(st.opt_state)
            if getattr(a, "ndim", 0) == 1 and a.size >= 8
        ][0]
        assert mu.sharding.spec[0] == axis

    def test_composes_with_grad_accumulation(self, topo8):
        """Both memory knobs together: accumulated ZeRO equals plain DP
        on the same global batch."""
        model = LeNet(compute_dtype=jnp.float32)
        opt = optax.adam(1e-3)
        x, y = _data(n=32, seed=2)
        ref = DataParallelTrainer(model, opt, topo8, donate_state=False)
        st_r = ref.init_state(jax.random.key(0), x[:2])
        za = ZeroDataParallelTrainer(
            model, opt, topo8, donate_state=False, accum_steps=2
        )
        st_z = za.init_state(jax.random.key(0), x[:2])
        for _ in range(2):
            st_r, m_r = ref.step(st_r, x, y)
            st_z, m_z = za.step(st_z, x, y)
            np.testing.assert_allclose(
                float(m_z["loss"]), float(m_r["loss"]), rtol=1e-5
            )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5
            ),
            st_z.params, st_r.params,
        )
        with pytest.raises(ValueError, match="accum_steps"):
            za.step(st_z, x[:8], y[:8])  # per-worker 1 % 2 != 0

    def test_quantized_scatter_tracks_raw(self, topo8):
        """quant="int8" routes the reduce-scatter through the blockwise
        quantized codes (stateless — docs/WIRE.md); the trajectory must
        stay close to the raw scatter, and mode "off" must be it."""
        model = LeNet(compute_dtype=jnp.float32)
        opt = optax.sgd(0.1, momentum=0.9)
        x, y = _data(n=32, seed=4)
        results = {}
        for mode in ("off", "int8"):
            tr = ZeroDataParallelTrainer(
                model, opt, topo8, donate_state=False, quant=mode
            )
            assert tr.quant == mode
            st = tr.init_state(jax.random.key(0), x[:2])
            losses = []
            for _ in range(3):
                st, m = tr.step(st, x, y)
                losses.append(float(m["loss"]))
            results[mode] = (
                losses,
                jax.tree.map(np.asarray, jax.device_get(st.params)),
            )
        assert all(np.isfinite(results["int8"][0]))
        np.testing.assert_allclose(
            results["int8"][0], results["off"][0], atol=2e-2
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=5e-3),
            results["int8"][1], results["off"][1],
        )
        with pytest.raises(ValueError, match="quant"):
            ZeroDataParallelTrainer(
                model, optax.sgd(0.1), topo8, quant="fp4"
            )

    def test_cross_leaf_optimizer_rejected(self, topo8):
        """Global-norm clipping over a CHUNK would differ per device —
        the behavioral probe refuses it up front."""
        with pytest.raises(ValueError, match="ELEMENTWISE"):
            ZeroDataParallelTrainer(
                LeNet(),
                optax.chain(
                    optax.clip_by_global_norm(1.0), optax.sgd(0.1)
                ),
                topo8,
            )

    def test_fit_and_w_invariance(self):
        """fit() through the shared loop; W=8 equals W=1 on the same
        global batch (the psum_scatter mean is the full mean)."""
        from mpit_tpu.data import Batches

        model = LeNet(compute_dtype=jnp.float32)
        opt = optax.sgd(0.1, momentum=0.9)
        x, y = _data(n=32, seed=1)
        results = {}
        for w in (8, 1):
            mpit_tpu.finalize()
            topo = mpit_tpu.init(num_workers=w)
            tr = ZeroDataParallelTrainer(
                model, opt, topo, donate_state=False
            )
            st = tr.init_state(jax.random.key(0), x[:2])
            st, m = tr.fit(
                Batches(x, y, global_batch=16, seed=0), st, epochs=2
            )
            results[w] = (
                float(m["loss"]),
                jax.tree.map(np.asarray, jax.device_get(st.params)),
            )
            mpit_tpu.finalize()
        assert results[8][0] == pytest.approx(results[1][0], rel=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=3e-5),
            results[8][1], results[1][1],
        )


@pytest.mark.slow
class TestClipNorm:
    def test_clip_matches_optax_chain_on_plain_dp(self, topo8):
        """clip_norm through the chunked update == optax.clip_by_global_norm
        on plain sync DP (where the chain IS safe, since grads are
        pmean-ed before the update). Clipping must actually engage."""
        model = LeNet(compute_dtype=jnp.float32)
        x, y = _data()
        c = 0.05  # far below a fresh LeNet's CE gradient norm

        ref = DataParallelTrainer(
            model,
            optax.chain(optax.clip_by_global_norm(c), optax.sgd(0.1)),
            topo8, donate_state=False,
        )
        st_r = ref.init_state(jax.random.key(0), x[:2])
        # prove the threshold engages: the unclipped grad norm exceeds c
        g = jax.grad(
            lambda p: optax.softmax_cross_entropy_with_integer_labels(
                model.apply({"params": p}, jnp.asarray(x)), jnp.asarray(y)
            ).mean()
        )(st_r.params)
        assert float(optax.global_norm(g)) > c

        zt = ZeroDataParallelTrainer(
            model, optax.sgd(0.1), topo8, donate_state=False, clip_norm=c
        )
        st_z = zt.init_state(jax.random.key(0), x[:2])
        for _ in range(3):
            st_r, mr = ref.step(st_r, x, y)
            st_z, mz = zt.step(st_z, x, y)
            assert float(mz["loss"]) == pytest.approx(
                float(mr["loss"]), rel=1e-6
            )
        jax.tree.map(
            lambda p, q: np.testing.assert_allclose(
                np.asarray(p), np.asarray(q), atol=2e-6
            ),
            jax.device_get(st_z.params), jax.device_get(st_r.params),
        )

    def test_clip_validation(self, topo8):
        model = LeNet(compute_dtype=jnp.float32)
        with pytest.raises(ValueError, match="clip_norm"):
            ZeroDataParallelTrainer(
                model, optax.sgd(0.1), topo8, clip_norm=0.0
            )
