"""bf16 vocab-head quality guard (VERDICT r4 weak-item 3 / item 6).

The LM vocab heads compute with compute-dtype operands and f32
accumulation (transformer.py / lstm.py ``_head``). The equivalence
suites pin ``compute_dtype=float32`` configs, where that choice is
bit-identical — so the shipped bf16 path's numerical effect on training
was covered by no test. This file closes that hole with a synthetic
train-and-eval parity check, isolated to the HEAD via the
``head_dtype`` override: the trunk stays f32 in both arms, so the only
difference is the head matmul's operand precision (forward AND the
gradients that flow through it).

Tolerance: final losses within ``TOL_LOSS`` after ``STEPS`` steps on a
learnable task, with both arms required to actually learn (no vacuous
pass). The old LSTM recipe — logits *quantized to bf16 on output* —
fails the logit-precision bound asserted here (that is the regression
this guard exists to catch); bf16 operands with f32 accumulation pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpit_tpu.models.lstm import LSTMLM
from mpit_tpu.models.transformer import TransformerLM

V, T, B = 512, 32, 32
STEPS = 120
TOL_LOSS = 0.05  # |final f32-head loss - final bf16-head loss|


def _data(seed, n=B * 4):
    """Learnable synthetic LM: next token = (3*t + 7) mod V, with the
    sequence start randomized — a task the models drive to near-zero
    loss in ~100 steps, so a head-precision problem shows as a loss
    gap, not as noise."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, V, (n, 1))
    steps = np.arange(T + 1)[None, :]
    seq = (starts + 3 * steps * (starts % 5 + 1)) % V
    return seq[:, :T].astype(np.int32), seq[:, 1:].astype(np.int32)


def _train(model, seed=0):
    x, y = _data(seed=1)
    params = model.init(jax.random.key(seed), x[:2])["params"]
    opt = optax.adam(3e-3)
    ost = opt.init(params)

    def loss_fn(p, xb, yb):
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()

    @jax.jit
    def step(p, o, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        up, o = opt.update(g, o)
        return optax.apply_updates(p, up), o, loss

    first = None
    for i in range(STEPS):
        j = (i * B) % len(x)
        params, ost, loss = step(params, ost, x[j:j + B], y[j:j + B])
        if first is None:
            first = float(loss)
    xe, ye = _data(seed=2, n=B)
    eval_loss = float(loss_fn(params, xe, ye))
    return first, float(loss), eval_loss, params


def _transformer(**kw):
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=64, num_heads=4, max_len=T,
        compute_dtype=jnp.float32, **kw,
    )


def _lstm(**kw):
    return LSTMLM(
        vocab_size=V, embed_dim=32, hidden=64, num_layers=1,
        compute_dtype=jnp.float32, **kw,
    )


@pytest.mark.parametrize("family", ["transformer", "lstm"])
def test_bf16_head_trains_to_f32_head_quality(family):
    """Same seed, same data, f32 trunk: a bf16-operand/f32-accum head
    must land within TOL_LOSS of the all-f32 head on BOTH final train
    loss and held-out eval loss — and both arms must actually learn."""
    build = _transformer if family == "transformer" else _lstm
    first, f32_final, f32_eval, _ = _train(build())
    _, bf16_final, bf16_eval, _ = _train(build(head_dtype=jnp.bfloat16))
    assert f32_final < 0.5 * first, "reference arm failed to learn"
    assert bf16_final < 0.5 * first, "bf16-head arm failed to learn"
    assert abs(f32_final - bf16_final) < TOL_LOSS, (
        f"{family}: bf16 head drifted {abs(f32_final - bf16_final):.4f} "
        f"in train loss (tolerance {TOL_LOSS})"
    )
    assert abs(f32_eval - bf16_eval) < TOL_LOSS, (
        f"{family}: bf16 head drifted {abs(f32_eval - bf16_eval):.4f} "
        f"in eval loss (tolerance {TOL_LOSS})"
    )


@pytest.mark.parametrize("family", ["transformer", "lstm"])
def test_head_dtype_none_is_compute_dtype(family):
    """The override's identity contract: head_dtype=f32 on an f32 model
    is bit-identical to the default — the A/B above really isolates the
    head, and adding the knob changed nothing for every existing
    config."""
    build = _transformer if family == "transformer" else _lstm
    x, _ = _data(seed=3, n=4)
    m0, m1 = build(), build(head_dtype=jnp.float32)
    params = m0.init(jax.random.key(0), x)["params"]
    a = m0.apply({"params": params}, x)
    b = m1.apply({"params": params}, x)
    assert jnp.array_equal(a, b)


def test_accumulation_beats_output_quantization():
    """Why f32 accumulation is the contract: logits QUANTIZED to bf16 on
    output (the old LSTM recipe) violate the precision this guard's
    tolerance encodes — the shipped head's error vs an all-f32 head
    stays well inside the error output-quantization adds on top."""
    model = _transformer()
    x, _ = _data(seed=4, n=8)
    params = model.init(jax.random.key(0), x)["params"]
    f32_logits = model.apply({"params": params}, x)
    shipped = _transformer(head_dtype=jnp.bfloat16).apply(
        {"params": params}, x
    )
    old_recipe = f32_logits.astype(jnp.bfloat16).astype(jnp.float32)
    shipped_err = float(jnp.max(jnp.abs(shipped - f32_logits)))
    quant_err = float(jnp.max(jnp.abs(old_recipe - f32_logits)))
    # the shipped path keeps f32 output resolution; quantization floors
    # the error at bf16's 8-bit mantissa regardless of accumulation
    assert shipped.dtype == jnp.float32
    assert shipped_err < 2.0 * quant_err  # comparable forward error...
    probs_f32 = jax.nn.softmax(f32_logits)
    probs_ship = jax.nn.softmax(shipped)
    probs_old = jax.nn.softmax(old_recipe)
    # ...but the distribution the model SAMPLES from is strictly more
    # faithful through the shipped head than through output quantization
    d_ship = float(jnp.max(jnp.abs(probs_ship - probs_f32)))
    d_old = float(jnp.max(jnp.abs(probs_old - probs_f32)))
    assert d_ship <= d_old
