"""Failure-detection tests: heartbeat + watchdog for the async PS mode.

The reference had NO failure handling — 'a dead rank hangs the job'
(SURVEY.md §5). These tests pin the do-better semantics: a silent client is
declared dead within the timeout instead of blocking teardown forever;
heartbeats keep a compute-bound client alive; a late message revives a
declared-dead client."""

import threading
import time

import numpy as np
import pytest

from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_PUSH_EASGD,
    TAG_STOP,
    PServer,
    spawn_server_thread,
)
from mpit_tpu.transport import Broker

DIM = 16


def _world(num_clients: int, client_timeout: float):
    broker = Broker(1 + num_clients)
    tps = broker.transports()
    server = PServer(
        tps[0],
        np.zeros(DIM, np.float32),
        num_clients=num_clients,
        alpha=0.5,
        client_ranks=list(range(1, 1 + num_clients)),
        client_timeout=client_timeout,
    )
    thread = spawn_server_thread(server)
    return tps, server, thread


class TestWatchdog:
    def test_silent_client_declared_dead_server_exits(self):
        """One client stops cleanly, the other goes silent: the server must
        exit within ~timeout, not hang forever (the reference's behavior)."""
        tps, server, thread = _world(2, client_timeout=0.8)
        tps[1].send(0, TAG_PUSH_EASGD, np.ones(DIM, np.float32))
        tps[1].send(0, TAG_STOP, None)
        # client rank 2 never says anything at all
        thread.join(timeout=10)
        assert not thread.is_alive(), "server hung on a dead client"
        assert server.dead_clients == {2}
        assert server.error is None

    def test_heartbeat_keeps_slow_client_alive(self):
        """A client computing for longer than the timeout but heartbeating
        must NOT be declared dead."""
        # 12x margin between heartbeat and timeout: the test pins ordering
        # semantics, not tight wall-clock — loaded CI schedulers stall
        tps, server, thread = _world(1, client_timeout=1.2)
        client = PClient(
            tps[1], [0], DIM, heartbeat_interval=0.1
        )
        time.sleep(3.6)  # 3x the timeout: silence would be fatal
        assert thread.is_alive()  # still serving — not declared dead
        client.push_easgd(np.ones(DIM, np.float32))
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive()
        assert server.dead_clients == set()
        assert server.counts["heartbeat"] >= 3
        assert server.counts["push_easgd"] == 1

    def test_late_message_revives_dead_client(self):
        """Declared-dead then heard-from again: the client is revived and
        its eventual STOP (not the death record) ends the job. Client 2
        heartbeats throughout so the server deterministically outlives
        client 1's dead period."""
        tps, server, thread = _world(2, client_timeout=1.0)
        keeper = PClient(tps[2], [0], DIM, heartbeat_interval=0.05)
        deadline = time.monotonic() + 10
        while 1 not in server.dead_clients and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in server.dead_clients  # client 1 silent past the timeout
        assert thread.is_alive()  # client 2's heartbeats keep serving alive
        tps[1].send(0, TAG_PUSH_EASGD, np.ones(DIM, np.float32))  # revival
        tps[1].send(0, TAG_STOP, None)
        keeper.stop()
        thread.join(timeout=5)
        assert not thread.is_alive()
        assert server.dead_clients == set()
        assert server.counts["push_easgd"] == 1

    def test_timeout_requires_client_ranks(self):
        with pytest.raises(ValueError, match="client_ranks"):
            PServer(
                Broker(2).transports()[0],
                np.zeros(DIM, np.float32),
                num_clients=1,
                client_timeout=1.0,
            )

    def test_nonpositive_timeout_rejected(self):
        with pytest.raises(ValueError, match="positive"):
            PServer(
                Broker(2).transports()[0],
                np.zeros(DIM, np.float32),
                num_clients=1,
                client_ranks=[1],
                client_timeout=0.0,
            )
        import optax

        from mpit_tpu.models import MLP
        from mpit_tpu.parallel import AsyncPSTrainer

        with pytest.raises(ValueError, match="positive"):
            AsyncPSTrainer(
                MLP(), optax.sgd(0.1), client_timeout=0, transport="inproc"
            )


class TestElasticRecovery:
    """SURVEY.md §5's optional do-better: checkpoint-restart for the PS
    center + client rejoin. The reference loses everything with any dead
    process."""

    def test_server_persists_and_restores_center(self, tmp_path):
        path = str(tmp_path / "center_0.npy")
        broker = Broker(2)
        tps = broker.transports()
        server = PServer(
            tps[0], np.zeros(DIM, np.float32), num_clients=1,
            alpha=0.5, ckpt_path=path, ckpt_every=1,
        )
        thread = spawn_server_thread(server)
        tps[1].send(0, TAG_PUSH_EASGD, np.ones(DIM, np.float32))
        tps[1].send(0, TAG_STOP, None)
        thread.join(timeout=10)
        assert not thread.is_alive() and server.error is None
        want = server.snapshot()
        assert want[0] == pytest.approx(0.5)  # the elastic move landed

        # a RESTARTED server on the same path resumes the persisted center
        revived = PServer(
            Broker(2).transports()[0], np.zeros(DIM, np.float32),
            num_clients=1, ckpt_path=path,
        )
        assert revived.restored
        np.testing.assert_array_equal(revived.snapshot(), want)

        # resuming across a layout change must fail loudly, not corrupt
        with pytest.raises(ValueError, match="shape"):
            PServer(
                Broker(2).transports()[0],
                np.zeros(DIM + 1, np.float32),
                num_clients=1, ckpt_path=path,
            )

    def test_trainer_resume_continues_from_persisted_center(self, tmp_path):
        import jax.numpy as jnp
        import optax

        from mpit_tpu.data.synthetic import synthetic_image_classification
        from mpit_tpu.models import MLP
        from mpit_tpu.parallel import AsyncPSTrainer

        x, y, *_ = synthetic_image_classification(
            256, 64, (8, 8, 1), 10, seed=0
        )
        kw = dict(
            num_clients=2, num_servers=2, tau=4, transport="inproc",
            ckpt_dir=str(tmp_path), ckpt_every=1,
        )
        mk = lambda **extra: AsyncPSTrainer(
            MLP(hidden=(16,), compute_dtype=jnp.float32),
            optax.sgd(0.1), **kw, **extra,
        )
        _, stats = mk().train(x, y, steps=8, batch_size=32)
        assert stats["center_restored"] is False  # nothing to restore yet
        assert sorted(p.name for p in tmp_path.glob("center_*.npy")) == [
            "center_0.npy", "center_1.npy"
        ]
        # a restarted job (same dir) picks the persisted center up
        _, stats = mk().train(x, y, steps=8, batch_size=32)
        assert stats["center_restored"] is True
        # a deliberate fresh start drops the stale chunks instead
        _, stats = mk(resume=False).train(x, y, steps=8, batch_size=32)
        assert stats["center_restored"] is False

    def test_replacement_client_rejoins_after_death(self):
        """A REPLACEMENT client on a dead client's rank needs no state:
        it fetches the live center, pushes, and its first message revives
        the rank — the job ends cleanly with no dead clients."""
        tps, server, thread = _world(2, client_timeout=1.0)
        keeper = PClient(tps[2], [0], DIM, heartbeat_interval=0.05)
        keeper.push_easgd(np.full(DIM, 2.0, np.float32))
        deadline = time.monotonic() + 10
        while 1 not in server.dead_clients and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in server.dead_clients
        # rejoin: fresh PClient object over the dead rank's transport
        replacement = PClient(tps[1], [0], DIM)
        center = replacement.fetch()
        assert center[0] == pytest.approx(1.0)  # sees keeper's live push
        replacement.push_easgd(np.zeros(DIM, np.float32))
        replacement.stop()
        keeper.stop()
        thread.join(timeout=5)
        assert not thread.is_alive()
        assert server.dead_clients == set()
        assert server.counts["push_easgd"] == 2


class TestTrainerIntegration:
    def test_training_with_watchdog_completes_cleanly(self):
        import jax.numpy as jnp
        import optax

        from mpit_tpu.data.synthetic import synthetic_image_classification
        from mpit_tpu.models import MLP
        from mpit_tpu.parallel import AsyncPSTrainer

        x, y, xt, yt = synthetic_image_classification(
            256, 64, (8, 8, 1), 10, seed=0
        )
        tr = AsyncPSTrainer(
            MLP(hidden=(16,), compute_dtype=jnp.float32),
            optax.sgd(0.1),
            num_clients=2, num_servers=1, tau=4,
            client_timeout=10.0, transport="inproc",
        )
        center, stats = tr.train(x, y, steps=8, batch_size=32)
        assert stats["dead_clients"] == []
        assert stats["server_counts"][0]["push_easgd"] == 4
