"""Ulysses (all-to-all) sequence parallelism ≡ dense ≡ ring.

The all_to_all pair is pure data movement: head-sharded dense attention
over the re-gathered sequence must equal both the unsharded reference
and the ring formulation bit-for-bit (same math, different collective).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from jax.sharding import PartitionSpec as P
from mpit_tpu.models.transformer import TransformerLM
from mpit_tpu.ops import dense_attention, ulysses_attention
from mpit_tpu.ops.ring_attention import ring_attention
from mpit_tpu.parallel import SeqParallelTrainer

B, T, H, D = 2, 32, 8, 4
V = 29


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, T, H, D)).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


def _sharded(topo, fn):
    spec = P(None, topo.worker_axis)
    return jax.jit(jax.shard_map(
        fn, mesh=topo.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ))


class TestUlyssesOp:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_and_ring(self, topo8, causal):
        q, k, v = _qkv()
        axis = topo8.worker_axis
        uly = _sharded(
            topo8,
            lambda a, b, c: ulysses_attention(a, b, c, axis, causal=causal),
        )(q, k, v)
        ring = _sharded(
            topo8,
            lambda a, b, c: ring_attention(a, b, c, axis, causal=causal),
        )(q, k, v)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(uly), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(uly), np.asarray(ring), rtol=2e-5, atol=2e-5
        )

    def test_bf16(self, topo8):
        q, k, v = _qkv(seed=1, dtype=jnp.bfloat16)
        axis = topo8.worker_axis
        uly = _sharded(
            topo8,
            lambda a, b, c: ulysses_attention(a, b, c, axis, causal=True),
        )(q, k, v)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(uly, np.float32), np.asarray(want, np.float32),
            rtol=3e-2, atol=3e-2,
        )
        assert uly.dtype == jnp.bfloat16

    def test_head_divisibility_error(self, topo8):
        q, k, v = _qkv()
        q6 = q[:, :, :6]  # 6 heads over an 8-wide axis
        axis = topo8.worker_axis
        with pytest.raises(ValueError, match="divisible"):
            _sharded(
                topo8,
                lambda a, b, c: ulysses_attention(a, b, c, axis),
            )(q6, q6, q6)


class TestUlyssesTrainer:
    def _run(self, seq_impl, steps=3):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        model = TransformerLM(
            vocab_size=V, num_layers=2, d_model=32, num_heads=8,
            max_len=T, compute_dtype=jnp.float32, seq_axis="sp",
            seq_impl=seq_impl,
        )
        tr = SeqParallelTrainer(
            model, optax.sgd(0.1, momentum=0.9), topo, donate_state=False
        )
        rng = np.random.default_rng(0)
        x = rng.integers(0, V, (8, T)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        state = tr.init_state(jax.random.key(0), x[:2, : T // 4])
        losses = []
        for _ in range(steps):
            state, m = tr.step(state, x, y)
            losses.append(float(m["loss"]))
        params = jax.tree.map(np.asarray, jax.device_get(state.params))
        mpit_tpu.finalize()
        return losses, params

    @pytest.mark.slow
    def test_ulysses_matches_ring_trajectory(self):
        """Scheme choice is pure communication: identical training."""
        ring = self._run("ring")
        uly = self._run("ulysses")
        np.testing.assert_allclose(
            uly[0], ring[0], rtol=2e-5, atol=2e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-4
            ),
            uly[1], ring[1],
        )


def test_unknown_seq_impl_rejected(topo8):
    model = TransformerLM(
        vocab_size=V, max_len=T, seq_impl="ulyses"  # typo must not
    )                                               # silently run ring
    x = np.zeros((2, 8), np.int32)
    with pytest.raises(ValueError, match="must be 'ring' or 'ulysses'"):
        model.init(jax.random.key(0), x)
