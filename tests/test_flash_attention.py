"""Flash-attention kernel ≡ dense attention (interpret mode on CPU).

The pallas kernel must compute EXACTLY softmax(QKᵀ/√d)V — same contract
ring attention proves against the same reference — across causal and
full attention, dtypes, and block/sequence-size combinations, including
the online-softmax edge cases (multi-block running max updates, fully
masked leading blocks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.ops.flash_attention import flash_attention
from mpit_tpu.ops.ring_attention import dense_attention


def _qkv(b=2, t=256, h=2, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((b, t, h, d)).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_multiblock(self, causal):
        """T=256 with 128-blocks: two q-blocks x two k-blocks exercises
        the cross-block running-max correction and (causal) the
        skipped above-diagonal block."""
        q, k, v = _qkv()
        got = flash_attention(q, k, v, causal=causal, use_pallas=True)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_matches_dense_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=1)
        got = flash_attention(q, k, v, causal=True, use_pallas=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2, atol=2e-2,
        )

    def test_small_blocks_many_iterations(self):
        """Tiny blocks force many online-softmax folds per row."""
        q, k, v = _qkv(t=128, seed=2)
        got = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, use_pallas=True
        )
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_untileable_length_falls_back_to_dense(self):
        # t=100 clamps the block to 100, which is not sublane-aligned
        # (100 % 8 != 0) — the wrapper must take the dense path, never
        # hand pallas an uncompilable tile
        q, k, v = _qkv(t=100, seed=3)
        got = flash_attention(q, k, v, causal=True, use_pallas=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
        )

    @pytest.mark.parametrize(
        "t,blocks,causal,dtype",
        [
            (128, 128, True, jnp.float32),   # single block
            (256, 128, True, jnp.float32),   # multi-block + skip logic
            (256, 128, False, jnp.float32),  # full attention
            (128, 32, True, jnp.float32),    # many tiny blocks
            (256, 128, True, jnp.bfloat16),  # reduced-precision inputs
        ],
    )
    def test_gradients_match_dense(self, t, blocks, causal, dtype):
        """Training through the kernel: the custom VJP (pallas dQ and
        dK/dV kernels) must produce the same q/k/v gradients as
        differentiating dense attention."""
        q, k, v = _qkv(t=t, dtype=dtype, seed=5)
        tol = 2e-5 if dtype == jnp.float32 else 3e-2

        def loss(fn):
            return lambda q_, k_, v_: (
                fn(q_, k_, v_).astype(jnp.float32) ** 2
            ).mean()

        g_flash = jax.grad(
            loss(lambda a, b, c: flash_attention(
                a, b, c, causal=causal, block_q=blocks, block_k=blocks,
                use_pallas=True,
            )),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_dense = jax.grad(
            loss(lambda a, b, c: dense_attention(a, b, c, causal=causal)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for gf, gd in zip(g_flash, g_dense):
            np.testing.assert_allclose(
                np.asarray(gf, np.float32), np.asarray(gd, np.float32),
                rtol=tol, atol=tol,
            )

    @pytest.mark.slow
    def test_training_step_matches_xla(self):
        """One SGD step of the flash-attention model equals the xla
        model's step — the kernel is trainable, not forward-only."""
        from mpit_tpu.models.transformer import TransformerLM

        rng = np.random.default_rng(6)
        x = rng.integers(0, 31, (2, 128)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        base = TransformerLM(
            vocab_size=31, num_layers=1, d_model=32, num_heads=4,
            max_len=128, compute_dtype=jnp.float32,
        )
        params = base.init(jax.random.key(0), x)["params"]

        def step(model):
            def loss(p):
                logits = model.apply({"params": p}, x)
                logp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), -1
                )
                return -jnp.take_along_axis(
                    logp, jnp.asarray(y)[..., None], -1
                ).mean()

            g = jax.grad(loss)(params)
            return jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g)

        new_xla = step(base)
        new_flash = step(base.clone(attn_impl="flash_force"))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            ),
            new_xla, new_flash,
        )

    def test_model_wiring(self):
        """TransformerLM(attn_impl='flash_force') must equal the 'xla'
        model on the same params — the flag changes scheduling, never
        math."""
        from mpit_tpu.models.transformer import TransformerLM

        x = np.random.default_rng(4).integers(0, 31, (2, 128)).astype(
            np.int32
        )
        base = TransformerLM(
            vocab_size=31, num_layers=2, d_model=32, num_heads=4,
            max_len=128, compute_dtype=jnp.float32,
        )
        params = base.init(jax.random.key(0), x)["params"]
        ref = base.apply({"params": params}, x)
        flash = base.clone(attn_impl="flash_force")
        got = flash.apply({"params": params}, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
