"""Flash-attention kernel ≡ dense attention (interpret mode on CPU).

The pallas kernel must compute EXACTLY softmax(QKᵀ/√d)V — same contract
ring attention proves against the same reference — across causal and
full attention, dtypes, and block/sequence-size combinations, including
the online-softmax edge cases (multi-block running max updates, fully
masked leading blocks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.ops.flash_attention import flash_attention
from mpit_tpu.ops.ring_attention import dense_attention


def _qkv(b=2, t=256, h=2, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((b, t, h, d)).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_multiblock(self, causal):
        """T=256 with 128-blocks: two q-blocks x two k-blocks exercises
        the cross-block running-max correction and (causal) the
        skipped above-diagonal block."""
        q, k, v = _qkv()
        got = flash_attention(q, k, v, causal=causal, use_pallas=True)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_matches_dense_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=1)
        got = flash_attention(q, k, v, causal=True, use_pallas=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2, atol=2e-2,
        )

    def test_small_blocks_many_iterations(self):
        """Tiny blocks force many online-softmax folds per row."""
        q, k, v = _qkv(t=128, seed=2)
        got = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, use_pallas=True
        )
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_untileable_length_falls_back_to_dense(self):
        # t=100 clamps the block to 100, which is not sublane-aligned
        # (100 % 8 != 0) — the wrapper must take the dense path, never
        # hand pallas an uncompilable tile
        q, k, v = _qkv(t=100, seed=3)
        got = flash_attention(q, k, v, causal=True, use_pallas=True)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
        )

    def test_model_wiring(self):
        """TransformerLM(attn_impl='flash_force') must equal the 'xla'
        model on the same params — the flag changes scheduling, never
        math."""
        from mpit_tpu.models.transformer import TransformerLM

        x = np.random.default_rng(4).integers(0, 31, (2, 128)).astype(
            np.int32
        )
        base = TransformerLM(
            vocab_size=31, num_layers=2, d_model=32, num_heads=4,
            max_len=128, compute_dtype=jnp.float32,
        )
        params = base.init(jax.random.key(0), x)["params"]
        ref = base.apply({"params": params}, x)
        flash = base.clone(attn_impl="flash_force")
        got = flash.apply({"params": params}, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
