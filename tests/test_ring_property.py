"""Property tier for the consistent-hash ring (optional: hypothesis).

Randomized member sets and churn sequences against the two invariants
the example-based pins in ``test_sharding.py`` can only sample:
stability (keys owned by survivors never move on a leave) and
canonicalization (enumeration order and duplicates never change the
ring). Skipped wholesale when hypothesis is not installed — the
deterministic pins still hold the line.
"""

import pytest

pytest.importorskip("hypothesis", reason="property tier needs hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from mpit_tpu.comm.topology import HashRing  # noqa: E402

members_st = st.lists(
    st.integers(min_value=0, max_value=31),
    min_size=2, max_size=6, unique=True,
)


@settings(max_examples=25, deadline=None)
@given(members=members_st, data=st.data())
def test_leave_never_moves_survivor_keys(members, data):
    leaver = data.draw(st.sampled_from(members))
    ring = HashRing(members, vnodes=16)
    shrunk = ring.without(leaver)
    for k in range(64):
        old = ring.owner(k)
        if old != leaver:
            assert shrunk.owner(k) == old
        else:
            assert shrunk.owner(k) != leaver


@settings(max_examples=25, deadline=None)
@given(members=members_st, data=st.data())
def test_enumeration_order_is_canonicalized(members, data):
    perm = data.draw(st.permutations(members))
    a = HashRing(members, vnodes=16)
    b = HashRing(list(perm) + [perm[0]], vnodes=16)  # dup too
    assert a == b
    for k in range(64):
        assert a.owner(k) == b.owner(k)
