"""End-to-end sync allreduce DP: the TPU-native `ptest`-class smoke test
(SURVEY.md §4: keep an MNIST e2e as the canonical integration test, plus the
unit checks the reference lacked)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.data import Batches, load_mnist
from mpit_tpu.models import LeNet
from mpit_tpu.parallel import DataParallelTrainer


@pytest.fixture
def mnist():
    return load_mnist(synthetic_train=2048, synthetic_test=512)


def test_grad_averaging_matches_single_worker(topo8):
    """8-worker DP on a global batch must equal 1 worker on the same batch:
    the collective average reproduces the full-batch gradient."""
    model = LeNet(compute_dtype=jnp.float32)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (16, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 16).astype(np.int32)

    t8 = DataParallelTrainer(model, opt, topo8, donate_state=False)
    s8 = t8.init_state(jax.random.key(0), x[:2])
    s8_next, m8 = t8.step(s8, x, y)

    mpit_tpu.finalize()
    topo1 = mpit_tpu.init(num_workers=1)
    t1 = DataParallelTrainer(model, opt, topo1, donate_state=False)
    s1 = t1.init_state(jax.random.key(0), x[:2])
    s1_next, m1 = t1.step(s1, x, y)

    np.testing.assert_allclose(
        float(m8["loss"]), float(m1["loss"]), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        s8_next.params,
        s1_next.params,
    )


def test_grad_accumulation_matches_full_batch(topo8):
    """accum_steps=4 on the same global batch must reproduce the
    unaccumulated step exactly (equal slice sizes, mean losses, no batch
    statistics in any model here) — accumulation is a memory knob, not a
    math change."""
    model = LeNet(compute_dtype=jnp.float32)
    opt = optax.sgd(0.1, momentum=0.9)
    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, (64, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)

    results = {}
    for accum in (1, 4):
        tr = DataParallelTrainer(
            model, opt, topo8, donate_state=False, accum_steps=accum
        )
        st = tr.init_state(jax.random.key(0), x[:2])
        losses = []
        for _ in range(3):
            st, m = tr.step(st, x, y)
            losses.append(float(m["loss"]))
        results[accum] = (
            losses, jax.tree.map(np.asarray, jax.device_get(st.params))
        )
    np.testing.assert_allclose(results[4][0], results[1][0], rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        results[4][1], results[1][1],
    )
    # divisibility: per-worker batch of 8 % accum 3 != 0
    tr3 = DataParallelTrainer(
        model, opt, topo8, donate_state=False, accum_steps=3
    )
    st3 = tr3.init_state(jax.random.key(0), x[:2])
    with pytest.raises(ValueError, match="accum_steps"):
        tr3.step(st3, x, y)


@pytest.mark.slow
def test_sync_dp_trains_mnist(topo8, mnist):
    x_tr, y_tr, x_te, y_te = mnist
    model = LeNet(compute_dtype=jnp.float32)
    trainer = DataParallelTrainer(model, optax.adam(1e-3), topo8)
    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    batches = Batches(x_tr, y_tr, global_batch=256, seed=0)

    acc0, _ = trainer.evaluate(state, x_te, y_te, batch=256)
    state, metrics = trainer.fit(batches, state, epochs=3)
    acc1, loss1 = trainer.evaluate(state, x_te, y_te, batch=256)

    assert acc0 < 0.3  # untrained ~ chance
    assert acc1 > 0.9, f"sync DP failed to learn: acc={acc1}, loss={loss1}"


def test_step_counts_and_batch_divisibility(topo8, mnist):
    x_tr, y_tr, *_ = mnist
    model = LeNet(compute_dtype=jnp.float32)
    trainer = DataParallelTrainer(model, optax.sgd(0.01), topo8)
    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    state, _ = trainer.step(state, x_tr[:16], y_tr[:16])
    assert int(state.step) == 1
    with pytest.raises(ValueError, match="not divisible"):
        trainer.step(state, x_tr[:17], y_tr[:17])


def test_batches_shapes_and_determinism(mnist):
    x_tr, y_tr, *_ = mnist
    b = Batches(x_tr, y_tr, global_batch=128, seed=7)
    e0 = list(b.epoch(0))
    e0_again = list(b.epoch(0))
    assert len(e0) == b.steps_per_epoch() == len(x_tr) // 128
    np.testing.assert_array_equal(e0[0][0], e0_again[0][0])
    assert e0[0][0].shape == (128, 28, 28, 1)


def test_shard_for_worker_partitions():
    from mpit_tpu.data import shard_for_worker

    x = np.arange(100)
    shards = [shard_for_worker(x, w, 8) for w in range(8)]
    assert all(len(s) == 12 for s in shards)
    assert len(np.unique(np.concatenate(shards))) == 96
