"""End-to-end sync allreduce DP: the TPU-native `ptest`-class smoke test
(SURVEY.md §4: keep an MNIST e2e as the canonical integration test, plus the
unit checks the reference lacked)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.data import Batches, load_mnist
from mpit_tpu.models import LeNet
from mpit_tpu.parallel import DataParallelTrainer


@pytest.fixture
def mnist():
    return load_mnist(synthetic_train=2048, synthetic_test=512)


def test_grad_averaging_matches_single_worker(topo8):
    """8-worker DP on a global batch must equal 1 worker on the same batch:
    the collective average reproduces the full-batch gradient."""
    model = LeNet(compute_dtype=jnp.float32)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (16, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 16).astype(np.int32)

    t8 = DataParallelTrainer(model, opt, topo8, donate_state=False)
    s8 = t8.init_state(jax.random.key(0), x[:2])
    s8_next, m8 = t8.step(s8, x, y)

    mpit_tpu.finalize()
    topo1 = mpit_tpu.init(num_workers=1)
    t1 = DataParallelTrainer(model, opt, topo1, donate_state=False)
    s1 = t1.init_state(jax.random.key(0), x[:2])
    s1_next, m1 = t1.step(s1, x, y)

    np.testing.assert_allclose(
        float(m8["loss"]), float(m1["loss"]), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        s8_next.params,
        s1_next.params,
    )


def test_grad_accumulation_matches_full_batch(topo8):
    """accum_steps=4 on the same global batch must reproduce the
    unaccumulated step exactly (equal slice sizes, mean losses, no batch
    statistics in any model here) — accumulation is a memory knob, not a
    math change."""
    model = LeNet(compute_dtype=jnp.float32)
    opt = optax.sgd(0.1, momentum=0.9)
    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, (64, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)

    results = {}
    for accum in (1, 4):
        tr = DataParallelTrainer(
            model, opt, topo8, donate_state=False, accum_steps=accum
        )
        st = tr.init_state(jax.random.key(0), x[:2])
        losses = []
        for _ in range(3):
            st, m = tr.step(st, x, y)
            losses.append(float(m["loss"]))
        results[accum] = (
            losses, jax.tree.map(np.asarray, jax.device_get(st.params))
        )
    np.testing.assert_allclose(results[4][0], results[1][0], rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        results[4][1], results[1][1],
    )
    # divisibility: per-worker batch of 8 % accum 3 != 0
    tr3 = DataParallelTrainer(
        model, opt, topo8, donate_state=False, accum_steps=3
    )
    st3 = tr3.init_state(jax.random.key(0), x[:2])
    with pytest.raises(ValueError, match="accum_steps"):
        tr3.step(st3, x, y)


@pytest.mark.slow
def test_sync_dp_trains_mnist(topo8, mnist):
    x_tr, y_tr, x_te, y_te = mnist
    model = LeNet(compute_dtype=jnp.float32)
    trainer = DataParallelTrainer(model, optax.adam(1e-3), topo8)
    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    batches = Batches(x_tr, y_tr, global_batch=256, seed=0)

    acc0, _ = trainer.evaluate(state, x_te, y_te, batch=256)
    state, metrics = trainer.fit(batches, state, epochs=3)
    acc1, loss1 = trainer.evaluate(state, x_te, y_te, batch=256)

    assert acc0 < 0.3  # untrained ~ chance
    assert acc1 > 0.9, f"sync DP failed to learn: acc={acc1}, loss={loss1}"


def test_step_counts_and_batch_divisibility(topo8, mnist):
    x_tr, y_tr, *_ = mnist
    model = LeNet(compute_dtype=jnp.float32)
    trainer = DataParallelTrainer(model, optax.sgd(0.01), topo8)
    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    state, _ = trainer.step(state, x_tr[:16], y_tr[:16])
    assert int(state.step) == 1
    with pytest.raises(ValueError, match="not divisible"):
        trainer.step(state, x_tr[:17], y_tr[:17])


def test_batches_shapes_and_determinism(mnist):
    x_tr, y_tr, *_ = mnist
    b = Batches(x_tr, y_tr, global_batch=128, seed=7)
    e0 = list(b.epoch(0))
    e0_again = list(b.epoch(0))
    assert len(e0) == b.steps_per_epoch() == len(x_tr) // 128
    np.testing.assert_array_equal(e0[0][0], e0_again[0][0])
    assert e0[0][0].shape == (128, 28, 28, 1)


class TestBucketedExchange:
    """ISSUE-11 bucketed / quantized gradient exchange
    (docs/PERF.md "overlapped DP exchange"): the staged bucket pipeline
    must reproduce the fused step, int8+EF must track it closely, and
    the armed path must journal honest roofline/dynamics records."""

    def _data(self, n=64, seed=3):
        rng = np.random.default_rng(seed)
        x = rng.uniform(0, 1, (n, 28, 28, 1)).astype(np.float32)
        y = rng.integers(0, 10, n).astype(np.int32)
        return x, y

    def _run(self, topo, x, y, steps=3, **kw):
        model = LeNet(compute_dtype=jnp.float32)
        tr = DataParallelTrainer(
            model,
            optax.sgd(0.1, momentum=0.9),
            topo,
            donate_state=False,
            **kw,
        )
        st = tr.init_state(jax.random.key(0), x[:2])
        losses = []
        for _ in range(steps):
            st, m = tr.step(st, x, y)
            losses.append(float(m["loss"]))
        params = jax.tree.map(np.asarray, jax.device_get(st.params))
        return tr, losses, params

    def test_raw_bucketed_matches_fused(self, topo8):
        x, y = self._data()
        _, l_fused, p_fused = self._run(topo8, x, y)
        tr, l_b, p_b = self._run(
            topo8, x, y, quant="off", bucket_bytes=64 << 10
        )
        assert tr.bucketed and len(tr._plan.buckets) > 1
        np.testing.assert_allclose(l_b, l_fused, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
            p_b,
            p_fused,
        )

    def test_int8_ef_tracks_fused(self, topo8):
        x, y = self._data()
        _, l_fused, p_fused = self._run(topo8, x, y, steps=5)
        tr, l_q, p_q = self._run(
            topo8, x, y, steps=5, quant="int8", bucket_bytes=64 << 10
        )
        # error feedback keeps the quantized stream on the raw
        # trajectory: tight but not bit-equal
        assert all(np.isfinite(l_q))
        np.testing.assert_allclose(l_q, l_fused, atol=2e-2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=5e-3),
            p_q,
            p_fused,
        )
        # int8 codes put ~4x fewer bytes on the wire than the raw
        # staged exchange over the same plan
        raw = DataParallelTrainer(
            LeNet(compute_dtype=jnp.float32),
            optax.sgd(0.1),
            topo8,
            donate_state=False,
            quant="off",
            bucket_bytes=64 << 10,
        )
        rs = raw.init_state(jax.random.key(0), x[:2])
        raw.step(rs, x, y)
        assert tr.wire_bytes_per_step() < raw.wire_bytes_per_step() / 3

    def test_obs_roofline_and_dynamics(self, topo8, tmp_path):
        from mpit_tpu.obs.core import ObsConfig
        from mpit_tpu.obs.dynamics import aggregate_dynamics
        from mpit_tpu.obs.merge import roofline

        x, y = self._data()
        steps = 4
        tr, losses, _ = self._run(
            topo8,
            x,
            y,
            steps=steps,
            quant="int8",
            bucket_bytes=64 << 10,
            obs=ObsConfig(dir=str(tmp_path)),
        )
        tr.close_obs()
        assert all(np.isfinite(losses))

        rr = roofline([str(tmp_path)])
        rank0 = rr["ranks"][0]
        assert rank0["role"] == "client"
        assert rank0["compute_s"] > 0 and rank0["wire_s"] > 0
        # every hop journals its exact byte count: 2 hops per bucket per
        # step, summing to the plan's per-step wire volume
        assert rank0["bytes"] == steps * tr.wire_bytes_per_step()
        assert rank0["sends"] == steps * 2 * len(tr._plan.buckets)

        rep = aggregate_dynamics([str(tmp_path)])
        assert rep["run"] is not None
        assert rep["run"]["clients"] == 1
        assert not rep["run"]["diverging"]
        c = rep["clients"][0]
        assert c["algo"] == "sync-dp" and c["rounds"] == steps
        assert c["elastic"]["final"] > 0  # EF residuals are live

    def test_env_knobs(self, topo8, monkeypatch):
        from mpit_tpu.parallel.sync import (
            dp_bucket_bytes_from_env,
            dp_quant_from_env,
        )

        assert dp_quant_from_env({}) == "off"
        assert dp_quant_from_env({"MPIT_DP_QUANT": "int8"}) == "int8"
        with pytest.raises(ValueError, match="MPIT_DP_QUANT"):
            dp_quant_from_env({"MPIT_DP_QUANT": "fp4"})
        assert dp_bucket_bytes_from_env({}) is None
        assert (
            dp_bucket_bytes_from_env({"MPIT_DP_BUCKET_BYTES": "4096"})
            == 4096
        )
        with pytest.raises(ValueError, match="MPIT_DP_BUCKET_BYTES"):
            dp_bucket_bytes_from_env({"MPIT_DP_BUCKET_BYTES": "0"})

        model = LeNet(compute_dtype=jnp.float32)
        monkeypatch.setenv("MPIT_DP_QUANT", "bf16")
        tr = DataParallelTrainer(model, optax.sgd(0.1), topo8)
        assert tr.bucketed and tr.quant == "bf16"
        monkeypatch.delenv("MPIT_DP_QUANT")
        # bucket bytes alone engages bucketing, unquantized
        monkeypatch.setenv("MPIT_DP_BUCKET_BYTES", "65536")
        tr = DataParallelTrainer(model, optax.sgd(0.1), topo8)
        assert tr.bucketed and tr.quant == "off"
        assert tr.bucket_bytes == 65536
        monkeypatch.delenv("MPIT_DP_BUCKET_BYTES")
        tr = DataParallelTrainer(model, optax.sgd(0.1), topo8)
        assert not tr.bucketed
        with pytest.raises(ValueError, match="quant"):
            DataParallelTrainer(model, optax.sgd(0.1), topo8, quant="q4")


def test_shard_for_worker_partitions():
    from mpit_tpu.data import shard_for_worker

    x = np.arange(100)
    shards = [shard_for_worker(x, w, 8) for w in range(8)]
    assert all(len(s) == 12 for s in shards)
    assert len(np.unique(np.concatenate(shards))) == 96
