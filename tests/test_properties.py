"""Property-based invariants (hypothesis) for the protocol and param layers.

SURVEY.md §5 race-detection row: the PS protocol's correctness rests on MPI's
per-(src,tag) message-ordering guarantee, and the survey's do-better plan is
property tests on exactly that ordering. These generate arbitrary send
interleavings and pytree shapes instead of hand-picked cases.
"""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from mpit_tpu import native
from mpit_tpu.transport import ANY_SOURCE, ANY_TAG, Broker
from mpit_tpu.utils.params import flatten_params, unflatten_params


def _make_broker(kind, size):
    """Both message planes must satisfy the same ordering laws: the pure-
    Python broker and the native C++ one (the reference-parity plane)."""
    if kind == "native":
        if not native.is_available():
            pytest.skip("native broker unavailable in this image")
        return native.NativeBroker(size)
    return Broker(size)


BROKERS = ("inproc", "native")

# -- transport ordering ------------------------------------------------------

# an interleaving: each element is (sender in {1,2}, tag in {0,1,2}); rank 0
# receives everything
_sends = st.lists(
    st.tuples(st.integers(1, 2), st.integers(0, 2)),
    min_size=0,
    max_size=40,
)


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=60, deadline=None)
@given(_sends)
def test_per_src_tag_fifo(kind, sends):
    """recv(src, tag) must see that (src, tag) stream in send order, for any
    interleaving of sends across sources and tags (the MPI ordering rule the
    PS protocol relies on)."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    seq = {}
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=(src, tag, i))
        seq.setdefault((src, tag), []).append(i)
    for (src, tag), expected in seq.items():
        got = [
            tps[0].recv(src=src, tag=tag, timeout=1).payload[2]
            for _ in expected
        ]
        assert got == expected, f"(src={src},tag={tag}) out of order"


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=60, deadline=None)
@given(_sends)
def test_wildcard_recv_exactly_once(kind, sends):
    """ANY_SOURCE/ANY_TAG receives deliver every message exactly once, and
    each (src, tag) substream stays in send order even under wildcards."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=i)
    got = [
        tps[0].recv(src=ANY_SOURCE, tag=ANY_TAG, timeout=1)
        for _ in sends
    ]
    assert sorted(m.payload for m in got) == list(range(len(sends)))
    assert not tps[0].probe()  # nothing left over
    per_stream = {}
    for m in got:
        per_stream.setdefault((m.src, m.tag), []).append(m.payload)
    for stream in per_stream.values():
        assert stream == sorted(stream), "wildcard recv broke FIFO"


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=40, deadline=None)
@given(_sends, st.integers(0, 2))
def test_tag_filter_never_steals(kind, sends, want_tag):
    """A tag-filtered recv must leave every other message untouched and
    available, whatever the interleaving."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    matching = 0
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=i)
        matching += tag == want_tag
    for _ in range(matching):
        m = tps[0].recv(src=ANY_SOURCE, tag=want_tag, timeout=1)
        assert m.tag == want_tag
    rest = [
        tps[0].recv(timeout=1) for _ in range(len(sends) - matching)
    ]
    assert all(m.tag != want_tag for m in rest)
    assert not tps[0].probe()


# -- flat-param round trip ---------------------------------------------------

_leaf_shapes = st.lists(
    st.lists(st.integers(1, 5), min_size=0, max_size=3), min_size=1,
    max_size=6,
)


@settings(max_examples=40, deadline=None)
@given(_leaf_shapes, st.randoms(use_true_random=False))
def test_flatten_roundtrip_arbitrary_trees(shapes, rnd):
    """flatten -> unflatten reproduces any nested dict pytree bit-exactly,
    and the flat size is the sum of leaf sizes (the getParameters()
    contract)."""
    rng = np.random.default_rng(rnd.randrange(2**32))
    tree = {}
    node = tree
    for i, shape in enumerate(shapes):
        leaf = rng.normal(size=tuple(shape)).astype(np.float32)
        node[f"leaf{i}"] = leaf
        if i % 2:  # nest every other level to vary the structure
            node[f"sub{i}"] = {}
            node = node[f"sub{i}"]
    flat, spec = flatten_params(tree)
    assert flat.shape == (sum(int(np.prod(s)) for s in shapes),)
    rebuilt = unflatten_params(spec, flat)

    import jax

    leaves0 = jax.tree.leaves(tree)
    leaves1 = jax.tree.leaves(rebuilt)
    assert len(leaves0) == len(leaves1)
    for a, b in zip(leaves0, leaves1):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
