"""Property-based invariants (hypothesis) for the protocol and param layers.

SURVEY.md §5 race-detection row: the PS protocol's correctness rests on MPI's
per-(src,tag) message-ordering guarantee, and the survey's do-better plan is
property tests on exactly that ordering. These generate arbitrary send
interleavings and pytree shapes instead of hand-picked cases.
"""

import numpy as np
import pytest

# integration tier — excluded from the smoke run (hypothesis property sweeps)
pytestmark = pytest.mark.slow
pytest.importorskip("hypothesis", reason="property tier needs hypothesis")
from hypothesis import given, settings, strategies as st

from mpit_tpu import native
from mpit_tpu.transport import ANY_SOURCE, ANY_TAG, Broker
from mpit_tpu.utils.params import flatten_params, unflatten_params


def _make_broker(kind, size):
    """Both message planes must satisfy the same ordering laws: the pure-
    Python broker and the native C++ one (the reference-parity plane)."""
    if kind == "native":
        if not native.is_available():
            pytest.skip("native broker unavailable in this image")
        return native.NativeBroker(size)
    return Broker(size)


BROKERS = ("inproc", "native")

# -- transport ordering ------------------------------------------------------

# an interleaving: each element is (sender in {1,2}, tag in {0,1,2}); rank 0
# receives everything
_sends = st.lists(
    st.tuples(st.integers(1, 2), st.integers(0, 2)),
    min_size=0,
    max_size=40,
)


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=60, deadline=None)
@given(_sends)
def test_per_src_tag_fifo(kind, sends):
    """recv(src, tag) must see that (src, tag) stream in send order, for any
    interleaving of sends across sources and tags (the MPI ordering rule the
    PS protocol relies on)."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    seq = {}
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=(src, tag, i))
        seq.setdefault((src, tag), []).append(i)
    for (src, tag), expected in seq.items():
        got = [
            tps[0].recv(src=src, tag=tag, timeout=1).payload[2]
            for _ in expected
        ]
        assert got == expected, f"(src={src},tag={tag}) out of order"


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=60, deadline=None)
@given(_sends)
def test_wildcard_recv_exactly_once(kind, sends):
    """ANY_SOURCE/ANY_TAG receives deliver every message exactly once, and
    each (src, tag) substream stays in send order even under wildcards."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=i)
    got = [
        tps[0].recv(src=ANY_SOURCE, tag=ANY_TAG, timeout=1)
        for _ in sends
    ]
    assert sorted(m.payload for m in got) == list(range(len(sends)))
    assert not tps[0].probe()  # nothing left over
    per_stream = {}
    for m in got:
        per_stream.setdefault((m.src, m.tag), []).append(m.payload)
    for stream in per_stream.values():
        assert stream == sorted(stream), "wildcard recv broke FIFO"


@pytest.mark.parametrize("kind", BROKERS)
@settings(max_examples=40, deadline=None)
@given(_sends, st.integers(0, 2))
def test_tag_filter_never_steals(kind, sends, want_tag):
    """A tag-filtered recv must leave every other message untouched and
    available, whatever the interleaving."""
    broker = _make_broker(kind, 3)
    tps = broker.transports()
    matching = 0
    for i, (src, tag) in enumerate(sends):
        tps[src].send(0, tag=tag, payload=i)
        matching += tag == want_tag
    for _ in range(matching):
        m = tps[0].recv(src=ANY_SOURCE, tag=want_tag, timeout=1)
        assert m.tag == want_tag
    rest = [
        tps[0].recv(timeout=1) for _ in range(len(sends) - matching)
    ]
    assert all(m.tag != want_tag for m in rest)
    assert not tps[0].probe()


# -- flat-param round trip ---------------------------------------------------

_leaf_shapes = st.lists(
    st.lists(st.integers(1, 5), min_size=0, max_size=3), min_size=1,
    max_size=6,
)


@settings(max_examples=40, deadline=None)
@given(_leaf_shapes, st.randoms(use_true_random=False))
def test_flatten_roundtrip_arbitrary_trees(shapes, rnd):
    """flatten -> unflatten reproduces any nested dict pytree bit-exactly,
    and the flat size is the sum of leaf sizes (the getParameters()
    contract)."""
    rng = np.random.default_rng(rnd.randrange(2**32))
    tree = {}
    node = tree
    for i, shape in enumerate(shapes):
        leaf = rng.normal(size=tuple(shape)).astype(np.float32)
        node[f"leaf{i}"] = leaf
        if i % 2:  # nest every other level to vary the structure
            node[f"sub{i}"] = {}
            node = node[f"sub{i}"]
    flat, spec = flatten_params(tree)
    assert flat.shape == (sum(int(np.prod(s)) for s in shapes),)
    rebuilt = unflatten_params(spec, flat)

    import jax

    leaves0 = jax.tree.leaves(tree)
    leaves1 = jax.tree.leaves(rebuilt)
    assert len(leaves0) == len(leaves1)
    for a, b in zip(leaves0, leaves1):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- sharded-op equivalence (ring attention / MoE) ---------------------------
#
# jit+mesh evaluations are slow per example on this box, so these run few,
# structurally diverse examples rather than hypothesis' default 100.

_ring_cfg = st.tuples(
    st.sampled_from([8, 16, 24]),    # T_local (global T = 8x)
    st.sampled_from([1, 2, 3]),      # heads
    st.sampled_from([4, 8, 17]),     # head dim (incl. non-power-of-2)
    st.booleans(),                   # causal
    st.integers(0, 2 ** 16),         # data seed
)


@settings(max_examples=8, deadline=None)
@given(_ring_cfg)
def test_ring_attention_equals_dense_for_arbitrary_shapes(cfg):
    import jax.numpy as jnp

    import mpit_tpu
    from mpit_tpu.ops import dense_attention, make_ring_attention

    t_local, h, d, causal, seed = cfg
    topo = mpit_tpu.init()  # idempotent: one world across examples
    rng = np.random.default_rng(seed)
    q, k, v = (
        rng.standard_normal((2, 8 * t_local, h, d)).astype(np.float32)
        for _ in range(3)
    )
    ring = make_ring_attention(topo.mesh, topo.worker_axis, causal=causal)
    got = np.asarray(ring(q, k, v))
    want = np.asarray(dense_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
    ))
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


@settings(max_examples=6, deadline=None)
@given(
    st.integers(1, 3),          # experts per device
    st.sampled_from([0.5, 1.0, 4.0]),  # capacity factor
    st.integers(0, 2 ** 16),    # seed
)
def test_moe_reference_equivalence_and_dropped_tokens_zero(e_local, cf, seed):
    """The sharded op equals the per-shard dense reference for arbitrary
    expert counts and capacities, and when capacity forces drops the
    dropped tokens emit exactly zero (directly asserted, not just via the
    reference — both paths share _routing, so equivalence alone would not
    catch a shared drop-rule bug)."""
    import jax

    import mpit_tpu
    from conftest import moe_dense_per_shard, run_moe_sharded
    from mpit_tpu.ops import init_moe_params

    ep, d, f, b, t = 8, 8, 16, 8, 6
    num_e = e_local * ep
    topo = mpit_tpu.init()  # idempotent: one world across examples
    params = init_moe_params(jax.random.key(seed % 1000), d, f, num_e)
    h = np.random.default_rng(seed).standard_normal((b, t, d)).astype(
        np.float32
    )
    got = run_moe_sharded(topo, params, h, cf)
    want = moe_dense_per_shard(params, h, cf, ep)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)
    if cf <= 0.5:
        # conservation under drops: any row that differs from the
        # ample-capacity run can only differ by having been DROPPED, and
        # a dropped token's output is exactly zero
        ample = run_moe_sharded(topo, params, h, float(num_e))
        diff = np.abs(got - ample).reshape(-1, d).sum(-1) > 1e-6
        zero = np.abs(got.reshape(-1, d)).sum(-1) == 0
        assert np.all(~diff | zero), (
            "a capacity-dropped token produced nonzero output"
        )
