"""Transport semantics tests: MPI-like matching the reference relied on
(SURVEY.md §5 race detection: 'the PS protocol's correctness relies on MPI
message ordering per (src,tag)' — here that guarantee gets the tests the
reference never had)."""

import multiprocessing
import threading
import time

import numpy as np
import pytest

from mpit_tpu.transport import (
    ANY_SOURCE,
    ANY_TAG,
    Broker,
    InProcTransport,
    Message,
    RecvTimeout,
    SocketTransport,
)


class TestInProc:
    def test_send_recv_roundtrip(self):
        tps = Broker(2).transports()
        payload = np.arange(5.0)
        tps[0].send(1, tag=7, payload=payload)
        msg = tps[1].recv(src=0, tag=7, timeout=1)
        np.testing.assert_array_equal(msg.payload, payload)
        assert msg.src == 0 and msg.tag == 7

    def test_per_src_tag_fifo_order(self):
        tps = Broker(2).transports()
        for i in range(20):
            tps[0].send(1, tag=3, payload=i)
        got = [tps[1].recv(0, 3, timeout=1).payload for _ in range(20)]
        assert got == list(range(20))

    def test_any_source_any_tag(self):
        tps = Broker(3).transports()
        tps[0].send(2, tag=1, payload="from0")
        tps[1].send(2, tag=9, payload="from1")
        first = tps[2].recv(ANY_SOURCE, ANY_TAG, timeout=1)
        second = tps[2].recv(ANY_SOURCE, ANY_TAG, timeout=1)
        assert {first.payload, second.payload} == {"from0", "from1"}

    def test_tag_selective_recv_leaves_others_queued(self):
        tps = Broker(2).transports()
        tps[0].send(1, tag=1, payload="a")
        tps[0].send(1, tag=2, payload="b")
        assert tps[1].recv(ANY_SOURCE, 2, timeout=1).payload == "b"
        assert tps[1].recv(ANY_SOURCE, 1, timeout=1).payload == "a"

    def test_probe(self):
        tps = Broker(2).transports()
        assert not tps[1].probe()
        tps[0].send(1, tag=4, payload=None)
        assert tps[1].probe(src=0, tag=4)
        assert not tps[1].probe(src=0, tag=5)

    def test_recv_timeout_raises(self):
        tps = Broker(2).transports()
        with pytest.raises(RecvTimeout):
            tps[1].recv(timeout=0.05)

    def test_blocking_recv_wakes_on_send(self):
        tps = Broker(2).transports()
        out = {}

        def receiver():
            out["msg"] = tps[1].recv(timeout=5)

        t = threading.Thread(target=receiver)
        t.start()
        time.sleep(0.05)
        tps[0].send(1, tag=0, payload="wake")
        t.join(timeout=5)
        assert out["msg"].payload == "wake"

    def test_isend_irecv_wait(self):
        tps = Broker(2).transports()
        h = tps[0].isend(1, tag=1, payload=123)
        h.wait(timeout=1)
        r = tps[1].irecv(src=0, tag=1)
        assert r.wait(timeout=1).payload == 123

    def test_bad_dst_raises(self):
        tps = Broker(2).transports()
        with pytest.raises(ValueError, match="out of range"):
            tps[0].send(5, tag=0, payload=None)


def _socket_child(rank, size, base_port, q):
    try:
        tp = SocketTransport(rank, size, base_port=base_port)
        # rank 1 echoes doubled arrays until it receives the stop tag 13
        if rank == 1:
            while True:
                msg = tp.recv(src=0, timeout=20)
                if msg.tag == 13:
                    break
                tp.send(0, tag=12, payload=np.asarray(msg.payload) * 2)
        q.put(("ok", rank))
        tp.close()
    except BaseException as e:
        q.put(("err", repr(e)))


class TestSocket:
    def test_cross_process_roundtrip(self):
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        base_port = 29_731
        child = ctx.Process(
            target=_socket_child, args=(1, 2, base_port, q), daemon=True
        )
        child.start()
        tp = SocketTransport(0, 2, base_port=base_port)
        payload = np.arange(1000, dtype=np.float32)
        # child may not be listening yet: retry connect
        deadline = time.monotonic() + 20
        while True:
            try:
                tp.send(1, tag=11, payload=payload)
                break
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        msg = tp.recv(src=1, tag=12, timeout=20)
        np.testing.assert_array_equal(msg.payload, payload * 2)

        # break the cached outbound socket: send() must evict + reconnect
        tp._out[1].close()
        tp.send(1, tag=11, payload=payload + 1)
        msg = tp.recv(src=1, tag=12, timeout=20)
        np.testing.assert_array_equal(msg.payload, (payload + 1) * 2)

        tp.send(1, tag=13, payload=None)
        status = q.get(timeout=20)
        assert status[0] == "ok", status
        child.join(timeout=10)
        tp.close()

    def test_sender_restart_not_fenced_out(self):
        """A fully-restarted sender (fresh transport object, same rank) must
        keep getting through — the reconnect fence is receiver-side accept
        ordering, not sender state (regression: a sender-epoch fence would
        silently drop a restarted sender's frames forever)."""
        base_port = 29_741
        rx = SocketTransport(0, 2, base_port=base_port)
        tx1 = SocketTransport(1, 2, base_port=base_port)
        tx1.send(0, tag=1, payload="before")
        assert rx.recv(src=1, tag=1, timeout=10).payload == "before"
        tx1.close()

        tx2 = SocketTransport(1, 2, base_port=base_port + 10)
        # restarted process: new transport, same rank, receiver unchanged
        tx2._addrs[0] = rx._addrs[0]
        tx2.send(0, tag=1, payload="after-restart")
        assert rx.recv(src=1, tag=1, timeout=10).payload == "after-restart"
        tx2.close()
        rx.close()


class TestFramedSocket:
    """The binary wire format (docs/WIRE.md): framed peers exchange
    zero-copy frames, unencodable payloads fall back to pickle on the
    same connection, byte accounting is exact, and a corrupted frame
    degrades to a CorruptedPayload without desyncing the stream."""

    def _pair(self, base_port, **kw):
        a = SocketTransport(0, 2, base_port=base_port, **kw)
        b = SocketTransport(1, 2, base_port=base_port, **kw)
        return a, b

    def test_framed_roundtrip_and_pickle_fallback(self):
        from mpit_tpu.transport.wire import QuantArray, quantize

        a, b = self._pair(29_871, wire_format="framed")
        try:
            arr = np.arange(512, dtype=np.float32)
            a.send(1, 2, (1 << 70, 5, 0, arr))  # framed: PS push shape
            got = b.recv(0, 2, timeout=10).payload
            assert got[0] == 1 << 70
            np.testing.assert_array_equal(got[3], arr)
            # dicts aren't in the structural codec: same connection,
            # pickle frame, still delivered (format detected per frame)
            a.send(1, 3, {"k": "v"})
            assert b.recv(0, 3, timeout=10).payload == {"k": "v"}
            q = quantize(arr, "int8")
            a.send(1, 4, q)
            got = b.recv(0, 4, timeout=10).payload
            assert isinstance(got, QuantArray) and got.mode == "int8"
            np.testing.assert_array_equal(got.data, q.data)
        finally:
            a.close()
            b.close()

    def test_exact_byte_accounting_both_formats(self):
        for fmt, port in (("framed", 29_873), ("pickle", 29_875)):
            a, b = self._pair(port, wire_format=fmt)
            try:
                payload = (7, 1, 0, np.ones(1000, np.float32))
                h = a.isend(1, 2, payload)
                assert h.wait(10)
                msg = b.recv(0, 2, timeout=10)
                # sender's handle and receiver's message agree on the
                # exact on-wire length of THIS message
                assert h.wire_nbytes is not None
                assert h.wire_nbytes == msg.wire_nbytes
                b.send(0, 3, "ack")
                a.recv(1, 3, timeout=10)
                # and the directional totals agree socket-to-socket
                ca, cb = a.wire_byte_counts(), b.wire_byte_counts()
                assert ca["tx"] == cb["rx"] > 0
                assert cb["tx"] == ca["rx"] > 0
                assert ca["rx_corrupt_dropped"] == 0
            finally:
                a.close()
                b.close()

    def test_framed_smaller_than_pickle_for_arrays(self):
        sizes = {}
        for fmt, port in (("framed", 29_877), ("pickle", 29_879)):
            a, b = self._pair(port, wire_format=fmt)
            try:
                a.send(1, 2, (1, 1, 0, np.zeros(4096, np.float32)))
                sizes[fmt] = b.recv(0, 2, timeout=10).wire_nbytes
            finally:
                a.close()
                b.close()
        assert sizes["framed"] < sizes["pickle"]

    def test_corrupt_frame_degrades_and_stream_resyncs(self):
        """A framed body that fails decode must surface as a
        CorruptedPayload on the right stream AND leave the connection
        length-synced — the next frame decodes normally."""
        import socket as skt
        import struct

        from mpit_tpu.transport import CorruptedPayload
        from mpit_tpu.transport import wire as w

        b = SocketTransport(1, 2, base_port=29_881, wire_format="framed")
        try:
            raw = skt.create_connection(b._addrs[1], timeout=10)
            raw.recv(w.HELLO_SIZE)  # the receiver's hello advertisement
            bufs = w.encode_frame(
                0, 6, (1, 2, np.arange(16, dtype=np.float32)),
                version=w.WIRE_FORMAT_VERSION,
            )
            frame = bytes(bufs[0]) + b"".join(bytes(x) for x in bufs[1:])
            # flip one structural-header bit -> CRC check must fail
            bad = bytearray(frame)
            bad[w.PREAMBLE_SIZE + 2] ^= 0x10
            raw.sendall(struct.pack(">Q", len(bad)) + bytes(bad))
            raw.sendall(struct.pack(">Q", len(frame)) + frame)
            first = b.recv(timeout=10)
            assert isinstance(first.payload, CorruptedPayload)
            second = b.recv(timeout=10)
            np.testing.assert_array_equal(
                second.payload[2], np.arange(16, dtype=np.float32)
            )
            assert b.wire_byte_counts()["rx_corrupt_dropped"] == 1
            raw.close()
        finally:
            b.close()

    def test_wire_format_validation(self):
        with pytest.raises(ValueError, match="wire_format"):
            SocketTransport(0, 1, base_port=29_883, wire_format="cbor")


class TestProbeAndIsend:
    """mpiT L2 parity items from round-1 verdict #9: MPI_Probe blocks;
    Isend genuinely overlaps."""

    def test_blocking_probe_inproc(self):
        tps = Broker(2).transports()
        assert tps[1].probe(timeout=0.05) is False  # expiry -> False
        def later():
            time.sleep(0.15)
            tps[0].send(1, tag=5, payload="x")
        threading.Thread(target=later, daemon=True).start()
        t0 = time.monotonic()
        assert tps[1].probe(src=0, tag=5, timeout=5) is True
        assert time.monotonic() - t0 < 4
        # probe must not consume
        assert tps[1].recv(0, 5, timeout=1).payload == "x"

    def test_socket_blocking_probe_and_overlapping_isend(self):
        base = 29_841
        a = SocketTransport(0, 2, base_port=base)
        b = SocketTransport(1, 2, base_port=base)
        try:
            handles = [
                a.isend(1, tag=i, payload=np.arange(64) + i)
                for i in range(10)
            ]
            for h in handles:
                assert h.wait(10) and h.done()
            assert b.probe(src=0, tag=3, timeout=5) is True
            for i in range(10):
                msg = b.recv(0, i, timeout=5)
                np.testing.assert_array_equal(msg.payload, np.arange(64) + i)
            # interleaved send/isend to one dst keep FIFO (same queue)
            a.isend(1, 50, "i0")
            a.send(1, 50, "s1")
            a.isend(1, 50, "i2")
            got = [b.recv(0, 50, timeout=5).payload for _ in range(3)]
            assert got == ["i0", "s1", "i2"]
        finally:
            a.close()
            b.close()

    def test_isend_error_parked_on_handle(self):
        """A failed async send surfaces from wait(), not a dead thread."""
        a = SocketTransport(0, 2, base_port=29_861, connect_retry_s=0.2)
        try:
            h = a.isend(1, tag=1, payload="x")  # rank 1 never exists
            with pytest.raises((ConnectionError, OSError)):
                h.wait(20)
            assert h.done()
        finally:
            a.close()
