"""The numerics analysis stack: the precision-dataflow model behind
MPT020-022 (analysis/numerics.py), the rules themselves, the `numerics`
CLI, and the RT104 runtime numerics sanitizer.

The fixture fires-exactly-once contract lives with every other rule in
test_analysis.py; here each seeded fixture additionally goes QUIET when
its one bug is fixed (the other half of the resolve-or-skip bar), and
the model's load-bearing behaviors — EF pairing in-function and through
one caller level, ef-off markers, push-path gating, mode/scale
provenance, the lockfile precision column — are pinned directly.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from mpit_tpu import quant
from mpit_tpu.analysis import lint
from mpit_tpu.analysis import runtime as rt
from mpit_tpu.analysis import schema as schema_mod

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "analysis"

NUMERICS_ONLY = ("MPT020", "MPT021", "MPT022")


def _lint_source(tmp_path, source, only=NUMERICS_ONLY):
    f = tmp_path / "mod.py"
    f.write_text(source)
    return lint.run_lint(
        [f], lint.Config(hot_all=True, only_rules=only)
    )


def _fixed_fixture(tmp_path, name, old, new):
    src = (FIXTURES / name).read_text()
    assert old in src, f"fixture {name} drifted: {old!r} not found"
    out = tmp_path / name
    out.write_text(src.replace(old, new))
    return lint.run_lint([out], lint.Config(hot_all=True))


# ------------------------------------------------------- quiet when fixed


def test_mpt020_fixture_quiet_when_reducing_the_reconstruction(tmp_path):
    findings = _fixed_fixture(
        tmp_path,
        "fixture_mpt020.py",
        "jnp.sum(codes, axis=0)",
        "jnp.sum(deq, axis=0)",
    )
    assert findings == [], [f.format() for f in findings]


def test_mpt021_fixture_quiet_when_residual_is_folded(tmp_path):
    findings = _fixed_fixture(
        tmp_path,
        "fixture_mpt021.py",
        "    q = quantize(delta, \"int8\")\n"
        "    transport.send(rank, TAG_GRAD_PUSH, q)",
        "    q = quantize(delta, \"int8\")\n"
        "    residual = delta - dequantize(q)\n"
        "    transport.send(rank, TAG_GRAD_PUSH, q)\n"
        "    return residual",
    )
    assert findings == [], [f.format() for f in findings]


def test_mpt021_fixture_quiet_under_an_ef_off_marker(tmp_path):
    findings = _fixed_fixture(
        tmp_path,
        "fixture_mpt021.py",
        "    q = quantize(delta, \"int8\")",
        "    # mpit-analysis: ef-off[test: stateless by design]\n"
        "    q = quantize(delta, \"int8\")",
    )
    assert findings == [], [f.format() for f in findings]


def test_mpt022_fixture_quiet_when_mode_and_scale_match(tmp_path):
    findings = _fixed_fixture(
        tmp_path,
        "fixture_mpt022.py",
        'dequantize_rows_jnp(codes, None, "bf16")',
        'dequantize_rows_jnp(codes, scales, "int8")',
    )
    assert findings == [], [f.format() for f in findings]


# ------------------------------------------------------- model behaviors


def test_pairing_resolves_through_one_caller_level(tmp_path):
    # the _quant_allreduce_leaf shape: the leaf RETURNS the
    # reconstruction and the caller folds the residual — paired, not
    # unpaired, even though the Sub is a function away
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.quant import dequantize_jnp, quantize_jnp\n"
        "def leaf(x, mode):\n"
        "    codes, scale = quantize_jnp(x, mode)\n"
        "    sent = dequantize_jnp(codes, scale, mode)\n"
        "    return codes, sent\n"
        "def caller(transport, x, mode):\n"
        "    codes, sent = leaf(x, mode)\n"
        "    residual = x - sent\n"
        "    transport.send(0, 7, (codes,))\n"
        "    return residual\n",
    )
    assert findings == [], [f.format() for f in findings]


def test_unresolved_escape_makes_no_claim(tmp_path):
    # codes returned to callers outside the module: the pass must skip,
    # never guess (the transport/fuzz.py generator shape)
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.quant import quantize\n"
        "def gen(rng):\n"
        "    return quantize(rng.standard_normal(8), \"int8\")\n",
    )
    assert findings == [], [f.format() for f in findings]


def test_local_quantize_without_a_send_makes_no_claim(tmp_path):
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.quant import quantize\n"
        "def roundtrip_only(x):\n"
        "    q = quantize(x, \"int8\")\n"
        "    return None\n",
    )
    assert findings == [], [f.format() for f in findings]


def test_collective_hop_counts_as_the_wire(tmp_path):
    # codes reaching lax.all_to_all are on the exchange path even with
    # no literal send() — unpaired must still fire
    findings = _lint_source(
        tmp_path,
        "from jax import lax\n"
        "from mpit_tpu.quant import quantize_rows_jnp\n"
        "def exchange(rows, axis):\n"
        "    codes, scales = quantize_rows_jnp(rows, \"int8\")\n"
        "    return lax.all_to_all(codes, axis, 0, 0)\n",
    )
    assert [f.rule for f in findings] == ["MPT021"], [
        f.format() for f in findings
    ]


def test_mode_resolves_through_a_local_constant(tmp_path):
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.quant import dequantize_rows_jnp, quantize_rows_jnp\n"
        "def roundtrip(rows):\n"
        "    push_mode = \"int8\"\n"
        "    codes, scales = quantize_rows_jnp(rows, push_mode)\n"
        "    deq = dequantize_rows_jnp(codes, scales, \"bf16\")\n"
        "    return rows - deq\n",
    )
    assert [f.rule for f in findings] == ["MPT022"], [
        f.format() for f in findings
    ]
    assert "'int8'" in findings[0].message


def test_scale_reused_across_chunks_is_flagged(tmp_path):
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.quant import dequantize_jnp, quantize_jnp\n"
        "def mixup(a, b):\n"
        "    ca, sa = quantize_jnp(a, \"int8\")\n"
        "    cb, sb = quantize_jnp(b, \"int8\")\n"
        "    bad = dequantize_jnp(cb, sa, \"int8\")\n"
        "    r1 = a - dequantize_jnp(ca, sa, \"int8\")\n"
        "    r2 = b - dequantize_jnp(cb, sb, \"int8\")\n"
        "    return bad, r1, r2\n",
    )
    assert [f.rule for f in findings] == ["MPT022"], [
        f.format() for f in findings
    ]
    assert "scale" in findings[0].message


def test_unresolved_mode_reduce_still_fires_on_codes(tmp_path):
    # operand provenance (codes) is enough for MPT020 even when the
    # mode variable never resolves to a literal
    findings = _lint_source(
        tmp_path,
        "import jax.numpy as jnp\n"
        "from mpit_tpu.quant import quantize_rows_jnp\n"
        "def reduce_codes(rows, mode):\n"
        "    codes, scales = quantize_rows_jnp(rows, mode)\n"
        "    return jnp.sum(codes, axis=0)\n",
        only=("MPT020",),
    )
    assert [f.rule for f in findings] == ["MPT020"]


def test_f32_astype_upcast_silences_mpt020(tmp_path):
    # an explicit astype(float32) is the sanctioned escape hatch: the
    # value is no longer claimed to be codes
    findings = _lint_source(
        tmp_path,
        "import jax.numpy as jnp\n"
        "from mpit_tpu.quant import quantize_rows_jnp\n"
        "def reduce_upcast(rows, mode):\n"
        "    codes, scales = quantize_rows_jnp(rows, mode)\n"
        "    return jnp.sum(codes.astype(jnp.float32) * scales, axis=0)\n",
        only=("MPT020",),
    )
    assert findings == [], [f.format() for f in findings]


def test_tag_precision_column_derivation():
    assert schema_mod.tag_precision(["(int, quant)"], []) == ["codes"]
    assert schema_mod.tag_precision(["ndarray"], ["quant"]) == [
        "codes",
        "f32",
    ]
    assert schema_mod.tag_precision(["(int, int)"], ["tuple"]) == []


def test_lockfile_precision_drift_is_flagged(tmp_path):
    # a repo whose lock pins ["codes"] for a tag whose senders now carry
    # plain ints: the drift leg anchors MPT022 at the sender site
    pkg = tmp_path / "repo"
    # keep the package NAME: tag constants resolve through the
    # `fixture_mpt016.tags` import, so the directory must match
    shutil.copytree(FIXTURES / "fixture_mpt016", pkg / "fixture_mpt016")
    (pkg / "pyproject.toml").write_text("[project]\nname = 'probe'\n")
    lock = {
        "version": schema_mod.SCHEMA_LOCK_VERSION,
        "tags": {
            "26": {
                "name": "TAG_DATA",
                "sender": [],
                "receiver": [],
                "precision": ["codes"],
            }
        },
        "snapshot": {"writes": [], "reads": []},
    }
    (pkg / schema_mod.SCHEMA_LOCK_FILENAME).write_text(json.dumps(lock))
    findings = lint.run_lint(
        [pkg / "fixture_mpt016"],
        lint.Config(hot_all=True, only_rules=("MPT022",)),
    )
    assert [f.rule for f in findings] == ["MPT022"], [
        f.format() for f in findings
    ]
    assert "precision drifted" in findings[0].message


def test_package_scan_has_no_unpaired_ef_and_documents_ef_off():
    """The whole-package ledger the PR signed off on: every quantize
    site is paired, annotated ef-off, or makes no claim — and the three
    deliberately-stateless paths carry their markers."""
    from mpit_tpu.analysis import numerics

    modules = []
    for ap, rel in lint.collect_files([REPO / "mpit_tpu"]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    project = lint.Project(modules=modules, config=lint.Config())
    doc = numerics.build_model(project).to_json()
    by_ef = {}
    for q in doc["quant_sites"]:
        by_ef.setdefault(q["ef"], []).append(q["site"])
    assert "unpaired" not in by_ef, by_ef
    assert len(by_ef.get("ef-off", [])) == 4, by_ef  # the 3 documented
    # paths (pserver's spans two sites: list and legacy chunk)


# ------------------------------------------------------------------- CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_cli_numerics_json_dump():
    proc = _cli("numerics", "--json", "--package",
                str(FIXTURES / "fixture_mpt022.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert len(doc["quant_sites"]) == 1
    assert doc["quant_sites"][0]["ef"] == "paired"
    assert len(doc["dequant_sites"]) == 1
    assert doc["dequant_sites"][0]["declared_mode"] == "bf16"
    assert doc["dequant_sites"][0]["codes_mode"] == "int8"


def test_cli_only_numerics_rule_gates_like_the_others():
    proc = _cli("--no-baseline", "--only", "MPT021",
                str(FIXTURES / "fixture_mpt021.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "MPT021" in proc.stdout
    proc = _cli("--no-baseline", "--only", "MPT020",
                str(FIXTURES / "fixture_mpt021.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------------------------ RT104


def test_rt104_silent_on_a_clean_quantized_round():
    with rt.checking(numerics=True) as ck:
        clean = np.arange(12, dtype=np.float32).reshape(3, 4)
        clean[1] = 0.0  # legitimate zero-absmax row
        codes, scales = quant.quantize_rows(clean, "int8")
        quant.dequantize_rows(codes, scales, "int8")
        quant.dequantize(quant.quantize(clean.ravel(), "int8"))
        quant.quantize(np.zeros(0, np.float32), "int8")  # empty chunk
    assert ck.findings == [], ck.findings


def test_rt104_catches_seeded_nan_once_per_site_with_stack():
    poisoned = np.ones(8, np.float32)
    poisoned[3] = np.nan
    with rt.checking(numerics=True) as ck:
        for _ in range(3):  # dedup: one report per call site
            quant.quantize(poisoned, "int8")
    rules = [f.rule for f in ck.findings]
    assert rules == ["RT104"], ck.findings
    assert "non-finite" in ck.findings[0].message
    assert 'File "' in ck.findings[0].message  # carries the stack


def test_rt104_catches_bad_dequant_scale():
    codes = np.array([1, 2, 3], np.int8)
    with rt.checking(numerics=True) as ck:
        quant.dequantize(quant.QuantArray("int8", float("inf"), codes))
    assert [f.rule for f in ck.findings] == ["RT104"], ck.findings


def test_rt104_zero_absmax_row_with_nonzero_codes():
    # can't be produced by the hardened kernels — drive the checker
    # directly, the way a future buggy kernel would
    with rt.checking(numerics=True) as ck:
        arr = np.zeros((2, 4), np.float32)
        codes = np.array([[0, 0, 0, 0], [7, 0, 0, 0]], np.int8)
        scales = np.ones((2, 1), np.float32)
        ck.on_quantize("quantize_rows", arr, "int8", scales, codes)
    assert [f.rule for f in ck.findings] == ["RT104"], ck.findings
    assert "zero-absmax" in ck.findings[0].message


def test_rt104_residual_norm_boundedness():
    with rt.checking(numerics=True) as ck:
        for _ in range(rt.RuntimeChecker._RESID_WARMUP):
            rt.note_residual_norm("t.ef", 0.5)
        rt.note_residual_norm("t.ef", 0.6)  # bounded: fine
        assert ck.findings == []
        rt.note_residual_norm(
            "t.ef", 0.5 * rt.RuntimeChecker.RESIDUAL_GROWTH_BOUND * 2
        )
    assert [f.rule for f in ck.findings] == ["RT104"], ck.findings
    assert "diverging" in ck.findings[0].message


def test_rt104_nonfinite_residual_norm():
    with rt.checking(numerics=True) as ck:
        rt.note_residual_norm("t.ef2", float("nan"))
    assert [f.rule for f in ck.findings] == ["RT104"], ck.findings


def test_rt104_server_apply_boundary():
    bad = np.ones(16, np.float32)
    bad[5] = np.inf
    with rt.checking(numerics=True) as ck:
        rt.note_numeric_array("pserver.apply", np.ones(16, np.float32))
        assert ck.findings == []
        rt.note_numeric_array("pserver.apply", bad)
    assert [f.rule for f in ck.findings] == ["RT104"], ck.findings


def test_rt104_off_means_zero_hooks():
    # race-only checker: the numerics hooks must stay dormant
    poisoned = np.ones(4, np.float32)
    poisoned[0] = np.nan
    with rt.checking(race=True) as ck:
        quant.quantize(poisoned, "int8")
        rt.note_residual_norm("t.off", float("nan"))
        rt.note_numeric_array("t.off", poisoned)
    assert [f for f in ck.findings if f.rule == "RT104"] == []


# --------------------------------------------- quantization error bound
#
# The property the whole EF story leans on (docs/WIRE.md): for every
# finite element, |dequantize(quantize(x)) - x| <= scale/2 for int8 and
# relative error <= 2^-8 for bf16 — INCLUDING arrays poisoned with
# NaN/Inf/-0.0, empty chunks, and all-zero blocks, where the hardened
# kernels must stay finite rather than accurate. Runs under hypothesis
# when available; otherwise a seeded-stdlib sweep covers the same space
# so the property still executes in tier-1.

_EDGE_VALUES = np.array(
    [0.0, -0.0, np.nan, np.inf, -np.inf, 1.0, -1.0,
     2.0 ** -120, 6.5e4, 3.0e38, -3.0e38],
    np.float32,
)


def _assert_roundtrip_bound(a):
    a = np.asarray(a, np.float32)
    finite = np.isfinite(a)
    # int8: finite scale/codes always; half-step absolute bound on the
    # finite lanes; NaN lanes reconstruct to exactly 0
    q = quant.quantize(a, "int8")
    assert np.isfinite(q.scale) and q.scale > 0
    assert np.abs(q.data).max(initial=0) <= 127
    out = quant.dequantize(q)
    assert np.isfinite(out).all()
    if finite.any():
        err = np.abs(out[finite] - a[finite])
        assert err.max() <= q.scale * 0.51, (a, q.scale, err.max())
    assert (out[np.isnan(a)] == 0).all()
    # bf16: lanes pass through the f32<->bf16 pair with <= 2^-8 relative
    # error on normal finite values; NaN stays NaN (representable)
    out = quant.dequantize(quant.quantize(a, "bf16"))
    normal = finite & (np.abs(a) >= 2.0 ** -100)
    nz = normal & (a != 0)
    if nz.any():
        rel = np.abs(out[nz] - a[nz]) / np.abs(a[nz])
        assert rel.max() <= 2.0 ** -8, (a, rel.max())
    assert np.isnan(out[np.isnan(a)]).all()
    # rows face: bit-equal to quantizing each row independently
    if a.size and a.size % 4 == 0:
        rows = a.reshape(-1, 4)
        codes, scales = quant.quantize_rows(rows, "int8")
        for j in range(rows.shape[0]):
            per_row = quant.quantize(rows[j], "int8")
            np.testing.assert_array_equal(codes[j], per_row.data)
            assert float(scales[j, 0]) == per_row.scale


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
except ImportError:
    def test_quantize_roundtrip_error_bound_property():
        rng = np.random.default_rng(0x20C)
        _assert_roundtrip_bound(np.zeros(0, np.float32))  # empty chunk
        _assert_roundtrip_bound(_EDGE_VALUES)
        for _ in range(200):
            n = int(rng.integers(0, 64))
            a = (
                rng.standard_normal(n)
                * np.float32(10.0) ** rng.integers(-6, 7)
            ).astype(np.float32)
            for _ in range(int(rng.integers(0, 4))):
                if n:
                    a[rng.integers(0, n)] = _EDGE_VALUES[
                        rng.integers(len(_EDGE_VALUES))
                    ]
            _assert_roundtrip_bound(a)
else:
    @settings(max_examples=300, deadline=None)
    @given(
        st.lists(
            st.floats(
                width=32, allow_nan=True, allow_infinity=True,
                allow_subnormal=True,
            ),
            max_size=64,
        )
    )
    def test_quantize_roundtrip_error_bound_property(xs):
        _assert_roundtrip_bound(np.array(xs, np.float32))
