"""mpit_tpu.obs.live + alerts tests (docs/OBSERVABILITY.md, "live").

Layers under test: the MetricsRegistry's rolling-window semantics under
an injected clock, the exporter's atomic heartbeat contract
(tmp+rename, monotonic ``seq``, first/final writes), the disabled fast
path's overhead (NULL_REGISTRY, pinned by a micro-benchmark like
NULL_SPAN), the recognized-knob env arming, the alert engine's three
conditions with dedup/re-arm — including a dead-rank alert within one
staleness window after a chaos kill silences a rank, and a straggler
alert whose skew comes from a seeded chaos delay on one rank's wire —
the checked-in golden snapshot, and the AsyncPSTrainer integration
(in-thread and, slow-marked, the real 3-process socket launch).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from mpit_tpu.obs import ObsConfig, config_from_env
from mpit_tpu.obs.__main__ import main as obs_main
from mpit_tpu.obs.alerts import (
    AlertConfig,
    AlertEngine,
    read_alerts,
    staleness_s,
)
from mpit_tpu.obs.live import (
    M_COMPUTE_S,
    M_EXCHANGE_S,
    M_REQ_FINISHED,
    M_ROUNDS,
    M_SAMPLES,
    M_SLO_MISSES,
    NULL_REGISTRY,
    SNAPSHOT_SCHEMA,
    LiveExporter,
    MetricsRegistry,
    aggregate,
    compute_fraction,
    find_live_dir,
    live_registry,
    percentile_ms,
    read_snapshots,
    validate_snapshot,
)
from mpit_tpu.transport import (
    Broker,
    ChaosConfig,
    ChaosTransport,
    RecvTimeout,
)

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "live"
)


class _Clock:
    """Injectable monotonic source for the rolling windows."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_total_and_rolling_rate(self):
        clk = _Clock()
        reg = MetricsRegistry(0, window_s=30.0, clock=clk)
        for _ in range(10):
            reg.inc(M_SAMPLES, 10)
        clk.t = 10.0
        snap = reg.snapshot()
        c = snap["counters"][M_SAMPLES]
        # covered = min(window, uptime) = 10s -> 100 samples / 10s
        assert c["total"] == 100
        assert c["rate"] == pytest.approx(10.0)

    def test_rolling_window_expires_rate_keeps_total(self):
        clk = _Clock()
        reg = MetricsRegistry(0, window_s=30.0, clock=clk)
        reg.inc(M_SAMPLES, 100)
        clk.t = 100.0  # all slices aged out; uptime > window
        snap = reg.snapshot()
        c = snap["counters"][M_SAMPLES]
        assert c["total"] == 100
        assert c["rate"] == 0.0

    def test_gauge_coerces_to_float(self):
        reg = MetricsRegistry(0)
        reg.set_gauge(M_ROUNDS, 3)  # int from a host-side counter dict
        v = reg.snapshot()["gauges"][M_ROUNDS]
        assert isinstance(v, float) and v == 3.0

    def test_hist_buckets_and_percentiles(self):
        reg = MetricsRegistry(0)
        for _ in range(99):
            reg.observe("x", 0.001)  # 1 ms
        reg.observe("x", 0.1)  # one 100 ms outlier
        h = reg.snapshot()["hists"]["x"]
        assert h["count"] == 100
        assert h["sum_s"] == pytest.approx(0.199, abs=1e-6)
        p50 = percentile_ms(h["buckets"], 0.50)
        p99 = percentile_ms(h["buckets"], 0.999)
        assert 0.5 < p50 < 2.0
        assert 50.0 < p99 < 200.0

    def test_broken_collector_contained(self):
        reg = MetricsRegistry(0)

        def boom():
            raise RuntimeError("collector died")

        reg.add_collector("bad", boom)
        reg.add_collector("good", lambda: {"n": 1})
        snap = reg.snapshot()
        assert "error" in snap["collect"]["bad"]
        assert snap["collect"]["good"] == {"n": 1}

    def test_compute_fraction_reads_rolling_rate(self):
        clk = _Clock()
        reg = MetricsRegistry(0, window_s=30.0, clock=clk)
        reg.inc(M_COMPUTE_S, 6.0)
        clk.t = 10.0
        assert compute_fraction(reg.snapshot()) == pytest.approx(0.6)
        assert compute_fraction(MetricsRegistry(1).snapshot()) is None

    def test_rejects_bad_window(self):
        with pytest.raises(ValueError):
            MetricsRegistry(0, window_s=0.0)
        with pytest.raises(ValueError):
            MetricsRegistry(0, slices=0)


# ------------------------------------------------------------- exporter


class TestExporter:
    def test_first_write_immediate_final_write_on_close(self, tmp_path):
        reg = MetricsRegistry(3)
        exp = LiveExporter(reg, str(tmp_path), interval_s=60.0)
        try:
            deadline = time.monotonic() + 5.0
            while not os.path.exists(exp.path):
                assert time.monotonic() < deadline, "no first heartbeat"
                time.sleep(0.01)
            with open(exp.path) as f:
                first = json.load(f)
            assert first["seq"] == 1  # immediately, not one interval in
        finally:
            exp.close()
        exp.close()  # idempotent
        with open(exp.path) as f:
            last = json.load(f)
        assert last["seq"] > first["seq"]
        assert last["interval_s"] == 60.0
        # atomic writes: no temp files survive
        assert [p.name for p in tmp_path.glob("*.tmp.*")] == []
        assert exp.write_errors == 0

    def test_snapshot_schema_round_trip(self, tmp_path):
        reg = MetricsRegistry(0, role="serve")
        reg.inc(M_REQ_FINISHED, 5)
        reg.observe("serve.e2e_s", 0.02)
        reg.set_gauge("serve.waiting", 2)
        reg.add_collector("wire", lambda: {"tx": {"msgs": 1}})
        exp = LiveExporter(reg, str(tmp_path), interval_s=60.0, start=False)
        exp.write()
        snaps = read_snapshots(str(tmp_path))
        assert list(snaps) == [0]
        snap = snaps[0]
        assert validate_snapshot(snap) == []
        assert snap["schema"] == SNAPSHOT_SCHEMA
        assert snap["role"] == "serve"
        assert snap["counters"][M_REQ_FINISHED]["total"] == 5
        assert snap["collect"]["wire"]["tx"]["msgs"] == 1

    def test_read_snapshots_skips_torn_and_invalid(self, tmp_path):
        reg = MetricsRegistry(0)
        LiveExporter(reg, str(tmp_path), interval_s=60.0, start=False).write()
        (tmp_path / "rank_1.json").write_text("{ torn")
        (tmp_path / "rank_2.json").write_text('{"schema": 999}')
        assert list(read_snapshots(str(tmp_path))) == [0]

    def test_validate_flags_missing_fields(self):
        assert validate_snapshot("nope") != []
        reg = MetricsRegistry(0)
        snap = reg.snapshot()  # no seq/interval_s: not exporter-stamped
        problems = validate_snapshot(snap)
        assert any("seq" in p for p in problems)


# ------------------------------------------------- arming + disabled cost


class TestArming:
    def test_live_knob_arms_and_parses(self):
        cfg = config_from_env(
            {"MPIT_OBS_LIVE": "1", "MPIT_OBS_LIVE_INTERVAL": "0.25"}
        )
        assert cfg is not None and cfg.live and cfg.live_interval == 0.25
        # recognized knob set to off-values must not flip live on
        cfg = config_from_env(
            {"MPIT_OBS_DIR": "/tmp/x", "MPIT_OBS_LIVE": "0"}
        )
        assert cfg is not None and not cfg.live

    def test_unrecognized_knob_must_not_arm(self):
        # the chaos contract: a typo'd knob is a silent no instead of a
        # silently-different run
        assert config_from_env({"MPIT_OBS_LIVELY": "1"}) is None

    def test_bad_interval_rejected(self):
        with pytest.raises(ValueError):
            ObsConfig(live=True, live_interval=0.0)

    def test_null_registry_shared_and_returned_for_unarmed(self):
        assert live_registry(object()) is NULL_REGISTRY
        assert live_registry(Broker(1).transports()[0]) is NULL_REGISTRY

    def test_disabled_publish_micro_benchmark(self):
        # the NULL_SPAN contract applied to metrics: with live off, a
        # publish site is a getattr + no-op call. Generous ceiling —
        # catches an accidental de-optimization, not scheduler noise.
        tp = Broker(1).transports()[0]
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            reg = live_registry(tp)
            reg.inc(M_SAMPLES, i)
            reg.set_gauge(M_ROUNDS, i)
        per_op = (time.perf_counter() - t0) / (2 * n)
        assert per_op < 25e-6, f"disabled publish costs {per_op*1e6:.1f}µs"


# --------------------------------------------------------------- alerts


def _stamped(reg, t, interval_s=0.1, seq=1):
    snap = reg.snapshot()
    snap["seq"] = seq
    snap["interval_s"] = interval_s
    snap["t"] = t
    return snap


class TestAlertEngine:
    def test_dead_rank_dedup_and_rearm(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        engine = AlertEngine(path, AlertConfig())
        fresh = _stamped(MetricsRegistry(0), t=100.0)
        stale = _stamped(MetricsRegistry(1), t=95.0)
        fired = engine.evaluate({0: fresh, 1: stale})
        assert [(f["kind"], f["rank"]) for f in fired] == [("dead_rank", 1)]
        assert fired[0]["detail"]["age_s"] > staleness_s(stale, engine.config)
        # condition persists: suppressed
        assert engine.evaluate({0: fresh, 1: stale}) == []
        # rank recovers: re-armed, then fires again on the next death
        assert engine.evaluate({0: fresh, 1: _stamped(
            MetricsRegistry(1), t=100.0)}) == []
        fired = engine.evaluate({0: fresh, 1: stale})
        assert [(f["kind"], f["rank"]) for f in fired] == [("dead_rank", 1)]
        # the file carries both firings; a NEW engine preloads them and
        # stays quiet on the still-active condition (--once re-runs)
        assert len(read_alerts(path)) == 2
        assert AlertEngine(path).evaluate({0: fresh, 1: stale}) == []

    def test_straggler_flags_farthest_from_median(self):
        clk = _Clock()
        regs = [MetricsRegistry(r, clock=clk) for r in range(3)]
        for reg, compute in zip(regs, (9.0, 0.2, 8.8)):
            reg.inc(M_COMPUTE_S, compute)
        clk.t = 10.0
        snaps = {r: _stamped(regs[r], t=100.0) for r in range(3)}
        fired = AlertEngine(None).evaluate(snaps)
        assert [(f["kind"], f["rank"]) for f in fired] == [("straggler", 1)]
        assert fired[0]["detail"]["compute_fraction"] == pytest.approx(
            0.02, abs=1e-3
        )

    def test_straggler_guards_uptime_and_floor(self):
        # below min_uptime the window is noise; all-idle ranks (a warmup
        # barrier) have spread 0-vs-0 and must not alert
        clk = _Clock()
        regs = [MetricsRegistry(r, clock=clk) for r in range(2)]
        regs[0].inc(M_COMPUTE_S, 0.5)
        clk.t = 0.5
        snaps = {r: _stamped(regs[r], t=100.0) for r in range(2)}
        assert AlertEngine(None).evaluate(snaps) == []

    def test_slo_burn(self):
        clk = _Clock()
        reg = MetricsRegistry(0, role="serve", clock=clk)
        reg.inc(M_REQ_FINISHED, 100)
        reg.inc(M_SLO_MISSES, 20)
        clk.t = 10.0
        fired = AlertEngine(None).evaluate({0: _stamped(reg, t=100.0)})
        assert [(f["kind"], f["rank"]) for f in fired] == [("slo_burn", 0)]
        # miss fraction 0.2 against a 0.05 error budget: burn 4x
        assert fired[0]["detail"]["burn"] == pytest.approx(4.0)

    def test_slo_burn_needs_traffic(self):
        clk = _Clock()
        reg = MetricsRegistry(0, role="serve", clock=clk)
        reg.inc(M_REQ_FINISHED, 2)  # 0.2 req/s < min_finished_rate
        reg.inc(M_SLO_MISSES, 2)
        clk.t = 10.0
        assert AlertEngine(None).evaluate({0: _stamped(reg, t=100.0)}) == []


class TestAlertsEndToEnd:
    def test_dead_rank_within_one_staleness_window_of_chaos_kill(
        self, tmp_path
    ):
        """A chaos ``kill_after`` silences rank 1's wire; its ping loop
        times out waiting for the echo that will never come and dies the
        way a real client does (final snapshot on teardown). The alert
        must fire within one staleness window of that death."""
        tps = Broker(2).transports()
        killed = ChaosTransport(tps[1], ChaosConfig(kill_after={1: 3}))
        live_dir = str(tmp_path / "live")
        interval = 0.1
        cfg = AlertConfig(min_staleness_s=0.5, staleness_factor=3.0)
        regs = [MetricsRegistry(r) for r in range(2)]
        exps = [
            LiveExporter(regs[r], live_dir, interval_s=interval)
            for r in range(2)
        ]
        stop = threading.Event()

        def echo():  # rank 0: reply to every ping until told to stop
            while not stop.is_set():
                try:
                    m = tps[0].recv(1, 3, timeout=0.05)
                except RecvTimeout:
                    continue
                tps[0].send(1, 4, m.payload)

        def pinger():  # rank 1: dies on the first unanswered ping
            try:
                for i in range(100):
                    killed.send(0, 3, i)
                    regs[1].inc(M_ROUNDS)
                    tps[1].recv(0, 4, timeout=0.3)
            except RecvTimeout:
                pass
            exps[1].close()  # the teardown final write a dying rank does

        t_echo = threading.Thread(target=echo, daemon=True)
        t_ping = threading.Thread(target=pinger, daemon=True)
        t_echo.start()
        t_ping.start()
        t_ping.join(timeout=10)
        assert not t_ping.is_alive(), "kill never silenced the pinger"
        death_t = time.time()

        window = staleness_s(
            {"interval_s": interval}, cfg
        )  # max(0.5, 3 x 0.1)
        engine = AlertEngine(str(tmp_path / "alerts.jsonl"), cfg)
        fired = []
        try:
            deadline = death_t + 4 * window
            while not fired and time.time() < deadline:
                fired = engine.evaluate(read_snapshots(live_dir))
                time.sleep(0.05)
        finally:
            stop.set()
            t_echo.join(timeout=5)
            exps[0].close()
        assert [(f["kind"], f["rank"]) for f in fired] == [("dead_rank", 1)]
        # one staleness window plus scheduling slack, not multiples of it
        assert time.time() - death_t < 2 * window, (
            f"detection took {time.time() - death_t:.2f}s "
            f"for a {window:.2f}s window"
        )
        assert regs[1].snapshot()["counters"][M_ROUNDS]["total"] >= 3
        assert read_alerts(str(tmp_path / "alerts.jsonl")) == fired

    def test_straggler_from_seeded_chaos_delay(self, tmp_path):
        """Three ranks run the same compute; rank 1's sends go through a
        seeded chaos delay. Its compute FRACTION collapses (wall time is
        eaten by the wire) and the straggler alert names it — the signal
        a group leader would use to route around a congested link."""
        tps = Broker(3).transports()
        slowed = ChaosTransport(
            tps[1], ChaosConfig(seed=5, delay=1.0, delay_s=0.03)
        )
        sends = {0: tps[0], 1: slowed, 2: tps[2]}
        live_dir = str(tmp_path / "live")
        regs = [MetricsRegistry(r) for r in range(3)]
        exps = [
            LiveExporter(regs[r], live_dir, interval_s=0.1)
            for r in range(3)
        ]

        def work(rank):
            deadline = time.monotonic() + 0.8
            i = 0
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                time.sleep(0.004)  # the "compute" every rank shares
                regs[rank].inc(M_COMPUTE_S, time.perf_counter() - t0)
                t1 = time.perf_counter()
                sends[rank].send((rank + 1) % 3, 3, i)
                regs[rank].inc(M_EXCHANGE_S, time.perf_counter() - t1)
                i += 1

        threads = [
            threading.Thread(target=work, args=(r,), daemon=True)
            for r in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        for e in exps:
            e.close()

        snaps = read_snapshots(live_dir)
        assert len(snaps) == 3
        engine = AlertEngine(
            None,
            AlertConfig(min_uptime_s=0.3, min_staleness_s=5.0),
        )
        fired = engine.evaluate(snaps)
        stragglers = [f for f in fired if f["kind"] == "straggler"]
        assert [f["rank"] for f in stragglers] == [1], fired
        fr = stragglers[0]["detail"]["fractions"]
        assert fr["1"] < min(fr["0"], fr["2"]) / 2, fr


# ------------------------------------------------------- golden fixture


class TestGoldenSnapshot:
    def test_checked_in_snapshot_validates_and_aggregates(self):
        snaps = read_snapshots(FIXTURES)
        assert list(snaps) == [0], "golden rank_0.json missing/invalid"
        assert validate_snapshot(snaps[0]) == []
        report = aggregate(snaps)
        assert report["run"]["ranks"] == 1
        assert report["run"]["throughput"] > 0
        row = report["ranks"][0]
        assert row["phases"]["compute"] > 0
        # the lint.sh gate is this exact CLI invocation
        assert obs_main(["live", FIXTURES, "--validate"]) == 0

    def test_find_live_dir_prefers_live_subdir(self, tmp_path):
        (tmp_path / "live").mkdir()
        assert find_live_dir(str(tmp_path)) == str(tmp_path / "live")
        assert find_live_dir(str(tmp_path / "live")) == str(
            tmp_path / "live"
        )


# ------------------------------------------------- trainer integration


def _live_trainer(tmp_path, obs="explicit", **kw):
    import jax.numpy as jnp
    import optax

    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import AsyncPSTrainer

    return AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo="easgd",
        tau=4,
        transport="inproc",
        obs=(
            ObsConfig(dir=str(tmp_path), live=True, live_interval=0.05)
            if obs == "explicit"
            else None
        ),
        max_exchange_failures=5,
        fetch_timeout=1.0,
        fetch_retries=3,
        **kw,
    )


@pytest.fixture(scope="module")
def mnist():
    from mpit_tpu.data import load_mnist

    return load_mnist(synthetic_train=2048, synthetic_test=512)


class TestTrainerIntegration:
    def test_live_run_snapshots_aggregate_and_cli(
        self, tmp_path, mnist, capsys
    ):
        x_tr, y_tr, *_ = mnist
        trainer = _live_trainer(tmp_path)
        _, stats = trainer.train(x_tr, y_tr, steps=24, batch_size=32)
        assert all(np.isfinite(l).all() for l in stats["losses"] if l)

        live_dir = str(tmp_path / "live")
        snaps = read_snapshots(live_dir)
        assert sorted(snaps) == [0, 1, 2]
        assert all(validate_snapshot(s) == [] for s in snaps.values())
        report = aggregate(snaps)
        assert report["run"]["ranks"] == 3
        assert report["run"]["throughput"] > 0  # samples/s, clients only
        for rank in (1, 2):
            row = report["ranks"][rank]
            assert row["samples"] > 0 and row["rounds"] > 0
            assert row["phases"]["compute"] > 0
        # the server rank publishes no compute counter -> no phase row
        assert "phases" not in report["ranks"][0]

        # the CLI over the same dir: machine-readable one-shot
        assert obs_main(
            ["live", str(tmp_path), "--once", "--json", "--no-alerts"]
        ) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["run"]["ranks"] == 3
        assert out["alerts_fired"] == []

    def test_env_knobs_arm_live(self, tmp_path, mnist, monkeypatch):
        x_tr, y_tr, *_ = mnist
        monkeypatch.setenv("MPIT_OBS_DIR", str(tmp_path))
        monkeypatch.setenv("MPIT_OBS_LIVE", "1")
        monkeypatch.setenv("MPIT_OBS_LIVE_INTERVAL", "0.05")
        trainer = _live_trainer(tmp_path, obs=None)  # config from env
        trainer.train(x_tr, y_tr, steps=8, batch_size=32)
        assert sorted(read_snapshots(str(tmp_path / "live"))) == [0, 1, 2]

    def test_live_off_writes_nothing(self, tmp_path, mnist):
        x_tr, y_tr, *_ = mnist
        trainer = _live_trainer(tmp_path, obs=None)
        trainer.train(x_tr, y_tr, steps=8, batch_size=32)
        assert not (tmp_path / "live").exists()


@pytest.mark.slow
def test_two_process_socket_live(tmp_path):
    """The acceptance run: 3 launcher-spawned OS processes over TCP with
    the live plane armed via env; the aggregator must see every rank."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    env["MPIT_OBS_DIR"] = str(tmp_path)
    env["MPIT_OBS_LIVE"] = "1"
    env["MPIT_OBS_LIVE_INTERVAL"] = "0.25"
    r = subprocess.run(
        [sys.executable, "-m", "mpit_tpu.launch", "-n", "3",
         os.path.join(repo, "examples", "ptest_proc.py"),
         "--model", "mlp", "--steps", "8", "--train-size", "256",
         "--algo", "ps-easgd"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "LIVE telemetry" in r.stderr
    snaps = read_snapshots(str(tmp_path / "live"))
    assert sorted(snaps) == [0, 1, 2]
    assert all(validate_snapshot(s) == [] for s in snaps.values())
    report = aggregate(snaps)
    assert report["run"]["throughput"] > 0
    # socket transports report real queue depth in the wire fragment
    assert any(
        row["queue_depth"] is not None
        for row in report["ranks"].values()
    )
    assert obs_main(
        ["live", str(tmp_path), "--once", "--json", "--no-alerts"]
    ) == 0
