"""Hardware-independent performance guards (VERDICT r4 item 3).

The perf story is *measured* only when the TPU tunnel answers; these
tests pin COMPILED-PROGRAM properties on the CPU mesh so a perf
regression — a host round-trip in a hot loop, a lost donation, a silent
model/step change — fails the smoke tier TODAY instead of surfacing in
some future hardware session. Three guard families:

- **Analytic FLOPs pins**: the matmul/conv FLOPs/sample that
  ``bench._model_flops_per_sample`` (the MFU numerator) reports per
  preset, pinned to recorded constants. The counter is a deterministic
  host-side jaxpr walk, so any silent change to a preset's model, loss,
  or shapes moves the number and fails here — and every archived MFU in
  ``docs/measurements/LATEST.json`` keeps meaning what it meant.
- **Compiled-program cleanliness + donation**: the serving decode
  segment and the fused trainer steps compile to programs with NO host
  callbacks/infeed/outfeed, and every donated buffer actually aliases
  an output (a lost donation = a full state copy per step; invisible to
  every correctness test, pure HBM/latency cost on hardware).
- **Compile-count stability**: trainer steps and serve segments reuse
  one compiled program across steps/rounds — a shape leak (recompile
  per step) would destroy throughput while still passing parity tests.
"""

import dataclasses
import pathlib
import re
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# bench.py lives at the repo root (it is the driver's entry point, not a
# package module); make it importable regardless of pytest's invocation dir
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import bench  # noqa: E402
from mpit_tpu.parallel.common import default_loss_fn
from mpit_tpu.run import _build_model, _load_dataset
from mpit_tpu.utils.config import TrainConfig

# ------------------------------------------------------------------ helpers

FORBIDDEN_HLO = ("callback", "infeed", "outfeed")
# custom-calls are fine when they are DEVICE kernels (TopK, on TPU also
# cholesky/sort/...); what must never appear is a host-side target
_HOST_CC = re.compile(
    r'custom_call_target="[^"]*(?:callback|host|python|py_)[^"]*"',
    re.IGNORECASE,
)


def _compiled_text(jitted, *args, **kw):
    """AOT-compile and return optimized HLO text, failing on any
    donation-discard warning raised during lowering/compilation."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        txt = jitted.lower(*args, **kw).compile().as_text()
    discarded = [w for w in caught if "donat" in str(w.message).lower()]
    assert not discarded, [str(w.message) for w in discarded]
    return txt


def _assert_clean(hlo_text):
    """No host round-trips inside the compiled program: a jax.debug
    print, io/pure_callback, or infeed/outfeed added to a hot loop
    shows up as one of these regardless of backend."""
    for bad in FORBIDDEN_HLO:
        assert bad not in hlo_text, f"compiled program contains {bad!r}"
    m = _HOST_CC.search(hlo_text)
    assert m is None, f"host-side custom call in compiled program: {m.group()}"


def _alias_count(hlo_text):
    """Entries in the HLO entry module's input_output_alias map."""
    # the map is "{ {0}: (24, {}, may-alias), ... }" — the spaced braces
    # delimit the whole map (inner "{}" carries no surrounding spaces)
    m = re.search(r"input_output_alias=\{ (.*?) \}", hlo_text)
    if m is None:
        return 0
    return m.group(1).count("-alias")


# ------------------------------------------------- analytic FLOPs pins

# FLOPs/sample of jax.grad(loss) per preset — the bench's MFU numerator
# basis (dot/conv only, 2/MAC, scan bodies × trip count), computed with
# bench._jaxpr_flops on the preset's full-size model exactly as the
# hardware bench does. Recorded 2026-08-01; rel tolerance 1e-3 (the
# count is deterministic — tolerance only absorbs float accumulation).
FLOPS_PINS = {
    "mnist-easgd": 6.755226e07,  # LeNet 28px (the 67.6M calibration
    #                              constant quoted in bench.py's docs)
    "cifar-vgg-sync": 9.256612e08,  # VGG-small 32px
    "alexnet-downpour": 4.144577e09,  # AlexNet 224px
    "resnet50-sync": 2.822966e10,  # ResNet-50 224px
    "ptb-lstm-easgd": 1.687683e09,  # 2x512 LSTM, T=32
    "ptb-transformer-seq": 2.771386e09,  # 4-layer 256/1024, T=256
    "ptb-transformer-large": 1.685481e11,  # GPT-2-small shape, T=512
}


@pytest.mark.parametrize("preset", sorted(FLOPS_PINS))
def test_analytic_flops_per_sample_pinned(preset):
    """The MFU numerator per preset is pinned: a silent model/loss/shape
    change (layer count, d_model, image size, head dtype path adding or
    removing a matmul, ...) moves this count and fails here, instead of
    silently re-basing every archived MFU number."""
    cfg = TrainConfig().apply_preset(preset)
    cfg = dataclasses.replace(cfg, train_size=8)
    x, y, *_rest, meta = _load_dataset(cfg)
    model = _build_model(cfg, meta)
    if getattr(model, "seq_axis", None):
        # the bench's own convention: the dense twin computes the same
        # FLOPs per sample (bench._model_flops_per_sample)
        model = model.clone(seq_axis=None)
    loss = default_loss_fn(model.apply)
    xb, yb = jnp.asarray(x[:2]), jnp.asarray(y[:2])
    pshape = jax.eval_shape(model.init, jax.random.key(0), xb)["params"]
    jaxpr = jax.make_jaxpr(jax.grad(loss))(pshape, xb, yb)
    got = bench._jaxpr_flops(jaxpr.jaxpr) / 2
    assert got == pytest.approx(FLOPS_PINS[preset], rel=1e-3), (
        f"{preset}: analytic FLOPs/sample drifted from the recorded pin "
        f"({got:.6e} vs {FLOPS_PINS[preset]:.6e}) — if the model change "
        "is intentional, update FLOPS_PINS and note that archived MFU "
        "rows predate it (docs/measurements/LATEST.json)"
    )


# ------------------------------------- serving decode segment guards


def _serve_fixture():
    from mpit_tpu.models import Server

    from mpit_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=17, num_layers=2, d_model=32, num_heads=4, max_len=64,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params, Server(model, params, max_batch=2, segment=4)


def test_serve_segment_compiles_clean_and_donates(topo8):
    """The decode segment — the serving hot loop — contains zero host
    transfers, and BOTH donated trees (resident cache + prev tokens)
    alias outputs, so a segment updates in place with no reallocation."""
    from mpit_tpu.models import sampling, serving

    model, params, srv = _serve_fixture()
    cache = sampling._zero_cache(srv._dec, srv._nb)
    prev = jnp.zeros((srv._nb,), jnp.int32)
    keys = jnp.stack([jax.random.split(jax.random.key(0), 4)] * srv._nb)
    ones = jnp.ones((srv._nb,), jnp.float32)
    txt = _compiled_text(
        serving._serve_segment,
        srv._dec, 4, True, None, False,
        params, cache, prev, keys, ones, ones,
    )
    _assert_clean(txt)
    want = len(jax.tree.leaves(cache)) + 1  # +1: the prev-token buffer
    assert _alias_count(txt) == want, (
        "donated decode state must alias outputs leaf-for-leaf "
        f"(got {_alias_count(txt)}, want {want})"
    )


def test_serve_spec_segment_compiles_clean_and_donates(topo8):
    """The speculative segment — the spec server's hot loop — has no
    host transfers and donates all three residents (target cache,
    draft cache, prev tokens) leaf-for-leaf."""
    from mpit_tpu.models import sampling, serving
    from mpit_tpu.models.transformer import TransformerLM

    model, params, srv_unused = _serve_fixture()
    dft = TransformerLM(
        vocab_size=17, num_layers=1, d_model=16, num_heads=2, max_len=64,
        compute_dtype=jnp.float32,
    )
    dp = dft.init(jax.random.key(5), jnp.zeros((1, 8), jnp.int32))["params"]
    srv = serving.Server(model, params, max_batch=2, draft_model=dft,
                         draft_params=dp, spec_k=3, spec_rounds=2)
    nb = srv._nb
    t_cache = sampling._zero_cache(srv._dec, nb)
    d_cache = sampling._zero_cache(srv._dft, nb)
    prev = jnp.zeros((nb,), jnp.int32)
    pos0 = jnp.ones((nb,), jnp.int32)
    txt = _compiled_text(
        serving._serve_spec_segment,
        srv._dec, srv._dft, srv.spec_k, srv.spec_rounds,
        params, dp, t_cache, d_cache, prev, pos0,
        jnp.asarray(srv.spec_rounds, jnp.int32),
    )
    _assert_clean(txt)
    want = (
        len(jax.tree.leaves(t_cache)) + len(jax.tree.leaves(d_cache)) + 1
    )
    assert _alias_count(txt) == want


def test_serve_steady_state_is_one_program(topo8):
    """A drain over same-bucket requests runs ONE compiled segment
    program — retirement/admission must not leak shapes into the
    decode loop."""
    from mpit_tpu.models import serving

    model, params, srv = _serve_fixture()
    srv.submit([1, 2, 3], 9)
    srv.submit([4, 5], 9)
    srv.step()  # compiles prefill + insert + segment
    n0 = serving._serve_segment._cache_size()
    srv.submit([6, 7, 8], 9)  # admitted into the retired slots later
    srv.drain()
    assert serving._serve_segment._cache_size() == n0


def test_batch_decode_kernel_compiles_clean(topo8):
    """The batched generate kernel (_prefill_decode_scan — every
    sampling entry point's program) contains zero host transfers."""
    from mpit_tpu.models import sampling
    from mpit_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=17, num_layers=2, d_model=32, num_heads=4, max_len=64,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    dec = model.clone(decode=True, remat=False, seq_axis=None,
                      attn_impl="xla")
    nb = 4
    keys = jnp.stack([jax.random.split(jax.random.key(i), 8)
                      for i in range(nb)])
    txt = _compiled_text(
        sampling._prefill_decode_scan,
        dec, 4, 8, True, None, False, False,
        params, sampling._zero_cache(dec, nb),
        jnp.zeros((nb, 4), jnp.int32),
        jnp.ones((nb,), jnp.int32), keys,
        jnp.asarray(1.0, jnp.float32), jnp.asarray(1.0, jnp.float32),
        jnp.asarray(0.0, jnp.float32),
    )
    _assert_clean(txt)


# ------------------------------------------------ trainer step guards


def _trainer_data():
    rng = np.random.default_rng(0)
    n = 64
    x = rng.random((n, 28, 28, 1), np.float32)
    y = rng.integers(0, 10, (n,))
    return x, y


def test_easgd_round_compiles_clean_and_donates(topo8):
    """The fused τ-round (τ local steps + elastic exchange as one
    program) has no host transfers and donates its whole state tree —
    worker params, worker opt, center, counter — leaf-for-leaf."""
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import EASGDTrainer

    tr = EASGDTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9), topo8, tau=2,
    )
    x, y = _trainer_data()
    state = tr.init_state(jax.random.key(0), x[:2])
    xr, yr = tr.round_batches(
        x.reshape(2, 32, 28, 28, 1), y.reshape(2, 32)
    )
    txt = _compiled_text(tr._round, state, xr, yr)
    _assert_clean(txt)
    want = len(jax.tree.leaves(state))
    assert _alias_count(txt) == want, (
        f"donated trainer state must alias leaf-for-leaf "
        f"(got {_alias_count(txt)}, want {want})"
    )
    # compile-count stability: rounds 2..N reuse round 1's program
    state, _ = tr.step(state, x.reshape(2, 32, 28, 28, 1), y.reshape(2, 32))
    n0 = tr._round._cache_size()
    for i in (1, 2):
        xi = np.roll(x, i, axis=0)
        state, _ = tr.step(
            state, xi.reshape(2, 32, 28, 28, 1), y.reshape(2, 32)
        )
    assert tr._round._cache_size() == n0 == 1


def test_seq_parallel_step_compiles_clean_and_donates():
    """Same guards for the seq-parallel trainer — the step both flagship
    MFU presets (ptb-transformer-seq/-large) actually run."""
    import mpit_tpu
    from mpit_tpu.models.transformer import TransformerLM
    from mpit_tpu.parallel import SeqParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(4, 2))
    model = TransformerLM(
        vocab_size=31, num_layers=2, d_model=32, num_heads=2, max_len=64,
        compute_dtype=jnp.float32, seq_axis="sp",
    )
    tr = SeqParallelTrainer(model, optax.sgd(0.1, momentum=0.9), topo)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 31, (8, 64)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(0), x[:2, :32])
    txt = _compiled_text(tr._step, state, x, y)
    _assert_clean(txt)
    want = len(jax.tree.leaves(state))
    assert _alias_count(txt) == want
    state, _ = tr.step(state, x, y)
    n0 = tr._step._cache_size()
    state, _ = tr.step(state, np.roll(x, 1, axis=0), y)
    assert tr._step._cache_size() == n0 == 1


def test_downpour_round_compiles_clean_and_donates(topo8):
    """Same guards for the Downpour τ-round."""
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import DownpourTrainer

    tr = DownpourTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9), topo8, tau=2,
    )
    x, y = _trainer_data()
    state = tr.init_state(jax.random.key(0), x[:2])
    xr, yr = tr.round_batches(
        x.reshape(2, 32, 28, 28, 1), y.reshape(2, 32)
    )
    txt = _compiled_text(tr._round, state, xr, yr)
    _assert_clean(txt)
    assert _alias_count(txt) == len(jax.tree.leaves(state))


def test_zero_step_compiles_clean_and_donates(topo8):
    """Same guards for ZeRO-1 (sharded Adam state; reduce-scatter +
    all-gather inside the step)."""
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import ZeroDataParallelTrainer

    tr = ZeroDataParallelTrainer(
        MLP(compute_dtype=jnp.float32), optax.adam(1e-3), topo8,
    )
    x, y = _trainer_data()
    state = tr.init_state(jax.random.key(0), x[:2])
    txt = _compiled_text(tr._step, state, x[:32], y[:32])
    _assert_clean(txt)
    assert _alias_count(txt) == len(jax.tree.leaves(state))


def test_moe_step_compiles_clean_and_donates(topo8):
    """Same guards for the expert-parallel step (all_to_all dispatch
    compiles into the program; no host hops around it)."""
    from mpit_tpu.models.transformer import TransformerLM
    from mpit_tpu.parallel import MoEParallelTrainer

    model = TransformerLM(
        vocab_size=31, num_layers=2, d_model=32, num_heads=4, max_len=16,
        compute_dtype=jnp.float32, moe_experts=8,
        moe_axis=topo8.worker_axis, moe_capacity_factor=4.0,
    )
    tr = MoEParallelTrainer(model, optax.sgd(0.1, momentum=0.9), topo8)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 31, (8, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = tr.init_state(jax.random.key(0), x[:2])
    if tr._step is None:
        tr._build(state)  # the lazy builder step() itself would call
    txt = _compiled_text(tr._step, state, jnp.asarray(x), jnp.asarray(y))
    _assert_clean(txt)
    assert _alias_count(txt) == len(jax.tree.leaves(state))


def test_composed_step_compiles_clean_and_donates():
    """Same guards for the 3-D dp×tp×sp composed step."""
    import mpit_tpu
    from mpit_tpu.models.transformer import TransformerLM
    from mpit_tpu.parallel import ComposedParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(
        axis_names=("dp", "tp", "sp"), mesh_shape=(2, 2, 2)
    )
    model = TransformerLM(
        vocab_size=29, num_layers=2, d_model=32, num_heads=8, max_len=32,
        compute_dtype=jnp.float32, seq_axis="sp",
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, 29, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    try:
        tr = ComposedParallelTrainer(
            model, optax.sgd(0.1, momentum=0.9), topo
        )
        state = tr.init_state(jax.random.key(0), x[:2, :16])
        txt = _compiled_text(
            tr._step, state, jnp.asarray(x), jnp.asarray(y)
        )
    except Exception as e:  # old jaxlibs can't SPMD-partition the
        if "PartitionId instruction is not supported" in str(e):
            pytest.skip(  # partial-manual (axis_names=) shard_map mode
                "backend cannot compile partial-manual shard_map"
            )
        raise
    _assert_clean(txt)
    assert _alias_count(txt) == len(jax.tree.leaves(state))


def test_pipeline_step_compiles_clean_and_donates():
    """Same guards for the pipeline trainer (gpipe default): its
    stage-sharded state dict (params + momentum + step) must donate
    leaf-for-leaf — this trainer historically lacked donation, which a
    correctness suite can never notice."""
    import mpit_tpu
    from mpit_tpu.parallel import PipelineParallelTrainer

    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
    tr = PipelineParallelTrainer(
        vocab_size=31, num_layers=4, d_model=32, num_heads=2,
        seq_len=32, topo=topo, n_micro=2,
    )
    state = tr.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    x = rng.integers(0, 31, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    txt = _compiled_text(tr._step, state, jnp.asarray(x), jnp.asarray(y))
    _assert_clean(txt)
    want = len(jax.tree.leaves(state))
    assert _alias_count(txt) == want
    state, _ = tr.step(state, x, y)
    n0 = tr._step._cache_size()
    state, _ = tr.step(state, np.roll(x, 1, axis=0), y)
    assert tr._step._cache_size() == n0 == 1


def test_sync_serial_fallback_bit_identical(topo8):
    """With both exchange knobs off (no MPIT_DP_QUANT, no
    MPIT_DP_BUCKET_BYTES) the trainer must run the pre-bucketing fused
    program EXACTLY: params equal to the BIT after several fixed-seed
    steps against a verbatim reimplementation of the original step.
    Guards the ISSUE-11 contract that the serial fallback is not
    "close", it is the same program."""
    from jax.sharding import PartitionSpec as P

    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import DataParallelTrainer
    from mpit_tpu.parallel import common as pcommon

    model = MLP(compute_dtype=jnp.float32)
    opt = optax.sgd(0.05, momentum=0.9)
    tr = DataParallelTrainer(model, opt, topo8, donate_state=False)
    assert not tr.bucketed
    x, y = _trainer_data()
    state = tr.init_state(jax.random.key(0), x[:2])

    axis = topo8.worker_axis
    loss_fn = pcommon.default_loss_fn(model.apply)

    # the pre-bucketing step, verbatim
    def train_step(state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, xb, yb)
        grads = jax.lax.pmean(grads, axis)
        loss = jax.lax.pmean(loss, axis)
        updates, opt_state = opt.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            pcommon.TrainState(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            {"loss": loss},
        )

    ref_step = jax.jit(
        jax.shard_map(
            train_step,
            mesh=topo8.mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )
    s_tr, s_ref = state, state
    for i in range(3):
        xb = np.roll(x, i, axis=0)[:32]
        yb = np.roll(y, i, axis=0)[:32]
        s_tr, _ = tr.step(s_tr, xb, yb)
        s_ref, m_ref = ref_step(s_ref, xb, yb)
        jax.block_until_ready(m_ref)
    for a, b in zip(
        jax.tree.leaves(s_tr.params), jax.tree.leaves(s_ref.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sync_step_compiles_clean_and_donates(topo8):
    """Same three guards for the sync-DP fused step (pmean inside the
    jitted program, donated TrainState)."""
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import DataParallelTrainer

    tr = DataParallelTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9), topo8,
    )
    x, y = _trainer_data()
    state = tr.init_state(jax.random.key(0), x[:2])
    txt = _compiled_text(tr._step, state, x[:32], y[:32])
    _assert_clean(txt)
    want = len(jax.tree.leaves(state))
    assert _alias_count(txt) == want
    state, _ = tr.step(state, x[:32], y[:32])
    n0 = tr._step._cache_size()
    state, _ = tr.step(state, x[32:], y[32:])
    assert tr._step._cache_size() == n0 == 1
