"""Sequence-parallel training: mesh-shape invariance on the 8-device mesh.

The claim under test (parallel/seq.py): the 2-D (dp × sp) trainer computes
the SAME function for every factorization of the 8 devices — losses and
updated parameters match between (8,1), (2,4) and (1,8) on the same global
batch, and the sp>1 path (ring attention + global positions) matches a
plain dense run of the same model.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.models.transformer import TransformerLM
from mpit_tpu.parallel import SeqParallelTrainer

V, B, T = 31, 8, 64


def _model(seq_axis):
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=2, max_len=T,
        compute_dtype=jnp.float32, seq_axis=seq_axis,
    )


def _data(seed=0, n=B):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, V, (n, T)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    return x, y


def _run_steps(mesh_shape, steps=3):
    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=mesh_shape)
    trainer = SeqParallelTrainer(
        _model("sp"), optax.sgd(0.1, momentum=0.9), topo,
        donate_state=False,
    )
    x, y = _data()
    state = trainer.init_state(
        jax.random.key(0), x[: B // mesh_shape[0], : T // mesh_shape[1]]
    )
    losses = []
    for _ in range(steps):
        state, m = trainer.step(state, x, y)
        losses.append(float(m["loss"]))
    params = jax.tree.map(np.asarray, jax.device_get(state.params))
    acc, ev_loss = trainer.evaluate(state, x, y)
    mpit_tpu.finalize()
    return losses, params, (acc, ev_loss)


class TestMeshShapeInvariance:
    @pytest.mark.slow
    def test_dp_sp_factorizations_match(self):
        ref_losses, ref_params, ref_eval = _run_steps((8, 1))
        for shape in ((2, 4), (1, 8)):
            losses, params, ev = _run_steps(shape)
            np.testing.assert_allclose(
                losses, ref_losses, rtol=1e-5, atol=1e-5,
                err_msg=f"losses diverged for mesh {shape}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=5e-5, atol=5e-5
                ),
                params, ref_params,
            )
            assert ev[0] == pytest.approx(ref_eval[0], abs=1e-6)
            assert ev[1] == pytest.approx(ref_eval[1], rel=1e-4)


class TestAgainstDense:
    def test_sharded_apply_matches_dense_apply(self):
        """One forward through the sp=8 mesh == the unsharded model."""
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(1, 8))
        trainer = SeqParallelTrainer(
            _model("sp"), optax.sgd(0.1), topo, donate_state=False
        )
        x, y = _data(seed=3, n=2)
        state = trainer.init_state(jax.random.key(1), x[:2, : T // 8])
        dense = _model(None)
        want = dense.apply({"params": state.params}, jnp.asarray(x))
        from jax.sharding import PartitionSpec as P

        sharded = jax.jit(jax.shard_map(
            lambda p, t: trainer.model.apply({"params": p}, t),
            mesh=topo.mesh,
            in_specs=(P(), P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        ))
        got = sharded(state.params, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        mpit_tpu.finalize()


class TestConvergence:
    def test_loss_decreases_on_learnable_stream(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        trainer = SeqParallelTrainer(
            _model("sp"), optax.adam(3e-3), topo, donate_state=False
        )
        # deterministic periodic token stream: trivially learnable
        stream = np.arange(B * T * 4, dtype=np.int32) % V
        x = stream.reshape(-1, T)[:B]
        y = np.roll(x, -1, axis=1).astype(np.int32)
        state = trainer.init_state(jax.random.key(2), x[:4, : T // 4])
        first = last = None
        for _ in range(30):
            state, m = trainer.step(state, x, y)
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
        assert last < first * 0.5, (first, last)
        mpit_tpu.finalize()


class TestValidation:
    def test_needs_2d_mesh(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        with pytest.raises(ValueError, match="2-D mesh"):
            SeqParallelTrainer(_model("sp"), optax.sgd(0.1), topo)
        mpit_tpu.finalize()

    def test_model_axis_must_match(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        with pytest.raises(ValueError, match="seq_axis"):
            SeqParallelTrainer(_model(None), optax.sgd(0.1), topo)
        mpit_tpu.finalize()

    def test_indivisible_batch_rejected(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        trainer = SeqParallelTrainer(
            _model("sp"), optax.sgd(0.1), topo, donate_state=False
        )
        x, y = _data()
        state = trainer.init_state(jax.random.key(0), x[:4, : T // 4])
        with pytest.raises(ValueError, match="not divisible"):
            trainer.step(state, x[:3], y[:3])
        mpit_tpu.finalize()

    def test_evaluate_accepts_indivisible_set_length(self):
        """The eval SET length owes the mesh nothing — only T must divide
        sp; the batch loop builds dp-divisible batches itself (caught by
        driving the PTB preset: its 31-window eval set crashed)."""
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        trainer = SeqParallelTrainer(
            _model("sp"), optax.sgd(0.1), topo, donate_state=False
        )
        x, y = _data(seed=5, n=7)  # 7 windows: not divisible by dp=2
        state = trainer.init_state(jax.random.key(0), x[:2, : T // 4])
        acc, loss = trainer.evaluate(state, x, y)
        assert 0.0 <= acc <= 1.0 and np.isfinite(loss)
        mpit_tpu.finalize()

    def test_max_len_guard(self):
        m = dataclasses.replace(_model(None), max_len=T // 2)
        with pytest.raises(ValueError, match="max_len"):
            m.init(jax.random.key(0), jnp.zeros((1, T), jnp.int32))
