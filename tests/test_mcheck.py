"""Model checker (MPT009-011) + trace conformance (TC201-203).

Three layers, mirroring the subsystem:

- semantics extraction: ``protocol.extract_semantics`` must read the
  shipped pserver/pclient pair's fault machinery out of the source
  exactly (attempt echo + check, reply timeout, dedup boundary);
- the explicit-state checker itself: clean on the shipped semantics,
  and each seeded single-bit mutation must produce exactly its
  violation — the model-level counterpart of the fixture packages that
  ``test_analysis.py`` lints end-to-end;
- conformance: the checked-in journals of a real chaos run pass, the
  synthetic violating journal fails with every TC rule represented, and
  the CLI's exit gate is format-independent.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import pytest

from mpit_tpu.analysis import astutil, conformance, lint, mcheck, protocol

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"
CONF = REPO / "tests" / "fixtures" / "conformance"


def _project(*paths):
    modules = []
    for ap, rel in lint.collect_files(paths or [PKG]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    return lint.Project(modules=modules, config=lint.Config())


@pytest.fixture(scope="module")
def shipped_sem():
    sem = protocol.extract_semantics(_project())
    assert sem is not None
    return sem


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=180,
    )


# ------------------------------------------------------------- folding


@pytest.mark.parametrize(
    "src, value",
    [
        ("2 + 1", 3),
        ("-1", -1),
        ("(1 << 4) | 2", 18),
        ("40 + 2", 42),
        ("'obs' + '1'", "obs1"),
        ("7 // 2", 3),
        ("True", None),  # bools are not tags
        ("1 + True", None),
        ("1 // 0", None),  # no fold, no crash
        ("2 ** 10", None),  # Pow deliberately unfolded
        ("x + 1", None),  # names are the graph's job
        ("'a' * 3", None),  # only concatenation folds for strings
    ],
)
def test_fold_constant(src, value):
    node = ast.parse(src, mode="eval").body
    assert astutil.fold_constant(node) == value


def test_mpt002_fires_on_folded_tag_expression(tmp_path):
    """The satellite regression: a literal tag written as arithmetic
    (``40 + 2``) used to be skipped; folding makes it a literal site."""
    mod = tmp_path / "folded.py"
    mod.write_text(
        "def push(transport, payload):\n"
        "    transport.send(0, 40 + 2, payload)\n"
    )
    findings = lint.run_lint([mod], lint.Config(hot_all=True))
    assert [f.rule for f in findings] == ["MPT002"], [
        f.format() for f in findings
    ]


# -------------------------------------------------- semantics extraction


def test_shipped_semantics_extracted_exactly(shipped_sem):
    sem = shipped_sem
    assert (sem.client_role, sem.server_role) == ("client", "server")
    assert sem.request_tag == 1 and sem.reply_tag == 4
    assert sem.push_tags == (2, 3) and sem.stop_tag == 5
    assert sem.attempt_echoed and sem.attempt_checked
    assert sem.reply_recv_timeout
    assert sem.dedup is not None and not sem.dedup_opaque
    assert sem.dedup.rejects_at_boundary  # the <= boundary, as written
    assert sem.dedup.checks_seen and sem.dedup.prunes_seen
    assert sem.dedup.window_default == 1024
    assert sem.dedup.symbol == "_DedupWindow.admit"
    assert sem.dedup.keyed_by_epoch  # (src, epoch) key, not src alone
    assert sem.snapshot_includes_dedup is True  # shard snapshot carries it
    assert sem.handoff_includes_dedup is True  # reshard ships the window
    assert sem.reply_send.rel.endswith("parallel/pserver.py")
    assert sem.reply_recv.rel.endswith("parallel/pclient.py")


# ----------------------------------------------------- the model checker


def test_shipped_protocol_is_clean_and_exhaustive(shipped_sem):
    """The acceptance bar: both default configurations explored to
    fixpoint, no violations, a real state count reported, and every
    fault kind contributing schedules."""
    results = mcheck.check_all(mcheck.from_protocol(shipped_sem))
    assert [r.config.algo for r in results] == [
        "easgd", "downpour", "easgd-elastic", "easgd-sharded"
    ]
    for r in results:
        assert r.ok, (r.config.algo, r.violations)
        assert not r.truncated
        assert r.states > 10_000  # exhaustive, not a smoke walk
        assert r.fault_points >= len(r.config.kinds)


def _mutate(sem, **kw):
    base = mcheck.from_protocol(sem)
    dk = kw.pop("dedup_kw", None)
    if dk:
        kw["dedup"] = dataclasses.replace(base.dedup, **dk)
    return dataclasses.replace(base, **kw)


@pytest.mark.parametrize(
    "mutation, rule",
    [
        # dedup boundary off-by-one: < where <= is needed
        ({"dedup_kw": {"rejects_at_boundary": False}}, "MPT009"),
        # seen-set membership test removed entirely
        ({"dedup_kw": {"checks_seen": False}}, "MPT009"),
        # reply wait can block forever: a dropped REQ deadlocks the run
        ({"reply_recv_timeout": False}, "MPT010"),
        # echoed attempt id never compared to the live one
        ({"attempt_checked": False}, "MPT011"),
        # no attempt id on the wire at all
        ({"attempt_echoed": False, "attempt_checked": False}, "MPT011"),
        # dedup window keyed by src alone: a replacement client's fresh
        # seq stream is mistaken for its predecessor's replays
        ({"dedup_keyed_by_epoch": False}, "MPT009"),
        # shard snapshot persists the center but not the dedup window:
        # crash-restore re-applies an already-acked push
        ({"snapshot_includes_dedup": False}, "MPT009"),
        # shard handoff ships the slice but forgets its dedup window:
        # the new owner re-applies a push the old owner already acked
        ({"handoff_carries_dedup": False}, "MPT009"),
    ],
)
def test_single_bit_mutations_each_caught(shipped_sem, mutation, rule):
    bad = _mutate(shipped_sem, **mutation)
    results = mcheck.check_all(bad)
    hit = {r_ for res in results for r_ in res.violations}
    assert rule in hit, (mutation, [res.violations for res in results])


def test_opaque_dedup_is_trusted_not_flagged(shipped_sem):
    """Resolve-or-skip: an admit the extractor can't parse must be
    assumed correct, not modeled as absent (which would always produce
    a spurious MPT009)."""
    opaque = dataclasses.replace(
        mcheck.from_protocol(shipped_sem), dedup=None, dedup_opaque=True
    )
    for res in mcheck.check_all(opaque):
        assert "MPT009" not in res.violations, res.violations


def test_checker_counts_distinct_fault_schedules(shipped_sem):
    r = mcheck.check(mcheck.from_protocol(shipped_sem))
    # drop/dup/reorder on every REQ/PUSH send point + stale on replies:
    # well above one per kind, and recorded per (kind, message)
    assert r.fault_points > 10


# ---------------------------------------------------------- conformance


def test_good_run_conforms():
    """Journals checked in from a real 3-rank socket run under
    MPIT_CHAOS_DUP — duplicated deliveries must be explained by the
    fault log, not flagged."""
    report = conformance.check_conformance(
        str(CONF / "good_run"), _project()
    )
    assert report.ok, [str(v) for v in report.violations]
    assert report.sends > 0 and report.recvs > 0
    assert report.faults > 0  # the chaos log was found and used


def test_bad_run_rejected_on_every_axis():
    report = conformance.check_conformance(
        str(CONF / "bad_run"), _project()
    )
    rules = sorted({v.rule for v in report.violations})
    assert rules == ["TC201", "TC202", "TC203"], [
        str(v) for v in report.violations
    ]


def test_orphan_reply_licensed_by_dup_request_fault(tmp_path):
    """A duplicated FETCH makes the server send an extra PARAM the
    client may exit without draining — the deficit on the reply stream
    must be licensed by the dup fault on the reverse request stream
    (seen live on a MPIT_CHAOS_DUP seed), and must still be flagged
    when no fault log explains it."""
    (tmp_path / "obs_rank1.jsonl").write_text(
        '{"ev": "send", "rank": 1, "t": 1.0, "step": 1, "dst": 0,'
        ' "mtag": 1, "n": 0, "bytes": 8, "dur": 0.001}\n'
        '{"ev": "recv", "rank": 1, "t": 1.3, "step": 4, "src": 0,'
        ' "mtag": 4, "n": 0, "bytes": 64, "wait": 0.001}\n'
    )
    (tmp_path / "obs_rank0.jsonl").write_text(
        '{"ev": "recv", "rank": 0, "t": 1.1, "step": 2, "src": 1,'
        ' "mtag": 1, "n": 0, "bytes": 8, "wait": 0.001}\n'
        '{"ev": "recv", "rank": 0, "t": 1.1, "step": 3, "src": 1,'
        ' "mtag": 1, "n": 1, "bytes": 8, "wait": 0.001}\n'
        '{"ev": "send", "rank": 0, "t": 1.2, "step": 4, "dst": 1,'
        ' "mtag": 4, "n": 0, "bytes": 64, "dur": 0.001}\n'
        '{"ev": "send", "rank": 0, "t": 1.2, "step": 5, "dst": 1,'
        ' "mtag": 4, "n": 1, "bytes": 64, "dur": 0.001}\n'
    )
    proj = _project()
    report = conformance.check_conformance(str(tmp_path), proj)
    rules = [v.rule for v in report.violations]
    assert rules == ["TC202", "TC202"], [str(v) for v in report.violations]

    (tmp_path / "faults_rank1.jsonl").write_text(
        '{"ev": "fault", "kind": "duplicate", "src": 1, "dst": 0,'
        ' "tag": 1, "n": 0}\n'
    )
    report = conformance.check_conformance(str(tmp_path), proj)
    assert report.ok, [str(v) for v in report.violations]


def test_conform_cli_gate():
    good = _cli("conform", str(CONF / "good_run"))
    assert good.returncode == 0, good.stdout + good.stderr
    bad = _cli("conform", str(CONF / "bad_run"), "--json")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    doc = json.loads(bad.stdout)
    assert {v["rule"] for v in doc["violations"]} == {
        "TC201", "TC202", "TC203"
    }
    missing = _cli("conform", str(CONF / "nonexistent"))
    assert missing.returncode == 2


def test_mcheck_cli_reports_state_counts():
    proc = _cli("mcheck", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    # easgd, downpour, easgd-elastic, easgd-sharded + fleet-route
    assert len(doc) == 5
    for entry in doc:
        assert entry["violations"] == {}
        assert not entry["truncated"]
    for entry in doc[:4]:  # the PS configs: exhaustive, not a smoke walk
        assert entry["states"] > 10_000
    fleet = doc[4]
    assert "fleet-route" in fleet["config"]
    assert fleet["states"] > 100  # small model, still a real exploration


# ------------------------------------------------ exit-gate consistency


def test_json_flag_gate_matches_text_mode(tmp_path):
    """The satellite fix: ``--json`` used to exit 2 (unknown flag) while
    text mode exited 1 on the same findings — the gate must not depend
    on the output format."""
    bad = tmp_path / "drifted.py"
    bad.write_text(
        "def push_update(transport, payload):\n"
        "    transport.send(0, 42, payload)\n"
    )
    codes = {}
    for label, args in {
        "text": (),
        "format_json": ("--format", "json"),
        "json_flag": ("--json",),
    }.items():
        codes[label] = _cli("--no-baseline", *args, str(bad)).returncode
    assert codes == {"text": 1, "format_json": 1, "json_flag": 1}, codes


# ------------------------------------------- end-to-end (slow, 2 procs)


@pytest.mark.slow
def test_two_process_chaos_run_conforms(tmp_path):
    """Full loop: launch the MNIST PS example as OS processes over TCP
    with dup-only chaos and obs armed, then audit the fresh journals
    with the conformance checker. Dup-only keeps the run fast (drops
    would ride out the client's default reply timeout)."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    env["MPIT_OBS_DIR"] = str(tmp_path)
    env["MPIT_CHAOS_DUP"] = "0.25"
    env["MPIT_CHAOS_SEED"] = "7"
    r = subprocess.run(
        [sys.executable, "-m", "mpit_tpu.launch", "-n", "3",
         str(REPO / "examples" / "ptest_proc.py"),
         "--model", "mlp", "--steps", "8", "--train-size", "256",
         "--algo", "ps-easgd"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    report = conformance.check_conformance(str(tmp_path), _project())
    assert report.ok, [str(v) for v in report.violations]
    assert report.faults > 0, "chaos produced no faults — raise DUP rate"
