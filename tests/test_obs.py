"""mpit_tpu.obs tests (docs/OBSERVABILITY.md).

Layers under test: the disabled fast path's overhead contract (no wrapper,
no span object, pinned by a micro-benchmark), cross-rank trace propagation
through the real PS protocol (client fetch and server reply share one
trace id), telemetry counters/sampling, the Perfetto merger (valid JSON,
per-rank monotonic timestamps, chaos faults as placed instant events), and
the AsyncPSTrainer integration — the ISSUE acceptance run: a 2-client
easgd job under chaos whose merged timeline has >= 1 cross-rank trace and
fault markers.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from mpit_tpu.obs import (
    NULL_SPAN,
    Journal,
    ObsConfig,
    config_from_env,
    diff_summaries,
    maybe_wrap,
    merge_to_chrome_trace,
    read_journal,
    span,
    summarize,
    trace_ids_by_rank,
    wrap_obs_transports,
    write_fault_log,
)
from mpit_tpu.obs.__main__ import main as obs_main
from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_FETCH,
    TAG_PARAM,
    PServer,
    spawn_server_thread,
)
from mpit_tpu.transport import Broker, FaultEvent, SocketTransport

DIM = 8


class TestConfig:
    def test_env_arming_recognized_knobs_only(self):
        assert config_from_env({}) is None
        assert config_from_env({"OTHER": "1"}) is None
        # unrecognized MPIT_OBS_* must not arm (the chaos contract)
        assert config_from_env({"MPIT_OBS_FOO": "1"}) is None
        cfg = config_from_env({
            "MPIT_OBS_DIR": "/tmp/x",
            "MPIT_OBS_SAMPLE": "3",
            "MPIT_OBS_TRACE": "0",
        })
        assert cfg.dir == "/tmp/x" and cfg.sample == 3 and not cfg.trace
        assert cfg.telemetry
        # any single recognized knob arms
        assert config_from_env({"MPIT_OBS_TELEMETRY": "1"}) is not None

    def test_sample_validation(self):
        with pytest.raises(ValueError, match="sample"):
            ObsConfig(sample=0)

    def test_max_records_knob(self):
        with pytest.raises(ValueError, match="max_records"):
            ObsConfig(max_records=0)
        assert config_from_env(
            {"MPIT_OBS_MAX_RECORDS": "100"}
        ).max_records == 100
        # the knob alone arms obs (recognized-knob contract)
        assert config_from_env({"MPIT_OBS_MAX_RECORDS": "5"}) is not None


class TestJournalCap:
    """MPIT_OBS_MAX_RECORDS: bounded journals that SAY they dropped."""

    def test_cap_drops_counted_in_footer(self, tmp_path):
        path = str(tmp_path / "obs_rank0.jsonl")
        j = Journal(path, 0, max_records=3)
        for i in range(10):
            j.event("send", i, n=i)
        assert j.dropped_records == 7
        j.close()
        recs = list(read_journal(path))
        assert len(recs) == 4  # 3 events + the footer
        footer = recs[-1]
        assert footer["ev"] == "journal_cap"
        assert footer["cap"] == 3
        assert footer["dropped_records"] == 7
        # the kept records are the FIRST three (head, not reservoir)
        assert [r["n"] for r in recs[:3]] == [0, 1, 2]

    def test_footer_written_even_at_zero_drops(self, tmp_path):
        path = str(tmp_path / "obs_rank0.jsonl")
        j = Journal(path, 0, max_records=100)
        j.event("send", 1, n=0)
        j.close()
        j.close()  # idempotent: one footer, not two
        recs = list(read_journal(path))
        footers = [r for r in recs if r.get("ev") == "journal_cap"]
        assert len(footers) == 1
        assert footers[0]["dropped_records"] == 0

    def test_uncapped_journal_has_no_footer(self, tmp_path):
        path = str(tmp_path / "obs_rank0.jsonl")
        j = Journal(path, 0)
        j.event("send", 1, n=0)
        j.close()
        assert all(
            r.get("ev") != "journal_cap" for r in read_journal(path)
        )

    def test_journal_validates_cap(self, tmp_path):
        with pytest.raises(ValueError, match="max_records"):
            Journal(str(tmp_path / "j.jsonl"), 0, max_records=0)


class TestDisabledFastPath:
    """The overhead contract: MPIT_OBS_* unset means no wrapper exists and
    the protocol-side hook is a getattr returning one shared no-op."""

    def test_maybe_wrap_identity(self):
        tp = Broker(1).transports()[0]
        assert maybe_wrap(tp, None) is tp

    def test_span_hook_is_shared_noop(self):
        tp = Broker(1).transports()[0]
        s1 = span(tp, "a", step=1)
        s2 = span(tp, "b")
        assert s1 is NULL_SPAN and s2 is NULL_SPAN  # no allocation at all
        with s1 as ctx:
            assert ctx is None

    def test_span_hook_micro_benchmark(self):
        # a deliberately generous ceiling (the hook measures ~0.3 µs);
        # catches an accidental de-optimization (journal/alloc on the
        # disabled path), not scheduler noise
        tp = Broker(1).transports()[0]
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with span(tp, "hot"):
                pass
        per_op = (time.perf_counter() - t0) / n
        assert per_op < 25e-6, f"disabled span hook costs {per_op*1e6:.1f}µs"

    def test_disabled_compute_hook_micro_benchmark(self):
        # the exact roofline pattern ps_roles runs every τ-block: a span
        # with args plus the conditional proof-of-completion guard. With
        # obs off the span is NULL_SPAN (ctx None) and the barrier must
        # never fire — pin the whole hook near zero like the bare span
        tp = Broker(1).transports()[0]
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            with span(tp, "compute", round=i, steps=4) as ctx:
                pass
            if ctx is not None:
                raise AssertionError("disabled span must yield None")
        per_op = (time.perf_counter() - t0) / n
        assert per_op < 25e-6, (
            f"disabled compute hook costs {per_op*1e6:.1f}µs"
        )


def _ps_obs_world(tmp_path, num_clients=1):
    """Obs-wrapped Broker world: rank 0 = PServer, ranks 1.. = clients."""
    cfg = ObsConfig(dir=str(tmp_path))
    tps = wrap_obs_transports(Broker(1 + num_clients).transports(), cfg)
    server = PServer(
        tps[0], np.full(DIM, 2.0, np.float32), num_clients=num_clients
    )
    thread = spawn_server_thread(server)
    return cfg, tps, server, thread


class TestTraceAcrossRanks:
    def test_fetch_and_reply_share_one_trace(self, tmp_path):
        cfg, tps, server, thread = _ps_obs_world(tmp_path)
        client = PClient(tps[1], [0], DIM, timeout=5.0)
        with span(tps[1], "exchange", round=0):
            out = client.fetch()
        np.testing.assert_array_equal(out, np.full(DIM, 2.0, np.float32))
        client.push_easgd(np.ones(DIM, np.float32))  # envelope transparency
        client.stop()
        thread.join(timeout=5)
        assert server.error is None
        assert server.counts["push_easgd"] == 1  # obs envelope was stripped
        for t in tps:
            t.obs_tracer.close()

        by_rank = trace_ids_by_rank([str(tmp_path)])
        assert set(by_rank) == {0, 1}
        shared = by_rank[0] & by_rank[1]
        assert shared, f"no cross-rank trace: {by_rank}"
        # the client's FETCH send and the server's PARAM reply are the
        # same trace, linked via the reply's remote parent
        recs0 = read_journal(str(tmp_path / "obs_rank0.jsonl"))
        reply = next(
            r for r in recs0 if r["ev"] == "send" and r["mtag"] == TAG_PARAM
        )
        recs1 = read_journal(str(tmp_path / "obs_rank1.jsonl"))
        fetch = next(
            r for r in recs1 if r["ev"] == "send" and r["mtag"] == TAG_FETCH
        )
        assert reply["trace"] == fetch["trace"]
        assert reply["parent"] == fetch["span"]

    def test_spans_do_not_chain_across_rounds(self, tmp_path):
        # two separate exchange spans must be two traces: the remote
        # parent from round N's PARAM recv must not leak into round N+1
        cfg, tps, server, thread = _ps_obs_world(tmp_path)
        client = PClient(tps[1], [0], DIM, timeout=5.0)
        for rnd in range(2):
            with span(tps[1], "exchange", round=rnd):
                client.fetch()
        client.stop()
        thread.join(timeout=5)
        for t in tps:
            t.obs_tracer.close()
        recs1 = read_journal(str(tmp_path / "obs_rank1.jsonl"))
        traces = {r["trace"] for r in recs1 if r.get("ev") == "span_b"}
        assert len(traces) == 2, traces

    def test_lamport_clock_orders_cause_before_effect(self, tmp_path):
        cfg, tps, server, thread = _ps_obs_world(tmp_path)
        client = PClient(tps[1], [0], DIM, timeout=5.0)
        client.fetch()
        client.stop()
        thread.join(timeout=5)
        for t in tps:
            t.obs_tracer.close()
        recs0 = read_journal(str(tmp_path / "obs_rank0.jsonl"))
        recs1 = read_journal(str(tmp_path / "obs_rank1.jsonl"))
        send = next(r for r in recs1 if r.get("mtag") == TAG_FETCH)
        recv = next(
            r for r in recs0
            if r["ev"] == "recv" and r.get("mtag") == TAG_FETCH
        )
        assert recv["step"] > send["step"]  # "step" carries the clock


class TestTelemetry:
    def test_counters_and_sampling(self, tmp_path):
        # sample=3 journals every 3rd event per stream; counters stay exact
        cfg = ObsConfig(dir=str(tmp_path), sample=3)
        tps = wrap_obs_transports(Broker(2).transports(), cfg)
        payload = np.arange(16, dtype=np.float32)
        for i in range(9):
            tps[0].send(1, 7, payload)
        for _ in range(9):
            tps[1].recv(0, 7, timeout=1)
        s = tps[0].summary()
        assert s["send"]["1:7"]["msgs"] == 9
        assert s["send"]["1:7"]["bytes"] == 9 * payload.nbytes
        assert tps[1].summary()["recv"]["0:7"]["msgs"] == 9
        for t in tps:
            t.obs_tracer.close()
        recs = read_journal(str(tmp_path / "obs_rank0.jsonl"))
        assert sum(1 for r in recs if r.get("ev") == "send") == 3  # n=0,3,6

    def test_recv_timeout_counted_not_journaled(self, tmp_path):
        from mpit_tpu.transport import RecvTimeout

        cfg = ObsConfig(dir=str(tmp_path))
        tps = wrap_obs_transports(Broker(2).transports(), cfg)
        with pytest.raises(RecvTimeout):
            tps[0].recv(1, 7, timeout=0.01)
        assert tps[0].summary()["recv"]["1:7"]["timeouts"] == 1
        tps[0].obs_tracer.close()
        recs = read_journal(str(tmp_path / "obs_rank0.jsonl"))
        assert recs == []  # a watchdog's poll loop must not spam records

    def test_approx_nbytes_exact_for_wire_payloads(self):
        from mpit_tpu.obs.telemetry import _approx_nbytes

        arr = np.arange(16, dtype=np.float32)
        assert _approx_nbytes(arr) == arr.nbytes == 64
        # the PS chunked scatter envelope: (epoch, seq, chunk) must report
        # scalar-int overhead + the chunk's TRUE nbytes (the byte counters
        # are the quantized-wire baseline — ISSUE 6 satellite)
        chunk = np.zeros(100, dtype=np.float32)
        assert _approx_nbytes((3, 7, chunk)) == 8 + 8 + chunk.nbytes
        # object-dtype ndarray: nbytes counts pointers, not contents
        ragged = np.empty(2, dtype=object)
        ragged[0] = np.zeros(4, np.float32)
        ragged[1] = np.zeros(8, np.float32)
        assert _approx_nbytes(ragged) == 16 + 32
        assert _approx_nbytes(b"abcd") == 4
        assert _approx_nbytes(None) == 0

    def test_journal_reserved_keys_sanitized(self, tmp_path):
        j = Journal(str(tmp_path / "obs_rank0.jsonl"), rank=0)
        j.event("custom", 1, step=9, tag="x", value=3)
        j.close()
        (rec,) = read_journal(str(tmp_path / "obs_rank0.jsonl"))
        assert rec["step"] == 1 and rec["tag"] == "obs"  # owner's fields
        assert rec["x_step"] == 9 and rec["x_tag"] == "x"
        assert rec["value"] == 3


class TestSocketPairTrace:
    def test_socket_fetch_reply_one_trace_and_valid_merge(self, tmp_path):
        base_port = 29_951
        cfg = ObsConfig(dir=str(tmp_path))
        srv = maybe_wrap(SocketTransport(0, 2, base_port=base_port), cfg)
        cli = maybe_wrap(SocketTransport(1, 2, base_port=base_port), cfg)

        def serve():
            msg = srv.recv(tag=TAG_FETCH, timeout=10)
            srv.send(msg.src, TAG_PARAM, np.full(DIM, 4.0, np.float32))

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        with span(cli, "exchange"):
            cli.send(0, TAG_FETCH, None)
            msg = cli.recv(0, TAG_PARAM, timeout=10)
        np.testing.assert_array_equal(
            msg.payload, np.full(DIM, 4.0, np.float32)
        )
        th.join(timeout=10)
        cli.close()
        srv.close()

        by_rank = trace_ids_by_rank([str(tmp_path)])
        assert by_rank[0] & by_rank[1], by_rank
        trace = merge_to_chrome_trace([str(tmp_path)])
        json.dumps(trace)  # Perfetto-loadable: plain JSON object format
        evs = trace["traceEvents"]
        assert {e["ph"] for e in evs} >= {"X", "s", "f", "B", "E"}
        # per-rank monotonic timestamps (journal order == time order)
        for path in sorted(os.listdir(tmp_path)):
            ts = [
                r["t"]
                for r in read_journal(str(tmp_path / path))
                if "t" in r
            ]
            assert ts == sorted(ts), path


class TestWirePhases:
    """The roofline wire split: SocketTransport times every send's
    serialize / queue_wait / write and every recv body's transfer /
    deserialize; the telemetry wrapper harvests both into per-(peer, tag)
    counters and the sampled journal records."""

    def test_socket_send_recv_phase_split(self, tmp_path):
        base_port = 29_961
        cfg = ObsConfig(dir=str(tmp_path))
        a = maybe_wrap(SocketTransport(0, 2, base_port=base_port), cfg)
        b = maybe_wrap(SocketTransport(1, 2, base_port=base_port), cfg)
        try:
            payload = np.arange(4096, dtype=np.float32)
            for _ in range(3):
                a.send(1, 7, payload)  # sync: isend().wait() under the hood
            for _ in range(3):
                b.recv(0, 7, timeout=10)
            sa = a.summary()
            ph = sa["send"]["1:7"]["phase_s"]
            assert set(ph) == {"serialize", "queue_wait", "write"}
            assert all(v >= 0 for v in ph.values())
            assert ph["serialize"] > 0  # pickling 16 KiB is measurable
            # receiver side: the read loop's transfer/deserialize split,
            # surfaced through the wrapper chain into the summary
            rx = b.summary()["rx_phase_s"]["0:7"]
            assert rx["msgs"] == 3
            assert rx["transfer"] >= 0 and rx["deserialize"] >= 0
            # sampled journal records carry the per-send split
            a.obs_tracer.close()
            recs = read_journal(str(tmp_path / "obs_rank0.jsonl"))
            sends = [r for r in recs if r.get("ev") == "send"]
            assert sends and all(
                {"ser", "qw", "wr"} <= set(r) for r in sends
            )
        finally:
            a.close()
            b.close()

    def test_inproc_sends_have_no_phase_split(self):
        # the base Transport's isend measures nothing — phase counters
        # must stay absent, not zero-filled (absence of evidence)
        cfg = ObsConfig()
        tps = wrap_obs_transports(Broker(2).transports(), cfg)
        tps[0].send(1, 7, np.zeros(8, np.float32))
        tps[1].recv(0, 7, timeout=1)
        s = tps[0].summary()
        assert "phase_s" not in s["send"]["1:7"]
        assert "rx_phase_s" not in s
        for t in tps:
            t.obs_tracer.close()


def _wire_echo_child(rank, size, base_port, n, q):
    try:
        tp = SocketTransport(rank, size, base_port=base_port)
        for _ in range(n):
            msg = tp.recv(src=0, tag=7, timeout=30)
            tp.send(0, 8, msg.payload)
        tp.recv(src=0, tag=9, timeout=30)  # stop marker
        q.put(("ok",))
        tp.close()
    except BaseException as e:
        q.put(("err", repr(e)))


class TestExactWireBytes:
    def test_summary_bytes_equal_socket_bytes_two_process(self, tmp_path):
        """The fast-wire byte-accounting contract (docs/WIRE.md): with a
        real peer in ANOTHER process, the telemetry summary's per-stream
        byte totals equal the socket layer's own tx/rx counters exactly —
        the summary reports on-wire frame lengths (length prefix
        included), not payload estimates."""
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        base_port = 29_971
        n = 5
        child = ctx.Process(
            target=_wire_echo_child,
            args=(1, 2, base_port, n, q),
            daemon=True,
        )
        child.start()
        cfg = ObsConfig(dir=str(tmp_path))
        raw = SocketTransport(0, 2, base_port=base_port)
        tp = maybe_wrap(raw, cfg)
        # mixed traffic: framed envelopes AND a pickle-fallback dict
        envelope = (1 << 70, 3, 0, np.arange(2048, dtype=np.float32))
        deadline = time.monotonic() + 20
        while True:  # child may not be listening yet
            try:
                tp.send(1, 7, envelope)
                break
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        for _ in range(n - 2):
            tp.send(1, 7, envelope)
        tp.send(1, 7, {"pickle": "fallback"})
        for _ in range(n):
            tp.recv(1, 8, timeout=30)
        tp.send(1, 9, None)
        assert q.get(timeout=30)[0] == "ok"
        child.join(timeout=10)
        s = tp.summary()
        counts = raw.wire_byte_counts()
        tx = sum(v["bytes"] for v in s["send"].values())
        rx = sum(v["bytes"] for v in s["recv"].values())
        assert tx == counts["tx"] > 0
        assert rx == counts["rx"] > 0
        tp.close()


class TestMerge:
    def _write_rank(self, tmp_path, rank, events):
        j = Journal(str(tmp_path / f"obs_rank{rank}.jsonl"), rank)
        for ev, clk, fields in events:
            j.event(ev, clk, **fields)
        j.close()

    def test_fault_overlay_placed_and_unplaced(self, tmp_path):
        self._write_rank(tmp_path, 1, [
            ("send", 1, {"dst": 0, "mtag": 2, "n": 0, "bytes": 8,
                         "dur": 0.001}),
        ])
        faults_path = str(tmp_path / "faults.jsonl")
        n = write_fault_log(
            [
                FaultEvent("corrupt", 1, 0, 2, 0),  # joins the send above
                FaultEvent("drop", 1, 0, 2, 99),    # no telemetry match
            ],
            faults_path,
        )
        assert n == 2
        trace = merge_to_chrome_trace([str(tmp_path)], faults_path)
        chaos = [e for e in trace["traceEvents"] if e.get("cat") == "chaos"]
        assert len(chaos) == 2
        placed = next(e for e in chaos if e["name"] == "fault corrupt")
        send = next(
            e for e in trace["traceEvents"]
            if e["ph"] == "X" and e["name"].startswith("send")
        )
        assert placed["ph"] == "i" and placed["ts"] == send["ts"]
        unplaced = next(e for e in chaos if e["name"] == "fault drop")
        assert unplaced["args"]["unplaced"] and unplaced["ts"] == 0.0

    def test_summarize_and_malformed_lines_skipped(self, tmp_path):
        self._write_rank(tmp_path, 0, [
            ("send", 1, {"dst": 1, "mtag": 1, "n": 0, "bytes": 10,
                         "dur": 0.0, "trace": 7, "span": 8}),
            ("recv", 2, {"src": 1, "mtag": 4, "n": 0, "bytes": 5,
                         "wait": 0.0}),
        ])
        with open(tmp_path / "obs_rank0.jsonl", "a") as f:
            f.write("{truncated by a killed rank\n")
        s = summarize([str(tmp_path)])
        assert s[0]["sends"] == 1 and s[0]["recvs"] == 1
        assert s[0]["bytes"] == 10 and s[0]["traces"] == 1

    def test_diff_summaries_streams_and_latency(self, tmp_path):
        """Two synthetic runs: one stream doubles its message count, one
        regresses its latency by 4x (two whole log2 buckets), one is
        identical — the diff must report exactly the first two."""
        run_a, run_b = tmp_path / "a", tmp_path / "b"
        run_a.mkdir(), run_b.mkdir()
        self._write_rank(run_a, 1, [
            ("send", 1, {"dst": 0, "mtag": 2, "n": 0, "bytes": 10,
                         "dur": 0.001}),
            ("recv", 2, {"src": 0, "mtag": 4, "n": 0, "bytes": 5,
                         "wait": 0.004}),
            ("send", 3, {"dst": 0, "mtag": 5, "n": 0, "bytes": 1,
                         "dur": 0.001}),
        ])
        self._write_rank(run_b, 1, [
            ("send", 1, {"dst": 0, "mtag": 2, "n": 0, "bytes": 10,
                         "dur": 0.001}),
            ("send", 2, {"dst": 0, "mtag": 2, "n": 1, "bytes": 10,
                         "dur": 0.001}),
            ("recv", 3, {"src": 0, "mtag": 4, "n": 0, "bytes": 5,
                         "wait": 0.016}),  # 4x slower: +2 buckets
            ("send", 4, {"dst": 0, "mtag": 5, "n": 0, "bytes": 1,
                         "dur": 0.001}),
        ])
        rows = diff_summaries([str(run_a)], [str(run_b)])
        by_key = {(r["dir"], r["tag"]): r for r in rows}
        grew = by_key[("send", 2)]
        assert (grew["msgs_a"], grew["msgs_b"]) == (1, 2)
        assert grew["delta_msgs"] == 1 and grew["delta_bytes"] == 10
        assert not grew["same"]
        slower = by_key[("recv", 4)]
        assert slower["delta_msgs"] == 0
        assert slower["delta_p50_bucket"] == 2
        assert not slower["same"]
        assert by_key[("send", 5)]["same"]

    def test_cli_summary_diff(self, tmp_path, capsys):
        run_a, run_b = tmp_path / "a", tmp_path / "b"
        run_a.mkdir(), run_b.mkdir()
        self._write_rank(run_a, 0, [
            ("send", 1, {"dst": 1, "mtag": 1, "n": 0, "bytes": 4,
                         "dur": 0.001}),
        ])
        self._write_rank(run_b, 0, [
            ("send", 1, {"dst": 1, "mtag": 1, "n": 0, "bytes": 4,
                         "dur": 0.001}),
            ("send", 2, {"dst": 1, "mtag": 1, "n": 1, "bytes": 4,
                         "dur": 0.001}),
        ])
        assert obs_main(["summary", "--diff", str(run_a), str(run_b)]) == 0
        out = capsys.readouterr().out
        assert "msgs 1 -> 2 (+1)" in out
        assert "1 stream(s) changed" in out
        # exactly two run dirs, both non-empty — anything else is usage
        assert obs_main(["summary", "--diff", str(run_a)]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert obs_main(
            ["summary", "--diff", str(run_a), str(empty)]
        ) == 2

    def test_cli_merge_and_empty_dir(self, tmp_path, capsys):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert obs_main(["merge", str(empty)]) == 2
        run = tmp_path / "run"
        run.mkdir()
        self._write_rank(run, 0, [
            ("send", 1, {"dst": 1, "mtag": 1, "n": 0, "bytes": 4,
                         "dur": 0.0}),
        ])
        assert obs_main(["merge", str(run)]) == 0
        out = json.load(open(run / "trace.json"))
        assert any(e["ph"] == "X" for e in out["traceEvents"])
        assert obs_main(["summary", str(run)]) == 0


def _obs_trainer(tmp_path, chaos=None, obs="explicit", **kw):
    import jax.numpy as jnp
    import optax

    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import AsyncPSTrainer

    return AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo="easgd",
        tau=4,
        transport="inproc",
        chaos=chaos,
        obs=ObsConfig(dir=str(tmp_path)) if obs == "explicit" else None,
        max_exchange_failures=5,
        fetch_timeout=1.0,
        fetch_retries=3,
        **kw,
    )


@pytest.fixture(scope="module")
def mnist():
    from mpit_tpu.data import load_mnist

    return load_mnist(synthetic_train=2048, synthetic_test=512)


class TestTrainerIntegration:
    def test_chaos_run_merges_with_cross_rank_traces_and_faults(
        self, tmp_path, mnist
    ):
        """The acceptance run: 2-client easgd under chaos, obs armed —
        the merged timeline must be Perfetto-loadable JSON with >= 1
        cross-rank trace and the injected faults as instant events."""
        from mpit_tpu.transport import ChaosConfig

        x_tr, y_tr, *_ = mnist
        chaos = ChaosConfig(
            seed=11, drop=0.05, corrupt=0.05, truncate=0.05,
            tags=(1, 2, 4),
        )
        trainer = _obs_trainer(tmp_path, chaos=chaos)
        _, stats = trainer.train(x_tr, y_tr, steps=24, batch_size=32)
        assert all(np.isfinite(l).all() for l in stats["losses"] if l)

        # telemetry folded into trainer stats, one summary per rank
        tele = stats["telemetry"]
        assert [t["rank"] for t in tele] == [0, 1, 2]
        assert any(
            v["msgs"] > 0 for t in tele for v in t["send"].values()
        )
        # chaos + obs together persist the fault log for the overlay
        faults_path = tmp_path / "faults.jsonl"
        assert faults_path.exists()

        journals = [
            str(tmp_path / f) for f in sorted(os.listdir(tmp_path))
            if f.startswith("obs_rank")
        ]
        assert len(journals) == 3
        trace = merge_to_chrome_trace(journals, str(faults_path))
        json.dumps(trace)
        evs = trace["traceEvents"]
        by_rank = trace_ids_by_rank(journals)
        cross = [
            t for t in set().union(*by_rank.values())
            if sum(1 for ids in by_rank.values() if t in ids) >= 2
        ]
        assert len(cross) >= 1, by_rank
        markers = [e for e in evs if e.get("cat") == "chaos"]
        assert len(markers) >= 1
        assert all(e["ph"] == "i" for e in markers)
        # exchange spans made it onto the timeline
        assert any(
            e["ph"] == "B" and e["name"] == "exchange" for e in evs
        )
        for j in journals:  # per-rank monotonic wall-clock
            ts = [r["t"] for r in read_journal(j) if "t" in r]
            assert ts == sorted(ts), j

    def test_env_knobs_activate_obs(self, tmp_path, mnist, monkeypatch):
        x_tr, y_tr, *_ = mnist
        monkeypatch.setenv("MPIT_OBS_DIR", str(tmp_path))
        trainer = _obs_trainer(tmp_path, obs=None)  # config from the env
        _, stats = trainer.train(x_tr, y_tr, steps=8, batch_size=32)
        assert "telemetry" in stats
        assert any(
            f.startswith("obs_rank") for f in os.listdir(tmp_path)
        )

    def test_obs_off_no_telemetry_key(self, tmp_path, mnist):
        x_tr, y_tr, *_ = mnist
        trainer = _obs_trainer(tmp_path, obs=None)
        _, stats = trainer.train(x_tr, y_tr, steps=8, batch_size=32)
        assert "telemetry" not in stats
        assert os.listdir(tmp_path) == []  # nothing written when unarmed


@pytest.mark.slow
def test_two_process_socket_trace(tmp_path):
    """The real thing: ptest_proc.py ranks as OS processes over TCP with
    MPIT_OBS_DIR armed via the launcher env; the merged journals must
    contain a cross-rank trace."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    env["MPIT_OBS_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "mpit_tpu.launch", "-n", "3",
         os.path.join(repo, "examples", "ptest_proc.py"),
         "--model", "mlp", "--steps", "8", "--train-size", "256",
         "--algo", "ps-easgd"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OBS tracing/telemetry active" in r.stderr
    by_rank = trace_ids_by_rank([str(tmp_path)])
    assert len(by_rank) == 3
    cross = [
        t for t in set().union(*by_rank.values())
        if sum(1 for ids in by_rank.values() if t in ids) >= 2
    ]
    assert len(cross) >= 1, {r: len(v) for r, v in by_rank.items()}
    trace = merge_to_chrome_trace([str(tmp_path)])
    json.dumps(trace)
    assert any(e["ph"] == "f" for e in trace["traceEvents"])
    # the roofline CLI over the same real socket run: one row per rank,
    # fractions summing to ~1.0 (ISSUE 6 acceptance)
    from mpit_tpu.obs import roofline

    report = roofline([str(tmp_path)])
    assert len(report["ranks"]) == 3
    for row in report["ranks"].values():
        assert abs(sum(row["phases"].values()) - 1.0) <= 0.02
    assert obs_main(["roofline", str(tmp_path)]) == 0
