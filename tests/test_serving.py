"""Continuous-batching serve loop: scheduling must be invisible.

The one contract worth pinning is EXACT parity — every request's result
equals its solo ``generate_fast`` call no matter how segments, batch
composition, admission, and retirement fell. Beyond parity: slots free
up on eos/budget and queued requests actually run in them.
"""

import jax
import jax.numpy as jnp
import pytest

from mpit_tpu.models import Server, generate_fast
from mpit_tpu.models.transformer import TransformerLM

V, T = 17, 64


def _model_params():
    model = TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


REQS = [  # (prompt, max_new) — deliberately unequal lengths and budgets
    ([3, 1, 4, 1, 5], 9),
    ([2], 14),
    ([7, 7, 7], 5),
    ([9, 8, 7, 6, 5, 4], 11),
    ([1, 2], 3),
]


def _solo(model, params, prompt, max_new, rng, **kw):
    return generate_fast(model, params, prompt, max_new, rng=rng, **kw)


def test_greedy_results_equal_solo_calls(topo8):
    model, params = _model_params()
    srv = Server(model, params, max_batch=3, segment=4)
    rngs = {}
    for prompt, mn in REQS:
        rid = srv.submit(prompt, mn)
        rngs[rid] = None
    got = srv.drain()
    assert len(got) == len(REQS)
    for rid, (prompt, mn) in enumerate(REQS):
        assert got[rid] == _solo(
            model, params, prompt, mn, jax.random.key(0)
        ), rid
    # capacity was respected and the queue really waited
    assert srv.segments_run >= 2


def test_sampled_results_equal_solo_calls(topo8):
    """The hard pin: per-request key streams survive re-batching, so
    SAMPLED serving equals solo calls token for token."""
    model, params = _model_params()
    kw = dict(temperature=0.9, top_k=7)
    srv = Server(model, params, max_batch=2, segment=3, **kw)
    rngs = {}
    for i, (prompt, mn) in enumerate(REQS):
        rng = jax.random.key(100 + i)
        rid = srv.submit(prompt, mn, rng=rng)
        rngs[rid] = rng
    got = srv.drain()
    for rid, (prompt, mn) in enumerate(REQS):
        want = _solo(model, params, prompt, mn, rngs[rid], **kw)
        assert got[rid] == want, rid


def test_mid_flight_admission_does_not_perturb_rows(topo8):
    """Submitting while others are mid-decode must not change anyone's
    tokens (admission re-prefills; row independence keeps results
    bit-stable)."""
    model, params = _model_params()
    kw = dict(temperature=0.7)
    srv = Server(model, params, max_batch=4, segment=3, **kw)
    r0 = srv.submit(*REQS[0], rng=jax.random.key(0))
    r1 = srv.submit(*REQS[1], rng=jax.random.key(1))
    srv.step()  # both mid-flight now
    r2 = srv.submit(*REQS[2], rng=jax.random.key(2))  # arrives late
    got = srv.drain()
    for rid, (prompt, mn), k in (
        (r0, REQS[0], 0), (r1, REQS[1], 1), (r2, REQS[2], 2)
    ):
        want = _solo(model, params, prompt, mn, jax.random.key(k), **kw)
        assert got[rid] == want, rid


def test_eos_retires_early_and_matches_solo(topo8):
    """eos ends a request at the shared truncation point and frees its
    slot for the queue."""
    model, params = _model_params()
    # find where the greedy continuation goes, then declare its second
    # generated token to be eos — forcing a mid-stream retirement
    probe = generate_fast(model, params, REQS[0][0], 8)
    eos = probe[len(REQS[0][0]) + 1]
    srv = Server(model, params, max_batch=1, segment=4, eos_id=eos)
    a = srv.submit(REQS[0][0], 8)
    b = srv.submit([t for t in REQS[3][0] if t != eos], 6)
    got = srv.drain()
    want_a = generate_fast(
        model, params, REQS[0][0], 8, eos_id=eos,
        rng=jax.random.key(0),
    )
    assert got[a] == want_a
    assert got[a][-1] == eos and len(got[a]) <= len(probe)
    want_b = generate_fast(
        model, params, [t for t in REQS[3][0] if t != eos], 6,
        eos_id=eos, rng=jax.random.key(0),
    )
    assert got[b] == want_b


def _count_prefills(monkeypatch):
    from mpit_tpu.models import serving

    calls = []
    real = serving._prefill_rows

    def counting(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(serving, "_prefill_rows", counting)
    return calls


def test_admission_never_reprefills_inflight_rows(topo8, monkeypatch):
    """The resident cache makes admission O(the newcomers' prompts):
    each request is prefilled exactly once over its whole life, no
    matter how arrivals interleave with in-flight decoding."""
    calls = _count_prefills(monkeypatch)
    model, params = _model_params()
    srv = Server(model, params, max_batch=2, segment=3)
    srv.submit(*REQS[0])  # p_len 5 -> bucket 8
    srv.submit(*REQS[1])  # p_len 1 -> bucket 1
    srv.step()
    srv.submit(*REQS[2])  # p_len 3 -> bucket 4, arrives mid-flight
    srv.submit(*REQS[3])  # p_len 6 -> bucket 8
    srv.drain()
    # four requests in four distinct (round, bucket) admission groups:
    # four prefill calls — never one per segment
    assert len(calls) == 4


def test_burst_admission_is_one_kernel_call(topo8, monkeypatch):
    """K same-bucket arrivals admitted at one scheduling boundary cost
    ONE prefill kernel call (the per-row clocks batch the group), and
    every result still equals its solo call."""
    calls = _count_prefills(monkeypatch)
    model, params = _model_params()
    kw = dict(temperature=0.8, top_k=5)
    burst = [([3, 1, 4, 1], 6), ([2, 7, 1, 8], 5), ([9, 9], 4),
             ([5, 3, 5], 7)]
    srv = Server(model, params, max_batch=4, segment=4, **kw)
    rngs = {}
    for i, (prompt, mn) in enumerate(burst):
        rng = jax.random.key(40 + i)
        rngs[srv.submit(prompt, mn, rng=rng)] = rng
    srv.step()
    # buckets: 4,4,2,4 -> two groups (the 3 bucket-4 rows, 1 bucket-2)
    assert len(calls) == 2
    got = srv.drain()
    for rid, (prompt, mn) in enumerate(burst):
        assert got[rid] == _solo(model, params, prompt, mn, rngs[rid],
                                 **kw), rid


def test_validation(topo8):
    model, params = _model_params()
    srv = Server(model, params)
    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit([1], 0)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(list(range(10)), T)
    with pytest.raises(ValueError, match="vocab_size"):
        srv.submit([999], 2)
    with pytest.raises(ValueError, match="max_batch"):
        Server(model, params, max_batch=0)
    with pytest.raises(ValueError, match="segment"):
        Server(model, params, segment=0)


def test_segment_failure_poisons_server(topo8, monkeypatch):
    """A failure inside a donated-buffer kernel must not leave the
    server silently unusable: the first failure propagates, and every
    later call reports the poisoning clearly instead of an opaque
    'array has been deleted'."""
    from mpit_tpu.models import serving

    model, params = _model_params()
    srv = Server(model, params, max_batch=1, segment=4)
    a = srv.submit(REQS[4][0], REQS[4][1])  # small budget: finishes fast
    b = srv.submit(*REQS[0])
    while a not in srv._results:
        srv.step()  # request a completes and retires; b is in flight

    def boom(*a, **k):
        raise RuntimeError("simulated mid-segment failure")

    monkeypatch.setattr(serving, "_serve_segment", boom)
    with pytest.raises(RuntimeError, match="simulated"):
        srv.step()
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="poisoned"):
        srv.step()
    with pytest.raises(RuntimeError, match="poisoned"):
        srv.submit(*REQS[1])
    with pytest.raises(RuntimeError, match="poisoned"):
        srv.drain()  # even though nothing LOOKS pending
    # completed work survives the poisoning: a finished BEFORE the
    # failure and its tokens are host-side ints
    done = srv.results()
    assert done[a] == _solo(
        model, params, REQS[4][0], REQS[4][1], jax.random.key(0)
    )
    assert b not in done  # in-flight work is honestly lost


def test_per_request_sampling_rules_equal_solo_calls(topo8):
    """One Server, heterogeneous rules: each request's temperature /
    top_p override rides a traced (NB,) vector through the SAME
    compiled segment program, and every row stays bit-equal to its
    solo call at its own rule."""
    from mpit_tpu.models import serving

    model, params = _model_params()
    srv = Server(model, params, max_batch=2, segment=3,
                 temperature=0.9, top_p=0.8)
    rules = [dict(temperature=0.5, top_p=0.95), dict(temperature=1.3),
             dict(top_p=0.6), dict()]
    want = {}
    for i, ((prompt, mn), rule) in enumerate(zip(REQS, rules)):
        rng = jax.random.key(200 + i)
        rid = srv.submit(prompt, mn, rng=rng, **rule)
        want[rid] = _solo(
            model, params, prompt, mn, rng,
            temperature=rule.get("temperature", 0.9),
            top_p=rule.get("top_p", 0.8),
        )
    n0 = serving._serve_segment._cache_size()
    got = srv.drain()
    for rid in want:
        assert got[rid] == want[rid], rid
    # mixed rules never forked the program (one (NB,) vector arg)
    assert serving._serve_segment._cache_size() == n0 + 1


def test_per_request_rule_validation(topo8):
    model, params = _model_params()
    greedy_srv = Server(model, params)
    with pytest.raises(ValueError, match="server-level mode"):
        greedy_srv.submit([1], 2, temperature=0.7)
    sampling_srv = Server(model, params, temperature=0.8)
    with pytest.raises(ValueError, match="must be > 0"):
        sampling_srv.submit([1], 2, temperature=0.0)
    with pytest.raises(ValueError, match="nucleus"):
        sampling_srv.submit([1], 2, top_p=0.5)
    with pytest.raises(ValueError, match="top_p"):
        Server(model, params, temperature=0.8, top_p=0.9) \
            .submit([1], 2, top_p=1.5)


def test_cancel(topo8):
    """Cancelling drops queued requests before they cost a prefill and
    frees in-flight slots; finished/unknown ids return False and
    survivors stay solo-equal."""
    model, params = _model_params()
    srv = Server(model, params, max_batch=1, segment=4)
    a = srv.submit(*REQS[0])
    b = srv.submit(*REQS[1])   # waits behind a (one slot)
    c = srv.submit(*REQS[2])
    assert srv.cancel(b)       # cancelled while queued
    srv.step()                 # a mid-flight now
    assert srv.cancel(a)       # cancelled mid-flight, slot freed
    got = srv.drain()
    assert set(got) == {c}
    assert got[c] == _solo(
        model, params, *REQS[2], jax.random.key(0)
    )
    assert not srv.cancel(c)   # already finished
    assert not srv.cancel(999)  # unknown
    assert srv.pending == 0


def test_prefix_cache_results_equal_solo_calls(topo8, monkeypatch):
    """Shared-prefix serving: every request equals the solo call on
    prefix + prompt; the prefix prefills exactly ONCE (template), and
    each admission prefills only its SUFFIX bucket."""
    from mpit_tpu.models import serving

    model, params = _model_params()
    prefix = [5, 4, 3, 2, 1, 2, 3, 4, 5, 6, 7, 8]  # 12 tokens
    pfx_calls, buckets = [], []
    real_pfx, real_rows = serving._prefill_prefix, serving._prefill_rows

    def count_pfx(*a, **k):
        pfx_calls.append(1)
        return real_pfx(*a, **k)

    def spy_rows(model_, pre_bucket, *a, **k):
        buckets.append(pre_bucket)
        return real_rows(model_, pre_bucket, *a, **k)

    monkeypatch.setattr(serving, "_prefill_prefix", count_pfx)
    monkeypatch.setattr(serving, "_prefill_rows", spy_rows)
    kw = dict(temperature=0.8, top_k=5)
    srv = Server(model, params, max_batch=2, segment=4, prefix=prefix,
                 **kw)
    rngs = {}
    for i, (prompt, mn) in enumerate(REQS[:4]):
        rng = jax.random.key(300 + i)
        rngs[srv.submit(prompt, mn, rng=rng)] = (prompt, mn, rng)
    got = srv.drain()
    for rid, (prompt, mn, rng) in rngs.items():
        want = _solo(model, params, prefix + prompt, mn, rng, **kw)
        assert got[rid] == want, rid
    assert len(pfx_calls) == 1  # the prefix prefilled once, ever
    # admission paid suffix-sized buckets (max suffix here is 6 -> 8),
    # never the prefix+prompt bucket (>= 16)
    assert buckets and max(buckets) <= 8


def test_long_prefix_near_max_len(topo8):
    """The suffix bucket is capped at max_len - prefix_len: a long
    prefix plus a prompt whose uncapped bucket would overhang the cache
    (prefix 36 + bucket(17)=32 > max_len 64 — the append would clamp
    into the prefix rows) must still decode exactly."""
    model, params = _model_params()  # max_len = 64
    prefix = [(i * 7 + 3) % V for i in range(36)]
    prompt = [(i * 5 + 1) % V for i in range(17)]  # bucket(17)=32 > 64-36
    srv = Server(model, params, max_batch=2, segment=4, prefix=prefix)
    rid = srv.submit(prompt, 8)
    got = srv.drain()
    assert got[rid] == _solo(
        model, params, prefix + prompt, 8, jax.random.key(0)
    )


def test_prefix_validation(topo8):
    model, params = _model_params()
    srv = Server(model, params, prefix=[1, 2, 3])
    with pytest.raises(ValueError, match="prefix"):
        srv.submit(list(range(10)), T - 10)  # prefix pushes past max_len
    with pytest.raises(ValueError, match="vocab_size"):
        Server(model, params, prefix=[999])
    # empty prefix means no prefix
    srv2 = Server(model, params, prefix=[])
    a = srv2.submit([1, 2], 3)
    assert srv2.drain()[a] == _solo(
        model, params, [1, 2], 3, jax.random.key(0)
    )


def _draft_model_params():
    dft = TransformerLM(
        vocab_size=V, num_layers=1, d_model=16, num_heads=2, max_len=T,
        compute_dtype=jnp.float32,
    )
    dp = dft.init(
        jax.random.key(11), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return dft, dp


def test_spec_server_results_equal_solo_calls(topo8):
    """Speculative continuous batching: greedy results bit-equal to the
    solo generate_fast call under mixed lengths, interleaved arrivals,
    and per-row acceptance rates — an (independently random) draft can
    only change speed, never tokens."""
    model, params = _model_params()
    dft, dp = _draft_model_params()
    srv = Server(model, params, max_batch=2, segment=4,
                 draft_model=dft, draft_params=dp, spec_k=3,
                 spec_rounds=2)
    rids = {}
    for prompt, mn in REQS[:3]:
        rids[srv.submit(prompt, mn)] = (prompt, mn)
    srv.step()
    rids[srv.submit(*REQS[3])] = REQS[3]  # arrives mid-flight
    got = srv.drain()
    for rid, (prompt, mn) in rids.items():
        assert got[rid] == _solo(
            model, params, prompt, mn, jax.random.key(0)
        ), rid


def test_spec_server_perfect_draft_and_eos(topo8):
    """Draft == target accepts everything; eos retires mid-segment at
    the shared truncation point."""
    model, params = _model_params()
    probe = generate_fast(model, params, REQS[0][0], 8)
    eos = probe[len(REQS[0][0]) + 1]
    srv = Server(model, params, max_batch=1, draft_model=model,
                 draft_params=params, spec_k=4, eos_id=eos)
    a = srv.submit(REQS[0][0], 8)
    b = srv.submit([t for t in REQS[3][0] if t != eos], 6)
    got = srv.drain()
    assert got[a] == generate_fast(
        model, params, REQS[0][0], 8, eos_id=eos, rng=jax.random.key(0)
    )
    assert got[b] == generate_fast(
        model, params, [t for t in REQS[3][0] if t != eos], 6,
        eos_id=eos, rng=jax.random.key(0),
    )


def test_spec_server_near_frontier(topo8):
    """A request ending right at the max_len - spec_k boundary: the
    per-boundary rounds cap keeps the chunk inside the cache and the
    result exact."""
    model, params = _model_params()  # max_len 64
    dft, dp = _draft_model_params()
    srv = Server(model, params, max_batch=2, draft_model=dft,
                 draft_params=dp, spec_k=4, spec_rounds=4)
    prompt = [(i * 3 + 1) % V for i in range(40)]
    mn = T - 40 - 4  # exactly the headroom limit
    rid = srv.submit(prompt, mn)
    got = srv.drain()
    assert got[rid] == _solo(model, params, prompt, mn, jax.random.key(0))


def test_spec_server_validation(topo8):
    model, params = _model_params()
    dft, dp = _draft_model_params()
    with pytest.raises(ValueError, match="greedy"):
        Server(model, params, temperature=0.5, draft_model=dft,
               draft_params=dp)
    with pytest.raises(ValueError, match="prefix"):
        Server(model, params, prefix=[1, 2], draft_model=dft,
               draft_params=dp)
    srv = Server(model, params, draft_model=dft, draft_params=dp,
                 spec_k=4)
    with pytest.raises(ValueError, match="headroom"):
        srv.submit(list(range(10)), T - 10 - 3)  # k=4 > 3 slots left
    with pytest.raises(ValueError, match="spec_k"):
        Server(model, params, draft_model=dft, draft_params=dp,
               spec_k=0)


def test_segment_caps_at_remaining_budget(topo8, monkeypatch):
    """A huge segment setting must not burn wasted ticks when every
    occupied row needs only a few more tokens: the segment caps at
    bucket(max remaining budget) — and results stay solo-equal."""
    from mpit_tpu.models import serving

    segs = []
    real = serving._serve_segment

    def recording(model, seg, *a, **k):
        segs.append(seg)
        return real(model, seg, *a, **k)

    monkeypatch.setattr(serving, "_serve_segment", recording)
    model, params = _model_params()
    srv = Server(model, params, max_batch=2, segment=32)
    a = srv.submit([3, 1, 4], 3)   # needs 2 ticks after admission
    b = srv.submit([2, 7], 5)      # needs 4
    got = srv.drain()
    assert segs and max(segs) <= 4, segs  # never a 32-tick segment
    assert got[a] == _solo(model, params, [3, 1, 4], 3, jax.random.key(0))
    assert got[b] == _solo(model, params, [2, 7], 5, jax.random.key(0))


def _schedule_ops(submit_extras):
    """Op-sequence strategy for the scheduling sweeps: submit tuples
    carry (prompt_len, budget, *extras), plus step and cancel ops."""
    from hypothesis import strategies as st

    return st.lists(
        st.one_of(
            st.tuples(st.just("submit"), st.integers(1, 7),
                      st.integers(1, 8), *submit_extras),
            st.tuples(st.just("step")),
            st.tuples(st.just("cancel"), st.integers(0, 9)),
        ),
        min_size=3, max_size=10,
    )


def _replay_and_check(srv, schedule, submit_fn, solo_cache, solo_fn):
    """The ONE schedule-replay contract both sweeps share: run the ops,
    drain, then assert cancelled requests vanished and every survivor
    equals its cached solo expectation."""
    live, cancelled = {}, set()
    for op in schedule:
        if op[0] == "submit":
            rid, key = submit_fn(srv, *op[1:])
            live[rid] = key
        elif op[0] == "step":
            srv.step()
        elif srv.cancel(op[1]):
            cancelled.add(op[1])
    got = srv.drain()
    for rid, key in live.items():
        if rid in cancelled:
            assert rid not in got
            continue
        assert rid in got  # drain completes everything uncancelled
        if key not in solo_cache:
            solo_cache[key] = solo_fn(key)
        assert got[rid] == solo_cache[key], (rid, key)


def _sched_prompt(plen):
    return [(plen * 13 + i * 7) % V for i in range(plen)]


@pytest.mark.slow
def test_random_scheduling_preserves_parity(topo8):
    """Hypothesis sweep over adversarial schedules: ANY interleaving of
    submit (varying lengths/budgets/rules), step, and cancel must leave
    every surviving request bit-equal to its solo call — the serving
    contract under schedules no hand-written test would pick."""
    from hypothesis import given, settings, strategies as st

    model, params = _model_params()
    kw = dict(temperature=0.8, top_k=7, top_p=0.9)
    solo_cache: dict = {}

    def submit(srv, plen, mn, temp):
        prompt = _sched_prompt(plen)
        rng = jax.random.key(plen * 100 + mn)
        over = {} if temp is None else {"temperature": temp}
        return srv.submit(prompt, mn, rng=rng, **over), \
            (tuple(prompt), mn, temp)

    def solo(key):
        prompt, mn, temp = key
        return _solo(
            model, params, list(prompt), mn,
            jax.random.key(len(prompt) * 100 + mn),
            **{**kw, **({} if temp is None else {"temperature": temp})},
        )

    @settings(max_examples=15, deadline=None)
    @given(_schedule_ops([st.sampled_from([None, 0.5, 1.2])]),
           st.integers(1, 3), st.integers(1, 4))
    def run(schedule, max_batch, segment):
        srv = Server(model, params, max_batch=max_batch,
                     segment=segment, **kw)
        _replay_and_check(srv, schedule, submit, solo_cache, solo)

    run()


@pytest.mark.slow
def test_random_scheduling_spec_server(topo8):
    """The same adversarial-schedule sweep against the SPECULATIVE
    server: per-row acceptance under random interleavings must never
    shift any request off its solo greedy decode."""
    from hypothesis import given, settings, strategies as st

    model, params = _model_params()
    dft, dp = _draft_model_params()
    solo_cache: dict = {}

    def submit(srv, plen, mn):
        prompt = _sched_prompt(plen)
        return srv.submit(prompt, mn), (tuple(prompt), mn)

    def solo(key):
        prompt, mn = key
        return _solo(model, params, list(prompt), mn, jax.random.key(0))

    @settings(max_examples=10, deadline=None)
    @given(_schedule_ops([]), st.integers(1, 3), st.integers(1, 3),
           st.integers(1, 2))
    def run(schedule, max_batch, spec_k, spec_rounds):
        srv = Server(model, params, max_batch=max_batch,
                     draft_model=dft, draft_params=dp,
                     spec_k=spec_k, spec_rounds=spec_rounds)
        _replay_and_check(srv, schedule, submit, solo_cache, solo)

    run()


class TestRNNServer:
    """The carry-decode family through the SAME scheduler: every result
    bit-equal to its solo generate_rnn call."""

    def _lstm(self):
        from mpit_tpu.models.lstm import LSTMLM

        model = LSTMLM(
            vocab_size=V, embed_dim=12, hidden=16, num_layers=2,
            compute_dtype=jnp.float32,
        )
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return model, params

    def _solo_rnn(self, model, params, prompt, mn, rng, **kw):
        from mpit_tpu.models import generate_rnn

        return generate_rnn(model, params, prompt, mn, rng=rng, **kw)

    def test_results_equal_solo_calls(self, topo8):
        from mpit_tpu.models import RNNServer

        model, params = self._lstm()
        kw = dict(temperature=0.9, top_k=5)
        srv = RNNServer(model, params, max_batch=2, segment=3, **kw)
        rngs = {}
        for i, (prompt, mn) in enumerate(REQS[:3]):
            rng = jax.random.key(400 + i)
            rngs[srv.submit(prompt, mn, rng=rng)] = (prompt, mn, rng)
        srv.step()
        rng = jax.random.key(404)
        rngs[srv.submit(*REQS[3], rng=rng)] = (*REQS[3], rng)
        got = srv.drain()
        for rid, (prompt, mn, rng) in rngs.items():
            want = self._solo_rnn(model, params, prompt, mn, rng, **kw)
            assert got[rid] == want, rid

    def test_prefix_and_long_generation(self, topo8):
        """Prefix template + a generation far past any transformer-style
        horizon (the RNN has none)."""
        from mpit_tpu.models import RNNServer

        model, params = self._lstm()
        prefix = [3, 1, 4, 1, 5, 9, 2, 6]
        srv = RNNServer(model, params, max_batch=2, segment=8,
                        prefix=prefix)
        a = srv.submit([7, 7], 150)  # way past T=64-style caps
        got = srv.drain()
        assert got[a] == self._solo_rnn(
            model, params, prefix + [7, 7], 150, jax.random.key(0)
        )

    def test_eos_and_cancel(self, topo8):
        from mpit_tpu.models import RNNServer, generate_rnn

        model, params = self._lstm()
        probe = generate_rnn(model, params, [3, 1, 4], 8)
        eos = probe[4]
        srv = RNNServer(model, params, max_batch=1, eos_id=eos)
        a = srv.submit([3, 1, 4], 8)
        b = srv.submit([2, 2], 5)
        assert srv.cancel(b)
        got = srv.drain()
        assert set(got) == {a}
        assert got[a] == generate_rnn(
            model, params, [3, 1, 4], 8, eos_id=eos, rng=jax.random.key(0)
        )

    def test_spec_rejected(self, topo8):
        from mpit_tpu.models import RNNServer

        model, params = self._lstm()
        dft, dp = _draft_model_params()
        with pytest.raises(ValueError, match="transformer-style"):
            RNNServer(model, params, draft_model=dft, draft_params=dp)

    def test_wrong_family_rejected_at_construction(self, topo8):
        """A KV-cache transformer into RNNServer fails loudly at init,
        not by poisoning the server at first admission."""
        from mpit_tpu.models import RNNServer

        t_model, t_params = _model_params()
        with pytest.raises(ValueError, match="carry-decode"):
            RNNServer(t_model, t_params)


def test_drain_empty_and_reuse(topo8):
    model, params = _model_params()
    srv = Server(model, params, max_batch=2, segment=4)
    assert srv.drain() == {}
    a = srv.submit([1, 2], 3)
    first = srv.drain()
    assert set(first) == {a}
    b = srv.submit([1, 2], 3)  # the server is reusable after a drain
    second = srv.drain()
    assert set(second) == {b}
    assert first[a] == second[b]  # same rng derivation per id? no —
    # ids differ, so streams differ; greedy makes them equal anyway
