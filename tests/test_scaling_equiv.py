"""Scaling-correctness: W-invariance of the training math on the CPU mesh.

The ≥90%-at-32-chips scaling-efficiency target (BASELINE.json:5) cannot be
*timed* on this rig (one real chip), but its correctness half can be tested:
with the same global batch and step budget, the collective path must deliver
the same converged quality at W=8 as at W=1 — sync-DP exactly (the pmean'd
gradient is the same global-batch mean), EASGD up to its W-dependent
dynamics (round-1 verdict item 6).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

import mpit_tpu
from mpit_tpu.data import load_mnist
from mpit_tpu.models import MLP
from mpit_tpu.parallel import DataParallelTrainer, EASGDTrainer


def _data():
    return load_mnist(synthetic_train=2048, synthetic_test=512)


def _global_batches(x, y, steps, gb, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        idx = rng.integers(0, len(x), gb)
        yield x[idx], y[idx]


def _train_sync(w, x, y, steps=150, gb=64):
    mpit_tpu.finalize()
    topo = mpit_tpu.init(num_workers=w)
    tr = DataParallelTrainer(
        MLP(compute_dtype=jnp.float32), optax.sgd(0.2), topo
    )
    state = tr.init_state(jax.random.key(0), x[: gb // w])
    for xb, yb in _global_batches(x, y, steps, gb):
        state, m = tr.step(state, xb, yb)
    return tr, state


class TestWInvariance:
    def test_sync_dp_w1_vs_w8_same_trajectory(self):
        """Sync-DP is exactly W-invariant: pmean over 8 shards of the global
        batch is the same mean gradient as W=1 — the final loss must agree
        to numerical tolerance, not just 'both converged'."""
        x, y, xt, yt = _data()
        tr1, s1 = _train_sync(1, x, y)
        tr8, s8 = _train_sync(8, x, y)
        acc1, loss1 = tr1.evaluate(s1, xt, yt)
        # evaluate on the W=8 trainer's own mesh
        acc8, loss8 = tr8.evaluate(s8, xt, yt)
        assert acc1 > 0.9 and acc8 > 0.9
        np.testing.assert_allclose(loss1, loss8, rtol=2e-3)
        assert abs(acc1 - acc8) < 0.02

    def test_easgd_w1_vs_w8_same_convergence(self):
        """EASGD's dynamics depend on W (W local models + elastic coupling),
        so equality is at the convergence level: same global batch and step
        budget must reach the same quality at W=1 and W=8."""
        x, y, xt, yt = _data()
        accs = {}
        for w in (1, 8):
            mpit_tpu.finalize()
            topo = mpit_tpu.init(num_workers=w)
            tr = EASGDTrainer(
                MLP(compute_dtype=jnp.float32),
                optax.sgd(0.05, momentum=0.9),
                topo,
                tau=4,
            )
            gb, rounds = 256, 40
            state = tr.init_state(jax.random.key(0), x[: max(gb // w, 1)])
            rng = np.random.default_rng(0)
            for _ in range(rounds):
                idx = rng.integers(0, len(x), (4, gb))
                state, m = tr.step(state, x[idx], y[idx])
            accs[w] = tr.evaluate(state, xt, yt)
        assert accs[1] > 0.9 and accs[8] > 0.9, accs
        assert abs(accs[1] - accs[8]) < 0.05, accs
