"""Host-async PS mode tests: the reference's '2 pclient + 1 pserver' MNIST
shape (BASELINE.json:7) with genuine thread-level asynchrony."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpit_tpu.data import load_mnist
from mpit_tpu.models import MLP
from mpit_tpu.parallel import AsyncPSTrainer
from mpit_tpu.parallel.pserver import partition_bounds


@pytest.fixture(scope="module")
def mnist():
    return load_mnist(synthetic_train=2048, synthetic_test=512)


def test_partition_bounds_cover_exactly():
    b = partition_bounds(103, 4)
    assert b[0][0] == 0 and b[-1][1] == 103
    assert all(b[i][1] == b[i + 1][0] for i in range(3))


def test_easgd_2client_1server_trains(mnist):
    x_tr, y_tr, x_te, y_te = mnist
    trainer = AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo="easgd",
        alpha=0.5,
        tau=4,
    )
    center, stats = trainer.train(x_tr, y_tr, steps=120, batch_size=64)
    acc = trainer.evaluate(center, x_te, y_te)
    assert acc > 0.9, f"async EASGD center failed to learn: acc={acc}, {stats['server_counts']}"
    counts = stats["server_counts"][0]
    # each client: one initial fetch + (steps/tau) push+fetch rounds
    assert counts["push_easgd"] == 2 * (120 // 4)
    assert counts["fetch"] == 2 * (120 // 4 + 1)


def test_downpour_sharded_servers_train(mnist):
    x_tr, y_tr, x_te, y_te = mnist
    trainer = AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05),
        num_clients=3,
        num_servers=2,
        algo="downpour",
        tau=4,
        server_lr=0.5,
    )
    center, stats = trainer.train(x_tr, y_tr, steps=160, batch_size=64)
    acc = trainer.evaluate(center, x_te, y_te)
    assert acc > 0.85, f"async Downpour failed: acc={acc}"
    # both servers saw every client's traffic
    for counts in stats["server_counts"]:
        assert counts["push_delta"] == 3 * (160 // 4)


def test_server_error_surfaces():
    """An unknown tag kills the server; train() must raise with the cause
    instead of burying it in a daemon thread (SURVEY.md §5 failure
    detection: the reference just hung)."""
    from mpit_tpu.parallel.pserver import PServer, spawn_server_thread
    from mpit_tpu.transport import Broker

    broker = Broker(2)
    tps = broker.transports()
    server = PServer(tps[0], np.zeros(4, np.float32), num_clients=1)
    thread = spawn_server_thread(server)
    tps[1].send(0, tag=999, payload=None)
    thread.join(timeout=5)
    assert not thread.is_alive()
    assert isinstance(server.error, ValueError)
    assert "unknown tag" in str(server.error)


def test_bad_algo_and_counts_raise():
    with pytest.raises(ValueError, match="unknown algo"):
        AsyncPSTrainer(MLP(), optax.sgd(0.1), algo="gossip")
    with pytest.raises(ValueError, match="at least one"):
        AsyncPSTrainer(MLP(), optax.sgd(0.1), num_clients=0)
    with pytest.raises(ValueError, match="transport"):
        AsyncPSTrainer(MLP(), optax.sgd(0.1), transport="carrier-pigeon")


def test_socket_transport_mode_trains(mnist):
    """transport="socket": the same thread-mode actors exchanging over
    real loopback TCP with the framed wire format — protocol counts
    unchanged, and the per-rank wire byte counters balance (every byte
    sent inside the world is received inside it)."""
    x_tr, y_tr, *_ = mnist
    trainer = AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo="easgd",
        alpha=0.5,
        tau=4,
        transport="socket",
    )
    center, stats = trainer.train(x_tr, y_tr, steps=40, batch_size=64)
    assert all(np.isfinite(l).all() for l in stats["losses"] if l)
    counts = stats["server_counts"][0]
    assert counts["push_easgd"] == 2 * (40 // 4)
    assert counts["fetch"] == 2 * (40 // 4 + 1)
    wb = stats["wire_bytes"]
    assert len(wb) == 3  # one counter set per rank
    assert sum(w["tx"] for w in wb) == sum(w["rx"] for w in wb) > 0
    assert all(w["rx_corrupt_dropped"] == 0 for w in wb)


def test_ps_easgd_matches_collective_trajectory(mnist):
    """The two EASGD runtimes implement the same math: a 1-client host-async
    PS run must reproduce the collective trainer's center trajectory when
    fed the identical batch schedule (paper update order — both moves
    against the pre-exchange center; round-1 verdict item 5)."""
    import jax

    import mpit_tpu
    from mpit_tpu.parallel import EASGDTrainer
    from mpit_tpu.utils.params import flatten_params

    x_tr, y_tr, *_ = mnist
    model = MLP(compute_dtype=jnp.float32)
    tau, alpha, steps, bs, seed = 4, 0.5, 24, 32, 0

    ps = AsyncPSTrainer(
        model, optax.sgd(0.05, momentum=0.9),
        num_clients=1, num_servers=1, algo="easgd", alpha=alpha, tau=tau,
    )
    center_ps, _ = ps.train(x_tr, y_tr, steps=steps, batch_size=bs, seed=seed)
    flat_ps = np.asarray(flatten_params(center_ps)[0])

    topo = mpit_tpu.init(num_workers=1)
    col = EASGDTrainer(
        model, optax.sgd(0.05, momentum=0.9), topo, tau=tau, alpha=alpha
    )
    state = col.init_state(jax.random.key(seed), x_tr[:2])
    # identical batch schedule: the PS client for index 0 samples with
    # default_rng(seed + 1000) over its (whole, W=1) shard
    rng = np.random.default_rng(seed + 1000)
    for _ in range(steps // tau):
        xs, ys = [], []
        for _ in range(tau):
            idx = rng.integers(0, len(x_tr), bs)
            xs.append(x_tr[idx])
            ys.append(y_tr[idx])
        state, _m = col.step(state, np.stack(xs), np.stack(ys))
    flat_col = np.asarray(flatten_params(col.center_params(state))[0])
    np.testing.assert_allclose(flat_ps, flat_col, rtol=2e-4, atol=2e-5)
