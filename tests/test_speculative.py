"""Speculative decoding: the exactness contract IS the test.

The one property that matters: for ANY draft model, the output equals
the target-only greedy decode token for token — a bad draft costs
speed, never correctness. Everything else (chunk scoring, cache
rewind, the bonus token) is internal and covered by that pin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpit_tpu.models import (
    generate_fast,
    generate_speculative,
    generate_speculative_batch,
)
from mpit_tpu.models.transformer import TransformerLM

V, T = 23, 128


def _target():
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )


def _draft(layers=1, d=16, heads=2):
    return TransformerLM(
        vocab_size=V, num_layers=layers, d_model=d, num_heads=heads,
        max_len=T, compute_dtype=jnp.float32,
    )


def _init(model, seed):
    return model.init(
        jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]


PROMPTS = [[3, 1, 4, 1, 5], [7], [2, 7, 1, 8, 2, 8, 1, 8]]


def test_exact_vs_target_greedy_any_draft(topo8):
    """A smaller independently-initialized draft (realistic) and every
    k: token-identical to the target-only greedy decode."""
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    for prompt in PROMPTS:
        want = generate_fast(tgt, tp, prompt, 11)
        for k in (1, 3, 4):
            got = generate_speculative(tgt, tp, dft, dp, prompt, 11, k=k)
            assert got == want, (prompt, k)


def test_exact_with_adversarial_draft(topo8):
    """Worst case — a garbage draft that agrees with the target on
    nothing still yields the exact target output (just one accepted
    token per chunk)."""
    tgt = _target()
    tp = _init(tgt, 0)
    dft = _draft()
    # random params, a different seed per leaf: maximally wrong draft
    dp = jax.tree.map(
        lambda a: jax.random.normal(
            jax.random.key(int(np.prod(a.shape)) % 97), a.shape, a.dtype
        ),
        _init(dft, 1),
    )
    want = generate_fast(tgt, tp, PROMPTS[0], 9)
    got = generate_speculative(tgt, tp, dft, dp, PROMPTS[0], 9, k=4)
    assert got == want


def test_perfect_draft_is_exact(topo8):
    """Draft == target: every proposal accepted (plus the bonus token);
    the result is still the pinned greedy decode."""
    tgt = _target()
    tp = _init(tgt, 0)
    for steps in (4, 12):
        want = generate_fast(tgt, tp, PROMPTS[0], steps)
        got = generate_speculative(tgt, tp, tgt, tp, PROMPTS[0], steps, k=3)
        assert got == want, steps


def test_batch_rows_equal_solo_calls(topo8):
    """Mixed-length batch, one compiled loop: every row equals its solo
    speculative call (hence the target-only greedy decode), no matter
    how the OTHER rows' acceptance rates desync the clocks — including
    the N=3 pad row."""
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    rows = generate_speculative_batch(
        tgt, tp, dft, dp, PROMPTS, 10, k=3
    )
    assert len(rows) == len(PROMPTS)
    for i, prompt in enumerate(PROMPTS):
        assert rows[i] == generate_fast(tgt, tp, prompt, 10), i
    assert generate_speculative_batch(tgt, tp, dft, dp, [], 5) == []


def test_batch_eos_per_row(topo8):
    """eos truncates each batch row at its own point, matching the solo
    eos calls."""
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    probe = generate_fast(tgt, tp, PROMPTS[0], 10)
    eos = probe[len(PROMPTS[0]) + 1]
    prompts = [PROMPTS[0], [t for t in PROMPTS[2] if t != eos]]
    rows = generate_speculative_batch(
        tgt, tp, dft, dp, prompts, 10, k=4, eos_id=eos
    )
    for i, q in enumerate(prompts):
        assert rows[i] == generate_fast(tgt, tp, q, 10, eos_id=eos), i


def test_stats_reflect_draft_quality(topo8):
    """Perfect draft: every chunk fully accepted (mean emitted k+1).
    The stats are the measured usefulness of the draft — the quantity
    the bench reports."""
    tgt = _target()
    tp = _init(tgt, 0)
    _, stats = generate_speculative(
        tgt, tp, tgt, tp, PROMPTS[0], 12, k=3, return_stats=True
    )
    assert stats["mean_emitted"] == 4.0  # k+1, every chunk
    assert stats["iterations"] >= 3
    dft = _draft()
    _, stats2 = generate_speculative(
        tgt, tp, dft, _init(dft, 7), PROMPTS[0], 12, k=3,
        return_stats=True,
    )
    assert 1.0 <= stats2["mean_emitted"] <= 4.0


def test_eos_truncation_matches(topo8):
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    probe = generate_fast(tgt, tp, PROMPTS[0], 10)
    eos = probe[len(PROMPTS[0]) + 2]  # force a mid-stream eos
    want = generate_fast(tgt, tp, PROMPTS[0], 10, eos_id=eos)
    got = generate_speculative(
        tgt, tp, dft, dp, PROMPTS[0], 10, k=3, eos_id=eos
    )
    assert got == want


def test_weights_dtype_matches_fast_path(topo8):
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    want = generate_fast(tgt, tp, PROMPTS[0], 8,
                         weights_dtype=jnp.bfloat16)
    got = generate_speculative(
        tgt, tp, dft, dp, PROMPTS[0], 8, k=3, weights_dtype=jnp.bfloat16
    )
    assert got == want


def test_validation(topo8):
    tgt = _target()
    tp = _init(tgt, 0)
    small_vocab = TransformerLM(
        vocab_size=V - 1, num_layers=1, d_model=16, num_heads=2, max_len=T,
        compute_dtype=jnp.float32,
    )
    sp = _init(small_vocab, 3)
    with pytest.raises(ValueError, match="vocab"):
        generate_speculative(tgt, tp, small_vocab, sp, [1], 4)
    dft = _draft()
    dp = _init(dft, 7)
    with pytest.raises(ValueError, match="k=0"):
        generate_speculative(tgt, tp, dft, dp, [1], 4, k=0)
    with pytest.raises(ValueError, match="headroom"):
        generate_speculative(tgt, tp, dft, dp, [1], T - 2, k=4)
    assert generate_speculative(tgt, tp, dft, dp, [1, 2], 0) == [1, 2]


def test_draft_with_smaller_max_len(topo8):
    """A draft whose max_len is below the target's: prompt buckets must
    fit the SMALLER cache (66 buckets to 128 under the target's cap —
    which would overflow a 96-slot draft cache) while results stay
    exact."""
    tgt = _target()  # max_len 128
    tp = _init(tgt, 0)
    dft = TransformerLM(
        vocab_size=V, num_layers=1, d_model=16, num_heads=2, max_len=96,
        compute_dtype=jnp.float32,
    )
    dp = _init(dft, 7)
    prompt = list(np.arange(66) % V)
    want = generate_fast(tgt, tp, prompt, 20)
    got = generate_speculative(tgt, tp, dft, dp, prompt, 20, k=4)
    assert got == want


def test_loop_gates_on_steps_not_bucket(topo8):
    """steps=5 buckets to gen_bucket=8, but the while_loop freezes rows
    at n >= steps: a never-agreeing draft must run at most ~steps
    verification chunks (k=1 emits >= 1 token per chunk), not bucket
    many — and the output still matches the target-only decode."""
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    prompt = PROMPTS[0]
    got, stats = generate_speculative(
        tgt, tp, dft, dp, prompt, 5, k=1, return_stats=True
    )
    assert got == generate_fast(tgt, tp, prompt, 5)
    # tok0 comes from the prefill; each chunk emits at least one token,
    # so even zero acceptances need only steps-1 = 4 chunks. Running to
    # the bucket would need up to 7.
    assert stats["iterations"] <= 4


def test_steps_below_bucket_rows_match_solo(topo8):
    """Batched rows under a steps < gen_bucket budget stay pinned to
    their solo calls (the freeze-at-steps path rides per-row clocks)."""
    tgt, dft = _target(), _draft()
    tp, dp = _init(tgt, 0), _init(dft, 7)
    rows = generate_speculative_batch(
        tgt, tp, dft, dp, PROMPTS, 5, k=3
    )
    for i, prompt in enumerate(PROMPTS):
        assert rows[i] == generate_speculative(
            tgt, tp, dft, dp, prompt, 5, k=3
        )
