"""Composed dp×tp×sp parallelism: factorization-invariance on 8 devices.

One jitted step composes data, tensor, and ring-attention sequence
parallelism. The math must not care how the 8 devices factor across the
three axes — every (dp, tp, sp) split must produce the same loss
trajectory and the same updated params, and the composed trainer must
match the dedicated 2-D seq trainer run on the same problem.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# integration tier — excluded from the smoke run (dp x tp x sp factorization sweeps)
pytestmark = pytest.mark.slow

import mpit_tpu
from mpit_tpu.models.transformer import TransformerLM
from mpit_tpu.parallel import ComposedParallelTrainer, SeqParallelTrainer

V, B, T = 29, 8, 32


def _model(seq_axis="sp"):
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=8, max_len=T,
        compute_dtype=jnp.float32, seq_axis=seq_axis,
    )


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, V, (B, T)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


def _run_composed(mesh_shape, steps=3, seq_impl="ring"):
    mpit_tpu.finalize()
    topo = mpit_tpu.init(
        axis_names=("dp", "tp", "sp"), mesh_shape=mesh_shape
    )
    tr = ComposedParallelTrainer(
        _model().clone(seq_impl=seq_impl),
        optax.sgd(0.1, momentum=0.9), topo, donate_state=False,
    )
    x, y = _data()
    state = tr.init_state(
        jax.random.key(0), x[:2, : T // mesh_shape[2]]
    )
    losses = []
    for _ in range(steps):
        state, m = tr.step(state, x, y)
        losses.append(float(m["loss"]))
    params = jax.tree.map(np.asarray, jax.device_get(state.params))
    ev = tr.evaluate(state, x, y)
    mpit_tpu.finalize()
    return losses, params, ev


class TestComposed:
    def test_factorizations_match(self):
        """(8,1,1), (2,2,2), (1,4,2), (2,1,4), (1,1,8) — one trajectory."""
        ref_losses, ref_params, ref_ev = _run_composed((8, 1, 1))
        for shape in ((2, 2, 2), (1, 4, 2), (2, 1, 4), (1, 1, 8)):
            losses, params, ev = _run_composed(shape)
            np.testing.assert_allclose(
                losses, ref_losses, rtol=2e-5, atol=2e-6,
                err_msg=f"mesh {shape}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=3e-4, atol=3e-4
                ),
                params, ref_params,
            )
            assert ev[0] == pytest.approx(ref_ev[0], abs=0.03)

    def test_ulysses_composes_too(self):
        """The sequence scheme is a model-level choice: the composed
        dp x tp x sp step with seq_impl='ulysses' (all_to_all inside the
        manual sp region, GSPMD tp outside) matches the ring trajectory."""
        ref_losses, ref_params, _ = _run_composed((2, 2, 2))
        losses, params, _ = _run_composed((2, 2, 2), seq_impl="ulysses")
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-5, atol=2e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-4
            ),
            params, ref_params,
        )

    def test_matches_dedicated_seq_trainer(self):
        """The composed step at tp=1 equals the 2-D dp×sp trainer."""
        composed_losses, composed_params, _ = _run_composed((2, 1, 4))
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        tr = SeqParallelTrainer(
            _model(), optax.sgd(0.1, momentum=0.9), topo,
            donate_state=False,
        )
        x, y = _data()
        state = tr.init_state(jax.random.key(0), x[:2, : T // 4])
        losses = []
        for _ in range(3):
            state, m = tr.step(state, x, y)
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(
            losses, composed_losses, rtol=2e-5, atol=2e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-4
            ),
            jax.tree.map(np.asarray, jax.device_get(state.params)),
            composed_params,
        )
        mpit_tpu.finalize()

    def test_weights_actually_sharded_on_tp(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(
            axis_names=("dp", "tp", "sp"), mesh_shape=(1, 4, 2)
        )
        tr = ComposedParallelTrainer(
            _model(), optax.sgd(0.1), topo, donate_state=False
        )
        x, _ = _data()
        state = tr.init_state(jax.random.key(0), x[:2, : T // 2])
        qkv = state.params["Block_0"]["Dense_0"]["kernel"]
        assert qkv.sharding.spec[-1] == "tp"
        down = state.params["Block_0"]["Dense_3"]["kernel"]
        assert down.sharding.spec[0] == "tp"
        mpit_tpu.finalize()

    def test_trains_to_low_loss(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(
            axis_names=("dp", "tp", "sp"), mesh_shape=(2, 2, 2)
        )
        tr = ComposedParallelTrainer(
            _model(), optax.sgd(0.3, momentum=0.9), topo,
            donate_state=False,
        )
        stream = np.arange(B * T * 2, dtype=np.int32) % V
        x = stream.reshape(-1, T)[:B]
        y = np.roll(x, -1, axis=1).astype(np.int32)
        state = tr.init_state(jax.random.key(1), x[:2, : T // 2])
        first = last = None
        for _ in range(40):
            state, m = tr.step(state, x, y)
            first = first if first is not None else float(m["loss"])
            last = float(m["loss"])
        assert last < first * 0.5, (first, last)
        mpit_tpu.finalize()

    def test_validation(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(
            axis_names=("dp", "tp", "sp"), mesh_shape=(2, 2, 2)
        )
        with pytest.raises(ValueError, match="seq_axis='sp'"):
            ComposedParallelTrainer(
                _model(seq_axis=None), optax.sgd(0.1), topo
            )
        moe = TransformerLM(
            vocab_size=V, max_len=T, seq_axis="sp", moe_experts=8
        )
        with pytest.raises(ValueError, match="MoEParallelTrainer"):
            ComposedParallelTrainer(moe, optax.sgd(0.1), topo)
        tr = ComposedParallelTrainer(
            _model(), optax.sgd(0.1), topo, donate_state=False
        )
        x, y = _data()
        with pytest.raises(ValueError, match="not divisible"):
            tr.step(None, x[:7], y[:7])
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        with pytest.raises(ValueError, match="dp', 'tp', 'sp"):
            ComposedParallelTrainer(_model(), optax.sgd(0.1), topo)
        mpit_tpu.finalize()
