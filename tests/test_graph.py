"""analysis/graph.py: the whole-program name-resolution index.

Everything here runs against ``tests/fixtures/graph_pkg`` — a package that
is parsed, never imported (half of it would NameError on import, which is
the point: the graph must work on code the linter cannot run).
"""

from __future__ import annotations

from pathlib import Path

import pytest

from mpit_tpu.analysis import lint
from mpit_tpu.analysis.graph import (
    MAX_DEPTH,
    ModuleGraph,
    module_name_for_rel,
)

GRAPH_PKG = Path(__file__).resolve().parent / "fixtures" / "graph_pkg"


@pytest.fixture(scope="module")
def graph():
    mods = [
        lint.load_module(ap, rel)
        for ap, rel in lint.collect_files([GRAPH_PKG])
    ]
    return ModuleGraph([m for m in mods if m is not None])


def _info(graph, name):
    info = graph.module(name)
    assert info is not None, sorted(graph.by_name)
    return info


# ------------------------------------------------------------- module names


@pytest.mark.parametrize(
    "rel, name",
    [
        ("mpit_tpu/parallel/pserver.py", "mpit_tpu.parallel.pserver"),
        ("graph_pkg/__init__.py", "graph_pkg"),
        ("graph_pkg/sub/__init__.py", "graph_pkg.sub"),
        ("solo.py", "solo"),
    ],
)
def test_module_name_for_rel(rel, name):
    assert module_name_for_rel(rel) == name


def test_graph_indexes_all_modules(graph):
    assert {
        "graph_pkg",
        "graph_pkg.consts",
        "graph_pkg.funcs",
        "graph_pkg.uses",
        "graph_pkg.starry",
        "graph_pkg.sub",
        "graph_pkg.sub.deep",
        "graph_pkg.sub.sibling",
    } <= set(graph.by_name)


# --------------------------------------------------------------- constants


@pytest.mark.parametrize(
    "module, dotted, value",
    [
        ("graph_pkg.consts", "BASE", 7),
        ("graph_pkg.consts", "DERIVED", 7),  # assign chain
        ("graph_pkg.consts", "NEG", -1),  # folded UnaryOp
        ("graph_pkg.consts", "SHIFTED", 8),  # folded BinOp over a name
        ("graph_pkg.consts", "MASK", 18),  # pure-literal arithmetic
        ("graph_pkg.consts", "WIRE", "obs1"),  # string concatenation
        ("graph_pkg.uses", "RENAMED", 7),  # from x import y as z
        ("graph_pkg.uses", "cc.BASE", 7),  # import x.y as z
        ("graph_pkg.uses", "consts.BASE", 7),  # from pkg import module
        ("graph_pkg.sub.deep", "UP", 7),  # from ..consts import
        ("graph_pkg.sub.deep", "NEAR", 21),  # from .sibling import
    ],
)
def test_resolve_constant(graph, module, dotted, value):
    assert graph.resolve_constant(_info(graph, module), dotted) == value


def test_star_import_refused(graph):
    """``starry.py`` star-imports consts: BASE *would* be in scope at
    runtime, but the graph must refuse to guess — while names the module
    binds itself still resolve."""
    starry = _info(graph, "graph_pkg.starry")
    assert "graph_pkg.consts" in starry.star_imports
    assert graph.resolve_constant(starry, "BASE") is None
    assert graph.resolve_constant(starry, "LOCAL") == 3


def test_assignment_cycle_terminates(graph):
    cyc = _info(graph, "graph_pkg.cyc")
    assert graph.resolve_constant(cyc, "A") is None
    assert graph.resolve_constant(cyc, "B") is None


def test_off_graph_names_resolve_to_none(graph):
    uses = _info(graph, "graph_pkg.uses")
    assert graph.resolve_constant(uses, "functools.reduce") is None
    assert graph.resolve_constant(uses, "nonexistent") is None


# --------------------------------------------------------------- callables


def test_resolve_callable_through_stacked_partials(graph):
    """uses.double = partial(rebound, 3); rebound = funcs.bound =
    partial(inner, 1, b=2) — the chain bottoms out at ``inner`` with TWO
    leading positionals consumed and ``b`` keyword-bound."""
    uses = _info(graph, "graph_pkg.uses")
    ci = graph.resolve_callable(uses, "double")
    assert ci is not None
    assert ci.fn.name == "inner"
    assert ci.module.name == "graph_pkg.funcs"
    assert ci.bound_pos == 2
    assert ci.bound_names == frozenset({"b"})
    assert ci.depth >= 3  # alias -> assign -> partial -> partial


def test_resolve_callable_through_passthrough_wrapper(graph):
    uses = _info(graph, "graph_pkg.uses")
    ci = graph.resolve_callable(uses, "forwarded")
    assert ci is not None
    assert ci.fn.name == "inner"
    assert ci.bound_pos == 0


def test_resolve_callable_alias_across_modules(graph):
    deep = _info(graph, "graph_pkg.sub.deep")
    ci = graph.resolve_callable(deep, "up_inner")
    assert ci is not None
    assert ci.fn.name == "inner"
    assert ci.module.name == "graph_pkg.funcs"


def test_max_depth_is_a_cycle_guard():
    # direct unit check: a synthetic 2-module alias cycle ends at MAX_DEPTH
    import ast as _ast

    class _Ctx:
        def __init__(self, rel, src):
            self.rel, self.tree = rel, _ast.parse(src)

    g = ModuleGraph(
        [
            _Ctx("a.py", "from b import x as x\n"),
            _Ctx("b.py", "from a import x as x\n"),
        ]
    )
    assert MAX_DEPTH >= 8
    assert g.resolve_constant(g.module("a"), "x") is None
