"""Pallas fused elastic-update kernel: numeric parity with the XLA path
(interpret mode on the CPU mesh; the same kernel runs natively on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpit_tpu.ops import elastic_update
from mpit_tpu.ops.elastic import BLOCK_ROWS, LANE


@pytest.mark.parametrize(
    "shape",
    [
        (7,),                       # far below one block, ragged
        (BLOCK_ROWS * LANE,),       # exactly one block
        (BLOCK_ROWS * LANE + 13,),  # one block + ragged tail
        (3, 50, 11),                # multi-rank
    ],
)
def test_kernel_matches_xla(shape):
    rng = np.random.default_rng(0)
    x = rng.normal(size=shape).astype(np.float32)
    c = rng.normal(size=shape).astype(np.float32)
    d = rng.normal(size=shape).astype(np.float32)
    alpha = 0.3
    ref_x, ref_c = elastic_update(x, c, d, alpha, use_pallas=False)
    out_x, out_c = elastic_update(x, c, d, alpha, use_pallas=True)
    assert out_x.shape == shape and out_c.shape == shape
    np.testing.assert_allclose(out_x, ref_x, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(out_c, ref_c, rtol=1e-6, atol=1e-6)


def test_easgd_round_pallas_path(topo8):
    """goptim.easgd_round(use_pallas=True) under shard_map on the CPU mesh:
    identical center and params to the plain path."""
    from jax.sharding import PartitionSpec as P

    from mpit_tpu import goptim

    w = topo8.num_workers
    rng = np.random.default_rng(1)
    params = {"a": rng.normal(size=(w, 40)).astype(np.float32),
              "b": rng.normal(size=(w, 3, 5)).astype(np.float32)}
    center = {"a": rng.normal(size=(40,)).astype(np.float32),
              "b": rng.normal(size=(3, 5)).astype(np.float32)}

    def mk(use_pallas):
        def f(p, c):
            p0 = jax.tree.map(lambda a: a[0], p)
            np_, nc = goptim.easgd_round(
                p0, c, 0.1, topo8.worker_axis, use_pallas=use_pallas
            )
            return jax.tree.map(lambda a: a[None], np_), nc

        return jax.jit(
            jax.shard_map(
                f, mesh=topo8.mesh,
                in_specs=(P(topo8.worker_axis), P()),
                out_specs=(P(topo8.worker_axis), P()),
                check_vma=False,
            )
        )

    px, pc = mk(False)(params, center)
    qx, qc = mk(True)(params, center)
    for a, b in zip(jax.tree.leaves((px, pc)), jax.tree.leaves((qx, qc))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_easgd_round_pallas_tuple_containers(topo8):
    """Pytrees whose CONTAINERS are tuples must round-trip intact through
    the pallas path (regression: an is_leaf=tuple unzip grabbed container
    elements instead of (new_x, new_c) pairs)."""
    from jax.sharding import PartitionSpec as P

    from mpit_tpu import goptim

    w = topo8.num_workers
    rng = np.random.default_rng(3)
    params = (rng.normal(size=(w, 4)).astype(np.float32),
              rng.normal(size=(w, 3)).astype(np.float32))
    center = (rng.normal(size=(4,)).astype(np.float32),
              rng.normal(size=(3,)).astype(np.float32))

    def mk(use_pallas):
        def f(p, c):
            p0 = jax.tree.map(lambda a: a[0], p)
            np_, nc = goptim.easgd_round(
                p0, c, 0.1, topo8.worker_axis, use_pallas=use_pallas
            )
            return jax.tree.map(lambda a: a[None], np_), nc

        return jax.jit(
            jax.shard_map(
                f, mesh=topo8.mesh,
                in_specs=(P(topo8.worker_axis), P()),
                out_specs=(P(topo8.worker_axis), P()),
                check_vma=False,
            )
        )

    px, pc = mk(False)(params, center)
    qx, qc = mk(True)(params, center)
    assert qx[1].shape == px[1].shape == (w, 3)
    for a, b in zip(jax.tree.leaves((px, pc)), jax.tree.leaves((qx, qc))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_easgd_trainer_with_pallas(topo8):
    """Full EASGDTrainer round with use_pallas=True trains and matches the
    plain trainer's loss trajectory."""
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import EASGDTrainer

    rng = np.random.default_rng(2)
    w, tau, b = topo8.num_workers, 2, 4
    x = rng.uniform(0, 1, (tau, w * b, 8, 8, 1)).astype(np.float32)
    y = rng.integers(0, 10, (tau, w * b)).astype(np.int32)

    losses = {}
    for flag in (False, True):
        tr = EASGDTrainer(
            MLP(hidden=(16,), compute_dtype=jnp.float32),
            optax.sgd(0.1), topo8, tau=tau, use_pallas=flag,
            donate_state=False,
        )
        st = tr.init_state(jax.random.key(0), x[0, :2])
        st, m = tr.step(st, x, y)
        st, m = tr.step(st, x, y)
        losses[flag] = float(m["loss"])
    assert np.isfinite(losses[True])
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
