"""mpit_tpu.analysis: linter rules, baseline discipline, CLI, runtime checker.

Three layers, mirroring the subsystem:

- the repo SELF-CHECK: linting ``mpit_tpu/`` must produce exactly the
  checked-in baseline (``analysis-baseline.json``) — a new finding anywhere
  in the package fails here before it fails in CI;
- seeded FIXTURES (``tests/fixtures/analysis/``): each file triggers
  exactly its one rule, pinning both directions (the rule fires on its
  target pattern, and fires on nothing else in the fixture);
- the RUNTIME checker: a seeded lock-order inversion and a seeded tag
  collision are detected, and clean transport traffic — including a
  multi-thread stress run — reports zero findings.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from mpit_tpu.analysis import findings as findings_mod
from mpit_tpu.analysis import lint, runtime
from mpit_tpu.analysis.findings import Finding
from mpit_tpu.transport import ANY_SOURCE, ANY_TAG, Broker

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"
BASELINE = REPO / lint.BASELINE_FILENAME


# ---------------------------------------------------------------- self-check


def test_repo_matches_baseline():
    """The package linted against the checked-in baseline is clean — the
    acceptance gate ``python -m mpit_tpu.analysis mpit_tpu/`` enforces."""
    findings = lint.run_lint([PKG])
    baseline = findings_mod.load_baseline(BASELINE)
    new = findings_mod.new_findings(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_baseline_has_no_new_rule_entries():
    """Satellite contract: the true positives MPT004/MPT007/MPT008 found
    in the repo were FIXED, not baselined — the baseline must carry zero
    fingerprints for them, ever. MPT012 joins the set: every live-metric
    publish in the package uses the registered M_* constants."""
    baseline = findings_mod.load_baseline(BASELINE)
    polluted = [
        fp
        for fp in baseline
        if fp.split("|")[0] in {"MPT004", "MPT007", "MPT008", "MPT012"}
    ]
    assert polluted == []


def test_baseline_is_not_stale():
    """Every baselined fingerprint still occurs — fixed violations must
    leave the baseline, or it masks a future regression of the same
    shape."""
    findings = lint.run_lint([PKG])
    from collections import Counter

    current = Counter(f.fingerprint for f in findings)
    baseline = findings_mod.load_baseline(BASELINE)
    stale = {
        fp: n for fp, n in baseline.items() if current.get(fp, 0) < n
    }
    assert not stale, f"baselined but no longer present: {sorted(stale)}"


# ------------------------------------------------------------------ fixtures


@pytest.mark.parametrize(
    "fixture, rule",
    [
        ("fixture_mpt001.py", "MPT001"),
        ("fixture_mpt002.py", "MPT002"),
        ("fixture_mpt003.py", "MPT003"),
        ("fixture_mpt004.py", "MPT004"),
        ("fixture_mpt005.py", "MPT005"),
        ("fixture_mpt006.py", "MPT006"),
        # cross-module rules: the fixture is a file (MPT007) or a whole
        # package (MPT008 roles, MPT004 wrapper chain) and must fire its
        # rule EXACTLY ONCE — the pairing/resolution around the one seeded
        # defect has to come out clean
        ("fixture_mpt007.py", "MPT007"),
        ("fixture_mpt007_frame.py", "MPT007"),
        ("fixture_mpt012.py", "MPT012"),
        ("fixture_mpt008", "MPT008"),
        ("fixture_mpt004_chain", "MPT004"),
        # model-checked rules: the whole miniature protocol pair is
        # correct except for the one seeded defect, and the checker has
        # to find the violating fault schedule (and nothing else)
        ("fixture_mpt009", "MPT009"),
        ("fixture_mpt011", "MPT011"),
        # concurrency rules: whole-program thread-root discovery + the
        # lockset walk over each seeded package (tests/test_threads.py
        # exercises the model itself; here each fixture pins the
        # fires-exactly-once contract like every other rule)
        ("fixture_mpt013", "MPT013"),
        ("fixture_mpt014", "MPT014"),
        ("fixture_mpt015", "MPT015"),
        # wire-schema rules: the payload-schema model over a role pair
        # (MPT016), a single pickle-fallback send (MPT017), and a
        # snapshot save/restore key diff (MPT018)
        ("fixture_mpt016", "MPT016"),
        ("fixture_mpt017.py", "MPT017"),
        ("fixture_mpt018.py", "MPT018"),
        # numerics rules: the precision-dataflow model over a seeded
        # codes-accumulation (MPT020), an unpaired lossy push (MPT021),
        # and a mode/scale provenance mismatch (MPT022)
        ("fixture_mpt020.py", "MPT020"),
        ("fixture_mpt021.py", "MPT021"),
        ("fixture_mpt022.py", "MPT022"),
    ],
)
def test_fixture_triggers_exactly_its_rule(fixture, rule):
    findings = lint.run_lint(
        [FIXTURES / fixture], lint.Config(hot_all=True)
    )
    assert [f.rule for f in findings] == [rule], [
        f.format() for f in findings
    ]


def test_fixtures_are_never_collected():
    """The seeded-bug files must stay parse-only: no test_ prefix, and
    nothing imports them (they contain deliberate defects)."""
    for py in FIXTURES.rglob("*.py"):
        top = py.relative_to(FIXTURES).parts[0]
        assert top.startswith("fixture_")


def test_mpt004_chain_reports_wrapper_depth():
    findings = lint.run_lint([FIXTURES / "fixture_mpt004_chain"])
    assert len(findings) == 1
    assert "wrapper chain" in findings[0].message
    assert findings[0].path.endswith("top.py")


def test_mpt008_fixture_flags_the_orphan_send_only():
    findings = lint.run_lint([FIXTURES / "fixture_mpt008"])
    assert len(findings) == 1
    f = findings[0]
    assert "TAG_ORPHAN" in f.message
    assert f.path.endswith("client.py")
    assert f.symbol == "leak"


# --------------------------------------------------------- rule specifics


def _lint_source(tmp_path, source, config=None):
    f = tmp_path / "mod.py"
    f.write_text(source)
    return lint.run_lint([f], config or lint.Config(hot_all=True))


def test_inline_ignore_suppresses(tmp_path):
    findings = _lint_source(
        tmp_path,
        "def f(xs):\n"
        "    for x in xs:\n"
        "        x.item()  # mpit-analysis: ignore[MPT005]\n",
    )
    assert findings == []


def test_inline_ignore_is_rule_scoped(tmp_path):
    findings = _lint_source(
        tmp_path,
        "def f(xs):\n"
        "    for x in xs:\n"
        "        x.item()  # mpit-analysis: ignore[MPT001]\n",
    )
    assert [f.rule for f in findings] == ["MPT005"]


def test_host_sync_barrier_marker(tmp_path):
    """A def carrying the marker is exempt (body and call sites), the
    utils/profiling.force_completion contract."""
    findings = _lint_source(
        tmp_path,
        "def sync(x):  # mpit-analysis: host-sync-barrier\n"
        "    return float(x)\n"
        "def f(xs):\n"
        "    for x in xs:\n"
        "        sync(x)\n",
    )
    assert findings == []


def test_bound_axis_not_flagged(tmp_path):
    """A literal axis the module itself binds (shard_map / P spec) is
    fine — only the copied-out-of-context collective fires MPT001."""
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def step(x):\n"
        "    return lax.psum(x, 'dp')\n"
        "f = jax.shard_map(step, mesh=None, in_specs=P('dp'),"
        " out_specs=P())\n",
    )
    assert findings == []


def test_jit_static_argnames_drift(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "@jax.jit(static_argnames=('gone',))\n"
        "def f(model, batch):\n"
        "    return batch\n",
    )
    assert [f.rule for f in findings] == ["MPT004"]


def test_jit_consistent_statics_clean(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, static_argnums=(0,),"
        " static_argnames=('batch',))\n"
        "def f(model, batch):\n"
        "    return batch\n",
    )
    assert findings == []


def test_mpt004_partial_chain_shifts_positional_frame(tmp_path):
    """partial(base, None) consumes base's first parameter, so index 1 of
    the jitted callable is past the effective signature."""
    findings = _lint_source(
        tmp_path,
        "import functools\n"
        "import jax\n"
        "def base(model, batch):\n"
        "    return batch\n"
        "g = functools.partial(base, None)\n"
        "h = jax.jit(g, static_argnums=(1,))\n",
    )
    assert [f.rule for f in findings] == ["MPT004"]
    assert "wrapper chain" in findings[0].message


def test_mpt004_partial_chain_in_range_clean(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import functools\n"
        "import jax\n"
        "def base(model, batch):\n"
        "    return batch\n"
        "g = functools.partial(base, None)\n"
        "h = jax.jit(g, static_argnums=(0,))\n",
    )
    assert findings == []


def test_mpt004_bare_decorator_partial_factory(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import functools\n"
        "import jax\n"
        "jit_static = functools.partial(jax.jit,"
        " static_argnames=('gone',))\n"
        "@jit_static\n"
        "def f(model, batch):\n"
        "    return batch\n",
    )
    assert [f.rule for f in findings] == ["MPT004"]


def test_mpt004_bare_decorator_def_factory(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "def make_jit(fn):\n"
        "    return jax.jit(fn, static_argnums=(3,))\n"
        "@make_jit\n"
        "def f(a, b):\n"
        "    return a\n",
    )
    assert [f.rule for f in findings] == ["MPT004"]


def test_mpt004_called_decorator_factory_not_guessed(tmp_path):
    """``@make(x)`` binds x (not the decorated def) to the factory's first
    parameter — its jit kwargs must NOT be checked against f."""
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "def make(fn):\n"
        "    return jax.jit(fn, static_argnums=(3,))\n"
        "@make('donate')\n"
        "def f(a, b):\n"
        "    return a\n",
    )
    assert findings == []


# ------------------------------------------------------------ MPT007 (wire)

_WIRE = "# mpit-analysis: wire-boundary\nimport pickle\n"


def test_mpt007_drifted_literal(tmp_path):
    findings = _lint_source(
        tmp_path, _WIRE + "def f(x):\n    return pickle.dumps(x, 4)\n"
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "drift" in findings[0].message


def test_mpt007_missing_protocol(tmp_path):
    findings = _lint_source(
        tmp_path, _WIRE + "def f(x):\n    return pickle.dumps(x)\n"
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "without protocol=" in findings[0].message


def test_mpt007_matching_literal_still_flagged(tmp_path):
    """protocol=5 equals the canonical value TODAY, but a bump of the
    constant would silently strand it — the named constant is required."""
    findings = _lint_source(
        tmp_path,
        _WIRE + "def f(x):\n    return pickle.dumps(x, protocol=5)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "hard-codes" in findings[0].message
    assert "use WIRE_PICKLE_PROTOCOL itself" in findings[0].message


def test_mpt007_interpreter_dependent(tmp_path):
    for spelling in ("-1", "pickle.HIGHEST_PROTOCOL"):
        findings = _lint_source(
            tmp_path,
            _WIRE
            + f"def f(x):\n    return pickle.dumps(x, protocol={spelling})\n",
        )
        assert [f.rule for f in findings] == ["MPT007"], spelling
        assert "interpreter-dependent" in findings[0].message


def test_mpt007_named_constant_pin_clean(tmp_path):
    findings = _lint_source(
        tmp_path,
        _WIRE
        + "WIRE_PICKLE_PROTOCOL = 5\n"
        "def f(x):\n"
        "    return pickle.dumps(x, protocol=WIRE_PICKLE_PROTOCOL)\n",
    )
    assert findings == []


def test_mpt007_wrong_valued_name_is_drift(tmp_path):
    findings = _lint_source(
        tmp_path,
        _WIRE
        + "MY_PROTO = 3\n"
        "def f(x):\n"
        "    return pickle.dumps(x, protocol=MY_PROTO)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "resolves to 3" in findings[0].message


def test_mpt007_loads_and_unmarked_modules_out_of_scope(tmp_path):
    # loads: the protocol id travels in the stream — nothing to pin
    findings = _lint_source(
        tmp_path, _WIRE + "def f(b):\n    return pickle.loads(b)\n"
    )
    assert findings == []
    # no marker, no transport/ path component: not a wire boundary
    findings = _lint_source(
        tmp_path,
        "import pickle\ndef f(x):\n    return pickle.dumps(x, 4)\n",
    )
    assert findings == []


def test_mpt007_config_override(tmp_path):
    """An overridden canonical value re-anchors the whole rule: the name
    pinned to the override is clean, and a dumps that matches the
    DEFAULT contract instead is now the drift."""
    cfg = lint.Config(hot_all=True, wire_pickle_protocol=4)
    findings = _lint_source(
        tmp_path,
        _WIRE
        + "WIRE_PICKLE_PROTOCOL = 4\n"
        "def f(x):\n"
        "    return pickle.dumps(x, protocol=WIRE_PICKLE_PROTOCOL)\n",
        cfg,
    )
    assert findings == []
    findings = _lint_source(
        tmp_path,
        _WIRE + "def f(x):\n    return pickle.dumps(x, protocol=5)\n",
        cfg,
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "drift" in findings[0].message


# ------------------------------------------- MPT007 (binary frame version)

_FRAMED = (
    "# mpit-analysis: wire-boundary\n"
    "from mpit_tpu.transport import wire\n"
)


def test_mpt007_frame_missing_version(tmp_path):
    findings = _lint_source(
        tmp_path,
        _FRAMED + "def f(x):\n    return wire.encode_frame(0, 2, x)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "without version=" in findings[0].message


def test_mpt007_frame_matching_literal_still_flagged(tmp_path):
    """version=1 equals WIRE_FORMAT_VERSION today; the named constant is
    still required — the same stranding argument as the pickle side."""
    findings = _lint_source(
        tmp_path,
        _FRAMED
        + "def f(x):\n    return wire.encode_frame(0, 2, x, version=1)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "hard-codes" in findings[0].message
    assert "use WIRE_FORMAT_VERSION itself" in findings[0].message


def test_mpt007_frame_drifted_literal(tmp_path):
    findings = _lint_source(
        tmp_path,
        _FRAMED
        + "def f(x):\n    return wire.encode_frame(0, 2, x, version=9)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "drift" in findings[0].message


def test_mpt007_frame_named_constant_pin_clean(tmp_path):
    """Every import spelling of the canonical pin comes out clean — the
    exact shapes the transport package uses."""
    for src in (
        _FRAMED
        + "WIRE_FORMAT_VERSION = 1\n"
        "def f(x):\n"
        "    return wire.encode_frame(0, 2, x, "
        "version=WIRE_FORMAT_VERSION)\n",
        _FRAMED
        + "def f(x):\n"
        "    return wire.encode_frame(0, 2, x, "
        "version=wire.WIRE_FORMAT_VERSION)\n",
        "# mpit-analysis: wire-boundary\n"
        "from mpit_tpu.transport.wire import encode_frame\n"
        "WIRE_FORMAT_VERSION = 1\n"
        "def f(x):\n"
        "    return encode_frame(0, 2, x, version=WIRE_FORMAT_VERSION)\n",
    ):
        findings = _lint_source(tmp_path, src)
        assert findings == [], [f.format() for f in findings]


def test_mpt007_frame_wrong_valued_name_is_drift(tmp_path):
    findings = _lint_source(
        tmp_path,
        _FRAMED
        + "MY_VER = 3\n"
        "def f(x):\n"
        "    return wire.encode_frame(0, 2, x, version=MY_VER)\n",
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "resolves to 3" in findings[0].message


def test_mpt007_frame_config_override(tmp_path):
    """An overridden canonical frame version re-anchors the check
    independently of the pickle side."""
    cfg = lint.Config(hot_all=True, wire_format_version=2)
    findings = _lint_source(
        tmp_path,
        _FRAMED
        + "def f(x):\n    return wire.encode_frame(0, 2, x, version=1)\n",
        cfg,
    )
    assert [f.rule for f in findings] == ["MPT007"]
    assert "drift" in findings[0].message


def test_mpt007_frame_decode_and_unmarked_out_of_scope(tmp_path):
    # readers dispatch on the preamble's version byte — nothing to pin
    findings = _lint_source(
        tmp_path,
        _FRAMED + "def f(h, c, b):\n"
        "    return wire.decode_frame(0, h, c, b)\n",
    )
    assert findings == []
    # no marker, no transport/ path component: not a wire boundary
    findings = _lint_source(
        tmp_path,
        "from mpit_tpu.transport import wire\n"
        "def f(x):\n    return wire.encode_frame(0, 2, x, version=9)\n",
    )
    assert findings == []


# --------------------------------------------------------- MPT012 (metrics)

_LIVE = "from mpit_tpu.obs.live import M_ROUNDS, live_registry\n"


def test_mpt012_matching_literal_still_flagged(tmp_path):
    """The literal equals a registered name TODAY, but a rename of the
    constant would silently strand it — the M_* constant is required."""
    findings = _lint_source(
        tmp_path, _LIVE + "def f(reg):\n    reg.inc('train.rounds')\n"
    )
    assert [f.rule for f in findings] == ["MPT012"]
    assert "strand" in findings[0].message


def test_mpt012_wrong_valued_constant_is_drift(tmp_path):
    """A module-local constant resolving to an unregistered value forks
    the series exactly like a literal typo would."""
    findings = _lint_source(
        tmp_path,
        _LIVE
        + "M_BOGUS = 'train.bogus'\n"
        "def f(reg):\n"
        "    reg.set_gauge(M_BOGUS, 1.0)\n",
    )
    assert [f.rule for f in findings] == ["MPT012"]
    assert "resolves to 'train.bogus'" in findings[0].message


def test_mpt012_unresolvable_namespace_shaped_name(tmp_path):
    """An M_* spelling the namespace does not define is a typo'd import
    or a deleted constant, even when resolution gives up."""
    findings = _lint_source(
        tmp_path, _LIVE + "def f(reg):\n    reg.observe(M_MISSPELLED, 0.1)\n"
    )
    assert [f.rule for f in findings] == ["MPT012"]
    assert "M_MISSPELLED" in findings[0].message


def test_mpt012_registered_constant_clean(tmp_path):
    findings = _lint_source(
        tmp_path, _LIVE + "def f(reg):\n    reg.inc(M_ROUNDS)\n"
    )
    assert findings == []


def test_mpt012_out_of_scope_observe_clean(tmp_path):
    # no live-plane import: ``observe`` here is LogicalClock/SLO-style,
    # not a registry publish — must not be checked at all
    findings = _lint_source(
        tmp_path, "def f(clock):\n    clock.observe('whatever')\n"
    )
    assert findings == []
    # in scope, but the argument is a local non-M_* name: out of static
    # reach, same stance as MPT007 on dynamic protocol expressions
    findings = _lint_source(
        tmp_path,
        _LIVE + "def f(clock, remote_clk):\n    clock.observe(remote_clk)\n",
    )
    assert findings == []


# ------------------------------------------------------------ MPT008 (roles)


def _lint_pkg(tmp_path, files, config=None):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, source in files.items():
        (pkg / name).write_text(source)
    return lint.run_lint([pkg], config or lint.Config())


_ROLE_TAGS = "TAG_A = 21\nTAG_B = 22\n"


def test_mpt008_cross_wait_deadlock(tmp_path):
    """Both roles recv-before-send on the tag only the OTHER side's later
    send satisfies — flagged from the two orderings, once per side."""
    findings = _lint_pkg(
        tmp_path,
        {
            "tags.py": _ROLE_TAGS,
            "left.py": (
                "from pkg.tags import TAG_A, TAG_B\n"
                "# mpit-analysis: protocol-role[left->right]\n"
                "def fa(t, p):\n"
                "    m = t.recv(0, TAG_A)\n"
                "    t.send(0, TAG_B, p)\n"
            ),
            "right.py": (
                "from pkg.tags import TAG_A, TAG_B\n"
                "# mpit-analysis: protocol-role[right->left]\n"
                "def fb(t, p):\n"
                "    m = t.recv(0, TAG_B)\n"
                "    t.send(0, TAG_A, p)\n"
            ),
        },
    )
    assert [f.rule for f in findings] == ["MPT008", "MPT008"]
    assert all("cross-wait deadlock" in f.message for f in findings)


def test_mpt008_ordered_exchange_clean(tmp_path):
    """Same tag sets, compatible order (one side sends first): clean."""
    findings = _lint_pkg(
        tmp_path,
        {
            "tags.py": _ROLE_TAGS,
            "left.py": (
                "from pkg.tags import TAG_A, TAG_B\n"
                "# mpit-analysis: protocol-role[left->right]\n"
                "def fa(t, p):\n"
                "    t.send(0, TAG_B, p)\n"
                "    m = t.recv(0, TAG_A)\n"
            ),
            "right.py": (
                "from pkg.tags import TAG_A, TAG_B\n"
                "# mpit-analysis: protocol-role[right->left]\n"
                "def fb(t, p):\n"
                "    m = t.recv(0, TAG_B)\n"
                "    t.send(0, TAG_A, p)\n"
            ),
        },
    )
    assert findings == []


def test_mpt008_unpaired_recv(tmp_path):
    findings = _lint_pkg(
        tmp_path,
        {
            "tags.py": _ROLE_TAGS,
            "left.py": (
                "from pkg.tags import TAG_A\n"
                "# mpit-analysis: protocol-role[left->right]\n"
                "def fa(t):\n"
                "    return t.recv(0, TAG_A)\n"
            ),
            "right.py": (
                "# mpit-analysis: protocol-role[right->left]\n"
                "def fb(t):\n"
                "    return t.recv(-1, -1)\n"
            ),
        },
    )
    assert [f.rule for f in findings] == ["MPT008"]
    assert "never sends" in findings[0].message


def test_mpt008_blind_dispatcher_exempts_sends(tmp_path):
    """A counterpart with a wildcard recv but NO visible dispatch tags is
    assumed to handle everything — no unpaired-send guessing."""
    findings = _lint_pkg(
        tmp_path,
        {
            "tags.py": _ROLE_TAGS,
            "left.py": (
                "from pkg.tags import TAG_A\n"
                "# mpit-analysis: protocol-role[left->right]\n"
                "def fa(t, p):\n"
                "    t.send(0, TAG_A, p)\n"
            ),
            "right.py": (
                "# mpit-analysis: protocol-role[right->left]\n"
                "def fb(t, handler):\n"
                "    handler(t.recv(-1, -1))\n"
            ),
        },
    )
    assert findings == []


def test_mpt008_counterpart_off_scan_set_unchecked(tmp_path):
    findings = _lint_pkg(
        tmp_path,
        {
            "tags.py": _ROLE_TAGS,
            "left.py": (
                "from pkg.tags import TAG_A\n"
                "# mpit-analysis: protocol-role[left->right]\n"
                "def fa(t, p):\n"
                "    t.send(0, TAG_A, p)\n"
            ),
        },
    )
    assert findings == []


def test_mpt008_repo_roles_pair_up():
    """The real pserver/pclient/ps_roles protocol closes: every client
    tag lands in the server dispatch, TAG_PARAM flows back, no MPT008."""
    from mpit_tpu.analysis import protocol as protocol_mod

    modules = []
    for ap, rel in lint.collect_files([PKG]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    project = lint.Project(modules=modules, config=lint.Config())
    roles = protocol_mod.extract_roles(project)
    assert set(roles) == {
        "client", "server", "serving_router", "serving_replica"
    }
    client, server = roles["client"], roles["server"]
    # FETCH/PUSH*/STOP/HEARTBEAT/JOIN/LEAVE/SHARD_MAP
    assert client.sent_tags == {1, 2, 3, 5, 6, 7, 8, 9}
    assert client.sent_tags <= server.dispatch_tags
    # TAG_PARAM to clients + TAG_RESHARD server-to-server (handoff);
    # the server dispatches RESHARD itself, closing the intra-role pair
    assert server.sent_tags == {4, 10}
    assert 10 in server.dispatch_tags
    assert {op.tag for op in client.concrete_recvs} == {4}
    assert server.has_wildcard_recv
    # the serving fleet closes the same way: ROUTE/WEIGHT_PUSH/STOP
    # down to replicas, REPLY/WEIGHT_SUB back up into concrete recvs
    router, replica = roles["serving_router"], roles["serving_replica"]
    assert router.sent_tags == {11, 14, 15}
    assert router.sent_tags <= replica.dispatch_tags
    assert replica.sent_tags == {12, 13}
    assert {op.tag for op in router.concrete_recvs} == {12, 13}
    assert replica.has_wildcard_recv


def test_baseline_counts_surplus(tmp_path):
    """The first baseline[fp] occurrences are accepted; a surplus COPY of
    a baselined violation is still new."""
    f = Finding(
        rule="MPT005", path="a.py", line=3, col=0,
        symbol="f", message="m", text="x.item()",
    )
    twin = Finding(
        rule="MPT005", path="a.py", line=9, col=0,
        symbol="f", message="m", text="x.item()",
    )
    assert f.fingerprint == twin.fingerprint  # line-number-free
    bl = tmp_path / "bl.json"
    findings_mod.write_baseline(bl, [f])
    baseline = findings_mod.load_baseline(bl)
    assert findings_mod.new_findings([f], baseline) == []
    assert findings_mod.new_findings([f, twin], baseline) == [twin]


# ----------------------------------------------------------------------- CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_cli_repo_scan_exits_clean():
    proc = _cli(str(PKG))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_new_finding_exits_nonzero():
    proc = _cli(
        "--no-baseline", str(FIXTURES / "fixture_mpt002.py"),
        "--format", "json",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["MPT002"]


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in ("MPT001", "MPT002", "MPT003", "MPT004", "MPT005",
                    "MPT006", "MPT007", "MPT008"):
        assert rule_id in proc.stdout


# -------------------------------------------------------------------- --fix


def test_fix_rewrites_known_literal_tags(tmp_path):
    from mpit_tpu.analysis import fixes

    f = tmp_path / "mod.py"
    f.write_text(
        '"""doc."""\n'
        "def g(transport, x):\n"
        "    transport.send(0, 2, x)\n"
        "    return transport.recv(0, 1)\n"
    )
    result = fixes.fix_file(f)
    assert result.error is None
    assert result.replaced == 2
    assert result.imported == ("TAG_FETCH", "TAG_PUSH_EASGD")
    text = f.read_text()
    assert "transport.send(0, TAG_PUSH_EASGD, x)" in text
    assert "transport.recv(0, TAG_FETCH)" in text
    assert (
        "from mpit_tpu.parallel.pserver import TAG_FETCH, TAG_PUSH_EASGD"
        in text
    )
    # the rewrite is lint-clean: no MPT002 left, no new rule tripped
    assert lint.run_lint([f]) == []


def test_fix_leaves_unknown_and_suppressed_literals(tmp_path):
    from mpit_tpu.analysis import fixes

    f = tmp_path / "mod.py"
    source = (
        "def g(transport, x):\n"
        "    transport.send(0, 42, x)\n"  # not a registry value
        "    transport.send(0, 3, x)  # mpit-analysis: ignore[MPT002]\n"
    )
    f.write_text(source)
    result = fixes.fix_file(f)
    assert result.replaced == 0
    assert result.skipped == 1  # the suppressed KNOWN literal
    assert f.read_text() == source  # byte-identical: nothing to do


def test_fix_skips_already_bound_import(tmp_path):
    """A module that already binds TAG_PUSH_EASGD must not get a second,
    shadowing import line."""
    from mpit_tpu.analysis import fixes

    f = tmp_path / "mod.py"
    f.write_text(
        "from mpit_tpu.parallel.pserver import TAG_PUSH_EASGD\n"
        "def g(transport, x):\n"
        "    transport.send(0, 2, x)\n"
    )
    result = fixes.fix_file(f)
    assert result.replaced == 1
    assert result.imported == ()
    assert f.read_text().count("import TAG_PUSH_EASGD") == 1


def test_cli_fix_end_to_end(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "def g(transport, x):\n"
        "    transport.send(0, 5, x)\n"
    )
    proc = _cli("--fix", "--no-baseline", str(f))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rewrote 1 literal tag site(s)" in proc.stdout
    assert "TAG_STOP" in f.read_text()


def test_cli_fix_does_not_touch_unfixable_fixture(tmp_path):
    """fixture_mpt002's 42 has no registry name: --fix leaves the file
    alone and the finding still fails the run."""
    import shutil

    f = tmp_path / "fixture_mpt002.py"
    shutil.copy(FIXTURES / "fixture_mpt002.py", f)
    before = f.read_text()
    proc = _cli("--fix", "--no-baseline", str(f))
    assert proc.returncode == 1  # still a finding: not mechanically fixable
    assert f.read_text() == before


# ------------------------------------------------------------ runtime: RT101


def test_rt101_seeded_lock_inversion():
    """Two threads acquiring {A, B} in opposite orders — the classic
    inversion — is caught from the ORDER GRAPH alone, no temporal
    overlap needed."""
    with runtime.checking() as checker:
        a = runtime.make_lock("A")
        b = runtime.make_lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start(); t1.join()
        t2 = threading.Thread(target=ba)
        t2.start(); t2.join()
    rules = [f.rule for f in checker.findings]
    assert rules == ["RT101"], checker.findings
    assert "A" in checker.findings[0].message
    assert "B" in checker.findings[0].message


def test_rt101_consistent_order_clean():
    with runtime.checking() as checker:
        a = runtime.make_lock("A")
        b = runtime.make_lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
            with b:  # B alone afterwards is NOT an inversion
                pass
    assert checker.findings == []


def test_make_lock_plain_when_inactive():
    lock = runtime.make_lock("x")
    assert not isinstance(lock, runtime._TrackedLock)
    with lock:
        pass


# ------------------------------------------------------------ runtime: RT102


def _spin_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError("condition not reached")
        time.sleep(0.005)


def test_rt102_seeded_tag_collision():
    """Two threads blocked in recv on the same (dst, tag) — two protocol
    roles claiming one tag — is flagged; both recvs then complete."""
    with runtime.checking() as checker:
        broker = Broker(2)
        t = broker.transports()[0]
        results = []

        def role(name):
            results.append((name, t.recv(src=1, tag=7, timeout=10).payload))

        th1 = threading.Thread(target=role, args=("fetcher",))
        th2 = threading.Thread(target=role, args=("pusher",))
        th1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        th2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        broker.transports()[1].send(0, 7, "x")
        broker.transports()[1].send(0, 7, "y")
        th1.join(10); th2.join(10)
    assert [f.rule for f in checker.findings] == ["RT102"]
    assert "tag 7" in checker.findings[0].message
    assert sorted(p for _, p in results) == ["x", "y"]


def test_rt102_wildcard_dispatcher_exempt():
    """recv(ANY_TAG) is the single-dispatcher pattern (the pserver loop)
    and must not collide with a concrete-tag waiter."""
    with runtime.checking() as checker:
        broker = Broker(2)
        t = broker.transports()[0]

        def dispatcher():
            t.recv(src=ANY_SOURCE, tag=ANY_TAG, timeout=10)

        def role():
            t.recv(src=1, tag=3, timeout=10)

        th1 = threading.Thread(target=dispatcher)
        th2 = threading.Thread(target=role)
        th1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        th2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        src = broker.transports()[1]
        # tag 9 first: only the wildcard can match it, so it can't steal
        # the role's tag-3 message afterwards
        src.send(0, 9, "disp")
        th1.join(10)
        src.send(0, 3, "role")
        th2.join(10)
    assert checker.findings == []


def test_rt102_stress_distinct_tags_clean_then_seeded_collision():
    """The stress satellite: N threads hammer one broker. Distinct
    per-role tags -> zero findings (no false positives under real
    concurrency); then one seeded duplicate-tag pair -> exactly one
    RT102."""
    n_roles, msgs = 8, 50
    with runtime.checking() as checker:
        broker = Broker(2)
        rx, tx = broker.transports()
        got = [0] * n_roles

        def role(i):
            for _ in range(msgs):
                m = rx.recv(src=1, tag=100 + i, timeout=30)
                assert m.payload == i
                got[i] += 1

        threads = [
            threading.Thread(target=role, args=(i,))
            for i in range(n_roles)
        ]
        for th in threads:
            th.start()
        for _ in range(msgs):
            for i in range(n_roles):
                tx.send(0, 100 + i, i)
        for th in threads:
            th.join(60)
        assert got == [msgs] * n_roles
        assert checker.findings == []  # clean under load

        # seeded collision: two fresh roles claim tag 100 concurrently
        def clash():
            rx.recv(src=1, tag=100, timeout=10)

        c1 = threading.Thread(target=clash)
        c2 = threading.Thread(target=clash)
        c1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        c2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        tx.send(0, 100, 0)
        tx.send(0, 100, 0)
        c1.join(10); c2.join(10)
    assert [f.rule for f in checker.findings] == ["RT102"]


# ----------------------------------------------- runtime: transport is clean


def test_socket_transport_clean_under_checker():
    """The real socket transport's lock discipline (per-dst send locks,
    outbound-cache lock) produces NO findings on healthy traffic — the
    zero-false-positives half of the acceptance bar."""
    from mpit_tpu.transport import SocketTransport

    with runtime.checking() as checker:
        import socket as _socket

        def _free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        addrs = [("127.0.0.1", _free_port()), ("127.0.0.1", _free_port())]
        t0 = SocketTransport(0, 2, addresses=addrs)
        t1 = SocketTransport(1, 2, addresses=addrs)
        try:
            for i in range(20):
                t0.send(1, 5, {"step": i})
                assert t1.recv(src=0, tag=5, timeout=10).payload == {
                    "step": i
                }
                t1.send(0, 6, i)
                assert t0.recv(src=1, tag=6, timeout=10).payload == i
        finally:
            t0.close()
            t1.close()
    assert checker.findings == []
