"""mpit_tpu.analysis: linter rules, baseline discipline, CLI, runtime checker.

Three layers, mirroring the subsystem:

- the repo SELF-CHECK: linting ``mpit_tpu/`` must produce exactly the
  checked-in baseline (``analysis-baseline.json``) — a new finding anywhere
  in the package fails here before it fails in CI;
- seeded FIXTURES (``tests/fixtures/analysis/``): each file triggers
  exactly its one rule, pinning both directions (the rule fires on its
  target pattern, and fires on nothing else in the fixture);
- the RUNTIME checker: a seeded lock-order inversion and a seeded tag
  collision are detected, and clean transport traffic — including a
  multi-thread stress run — reports zero findings.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from mpit_tpu.analysis import findings as findings_mod
from mpit_tpu.analysis import lint, runtime
from mpit_tpu.analysis.findings import Finding
from mpit_tpu.transport import ANY_SOURCE, ANY_TAG, Broker

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"
BASELINE = REPO / lint.BASELINE_FILENAME


# ---------------------------------------------------------------- self-check


def test_repo_matches_baseline():
    """The package linted against the checked-in baseline is clean — the
    acceptance gate ``python -m mpit_tpu.analysis mpit_tpu/`` enforces."""
    findings = lint.run_lint([PKG])
    baseline = findings_mod.load_baseline(BASELINE)
    new = findings_mod.new_findings(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_baseline_is_not_stale():
    """Every baselined fingerprint still occurs — fixed violations must
    leave the baseline, or it masks a future regression of the same
    shape."""
    findings = lint.run_lint([PKG])
    from collections import Counter

    current = Counter(f.fingerprint for f in findings)
    baseline = findings_mod.load_baseline(BASELINE)
    stale = {
        fp: n for fp, n in baseline.items() if current.get(fp, 0) < n
    }
    assert not stale, f"baselined but no longer present: {sorted(stale)}"


# ------------------------------------------------------------------ fixtures


@pytest.mark.parametrize(
    "fixture, rule",
    [
        ("fixture_mpt001.py", "MPT001"),
        ("fixture_mpt002.py", "MPT002"),
        ("fixture_mpt003.py", "MPT003"),
        ("fixture_mpt004.py", "MPT004"),
        ("fixture_mpt005.py", "MPT005"),
        ("fixture_mpt006.py", "MPT006"),
    ],
)
def test_fixture_triggers_exactly_its_rule(fixture, rule):
    findings = lint.run_lint(
        [FIXTURES / fixture], lint.Config(hot_all=True)
    )
    assert {f.rule for f in findings} == {rule}, [
        f.format() for f in findings
    ]


def test_fixtures_are_never_collected():
    """The seeded-bug files must stay parse-only: no test_ prefix, and
    nothing imports them (they contain deliberate defects)."""
    for py in FIXTURES.glob("*.py"):
        assert py.name.startswith("fixture_")


# --------------------------------------------------------- rule specifics


def _lint_source(tmp_path, source, config=None):
    f = tmp_path / "mod.py"
    f.write_text(source)
    return lint.run_lint([f], config or lint.Config(hot_all=True))


def test_inline_ignore_suppresses(tmp_path):
    findings = _lint_source(
        tmp_path,
        "def f(xs):\n"
        "    for x in xs:\n"
        "        x.item()  # mpit-analysis: ignore[MPT005]\n",
    )
    assert findings == []


def test_inline_ignore_is_rule_scoped(tmp_path):
    findings = _lint_source(
        tmp_path,
        "def f(xs):\n"
        "    for x in xs:\n"
        "        x.item()  # mpit-analysis: ignore[MPT001]\n",
    )
    assert [f.rule for f in findings] == ["MPT005"]


def test_host_sync_barrier_marker(tmp_path):
    """A def carrying the marker is exempt (body and call sites), the
    utils/profiling.force_completion contract."""
    findings = _lint_source(
        tmp_path,
        "def sync(x):  # mpit-analysis: host-sync-barrier\n"
        "    return float(x)\n"
        "def f(xs):\n"
        "    for x in xs:\n"
        "        sync(x)\n",
    )
    assert findings == []


def test_bound_axis_not_flagged(tmp_path):
    """A literal axis the module itself binds (shard_map / P spec) is
    fine — only the copied-out-of-context collective fires MPT001."""
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def step(x):\n"
        "    return lax.psum(x, 'dp')\n"
        "f = jax.shard_map(step, mesh=None, in_specs=P('dp'),"
        " out_specs=P())\n",
    )
    assert findings == []


def test_jit_static_argnames_drift(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import jax\n"
        "@jax.jit(static_argnames=('gone',))\n"
        "def f(model, batch):\n"
        "    return batch\n",
    )
    assert [f.rule for f in findings] == ["MPT004"]


def test_jit_consistent_statics_clean(tmp_path):
    findings = _lint_source(
        tmp_path,
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, static_argnums=(0,),"
        " static_argnames=('batch',))\n"
        "def f(model, batch):\n"
        "    return batch\n",
    )
    assert findings == []


def test_baseline_counts_surplus(tmp_path):
    """The first baseline[fp] occurrences are accepted; a surplus COPY of
    a baselined violation is still new."""
    f = Finding(
        rule="MPT005", path="a.py", line=3, col=0,
        symbol="f", message="m", text="x.item()",
    )
    twin = Finding(
        rule="MPT005", path="a.py", line=9, col=0,
        symbol="f", message="m", text="x.item()",
    )
    assert f.fingerprint == twin.fingerprint  # line-number-free
    bl = tmp_path / "bl.json"
    findings_mod.write_baseline(bl, [f])
    baseline = findings_mod.load_baseline(bl)
    assert findings_mod.new_findings([f], baseline) == []
    assert findings_mod.new_findings([f, twin], baseline) == [twin]


# ----------------------------------------------------------------------- CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_cli_repo_scan_exits_clean():
    proc = _cli(str(PKG))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_new_finding_exits_nonzero():
    proc = _cli(
        "--no-baseline", str(FIXTURES / "fixture_mpt002.py"),
        "--format", "json",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["MPT002"]


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in ("MPT001", "MPT002", "MPT003", "MPT004", "MPT005",
                    "MPT006"):
        assert rule_id in proc.stdout


# ------------------------------------------------------------ runtime: RT101


def test_rt101_seeded_lock_inversion():
    """Two threads acquiring {A, B} in opposite orders — the classic
    inversion — is caught from the ORDER GRAPH alone, no temporal
    overlap needed."""
    with runtime.checking() as checker:
        a = runtime.make_lock("A")
        b = runtime.make_lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start(); t1.join()
        t2 = threading.Thread(target=ba)
        t2.start(); t2.join()
    rules = [f.rule for f in checker.findings]
    assert rules == ["RT101"], checker.findings
    assert "A" in checker.findings[0].message
    assert "B" in checker.findings[0].message


def test_rt101_consistent_order_clean():
    with runtime.checking() as checker:
        a = runtime.make_lock("A")
        b = runtime.make_lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
            with b:  # B alone afterwards is NOT an inversion
                pass
    assert checker.findings == []


def test_make_lock_plain_when_inactive():
    lock = runtime.make_lock("x")
    assert not isinstance(lock, runtime._TrackedLock)
    with lock:
        pass


# ------------------------------------------------------------ runtime: RT102


def _spin_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError("condition not reached")
        time.sleep(0.005)


def test_rt102_seeded_tag_collision():
    """Two threads blocked in recv on the same (dst, tag) — two protocol
    roles claiming one tag — is flagged; both recvs then complete."""
    with runtime.checking() as checker:
        broker = Broker(2)
        t = broker.transports()[0]
        results = []

        def role(name):
            results.append((name, t.recv(src=1, tag=7, timeout=10).payload))

        th1 = threading.Thread(target=role, args=("fetcher",))
        th2 = threading.Thread(target=role, args=("pusher",))
        th1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        th2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        broker.transports()[1].send(0, 7, "x")
        broker.transports()[1].send(0, 7, "y")
        th1.join(10); th2.join(10)
    assert [f.rule for f in checker.findings] == ["RT102"]
    assert "tag 7" in checker.findings[0].message
    assert sorted(p for _, p in results) == ["x", "y"]


def test_rt102_wildcard_dispatcher_exempt():
    """recv(ANY_TAG) is the single-dispatcher pattern (the pserver loop)
    and must not collide with a concrete-tag waiter."""
    with runtime.checking() as checker:
        broker = Broker(2)
        t = broker.transports()[0]

        def dispatcher():
            t.recv(src=ANY_SOURCE, tag=ANY_TAG, timeout=10)

        def role():
            t.recv(src=1, tag=3, timeout=10)

        th1 = threading.Thread(target=dispatcher)
        th2 = threading.Thread(target=role)
        th1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        th2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        src = broker.transports()[1]
        # tag 9 first: only the wildcard can match it, so it can't steal
        # the role's tag-3 message afterwards
        src.send(0, 9, "disp")
        th1.join(10)
        src.send(0, 3, "role")
        th2.join(10)
    assert checker.findings == []


def test_rt102_stress_distinct_tags_clean_then_seeded_collision():
    """The stress satellite: N threads hammer one broker. Distinct
    per-role tags -> zero findings (no false positives under real
    concurrency); then one seeded duplicate-tag pair -> exactly one
    RT102."""
    n_roles, msgs = 8, 50
    with runtime.checking() as checker:
        broker = Broker(2)
        rx, tx = broker.transports()
        got = [0] * n_roles

        def role(i):
            for _ in range(msgs):
                m = rx.recv(src=1, tag=100 + i, timeout=30)
                assert m.payload == i
                got[i] += 1

        threads = [
            threading.Thread(target=role, args=(i,))
            for i in range(n_roles)
        ]
        for th in threads:
            th.start()
        for _ in range(msgs):
            for i in range(n_roles):
                tx.send(0, 100 + i, i)
        for th in threads:
            th.join(60)
        assert got == [msgs] * n_roles
        assert checker.findings == []  # clean under load

        # seeded collision: two fresh roles claim tag 100 concurrently
        def clash():
            rx.recv(src=1, tag=100, timeout=10)

        c1 = threading.Thread(target=clash)
        c2 = threading.Thread(target=clash)
        c1.start()
        _spin_until(lambda: len(checker._waiters) >= 1)
        c2.start()
        _spin_until(lambda: len(checker._waiters) >= 2)
        tx.send(0, 100, 0)
        tx.send(0, 100, 0)
        c1.join(10); c2.join(10)
    assert [f.rule for f in checker.findings] == ["RT102"]


# ----------------------------------------------- runtime: transport is clean


def test_socket_transport_clean_under_checker():
    """The real socket transport's lock discipline (per-dst send locks,
    outbound-cache lock) produces NO findings on healthy traffic — the
    zero-false-positives half of the acceptance bar."""
    from mpit_tpu.transport import SocketTransport

    with runtime.checking() as checker:
        import socket as _socket

        def _free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        addrs = [("127.0.0.1", _free_port()), ("127.0.0.1", _free_port())]
        t0 = SocketTransport(0, 2, addresses=addrs)
        t1 = SocketTransport(1, 2, addresses=addrs)
        try:
            for i in range(20):
                t0.send(1, 5, {"step": i})
                assert t1.recv(src=0, tag=5, timeout=10).payload == {
                    "step": i
                }
                t1.send(0, 6, i)
                assert t0.recv(src=1, tag=6, timeout=10).payload == i
        finally:
            t0.close()
            t1.close()
    assert checker.findings == []
