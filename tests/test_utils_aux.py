"""Aux-subsystem tests: checkpoint/resume, metrics, config, profiling.

The reference had none of these (SURVEY.md §5) — these tests pin down the
do-better behavior: checkpoints must reproduce the EASGD center variable
exactly, resume must continue (not restart) training, configs must
round-trip, presets must map to the five baseline configs.
"""

import io
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpit_tpu
from mpit_tpu.models import MLP
from mpit_tpu.parallel import DataParallelTrainer, EASGDTrainer
from mpit_tpu.utils import (
    PRESETS,
    MetricsLogger,
    StepTimer,
    Throughput,
    TrainConfig,
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    save_checkpoint,
)


def _tiny_batches(w=8, tau=2, b=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(0, 1, (tau, w * b, 8, 8, 1)).astype(np.float32)
    y = rng.integers(0, 10, (tau, w * b)).astype(np.int32)
    return x, y


class TestCheckpoint:
    def test_roundtrip_easgd_state_center_exact(self, topo8, tmp_path):
        """Resume must reproduce the center variable bit-exactly
        (SURVEY.md §5 checkpoint item)."""
        model = MLP(hidden=(16,), compute_dtype=jnp.float32)
        tr = EASGDTrainer(model, optax.sgd(0.1, momentum=0.9), topo8, tau=2)
        x, y = _tiny_batches()
        state = tr.init_state(jax.random.key(0), x[0, :2])
        state, _ = tr.step(state, x, y)

        save_checkpoint(str(tmp_path), state, step=int(state.round))
        template = tr.init_state(jax.random.key(1), x[0, :2])  # different rng
        restored, step = restore_checkpoint(str(tmp_path), template)
        assert step == 1

        for a, b in zip(
            jax.tree.leaves(jax.device_get(state.center)),
            jax.tree.leaves(jax.device_get(restored.center)),
        ):
            np.testing.assert_array_equal(a, b)
        # worker-sharded leaves too
        for a, b in zip(
            jax.tree.leaves(jax.device_get(state.worker_params)),
            jax.tree.leaves(jax.device_get(restored.worker_params)),
        ):
            np.testing.assert_array_equal(a, b)

    def test_resume_continues_training(self, topo8, tmp_path):
        """Train 2 rounds, checkpoint, train 2 more; vs restore + 2 rounds —
        identical final state (deterministic data ⇒ bit-equal)."""
        model = MLP(hidden=(16,), compute_dtype=jnp.float32)
        tr = EASGDTrainer(model, optax.sgd(0.1), topo8, tau=2,
                          donate_state=False)
        x1, y1 = _tiny_batches(seed=1)
        x2, y2 = _tiny_batches(seed=2)
        state = tr.init_state(jax.random.key(0), x1[0, :2])
        state, _ = tr.step(state, x1, y1)
        save_checkpoint(str(tmp_path), state, step=1)
        state, _ = tr.step(state, x2, y2)
        final_direct = jax.device_get(tr.center_params(state))

        template = tr.init_state(jax.random.key(9), x1[0, :2])
        shardings = jax.tree.map(lambda a: a.sharding, template)
        restored, step = restore_checkpoint(
            str(tmp_path), template, shardings=shardings
        )
        assert step == 1
        restored, _ = tr.step(restored, x2, y2)
        final_resumed = jax.device_get(tr.center_params(restored))
        for a, b in zip(
            jax.tree.leaves(final_direct), jax.tree.leaves(final_resumed)
        ):
            np.testing.assert_array_equal(a, b)

    def test_retention_and_latest(self, tmp_path):
        state = {"w": jnp.arange(4.0)}
        for s in (1, 2, 3, 4, 5):
            save_checkpoint(str(tmp_path), state, step=s, keep=3)
        assert list_checkpoints(str(tmp_path)) == [3, 4, 5]
        assert latest_checkpoint(str(tmp_path)) == 5

    def test_restore_empty_dir_cold_start(self, tmp_path):
        template = {"w": jnp.ones(3)}
        state, step = restore_checkpoint(str(tmp_path / "nope"), template)
        assert step is None
        np.testing.assert_array_equal(state["w"], np.ones(3))

    def test_specific_step_and_metadata(self, tmp_path):
        for s in (10, 20):
            save_checkpoint(
                str(tmp_path), {"w": jnp.full(2, float(s))}, step=s,
                metadata={"algo": "easgd"},
            )
        state, step = restore_checkpoint(
            str(tmp_path), {"w": jnp.zeros(2)}, step=10
        )
        assert step == 10
        np.testing.assert_array_equal(state["w"], np.full(2, 10.0))
        meta = json.load(open(tmp_path / "ckpt_00000010.json"))
        assert meta == {"step": 10, "algo": "easgd"}


class TestMetrics:
    def test_jsonl_records(self):
        buf = io.StringIO()
        log = MetricsLogger(tag="t", echo=False, _stream=buf)
        log.log(1, loss=jnp.float32(0.5), acc=0.9)
        log.log(2, loss=0.25)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert [l["step"] for l in lines] == [1, 2]
        assert lines[0]["loss"] == 0.5 and lines[0]["tag"] == "t"
        assert lines[0]["process"] == 0

    def test_file_append_and_dirs(self, tmp_path):
        p = tmp_path / "sub" / "m.jsonl"
        with MetricsLogger(path=str(p), echo=False) as log:
            log.log(0, loss=1.0)
        with MetricsLogger(path=str(p), echo=False) as log:
            log.log(1, loss=0.5)
        lines = open(p).read().splitlines()
        assert len(lines) == 2

    def test_nonscalar_values_serialize(self):
        buf = io.StringIO()
        log = MetricsLogger(tag="t", echo=False, _stream=buf)
        log.log(0, grad_norms=np.arange(3.0), name="run", counts=[1, 2])
        rec = json.loads(buf.getvalue())
        assert rec["grad_norms"] == [0.0, 1.0, 2.0]
        assert rec["name"] == "run" and rec["counts"] == [1, 2]

    def test_throughput(self):
        tp = Throughput()
        assert tp.tick(100) is None
        assert tp.tick(100) > 0


class TestConfig:
    def test_presets_cover_baseline_configs(self):
        # BASELINE.md table rows 1-5 (+ the literal ps shape); extras must
        # be a superset, never displace a baseline config
        assert set(PRESETS) >= {
            "mnist-easgd", "mnist-ps", "cifar-vgg-sync",
            "alexnet-downpour", "resnet50-sync", "ptb-lstm-easgd",
        }
        assert "ptb-transformer-seq" in PRESETS  # beyond-parity preset

    def test_json_roundtrip(self):
        cfg = TrainConfig(model="vgg", lr=0.02, tau=8)
        cfg2 = TrainConfig.from_json(cfg.to_json())
        assert cfg2 == cfg

    def test_from_args_preset_overlay(self):
        cfg = TrainConfig.from_args(["--preset", "cifar-vgg-sync"])
        assert cfg.model == "vgg" and cfg.algo == "sync"
        assert cfg.dataset == "cifar10"

    def test_explicit_flag_beats_preset(self):
        cfg = TrainConfig.from_args(
            ["--preset", "cifar-vgg-sync", "--lr", "0.5"]
        )
        assert cfg.lr == 0.5 and cfg.model == "vgg"

    def test_explicit_default_valued_flag_beats_preset(self):
        # --lr 0.05 IS the dataclass default; typing it must still win over
        # the preset's lr (ptb preset sets lr=1.0)
        cfg = TrainConfig.from_args(
            ["--preset", "ptb-lstm-easgd", "--lr", "0.05"]
        )
        assert cfg.lr == 0.05 and cfg.model == "lstm"

    def test_unknown_preset_raises(self):
        with pytest.raises(ValueError, match="unknown preset"):
            TrainConfig().apply_preset("nope")


class TestProfiling:
    def test_step_timer_skips_compile(self):
        t = StepTimer(skip_first=1)
        for _ in range(3):
            t.start()
            t.stop(jnp.ones(4))
        assert t.count == 2
        s = t.summary()
        assert s["steps"] == 2 and s["mean_s"] > 0

    def test_trace_noop_without_dir(self):
        from mpit_tpu.utils.profiling import trace

        with trace(None):
            pass

    def test_trace_writes(self, tmp_path):
        from mpit_tpu.utils.profiling import trace

        with trace(str(tmp_path)):
            jax.block_until_ready(jnp.ones(8) * 2)
        assert os.listdir(tmp_path)  # trace artifacts exist


def test_state_to_host_sharded_leaf(topo8):
    """state_to_host is the collective-safe gather save_checkpoint routes
    every leaf through; on a fully-addressable mesh it must be a plain
    value-preserving fetch for sharded and replicated leaves alike."""
    from mpit_tpu.utils.checkpoint import state_to_host

    val = np.arange(16, dtype=np.float32).reshape(8, 2)
    sharded = jax.device_put(val, topo8.worker_sharding())
    replicated = jax.device_put(val, topo8.replicated_sharding())
    host = state_to_host({"s": sharded, "r": replicated, "n": 3})
    np.testing.assert_array_equal(host["s"], val)
    np.testing.assert_array_equal(host["r"], val)
    assert host["n"] == 3


class TestForceCompletion:
    """The shared completion-proof helper (the block_until_ready-lies
    workaround): must fetch one scalar per argument and survive pytrees
    with non-floating leaves (ints, PRNG keys — review-caught crash)."""

    def test_returns_data_dependent_scalar_per_argument(self):
        import jax.numpy as jnp

        from mpit_tpu.utils import force_completion

        state = {"w": jnp.full((4, 3), 2.0), "step": jnp.int32(7)}
        metrics = {"loss": jnp.float32(1.5)}
        # smallest floating leaf of each arg: w (sum 24.0) + loss (1.5)
        assert force_completion(state, metrics) == 25.5

    def test_prng_key_and_int_leaves_are_skipped(self):
        import jax
        import jax.numpy as jnp

        from mpit_tpu.utils import force_completion

        tree = {
            "key": jax.random.key(0),
            "count": jnp.int32(3),
            "p": jnp.ones(5),
        }
        assert force_completion(tree) == 5.0

    def test_no_floating_leaves_falls_back(self):
        import jax.numpy as jnp

        from mpit_tpu.utils import force_completion

        assert force_completion({"i": jnp.int32(1)}) == 0.0

    def test_step_timer_spreads_tuple_results(self):
        import jax.numpy as jnp

        from mpit_tpu.utils import StepTimer

        t = StepTimer(skip_first=0)
        t.start()
        dt = t.stop(({"w": jnp.ones(3)}, {"loss": jnp.float32(0.5)}))
        assert dt >= 0
        t.start()
        assert t.stop(jnp.float32(2.0)) >= 0
        t.start()
        assert t.stop(None) >= 0
