"""Pipeline parallelism: schedule-invariance on the 8-device mesh.

The GPipe schedule must be pure bookkeeping: the pipelined loss/update
trajectory must equal the unpipelined reference apply with the same
params, for every (dp, pp) factorization and microbatch count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# integration tier — excluded from the smoke run (schedule-trajectory equivalences)
pytestmark = pytest.mark.slow

import mpit_tpu
from mpit_tpu.parallel.pipeline import (
    PipelineParallelTrainer,
    init_params,
    reference_apply,
)

V, B, T, L, D, H = 23, 8, 16, 8, 32, 4


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, V, (B, T)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


def _ref_loss(params, x, y):
    logits = reference_apply(params, jnp.asarray(x), H).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return float(
        -jnp.take_along_axis(logp, jnp.asarray(y)[..., None], -1).mean()
    )


def _run(mesh_shape, n_micro, steps=3, schedule="gpipe", virtual=2,
         with_eval=False):
    mpit_tpu.finalize()
    topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=mesh_shape)
    tr = PipelineParallelTrainer(
        vocab_size=V, num_layers=L, d_model=D, num_heads=H, seq_len=T,
        topo=topo, n_micro=n_micro, lr=0.1, momentum=0.9,
        schedule=schedule, virtual=virtual,
    )
    state = tr.init_state(jax.random.key(0))
    x, y = _data()
    losses = []
    for _ in range(steps):
        state, m = tr.step(state, x, y)
        losses.append(float(m["loss"]))
    ev = tr.evaluate(state, x, y) if with_eval else None
    # compare in GLOBAL layer order regardless of storage layout
    params = jax.tree.map(
        np.asarray, jax.device_get(tr._unpermute(state["params"]))
    )
    mpit_tpu.finalize()
    return (losses, params, ev) if with_eval else (losses, params)


class TestPipelineParallel:
    def test_first_loss_matches_unpipelined_reference(self):
        losses, _ = _run((1, 8), n_micro=4, steps=1)
        params = init_params(jax.random.key(0), V, L, D, 4 * D, T,
                             num_heads=H)
        x, y = _data()
        assert losses[0] == pytest.approx(_ref_loss(params, x, y), rel=1e-5)

    def test_factorizations_and_microbatching_match(self):
        ref_losses, ref_params = _run((1, 8), n_micro=4)
        for shape, m in (((2, 4), 4), ((4, 2), 2), ((1, 8), 8)):
            losses, params = _run(shape, n_micro=m)
            np.testing.assert_allclose(
                losses, ref_losses, rtol=2e-5, atol=2e-6,
                err_msg=f"mesh {shape} n_micro={m}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-4, atol=2e-4
                ),
                params, ref_params,
            )

    def test_1f1b_schedule_properties(self):
        """Span 2(M+S−1); in-flight bounded by min(S, M), not M —
        the memory property that motivates 1F1B."""
        from mpit_tpu.parallel.pipeline import schedule_1f1b

        for m, s in ((4, 4), (8, 4), (2, 8), (8, 8), (1, 4)):
            tabs = schedule_1f1b(m, s)
            assert tabs["ticks"] == 2 * (m + s - 1), (m, s)
            assert max(tabs["max_inflight"]) <= min(s, m), (m, s)
            # every stage runs exactly m forwards and m backwards
            op = tabs["op"]
            assert (op == 1).sum(0).tolist() == [m] * s
            assert (op == 2).sum(0).tolist() == [m] * s

    def test_1f1b_matches_gpipe_trajectory(self):
        """The schedule is pure bookkeeping: 1F1B must produce the same
        losses and params as GPipe (and hence the unpipelined
        reference) on every factorization."""
        ref_losses, ref_params = _run((1, 8), n_micro=4)
        for shape, m in (((1, 8), 4), ((2, 4), 4), ((4, 2), 2)):
            losses, params = _run(shape, n_micro=m, schedule="1f1b")
            np.testing.assert_allclose(
                losses, ref_losses, rtol=2e-5, atol=2e-6,
                err_msg=f"1f1b mesh {shape} n_micro={m}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-4, atol=2e-4
                ),
                params, ref_params,
            )

    def test_interleaved_matches_gpipe_trajectory(self):
        """Virtual chunks (Megatron interleaving) are pure bookkeeping
        too: same losses, same (globally-reordered) params, same eval as
        GPipe — and the storage permutation round-trips."""
        ref = _run((1, 8), n_micro=4, with_eval=True)
        for shape, m, v in (((1, 8), 4, 1), ((2, 4), 4, 2),
                            ((4, 2), 2, 2), ((2, 4), 4, 1)):
            losses, params, ev = _run(
                shape, n_micro=m, schedule="interleaved", virtual=v,
                with_eval=True,
            )
            np.testing.assert_allclose(
                losses, ref[0], rtol=2e-5, atol=2e-6,
                err_msg=f"interleaved mesh {shape} v={v}",
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-4, atol=2e-4
                ),
                params, ref[1],
            )
            assert ev[0] == pytest.approx(ref[2][0], abs=1e-6)

    def test_interleaved_span_wins_when_bubble_dominates(self):
        """The point of virtual chunks: in stage-time units the span
        shrinks when M <~ S (and the simulator honestly shows it does
        NOT win for M >> S under the 1-tick-hop executor)."""
        from mpit_tpu.parallel.pipeline import schedule_pipeline

        for m, s in ((4, 4), (8, 8)):
            plain = schedule_pipeline(m, s, 1)["ticks"]
            inter = schedule_pipeline(m, s, 2)["ticks"] / 2
            assert inter < plain, (m, s, inter, plain)
        # ... and the honest flip side: for M >> S the extra per-chunk
        # hop latency eats the gain (documented, so pinned)
        plain = schedule_pipeline(32, 4, 1)["ticks"]
        inter = schedule_pipeline(32, 4, 2)["ticks"] / 2
        assert inter >= plain, (inter, plain)

    def test_trains_to_low_loss(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=L, d_model=D, num_heads=H, seq_len=T,
            topo=topo, n_micro=2, lr=0.3, momentum=0.9,
        )
        state = tr.init_state(jax.random.key(1))
        stream = np.arange(B * T * 2, dtype=np.int32) % V
        x = stream.reshape(-1, T)[:B]
        y = np.roll(x, -1, axis=1).astype(np.int32)
        first = last = None
        for _ in range(40):
            state, m = tr.step(state, x, y)
            first = first if first is not None else float(m["loss"])
            last = float(m["loss"])
        assert last < first * 0.5, (first, last)
        mpit_tpu.finalize()

    def test_validation(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(1, 8))
        with pytest.raises(ValueError, match="not divisible by pp"):
            PipelineParallelTrainer(
                vocab_size=V, num_layers=6, d_model=D, num_heads=H,
                seq_len=T, topo=topo,
            )
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=L, d_model=D, num_heads=H, seq_len=T,
            topo=topo, n_micro=4,
        )
        state = tr.init_state(jax.random.key(0))
        x, y = _data()
        with pytest.raises(ValueError, match="n_micro"):
            tr.step(state, x[:6], y[:6])
        long_x = np.zeros((B, T * 2), np.int32)
        with pytest.raises(ValueError, match="position"):
            tr.step(state, long_x, long_x)
        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        with pytest.raises(ValueError, match="second axis is 'pp'"):
            PipelineParallelTrainer(
                vocab_size=V, num_layers=L, d_model=D, num_heads=H,
                seq_len=T, topo=topo,
            )
        mpit_tpu.finalize()


class TestOptaxOptimizer:
    """optimizer=: a real optax transform through the pipelined update
    (elementwise-probed), with the mesh-correct clip_norm option."""

    def _run_opt(self, mesh_shape, n_micro, optimizer=None, clip_norm=None,
                 steps=3, lr=0.1, momentum=0.9):
        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=mesh_shape)
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=L, d_model=D, num_heads=H, seq_len=T,
            topo=topo, n_micro=n_micro, lr=lr, momentum=momentum,
            optimizer=optimizer, clip_norm=clip_norm,
        )
        state = tr.init_state(jax.random.key(0))
        x, y = _data()
        losses = []
        for _ in range(steps):
            state, m = tr.step(state, x, y)
            losses.append(float(m["loss"]))
        params = jax.tree.map(
            np.asarray, jax.device_get(tr._unpermute(state["params"]))
        )
        mpit_tpu.finalize()
        return losses, params

    def test_optax_sgd_matches_builtin(self):
        """optax.sgd(momentum) IS the built-in update: trajectories must
        be identical on a real (dp, pp) mesh."""
        import optax

        a_l, a_p = self._run_opt((2, 4), 4)
        b_l, b_p = self._run_opt(
            (2, 4), 4, optimizer=optax.sgd(0.1, momentum=0.9)
        )
        np.testing.assert_allclose(b_l, a_l, rtol=1e-6, atol=1e-7)
        jax.tree.map(
            lambda p, q: np.testing.assert_allclose(
                p, q, rtol=1e-5, atol=1e-6
            ),
            b_p, a_p,
        )

    def test_adam_factorization_invariant(self):
        """Adam state (params-shaped mu/nu + scalar count) shards along
        with the stages and the trajectory is mesh-factorization
        invariant — the spec inference handles non-trivial opt states."""
        import optax

        ref = self._run_opt((1, 8), 8, optimizer=optax.adam(1e-2))
        got = self._run_opt((4, 2), 2, optimizer=optax.adam(1e-2))
        np.testing.assert_allclose(got[0], ref[0], rtol=2e-5, atol=2e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=2e-4, atol=2e-4
            ),
            got[1], ref[1],
        )

    def test_clip_engages_and_is_factorization_invariant(self):
        """clip_norm: the psum-over-pp norm equals the full-model norm,
        so clipped trajectories agree across factorizations and differ
        from unclipped ones (the threshold engages)."""
        import optax

        c = 0.05
        plain = self._run_opt((2, 4), 4, optimizer=optax.sgd(0.1))
        ref = self._run_opt(
            (1, 8), 8, optimizer=optax.sgd(0.1), clip_norm=c
        )
        got = self._run_opt(
            (2, 4), 4, optimizer=optax.sgd(0.1), clip_norm=c
        )
        assert not np.allclose(ref[0], plain[0]), "clip never engaged"
        np.testing.assert_allclose(got[0], ref[0], rtol=2e-5, atol=2e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=2e-4, atol=2e-4
            ),
            got[1], ref[1],
        )

    def test_cross_leaf_optimizer_rejected(self):
        import optax

        mpit_tpu.finalize()
        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
        with pytest.raises(ValueError, match="ELEMENTWISE"):
            PipelineParallelTrainer(
                vocab_size=V, num_layers=L, d_model=D, num_heads=H,
                seq_len=T, topo=topo,
                optimizer=optax.chain(
                    optax.clip_by_global_norm(1.0), optax.sgd(0.1)
                ),
            )
        mpit_tpu.finalize()
