"""Black-box flight recorder + cross-rank post-mortem tests
(docs/OBSERVABILITY.md "Black box & post-mortem").

Layers under test: the ring-mode Journal and its kill-safe incremental
``journal_cap`` footer, the BlackBox ring (count + horizon bounds,
accumulating atomic dump segments, per-incident dedup, dump-time
sources), the cross-process triggers (dump_request.json watcher, the
explicit dump signal), conformance's truncation licensing, the serving
lifecycle tags the dumps rely on, the post-mortem analyzer over the
checked-in golden incident (tests/fixtures/blackbox — 3 ranks, rank 2
SIGKILLed), the armed-ring overhead pin, and — slow tier — the full
launcher story: a seeded supervisor kill on one of three OS processes
must leave dumps on every survivor and a post-mortem that names the
victim as first-mover.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from mpit_tpu.obs import (
    BlackBox,
    Journal,
    ObsConfig,
    analyze_postmortem,
    arm_process_triggers,
    format_postmortem,
    load_dumps,
    read_journal,
    request_dump,
)
from mpit_tpu.obs.__main__ import main as obs_main
from mpit_tpu.obs.blackbox import REQUEST_FILE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "fixtures", "blackbox")


class TestRingJournal:
    """MPIT_OBS_RING: the journal keeps its crash, not its start."""

    def test_ring_keeps_newest_with_footer(self, tmp_path):
        path = str(tmp_path / "obs_rank0.jsonl")
        j = Journal(path, 0, max_records=4, mode="ring")
        for i in range(10):
            j.event("send", i, n=i)
        # nothing on disk until close — the buffered tail is the
        # documented cost the black-box triggers exist to cover
        assert not os.path.exists(path) or not list(read_journal(path))
        j.close()
        recs = list(read_journal(path))
        assert [r["n"] for r in recs[:-1]] == [6, 7, 8, 9]
        footer = recs[-1]
        assert footer["ev"] == "journal_cap"
        assert footer["mode"] == "ring"
        assert footer["evicted_records"] == 6
        assert footer["dropped_records"] == 0

    def test_ring_mode_default_cap(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"), 0, mode="ring")
        assert j.max_records == Journal._RING_DEFAULT_RECORDS
        with pytest.raises(ValueError, match="mode"):
            Journal(str(tmp_path / "k.jsonl"), 0, mode="reservoir")

    def test_ring_env_knob(self):
        from mpit_tpu.obs import config_from_env

        cfg = config_from_env({"MPIT_OBS_RING": "1"})
        assert cfg is not None and cfg.ring
        assert not config_from_env({"MPIT_OBS_DIR": "/tmp/x"}).ring

    def test_incremental_footer_survives_no_close(self, tmp_path):
        """The kill-safety contract: a capped journal's footer must be
        on disk after the first drop — a SIGKILLed rank never reaches
        close(), and conformance still needs the confession."""
        path = str(tmp_path / "obs_rank0.jsonl")
        j = Journal(path, 0, max_records=2)
        for i in range(5):
            j.event("send", i, n=i)
        # no close() on purpose
        recs = list(read_journal(path))
        footers = [r for r in recs if r.get("ev") == "journal_cap"]
        assert len(footers) == 1
        assert footers[0]["dropped_records"] >= 1
        assert recs[-1]["ev"] == "journal_cap"  # footer stays last
        j.close()
        recs = list(read_journal(path))
        footers = [r for r in recs if r.get("ev") == "journal_cap"]
        assert len(footers) == 1  # rewritten in place, not appended
        assert footers[0]["dropped_records"] == 3


class TestTruncationLicensing:
    """A journal_cap footer with drops/evictions licenses the rank's
    incomplete record set for TC201/TC202 — same as membership churn,
    but self-declared and never disabled by --strict."""

    def test_truncated_ranks(self):
        from mpit_tpu.analysis.conformance import truncated_ranks

        recs = [
            {"ev": "send", "rank": 0},
            {"ev": "journal_cap", "rank": 0, "dropped_records": 7},
            {"ev": "journal_cap", "rank": 1, "dropped_records": 0,
             "mode": "ring", "evicted_records": 12},
            # complete journal: footer present, nothing lost -> no license
            {"ev": "journal_cap", "rank": 2, "dropped_records": 0},
        ]
        assert truncated_ranks(recs) == frozenset({0, 1})
        assert truncated_ranks([]) == frozenset()


class TestBlackBoxRing:
    def test_count_bound_evicts_head(self, tmp_path):
        box = BlackBox(str(tmp_path), 0, max_records=3, max_seconds=1e6)
        for i in range(8):
            box.record(time.time(), i, "send", {"n": i})
        s = box.stats()
        assert s["records"] == 3 and s["evicted"] == 5
        box.close()

    def test_horizon_bound_evicts_old(self, tmp_path):
        box = BlackBox(str(tmp_path), 0, max_records=100, max_seconds=5.0)
        now = time.time()
        box.record(now - 60.0, 1, "send", {"n": 0})  # outside horizon
        box.record(now, 2, "send", {"n": 1})
        s = box.stats()
        assert s["records"] == 1 and s["evicted"] == 1
        box.close()

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError, match="max_records"):
            BlackBox(str(tmp_path), 0, max_records=0)
        with pytest.raises(ValueError, match="max_seconds"):
            BlackBox(str(tmp_path), 0, max_seconds=0)

    def test_dump_segments_accumulate_and_dedup(self, tmp_path):
        box = BlackBox(str(tmp_path), 3, max_records=10, gen=2)
        t = time.time()
        for i in range(4):
            box.record(t + i * 1e-3, 10 + i, "send", {"n": i})
        p1 = box.dump("request", incident="inc-a")
        assert p1 == box.path and os.path.exists(p1)
        # same incident on any box dumps once, however often requested
        assert box.dump("request", incident="inc-a") is None
        box.record(t + 1.0, 99, "send", {"n": 4})
        assert box.dump("request", incident="inc-b") == p1
        lines = [json.loads(s) for s in open(p1)]
        headers = [r for r in lines if r["ev"] == "blackbox"]
        assert [h["incident"] for h in headers] == ["inc-a", "inc-b"]
        assert headers[0]["gen"] == 2 and headers[0]["trigger"] == "request"
        assert headers[0]["records"] == 4 and headers[1]["records"] == 5
        assert headers[0]["t_first"] == pytest.approx(t)
        # the loader folds overlapping segments back to unique records
        ranks = load_dumps(str(tmp_path))
        assert set(ranks) == {(3, 2)}
        assert len(ranks[(3, 2)]["records"]) == 5
        assert len(ranks[(3, 2)]["headers"]) == 2
        box.close()

    def test_empty_ring_skips_quiet_triggers(self, tmp_path):
        box = BlackBox(str(tmp_path), 0)
        assert box.dump("atexit") is None
        assert box.dump("close") is None
        assert not os.path.exists(box.path)
        box.close()

    def test_dump_time_sources_ride_along(self, tmp_path):
        box = BlackBox(str(tmp_path), 1)
        box.record(time.time(), 1, "send", {"n": 0})
        box.add_source(
            "faults", lambda: [{"ev": "fault", "kind": "drop", "n": 3}]
        )
        box.dump("request", incident="x")
        lines = [json.loads(s) for s in open(box.path)]
        extra = [r for r in lines if r.get("x_source") == "faults"]
        assert len(extra) == 1
        assert extra[0]["kind"] == "drop" and extra[0]["rank"] == 1
        box.close()

    def test_closed_box_records_and_dumps_nothing(self, tmp_path):
        box = BlackBox(str(tmp_path), 0)
        box.record(time.time(), 1, "send", {"n": 0})
        box.close()
        box.record(time.time(), 2, "send", {"n": 1})
        assert box.stats()["records"] == 0


class TestJournalTee:
    def test_tee_sees_records_the_cap_drops(self, tmp_path):
        """The inversion that makes the black box worth having: the cap
        keeps the run's head on disk, the flight recorder keeps its
        tail in memory — including every record the cap dropped."""
        box = BlackBox(str(tmp_path), 0, max_records=100)
        j = Journal(
            str(tmp_path / "obs_rank0.jsonl"), 0, max_records=2,
            blackbox=box,
        )
        for i in range(6):
            j.event("send", i, n=i)
        assert j.dropped_records == 4
        assert box.stats()["records"] == 6
        j.close()
        # close() dumps the final window and closes the box with it
        ranks = load_dumps(str(tmp_path))
        slot = ranks[(0, 0)]
        assert [r["n"] for r in slot["records"]] == list(range(6))
        assert slot["headers"][-1]["trigger"] == "close"
        assert box.stats()["records"] == 0  # closed


class TestProcessTriggers:
    def test_request_dump_freezes_local_boxes(self, tmp_path):
        box = BlackBox(str(tmp_path), 0)
        box.record(time.time(), 1, "send", {"n": 0})
        incident = request_dump(str(tmp_path), "test-reason")
        assert "test-reason@" in incident
        # requester-local boxes dump synchronously (observer == observed
        # in thread mode), no watcher poll needed
        assert os.path.exists(box.path)
        hdr = json.loads(open(box.path).readline())
        assert hdr["trigger"] == "request" and hdr["incident"] == incident
        req = json.load(
            open(os.path.join(str(tmp_path), "blackbox", REQUEST_FILE))
        )
        assert req["reason"] == "test-reason"
        box.close()

    def test_watcher_sees_foreign_request(self, tmp_path):
        """The cross-process path: a request file written by someone
        else (the supervisor, the alert engine) must be picked up by
        the poller within a couple of intervals."""
        box = BlackBox(str(tmp_path), 0)
        box.record(time.time(), 1, "send", {"n": 0})
        os.makedirs(box.dir, exist_ok=True)
        req = os.path.join(box.dir, REQUEST_FILE)
        with open(req, "w") as f:
            json.dump({"incident": "foreign-1", "reason": "kill"}, f)
        deadline = time.time() + 3.0
        while not os.path.exists(box.path) and time.time() < deadline:
            time.sleep(0.05)
        assert os.path.exists(box.path), "watcher never dumped"
        hdr = json.loads(open(box.path).readline())
        assert hdr["incident"] == "foreign-1"
        box.close()

    @pytest.mark.skipif(
        not hasattr(signal, "SIGUSR1"), reason="needs SIGUSR1"
    )
    def test_dump_signal(self, tmp_path):
        box = BlackBox(str(tmp_path), 0)
        box.record(time.time(), 1, "send", {"n": 0})
        arm_process_triggers(dump_signal="USR1")
        signal.raise_signal(signal.SIGUSR1)
        deadline = time.time() + 2.0
        while not os.path.exists(box.path) and time.time() < deadline:
            time.sleep(0.02)
        assert os.path.exists(box.path)
        assert json.loads(open(box.path).readline())["trigger"] == "signal"
        box.close()

    def test_parse_signal(self):
        from mpit_tpu.obs.blackbox import _parse_signal

        assert _parse_signal("USR1") == signal.SIGUSR1
        assert _parse_signal("SIGUSR1") == signal.SIGUSR1
        assert _parse_signal(str(int(signal.SIGUSR1))) == signal.SIGUSR1
        assert _parse_signal("NOSUCH") is None


class TestServeLifecycleTags:
    def test_latencies_stamped_into_journal_records(self, tmp_path):
        """A dumped serving window must be readable on its face: TTFT/
        e2e/SLO land IN the req_* records, not only in the live plane."""
        from mpit_tpu.models.serving import _ServeObs

        obs = _ServeObs(ObsConfig(dir=str(tmp_path), blackbox=False))
        obs.event("req_enqueue", rid=7, prompt_len=4, slo_ms=0.001)
        obs.event("req_first_token", rid=7)
        obs.event("req_finish", rid=7, tokens=3)
        obs.event("req_enqueue", rid=8, prompt_len=4, slo_ms=1e9)
        obs.event("req_finish", rid=8, tokens=1)
        obs.journal.close()
        recs = {
            (r["ev"], r.get("rid")): r
            for r in read_journal(str(tmp_path / "obs_rank0.jsonl"))
        }
        assert recs[("req_first_token", 7)]["ttft_ms"] >= 0.0
        fin7 = recs[("req_finish", 7)]
        assert fin7["e2e_ms"] >= 0.0 and fin7["slo_miss"] is True
        assert recs[("req_finish", 8)]["slo_miss"] is False


class TestPostmortemGolden:
    """The analyzer over the checked-in incident (3 ranks, rank 2
    SIGKILLed mid-exchange) — the same fixture the lint gate pins."""

    def test_verdict_and_first_mover(self):
        rep = analyze_postmortem(GOLDEN)
        assert rep["verdict"] == "incident"
        mover = rep["first_mover"]
        assert mover["rank"] == 2
        assert mover["source"] == "membership"
        assert "SIGKILL" in mover["why"]

    def test_killed_rank_gets_server_view_rounds(self):
        """Rank 2 left no dump (SIGKILL flushes nothing); its final
        pushes must still appear, reconstructed from the server's recv
        window."""
        rep = analyze_postmortem(GOLDEN)
        entry = rep["exchanges"]["2"]
        assert entry["view"] == "server"
        assert len(entry["pushes"]) == 3
        assert all(p["acked"] for p in entry["pushes"])
        assert "2" not in rep["ranks"]  # truly no dumped window

    def test_surviving_client_rounds_acked_with_phases(self):
        rep = analyze_postmortem(GOLDEN)
        entry = rep["exchanges"]["1"]
        assert [p["n"] for p in entry["pushes"]] == [0, 1, 2, 3, 4]
        assert all(p["acked"] is True for p in entry["pushes"])
        assert all("phases" in p for p in entry["pushes"])
        assert entry["staleness_at_server"]["0"][-1]["staleness"] == 1

    def test_clock_pairing_bounds_skew(self):
        rep = analyze_postmortem(GOLDEN)
        clock = rep["clock"]
        assert clock["paired_messages"] >= 5
        assert clock["skew_median_ms"] is not None

    def test_human_report_renders(self):
        rep = analyze_postmortem(GOLDEN)
        text = format_postmortem(rep)
        assert "INCIDENT" in text
        assert "first-mover: rank 2" in text
        assert "server view" in text
        assert "staleness at server 0" in text

    def test_no_dumps_is_none(self, tmp_path):
        assert analyze_postmortem(str(tmp_path)) is None

    def test_cli_exit_codes(self, tmp_path, capsys):
        assert obs_main(["postmortem", GOLDEN]) == 1
        assert "first-mover: rank 2" in capsys.readouterr().out
        assert obs_main(["postmortem", GOLDEN, "--json"]) == 1
        rep = json.loads(capsys.readouterr().out)
        assert rep["verdict"] == "incident"
        assert obs_main(["postmortem", str(tmp_path)]) == 2

    def test_cli_perfetto_overlay(self, tmp_path, capsys):
        out = str(tmp_path / "incident.json")
        assert obs_main(
            ["postmortem", GOLDEN, "--json", "--perfetto", out]
        ) == 1
        capsys.readouterr()
        trace = json.load(open(out))
        names = {e.get("name") for e in trace["traceEvents"]}
        assert any(
            n and n.startswith("blackbox dump") for n in names
        )


class TestOverheadPin:
    """ISSUE satellite: an armed-but-untriggered flight recorder must
    add < 5% to the journal hot path (with a small absolute escape
    hatch — the journal write is file IO, so 5% of it is sub-µs and
    scheduler noise would dominate a pure ratio)."""

    def test_armed_ring_tee_overhead(self, tmp_path):
        # paired short slices, median of the per-slice deltas: a
        # scheduler burst lands on one slice, not on the median — the
        # differential survives a busy CI box instead of measuring it.
        # The absolute hatch absorbs what remains (5% of the file-IO
        # base is sub-µs — below timer noise on a shared runner).
        n, slices = 500, 24
        bare = Journal(str(tmp_path / "bare.jsonl"), 0)
        box = BlackBox(str(tmp_path), 0, max_records=2048)
        teed = Journal(str(tmp_path / "teed.jsonl"), 0, blackbox=box)
        for i in range(500):  # warmup: page in the file + dict paths
            bare.event("send", i, n=i)
            teed.event("send", i, n=i)
        bases, deltas = [], []
        for _ in range(slices):
            t0 = time.perf_counter()
            for i in range(n):
                bare.event("send", i, n=i)
            b = (time.perf_counter() - t0) / n
            t0 = time.perf_counter()
            for i in range(n):
                teed.event("send", i, n=i)
            bases.append(b)
            deltas.append((time.perf_counter() - t0) / n - b)
        bare.close()
        teed.close()
        base = sorted(bases)[slices // 2]
        delta = sorted(deltas)[slices // 2]
        limit = max(0.05 * base, 3.5e-6)
        assert delta < limit, (
            f"armed black-box tee adds {delta*1e6:.2f}µs/record "
            f"(base {base*1e6:.2f}µs, limit {limit*1e6:.2f}µs)"
        )

    def test_disabled_span_path_untouched(self):
        """Arming boxes must not grow the NULL_SPAN fast path: an
        unwrapped transport still gets the shared no-op."""
        from mpit_tpu.obs import NULL_SPAN, span
        from mpit_tpu.transport import Broker

        tp = Broker(1).transports()[0]
        assert span(tp, "hot") is NULL_SPAN


@pytest.mark.slow
def test_supervisor_kill_yields_cross_rank_postmortem(tmp_path):
    """The acceptance story end-to-end on real OS processes: a seeded
    supervisor kill (SIGKILL — uncatchable) on one of three ranks must
    leave black-box dumps on every survivor, and ``obs postmortem``
    must name the victim as first-mover with reconstructed final
    exchange rounds."""
    out = str(tmp_path / "obs")
    ckpt = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MPIT_OBS_DIR": out,
        "MPIT_ELASTIC_RESPAWN": "1",
        "MPIT_ELASTIC_CKPT_DIR": ckpt,
        "MPIT_ELASTIC_CKPT_EVERY": "3",
        "MPIT_ELASTIC_KILL_EVERY_S": "3",
        "MPIT_ELASTIC_KILL_SEED": "1234",
        "MPIT_ELASTIC_MAX_RESPAWNS": "3",
    })
    env.pop("MPIT_RANK", None)
    env.pop("MPIT_WORLD_SIZE", None)
    r = subprocess.run(
        [sys.executable, "-m", "mpit_tpu.launch", "-n", "3",
         os.path.join(REPO, "examples", "ptest_proc.py"),
         "--model", "mlp", "--steps", "48", "--train-size", "256",
         "--algo", "ps-easgd"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    members = [
        json.loads(line)
        for line in open(os.path.join(out, "membership.jsonl"))
    ]
    killed = {m["rank"] for m in members if m.get("kind") == "kill"}
    assert killed, "seeded killer never fired (machine too fast?)"
    # the supervisor recorded the victim's exit as the kill signal
    exits = [m for m in members if m.get("kind") == "exit"]
    assert any(m.get("signal") == "SIGKILL" for m in exits)
    # every surviving rank froze its window (request trigger or close)
    world = {m["rank"] for m in members if m.get("kind") == "spawn"}
    dumped = {
        key[0] for key in load_dumps(out)
    }
    assert world - killed <= dumped, (world, killed, dumped)
    rep = analyze_postmortem(out)
    assert rep is not None and rep["verdict"] == "incident"
    assert rep["first_mover"]["rank"] in killed
    assert rep["first_mover"]["source"] == "membership"
    rounds = sum(len(e["pushes"]) for e in rep["exchanges"].values())
    assert rounds > 0, "no exchange rounds reconstructed"
