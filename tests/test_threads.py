"""Whole-program concurrency analysis (mpit_tpu.analysis.threads) and the
RT103 vector-clock race sanitizer.

Four layers:

- the MODEL: thread-root discovery and per-access locksets over the real
  package — the named daemon threads must be found, and the PServer hot
  state must carry the lockset the code actually takes;
- the RULES going QUIET: each seeded MPT013/014/015 fixture, with its
  one bug fixed, lints clean (tests/test_analysis.py pins the firing
  direction; this file pins the silence direction);
- the CLI: the ``threads`` subcommand and the ``--only`` rule filter;
- RT103: the sanitizer catches a seeded unsynchronized mutation of live
  PServer state with both stacks, stays silent across a swarm-shaped
  multi-client round, and arms from MPIT_RT_RACE=1.

Plus the lock-hygiene contract: every raw ``threading.Lock/RLock/
Condition`` constructed in the package is either routed through
``make_lock``/``make_condition`` or allowlisted with a reason.
"""

from __future__ import annotations

import ast
import json
import os
import shutil
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from mpit_tpu.analysis import lint
from mpit_tpu.analysis import runtime as rt

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "mpit_tpu"
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"
ALLOWLIST = Path(__file__).resolve().parent / "lock_allowlist.json"


def _model(paths):
    modules = []
    for ap, rel in lint.collect_files(paths):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    project = lint.Project(modules=modules, config=lint.Config())
    return project.threads


@pytest.fixture(scope="module")
def package_model():
    return _model([PKG])


# ------------------------------------------------------------------ model


def test_package_model_discovers_known_roots(package_model):
    names = {r.name for r in package_model.roots}
    # the load-bearing daemons: the PS server loop, the socket reader
    # machinery, the heartbeat, the blackbox watcher, and the live
    # exporter — losing any of these silently blinds MPT013-015
    for expected in (
        "mpit-pserver",
        "mpit-pclient-heartbeat",
        "mpit-blackbox-watch",
        "SocketTransport._accept_loop",
        "SocketTransport._read_loop",
        "LiveExporter._run",
    ):
        assert expected in names, sorted(names)


def test_pserver_center_is_shared_and_locked(package_model):
    """The acceptance enumeration: PServer.center is cross-root shared
    state whose server-side WRITES all hold PServer._lock."""
    states = package_model.owner_state("PServer")
    center = next(
        (pr for s, pr in states.items() if s.name == "center"), None
    )
    assert center is not None, sorted(s.label() for s in states)
    assert len(center) >= 2, "center must be touched from >=2 roots"
    server = center.get("mpit-pserver")
    assert server is not None and server["writes"] > 0
    for ls in server["write_locksets"]:
        assert any("PServer._lock" in l.label() for l in ls), ls


def test_pserver_counts_writes_are_all_locked(package_model):
    states = package_model.owner_state("PServer")
    counts = next(
        (pr for s, pr in states.items() if s.name == "counts"), None
    )
    assert counts is not None
    server = counts.get("mpit-pserver")
    assert server is not None
    for ls in server["write_locksets"]:
        assert any("PServer._lock" in l.label() for l in ls), ls


def test_model_json_shape(package_model):
    doc = package_model.to_json()
    assert doc["roots"] and doc["shared_state"] is not None
    json.dumps(doc)  # the --json contract: serializable as-is


# ------------------------------------------------- rules go quiet when fixed

_FIXES = {
    "fixture_mpt013": (
        "worker.py",
        "    def submit(self, job):\n"
        "        self.pending.append(job)  # BUG: no lock — races with _drain\n",
        "    def submit(self, job):\n"
        "        with self._lock:\n"
        "            self.pending.append(job)\n",
    ),
    "fixture_mpt014": (
        "deadlock.py",
        "        with self._b_lock:  # BUG: opposite order — cycle with _forward\n"
        "            with self._a_lock:\n",
        "        with self._a_lock:\n"
        "            with self._b_lock:\n",
    ),
    "fixture_mpt015": (
        "flusher.py",
        "        with self._lock:\n"
        "            self._flush()  # BUG: the lock spans the blocking write below\n",
        "        with self._lock:\n"
        "            pass\n"
        "        self._flush()\n",
    ),
}


@pytest.mark.parametrize("fixture", sorted(_FIXES))
def test_fixture_goes_quiet_when_fixed(fixture, tmp_path):
    """The other half of the fires-exactly-once contract: applying the
    obvious fix silences the rule (no residual finding survives)."""
    target, bug, fix = _FIXES[fixture]
    dst = tmp_path / fixture
    shutil.copytree(FIXTURES / fixture, dst)
    f = dst / target
    src = f.read_text()
    assert bug in src, "fixture drifted from the test's patch"
    f.write_text(src.replace(bug, fix))
    findings = lint.run_lint([dst], lint.Config(hot_all=True))
    assert findings == [], [x.format() for x in findings]


# -------------------------------------------------------------------- CLI


def _cli(*args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "mpit_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        **kw,
    )


def test_threads_cli_json():
    p = _cli("threads", "--package", "mpit_tpu", "--json")
    assert p.returncode == 0, p.stderr
    doc = json.loads(p.stdout)
    assert any(r["name"] == "mpit-pserver" for r in doc["roots"])
    assert doc["shared_state"]


def test_threads_cli_owner_filter():
    p = _cli("threads", "--package", "mpit_tpu", "--owner", "PServer")
    assert p.returncode == 0, p.stderr
    assert "center" in p.stdout and "PServer._lock" in p.stdout


def test_only_filter_skips_other_rules():
    # the MPT015 fixture under an MPT013-only run: nothing may fire
    fx = str(FIXTURES / "fixture_mpt015")
    p = _cli("--no-baseline", "--only", "MPT013", fx)
    assert p.returncode == 0, p.stdout + p.stderr
    p = _cli("--no-baseline", "--only", "MPT015", fx)
    assert p.returncode == 1
    assert "MPT015" in p.stdout


def test_only_filter_rejects_unknown_rule():
    p = _cli("--no-baseline", "--only", "MPT999", "mpit_tpu")
    assert p.returncode == 2
    assert "unknown rule" in p.stderr


def test_only_filter_in_process():
    findings = lint.run_lint(
        [FIXTURES / "fixture_mpt013"],
        lint.Config(hot_all=True, only_rules=["MPT014"]),
    )
    assert findings == []


# ------------------------------------------------------------------- RT103


def _pserver_world(n_clients):
    from mpit_tpu.parallel.pserver import PServer, spawn_server_thread
    from mpit_tpu.transport import Broker

    broker = Broker(n_clients + 1)
    tps = broker.transports()
    server = PServer(
        tps[0], np.zeros(16, np.float32), num_clients=n_clients, alpha=0.3
    )
    return server, spawn_server_thread(server), tps


def test_rt103_catches_seeded_pserver_race():
    """A rogue thread mutating live server state WITHOUT the server lock
    while real traffic flows: RT103 must report the pair with both
    stacks (the whole point over a plain assertion — you see both
    sides of the interleaving)."""
    from mpit_tpu.parallel.pserver import TAG_HEARTBEAT, TAG_STOP

    with rt.checking(race=True) as ck:
        server, th, tps = _pserver_world(1)

        def rogue():
            for _ in range(100):
                server._note("counts")  # the bug: no server._lock held
                server.counts["heartbeat"] += 1

        rg = threading.Thread(target=rogue, name="rogue-mutator")
        rg.start()
        for _ in range(30):
            tps[1].send(0, TAG_HEARTBEAT, None)
        rg.join()
        tps[1].send(0, TAG_STOP, None)
        th.join(timeout=5)
        assert not th.is_alive() and server.error is None
    races = [f for f in ck.findings if f.rule == "RT103"]
    assert races, [f.format() for f in ck.findings]
    msg = races[0].message
    assert "counts" in msg
    assert msg.count('File "') >= 2, "both stacks must be reported:\n" + msg


def test_rt103_silent_on_multi_client_swarm():
    """Swarm shape: 8 clients hammering fetch/push/heartbeat against one
    live server through the broker — every annotated access is ordered
    by PServer._lock / the mailbox conditions, so RT103 stays silent."""
    from mpit_tpu.parallel.pserver import (
        TAG_FETCH,
        TAG_HEARTBEAT,
        TAG_PARAM,
        TAG_PUSH_EASGD,
        TAG_STOP,
    )

    n = 8
    with rt.checking(race=True) as ck:
        server, th, tps = _pserver_world(n)

        def client(r):
            tp = tps[r]
            for _ in range(5):
                tp.send(0, TAG_FETCH, None)
                center = tp.recv(src=0, tag=TAG_PARAM, timeout=10).payload
                tp.send(0, TAG_PUSH_EASGD, center + 0.01 * r)
                tp.send(0, TAG_HEARTBEAT, None)
            tp.send(0, TAG_STOP, None)

        ts = [
            threading.Thread(target=client, args=(r,))
            for r in range(1, n + 1)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        th.join(timeout=10)
        assert not th.is_alive() and server.error is None
        assert server.counts["push_easgd"] == n * 5
    races = [f for f in ck.findings if f.rule == "RT103"]
    assert races == [], [f.format() for f in races]


def test_rt103_condition_handoff_is_ordered():
    """wait()/notify() through a tracked condition is a happens-before
    edge: producer-consumer over make_condition must not report."""
    with rt.checking(race=True) as ck:
        cv = rt.make_condition("t.cv")
        box = []

        def producer():
            with cv:
                rt.note("t.box", True)
                box.append(1)
                cv.notify()

        def consumer():
            with cv:
                while not box:
                    cv.wait(5.0)
                rt.note("t.box", False)

        tc = threading.Thread(target=consumer)
        tc.start()
        tp_ = threading.Thread(target=producer)
        tp_.start()
        tc.join(5)
        tp_.join(5)
    assert [f for f in ck.findings if f.rule == "RT103"] == []


def test_rt103_arms_from_env():
    """MPIT_RT_RACE=1 arms the sanitizer at import and prints the atexit
    report — the knob chaos_soak.sh's RT103 round greps for."""
    p = subprocess.run(
        [sys.executable, "-c", "import mpit_tpu.analysis.runtime"],
        capture_output=True,
        text=True,
        env={**os.environ, "MPIT_RT_RACE": "1"},
        cwd=REPO,
    )
    assert p.returncode == 0, p.stderr
    assert "vector-clock race sanitizer armed" in p.stderr
    assert "0 finding(s)" in p.stderr


# ---------------------------------------------------------------- hygiene

_RAW_CTORS = {"Lock", "RLock", "Condition"}


def _raw_lock_files():
    """Repo-relative paths of package files that construct a raw
    threading.Lock/RLock/Condition (AST-level: comments and strings
    don't count, aliased imports do)."""
    offenders = set()
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _RAW_CTORS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
            ):
                offenders.add(py.relative_to(REPO).as_posix())
    return offenders


def test_raw_lock_constructors_are_allowlisted():
    """Every raw lock/condition constructor in the package either goes
    through the tracked factory or is in tests/lock_allowlist.json with
    a reason — and the allowlist carries no stale entries."""
    allow = json.loads(ALLOWLIST.read_text())["allowed"]
    offenders = _raw_lock_files()
    unlisted = offenders - set(allow)
    assert not unlisted, (
        f"raw threading.Lock/RLock/Condition in {sorted(unlisted)} — "
        "route through mpit_tpu.analysis.runtime.make_lock/make_condition "
        "or add an allowlist entry with a reason"
    )
    stale = set(allow) - offenders
    assert not stale, f"stale allowlist entries: {sorted(stale)}"
    for path, reason in allow.items():
        assert len(reason) > 20, f"{path}: allowlist reason too thin"
