"""Expert-parallel MoE ≡ per-token dense reference, on the 8-device mesh.

The all_to_all dispatch is pure data movement: with ample capacity the
sharded MoE must equal gate·FFN_expert(token) computed directly; with
tight capacity it must equal the dense reference applying the identical
per-shard overflow rule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# integration tier — excluded from the smoke run (MoE trainer equivalences)
pytestmark = pytest.mark.slow

import mpit_tpu
from conftest import moe_dense_per_shard, run_moe_sharded
from jax.sharding import PartitionSpec as P
from mpit_tpu.ops import init_moe_params, moe_ffn

EP, E, D, F = 8, 16, 16, 32
B, T = 8, 12  # one batch row per device


@pytest.fixture(scope="module")
def topo():
    mpit_tpu.finalize()
    t = mpit_tpu.init(num_workers=EP)
    yield t
    mpit_tpu.finalize()


def _setup(seed=0):
    params = init_moe_params(jax.random.key(seed), D, F, E)
    h = (
        np.random.default_rng(seed)
        .standard_normal((B, T, D))
        .astype(np.float32)
    )
    return params, h


class TestMoE:
    def test_matches_per_token_expert_choice_ample_capacity(self, topo):
        """No drops: every token must get exactly gate * its expert's FFN."""
        params, h = _setup()
        got = run_moe_sharded(topo, params, h, float(E))
        # direct per-token computation, no capacity machinery at all
        h2 = h.reshape(-1, D)
        logits = h2 @ np.asarray(params["router"])
        probs = jax.nn.softmax(logits, axis=-1)
        expert = np.argmax(probs, axis=-1)
        gate = np.take_along_axis(
            np.asarray(probs), expert[:, None], axis=1
        )[:, 0]
        want = np.stack([
            gate[i] * np.asarray(
                jax.nn.gelu(
                    h2[i] @ params["w_up"][expert[i]]
                    + params["b_up"][expert[i]]
                )
                @ params["w_down"][expert[i]]
                + params["b_down"][expert[i]]
            )
            for i in range(len(h2))
        ]).reshape(B, T, D)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_matches_dense_reference_with_drops(self, topo):
        """Tight capacity: per-shard overflow must equal the dense
        reference run shard-by-shard with the same local token count."""
        params, h = _setup(seed=1)
        cf = 0.5  # forces drops
        got = run_moe_sharded(topo, params, h, cf)
        want = moe_dense_per_shard(params, h, cf, EP)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
        # and drops actually happened (otherwise the test proves nothing)
        ample = run_moe_sharded(topo, params, h, float(E))
        assert not np.allclose(got, ample)

    def test_top2_matches_per_token_ample_capacity(self, topo):
        """Top-2: every token gets its two experts' outputs mixed by the
        renormalized gates (the GShard rule)."""
        params, h = _setup(seed=3)
        got = run_moe_sharded(topo, params, h, float(E), top_k=2)
        h2 = h.reshape(-1, D)
        probs = np.asarray(
            jax.nn.softmax(h2 @ np.asarray(params["router"]), axis=-1)
        )
        want = np.zeros_like(h2)
        for i in range(len(h2)):
            idx = np.argsort(-probs[i])[:2]
            g = probs[i][idx] / probs[i][idx].sum()
            for gw, ex in zip(g, idx):
                want[i] += gw * np.asarray(
                    jax.nn.gelu(
                        h2[i] @ params["w_up"][ex] + params["b_up"][ex]
                    )
                    @ params["w_down"][ex]
                    + params["b_down"][ex]
                )
        np.testing.assert_allclose(
            got, want.reshape(B, T, D), rtol=2e-4, atol=2e-4
        )

    def test_top2_matches_dense_reference_with_drops(self, topo):
        """Tight capacity, top-2: the sharded op equals the dense
        reference per shard — including the choice-major priority rule
        (first choices claim slots before any second choice)."""
        params, h = _setup(seed=4)
        cf = 0.75  # tight enough that second choices overflow
        got = run_moe_sharded(topo, params, h, cf, top_k=2)
        want = moe_dense_per_shard(params, h, cf, EP, top_k=2)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
        ample = run_moe_sharded(topo, params, h, float(E), top_k=2)
        assert not np.allclose(got, ample)

    def test_aux_sharded_matches_dense_global(self, topo):
        """The pmean-ed sharded aux equals the dense aux on the full
        batch (ample capacity so the drop stat agrees too)."""
        from jax.sharding import PartitionSpec as P

        from mpit_tpu.ops import moe_ffn, moe_ffn_dense_reference

        params, h = _setup(seed=5)
        axis = topo.worker_axis
        spec = {k: (P() if k == "router" else P(axis)) for k in params}
        fn = jax.jit(jax.shard_map(
            lambda p, x: moe_ffn(
                p, x, axis=axis, capacity_factor=float(E), top_k=2,
                with_aux=True,
            )[1],
            mesh=topo.mesh, in_specs=(spec, P(axis)), out_specs=P(),
            check_vma=False,
        ))
        got = {k: float(v) for k, v in fn(params, h).items()}
        _, want = moe_ffn_dense_reference(
            jax.tree.map(jnp.asarray, params), jnp.asarray(h),
            capacity_factor=float(E), top_k=2, with_aux=True,
        )
        for k in got:
            np.testing.assert_allclose(
                got[k], float(want[k]), rtol=1e-5, atol=1e-6, err_msg=k
            )

    def test_balance_loss_detects_and_fixes_skew(self, topo):
        """A router collapsed onto one expert scores a high balance loss
        and drops tokens; descending the balance loss alone re-spreads
        the routing and recovers the dropped tokens."""
        from mpit_tpu.ops.moe import moe_ffn_dense_reference

        params, h = _setup(seed=6)
        params = dict(params)
        skewed = np.asarray(params["router"]).copy()
        skewed[:, 0] = 5.0  # every token's top choice becomes expert 0
        params["router"] = jnp.asarray(skewed)
        cf = 1.5

        def aux_of(p):
            return moe_ffn_dense_reference(
                p, jnp.asarray(h), capacity_factor=cf, top_k=1,
                with_aux=True,
            )[1]

        before = aux_of(params)
        assert float(before["balance"]) > 2.0  # uniform scores 1.0
        assert float(before["dropped_frac"]) > 0.3

        grad_fn = jax.jit(jax.grad(
            lambda r: aux_of({**params, "router": r})["balance"]
        ))
        r = params["router"]
        for _ in range(250):
            r = r - 2.0 * grad_fn(r)
        after = aux_of({**params, "router": r})
        assert float(after["balance"]) < float(before["balance"]) * 0.6
        assert float(after["dropped_frac"]) < float(before["dropped_frac"])

    def test_gradients_flow_to_local_experts(self, topo):
        """grad through the all_to_all pair lands on the expert weights."""
        params, h = _setup(seed=2)
        axis = topo.worker_axis
        shard_spec = {
            "router": P(),
            "w_up": P(axis), "b_up": P(axis),
            "w_down": P(axis), "b_down": P(axis),
        }

        def grads_fn(p, x):
            def local_loss(q):
                out = moe_ffn(q, x, axis=axis, capacity_factor=float(E))
                return (out.astype(jnp.float32) ** 2).mean()

            g = jax.grad(local_loss)(p)
            # grad locally, reduce after (differentiating through a psum
            # scales cotangents by the axis size); replicated router grad
            # sums every shard's contribution
            g["router"] = jax.lax.psum(g["router"], axis)
            return g

        g = jax.jit(jax.shard_map(
            grads_fn,
            mesh=topo.mesh,
            in_specs=(shard_spec, P(axis)),
            out_specs=shard_spec,
            check_vma=False,
        ))(params, h)
        assert float(jnp.abs(g["w_up"]).sum()) > 0
        assert float(jnp.abs(g["router"]).sum()) > 0


class TestMoETrainer:
    """MoEParallelTrainer: the op made load-bearing in a trainable LM."""

    def _trainer(self, topo, experts=16, cf=16.0, **model_kw):
        import optax

        from mpit_tpu.models.transformer import TransformerLM
        from mpit_tpu.parallel import MoEParallelTrainer

        model = TransformerLM(
            vocab_size=31, num_layers=2, d_model=32, num_heads=4,
            max_len=16, compute_dtype=jnp.float32,
            moe_experts=experts, moe_axis=topo.worker_axis,
            moe_capacity_factor=cf, **model_kw,
        )
        return MoEParallelTrainer(
            model, optax.sgd(0.1, momentum=0.9), topo, donate_state=False
        )

    def _tokens(self, n=8, t=16, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.integers(0, 31, (n, t)).astype(np.int32)
        return x, np.roll(x, -1, axis=1).astype(np.int32)

    def test_w_invariance_with_ample_capacity(self):
        """No drops -> the W=8 expert-sharded trajectory equals W=1 (all
        experts local) on the same global batch."""
        results = {}
        for w in (8, 1):
            mpit_tpu.finalize()
            topo = mpit_tpu.init(num_workers=w)
            tr = self._trainer(topo)
            x, y = self._tokens()
            state = tr.init_state(jax.random.key(0), x[: max(8 // w, 1)])
            losses = []
            for _ in range(3):
                state, m = tr.step(state, x, y)
                losses.append(float(m["loss"]))
            results[w] = (
                losses, jax.tree.map(np.asarray, jax.device_get(state.params))
            )
            mpit_tpu.finalize()
        np.testing.assert_allclose(
            results[8][0], results[1][0], rtol=1e-4, atol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-4
            ),
            results[8][1], results[1][1],
        )

    def test_w_invariance_top2_with_aux_losses(self):
        """Top-2 routing with balance + z losses in the objective is
        still exactly mesh-width-invariant (the aux stats are pmean-ed
        inside the op, so W=8 and W=1 optimize the identical loss)."""
        results = {}
        for w in (8, 1):
            mpit_tpu.finalize()
            topo = mpit_tpu.init(num_workers=w)
            tr = self._trainer(
                topo, moe_top_k=2, moe_balance_weight=0.02,
                moe_zloss_weight=1e-3,
            )
            x, y = self._tokens(seed=3)
            state = tr.init_state(jax.random.key(0), x[: max(8 // w, 1)])
            losses = []
            for _ in range(3):
                state, m = tr.step(state, x, y)
                losses.append(
                    (float(m["loss"]), float(m["moe_balance"]))
                )
            results[w] = (
                losses,
                jax.tree.map(np.asarray, jax.device_get(state.params)),
            )
            mpit_tpu.finalize()
        np.testing.assert_allclose(
            results[8][0], results[1][0], rtol=1e-4, atol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-4
            ),
            results[8][1], results[1][1],
        )

    def test_aux_metrics_reported(self):
        """Every step reports the routing-quality stats, weighted into
        the objective or not."""
        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        tr = self._trainer(topo)
        x, y = self._tokens()
        state = tr.init_state(jax.random.key(0), x[:1])
        _, m = tr.step(state, x, y)
        assert {"moe_balance", "moe_zloss", "moe_dropped_frac"} <= set(m)
        assert float(m["moe_balance"]) >= 1.0 - 1e-3
        assert 0.0 <= float(m["moe_dropped_frac"]) <= 1.0
        mpit_tpu.finalize()

    def test_converges(self):
        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        tr = self._trainer(topo, cf=4.0)
        stream = np.arange(8 * 16 * 2, dtype=np.int32) % 31
        x = stream.reshape(-1, 16)[:8]
        y = np.roll(x, -1, axis=1).astype(np.int32)
        state = tr.init_state(jax.random.key(1), x[:1])
        first = last = None
        for _ in range(40):
            state, m = tr.step(state, x, y)
            first = first if first is not None else float(m["loss"])
            last = float(m["loss"])
        assert last < first * 0.5, (first, last)
        acc, _ = tr.evaluate(state, x, y)
        assert acc > 0.5
        # expert weights really live sharded
        wup = state.params["Block_0"]["moe_w_up"]
        assert wup.sharding.spec[0] == topo.worker_axis
        mpit_tpu.finalize()

    def test_cross_leaf_optimizer_rejected(self):
        """clip_by_global_norm couples leaves through the global norm —
        inside shard_map on device-varying expert grads that silently
        desynchronizes replicated params, so the constructor refuses it.
        Per-leaf clipping composes fine."""
        import optax

        from mpit_tpu.models.transformer import TransformerLM
        from mpit_tpu.parallel import MoEParallelTrainer

        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        model = TransformerLM(
            vocab_size=31, max_len=16, moe_experts=16,
            moe_axis=topo.worker_axis,
        )
        with pytest.raises(ValueError, match="ELEMENTWISE"):
            MoEParallelTrainer(
                model,
                optax.chain(
                    optax.clip_by_global_norm(1.0), optax.sgd(0.1)
                ),
                topo,
            )
        # conditionally-coupled transforms are caught too: apply_if_finite
        # skips the update for ALL leaves when ANY leaf goes non-finite
        with pytest.raises(ValueError, match="ELEMENTWISE"):
            MoEParallelTrainer(
                model, optax.apply_if_finite(optax.sgd(0.1), 5), topo
            )
        # and a global-norm threshold well above the old probe magnitude
        with pytest.raises(ValueError, match="ELEMENTWISE"):
            MoEParallelTrainer(
                model,
                optax.chain(
                    optax.clip_by_global_norm(5e4), optax.sgd(0.1)
                ),
                topo,
            )
        # per-leaf clip and adam pass the probe
        MoEParallelTrainer(
            model, optax.chain(optax.clip(1.0), optax.adam(1e-3)), topo
        )
        mpit_tpu.finalize()

    def test_validation(self):
        import optax

        from mpit_tpu.models.transformer import TransformerLM
        from mpit_tpu.parallel import MoEParallelTrainer

        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        dense = TransformerLM(vocab_size=31, max_len=16)
        with pytest.raises(ValueError, match="moe_experts > 0"):
            MoEParallelTrainer(dense, optax.sgd(0.1), topo)
        wrong_axis = TransformerLM(
            vocab_size=31, max_len=16, moe_experts=16, moe_axis="ep"
        )
        with pytest.raises(ValueError, match="worker axis"):
            MoEParallelTrainer(wrong_axis, optax.sgd(0.1), topo)
        indivisible = TransformerLM(
            vocab_size=31, max_len=16, moe_experts=12, moe_axis="dp"
        )
        with pytest.raises(ValueError, match="not divisible"):
            MoEParallelTrainer(indivisible, optax.sgd(0.1), topo)
        mpit_tpu.finalize()


class TestClipNorm:
    """clip_norm: the mesh-correct global-norm clip the elementwise probe
    exists to protect — equal to optax.clip_by_global_norm on the dense
    model, and mesh-width-invariant on the sharded one."""

    def _model(self, axis):
        from mpit_tpu.models.transformer import TransformerLM

        return TransformerLM(
            vocab_size=31, num_layers=2, d_model=32, num_heads=4,
            max_len=16, compute_dtype=jnp.float32,
            moe_experts=16, moe_axis=axis, moe_capacity_factor=16.0,
        )

    def test_clip_matches_optax_dense_and_w_invariant(self):
        import optax

        from mpit_tpu.parallel import MoEParallelTrainer
        from mpit_tpu.parallel.common import cross_entropy_loss

        rng = np.random.default_rng(5)
        x = rng.integers(0, 31, (8, 16)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        c = 0.5

        # ground truth: dense model + the optax transform itself
        mpit_tpu.finalize()
        topo1 = mpit_tpu.init(num_workers=1)
        dense = self._model(None)
        params = dense.init(
            jax.random.key(0), jnp.asarray(x[:8])
        )["params"]
        opt = optax.chain(optax.clip_by_global_norm(c), optax.sgd(0.1))
        opt_state = opt.init(params)

        def loss_fn(p):
            return cross_entropy_loss(
                dense.apply({"params": p}, jnp.asarray(x)), jnp.asarray(y)
            )

        g0 = jax.grad(loss_fn)(params)
        assert float(optax.global_norm(g0)) > c, "clip would not engage"
        ref_losses, ref_params = [], params
        for _ in range(3):
            loss, g = jax.value_and_grad(loss_fn)(ref_params)
            upd, opt_state = opt.update(g, opt_state, ref_params)
            ref_params = optax.apply_updates(ref_params, upd)
            ref_losses.append(float(loss))
        ref_params = jax.tree.map(np.asarray, jax.device_get(ref_params))
        mpit_tpu.finalize()

        got = {}
        for w in (1, 8):
            topo = mpit_tpu.init(num_workers=w)
            tr = MoEParallelTrainer(
                self._model(topo.worker_axis), optax.sgd(0.1), topo,
                donate_state=False, clip_norm=c,
            )
            st = tr.init_state(jax.random.key(0), x[: max(8 // w, 1)])
            losses = []
            for _ in range(3):
                st, m = tr.step(st, x, y)
                losses.append(float(m["loss"]))
            got[w] = (
                losses, jax.tree.map(np.asarray, jax.device_get(st.params))
            )
            mpit_tpu.finalize()

        for w in (1, 8):
            np.testing.assert_allclose(
                got[w][0], ref_losses, rtol=1e-4, atol=1e-5
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=3e-4, atol=3e-4
                ),
                got[w][1], ref_params,
            )

    def test_clip_validation(self):
        import optax

        from mpit_tpu.parallel import MoEParallelTrainer

        mpit_tpu.finalize()
        topo = mpit_tpu.init()
        with pytest.raises(ValueError, match="clip_norm"):
            MoEParallelTrainer(
                self._model(topo.worker_axis), optax.sgd(0.1), topo,
                clip_norm=-1.0,
            )
        mpit_tpu.finalize()
