"""Expert-parallel MoE ≡ per-token dense reference, on the 8-device mesh.

The all_to_all dispatch is pure data movement: with ample capacity the
sharded MoE must equal gate·FFN_expert(token) computed directly; with
tight capacity it must equal the dense reference applying the identical
per-shard overflow rule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpit_tpu
from conftest import moe_dense_per_shard, run_moe_sharded
from jax.sharding import PartitionSpec as P
from mpit_tpu.ops import init_moe_params, moe_ffn

EP, E, D, F = 8, 16, 16, 32
B, T = 8, 12  # one batch row per device


@pytest.fixture(scope="module")
def topo():
    mpit_tpu.finalize()
    t = mpit_tpu.init(num_workers=EP)
    yield t
    mpit_tpu.finalize()


def _setup(seed=0):
    params = init_moe_params(jax.random.key(seed), D, F, E)
    h = (
        np.random.default_rng(seed)
        .standard_normal((B, T, D))
        .astype(np.float32)
    )
    return params, h


class TestMoE:
    def test_matches_per_token_expert_choice_ample_capacity(self, topo):
        """No drops: every token must get exactly gate * its expert's FFN."""
        params, h = _setup()
        got = run_moe_sharded(topo, params, h, float(E))
        # direct per-token computation, no capacity machinery at all
        h2 = h.reshape(-1, D)
        logits = h2 @ np.asarray(params["router"])
        probs = jax.nn.softmax(logits, axis=-1)
        expert = np.argmax(probs, axis=-1)
        gate = np.take_along_axis(
            np.asarray(probs), expert[:, None], axis=1
        )[:, 0]
        want = np.stack([
            gate[i] * np.asarray(
                jax.nn.gelu(
                    h2[i] @ params["w_up"][expert[i]]
                    + params["b_up"][expert[i]]
                )
                @ params["w_down"][expert[i]]
                + params["b_down"][expert[i]]
            )
            for i in range(len(h2))
        ]).reshape(B, T, D)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_matches_dense_reference_with_drops(self, topo):
        """Tight capacity: per-shard overflow must equal the dense
        reference run shard-by-shard with the same local token count."""
        params, h = _setup(seed=1)
        cf = 0.5  # forces drops
        got = run_moe_sharded(topo, params, h, cf)
        want = moe_dense_per_shard(params, h, cf, EP)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
        # and drops actually happened (otherwise the test proves nothing)
        ample = run_moe_sharded(topo, params, h, float(E))
        assert not np.allclose(got, ample)

    def test_gradients_flow_to_local_experts(self, topo):
        """grad through the all_to_all pair lands on the expert weights."""
        params, h = _setup(seed=2)
        axis = topo.worker_axis
        shard_spec = {
            "router": P(),
            "w_up": P(axis), "b_up": P(axis),
            "w_down": P(axis), "b_down": P(axis),
        }

        def grads_fn(p, x):
            def local_loss(q):
                out = moe_ffn(q, x, axis=axis, capacity_factor=float(E))
                return (out.astype(jnp.float32) ** 2).mean()

            g = jax.grad(local_loss)(p)
            # grad locally, reduce after (differentiating through a psum
            # scales cotangents by the axis size); replicated router grad
            # sums every shard's contribution
            g["router"] = jax.lax.psum(g["router"], axis)
            return g

        g = jax.jit(jax.shard_map(
            grads_fn,
            mesh=topo.mesh,
            in_specs=(shard_spec, P(axis)),
            out_specs=shard_spec,
            check_vma=False,
        ))(params, h)
        assert float(jnp.abs(g["w_up"]).sum()) > 0
        assert float(jnp.abs(g["router"]).sum()) > 0
