"""goptim math + EASGD/Downpour trainer tests.

Covers what SURVEY.md §4 prescribes beyond the reference's smoke-only
strategy: EASGD fixed-point convergence (clients and center agree at the
optimum under elastic coupling) and optimizer-math unit checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import mpit_tpu
from mpit_tpu import goptim
from mpit_tpu.data import Batches, load_mnist
from mpit_tpu.models import MLP
from mpit_tpu.parallel import DownpourTrainer, EASGDTrainer


class TestGoptimMath:
    def test_elastic_client_move(self):
        p = {"w": jnp.array([2.0, 4.0])}
        c = {"w": jnp.array([0.0, 0.0])}
        out = goptim.elastic_client_move(p, c, alpha=0.5)
        np.testing.assert_allclose(out["w"], [1.0, 2.0])

    def test_easgd_round_under_spmd(self, topo8):
        """Center moves toward the client mean; clients toward the center."""

        def body(p, c):
            new_p, new_c = goptim.easgd_round(p[0], c, alpha=0.1, axis_name="dp")
            return new_p[None], new_c

        f = jax.jit(
            jax.shard_map(
                body,
                mesh=topo8.mesh,
                in_specs=(P("dp"), P()),
                out_specs=(P("dp"), P()),
                check_vma=False,
            )
        )
        params = jnp.arange(8.0)  # worker i holds value i
        center = jnp.zeros(())
        new_p, new_c = f(params, center)
        # center: 0 + 0.1 * sum(i - 0) = 2.8
        np.testing.assert_allclose(float(new_c), 2.8, rtol=1e-6)
        # client i: i - 0.1*(i - 0) = 0.9 i (old center used)
        np.testing.assert_allclose(np.asarray(new_p), 0.9 * np.arange(8.0), rtol=1e-6)

    def test_downpour_push_average_vs_sum(self, topo8):
        def body_avg(c, d):
            return goptim.downpour_push(c, d[0], "dp", average=True)

        def body_sum(c, d):
            return goptim.downpour_push(c, d[0], "dp", average=False)

        deltas = jnp.arange(8.0)
        center = jnp.full((), 1.0)
        favg = jax.jit(
            jax.shard_map(
                body_avg, mesh=topo8.mesh, in_specs=(P(), P("dp")),
                out_specs=P(), check_vma=False,
            )
        )
        fsum = jax.jit(
            jax.shard_map(
                body_sum, mesh=topo8.mesh, in_specs=(P(), P("dp")),
                out_specs=P(), check_vma=False,
            )
        )
        np.testing.assert_allclose(float(favg(center, deltas)), 1.0 + 3.5)
        np.testing.assert_allclose(float(fsum(center, deltas)), 1.0 + 28.0)


class TestEASGDFixedPoint:
    def test_quadratic_converges_to_shared_minimum(self, topo8):
        """Workers with *different* quadratic minima: EASGD's consensus center
        must converge to the average minimizer (the EASGD paper's consensus
        property), and clients must agree with the center."""
        # per-worker target encoded via the batch: loss = ||p - target||^2
        def loss_fn(params, x, y):
            del y
            return jnp.sum((params["p"] - x[0]) ** 2)

        trainer = EASGDTrainer(
            model=None,
            optimizer=optax.sgd(0.05),
            topo=topo8,
            loss_fn=loss_fn,
            alpha=0.05,
            tau=5,
            donate_state=False,
        )
        params0 = {"p": jnp.zeros((2,))}
        state = trainer.init_state(None, params=params0)

        targets = np.stack(
            [np.full((2,), float(i)) for i in range(8)]
        ).astype(np.float32)  # worker i pulls toward i
        # every local step uses the same per-worker target "batch"
        x_round = np.tile(targets.reshape(1, 8, 1, 2), (5, 1, 1, 1)).reshape(
            5, 8, 2
        )
        y_round = np.zeros((5, 8, 1), np.float32)

        for _ in range(200):
            state, _ = trainer.step(state, x_round, y_round)

        center = np.asarray(state.center["p"])
        workers = np.asarray(state.worker_params["p"])  # (8, 2)
        # consensus: center ≈ mean of targets = 3.5
        np.testing.assert_allclose(center, [3.5, 3.5], atol=0.2)
        # elastic equilibrium: worker i sits between its target and center,
        # and the worker MEAN equals the center
        np.testing.assert_allclose(workers.mean(0), center, atol=0.2)
        assert workers[0, 0] < workers[7, 0]  # heterogeneity preserved

    def test_alpha_default_follows_paper_rule(self, topo8):
        t = EASGDTrainer(
            model=None,
            optimizer=optax.sgd(0.1),
            topo=topo8,
            loss_fn=lambda p, x, y: jnp.sum(p["p"] ** 2),
        )
        assert t.alpha == pytest.approx(0.9 / 8)


class TestTrainersEndToEnd:
    @pytest.fixture(scope="class")
    def mnist(self):
        return load_mnist(synthetic_train=2048, synthetic_test=512)

    def test_easgd_trains_mnist(self, topo8, mnist):
        x_tr, y_tr, x_te, y_te = mnist
        model = MLP(compute_dtype=jnp.float32)
        trainer = EASGDTrainer(
            model, optax.sgd(0.05, momentum=0.9), topo8, tau=4
        )
        state = trainer.init_state(jax.random.key(0), x_tr[:2])
        batches = Batches(x_tr, y_tr, global_batch=256, seed=0)
        state, metrics = trainer.fit(batches, state, epochs=4)
        acc = trainer.evaluate(state, x_te, y_te, batch=256)
        assert acc > 0.9, f"EASGD center failed to learn: acc={acc}"
        assert int(state.round) == 4 * (2048 // 256) // 4

    def test_downpour_trains_mnist(self, topo8, mnist):
        x_tr, y_tr, x_te, y_te = mnist
        model = MLP(compute_dtype=jnp.float32)
        trainer = DownpourTrainer(
            model, optax.sgd(0.05, momentum=0.9), topo8, tau=4
        )
        state = trainer.init_state(jax.random.key(0), x_tr[:2])
        batches = Batches(x_tr, y_tr, global_batch=256, seed=0)
        state, metrics = trainer.fit(batches, state, epochs=4)
        acc = trainer.evaluate(state, x_te, y_te, batch=256)
        assert acc > 0.9, f"Downpour center failed to learn: acc={acc}"

    def test_downpour_stale_still_trains(self, topo8, mnist):
        x_tr, y_tr, x_te, y_te = mnist
        model = MLP(compute_dtype=jnp.float32)
        # stable delayed-gradient regime: no momentum, small step. Larger
        # lr/staleness genuinely oscillates — that pathology is the point of
        # the knob, not a bug (delay-D gradient descent needs step ∝ 1/D).
        trainer = DownpourTrainer(
            model,
            optax.sgd(0.02),
            topo8,
            tau=4,
            staleness=1,
        )
        state = trainer.init_state(jax.random.key(0), x_tr[:2])
        batches = Batches(x_tr, y_tr, global_batch=256, seed=0)
        # staleness=2 wastes the first 2 rounds' pulls; give it more rounds
        state, _ = trainer.fit(batches, state, epochs=8)
        acc = trainer.evaluate(state, x_te, y_te, batch=256)
        assert acc > 0.85, f"stale Downpour failed to learn: acc={acc}"

    def test_downpour_with_server_optimizer(self, topo8, mnist):
        x_tr, y_tr, x_te, y_te = mnist
        model = MLP(compute_dtype=jnp.float32)
        trainer = DownpourTrainer(
            model,
            optax.sgd(0.05, momentum=0.9),
            topo8,
            tau=4,
            server_optimizer=optax.sgd(1.0),
        )
        state = trainer.init_state(jax.random.key(0), x_tr[:2])
        batches = Batches(x_tr, y_tr, global_batch=256, seed=0)
        state, _ = trainer.fit(batches, state, epochs=4)
        acc = trainer.evaluate(state, x_te, y_te, batch=256)
        assert acc > 0.9

    def test_round_batch_shape_validation(self, topo8):
        model = MLP(compute_dtype=jnp.float32)
        trainer = EASGDTrainer(model, optax.sgd(0.1), topo8, tau=3)
        x = np.zeros((2, 64, 28, 28, 1), np.float32)  # wrong tau
        y = np.zeros((2, 64), np.int32)
        with pytest.raises(ValueError, match="need 3 stacked batches"):
            trainer.step(trainer.init_state(jax.random.key(0), x[0, :2]), x, y)


class TestCompressedExchange:
    """bf16-compressed exchange collective (goptim.summed_client_diffs):
    halves the psum's bytes; the perturbation must stay a bounded rounding
    error on the diffs, not drift of the full-precision state."""

    def test_round_matches_f32_within_bf16_tolerance(self, topo8):
        def body(p, c):
            exact = goptim.easgd_round(p[0], c, 0.1, "dp")
            comp = goptim.easgd_round(
                p[0], c, 0.1, "dp", compress_dtype=jnp.bfloat16
            )
            return (exact[0][None], exact[1], comp[0][None], comp[1])

        f = jax.jit(
            jax.shard_map(
                body, mesh=topo8.mesh,
                in_specs=(P("dp"), P()),
                out_specs=(P("dp"), P(), P("dp"), P()),
                check_vma=False,
            )
        )
        rng = np.random.default_rng(0)
        params = jnp.asarray(rng.normal(0, 1, (8, 1024)), jnp.float32)
        center = jnp.asarray(rng.normal(0, 1, 1024), jnp.float32)
        pe, ce, pc, cc = f(params, center)
        # outputs stay f32
        assert pc.dtype == jnp.float32 and cc.dtype == jnp.float32
        # client move has no collective: identical
        np.testing.assert_array_equal(np.asarray(pe), np.asarray(pc))
        # center move: bf16 has ~8 mantissa bits -> relative error ~1/256
        np.testing.assert_allclose(
            np.asarray(cc), np.asarray(ce), rtol=2e-2, atol=2e-2
        )
        assert np.any(np.asarray(cc) != np.asarray(ce))  # really compressed

    def test_easgd_trains_with_bf16_exchange(self, topo8):
        x_tr, y_tr, x_te, y_te = load_mnist(
            synthetic_train=2048, synthetic_test=512
        )
        model = MLP(compute_dtype=jnp.float32)
        trainer = EASGDTrainer(
            model, optax.sgd(0.05, momentum=0.9), topo8, tau=4,
            exchange_dtype=jnp.bfloat16,
        )
        state = trainer.init_state(jax.random.key(0), x_tr[:2])
        batches = Batches(x_tr, y_tr, global_batch=256, seed=0)
        state, _ = trainer.fit(batches, state, epochs=4)
        acc = trainer.evaluate(state, x_te, y_te, batch=256)
        assert acc > 0.9, f"bf16-exchange EASGD failed to learn: acc={acc}"
