"""Quantized collectives (docs/WIRE.md "Quantized collectives"):
known-answer exactness for the EQuARX-style int8/bf16 allreduce on 2-
and 4-way CPU meshes, the quantized reduce-scatter hook, and the
error-feedback convergence pin.

The known-answer inputs are CONSTRUCTED to quantize exactly on both
hops: every per-worker block holds integer values with absmax 127
(scale = 1, codes = values), and every reduced block's absmax is an
exact power-of-two multiple of 127 (scale = 2 or 4 exactly in f32, sums
all divisible) — so the quantized allreduce must equal the raw sum to
the bit, isolating wiring mistakes (row routing, scale transport,
padding) from rounding noise."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mpit_tpu
from mpit_tpu.comm import collectives as coll


def _mesh_fn(topo, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=topo.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    )


def _topo(workers):
    return mpit_tpu.init(num_workers=workers)


class TestKnownAnswerAllreduce:
    def test_int8_sum_exact_2way(self):
        topo = _topo(2)
        # per-worker rows (chunk size 4) each have absmax 127 → scale 1;
        # reduced chunks have absmax 254 → scale exactly 2, sums even
        x = np.array(
            [
                [127, 2, -4, 100, 127, 2, 64, -32],
                [127, 4, -2, -90, 127, 2, -64, 32],
            ],
            np.float32,
        )
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.SUM, quant="int8"),
            P("dp", None),
            P(),
        )
        np.testing.assert_array_equal(np.asarray(f(x)), x.sum(axis=0))

    def test_int8_avg_exact_2way(self):
        topo = _topo(2)
        x = np.array(
            [
                [127, 2, -4, 100, 127, 2, 64, -32],
                [127, 4, -2, -90, 127, 2, -64, 32],
            ],
            np.float32,
        )
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.AVG, quant="int8"),
            P("dp", None),
            P(),
        )
        # mean divides BEFORE the second quantization: reduced absmax is
        # back to 127, scale 1, integer codes — exact again
        np.testing.assert_array_equal(np.asarray(f(x)), x.mean(axis=0))

    def test_int8_sum_exact_4way(self):
        topo = _topo(4)
        # 4 identical workers: every 2-element block holds a ±127 →
        # scale 1; reduced blocks are 4x → absmax 508, scale exactly 4
        row = np.array([127, 3, -127, 5, 127, -7, -127, 9], np.float32)
        x = np.tile(row, (4, 1))
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.SUM, quant="int8"),
            P("dp", None),
            P(),
        )
        np.testing.assert_array_equal(np.asarray(f(x)), 4 * row)

    def test_int8_sum_exact_with_padding(self):
        topo = _topo(2)
        # length 5 pads to 6 (chunk 3); the pad element quantizes to
        # code 0 and must not leak into the truncated output
        x = np.array(
            [[127, 2, -4, 127, 2], [127, 4, -2, 127, 2]], np.float32
        )
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.SUM, quant="int8"),
            P("dp", None),
            P(),
        )
        np.testing.assert_array_equal(np.asarray(f(x)), x.sum(axis=0))

    def test_bf16_sum_exact_2way(self):
        topo = _topo(2)
        # all contributions AND sums exactly representable in bf16
        x = np.array(
            [
                [1, 2, 3, 4, 100, 0.5, -8, 16],
                [5, -2, 1, 4, 28, 0.5, 8, -16],
            ],
            np.float32,
        )
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.SUM, quant="bf16"),
            P("dp", None),
            P(),
        )
        np.testing.assert_array_equal(np.asarray(f(x)), x.sum(axis=0))

    def test_int8_random_error_bounded(self):
        topo = _topo(4)
        rng = np.random.default_rng(5)
        x = rng.standard_normal((4, 256)).astype(np.float32)
        f = _mesh_fn(
            topo,
            lambda s: coll.allreduce(s[0], coll.SUM, quant="int8"),
            P("dp", None),
            P(),
        )
        got = np.asarray(f(x))
        want = x.sum(axis=0)
        # per-hop bound: W first-hop roundings at ≤ scale1/2 each plus
        # one second-hop rounding at ≤ scale2/2
        s1 = np.abs(x).max() / 127.0
        s2 = np.abs(want).max() / 127.0
        assert np.max(np.abs(got - want)) <= 4 * s1 / 2 + s2 / 2 + 1e-6

    def test_pytree_and_dtype_preserved(self):
        topo = _topo(2)
        # blocks are chunk-sized (leaf_size / W): every block carries a
        # ±127 (scale 1) and reduced blocks hit exact power-of-two
        # scales — "b" has single-element blocks, so values are
        # 127·2^k exactly
        tree = {
            "a": np.array([[127, 2, -4, 127]] * 2, np.float32),
            "b": np.array([[127, 254]] * 2, np.float32),
        }
        spec = {"a": P("dp", None), "b": P("dp", None)}
        f = _mesh_fn(
            topo,
            lambda t: coll.allreduce(
                {k: v[0] for k, v in t.items()}, coll.SUM, quant="int8"
            ),
            (spec,),
            {"a": P(), "b": P()},
        )
        out = f(tree)
        assert out["a"].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(out["a"]), tree["a"].sum(axis=0)
        )
        np.testing.assert_array_equal(
            np.asarray(out["b"]), tree["b"].sum(axis=0)
        )

    def test_quant_rejects_non_sum_ops_and_bad_modes(self):
        topo = _topo(2)
        x = np.ones((2, 4), np.float32)
        with pytest.raises(ValueError, match="SUM/AVG"):
            _mesh_fn(
                topo,
                lambda s: coll.allreduce(s, coll.MAX, quant="int8"),
                P("dp", None),
                P("dp", None),
            )(x)
        with pytest.raises(ValueError, match="mode"):
            _mesh_fn(
                topo,
                lambda s: coll.quantized_allreduce(s, mode="fp4")[0],
                P("dp", None),
                P("dp", None),
            )(x)


class TestQuantizedPsumScatter:
    def test_int8_exact_scatter_2way(self):
        topo = _topo(2)
        x = np.array(
            [
                [127, 2, -4, 100, 127, 2, 64, -32],
                [127, 4, -2, -90, 127, 2, -64, 32],
            ],
            np.float32,
        )

        def f(s):
            return coll.quantized_psum_scatter(s[0], mode="int8")[None]

        out = _mesh_fn(topo, f, P("dp", None), P("dp", None))(x)
        # worker k holds chunk k of the full sum — first hop only, so
        # the f32 accumulate is exact once the codes are
        np.testing.assert_array_equal(
            np.asarray(out).ravel(), x.sum(axis=0)
        )

    def test_off_mode_is_raw_psum_scatter(self):
        topo = _topo(2)
        x = np.stack(
            [np.arange(8, dtype=np.float32) + 10 * i for i in range(2)]
        )

        def f(s):
            return coll.quantized_psum_scatter(s[0], mode="off")[None]

        out = _mesh_fn(topo, f, P("dp", None), P("dp", None))(x)
        np.testing.assert_allclose(np.asarray(out).ravel(), x.sum(axis=0))

    def test_bad_mode_raises(self):
        topo = _topo(2)
        with pytest.raises(ValueError, match="psum_scatter mode"):
            _mesh_fn(
                topo,
                lambda s: coll.quantized_psum_scatter(s[0], mode="fp8")[
                    None
                ],
                P("dp", None),
                P("dp", None),
            )(np.ones((2, 4), np.float32))


class TestErrorFeedback:
    def test_ef_mean_converges_past_one_shot_error(self):
        """The EF recurrence (docs/WIRE.md) applied to the quantized
        allreduce: with BOTH residual levels threaded (contribution +
        owned-chunk requantization), the MEAN of the reduced outputs
        over N rounds lands far inside one round's quantization error —
        the same contract the wire path pins in tests/test_wire.py,
        here through the two-hop collective."""
        topo = _topo(2)
        rng = np.random.default_rng(13)
        g = rng.standard_normal((2, 128)).astype(np.float32)
        want = g.mean(axis=0)

        def f(s, r, r2):
            red, new_r, new_r2 = coll.quantized_allreduce(
                s[0], mode="int8", mean=True,
                residual=r[0], residual2=r2[0],
            )
            return red, new_r[None], new_r2[None]

        step = _mesh_fn(
            topo, f,
            (P("dp", None), P("dp", None), P("dp", None)),
            (P(), P("dp", None), P("dp", None)),
        )
        res = np.zeros_like(g)
        res2 = np.zeros((2, g.shape[1] // 2), np.float32)
        acc = np.zeros_like(want)
        n = 50
        for _ in range(n):
            red, res, res2 = step(g, res, res2)
            jax.block_until_ready(res)  # XLA:CPU: one in-flight program
            acc += np.asarray(red)
        one = _mesh_fn(
            topo,
            lambda s: coll.quantized_allreduce(s[0], mode="int8", mean=True)[0],
            P("dp", None),
            P(),
        )
        one_shot = np.mean(np.abs(np.asarray(one(g)) - want))
        ef_err = np.mean(np.abs(acc / n - want))
        assert ef_err < one_shot / 10, (ef_err, one_shot)
