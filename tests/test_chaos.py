"""Chaos-seeded robustness tests (docs/ROBUSTNESS.md).

Every fault below is scheduled from a seed — a failure here replays
byte-for-byte under a debugger with the same ``ChaosConfig``. Layers under
test: ChaosTransport's own schedule determinism, PClient retry/attempt-id
machinery, PServer's exactly-once push window, and the full AsyncPSTrainer
run surviving a seeded drop+duplicate+reset schedule with per-push
accounting intact (the ISSUE acceptance pin).
"""

import os
import time

import numpy as np
import pytest

from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_FETCH,
    TAG_PARAM,
    TAG_PUSH_DELTA,
    TAG_PUSH_EASGD,
    TAG_STOP,
    PServer,
    _DedupWindow,
    spawn_server_thread,
)
from mpit_tpu.transport import (
    Broker,
    ChaosConfig,
    ChaosTransport,
    FaultLog,
    RecvTimeout,
    SocketTransport,
)
from mpit_tpu.transport.chaos import config_from_env, iter_fault_lines

DIM = 8


def _run_pattern(cfg):
    """Fixed message pattern through a fresh broker; returns the log."""
    tps = Broker(2).transports()
    chaos = ChaosTransport(tps[0], cfg)
    for tag in (3, 5):
        for i in range(150):
            try:
                chaos.send(1, tag, i)
            except ConnectionError:
                pass  # injected reset
    return chaos.log


class TestSchedule:
    def test_same_seed_identical_fault_log(self):
        cfg = ChaosConfig(
            seed=42, drop=0.3, duplicate=0.3, delay=0.2, delay_s=0.0,
            reset=0.2, blackhole=0.05, blackhole_len=3,
        )
        log1, log2 = _run_pattern(cfg), _run_pattern(cfg)
        assert log1.events() == log2.events()
        counts = log1.counts()
        assert len(counts) >= 3 and sum(counts.values()) > 0
        # the soak-script text rendering is part of the replay contract
        assert list(iter_fault_lines(log1.events())) == list(
            iter_fault_lines(log2.events())
        )

    def test_different_seed_different_schedule(self):
        cfg = ChaosConfig(seed=42, drop=0.3, duplicate=0.3)
        other = ChaosConfig(seed=43, drop=0.3, duplicate=0.3)
        assert _run_pattern(cfg).events() != _run_pattern(other).events()

    def test_blackhole_swallows_whole_burst(self):
        tps = Broker(2).transports()
        chaos = ChaosTransport(
            tps[0], ChaosConfig(seed=0, blackhole=1.0, blackhole_len=8)
        )
        for i in range(10):
            chaos.send(1, 3, i)
        assert chaos.log.counts() == {"blackhole": 10}
        assert not tps[1].probe(src=0, tag=3)

    def test_kill_after_goes_silent(self):
        tps = Broker(2).transports()
        chaos = ChaosTransport(tps[0], ChaosConfig(kill_after={0: 3}))
        for i in range(5):
            chaos.send(1, 3, i)  # dead rank raises nothing
        got = [tps[1].recv(0, 3, timeout=1).payload for _ in range(3)]
        assert got == [0, 1, 2]
        assert not tps[1].probe(src=0, tag=3)
        assert chaos.log.counts() == {"kill": 2}

    def test_per_kind_tags_gate_without_shifting_draws(self):
        # same seed, drop narrowed to tag 5: no drop may fire on tag 3,
        # tag-5 duplicates are bit-identical (their draws didn't shift),
        # and tag-3 duplicates only GROW — messages the wide config
        # dropped before the duplicate check now survive to reveal theirs
        wide = ChaosConfig(seed=9, drop=0.4, duplicate=0.4)
        narrow = ChaosConfig(seed=9, drop=0.4, duplicate=0.4, drop_tags=(5,))
        ev_wide = _run_pattern(wide).events()
        ev_narrow = _run_pattern(narrow).events()
        assert all(e.tag == 5 for e in ev_narrow if e.kind == "drop")

        def dups(events, tag):
            return {e.n for e in events if e.kind == "duplicate" and e.tag == tag}

        assert dups(ev_wide, 5) == dups(ev_narrow, 5)
        assert dups(ev_wide, 3) <= dups(ev_narrow, 3)
        drops_wide_3 = {e.n for e in ev_wide if e.kind == "drop" and e.tag == 3}
        assert dups(ev_narrow, 3) - dups(ev_wide, 3) <= drops_wide_3

    def test_config_validation(self):
        with pytest.raises(ValueError, match="probability"):
            ChaosConfig(drop=1.5)
        with pytest.raises(ValueError, match="subset"):
            ChaosConfig(tags=(1,), drop_tags=(4,))
        with pytest.raises(ValueError, match="unknown fault kind"):
            ChaosConfig(scripted={(0, 1, 3, 0): "explode"})

    def test_config_from_env(self):
        assert config_from_env({}) is None
        assert config_from_env({"OTHER": "1"}) is None
        # only RECOGNIZED knobs arm chaos (soak-offset is bookkeeping)
        assert config_from_env({"MPIT_CHAOS_SOAK_OFFSET": "2"}) is None
        cfg = config_from_env({
            "MPIT_CHAOS_SEED": "5",
            "MPIT_CHAOS_DROP": "0.25",
            "MPIT_CHAOS_DUP_TAGS": "2,3",
            "MPIT_CHAOS_TAGS": "1,2,3,4",
            "MPIT_CHAOS_KILL_RANK": "1",
            "MPIT_CHAOS_KILL_AFTER": "7",
        })
        assert cfg.seed == 5 and cfg.drop == 0.25
        assert cfg.duplicate_tags == (2, 3) and cfg.tags == (1, 2, 3, 4)
        assert cfg.kill_after == {1: 7}


class TestFifoUnderFaults:
    def test_duplication_preserves_fifo(self):
        tps = Broker(2).transports()
        chaos = ChaosTransport(tps[0], ChaosConfig(seed=0, duplicate=1.0))
        for i in range(20):
            chaos.send(1, 3, i)
        got = [tps[1].recv(0, 3, timeout=1).payload for _ in range(40)]
        assert got == [i // 2 for i in range(40)]

    def test_socket_fifo_under_duplication_and_reconnect(self):
        base_port = 29_921
        rx = SocketTransport(0, 2, base_port=base_port)
        tx = SocketTransport(1, 2, base_port=base_port)
        chaos = ChaosTransport(tx, ChaosConfig(seed=7, duplicate=0.5))
        try:
            for i in range(30):
                chaos.send(0, 7, i)
                if i == 14:  # break the cached socket: evict + reconnect
                    tx._out[0].close()
            ndup = chaos.log.counts().get("duplicate", 0)
            assert ndup > 0  # seed 7 must actually duplicate
            order = [
                rx.recv(1, 7, timeout=10).payload for _ in range(30 + ndup)
            ]
            assert order == sorted(order)  # per-(src,tag) FIFO held
            deduped = sorted(set(order))
            assert deduped == list(range(30))  # nothing lost across evict
        finally:
            chaos.close()
            rx.close()


def _ps_world(chaos_on, cfg, dim=DIM, center=0.0, **server_kw):
    """Broker(2) world: rank 0 = server, rank 1 = client; ``chaos_on``
    selects which side's transport gets wrapped ("server"/"client")."""
    tps = Broker(2).transports()
    log = FaultLog()
    if chaos_on == "server":
        tps[0] = ChaosTransport(tps[0], cfg, log)
    else:
        tps[1] = ChaosTransport(tps[1], cfg, log)
    server = PServer(
        tps[0], np.full(dim, center, np.float32), num_clients=1, **server_kw
    )
    thread = spawn_server_thread(server)
    return tps, server, thread, log


class TestFetchRetry:
    def test_fetch_survives_dropped_param(self):
        cfg = ChaosConfig(scripted={(0, 1, TAG_PARAM, 0): "drop"})
        tps, server, thread, log = _ps_world("server", cfg, center=5.0)
        client = PClient(
            tps[1], [0], DIM, timeout=0.3, max_retries=2, backoff_base=0.01
        )
        out = client.fetch()
        np.testing.assert_array_equal(out, np.full(DIM, 5.0, np.float32))
        assert server.counts["fetch"] == 2  # first attempt's reply dropped
        assert [e.kind for e in log.events()] == ["drop"]
        assert client.stale_params_dropped == 0
        client.stop()
        thread.join(timeout=5)
        assert not thread.is_alive() and server.error is None

    def test_stale_param_discarded_not_misassembled(self):
        # duplicate the client's first FETCH: the server answers it twice,
        # the second PARAM parks in the mailbox as a stale reply
        cfg = ChaosConfig(scripted={(1, 0, TAG_FETCH, 0): "duplicate"})
        tps, server, thread, log = _ps_world("client", cfg, center=0.0)
        client = PClient(
            tps[1], [0], DIM, timeout=1.0, max_retries=1, backoff_base=0.01
        )
        np.testing.assert_array_equal(client.fetch(), np.zeros(DIM))
        client.push_easgd(np.ones(DIM))  # alpha 0.5: center -> 0.5
        deadline = time.monotonic() + 5
        while server.counts["push_easgd"] < 1:  # async apply
            assert time.monotonic() < deadline
            time.sleep(0.005)
        out = client.fetch()  # must skip the parked stale 0-center reply
        np.testing.assert_array_equal(out, np.full(DIM, 0.5, np.float32))
        assert client.stale_params_dropped == 1
        assert server.counts["fetch"] == 3  # dup'd FETCH answered twice
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_fetch_exhausted_retries_raise(self):
        cfg = ChaosConfig(drop=1.0, tags=(TAG_PARAM,))
        tps, server, thread, log = _ps_world("server", cfg)
        client = PClient(
            tps[1], [0], DIM, timeout=0.05, max_retries=1, backoff_base=0.01
        )
        with pytest.raises(RecvTimeout, match="after 2 attempts"):
            client.fetch()
        assert log.counts()["drop"] == 2
        client.stop()  # STOP is not faulted: clean teardown still works
        thread.join(timeout=5)
        assert server.error is None

    def test_push_send_reset_retried(self):
        cfg = ChaosConfig(scripted={(1, 0, TAG_PUSH_EASGD, 0): "reset"})
        tps, server, thread, log = _ps_world("client", cfg)
        client = PClient(tps[1], [0], DIM, timeout=1.0, backoff_base=0.01)
        client.push_easgd(np.ones(DIM))  # first send resets; retry lands
        client.stop()
        thread.join(timeout=5)
        assert server.counts["push_easgd"] == 1
        assert server.counts["dup_dropped"] == 0
        assert client.push_sent[0] == 1
        np.testing.assert_array_equal(
            server.snapshot(), np.full(DIM, 0.5, np.float32)
        )


class TestExactlyOnce:
    def test_duplicated_push_applies_once(self):
        cfg = ChaosConfig(seed=0, duplicate=1.0, tags=(TAG_PUSH_EASGD,))
        tps, server, thread, log = _ps_world("client", cfg)
        client = PClient(tps[1], [0], DIM, timeout=1.0)
        client.push_easgd(np.ones(DIM))
        client.stop()
        thread.join(timeout=5)
        # applied once: center is 0.5, not 0.75 (a second elastic move)
        np.testing.assert_array_equal(
            server.snapshot(), np.full(DIM, 0.5, np.float32)
        )
        assert server.counts["push_easgd"] == 1 == client.push_sent[0]
        assert server.counts["dup_dropped"] == 1
        assert log.counts()["duplicate"] == 1

    def test_replacement_client_not_deduped_as_replay(self):
        tps = Broker(2).transports()
        server = PServer(tps[0], np.zeros(DIM, np.float32), num_clients=1)
        thread = spawn_server_thread(server)
        first = PClient(tps[1], [0], DIM, timeout=1.0)
        first.push_easgd(np.ones(DIM))
        first.push_easgd(np.ones(DIM))  # seqs 1, 2 under first's epoch
        # replacement on the same rank restarts seq at 1 — its fresh epoch
        # must keep it from looking like a replay of its predecessor
        replacement = PClient(tps[1], [0], DIM, timeout=1.0)
        replacement.push_easgd(np.ones(DIM))
        replacement.stop()
        thread.join(timeout=5)
        assert server.counts["push_easgd"] == 3
        assert server.counts["dup_dropped"] == 0

    def test_dedup_window_semantics(self):
        w = _DedupWindow(4)
        assert w.admit(1, 0, 1) and not w.admit(1, 0, 1)
        assert w.admit(1, 0, 2)
        assert w.admit(1, 0, 10)  # window floor moves to 6
        assert not w.admit(1, 0, 5)  # beyond the window: at-most-once side
        assert w.admit(1, 0, 7)  # in-window gap is still admissible
        assert w.admit(1, 1, 1)  # fresh epoch, same src
        assert w.admit(2, 0, 1)  # same seq, different src


def _chaos_trainer(cfg, algo="easgd", **kw):
    import jax.numpy as jnp
    import optax

    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import AsyncPSTrainer

    return AsyncPSTrainer(
        MLP(compute_dtype=jnp.float32),
        optax.sgd(0.05, momentum=0.9),
        num_clients=2,
        num_servers=1,
        algo=algo,
        alpha=0.5,
        tau=4,
        transport="inproc",
        chaos=cfg,
        max_exchange_failures=5,
        fetch_timeout=1.0,
        fetch_retries=3,
        **kw,
    )


def _assert_exactly_once(stats, algo="easgd"):
    """Every push a client handed to the transport was applied exactly
    once — the dedup window absorbed duplicates, resets never delivered."""
    key = "push_easgd" if algo == "easgd" else "push_delta"
    for s, counts in enumerate(stats["server_counts"]):
        sent = sum(
            per_client.get(s, 0) for per_client in stats["push_sent"]
        )
        assert counts[key] == sent, (
            f"server {s}: applied {counts[key]} != sent {sent} "
            f"(dup_dropped={counts['dup_dropped']}, stats={stats})"
        )


# the ISSUE acceptance schedule: drops hit only the retryable FETCH/PARAM
# path, duplicates and resets additionally exercise the push dedup — so
# "applied exactly once" stays checkable as counts == sends
_ACCEPT_CFG = dict(
    drop=0.06,
    drop_tags=(TAG_FETCH, TAG_PARAM),
    duplicate=0.12,
    reset=0.08,
    reset_tags=(TAG_FETCH, TAG_PUSH_EASGD),
    tags=(TAG_FETCH, TAG_PARAM, TAG_PUSH_EASGD),
)


@pytest.fixture(scope="module")
def mnist():
    from mpit_tpu.data import load_mnist

    return load_mnist(synthetic_train=2048, synthetic_test=512)


class TestTrainerUnderChaos:
    def test_seeded_schedule_finishes_exactly_once_and_replays(self, mnist):
        x_tr, y_tr, *_ = mnist
        cfg = ChaosConfig(seed=1234, **_ACCEPT_CFG)

        def run():
            trainer = _chaos_trainer(cfg)
            _, stats = trainer.train(x_tr, y_tr, steps=24, batch_size=32)
            return stats, trainer.fault_log

        stats, log = run()
        assert all(np.isfinite(l).all() for l in stats["losses"] if l)
        _assert_exactly_once(stats)
        faults = stats["chaos_faults"]
        for kind in ("drop", "duplicate", "reset"):  # schedule actually bit
            assert faults.get(kind, 0) > 0, faults
        # same seed -> the identical fault log, event for event
        stats2, log2 = run()
        assert log.events() == log2.events()
        _assert_exactly_once(stats2)

    def test_env_knobs_activate_chaos(self, mnist, monkeypatch):
        x_tr, y_tr, *_ = mnist
        monkeypatch.setenv("MPIT_CHAOS_SEED", "77")
        monkeypatch.setenv("MPIT_CHAOS_DUP", "0.3")
        monkeypatch.setenv(
            "MPIT_CHAOS_TAGS", f"{TAG_PUSH_EASGD}"
        )
        trainer = _chaos_trainer(None)  # config comes from the env
        _, stats = trainer.train(x_tr, y_tr, steps=16, batch_size=32)
        assert trainer.fault_log is not None
        assert stats["chaos_faults"].get("duplicate", 0) > 0
        _assert_exactly_once(stats)
        counts = stats["server_counts"][0]
        assert counts["dup_dropped"] == stats["chaos_faults"]["duplicate"]


@pytest.mark.slow
@pytest.mark.parametrize(
    "algo,seed",
    [("easgd", 1), ("easgd", 2), ("easgd", 3), ("downpour", 4), ("downpour", 5)],
)
def test_chaos_soak(mnist, algo, seed):
    """Multi-seed soak: heavier schedule (delay + PARAM blackhole on top of
    the acceptance faults) must still finish with finite losses and
    exactly-once pushes for every seed."""
    x_tr, y_tr, *_ = mnist
    push_tag = TAG_PUSH_EASGD if algo == "easgd" else TAG_PUSH_DELTA
    # scripts/chaos_soak.sh widens the swept seed space per round; the
    # name is deliberately NOT a recognized config_from_env knob
    seed += 10 * int(os.environ.get("MPIT_CHAOS_SOAK_OFFSET", "0"))
    cfg = ChaosConfig(
        seed=seed,
        drop=0.06,
        drop_tags=(TAG_FETCH, TAG_PARAM),
        duplicate=0.15,
        delay=0.1,
        delay_s=0.005,
        reset=0.1,
        reset_tags=(TAG_FETCH, push_tag),
        blackhole=0.02,
        blackhole_tags=(TAG_PARAM,),
        blackhole_len=2,
        tags=(TAG_FETCH, TAG_PARAM, push_tag),
    )
    trainer = _chaos_trainer(cfg, algo=algo)
    _, stats = trainer.train(x_tr, y_tr, steps=32, batch_size=32)
    assert all(np.isfinite(l).all() for l in stats["losses"] if l)
    _assert_exactly_once(stats, algo)
    assert sum(stats["chaos_faults"].values()) > 0


class TestStopAggregation:
    class _FailTo:
        """Transport stub whose sends to one dst always fail."""

        def __init__(self, inner, bad_dst):
            self.inner, self.bad_dst = inner, bad_dst
            self.rank, self.size = inner.rank, inner.size

        def send(self, dst, tag, payload):
            if dst == self.bad_dst:
                raise ConnectionError(f"unreachable dst {dst}")
            self.inner.send(dst, tag, payload)

        def recv(self, src=-1, tag=-1, timeout=None):
            return self.inner.recv(src, tag, timeout)

    def test_stop_attempts_all_servers_and_aggregates(self):
        tps = Broker(3).transports()
        client = PClient(
            self._FailTo(tps[2], bad_dst=0), [0, 1], DIM,
            timeout=0.5, max_retries=0,
        )
        with pytest.raises(RuntimeError, match=r"STOP failed.*\[0\]"):
            client.stop()
        # the healthy server still got its STOP — no watchdog-only exit
        assert tps[1].recv(2, TAG_STOP, timeout=1).payload is None


class TestCorruptTruncate:
    """Recv-path frame faults: the message ARRIVES, but mangled. The PS
    protocol must surface these as retriable exchange failures — dropped
    and counted, never a crash, never junk applied to the center."""

    def test_determinism_and_replay(self):
        cfg = ChaosConfig(seed=13, corrupt=0.2, truncate=0.2)
        log1, log2 = _run_pattern(cfg), _run_pattern(cfg)
        assert log1.events() == log2.events()
        assert set(log1.counts()) == {"corrupt", "truncate"}

    def test_new_draws_do_not_shift_old_kinds(self):
        # the replay contract across kinds: arming corrupt/truncate (their
        # draws are APPENDED after the original six) must leave the same
        # seed's drop/duplicate/reset schedule bit-identical
        base = ChaosConfig(seed=9, drop=0.3, duplicate=0.3, reset=0.1)
        plus = ChaosConfig(
            seed=9, drop=0.3, duplicate=0.3, reset=0.1,
            corrupt=0.5, truncate=0.3,
        )
        ev_base = _run_pattern(base).events()
        ev_plus = _run_pattern(plus).events()
        old = tuple(
            e for e in ev_plus
            if e.kind in ("drop", "duplicate", "reset")
        )
        assert old == ev_base
        assert any(e.kind == "corrupt" for e in ev_plus)
        assert any(e.kind == "truncate" for e in ev_plus)

    def test_truncate_cuts_arrays_keeps_envelope_scalars(self):
        from mpit_tpu.transport.chaos import _truncate_payload

        env = (7, 3, np.arange(10, dtype=np.float32))
        cut = _truncate_payload(env)
        assert cut[0] == 7 and cut[1] == 3 and len(cut[2]) == 5
        # nothing array-like to cut -> the caller degrades to corruption
        assert _truncate_payload(None) is None
        assert _truncate_payload(42) is None

    def test_corrupted_payload_resists_apply(self):
        from mpit_tpu.transport import CorruptedPayload

        with pytest.raises((TypeError, ValueError)):
            np.asarray(CorruptedPayload(), dtype=np.float32)

    def test_scripted_corrupt_param_fetch_retries(self):
        cfg = ChaosConfig(scripted={(0, 1, TAG_PARAM, 0): "corrupt"})
        tps, server, thread, log = _ps_world("server", cfg, center=5.0)
        client = PClient(
            tps[1], [0], DIM, timeout=0.3, max_retries=2, backoff_base=0.01
        )
        out = client.fetch()  # first reply is garbage; retry resolves it
        np.testing.assert_array_equal(out, np.full(DIM, 5.0, np.float32))
        assert client.corrupt_params_dropped == 1
        assert server.counts["fetch"] == 2
        assert [e.kind for e in log.events()] == ["corrupt"]
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_truncated_push_dropped_as_malformed(self):
        cfg = ChaosConfig(scripted={(1, 0, TAG_PUSH_EASGD, 0): "truncate"})
        tps, server, thread, log = _ps_world("client", cfg)
        client = PClient(tps[1], [0], DIM, timeout=1.0, backoff_base=0.01)
        client.push_easgd(np.ones(DIM, np.float32))  # arrives half-length
        client.push_easgd(np.ones(DIM, np.float32))  # clean
        client.fetch()  # barrier: per-(src, tag) FIFO, pushes are done
        client.stop()
        thread.join(timeout=5)
        assert server.error is None
        assert server.counts["malformed_dropped"] == 1
        assert server.counts["push_easgd"] == 1  # only the clean one
        assert server.counts["dup_dropped"] == 0  # no dedup slot consumed
        np.testing.assert_array_equal(
            server.snapshot(), np.full(DIM, 0.5, np.float32)
        )

    def test_corrupt_fetch_dropped_no_crash(self):
        cfg = ChaosConfig(scripted={(1, 0, TAG_FETCH, 0): "corrupt"})
        tps, server, thread, log = _ps_world("client", cfg, center=3.0)
        client = PClient(
            tps[1], [0], DIM, timeout=0.3, max_retries=2, backoff_base=0.01
        )
        out = client.fetch()  # garbled FETCH never answered; retry is
        np.testing.assert_array_equal(out, np.full(DIM, 3.0, np.float32))
        assert server.counts["malformed_dropped"] == 1
        assert server.counts["fetch"] == 1
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_config_env_and_validation(self):
        cfg = config_from_env({
            "MPIT_CHAOS_CORRUPT": "0.1",
            "MPIT_CHAOS_TRUNCATE": "0.2",
            "MPIT_CHAOS_TRUNCATE_TAGS": "2,4",
            "MPIT_CHAOS_TAGS": "1,2,4",
        })
        assert cfg.corrupt == 0.1 and cfg.truncate == 0.2
        assert cfg.truncate_tags == (2, 4)
        with pytest.raises(ValueError, match="probability"):
            ChaosConfig(truncate=2.0)
        with pytest.raises(ValueError, match="subset"):
            ChaosConfig(tags=(1,), corrupt_tags=(4,))
        # scripted accepts the new kinds
        ChaosConfig(scripted={(0, 1, 2, 0): "corrupt",
                              (0, 1, 2, 1): "truncate"})

    def test_trainer_survives_corrupt_truncate(self, mnist):
        x_tr, y_tr, *_ = mnist
        cfg = ChaosConfig(
            seed=21, corrupt=0.08, truncate=0.08,
            tags=(TAG_FETCH, TAG_PARAM, TAG_PUSH_EASGD),
        )
        trainer = _chaos_trainer(cfg)
        _, stats = trainer.train(x_tr, y_tr, steps=24, batch_size=32)
        assert all(np.isfinite(l).all() for l in stats["losses"] if l)
        faults = stats["chaos_faults"]
        assert faults.get("corrupt", 0) + faults.get("truncate", 0) > 0
        counts = stats["server_counts"][0]
        sent = sum(pc.get(0, 0) for pc in stats["push_sent"])
        # a mangled push is LOST (dropped as malformed), never mis-applied:
        # applied <= sent, and every gap is accounted for by a mangle
        assert counts["push_easgd"] <= sent
        assert sent - counts["push_easgd"] <= sum(faults.values())


class TestFramedChaos:
    """Chaos faults against the binary wire format (docs/WIRE.md): the
    payload-object mangling happens above the codec, so framed messages
    degrade through the SAME counters as pickle ones, quantized chunks
    truncate like raw arrays, and arming quantization adds zero RNG
    draws — old seeds replay bit-identically."""

    def test_truncate_cuts_quantized_chunk_keeps_envelope(self):
        from mpit_tpu.transport.chaos import _truncate_payload
        from mpit_tpu.transport.wire import QuantArray, quantize

        q = quantize(np.arange(10, dtype=np.float32), "int8")
        env = (1 << 70, 3, 0, q)
        cut = _truncate_payload(env)
        assert cut[0] == 1 << 70 and cut[1] == 3
        assert isinstance(cut[3], QuantArray)
        assert cut[3].mode == "int8" and cut[3].scale == q.scale
        assert len(cut[3].data) == 5
        # a scalar-only QuantArray-free envelope still degrades to None
        assert _truncate_payload((1, 2, 3)) is None

    def test_truncated_quantized_push_dropped_as_malformed(self):
        # the dequantized wrong-length chunk must fail shape validation
        # BEFORE the dedup admit — same path as a truncated raw push
        cfg = ChaosConfig(scripted={(1, 0, TAG_PUSH_EASGD, 0): "truncate"})
        tps, server, thread, log = _ps_world("client", cfg)
        client = PClient(
            tps[1], [0], DIM, timeout=1.0, backoff_base=0.01,
            quant="int8",
        )
        client.push_easgd(np.ones(DIM, np.float32))  # arrives half-length
        client.push_easgd(np.ones(DIM, np.float32))  # clean
        client.fetch()  # FIFO barrier
        client.stop()
        thread.join(timeout=5)
        assert server.error is None
        assert server.counts["malformed_dropped"] == 1
        assert server.counts["push_easgd"] == 1
        assert server.counts["dup_dropped"] == 0

    def test_corrupt_param_with_quant_retries(self):
        cfg = ChaosConfig(scripted={(0, 1, TAG_PARAM, 0): "corrupt"})
        tps, server, thread, log = _ps_world(
            "server", cfg, center=5.0, quant="int8"
        )
        client = PClient(
            tps[1], [0], DIM, timeout=0.3, max_retries=2,
            backoff_base=0.01, quant="int8",
        )
        out = client.fetch()
        np.testing.assert_allclose(
            out, np.full(DIM, 5.0, np.float32), rtol=1e-2
        )
        assert client.corrupt_params_dropped == 1
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_quant_payloads_do_not_shift_fault_schedule(self):
        """Replay contract: the fault schedule is a function of (seed,
        src, dst, tag, n) only — swapping payloads from raw arrays to
        QuantArrays (or ints) must reproduce the exact event stream."""
        from mpit_tpu.transport.wire import quantize

        cfg = ChaosConfig(
            seed=17, drop=0.2, duplicate=0.2, corrupt=0.2, truncate=0.2,
        )

        def run(payload_of):
            tps = Broker(2).transports()
            chaos = ChaosTransport(tps[0], cfg)
            for tag in (3, 5):
                for i in range(120):
                    try:
                        chaos.send(1, tag, payload_of(i))
                    except ConnectionError:
                        pass
            return chaos.log.events()

        raw = run(lambda i: (i, np.arange(8, dtype=np.float32)))
        quant = run(
            lambda i: (
                i, quantize(np.arange(8, dtype=np.float32), "int8")
            )
        )
        ints = run(lambda i: i)
        assert raw == quant == ints

    def test_corrupt_over_framed_socket_delivered(self):
        """Chaos sits above the codec: a CorruptedPayload is unencodable,
        so the framed transport pickles it — delivery (and the receiver's
        drop accounting) is format-independent."""
        from mpit_tpu.transport import CorruptedPayload

        base_port = 29_885
        a = SocketTransport(0, 2, base_port=base_port, wire_format="framed")
        b = SocketTransport(1, 2, base_port=base_port, wire_format="framed")
        chaos = ChaosTransport(
            a, ChaosConfig(scripted={(0, 1, 7, 0): "corrupt"})
        )
        try:
            chaos.send(1, 7, (1, 2, np.ones(4, np.float32)))
            chaos.send(1, 7, (3, 4, np.ones(4, np.float32)))
            first = b.recv(0, 7, timeout=10)
            assert isinstance(first.payload, CorruptedPayload)
            second = b.recv(0, 7, timeout=10)
            assert second.payload[0] == 3
        finally:
            a.close()
            b.close()
