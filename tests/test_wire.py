"""Fast-wire tests (docs/WIRE.md): the framed codec's structural
roundtrips and integrity checks, quantization + error feedback, the
coalesced scatter, and mixed-version (framed vs pickle-only) peers
completing real EASGD exchanges over sockets."""

import os
import threading
import time

import numpy as np
import pytest

from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_PUSH_EASGD,
    PServer,
    spawn_server_thread,
)
from mpit_tpu.transport import Broker, SocketTransport
from mpit_tpu.transport import wire
from mpit_tpu.transport.wire import (
    WIRE_FORMAT_VERSION,
    QuantArray,
    WireDecodeError,
    dequantize,
    quantize,
)

DIM = 16


def _roundtrip(payload, src=3, tag=2):
    """encode → (simulated wire) → decode, returning (src, tag, payload).
    Joins the zero-copy buffer list the way the socket writes it."""
    bufs = wire.encode_frame(src, tag, payload, version=WIRE_FORMAT_VERSION)
    assert bufs is not None
    head = bytes(bufs[0])
    body = b"".join(bytes(b) for b in bufs[1:])
    version, flags, hlen, hcrc = wire.split_preamble(
        head[: wire.PREAMBLE_SIZE]
    )
    assert version == WIRE_FORMAT_VERSION
    assert hlen == len(head) - wire.PREAMBLE_SIZE
    return wire.decode_frame(flags, hcrc, head[wire.PREAMBLE_SIZE:], body)


class TestCodec:
    def test_structural_roundtrip(self):
        payload = (
            None, True, False, 0, -17, 3.25, "τ-steps", b"\x00\xff",
            ["a", (1, 2.0, None)], [],
        )
        src, tag, out = _roundtrip(payload, src=5, tag=9)
        assert (src, tag) == (5, 9)
        assert out == payload

    def test_epoch_int_wider_than_u64(self):
        # client epochs come from os.urandom(8) and CAN exceed a signed
        # 64-bit slot; arbitrary-width magnitudes are part of the format
        for v in (2 ** 63, 2 ** 80 + 13, -(2 ** 70), 2 ** 64 - 1):
            assert _roundtrip((v, 1, 0, None))[2][0] == v

    def test_ndarray_roundtrip_and_views(self):
        arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        bufs = wire.encode_frame(
            0, 2, arr, version=WIRE_FORMAT_VERSION
        )
        # send side is zero-copy: the body buffer aliases the input array
        assert isinstance(bufs[1], memoryview)
        assert bufs[1].obj is arr.data.obj or np.shares_memory(
            np.frombuffer(bufs[1], dtype=np.float32).reshape(arr.shape),
            arr,
        )
        _, _, out = _roundtrip(arr)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        # recv side is zero-copy: the decoded array is a view into the
        # body buffer, not a fresh allocation
        assert not out.flags.owndata

    def test_every_registered_dtype_roundtrips(self):
        for dtype in (
            np.float32, np.float64, np.float16, np.int64, np.int32,
            np.int16, np.int8, np.uint8, np.uint16, np.uint32,
            np.uint64, np.bool_,
        ):
            arr = np.zeros(5, dtype=dtype)
            arr[1] = 1
            _, _, out = _roundtrip(arr)
            assert out.dtype == np.dtype(dtype)
            np.testing.assert_array_equal(out, arr)

    def test_unencodable_returns_none_for_pickle_fallback(self):
        from mpit_tpu.transport.chaos import CorruptedPayload

        for payload in (
            object(), {"a": 1}, np.float32(1.5), CorruptedPayload(),
            (1, 2, {3}),
        ):
            assert wire.encode_frame(
                0, 1, payload, version=WIRE_FORMAT_VERSION
            ) is None

    def test_header_crc_flip_raises(self):
        bufs = wire.encode_frame(
            1, 2, (1, 2, np.ones(4, np.float32)),
            version=WIRE_FORMAT_VERSION,
        )
        head = bytearray(bytes(bufs[0]))
        body = b"".join(bytes(b) for b in bufs[1:])
        head[wire.PREAMBLE_SIZE] ^= 0x40  # flip a structural header bit
        _, flags, _, hcrc = wire.split_preamble(
            bytes(head[: wire.PREAMBLE_SIZE])
        )
        with pytest.raises(WireDecodeError, match="CRC"):
            wire.decode_frame(
                flags, hcrc, bytes(head[wire.PREAMBLE_SIZE:]), body
            )

    def test_body_length_mismatch_carries_src_tag(self):
        arr = np.ones(8, np.float32)
        bufs = wire.encode_frame(
            7, 4, (1, 2, arr), version=WIRE_FORMAT_VERSION
        )
        head = bytes(bufs[0])
        body = b"".join(bytes(b) for b in bufs[1:])
        _, flags, _, hcrc = wire.split_preamble(head[: wire.PREAMBLE_SIZE])
        with pytest.raises(WireDecodeError) as ei:
            wire.decode_frame(
                flags, hcrc, head[wire.PREAMBLE_SIZE:], body[:-4]
            )
        # src/tag decoded before the body check: the transport can still
        # route a corruption marker to the right (src, tag) stream
        assert ei.value.src == 7 and ei.value.tag == 4
        with pytest.raises(WireDecodeError, match="mismatch"):
            wire.decode_frame(
                flags, hcrc, head[wire.PREAMBLE_SIZE:], body + b"xx"
            )

    def test_future_version_rejected(self):
        bufs = wire.encode_frame(
            0, 1, None, version=WIRE_FORMAT_VERSION + 1
        )
        with pytest.raises(WireDecodeError, match="newer"):
            wire.split_preamble(bytes(bufs[0])[: wire.PREAMBLE_SIZE])
        with pytest.raises(ValueError, match="out of range"):
            wire.encode_frame(0, 1, None, version=300)

    def test_no_magic_collision_with_pickle(self):
        # per-frame dispatch depends on it: a protocol>=2 pickle always
        # starts 0x80, a framed body always starts b"MW"
        import pickle

        assert wire.MAGIC[0:1] != pickle.dumps(None, protocol=5)[0:1]
        assert wire.MAGIC == b"MW"

    def test_hello_roundtrip_and_rejects_garbage(self):
        assert wire.decode_hello(wire.encode_hello()) == (
            WIRE_FORMAT_VERSION
        )
        assert wire.decode_hello(b"") is None
        assert wire.decode_hello(b"\x80\x05x") is None
        assert wire.decode_hello(b"MWX\x01") is None

    def test_frame_nbytes_counts_whole_body(self):
        arr = np.ones(10, np.float32)
        bufs = wire.encode_frame(
            0, 2, arr, version=WIRE_FORMAT_VERSION
        )
        joined = bytes(bufs[0]) + b"".join(bytes(b) for b in bufs[1:])
        assert wire.frame_nbytes(bufs) == len(joined)


class TestQuantization:
    def test_bf16_roundtrip_precision(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal(4096).astype(np.float32) * 100
        out = dequantize(quantize(a, "bf16"))
        # bf16 keeps 8 mantissa bits: relative error < 2^-8 after RNE
        nz = np.abs(a) > 0
        assert np.max(np.abs(out[nz] - a[nz]) / np.abs(a[nz])) < 2 ** -8

    def test_int8_symmetric_absmax(self):
        a = np.array([-4.0, -1.0, 0.0, 2.0, 4.0], np.float32)
        q = quantize(a, "int8")
        assert q.mode == "int8" and q.data.dtype == np.int8
        assert q.scale == pytest.approx(4.0 / 127.0)
        out = dequantize(q)
        assert np.max(np.abs(out - a)) <= q.scale / 2 + 1e-7
        # all-zero chunk must not divide by zero
        z = quantize(np.zeros(3, np.float32), "int8")
        np.testing.assert_array_equal(dequantize(z), np.zeros(3))

    def test_quant_array_over_the_wire(self):
        a = np.linspace(-1, 1, 64, dtype=np.float32)
        q = quantize(a, "int8")
        _, _, out = _roundtrip((123, 4, 0, q))
        got = out[3]
        assert isinstance(got, QuantArray)
        assert got.mode == "int8" and got.scale == pytest.approx(q.scale)
        np.testing.assert_allclose(
            dequantize(got), a, atol=q.scale / 2 + 1e-7
        )

    def test_error_feedback_cancels_quantizer_bias(self):
        # EF contract (docs/WIRE.md): residual carried into the next
        # push makes the MEAN of dequantized pushes converge to the true
        # vector far beyond one push's quantization error
        rng = np.random.default_rng(3)
        target = rng.standard_normal(256).astype(np.float32)
        res = np.zeros_like(target)
        acc = np.zeros_like(target)
        n = 50
        for _ in range(n):
            comp = target + res
            q = quantize(comp, "int8")
            deq = dequantize(q)
            res = comp - deq
            acc += deq
        one_shot = np.mean(
            np.abs(dequantize(quantize(target, "int8")) - target)
        )
        ef_err = np.mean(np.abs(acc / n - target))
        assert ef_err < one_shot / 10

    def test_env_readers_validate(self, monkeypatch):
        assert wire.wire_format_from_env({}) == "framed"
        assert wire.quant_mode_from_env({}) == "off"
        assert wire.negotiate_enabled_from_env({}) is True
        assert wire.negotiate_enabled_from_env(
            {"MPIT_WIRE_NEGOTIATE": "0"}
        ) is False
        assert wire.negotiate_timeout_from_env(
            {"MPIT_WIRE_NEGOTIATE_TIMEOUT_S": "0.25"}
        ) == 0.25
        with pytest.raises(ValueError, match="MPIT_WIRE_FORMAT"):
            wire.wire_format_from_env({"MPIT_WIRE_FORMAT": "msgpack"})
        with pytest.raises(ValueError, match="MPIT_WIRE_QUANT"):
            wire.quant_mode_from_env({"MPIT_WIRE_QUANT": "fp4"})
        with pytest.raises(ValueError, match="quant"):
            PClient(Broker(2).transports()[1], [0], DIM, quant="fp4")


class TestQuantHardening:
    """The hardened-kernel contract (docs/ANALYSIS.md, RT104): on the
    int8 faces, non-finite inputs and degenerate blocks must produce a
    finite scale and finite codes — a NaN gradient element may poison
    ITS lane's code (pinned to 0) but never the block scale, and an
    all-zero or all-NaN block quantizes to zeros at scale 1 instead of
    dividing by zero. bf16 represents NaN and passes it through bit-true
    (RT104 reports it at the boundary instead of the kernel hiding it)."""

    def test_all_nan_block_pins_scale_and_codes(self):
        q = quantize(np.full(6, np.nan, np.float32), "int8")
        assert q.scale == 1.0
        np.testing.assert_array_equal(q.data, np.zeros(6, np.int8))
        np.testing.assert_array_equal(dequantize(q), np.zeros(6))

    def test_inf_sets_scale_from_finite_values_nan_lane_zeroed(self):
        a = np.array([1.0, np.inf, -np.inf, np.nan], np.float32)
        q = quantize(a, "int8")
        # absmax over the FINITE values only: 1.0 -> scale 1/127
        assert q.scale == pytest.approx(1.0 / 127.0)
        # inf lanes saturate, the nan lane pins to 0
        np.testing.assert_array_equal(
            q.data, np.array([127, 127, -127, 0], np.int8)
        )
        out = dequantize(q)
        assert np.isfinite(out).all()

    def test_empty_chunk_roundtrips_on_both_layouts(self):
        from mpit_tpu import quant as qk

        q = quantize(np.zeros(0, np.float32), "int8")
        assert q.scale == 1.0 and dequantize(q).shape == (0,)
        codes, scales = qk.quantize_rows(
            np.zeros((0, 4), np.float32), "int8"
        )
        assert codes.shape == (0, 4) and scales.shape == (0, 1)
        assert qk.dequantize_rows(codes, scales, "int8").shape == (0, 4)

    def test_rows_face_matches_per_row_scalar_on_poisoned_input(self):
        from mpit_tpu import quant as qk

        rng = np.random.default_rng(11)
        a = rng.standard_normal((5, 32)).astype(np.float32)
        a[0, 3] = np.nan
        a[1, :] = np.nan  # all-NaN row
        a[2, 7] = np.inf
        a[3, :] = 0.0  # all-zero row
        codes, scales = qk.quantize_rows(a, "int8")
        for j in range(a.shape[0]):
            host = quantize(a[j], "int8")
            np.testing.assert_array_equal(codes[j], host.data)
            assert np.float32(host.scale).tobytes() == (
                scales[j].astype(np.float32).tobytes()
            )
        np.testing.assert_array_equal(
            qk.dequantize_rows(codes, scales, "int8"),
            np.stack([dequantize(quantize(a[j], "int8"))
                      for j in range(a.shape[0])]),
        )

    def test_jnp_faces_match_host_on_poisoned_input(self):
        from mpit_tpu import quant as qk

        a = np.array(
            [[1.0, np.inf, np.nan, -2.0],
             [np.nan, np.nan, np.nan, np.nan],
             [0.0, 0.0, 0.0, 0.0]],
            np.float32,
        )
        codes, scale = qk.quantize_jnp(a.ravel(), "int8")
        host = quantize(a.ravel(), "int8")
        np.testing.assert_array_equal(np.asarray(codes), host.data)
        assert np.isfinite(
            np.asarray(qk.dequantize_jnp(codes, scale, "int8"))
        ).all()
        codes, scales = qk.quantize_rows_jnp(a, "int8")
        h_codes, h_scales = qk.quantize_rows(a, "int8")
        np.testing.assert_array_equal(np.asarray(codes), h_codes)
        np.testing.assert_array_equal(
            np.asarray(scales, np.float32), h_scales.astype(np.float32)
        )

    def test_bf16_preserves_nan_and_rt104_reports_it(self):
        # bf16 REPRESENTS NaN, so the kernel passes it through bit-true
        # (no silent zeroing that would hide the bug) — detection is the
        # runtime sanitizer's job, at the quantize boundary
        from mpit_tpu.analysis import runtime as rt

        a = np.array([1.5, np.nan, -2.25], np.float32)
        out = dequantize(quantize(a, "bf16"))
        assert np.isnan(out[1])
        assert out[0] == pytest.approx(1.5) and out[2] == pytest.approx(-2.25)
        with rt.checking(numerics=True) as ck:
            quantize(a, "bf16")
        assert [f.rule for f in ck.findings] == ["RT104"]


class TestHostDeviceKernelEquivalence:
    """The factored kernels (mpit_tpu.quant) must agree BIT-FOR-BIT
    between the numpy (wire) and jnp (collective) paths: the error-
    feedback residual treats deq(quant(x)) as one deterministic
    function, so any host/device disagreement becomes exactly that much
    bias in the gradient average."""

    def _vectors(self):
        rng = np.random.default_rng(7)
        return np.concatenate([
            rng.standard_normal(1024).astype(np.float32) * 1e3,
            # edge cases: signed zero, exact powers of two (bf16 RNE
            # halfway carries), denormal-ish tiny, large
            np.array([0.0, -0.0, 1.0, -1.0, 2.0 ** -120, 6.5e4,
                      0.5, -3.0], np.float32),
        ])

    def test_bf16_rne_bits_match(self):
        from mpit_tpu import quant as qk

        a = self._vectors()
        host = quantize(a, "bf16")
        codes, scale = qk.quantize_jnp(a, "bf16")
        np.testing.assert_array_equal(np.asarray(codes), host.data)
        np.testing.assert_array_equal(
            np.asarray(qk.dequantize_jnp(codes, scale, "bf16")),
            dequantize(host),
        )

    def test_int8_absmax_bits_match(self):
        from mpit_tpu import quant as qk

        a = self._vectors()
        host = quantize(a, "int8")
        codes, scale = qk.quantize_jnp(a, "int8")
        np.testing.assert_array_equal(np.asarray(codes), host.data)
        # the scale itself is bit-equal, not approx: both paths divide
        # in f32 (a float64 host division would double-round)
        assert np.float32(host.scale).tobytes() == (
            np.asarray(scale, np.float32).tobytes()
        )
        np.testing.assert_array_equal(
            np.asarray(qk.dequantize_jnp(codes, scale, "int8")),
            dequantize(host),
        )
        # all-zero block: scale pinned to 1 on both paths
        z_codes, z_scale = qk.quantize_jnp(
            np.zeros(5, np.float32), "int8"
        )
        assert float(z_scale) == quantize(
            np.zeros(5, np.float32), "int8"
        ).scale == 1.0

    def test_blockwise_rows_equal_per_row_host_quantize(self):
        from mpit_tpu import quant as qk

        rng = np.random.default_rng(9)
        a = rng.standard_normal((4, 64)).astype(np.float32) * 10
        a[2] = 0.0  # one all-zero block
        codes, scales = qk.quantize_rows_jnp(a, "int8")
        for j in range(a.shape[0]):
            host = quantize(a[j], "int8")
            np.testing.assert_array_equal(np.asarray(codes)[j], host.data)
            assert np.float32(host.scale).tobytes() == (
                np.asarray(scales, np.float32)[j].tobytes()
            )
        np.testing.assert_array_equal(
            np.asarray(qk.dequantize_rows_jnp(codes, scales, "int8")),
            np.stack([dequantize(quantize(a[j], "int8"))
                      for j in range(a.shape[0])]),
        )


class TestCoalescedScatter:
    def _world(self, center=0.0, **server_kw):
        tps = Broker(2).transports()
        server = PServer(
            tps[0], np.full(DIM, center, np.float32), num_clients=1,
            **server_kw,
        )
        thread = spawn_server_thread(server)
        return tps, server, thread

    def test_repeated_rank_coalesces_to_one_message(self):
        tps, server, thread = self._world()
        # one server owning two adjacent chunks: the classic sharded
        # layout collapsed onto one rank — chunks must merge
        client = PClient(tps[1], [0, 0], DIM, timeout=5)
        assert client.ranks == [0]
        assert client.rank_bounds == [(0, DIM)]
        client.push_easgd(np.ones(DIM, np.float32))
        out = client.fetch()  # FIFO barrier: the push has been applied
        assert out.shape == (DIM,)
        # ONE push message and ONE fetch round trip, not two of each
        assert server.counts["push_easgd"] == 1
        assert server.counts["fetch"] == 1
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_non_adjacent_repeat_accepted(self):
        # the old non-adjacent restriction is lifted: ring placement can
        # hand one rank non-contiguous chunks, and they coalesce into one
        # message per destination (behavior pinned end-to-end in
        # tests/test_sharding.py::TestScatterCoalescing)
        tps = Broker(3).transports()
        client = PClient(tps[2], [0, 1, 0], 12)
        assert client.ranks == [0, 1]
        assert client._rank_chunks[0] == [(0, 4), (8, 12)]

    def test_dedup_holds_across_coalesced_envelope(self):
        tps, server, thread = self._world()
        client = PClient(tps[1], [0, 0], DIM, timeout=5)
        flat = np.ones(DIM, np.float32)
        client.push_easgd(flat)
        # a retry re-offers the identical coalesced envelope (same epoch,
        # same seq, the full merged chunk) — replay it verbatim
        tps[1].send(
            0, TAG_PUSH_EASGD, (client._epoch, 1, 0, flat)
        )
        client.fetch()  # FIFO barrier
        assert server.counts["push_easgd"] == 1
        assert server.counts["dup_dropped"] == 1
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_multi_chunk_param_reply_concatenates(self):
        # a sharded server may answer one coalesced FETCH with its
        # per-shard chunks in a single message: list-of-parts replies
        # reassemble (mixing raw and quantized parts)
        tps = Broker(2).transports()
        client = PClient(tps[1], [0], 12, timeout=5)
        a = np.arange(8, dtype=np.float32)
        b = np.arange(8, 12, dtype=np.float32)
        whole = np.concatenate([a, b])
        assert np.array_equal(client._chunk_ok([a, b], 12), whole)
        got = client._chunk_ok([a, quantize(b, "bf16")], 12)
        np.testing.assert_allclose(got, whole, rtol=2 ** -8)
        # malformed lists are rejected, not crashed on
        assert client._chunk_ok([], 12) is None
        assert client._chunk_ok([a], 12) is None


class TestQuantizedExchange:
    def test_int8_easgd_with_ef_converges(self):
        tps = Broker(2).transports()
        server = PServer(
            tps[0], np.zeros(DIM, np.float32), num_clients=1,
            alpha=0.5, quant="int8",
        )
        thread = spawn_server_thread(server)
        client = PClient(tps[1], [0], DIM, timeout=5, quant="int8")
        rng = np.random.default_rng(11)
        target = rng.standard_normal(DIM).astype(np.float32)
        for _ in range(60):
            center = client.fetch()  # quantized PARAM reply, dequantized
            client.push_easgd(target)
        # without EF the int8 push bias would floor the center error near
        # the quantization step; with it the TRUE center converges well
        # inside it (the fetch view adds one un-fed-back snapshot
        # quantization, so it is only step-accurate)
        snap = server.snapshot()
        step = float(np.max(np.abs(snap))) / 127.0
        err = float(np.max(np.abs(snap - target)))
        assert err < step / 2, (err, step)
        fetch_err = float(np.max(np.abs(client.fetch() - target)))
        assert fetch_err <= err + step / 2 + 1e-6, (fetch_err, step)
        client.stop()
        thread.join(timeout=5)
        assert server.error is None

    def test_unversioned_fetch_never_gets_quantized_reply(self):
        # a legacy client (no attempt id) cannot dequantize — the server
        # must answer it with the raw snapshot even when quant is on
        tps = Broker(2).transports()
        server = PServer(
            tps[0], np.full(DIM, 2.0, np.float32), num_clients=1,
            quant="int8",
        )
        thread = spawn_server_thread(server)
        from mpit_tpu.parallel.pserver import TAG_FETCH, TAG_PARAM

        tps[1].send(0, TAG_FETCH, None)  # legacy un-id'd FETCH
        msg = tps[1].recv(0, TAG_PARAM, timeout=5)
        assert isinstance(msg.payload, np.ndarray)
        np.testing.assert_array_equal(
            msg.payload, np.full(DIM, 2.0, np.float32)
        )
        from mpit_tpu.parallel.pserver import TAG_STOP

        tps[1].send(0, TAG_STOP, None)
        thread.join(timeout=5)
        assert server.error is None

    def test_quant_validation(self):
        tps = Broker(2).transports()
        with pytest.raises(ValueError, match="quant"):
            PServer(
                tps[0], np.zeros(4, np.float32), num_clients=1,
                quant="fp8",
            )


def _free_ports(n):
    import socket as _socket

    probes, addrs = [], []
    for _ in range(n):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        addrs.append(("127.0.0.1", s.getsockname()[1]))
        probes.append(s)
    for s in probes:
        s.close()
    return addrs


class TestMixedVersionSocket:
    """A framed-capable peer and a pickle-only peer (emulated with
    MPIT_WIRE_NEGOTIATE=0 — no hello sent, none awaited, nothing framed)
    must complete real EASGD exchanges in BOTH pairings: negotiation
    falls the framed side back to pickle, and protocol semantics are
    format-independent."""

    @pytest.mark.parametrize("legacy_side", ["server", "client"])
    def test_two_round_easgd_exchange(self, legacy_side, monkeypatch):
        # keep the framed side's hello wait short: the legacy peer will
        # never send one and the connect path eats the full timeout
        monkeypatch.setenv("MPIT_WIRE_NEGOTIATE_TIMEOUT_S", "0.3")
        addrs = _free_ports(2)

        def build(rank, legacy):
            if legacy:
                monkeypatch.setenv("MPIT_WIRE_NEGOTIATE", "0")
            else:
                monkeypatch.delenv("MPIT_WIRE_NEGOTIATE", raising=False)
            return SocketTransport(rank, 2, addresses=addrs)

        srv_tp = build(0, legacy_side == "server")
        cli_tp = build(1, legacy_side == "client")
        alpha = 0.5
        server = PServer(
            srv_tp, np.zeros(DIM, np.float32), num_clients=1, alpha=alpha,
        )
        thread = spawn_server_thread(server)
        client = PClient(cli_tp, [0], DIM, timeout=10)
        try:
            ones = np.ones(DIM, np.float32)
            c0 = client.fetch()
            np.testing.assert_array_equal(c0, np.zeros(DIM))
            client.push_easgd(ones)  # center += alpha * (x - center)
            c1 = client.fetch()
            np.testing.assert_allclose(c1, alpha * ones, rtol=1e-6)
            client.push_easgd(ones)
            c2 = client.fetch()
            np.testing.assert_allclose(
                c2, (alpha + alpha * (1 - alpha)) * ones, rtol=1e-6
            )
            assert server.counts["push_easgd"] == 2
            assert server.counts["fetch"] == 3
        finally:
            client.stop()
            thread.join(timeout=10)
            srv_tp.close()
            cli_tp.close()
        assert server.error is None
