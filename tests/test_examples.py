"""The runnable examples stay runnable (API-drift regression guard).

Examples are documentation that executes — a trainer signature change
that misses one silently breaks the first thing a new user runs. Each
example here runs as a real subprocess, exactly as the README says to
invoke it.
"""

import os
import subprocess
import sys
import pytest

# integration tier — excluded from the smoke run (end-to-end example scripts)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parallelism_tour_runs():
    """The tour (dp/zero/accum/sp/tp/pp x3/ep/composed) provisions its
    own 8-device CPU mesh and must train every section."""
    # generous ceiling: ~10 jitted trainer compiles on the 1-core box
    # under suite contention measured ~160 s; 1800 keeps slow != dead
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "parallelism_tour.py")],
        cwd=REPO, capture_output=True, text=True, timeout=1800,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "tour complete" in out
    for tag in ("dp (sync allreduce)", "ZeRO-1", "grad accumulation",
                "ring attention", "GSPMD", "gpipe", "1f1b",
                "interleaved", "top-2 MoE", "composed"):
        assert tag in out, f"tour section missing: {tag}\n{out}"


def test_generate_text_example_runs():
    """The serving tour trains and decodes with all six recipes."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "generate_text.py"),
         "--steps", "120"],
        cwd=REPO, capture_output=True, text=True, timeout=1800,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    for tag in ("generate ", "generate_fast", "batched row", "beam (K=4)",
                "speculative", "served"):
        assert tag in r.stdout, f"missing: {tag}\n{r.stdout}"
