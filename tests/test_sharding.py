"""Sharded parameter servers: ring, reshard schedule, repair under churn.

The consistent-hash ring (`comm/topology.py`, docs/ROBUSTNESS.md "Shard
ownership & resharding") decides which server owns which slice of the
flat parameter vector; membership churn moves *ownership*, never the cut
points. These tests pin the ring's contract (deterministic across
processes, minimal movement on churn, insensitive to member enumeration
order), the slice-exchange schedule's peak-memory bound (a resharding
server holds its old slice plus the incoming one — never a full model
duplicate), the per-destination scatter coalescing, and the full
failure-during-failure story over the wire: a server killed mid-run is a
repair (rerouted chunks, adopted shards) rather than a skipped round,
and exactly-once survives both graceful handoff and crash-restore.
"""

import json
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from mpit_tpu.comm.topology import (
    HashRing,
    ShardMap,
    reshard_schedule,
    schedule_peak_elems,
    shard_layout,
)
from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import (
    TAG_PUSH_EASGD,
    TAG_SHARD_MAP,
    TAG_STOP,
    PServer,
    spawn_server_thread,
)
from mpit_tpu.transport import Broker

DIM = 97
NSHARDS = 6


def _flat():
    return np.arange(DIM, dtype=np.float32)


def _shard_map(members=(0, 1)):
    return ShardMap(HashRing(members), DIM, NSHARDS)


def _owned_concat(flat0, sm, r):
    rng = sm.ranges_for(r)
    if not rng:
        return np.zeros(0, np.float32)
    return np.concatenate([flat0[s:e] for _, s, e in rng])


# ------------------------------------------------------------------ ring


class TestHashRing:
    def test_deterministic_across_processes(self):
        """Every client and server must derive the same assignment from
        the same member set with no coordination — so the ring may never
        lean on Python's per-process randomized ``hash()``. A fresh
        interpreter with a different forced hash seed must agree."""
        want = ShardMap(HashRing([0, 1, 2]), 300, 12).assignment
        code = (
            "import json;"
            "from mpit_tpu.comm.topology import HashRing, ShardMap;"
            "print(json.dumps(ShardMap(HashRing([0,1,2]),300,12)"
            ".assignment))"
        )
        import os

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONHASHSEED"] = "12345"  # would flip a hash()-based ring
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert tuple(json.loads(proc.stdout)) == want

    def test_assignment_pin(self):
        """Golden pin: a wire-visible constant (rides TAG_SHARD_MAP), so
        a hash-function change must be a deliberate, versioned event."""
        assert _shard_map().assignment == (1, 1, 0, 1, 0, 1)

    def test_member_enumeration_order_is_irrelevant(self):
        """Membership arrives as dict keys / set iteration in places —
        the ring must canonicalize, not trust enumeration order."""
        a = HashRing([0, 1, 2])
        b = HashRing([2, 0, 1, 0])  # permuted, with a duplicate
        assert a == b and a.members == b.members
        for k in range(200):
            assert a.owner(k) == b.owner(k)

    def test_leave_moves_only_the_leavers_keys(self):
        """Consistent hashing's whole point: removing one of N members
        relocates only the keys the leaver owned (~1/N), everything else
        stays put — this is what bounds reshard traffic under churn."""
        keys = range(300)
        ring = HashRing([0, 1, 2])
        shrunk = ring.without(1)
        moved = 0
        for k in keys:
            old = ring.owner(k)
            if old == 1:
                moved += 1
                assert shrunk.owner(k) in (0, 2)
            else:
                assert shrunk.owner(k) == old  # survivor keys never move
        assert 0 < moved <= 150  # ~1/3 of 300; far from a full reshuffle

    def test_join_after_leave_restores_exactly(self):
        ring = HashRing([0, 1, 2])
        back = ring.without(1).with_member(1)
        assert back == ring
        for k in range(200):
            assert back.owner(k) == ring.owner(k)
        assert back.version == ring.version + 2  # churn still visible

    def test_version_bumps_on_every_membership_change(self):
        ring = HashRing([0, 1])
        assert ring.version == 0
        assert ring.with_member(2).version == 1
        assert ring.without(0).version == 1


# ------------------------------------------------------ reshard schedule


class TestReshardSchedule:
    def test_moves_cover_exactly_the_leavers_shards(self):
        old = ShardMap(HashRing([0, 1, 2]), 300, 12)
        new = old.with_ring(old.ring.without(1))
        moves = reshard_schedule(old, new)
        assert {m["shard"] for m in moves} == {
            sid for sid in range(12) if old.assignment[sid] == 1
        }
        for m in moves:
            assert m["src"] == 1 and m["dst"] in (0, 2)
            assert m["size"] == old.shard_size(m["shard"])

    def test_peak_memory_is_old_slice_plus_incoming(self):
        """The acceptance bound: executing the schedule in order, no
        server ever materializes more than its old slice plus what it is
        adopting — never a full-model duplicate."""
        old = ShardMap(HashRing([0, 1, 2]), 300, 12)
        new = old.with_ring(old.ring.without(1))
        moves = reshard_schedule(old, new)
        peak = schedule_peak_elems(moves, old)
        incoming = {r: 0 for r in old.ring.members}
        for m in moves:
            incoming[m["dst"]] += m["size"]
        for r in old.ring.members:
            assert peak[r] <= old.owned_size(r) + incoming[r]
            assert peak[r] < old.param_size  # never the full model
        assert peak[1] == old.owned_size(1)  # the source never grows
        # and the end state is exactly the new ownership
        assert sum(new.owned_size(r) for r in (0, 2)) == 300

    def test_layout_mismatch_rejected(self):
        a = ShardMap(HashRing([0, 1]), 300, 12)
        b = ShardMap(HashRing([0, 1]), 301, 12)
        with pytest.raises(ValueError, match="identical layout"):
            reshard_schedule(a, b)

    def test_layout_is_contiguous_and_near_equal(self):
        bounds = shard_layout(97, 6)
        assert bounds[0][0] == 0 and bounds[-1][1] == 97
        sizes = [e - s for s, e in bounds]
        assert max(sizes) - min(sizes) <= 1
        for (_, e), (s2, _) in zip(bounds, bounds[1:]):
            assert e == s2


# ------------------------------------- per-destination scatter coalescing


class TestScatterCoalescing:
    def test_non_adjacent_chunks_same_rank(self):
        """The lifted restriction: ranks ``[0, 1, 0]`` used to raise —
        now all chunks bound for one destination coalesce into a single
        send/recv pair regardless of adjacency."""
        tps = Broker(3).transports()
        flat0 = np.arange(12, dtype=np.float32)
        s0 = PServer(
            tps[0], np.concatenate([flat0[0:4], flat0[8:12]]), 1
        )
        s1 = PServer(tps[1], flat0[4:8], 1)
        t0, t1 = spawn_server_thread(s0), spawn_server_thread(s1)
        c = PClient(tps[2], [0, 1, 0], 12, timeout=5)
        assert c.ranks == [0, 1]
        assert c._rank_chunks == {0: [(0, 4), (8, 12)], 1: [(4, 8)]}
        np.testing.assert_allclose(c.fetch(), flat0)
        c.push_easgd(flat0)  # push == center: a no-op update
        np.testing.assert_allclose(c.fetch(), flat0)
        c.stop()
        t0.join(5)
        t1.join(5)
        assert not t0.is_alive() and not t1.is_alive()
        assert s0.error is None and s1.error is None
        # ONE push per destination, though rank 0 serves two chunks
        assert s0.counts["push_easgd"] == 1
        assert s1.counts["push_easgd"] == 1


# --------------------------------------------- sharded wire: happy path


class TestShardedProtocol:
    def test_fetch_and_easgd_round_trip(self):
        """Two servers, ring-routed shards: fetch reassembles the flat
        vector exactly, and an EASGD push moves every shard's center by
        alpha toward the pushed params — byte-identical to the single-
        server math, just cut along the static layout."""
        flat0 = _flat()
        tps = Broker(4).transports()
        s0 = PServer(
            tps[0], _owned_concat(flat0, _shard_map(), 0), 2,
            client_ranks=[2, 3], shard_map=_shard_map(),
        )
        s1 = PServer(
            tps[1], _owned_concat(flat0, _shard_map(), 1), 2,
            client_ranks=[2, 3], shard_map=_shard_map(),
        )
        t0, t1 = spawn_server_thread(s0), spawn_server_thread(s1)
        c2 = PClient(tps[2], [0, 1], DIM, timeout=5,
                     shard_map=_shard_map())
        c3 = PClient(tps[3], [0, 1], DIM, timeout=5,
                     shard_map=_shard_map())
        np.testing.assert_allclose(c2.fetch(), flat0)
        c2.push_easgd(flat0)  # center == push: no-op
        c3.push_easgd(np.zeros(DIM, np.float32))
        # alpha=0.5 pulls every shard's center halfway toward zero
        np.testing.assert_allclose(c3.fetch(), flat0 * 0.5)
        c2.stop()
        c3.stop()
        t0.join(5)
        t1.join(5)
        assert s0.counts["push_easgd"] == 2
        assert s1.counts["push_easgd"] == 2
        assert s0.error is None and s1.error is None


# ------------------------------------ failure during failure: the point


class TestKillRepair:
    def test_killed_server_is_a_reshard_not_an_outage(self, tmp_path):
        """One of two servers dies mid-training. The round must NOT be
        skipped: each client times out on the dead rank, drops it from
        its ring view, re-offers the failed chunks to the surviving
        owner (``repaired_chunks``), and the survivor adopts the orphan
        shards from the push payloads. Then the killed server's
        snapshot is restored — and a replayed pre-kill push must still
        be a dup, because the dedup window rode the snapshot."""
        flat0 = _flat()
        path = str(tmp_path / "shard_1.msgpack")
        killed = str(tmp_path / "shard_1.killed.msgpack")
        tps = Broker(4).transports()
        s0 = PServer(
            tps[0], _owned_concat(flat0, _shard_map(), 0), 2,
            client_ranks=[2, 3], shard_map=_shard_map(),
        )
        s1 = PServer(
            tps[1], _owned_concat(flat0, _shard_map(), 1), 2,
            client_ranks=[2, 3], shard_map=_shard_map(),
            ckpt_path=path, ckpt_every=1,
        )
        t0, t1 = spawn_server_thread(s0), spawn_server_thread(s1)
        c2 = PClient(tps[2], [0, 1], DIM, timeout=0.3, max_retries=0,
                     shard_map=_shard_map())
        c3 = PClient(tps[3], [0, 1], DIM, timeout=0.3, max_retries=0,
                     shard_map=_shard_map())
        local2, local3 = flat0.copy(), flat0.copy()

        # healthy round: both clients' seq 1 admitted at both servers
        for c, loc in ((c2, local2), (c3, local3)):
            c.fetch(fallback=loc)
            c.push_easgd(loc)
        deadline = time.monotonic() + 5
        while s1.counts["push_easgd"] < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert s1.counts["push_easgd"] == 2

        # preempt server 1 (both clients' transports release teardown);
        # freeze its snapshot BEFORE the stops rewrite membership
        shutil.copy(path, killed)
        tps[2].send(1, TAG_STOP, None)
        tps[3].send(1, TAG_STOP, None)
        t1.join(5)
        assert not t1.is_alive()

        # post-kill rounds: never an exception, never a skipped round
        skipped = 0
        for _ in range(3):
            for c, loc in ((c2, local2), (c3, local3)):
                try:
                    c.fetch(fallback=loc)
                    c.push_easgd(loc)
                except Exception:
                    skipped += 1
        assert skipped == 0
        assert c2.repaired_chunks > 0 and c3.repaired_chunks > 0
        assert s0.counts["adopted_shards"] > 0
        assert len(s0.owned_ranges()) == NSHARDS  # survivor owns it all
        assert c2.fetch(fallback=local2).shape == (DIM,)
        c2.stop()
        c3.stop()
        t0.join(5)
        assert s0.error is None

        # restore the killed server from its frozen snapshot: the dedup
        # window came back with the center, so the pre-kill (epoch, 1)
        # push is STILL a replay — crash-restore cannot double-apply
        tps2 = Broker(4).transports()
        revived = PServer(
            tps2[1], _owned_concat(flat0, _shard_map(), 1), 2,
            client_ranks=[2, 3], shard_map=_shard_map(),
            ckpt_path=killed, ckpt_every=1,
        )
        t1b = spawn_server_thread(revived)
        assert revived.restored
        parts = [
            (sid, local2[s:e])
            for sid, s, e in revived.owned_ranges()
        ]
        tps2[2].send(1, TAG_PUSH_EASGD, (c2._epoch, 1, 0, parts))
        deadline = time.monotonic() + 5
        while (
            revived.counts["dup_dropped"] < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert revived.counts["dup_dropped"] == 1
        assert revived.counts["push_easgd"] == 0
        # ...while a FRESH seq under the same epoch applies normally
        tps2[2].send(1, TAG_PUSH_EASGD, (c2._epoch, 99, 0, parts))
        tps2[2].send(1, TAG_STOP, None)
        tps2[3].send(1, TAG_STOP, None)
        t1b.join(5)
        assert not t1b.is_alive() and revived.error is None
        assert revived.counts["push_easgd"] == 1


class TestGracefulHandoff:
    def test_handoff_carries_the_dedup_window(self, ):
        """A TAG_SHARD_MAP announce moves shards to a joining server via
        TAG_RESHARD slice exchanges. Exactly-once must survive the
        handoff: a push the OLD owner already admitted is a dup at the
        NEW owner too — the window travels with the slice (the seeded
        mcheck mutation ``handoff_carries_dedup=False`` is exactly this
        bug, caught as MPT009)."""
        flat0 = _flat()
        sm0 = ShardMap(HashRing([0]), DIM, NSHARDS)
        tps = Broker(4).transports()
        s0 = PServer(tps[0], flat0.copy(), 2, client_ranks=[2, 3],
                     shard_map=ShardMap(HashRing([0]), DIM, NSHARDS))
        s1 = PServer(tps[1], np.zeros(0, np.float32), 2,
                     client_ranks=[2, 3],
                     shard_map=ShardMap(HashRing([0]), DIM, NSHARDS))
        t0, t1 = spawn_server_thread(s0), spawn_server_thread(s1)
        c2 = PClient(tps[2], [0], DIM, timeout=2,
                     shard_map=ShardMap(HashRing([0]), DIM, NSHARDS))
        c2.fetch()
        c2.push_easgd(flat0)  # admitted at server 0 as (epoch, seq=1)

        # membership change: rank 1 joins the ring → ownership moves
        ring1 = sm0.ring.with_member(1)
        announce = (ring1.version, list(ring1.members))
        tps[2].send(0, TAG_SHARD_MAP, announce)
        tps[2].send(1, TAG_SHARD_MAP, announce)
        sm1 = sm0.with_ring(ring1)
        moved = [
            sid for sid in range(NSHARDS) if sm1.assignment[sid] == 1
        ]
        assert moved  # the join must actually relocate something
        deadline = time.monotonic() + 5
        while (
            s1.counts["reshard"] < len(moved)
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert s0.counts["handoff_sent"] == len(moved)
        assert s1.counts["reshard"] == len(moved)
        assert len(s0.owned_ranges()) == NSHARDS - len(moved)
        assert len(s1.owned_ranges()) == len(moved)

        # replay the already-admitted push AT THE NEW OWNER: still a dup
        parts = [
            (sid, flat0[s:e])
            for sid, (s, e) in enumerate(sm1.layout)
            if sid in moved
        ]
        tps[2].send(1, TAG_PUSH_EASGD, (c2._epoch, 1, 0, parts))
        deadline = time.monotonic() + 5
        while (
            s1.counts["dup_dropped"] < 1 and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert s1.counts["dup_dropped"] == 1
        assert s1.counts["push_easgd"] == 0
        # a fresh seq is new work, not a replay
        tps[2].send(1, TAG_PUSH_EASGD, (c2._epoch, 2, 0, parts))
        for dst in (0, 1):
            tps[2].send(dst, TAG_STOP, None)
            tps[3].send(dst, TAG_STOP, None)
        t0.join(5)
        t1.join(5)
        assert not t0.is_alive() and not t1.is_alive()
        assert s0.error is None and s1.error is None
        assert s1.counts["push_easgd"] == 1
