"""Unit tests for the comm core: topology + collectives.

These are the tests the reference never had (SURVEY.md §4 "add real unit
tests for the comm API (allreduce/bcast numerics ...)").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpit_tpu
from mpit_tpu.comm import collectives as coll


def shard_map_over(topo, fn, in_specs, out_specs):
    from jax.sharding import PartitionSpec as P

    return jax.jit(
        jax.shard_map(
            fn,
            mesh=topo.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    )


class TestTopology:
    def test_init_discovers_all_devices(self, topo8):
        assert topo8.num_workers == jax.device_count() == 8
        assert topo8.process_count == 1
        assert mpit_tpu.size() == 8
        assert mpit_tpu.process_rank() == 0

    def test_init_idempotent(self, topo8):
        assert mpit_tpu.init() is topo8

    def test_finalize_allows_reinit(self, topo8):
        mpit_tpu.finalize()
        assert not mpit_tpu.is_initialized()
        t2 = mpit_tpu.init(num_workers=4)
        assert t2.num_workers == 4

    def test_subworld(self):
        t = mpit_tpu.init(num_workers=2)
        assert t.num_workers == 2
        assert len(t.devices) == 2

    def test_2d_mesh(self):
        t = mpit_tpu.init(axis_names=("dp", "mp"), mesh_shape=(4, 2))
        assert t.mesh.axis_names == ("dp", "mp")
        # size()/num_workers is the worker-axis length, not total devices
        assert t.num_workers == 4
        assert t.num_devices == 8

    def test_explicit_init_over_existing_raises(self, topo8):
        with pytest.raises(RuntimeError, match="already exists"):
            mpit_tpu.init(num_workers=4)

    def test_bad_mesh_shape_raises(self):
        with pytest.raises(ValueError):
            mpit_tpu.init(mesh_shape=(3,))

    def test_too_many_workers_raises(self):
        with pytest.raises(ValueError):
            mpit_tpu.init(num_workers=1000)


class TestCollectives:
    def test_allreduce_sum_matches_numpy(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        f = shard_map_over(
            topo8, lambda s: coll.allreduce(s, coll.SUM), P("dp", None), P("dp", None)
        )
        out = np.asarray(f(x))
        # every shard holds the global sum of its (1,2) rows
        np.testing.assert_allclose(out, np.tile(x.sum(0), (8, 1)))

    @pytest.mark.parametrize(
        "op,npop",
        [
            (coll.MAX, np.max),
            (coll.MIN, np.min),
            (coll.PROD, np.prod),
        ],
    )
    def test_allreduce_ops(self, topo8, op, npop):
        from jax.sharding import PartitionSpec as P

        rng = np.random.default_rng(0)
        x = rng.uniform(0.5, 1.5, size=(8, 3)).astype(np.float32)
        f = shard_map_over(
            topo8, lambda s: coll.allreduce(s, op), P("dp", None), P("dp", None)
        )
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.tile(npop(x, axis=0), (8, 1)), rtol=1e-5)

    def test_allreduce_avg(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        f = shard_map_over(
            topo8, lambda s: coll.allreduce(s, coll.AVG), P("dp", None), P("dp", None)
        )
        np.testing.assert_allclose(np.asarray(f(x)), np.full((8, 1), 3.5))

    def test_allreduce_pytree(self, topo8):
        from jax.sharding import PartitionSpec as P

        tree = {
            "a": np.ones((8, 2), np.float32),
            "b": {"c": np.full((8, 4), 2.0, np.float32)},
        }
        f = shard_map_over(
            topo8,
            lambda t: coll.allreduce(t),
            ({"a": P("dp", None), "b": {"c": P("dp", None)}},),
            {"a": P("dp", None), "b": {"c": P("dp", None)}},
        )
        out = f(tree)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((8, 2), 8.0))
        np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.full((8, 4), 16.0))

    def test_reducer_table_covers_every_op(self):
        """Every exported op constant except AVG (pmean, dispatched
        directly in allreduce) must have a ``_REDUCERS`` entry — a new
        constant without a reducer previously slipped through as a
        KeyError at trace time (the PROD regression)."""
        ops = {coll.SUM, coll.PROD, coll.MAX, coll.MIN}
        assert set(coll._REDUCERS) == ops
        assert coll.AVG not in coll._REDUCERS
        assert all(callable(r) for r in coll._REDUCERS.values())

    def test_allreduce_unknown_op_raises(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.ones((8, 1), np.float32)
        with pytest.raises(ValueError, match="unknown reduction"):
            f = shard_map_over(
                topo8,
                lambda s: coll.allreduce(s, "bogus"),
                P("dp", None),
                P("dp", None),
            )
            f(x)

    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_bcast_from_root(self, topo8, root):
        from jax.sharding import PartitionSpec as P

        x = np.arange(8, dtype=np.float32).reshape(8, 1) * 10
        f = shard_map_over(
            topo8, lambda s: coll.bcast(s, root=root), P("dp", None), P("dp", None)
        )
        np.testing.assert_allclose(np.asarray(f(x)), np.full((8, 1), root * 10.0))

    def test_bcast_root_out_of_range_raises(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.ones((8, 1), np.float32)
        with pytest.raises(ValueError, match="out of range"):
            shard_map_over(
                topo8, lambda s: coll.bcast(s, root=8), P("dp", None), P("dp", None)
            )(x)

    def test_allgather(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        f = shard_map_over(
            topo8,
            lambda s: coll.allgather(s, tiled=True),
            P("dp", None),
            P(None, None),
        )
        out = np.asarray(f(x))
        # out_specs replicated: every worker returns the full gathered array
        np.testing.assert_allclose(out, x)

    def test_device_barrier_returns_world_size(self, topo8):
        from jax.sharding import PartitionSpec as P

        f = shard_map_over(
            topo8, lambda s: coll.device_barrier() + 0 * s[0, 0].astype(jnp.int32),
            P("dp", None), P()
        )
        assert int(f(np.zeros((8, 1), np.float32))) == 8

    def test_host_barrier_single_process_noop(self, topo8):
        coll.barrier("test")  # must not raise or hang

    def test_ppermute_ring(self, topo8):
        from jax.sharding import PartitionSpec as P

        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        f = shard_map_over(
            topo8, lambda s: coll.ppermute_ring(s, shift=1), P("dp", None), P("dp", None)
        )
        out = np.asarray(f(x)).ravel()
        # worker i sends to i+1: worker 0 now holds worker 7's value
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))

    def test_reduce_scatter(self, topo8):
        from jax.sharding import PartitionSpec as P

        w = 8
        # per-worker input: each worker contributes a full 16-vector; each
        # ends up with its 2-element shard of the cross-worker sum
        x = np.stack(
            [np.arange(16, dtype=np.float32) + 100 * i for i in range(w)]
        )

        def f(s):
            return coll.reduce_scatter(s[0])[None]

        out = shard_map_over(topo8, f, P("dp", None), P("dp", None))(x)
        expected = x.sum(axis=0)  # full reduction, then shard i gets [2i:2i+2]
        np.testing.assert_allclose(np.asarray(out).ravel(), expected)

    def test_rank_inside_spmd(self, topo8):
        from jax.sharding import PartitionSpec as P

        f = shard_map_over(
            topo8,
            lambda s: mpit_tpu.rank().astype(jnp.int32)[None] + 0 * s[:, 0].astype(jnp.int32),
            P("dp", None),
            P("dp"),
        )
        out = np.asarray(f(np.zeros((8, 1), np.float32)))
        np.testing.assert_array_equal(out, np.arange(8))
