"""Benchmark: async-SGD (EASGD) samples/sec/chip on MNIST LeNet.

North-star metric per BASELINE.json:2. The reference published no numbers
(BASELINE.json:13); its bundled example ran Torch7 on CPU (BASELINE.json:7),
so ``vs_baseline`` is measured against the same LeNet training loop in
torch (CPU) built here — the closest live stand-in for the reference stack.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}
Extra fields are informative; the driver keys on the four required ones.

Flags (SURVEY.md §7 step 7 — the harness covers every BASELINE config):
  --preset NAME   time one workload config instead (same JSON-line shape)
  --all           headline metric + a "configs" map over all five workloads
"""

import json
import os
import sys
import time

import numpy as np


def _honor_platform_env():
    """A sitecustomize-registered hardware backend wins over JAX_PLATFORMS
    set after interpreter start; re-pin through the config API (the shared
    recipe in utils/vmesh.py) so CPU-mesh runs of this harness work."""
    if os.environ.get("JAX_PLATFORMS"):
        from mpit_tpu.utils.vmesh import repin_platform

        repin_platform(os.environ["JAX_PLATFORMS"])


def _stage_and_time(trainer, is_sync, topo, x_tr, y_tr, pwb, tau, rounds):
    """The one timing harness (both the headline and the preset benches).

    Dataset lives on device, loaded once outside the timed region: the
    reference's Torch example equally held it in host RAM, and a production
    input pipeline overlaps transfers; timing a per-step host->device copy
    would benchmark this harness's PCIe/tunnel link, not the training
    system. Several distinct pre-staged rounds are cycled so no single batch
    is hot in any cache-like path, staged with the step's own input sharding
    (leading worker axis) — a default device_put would commit to device 0
    and sneak a redistribute-to-mesh back INTO every timed step.
    """
    import jax

    w = topo.num_workers
    gb = pwb * w
    rng = np.random.default_rng(0)
    sharding = topo.worker_sharding()
    step = trainer._step if is_sync else trainer._round
    staged = []
    for _ in range(8):
        idx = rng.integers(0, len(x_tr), tau * gb)
        if is_sync:
            xb, yb = x_tr[idx], y_tr[idx]
        else:
            xb, yb = trainer.round_batches(
                x_tr[idx].reshape(tau, gb, *x_tr.shape[1:]),
                y_tr[idx].reshape(tau, gb, *y_tr.shape[1:]),
            )
        staged.append(
            (jax.device_put(xb, sharding), jax.device_put(yb, sharding))
        )

    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    # warmup (compile)
    for _ in range(3):
        state, m = step(state, *staged[0])
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for r in range(rounds):
        state, m = step(state, *staged[r % len(staged)])
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    samples = rounds * tau * gb
    return {
        "samples_per_sec": samples / dt,
        "samples_per_sec_per_chip": samples / dt / w,
        "chips": w,
        "platform": topo.platform,
        "tau": tau,
        "per_worker_batch": pwb,
        "timed_samples": samples,
        "timed_seconds": round(dt, 3),
    }


def bench_jax(
    per_worker_batch: int = 256,
    tau: int = 4,
    num_workers=None,
    rounds: int = 30,
) -> dict:
    import jax
    import optax

    import mpit_tpu
    from mpit_tpu.data import load_mnist
    from mpit_tpu.models import LeNet
    from mpit_tpu.parallel import EASGDTrainer

    mpit_tpu.finalize()  # allow re-init at a different world size
    topo = mpit_tpu.init(num_workers=num_workers)
    x_tr, y_tr, *_ = load_mnist(synthetic_train=4096)
    trainer = EASGDTrainer(
        LeNet(), optax.sgd(0.05, momentum=0.9), topo, tau=tau
    )
    return _stage_and_time(
        trainer, False, topo, x_tr, y_tr, per_worker_batch, tau, rounds
    )


# throughput-leg sizing per workload preset: (per-worker batch, timed
# rounds), tuned so every leg times >= ~1 s of steady state at the rates
# measured on one v5e chip — long enough that dispatch hiccups and clock
# jitter are sub-percent.
_PRESET_BENCH = {
    "mnist-easgd": (256, 1500),
    "cifar-vgg-sync": (256, 10_000),
    "alexnet-downpour": (64, 6000),
    "resnet50-sync": (32, 1000),
    "ptb-lstm-easgd": (128, 6000),
}


def bench_preset(name: str, num_workers=None, cpu_smoke: bool = False) -> dict:
    """Steady-state training samples/sec/chip for one BASELINE workload
    config (same staging/timing harness as the headline metric)."""
    import dataclasses

    import optax

    import mpit_tpu
    from mpit_tpu.run import _build_model, _load_dataset, build_trainer
    from mpit_tpu.utils.config import TrainConfig

    if name not in _PRESET_BENCH:
        raise ValueError(
            f"unknown bench preset {name!r}; have {sorted(_PRESET_BENCH)}"
        )
    pwb, rounds = _PRESET_BENCH[name]
    image_cap = 128
    if cpu_smoke:
        # tiny wiring run: the XLA-CPU backend's conv compile time explodes
        # with batch AND image size (see main()); shrink both
        pwb, rounds, image_cap = 8, 3, 64
    cfg = TrainConfig().apply_preset(name)

    mpit_tpu.finalize()
    topo = mpit_tpu.init(num_workers=num_workers)
    gb = pwb * topo.num_workers
    tau = 1 if cfg.algo == "sync" else cfg.tau
    cfg = dataclasses.replace(
        cfg, train_size=tau * gb * 2, image_size=min(cfg.image_size, image_cap)
    )
    x_tr, y_tr, *_rest, _meta = _load_dataset(cfg)
    model = _build_model(cfg, _meta)
    opt = optax.sgd(cfg.lr, momentum=cfg.momentum)
    trainer = build_trainer(cfg, model, opt, topo)
    res = _stage_and_time(
        trainer, cfg.algo == "sync", topo, x_tr, y_tr, pwb, tau, rounds
    )
    return {**res, "algo": cfg.algo, "model": cfg.model}


def measure_scaling_efficiency(full: dict) -> dict:
    """Scaling efficiency vs single chip (the BASELINE.md north-star's
    second half: per-chip throughput at W chips / per-chip throughput at 1).

    Only meaningful with >1 REAL device — on one chip (or a CPU-simulated
    mesh sharing one host) the honest answer is null, not a fake 100%."""
    import jax

    n = len(jax.devices())
    if n < 2 or jax.devices()[0].platform == "cpu":
        return {"scaling_efficiency": None, "scaling_note":
                f"needs >1 real chip (found {n} "
                f"{jax.devices()[0].platform} device(s))"}
    # same ~1M-sample budget as the numerator: a short denominator leg would
    # put run-to-run noise straight into the efficiency ratio
    single = bench_jax(num_workers=1, rounds=1000)
    eff = full["samples_per_sec_per_chip"] / single["samples_per_sec_per_chip"]
    return {
        "scaling_efficiency": round(eff, 4),
        "single_chip_samples_per_sec": round(
            single["samples_per_sec_per_chip"], 1
        ),
    }


def bench_torch_cpu(batch: int = 256, steps: int = 12) -> float:
    """Reference-stack stand-in: the same LeNet trained with torch on CPU
    (the reference's ptest example ran Torch on CPU, BASELINE.json:7)."""
    try:
        import torch
        import torch.nn as tnn
    except Exception:
        return float("nan")

    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Conv2d(1, 32, 5, padding=2), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Conv2d(32, 64, 5, padding=2), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Flatten(),
        tnn.Linear(64 * 7 * 7, 256), tnn.ReLU(),
        tnn.Linear(256, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
    loss_fn = tnn.CrossEntropyLoss()
    x = torch.rand(batch, 1, 28, 28)
    y = torch.randint(0, 10, (batch,))
    # warmup
    for _ in range(2):
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    dt = time.perf_counter() - t0
    return batch * steps / dt


def main():
    _honor_platform_env()
    import jax

    cpu = jax.devices()[0].platform == "cpu"

    if "--preset" in sys.argv:
        name = sys.argv[sys.argv.index("--preset") + 1]
        try:
            res = bench_preset(name, cpu_smoke=cpu)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(json.dumps({
            "metric": f"{name}_throughput",
            "value": round(res["samples_per_sec_per_chip"], 1),
            "unit": "samples/sec/chip",
            "vs_baseline": None,  # only the headline config has a baseline
            **{k: res[k] for k in ("chips", "algo", "model")},
        }))
        return

    if cpu:
        # smoke-run sizing: a CPU mesh shares one host's cores AND the CPU
        # backend's conv compile time grows steeply with batch size (>200s
        # at 64/worker); keep the smoke run tiny — the number it prints is
        # wiring validation, not a benchmark
        jax_res = bench_jax(per_worker_batch=8, rounds=3)
    else:
        # at ~100k+ samples/sec/chip a 30-round run is noise; time ~1M samples
        jax_res = bench_jax(rounds=1000)
    scaling = measure_scaling_efficiency(jax_res)
    torch_sps = bench_torch_cpu()
    value = jax_res["samples_per_sec_per_chip"]
    # no torch -> no baseline measurement; report null, not fake parity
    vs = round(value / torch_sps, 2) if np.isfinite(torch_sps) else None
    out = {
        "metric": "easgd_mnist_lenet_throughput",
        "value": round(value, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": vs,
        "baseline": "torch-cpu LeNet train step (reference ran Torch on CPU)",
        "baseline_samples_per_sec": round(torch_sps, 1)
        if np.isfinite(torch_sps)
        else None,
        "chips": jax_res["chips"],
        "platform": jax_res["platform"],
        **scaling,
    }
    if "--all" in sys.argv:
        out["configs"] = {
            name: round(
                bench_preset(name, cpu_smoke=cpu)["samples_per_sec_per_chip"],
                1,
            )
            for name in _PRESET_BENCH
            if name != "mnist-easgd"  # the headline metric above
        }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
