"""Benchmark: async-SGD (EASGD) samples/sec/chip on MNIST LeNet.

North-star metric per BASELINE.json:2. The reference published no numbers
(BASELINE.json:13); its bundled example ran Torch7 on CPU (BASELINE.json:7),
so ``vs_baseline`` is measured against the same LeNet training loop in
torch (CPU) built here — the closest live stand-in for the reference stack.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}
Extra fields are informative; the driver keys on the four required ones.

Flags (SURVEY.md §7 step 7 — the harness covers every BASELINE config):
  --preset NAME   time one workload config instead (same JSON-line shape)
  --all           headline metric + a "configs" map over all five workloads
  --profile DIR   capture a jax.profiler trace of the whole benchmark run
                  (staging + compile + timed legs) into DIR; opens in
                  Perfetto/TensorBoard: XLA op timeline, collectives
                  included. Profiling adds overhead — the JSON line carries
                  "profiled": true so the number is never mistaken for a
                  clean benchmark result.
"""

import json
import os
import sys
import time

import numpy as np


def _honor_platform_env():
    """A sitecustomize-registered hardware backend wins over JAX_PLATFORMS
    set after interpreter start; re-pin through the config API (the shared
    recipe in utils/vmesh.py) so CPU-mesh runs of this harness work."""
    if os.environ.get("JAX_PLATFORMS"):
        from mpit_tpu.utils.vmesh import repin_platform

        repin_platform(os.environ["JAX_PLATFORMS"])


# one probe per process: the verdict cannot change mid-run, and a --all
# sweep re-probing before every leg would burn 2x180 s per preset on a
# dead tunnel. "seconds" is the wall-clock the probe cost this process.
_PROBE_CACHE: dict = {}


def _backend_alive(timeout: float = None, attempts: int = 2) -> bool:
    """Probe the default backend in a TIME-LIMITED subprocess (kill-safe
    pattern shared in ``mpit_tpu.utils.vmesh.run_bounded``).

    Initializing the axon backend in-process hangs indefinitely while the
    TPU tunnel is down (observed 2026-07-29); a benchmark that hangs
    produces no JSON line at all. A generous timeout plus one retry keeps a
    merely-slow cold tunnel (or one transient plugin error) from silently
    downgrading a real benchmark run to CPU smoke numbers.

    The verdict is cached per process, the per-attempt timeout honors
    ``MPIT_BENCH_PROBE_TIMEOUT`` (seconds, default 180), and the probe's
    wall-clock cost lands in the JSON line as ``probe_seconds`` so the
    fallback's 2x-timeout burn is visible instead of silent."""
    if "ok" in _PROBE_CACHE:
        return _PROBE_CACHE["ok"]
    if timeout is None:
        timeout = float(os.environ.get("MPIT_BENCH_PROBE_TIMEOUT", "180"))
    from mpit_tpu.utils.vmesh import run_bounded

    t0 = time.perf_counter()
    ok = any(
        run_bounded("import jax; jax.devices()", timeout=timeout, quiet=True)
        == 0
        for _ in range(attempts)
    )
    _PROBE_CACHE["ok"] = ok
    _PROBE_CACHE["seconds"] = round(time.perf_counter() - t0, 3)
    return ok


def _probe_tag() -> dict:
    """``{"probe_seconds": N}`` for the JSON line, when a probe ran — in
    this process, or (after the cpu re-exec) in the parent, whose cost
    rides in on MPIT_BENCH_PROBE_SECONDS."""
    secs = _PROBE_CACHE.get("seconds")
    if secs is None:
        env = os.environ.get("MPIT_BENCH_PROBE_SECONDS")
        if env:
            try:
                secs = float(env)
            except ValueError:
                secs = None
    return {"probe_seconds": secs} if secs is not None else {}


def _force_completion(state, m) -> float:
    """Proof of execution, not just dispatch — shared implementation in
    ``mpit_tpu.utils.profiling.force_completion`` (see its docstring for
    the platform finding): one fused scalar, data-dependent on both the
    final state (optimizer update) and the last metrics (fwd/bwd chain),
    fetched with a single tunnel round-trip."""
    from mpit_tpu.utils.profiling import force_completion

    return force_completion(state, m)


def _leg_phases(raw_dt: float, dt: float) -> dict:
    """Roofline phase fractions for a collective timed leg (the schema
    docs/OBSERVABILITY.md §roofline defines; ``phase_source:
    "timed-leg"``). The collective trainers run compute and collective
    transfer fused inside one XLA program, so the leg cannot split wire
    from compute — the honest attribution is: corrected time is compute
    (which here INCLUDES in-program collectives), the subtracted fetch
    RTT is harness overhead, wire/idle are unmeasured zeros. The
    host-async PS bench reports the real four-way split from its obs
    journals instead (``phase_source: "obs"``)."""
    compute = min(dt / raw_dt, 1.0) if raw_dt > 0 else 0.0
    return {
        "compute": round(compute, 4),
        "wire": 0.0,
        "idle": 0.0,
        "overhead": round(1.0 - compute, 4),
    }


_MEASUREMENTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "docs", "measurements"
)


def last_tpu_measurement(key: str):
    """Latest archived real-hardware number for ``key`` (a preset name or
    "decode[-bf16]"), from docs/measurements/LATEST.json — the evidence
    trail the CPU-fallback JSON carries so an outage round still ships a
    driver-visible TPU number (with its date and caveat) instead of
    silently reporting smoke throughput alone."""
    try:
        with open(os.path.join(_MEASUREMENTS, "LATEST.json")) as f:
            return json.load(f).get(key)
    except Exception:
        return None


def update_latest_measurement(key: str, record: dict) -> None:
    """Record a fresh real-hardware measurement under ``key`` in
    LATEST.json (called by this harness and scripts/measure_presets.py
    whenever a leg lands on a non-cpu platform). Best-effort: a read-only
    checkout must not fail the benchmark that produced the number."""
    path = os.path.join(_MEASUREMENTS, "LATEST.json")
    try:
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            data = {}
        data[key] = {
            **record,
            "date": time.strftime("%Y-%m-%d"),
            "caveat": "builder-measured on the live tunnel, "
                      "not driver-captured",
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
        os.replace(tmp, path)
    except Exception:
        pass


# Dense bf16 peak FLOP/s per chip, by device_kind substring (models here
# compute in bfloat16). Used for the MFU denominator; unknown kinds -> None.
_PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}


def _peak_flops_per_chip():
    import jax

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return None
    kind = getattr(dev, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def _jaxpr_flops(jaxpr) -> float:
    """Matmul/conv FLOPs (2/MAC) in a jaxpr, recursing into sub-jaxprs
    (pjit, custom_vjp, ...) and multiplying scan bodies by trip count."""
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            batch = float(np.prod([lhs[i] for i in lb], dtype=np.float64))
            contract = float(np.prod([lhs[i] for i in lc], dtype=np.float64))
            lhs_free = float(np.prod(
                [d for i, d in enumerate(lhs) if i not in lc and i not in lb],
                dtype=np.float64,
            ))
            rhs_free = float(np.prod(
                [d for i, d in enumerate(rhs) if i not in rc and i not in _rb],
                dtype=np.float64,
            ))
            total += 2.0 * batch * contract * lhs_free * rhs_free
        elif name == "conv_general_dilated":
            out = eqn.outvars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            rhs_spec = eqn.params["dimension_numbers"].rhs_spec
            k_spatial = float(np.prod(
                [rhs[i] for i in rhs_spec[2:]], dtype=np.float64
            ))
            # rhs input-feature dim is already per-group (C_in / groups)
            in_ch = float(rhs[rhs_spec[1]])
            total += (
                2.0 * float(np.prod(out, dtype=np.float64)) * k_spatial * in_ch
            )
        elif eqn.params:
            mult = float(eqn.params.get("length", 1)) if name == "scan" else 1.0
            for val in eqn.params.values():
                for sub in val if isinstance(val, (tuple, list)) else (val,):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        total += mult * _jaxpr_flops(inner)
                    elif hasattr(sub, "eqns"):
                        total += mult * _jaxpr_flops(sub)
    return total


def _model_flops_per_sample(trainer, state, x, y):
    """Fwd+bwd FLOPs per sample: dot/conv FLOPs counted in the jaxpr of a
    plain grad of the trainer's loss — the standard MFU accounting basis
    (matmul FLOPs only). Host-side tracing, no XLA compile: an AOT compile
    of ResNet-50@224 for cost analysis doubled the bench's wall time, and
    the compiled cost model undercounts ``lax.scan`` bodies (counted once
    regardless of trip count). Calibration on LeNet grad: 67.6M
    flops/sample here vs 58.2M from XLA's compiled cost analysis — the
    delta is first-layer input-gradients the compiler DCEs; this counter
    follows the standard analytic convention (≈3× forward) and is applied
    uniformly across presets."""
    import jax

    try:
        if isinstance(state, dict):  # pipeline trainer: dict state
            params = state["params"]
        else:
            params = state.center if hasattr(state, "center") else state.params
        loss_fn = trainer.loss_fn
        model = getattr(trainer, "model", None)
        if model is not None and getattr(model, "seq_axis", None):
            # the sharded model needs a mesh axis to trace; its dense twin
            # computes the same FLOPs per sample
            from mpit_tpu.parallel.common import default_loss_fn

            loss_fn = default_loss_fn(model.clone(seq_axis=None).apply)
        jaxpr = jax.make_jaxpr(jax.grad(loss_fn))(params, x, y)
        flops = _jaxpr_flops(jaxpr.jaxpr)
        return flops / len(x) if np.isfinite(flops) and flops > 0 else None
    except Exception:
        return None


def _stage_and_time(
    trainer, is_sync, topo, x_tr, y_tr, pwb, tau,
    rounds=None, target_seconds=2.0, input_dtype="float32", repeats=1,
):
    """The one timing harness (both the headline and the preset benches).

    Dataset lives on device, loaded once outside the timed region: the
    reference's Torch example equally held it in host RAM, and a production
    input pipeline overlaps transfers; timing a per-step host->device copy
    would benchmark this harness's PCIe/tunnel link, not the training
    system. Several distinct pre-staged rounds are cycled so no single batch
    is hot in any cache-like path, staged with the step's own input sharding
    (leading worker axis) — a default device_put would commit to device 0
    and sneak a redistribute-to-mesh back INTO every timed step.

    ``rounds=None`` sizes the timed leg adaptively from a short calibration
    run so every preset times ~``target_seconds`` of steady state regardless
    of how fast its step is. Completion of each leg is proven by
    ``_force_completion`` — never by ``block_until_ready`` (see its
    docstring for why that lies here).
    """
    import jax

    from mpit_tpu.data import cast_input_dtype

    w = topo.num_workers
    gb = pwb * w
    rng = np.random.default_rng(0)
    # the seq trainer's inputs shard over BOTH mesh axes; everything else
    # shards the leading batch axis over the worker axis
    sharding = (
        trainer.data_sharding()
        if hasattr(trainer, "data_sharding")
        else topo.worker_sharding()
    )
    x_tr = cast_input_dtype(x_tr, input_dtype)
    staged = []
    for _ in range(8):
        idx = rng.integers(0, len(x_tr), tau * gb)
        if is_sync:
            xb, yb = x_tr[idx], y_tr[idx]
        else:
            xb, yb = trainer.round_batches(
                x_tr[idx].reshape(tau, gb, *x_tr.shape[1:]),
                y_tr[idx].reshape(tau, gb, *y_tr.shape[1:]),
            )
        staged.append(
            (jax.device_put(xb, sharding), jax.device_put(yb, sharding))
        )

    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    # grab the compiled step AFTER init_state: some trainers (MoE) build
    # it lazily from the state template
    step = trainer._step if is_sync else trainer._round
    flops_per_sample = _model_flops_per_sample(
        trainer, state, x_tr[:gb], y_tr[:gb]
    )
    # warmup (compile; also compiles _force_completion's reduction)
    from mpit_tpu.parallel.common import bound_cpu_dispatch

    for _ in range(3):
        state, m = step(state, *staged[0])
        bound_cpu_dispatch(topo, m)  # cpu-mesh rendezvous deadlock guard
    _force_completion(state, m)
    # Pure fetch latency: everything is already complete here, so timing a
    # second completion fetch measures the host round-trip alone. It is
    # subtracted from each timed leg — the fetch proves completion but its
    # fixed tunnel RTT (~100 ms) is harness cost, not training time.
    t_f = time.perf_counter()
    _force_completion(state, m)
    fetch_overhead = time.perf_counter() - t_f

    def time_leg(state, m, n_rounds):
        """THE timed-leg rule, in one place (every leg — adaptive sizing
        and variance repeats — must measure under identical rules): run
        ``n_rounds``, prove completion, subtract the calibrated fetch
        RTT clamped to half the leg (the correction must trim bias, not
        manufacture throughput out of a mis-measured RTT)."""
        t0 = time.perf_counter()
        for r in range(n_rounds):
            state, m = step(state, *staged[r % len(staged)])
            bound_cpu_dispatch(topo, m)  # no-op on real chips (async)
        _force_completion(state, m)
        raw = time.perf_counter() - t0
        return state, m, raw, max(raw - fetch_overhead, raw * 0.5)

    adaptive = rounds is None
    if adaptive:
        rounds = 10
    while True:
        state, m, raw_dt, dt = time_leg(state, m, rounds)
        # The completion fetch pays one host round-trip (~100 ms on the
        # tunnel), so a leg sized from a short calibration undershoots
        # badly; grow until the leg genuinely covers the target.
        if not adaptive or raw_dt >= 0.7 * target_seconds or rounds >= 50_000:
            break
        rounds = int(
            min(max(rounds * target_seconds / raw_dt * 1.2, rounds * 2),
                50_000)
        )

    samples = rounds * tau * gb
    # variance control (the 35%-outlier class, PERF.md): re-run the
    # same-sized leg repeats-1 more times, report the MEDIAN rate and the
    # relative spread so a host-interference outlier is visible in the
    # row instead of silently kept. One leg (the default) reports
    # spread=None — absence of evidence, not zero variance.
    leg_rates = [samples / dt]
    for _ in range(repeats - 1):
        state, m, _raw, leg_dt = time_leg(state, m, rounds)
        leg_rates.append(samples / leg_dt)
    rate = float(np.median(leg_rates))
    spread = (
        round((max(leg_rates) - min(leg_rates)) / rate, 4)
        if len(leg_rates) > 1 else None
    )
    chips = topo.num_devices  # == w except on the 2-D seq-sync mesh
    res = {
        "samples_per_sec": rate,
        "samples_per_sec_per_chip": rate / chips,
        "chips": chips,
        "platform": topo.platform,
        "tau": tau,
        "per_worker_batch": pwb,
        "timed_rounds": rounds,
        "timed_samples": samples,
        "timed_seconds": round(samples / rate, 3),
        "repeats": len(leg_rates),
        # phase split of the last calibration leg (raw vs corrected time)
        "phases": _leg_phases(raw_dt, dt),
        "phase_source": "timed-leg",
        "spread": spread,
        # >10% leg-to-leg swing: host interference suspected — the row
        # needs a solo re-run before it is quoted (PERF.md variance note)
        "variance_flagged": bool(spread is not None and spread > 0.10),
    }
    peak = _peak_flops_per_chip()
    if flops_per_sample is not None:
        achieved = flops_per_sample * res["samples_per_sec_per_chip"]
        res["model_flops_per_sample"] = round(flops_per_sample, 1)
        res["model_flops_per_sec_per_chip"] = round(achieved, 1)
        if peak is not None:
            res["mfu"] = round(achieved / peak, 4)
            res["mfu_peak_flops"] = peak
    return res


def bench_jax(
    per_worker_batch: int = 1024,
    tau: int = 4,
    num_workers=None,
    rounds=None,
    input_dtype: str = "float32",
    repeats: int = 1,
) -> dict:
    import jax
    import optax

    import mpit_tpu
    from mpit_tpu.data import load_mnist
    from mpit_tpu.models import LeNet
    from mpit_tpu.parallel import EASGDTrainer

    mpit_tpu.finalize()  # allow re-init at a different world size
    topo = mpit_tpu.init(num_workers=num_workers)
    x_tr, y_tr, *_ = load_mnist(synthetic_train=4096)
    trainer = EASGDTrainer(
        LeNet(), optax.sgd(0.05, momentum=0.9), topo, tau=tau
    )
    return _stage_and_time(
        trainer, False, topo, x_tr, y_tr, per_worker_batch, tau, rounds,
        input_dtype=input_dtype, repeats=repeats,
    )


# per-worker batch for each workload preset; the timed-leg length is sized
# adaptively by _stage_and_time so every preset times ~2 s of steady state
# at whatever rate the platform actually delivers.
_PRESET_BENCH = {
    "mnist-easgd": 1024,
    "cifar-vgg-sync": 256,
    "alexnet-downpour": 64,
    "resnet50-sync": 32,
    "ptb-lstm-easgd": 128,
    # beyond-parity long-context config (T=256 tokens/sample; sp=1 on one
    # chip — the ring is exercised by the CPU-mesh tests and dryrun)
    "ptb-transformer-seq": 64,
    # beyond-parity pipeline config (pp=1 on one chip — microbatching and
    # the schedule still run; multi-stage proven on the CPU mesh/dryrun)
    "ptb-transformer-pp": 64,
    # MFU-ceiling config: GPT-2-small shape (768/3072, T=512) — the row
    # that shows the low parity-preset MFUs are model shapes, not the
    # framework
    "ptb-transformer-large": 8,
}
# every benchmarkable preset (the staged collective ones above plus the
# host-async literal-PS shape, which has its own harness)
ALL_BENCH_PRESETS = (*_PRESET_BENCH, "mnist-ps")


def bench_ps_literal(
    cpu_smoke: bool = False, input_dtype: str = "float32"
) -> dict:
    """The reference's literal shape (BASELINE.json:7): host-async PS,
    2 pclients + 1 pserver, concurrent actors over the tagged transport.

    Unlike the collective presets this measures the HOST-ASYNC path: the
    wall clock covers the whole concurrent run (client threads, tagged
    messages, server dispatch), and client losses are host-fetched in one
    batched transfer at every τ exchange (the exchange itself proves
    completion; fetching EVERY step timed the device round-trip instead
    of the system). A short untimed run first warms the shared jitted local step
    (one compiled function for all clients), so the timed leg measures
    steady state like the other presets; smoke mode shrinks the per-client
    batch too (XLA-CPU conv compile time explodes with batch size).

    The timed run is obs-armed: journals land in a throwaway dir and the
    roofline join (``mpit_tpu.obs.roofline``) turns them into the
    ``phases: {compute, wire, idle, overhead}`` split every bench JSON
    line now carries — here measured for real (``phase_source: "obs"``),
    compute spans proof-of-completion-closed by the training loop. The
    warmup run stays un-instrumented: journals append, so a warmed
    journal would pollute the timed window.

    The same journals also yield the ``dynamics`` roll-up (staleness
    p99, final elastic distance, update/param norm ratio) — update
    QUALITY riding next to samples/s, so an async-speedup comparison
    carries its own convergence-cost evidence (``scripts/bench_gate.py``
    compares the fields across runs)."""
    import tempfile

    import optax

    from mpit_tpu.data import load_mnist
    from mpit_tpu.run import _build_model
    from mpit_tpu.parallel import AsyncPSTrainer
    from mpit_tpu.utils.config import TrainConfig

    from mpit_tpu.data import cast_input_dtype

    cfg = TrainConfig().apply_preset("mnist-ps")
    per_client = 8 if cpu_smoke else max(cfg.global_batch // cfg.clients, 1)
    steps = 24 if cpu_smoke else 600
    x_tr, y_tr, x_te, y_te = load_mnist(synthetic_train=2048)
    x_tr = cast_input_dtype(x_tr, input_dtype)
    # the wire-format A/B lever (docs/WIRE.md): MPIT_BENCH_PS_TRANSPORT=
    # socket runs the same actors over real loopback TCP, where
    # MPIT_WIRE_FORMAT / MPIT_WIRE_QUANT select the codec — the framed-vs-
    # pickle serialize+deserialize comparison the fast-wire item records
    ps_transport = os.environ.get("MPIT_BENCH_PS_TRANSPORT", "auto")
    trainer = AsyncPSTrainer(
        _build_model(cfg, {}),
        optax.sgd(cfg.lr, momentum=cfg.momentum),
        num_clients=cfg.clients,
        num_servers=cfg.servers,
        algo=cfg.resolved_algo().removeprefix("ps-"),
        alpha=cfg.alpha if cfg.alpha is not None else 0.9 / cfg.clients,
        tau=cfg.tau,
        transport=ps_transport,
    )
    from mpit_tpu.obs import ObsConfig, roofline
    from mpit_tpu.obs.live import aggregate, read_snapshots, validate_snapshot

    # warm the shared jitted local step outside the timed region —
    # deliberately WITHOUT obs (journals append; see docstring)
    trainer.train(x_tr, y_tr, steps=2 * cfg.tau, batch_size=per_client)
    with tempfile.TemporaryDirectory(prefix="mpit_bench_obs_") as obs_dir:
        # arm obs for the timed run only: train() reads self.obs per
        # call, and the shared jitted step is already compiled, so the
        # attribute swap changes instrumentation, not the compute. live
        # rides along — the exporter is one 1 Hz daemon thread per rank,
        # and every bench run then doubles as a live-plane schema check
        trainer.obs = ObsConfig(dir=obs_dir, live=True)
        t0 = time.perf_counter()
        center, stats = trainer.train(
            x_tr, y_tr, steps=steps, batch_size=per_client, seed=1
        )
        wall = time.perf_counter() - t0
        trainer.obs = None
        report = roofline([obs_dir])
        snaps = read_snapshots(os.path.join(obs_dir, "live"))
        live_rep = aggregate(snaps) if snaps else None
        live_invalid = sum(
            1 for s in snaps.values() if validate_snapshot(s)
        )
        # update-quality roll-up from the same journals (must run inside
        # the with-block — the tempdir dies at dedent): staleness p99,
        # final elastic distance, update/param norm ratio — the quality
        # counterweight to samples/s for async-speedup comparisons
        from mpit_tpu.obs.dynamics import aggregate_dynamics

        dyn_run = aggregate_dynamics([obs_dir])["run"]
    run = report["run"]
    samples = steps * per_client * cfg.clients
    # wire-phase seconds summed across ranks from the telemetry
    # summaries: serialize/queue_wait/write off the SendHandles,
    # transfer/deserialize off the socket read loops — the exact
    # quantity the framed codec is meant to shrink (zero when the
    # transport measures no split, i.e. the reference-passing brokers)
    wire_detail = {
        "serialize_s": 0.0, "queue_wait_s": 0.0, "write_s": 0.0,
        "transfer_s": 0.0, "deserialize_s": 0.0,
    }
    for tel in stats.get("telemetry", []):
        for s in tel.get("send", {}).values():
            ph = s.get("phase_s", {})
            wire_detail["serialize_s"] += ph.get("serialize", 0.0)
            wire_detail["queue_wait_s"] += ph.get("queue_wait", 0.0)
            wire_detail["write_s"] += ph.get("write", 0.0)
        for v in tel.get("rx_phase_s", {}).values():
            wire_detail["transfer_s"] += v.get("transfer", 0.0)
            wire_detail["deserialize_s"] += v.get("deserialize", 0.0)
    wire_detail = {k: round(v, 4) for k, v in wire_detail.items()}
    from mpit_tpu.transport import wire as _wirecodec

    return {
        "samples_per_sec": samples / wall,
        # one host (and on this rig one chip) runs all actors
        "samples_per_sec_per_chip": samples / wall,
        "chips": 1,
        "algo": cfg.algo,
        "model": cfg.model,
        "clients": cfg.clients,
        "servers": cfg.servers,
        "accuracy": trainer.evaluate(center, x_te, y_te),
        "timed_seconds": round(wall, 3),
        "per_client_batch": per_client,
        "ps_transport": ps_transport,
        # effective codec knobs: the framed/pickle split only exists on
        # the socket path; broker modes pass references (no codec at all)
        "wire_format": (
            _wirecodec.wire_format_from_env()
            if ps_transport == "socket" else "none"
        ),
        "wire_quant": _wirecodec.quant_mode_from_env(),
        "wire_detail": wire_detail,
        **({
            "wire_bytes_total": sum(
                w["tx"] for w in stats["wire_bytes"]
            ),
        } if "wire_bytes" in stats else {}),
        **({
            "phases": {
                k: round(v, 4) for k, v in run["phases"].items()
            },
            "phase_source": "obs",
        } if run is not None else {}),
        **({
            # live-plane cross-check: rank count and final rolling
            # throughput from the in-run snapshots (the wall-clock
            # metric above remains the headline number)
            "live": {
                "ranks": live_rep["run"]["ranks"],
                "throughput": live_rep["run"]["throughput"],
                "invalid_snapshots": live_invalid,
            },
        } if live_rep is not None else {}),
        **({
            "dynamics": {
                "staleness_p99": dyn_run["staleness_p99"],
                "elastic_dist_final": (
                    None if dyn_run["elastic_dist_final"] is None
                    else round(dyn_run["elastic_dist_final"], 4)
                ),
                "norm_ratio": (
                    None if dyn_run["norm_ratio"] is None
                    else round(dyn_run["norm_ratio"], 5)
                ),
            },
        } if dyn_run is not None else {}),
    }


def bench_wire(cpu_smoke: bool = False) -> dict:
    """Codec microbench (the ``--wire`` preset): per-payload-size
    round-trip cost of the three wire paths — pickle (the old format),
    framed (``transport/wire.py``, zero-copy binary), and framed+int8
    quantized — plus a loopback-TCP one-way leg through real
    :class:`SocketTransport` pairs in both formats.

    The headline ``value`` is framed encode+decode throughput (MB/s,
    largest payload — higher is better); the per-size ``*_ms`` fields are
    what ``scripts/bench_gate.py --trend`` watches for codec regressions.
    Payloads are the PS push envelope shape ``(epoch, seq, basis,
    chunk)`` — the hot-path message this codec exists for."""
    import pickle
    import socket as _socket

    from mpit_tpu.transport import wire
    from mpit_tpu.transport.socket_transport import (
        WIRE_PICKLE_PROTOCOL,
        SocketTransport,
    )

    sizes = (
        {"4kb": 1 << 10, "64kb": 1 << 14}
        if cpu_smoke else
        {"64kb": 1 << 14, "1mb": 1 << 18, "4mb": 1 << 20}
    )
    rng = np.random.default_rng(7)
    fields: dict = {}
    framed_mbps = 0.0
    for label, n in sizes.items():
        arr = rng.standard_normal(n).astype(np.float32)
        payload = (1 << 62, 17, 3, arr)
        nbytes = arr.nbytes
        reps = max(3, min(200, int(2e8 / max(nbytes, 1))))

        t0 = time.perf_counter()
        for _ in range(reps):
            blob = pickle.dumps(payload, protocol=WIRE_PICKLE_PROTOCOL)
            pickle.loads(blob)
        fields[f"pickle_{label}_ms"] = (
            (time.perf_counter() - t0) / reps * 1e3
        )

        t0 = time.perf_counter()
        for _ in range(reps):
            bufs = wire.encode_frame(
                1, 2, payload, version=wire.WIRE_FORMAT_VERSION
            )
            head = bytes(bufs[0])
            body = b"".join(bytes(b) for b in bufs[1:])
            _v, flags, hlen, hcrc = wire.split_preamble(
                head[: wire.PREAMBLE_SIZE]
            )
            wire.decode_frame(
                flags, hcrc, head[wire.PREAMBLE_SIZE:], body
            )
        dt = (time.perf_counter() - t0) / reps
        fields[f"framed_{label}_ms"] = dt * 1e3
        framed_mbps = nbytes / dt / 1e6  # last (largest) size wins

        t0 = time.perf_counter()
        for _ in range(reps):
            q = wire.quantize(arr, "int8")
            bufs = wire.encode_frame(
                1, 2, (1 << 62, 17, 3, q),
                version=wire.WIRE_FORMAT_VERSION,
            )
            head = bytes(bufs[0])
            body = b"".join(bytes(b) for b in bufs[1:])
            _v, flags, hlen, hcrc = wire.split_preamble(
                head[: wire.PREAMBLE_SIZE]
            )
            _s, _t, out = wire.decode_frame(
                flags, hcrc, head[wire.PREAMBLE_SIZE:], body
            )
            wire.dequantize(out[3])
        fields[f"quant_int8_{label}_ms"] = (
            (time.perf_counter() - t0) / reps * 1e3
        )

    # loopback-TCP one-way leg: real sockets, both codecs. Same payload
    # count and size; the delta is the serialize+copy the framed path
    # removed (plus the 4x bytes the pickle of an f32 array still moves).
    msg_n = sizes[max(sizes, key=lambda k: sizes[k])]
    msgs = 8 if cpu_smoke else 32
    arr = rng.standard_normal(msg_n).astype(np.float32)
    for fmt in ("pickle", "framed"):
        probes = []
        addrs = []
        for _ in range(2):
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            addrs.append(("127.0.0.1", s.getsockname()[1]))
            probes.append(s)
        for s in probes:
            s.close()
        ta = SocketTransport(0, 2, addresses=addrs, wire_format=fmt)
        tb = SocketTransport(1, 2, addresses=addrs, wire_format=fmt)
        try:
            ta.send(1, 2, (1, 0, 0, arr))  # warm the connection + hello
            tb.recv(timeout=30)
            t0 = time.perf_counter()
            for i in range(msgs):
                ta.send(1, 2, (1, i + 1, 0, arr))
            for _ in range(msgs):
                tb.recv(timeout=30)
            fields[f"loopback_{fmt}_ms"] = (
                (time.perf_counter() - t0) / msgs * 1e3
            )
        finally:
            ta.close()
            tb.close()
    return {
        "framed_mb_per_sec": framed_mbps,
        "sizes": sorted(sizes),
        "loopback_msgs": msgs,
        **{k: round(v, 4) for k, v in fields.items()},
    }


def bench_dp(
    cpu_smoke: bool = False, quant: str = "int8", bucket_bytes: int = None,
    steps: int = None,
) -> dict:
    """Sync-DP gradient-exchange A/B (the ``--dp`` preset): the same
    staged bucketed exchange at raw f32 width vs quantized codes, same
    seed, same bucket plan, same platform — the collective-path half of
    the fast-wire item (the socket half is ``--wire``).

    Both legs warm uninstrumented, then arm obs for the timed window
    (the attribute-swap pattern bench_ps_literal established): each wire
    hop is a separate collective-only XLA program journaled as a
    ``send``, quant math blocks inside ``compute`` spans, so the
    roofline split measures the wire *shrinking* under quantization
    instead of hiding quantize cost in the wire figure
    (``phase_source: "obs"``). The same journals yield the dynamics
    roll-up — EF-residual elastic distance riding next to samples/s, so
    a quantized-speedup claim carries its convergence-cost evidence.

    On the CPU mesh the staged hops run serially (one collective
    program in flight — the rendezvous bound); the byte drop and the
    wire-fraction drop are real there, the overlap itself materializes
    on hardware. The JSON line says which regime produced the number.
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    import mpit_tpu
    from mpit_tpu.data import load_mnist
    from mpit_tpu.models import LeNet
    from mpit_tpu.obs import ObsConfig, roofline
    from mpit_tpu.obs.dynamics import aggregate_dynamics
    from mpit_tpu.parallel import DataParallelTrainer

    if quant not in ("bf16", "int8"):
        raise ValueError(f"--dp quant must be bf16|int8, got {quant!r}")
    mpit_tpu.finalize()
    topo = mpit_tpu.init()
    w = topo.num_workers
    pwb = 8 if cpu_smoke else 128
    steps = steps or (8 if cpu_smoke else 60)
    if bucket_bytes is None:
        # small enough that LeNet still splits into several buckets —
        # the plan must exercise the pipeline, not collapse to one hop
        bucket_bytes = 64 << 10
    gb = pwb * w
    x_tr, y_tr, *_ = load_mnist(synthetic_train=max(2048, gb))
    rng = np.random.default_rng(0)
    idx = rng.integers(0, len(x_tr), gb)
    x, y = x_tr[idx], y_tr[idx]

    def leg(mode):
        tr = DataParallelTrainer(
            LeNet(compute_dtype=jnp.float32),
            optax.sgd(0.05, momentum=0.9),
            topo,
            quant=mode,
            bucket_bytes=bucket_bytes,
        )
        st = tr.init_state(jax.random.key(0), x[:2])
        for _ in range(3):  # warmup: compile, EF state — obs unarmed
            st, m = tr.step(st, x, y)
        with tempfile.TemporaryDirectory(prefix="mpit_dp_obs_") as d:
            tr.obs = ObsConfig(dir=d)
            t0 = time.perf_counter()
            for _ in range(steps):
                st, m = tr.step(st, x, y)
            wall = time.perf_counter() - t0
            tr.close_obs()
            run = roofline([d])["run"]
            dyn = aggregate_dynamics([d])["run"]
        return {
            "samples_per_sec": steps * gb / wall,
            "buckets": len(tr._plan.buckets),
            "wire_bytes_per_step": tr.wire_bytes_per_step(),
            "phases": {k: round(v, 4) for k, v in run["phases"].items()},
            "dynamics": {
                "elastic_dist_final": (
                    None if dyn["elastic_dist_final"] is None
                    else round(dyn["elastic_dist_final"], 4)
                ),
                "norm_ratio": (
                    None if dyn["norm_ratio"] is None
                    else round(dyn["norm_ratio"], 5)
                ),
                "diverging": dyn["diverging"],
            },
        }

    raw = leg("off")
    q = leg(quant)
    chips = topo.num_devices
    return {
        "samples_per_sec": q["samples_per_sec"],
        "samples_per_sec_per_chip": q["samples_per_sec"] / chips,
        "chips": chips,
        "platform": topo.platform,
        "dp_quant": quant,
        "dp_bucket_bytes": bucket_bytes,
        # the staged pipeline dispatches hops as separate programs —
        # async (true overlap) on hardware, serialized on the CPU mesh
        "dp_overlap": topo.platform != "cpu",
        "buckets": q["buckets"],
        "per_worker_batch": pwb,
        "timed_steps": steps,
        "raw_samples_per_sec": round(raw["samples_per_sec"], 1),
        "vs_raw": round(q["samples_per_sec"] / raw["samples_per_sec"], 3),
        "wire_bytes_per_step": q["wire_bytes_per_step"],
        "raw_wire_bytes_per_step": raw["wire_bytes_per_step"],
        "wire_bytes_ratio": round(
            raw["wire_bytes_per_step"] / q["wire_bytes_per_step"], 2
        ),
        "phases": q["phases"],
        "raw_phases": raw["phases"],
        "phase_source": "obs",
        "dynamics": q["dynamics"],
    }


def bench_preset(
    name: str, num_workers=None, cpu_smoke: bool = False,
    input_dtype: str = "float32", stem: str = None, remat: bool = False,
    overrides: dict = None, repeats: int = 1,
) -> dict:
    """Steady-state training samples/sec/chip for one BASELINE workload
    config (same staging/timing harness as the headline metric).

    ``overrides``: extra TrainConfig field replacements applied on top of
    the preset — the generic channel for measuring variant axes
    (``{"attn_impl": "flash"}``, ``{"seq_impl": "ulysses"}``,
    ``{"algo": "zero-sync"}``, ``{"pp_schedule": "1f1b"}``, ...) without
    a dedicated flag per axis. Unknown fields raise."""
    import dataclasses

    import optax

    import mpit_tpu
    from mpit_tpu.run import _build_model, _load_dataset, build_trainer
    from mpit_tpu.utils.config import TrainConfig

    if name not in ALL_BENCH_PRESETS:
        raise ValueError(
            f"unknown bench preset {name!r}; have "
            f"{sorted(ALL_BENCH_PRESETS)}"
        )
    cfg = TrainConfig().apply_preset(name)
    if overrides:
        unknown = set(overrides) - {
            f.name for f in dataclasses.fields(TrainConfig)
        }
        if unknown:
            raise ValueError(
                f"unknown TrainConfig override(s) {sorted(unknown)}"
            )
        # fields the harness OWNS — an override would be silently stomped
        # (train_size/image_size are replaced below; the batch comes from
        # the per-preset table, epochs from the adaptive timed leg)
        harness_owned = {
            "input_dtype": "pass input_dtype=... instead",
            "train_size": "the harness sizes the staged dataset itself",
            "image_size": "the harness caps resolution itself",
            "global_batch": "per-worker batch comes from _PRESET_BENCH",
            "epochs": "the timed leg is sized adaptively, not by epochs",
        }
        clashes = set(overrides) & set(harness_owned)
        if clashes:
            raise ValueError(
                "override(s) the bench harness owns would be silently "
                "ignored: "
                + "; ".join(f"{k}: {harness_owned[k]}" for k in clashes)
            )
        cfg = dataclasses.replace(cfg, **overrides)
    if name == "mnist-ps" and overrides:
        raise ValueError(
            "mnist-ps runs the dedicated host-async harness "
            "(bench_ps_literal), which takes no config overrides — drop "
            "--set for this preset"
        )
    if stem is not None:  # measure the s2d-stem variant of a stem model
        from mpit_tpu.models import STEM_MODELS

        if cfg.model.lower() not in STEM_MODELS:
            raise ValueError(
                f"preset {name!r} (model {cfg.model!r}) has no stem "
                f"choice; stem applies to {STEM_MODELS}"
            )
        cfg = dataclasses.replace(cfg, stem=stem)
    if remat:
        from mpit_tpu.models import REMAT_MODELS

        if cfg.model.lower() not in REMAT_MODELS:
            raise ValueError(
                f"preset {name!r} (model {cfg.model!r}) has no remat "
                f"support; remat applies to {REMAT_MODELS}"
            )
        cfg = dataclasses.replace(cfg, remat=True)
    if name == "mnist-ps":
        return bench_ps_literal(cpu_smoke, input_dtype=input_dtype)
    pwb, rounds = _PRESET_BENCH[name], None
    # On real hardware run the config's true resolution (224px for the
    # ImageNet configs — the large-tensor stress BASELINE.json:10 names);
    # only the CPU smoke path shrinks the workload.
    image_cap = cfg.image_size
    if cpu_smoke:
        # tiny wiring run: the XLA-CPU backend's conv compile time explodes
        # with batch AND image size (see main()); shrink both
        pwb, rounds, image_cap = 8, 3, 64

    mpit_tpu.finalize()
    from mpit_tpu.run import second_axis_for

    second_axis = second_axis_for(cfg)
    if cfg.resolved_algo() in second_axis:
        ax, extent = second_axis[cfg.resolved_algo()]
        if num_workers is not None:  # honor a carved-down world here too
            usable = (num_workers // extent) * extent
            topo = mpit_tpu.init(
                axis_names=("dp", ax),
                mesh_shape=(usable // extent, extent),
                num_workers=usable,
            )
        else:
            from mpit_tpu.run import _world_for

            topo = _world_for(cfg)
    else:
        topo = mpit_tpu.init(num_workers=num_workers)
    # all devices execute every step; on the 2-D seq-sync mesh that is
    # dp*sp chips, not just the worker-axis extent
    gb = pwb * topo.num_workers
    from mpit_tpu.run import SYNC_ALGOS

    is_sync = cfg.resolved_algo() in SYNC_ALGOS
    tau = 1 if is_sync else cfg.tau
    cfg = dataclasses.replace(
        cfg, train_size=tau * gb * 2, image_size=min(cfg.image_size, image_cap)
    )
    x_tr, y_tr, *_rest, _meta = _load_dataset(cfg)
    model = _build_model(cfg, _meta, worker_axis=topo.worker_axis)
    # honor --set optimizer=.../lr_schedule=... (adam state math changes
    # step cost; the schedule is a count-based scalar, timing-neutral).
    # The horizon only shapes the cosine curve, not throughput.
    from mpit_tpu.run import build_optimizer

    opt = build_optimizer(cfg, 10_000)
    trainer = build_trainer(cfg, model, opt, topo)
    res = _stage_and_time(
        trainer, is_sync, topo, x_tr, y_tr, pwb, tau, rounds,
        input_dtype=input_dtype, repeats=repeats,
    )
    return {**res, "algo": cfg.algo, "model": cfg.model,
            **({"stem": cfg.stem} if stem is not None else {}),
            **({"remat": True} if remat else {})}


def measure_scaling_efficiency(full: dict) -> dict:
    """Scaling efficiency vs single chip (the BASELINE.md north-star's
    second half: per-chip throughput at W chips / per-chip throughput at 1).

    Only meaningful with >1 REAL device — on one chip (or a CPU-simulated
    mesh sharing one host) the honest answer is null, not a fake 100%."""
    import jax

    n = len(jax.devices())
    if n < 2 or jax.devices()[0].platform == "cpu":
        return {"scaling_efficiency": None, "scaling_note":
                f"needs >1 real chip (found {n} "
                f"{jax.devices()[0].platform} device(s))"}
    # same adaptive ~2 s budget as the numerator: a short denominator leg
    # would put run-to-run noise straight into the efficiency ratio
    single = bench_jax(num_workers=1)
    eff = full["samples_per_sec_per_chip"] / single["samples_per_sec_per_chip"]
    return {
        "scaling_efficiency": round(eff, 4),
        "single_chip_samples_per_sec": round(
            single["samples_per_sec_per_chip"], 1
        ),
    }


def bench_decode(
    cpu_smoke: bool = False, weights_dtype: str = None,
    mixed: bool = False,
) -> dict:
    """Serving throughput: greedy tokens/sec of the batched KV-cached
    decode (``models.sampling.generate_batch``) on the GPT-2-small-shaped
    LM (the ptb-transformer-large dims), random params.

    ``mixed=True`` is the realistic serving shape: prompt lengths spread
    across the batch (rows get p_len, p_len-7, p_len-13, ... down to
    ~p_len/2). Per-row cache clocks prefill every row's entire prompt in
    the same dense pass, so this measures the same kernel as the uniform
    run on an unequal batch. tokens/sec counts GENERATED tokens, and
    every row generates ``steps``, so the metric is comparable to the
    uniform run.

    Completion needs no separate proof here: the sampled tokens
    themselves are host-fetched by the API (the return value IS the
    data-dependent fetch), so the wall clock covers real device work by
    construction. One fetch per CALL (not per token) — the tunnel RTT
    amortizes over batch x steps generated tokens.
    """
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models import generate_batch
    from mpit_tpu.models.transformer import TransformerLM

    if cpu_smoke:  # wiring run: tiny model, tiny budget
        dims = dict(vocab_size=101, num_layers=2, d_model=32,
                    num_heads=4, max_len=64)
        nb, p_len, steps = 2, 8, 24
    else:
        # prompt+steps == max_len == the 512 scan bucket exactly, so NO
        # timed tick is bucket-overrun waste (total-1=511 kept ticks of
        # a 512-tick scan)
        dims = dict(vocab_size=10_000, num_layers=6, d_model=768,
                    num_heads=12, max_len=512)
        nb, p_len, steps = 8, 64, 512 - 64
    model = TransformerLM(**dims)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)
    if mixed:
        # spread lengths over [p_len/2, p_len]: realistic unequal prompts
        lens = [
            max(p_len // 2, p_len - 1 - (7 * i) % (p_len // 2 + 1))
            for i in range(nb)
        ]
        # longest row at p_len keeps the prefill/scan buckets identical
        # to the uniform run, so the two metrics compare like for like
        lens[0] = p_len
    else:
        lens = [p_len] * nb
    prompts = [
        rng.integers(0, dims["vocab_size"], n).tolist() for n in lens
    ]
    if weights_dtype == "bf16":
        # cast ONCE, before the timing loop — steady-state serving pays
        # this once, so per-call casting would bias the very bandwidth
        # metric the flag measures (and hold f32+bf16 live at 1.5x)
        from mpit_tpu.models.sampling import cast_weights

        params = cast_weights(params, jnp.bfloat16)
    gen = lambda: generate_batch(model, params, prompts, steps)
    first = gen()  # compile + warmup
    assert all(
        len(r) == n + steps for r, n in zip(first, lens)
    )
    # same variance control as the training legs: median of N timed
    # legs + relative spread, flagged >10% (the one-core-host
    # interference class) — a flagged decode leg must not become the
    # LATEST.json evidence trail either
    leg_rates, calls = [], 0
    for _ in range(1 if cpu_smoke else 3):
        legc = 0
        t0 = time.perf_counter()
        while legc < 2 or time.perf_counter() - t0 < 2.0:
            gen()
            legc += 1
        leg_rates.append(legc * nb * steps / (time.perf_counter() - t0))
        calls += legc
    rate = float(np.median(leg_rates))
    spread = (
        round((max(leg_rates) - min(leg_rates)) / rate, 4)
        if len(leg_rates) > 1 else None
    )
    return {
        "tokens_per_sec": rate,
        "spread": spread,
        "variance_flagged": bool(spread is not None and spread > 0.10),
        "batch": nb,
        "prompt_len": p_len,
        **({"mixed_prompt_lens": lens} if mixed else {}),
        "steps": steps,
        "calls": calls,
        # wall ms per decode TICK (all nb rows advance one token/tick)
        "per_token_ms": 1e3 * nb / rate,
        "model": "transformer-large" if not cpu_smoke else "tiny",
        **({"weights_dtype": weights_dtype} if weights_dtype else {}),
    }


def bench_serve(
    cpu_smoke: bool = False, weights_dtype: str = None,
    burst: bool = False, prefix_len: int = 0,
) -> dict:
    """Continuous-batching throughput: sustained generated tokens/sec of
    ``models.serving.Server`` draining a queue of unequal requests
    (prompt lengths AND budgets spread) through a fixed slot count —
    the serving metric with retirement + admission in the loop, where
    ``--decode`` measures one static batch. Completion is by
    construction: every generated token is host-fetched by the drain.

    ``burst``: instead of a pre-filled queue, submit only the first
    slot-full, run one segment, then dump EVERY remaining request
    mid-flight — the admission-cost regime (grouped same-bucket
    prefills at a scheduling boundary) that the plain drain never
    exercises because its queue admits into free slots one segment at
    a time.

    ``prefix_len``: share a prefix_len-token prompt prefix across every
    request (the system-prompt regime) — the server prefills it once
    into a cache template; admission pays suffix FLOPs only.
    """
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models import Server
    from mpit_tpu.models.transformer import TransformerLM

    if cpu_smoke:
        dims = dict(vocab_size=101, num_layers=2, d_model=32,
                    num_heads=4, max_len=64)
        reqs = [(6 + (i * 3) % 10, 8 + (i * 5) % 12) for i in range(6)]
        max_batch, segment, legs = 2, 8, 1
    else:
        dims = dict(vocab_size=10_000, num_layers=6, d_model=768,
                    num_heads=12, max_len=512)
        # 24 requests over 8 slots: prompts 32..128, budgets 128..320
        reqs = [
            (32 + (i * 13) % 97, 128 + (i * 29) % 193) for i in range(24)
        ]
        max_batch, segment, legs = 8, 64, 3
    model = TransformerLM(**dims)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if weights_dtype == "bf16":
        from mpit_tpu.models.sampling import cast_weights

        params = cast_weights(params, jnp.bfloat16)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, dims["vocab_size"], p).tolist() for p, _ in reqs
    ]
    prefix = (
        rng.integers(0, dims["vocab_size"], prefix_len).tolist()
        if prefix_len else None
    )
    if prefix_len:
        # keep prefix + prompt + budget within max_len (>=1 so an
        # impossible prefix fails loudly in submit, not silently here)
        reqs = [(p, max(1, min(mn, dims["max_len"] - prefix_len - p - 1)))
                for p, mn in reqs]

    def drain_once():
        srv = Server(model, params, max_batch=max_batch, segment=segment,
                     prefix=prefix)
        pairs = list(zip(prompts, (mn for _, mn in reqs)))
        head = pairs[:max_batch] if burst else pairs
        for q, mn in head:
            srv.submit(q, mn)
        if burst:
            srv.step()  # head requests are mid-flight...
            for q, mn in pairs[max_batch:]:
                srv.submit(q, mn)  # ...when the burst arrives at once
        out = srv.drain()
        return sum(mn for _, mn in reqs), srv.segments_run, out

    drain_once()  # compile + warmup (all bucket shapes)
    leg_rates, segments = [], 0
    for _ in range(legs):
        t0 = time.perf_counter()
        tokens, segments, _ = drain_once()
        leg_rates.append(tokens / (time.perf_counter() - t0))
    rate = float(np.median(leg_rates))
    spread = (
        round((max(leg_rates) - min(leg_rates)) / rate, 4)
        if len(leg_rates) > 1 else None
    )
    return {
        "tokens_per_sec": rate,
        "spread": spread,
        "variance_flagged": bool(spread is not None and spread > 0.10),
        "requests": len(reqs),
        "max_batch": max_batch,
        "segment": segment,
        "segments_per_drain": segments,
        "model": "transformer-large" if not cpu_smoke else "tiny",
        **({"weights_dtype": weights_dtype} if weights_dtype else {}),
        **({"admission": "burst"} if burst else {}),
        **({"prefix_len": prefix_len} if prefix_len else {}),
    }


def bench_load(cpu_smoke: bool = False, seed: int = 0) -> dict:
    """Serving under traffic: the open-loop load harness
    (``mpit_tpu.loadgen``) drives a Server with Poisson arrivals and
    mixed length buckets while the server journals every request
    lifecycle; the reported numbers are the journal's reduction (the
    same one ``python -m mpit_tpu.obs slo`` computes) — tokens/sec AND
    the latency scorecard (TTFT/TPOT/e2e percentiles, goodput) that a
    drain-style bench cannot see. Seeded end to end: the schedule is a
    pure function of ``seed``, so a regression replays.
    """
    import glob
    import tempfile

    import jax
    import jax.numpy as jnp

    from mpit_tpu.loadgen import (
        LoadHarness, LoadSpec, aggregate_paths, make_workload,
    )
    from mpit_tpu.models import Server
    from mpit_tpu.models.transformer import TransformerLM
    from mpit_tpu.obs.core import ObsConfig

    if cpu_smoke:
        dims = dict(vocab_size=101, num_layers=2, d_model=32,
                    num_heads=4, max_len=64)
        spec = LoadSpec(requests=12, rate=500.0, seed=seed)
        max_batch, segment = 2, 8
    else:
        dims = dict(vocab_size=10_000, num_layers=6, d_model=768,
                    num_heads=12, max_len=512)
        spec = LoadSpec(
            requests=48, rate=50.0, seed=seed,
            prompt_buckets=((8, 48, 0.6), (48, 128, 0.4)),
            output_buckets=((16, 64, 0.6), (64, 160, 0.4)),
        )
        max_batch, segment = 8, 32
    model = TransformerLM(**dims)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    work = make_workload(spec, dims["vocab_size"],
                         max_len=dims["max_len"])

    # warmup drain without obs: compile every bucket shape the measured
    # run will hit, so TTFT measures scheduling rather than XLA
    warm = Server(model, params, max_batch=max_batch, segment=segment)
    for r in work:
        warm.submit(list(r.prompt), r.max_new)
    warm.drain()

    with tempfile.TemporaryDirectory() as obs_dir:
        srv = Server(
            model, params, max_batch=max_batch, segment=segment,
            obs=ObsConfig(dir=obs_dir),
        )
        rep = LoadHarness(srv, work).run()
        report = aggregate_paths(
            sorted(glob.glob(os.path.join(obs_dir, "obs_rank*.jsonl")))
        )
    tps = report["tokens_per_sec"]
    return {
        "tokens_per_sec": (
            float(tps) if tps is not None
            else report["tokens"] / max(rep.wall_s, 1e-9)
        ),
        "requests": spec.requests,
        "rate": spec.rate,
        "seed": seed,
        "max_batch": max_batch,
        "segment": segment,
        "ttft_p50_ms": report["ttft"].get("p50_ms"),
        "ttft_p99_ms": report["ttft"].get("p99_ms"),
        "tpot_p50_ms": report["tpot"].get("p50_ms"),
        "e2e_p99_ms": report["e2e"].get("p99_ms"),
        "goodput": report["goodput"],
        "finished": report["requests"]["finished"],
        "unfinished": report["requests"]["unfinished"],
        "model": "transformer-large" if not cpu_smoke else "tiny",
    }


def bench_fleet_load(
    cpu_smoke: bool = False, seed: int = 0, n_replicas: int = 3,
    policy: str = "p2c",
) -> dict:
    """The fleet variant of :func:`bench_load`: the same seeded open-loop
    workload offered to a ``mpit_tpu.fleet`` router over ``n_replicas``
    in-process replicas instead of one Server. e2e/goodput/tokens come
    from the ROUTER journal (admission-to-ack, the number a client
    feels); TTFT/TPOT come from the replica journals pooled per-replica
    (replica rid spaces collide, so they aggregate separately and the
    histograms merge). ``replica_count``/``router_policy`` ride the JSON
    line as comparability keys — scripts/bench_gate.py never trends a
    3-replica round against a 1-replica round.
    """
    import glob
    import tempfile

    import jax
    import jax.numpy as jnp

    from mpit_tpu.fleet import FleetHarness, audit_lifecycle
    from mpit_tpu.loadgen import (
        LoadSpec, aggregate_paths, make_workload, pooled_latencies,
    )
    from mpit_tpu.models import Server
    from mpit_tpu.models.transformer import TransformerLM
    from mpit_tpu.obs.core import ObsConfig

    # same workload shapes as bench_load, cancellations off (the fleet
    # wire has no CANCEL lane)
    if cpu_smoke:
        dims = dict(vocab_size=101, num_layers=2, d_model=32,
                    num_heads=4, max_len=64)
        spec = LoadSpec(requests=12, rate=500.0, seed=seed,
                        cancel_prob=0.0)
        max_batch, segment = 2, 8
    else:
        dims = dict(vocab_size=10_000, num_layers=6, d_model=768,
                    num_heads=12, max_len=512)
        spec = LoadSpec(
            requests=48, rate=50.0, seed=seed, cancel_prob=0.0,
            prompt_buckets=((8, 48, 0.6), (48, 128, 0.4)),
            output_buckets=((16, 64, 0.6), (64, 160, 0.4)),
        )
        max_batch, segment = 8, 32
    model = TransformerLM(**dims)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    work = make_workload(spec, dims["vocab_size"],
                         max_len=dims["max_len"])

    # warmup drain: replicas share this process's compile cache, so one
    # drain of every bucket shape warms the whole fleet
    warm = Server(model, params, max_batch=max_batch, segment=segment)
    for r in work:
        warm.submit(list(r.prompt), r.max_new)
    warm.drain()

    with tempfile.TemporaryDirectory() as out:
        rep_dirs = {}

        def factory(rank):
            d = os.path.join(out, f"rep{rank}")
            os.makedirs(d, exist_ok=True)
            rep_dirs[rank] = d
            return Server(model, params, max_batch=max_batch,
                          segment=segment, obs=ObsConfig(dir=d))

        router_dir = os.path.join(out, "router")
        os.makedirs(router_dir)
        fleet = FleetHarness(
            factory, work, n_replicas=n_replicas, policy=policy,
            seed=seed, obs_dir=router_dir,
        )
        rep = fleet.run()
        router_paths = sorted(
            glob.glob(os.path.join(router_dir, "obs_rank*.jsonl"))
        )
        report = aggregate_paths(router_paths)
        audit = audit_lifecycle(router_paths)
        lat = pooled_latencies(
            sorted(glob.glob(os.path.join(d, "obs_rank*.jsonl")))
            for d in rep_dirs.values()
        )
    tps = report["tokens_per_sec"]
    return {
        "tokens_per_sec": (
            float(tps) if tps is not None
            else report["tokens"] / max(rep.wall_s, 1e-9)
        ),
        "requests": spec.requests,
        "rate": spec.rate,
        "seed": seed,
        "max_batch": max_batch,
        "segment": segment,
        "replica_count": n_replicas,
        "router_policy": policy,
        "ttft_p50_ms": lat["ttft"].get("p50_ms"),
        "ttft_p99_ms": lat["ttft"].get("p99_ms"),
        "tpot_p50_ms": lat["tpot"].get("p50_ms"),
        "e2e_p99_ms": report["e2e"].get("p99_ms"),
        "goodput": report["goodput"],
        "finished": report["requests"]["finished"],
        "unfinished": report["requests"]["unfinished"],
        "lost": len(audit["lost"]),
        "audit_ok": bool(audit["ok"]),
        "model": "transformer-large" if not cpu_smoke else "tiny",
    }


def bench_spec(cpu_smoke: bool = False, k: int = 4) -> dict:
    """Speculative-decoding throughput: greedy tokens/sec of
    ``generate_speculative`` vs the plain cached decode on the SAME
    trained target — the serving-acceleration metric. Both models train
    briefly on a deterministic next-token pattern so the draft's
    proposals actually agree with the target (random-init models agree
    at chance, which would measure nothing); the draft has ~1/6 the
    target's width/depth, so accepted chunks pay draft-sized FLOPs for
    target-sized progress. Completion is by construction (the returned
    tokens are the host fetch). ``mean_emitted`` reports tokens emitted
    per verification chunk (in [1, k+1]) — the measured draft quality.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from mpit_tpu.models import generate_fast, generate_speculative
    from mpit_tpu.models.transformer import TransformerLM

    V = 512
    if cpu_smoke:
        t_dims, d_dims = (2, 64, 4), (1, 32, 2)
        max_len, steps, train_steps, legs = 128, 48, 60, 1
    else:
        t_dims, d_dims = (6, 512, 8), (2, 128, 4)
        max_len, steps, train_steps, legs = 1024, 512, 300, 3

    def build(layers, d, heads):
        return TransformerLM(
            vocab_size=V, num_layers=layers, d_model=d, num_heads=heads,
            max_len=max_len,
        )

    def pattern(n, t, seed):
        rng = np.random.default_rng(seed)
        starts = rng.integers(0, V, (n, 1))
        stepixs = np.arange(t + 1)[None, :]
        seq = (starts + 3 * stepixs * (starts % 5 + 1)) % V
        return seq[:, :t].astype(np.int32), seq[:, 1:].astype(np.int32)

    def train(model, seed):
        x, y = pattern(32, 64, seed=1)
        params = model.init(jax.random.key(seed), x[:2])["params"]
        opt = optax.adam(3e-3)
        ost = opt.init(params)

        @jax.jit
        def step(p, o, xb, yb):
            def loss_fn(p):
                logits = model.apply({"params": p}, xb)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                ).mean()

            loss, g = jax.value_and_grad(loss_fn)(p)
            up, o = opt.update(g, o)
            return optax.apply_updates(p, up), o, loss

        for _ in range(train_steps):
            params, ost, _ = step(params, ost, x, y)
        return params

    target, draft = build(*t_dims), build(*d_dims)
    tp, dp = train(target, seed=0), train(draft, seed=5)
    # the prompt is a TRAINING row: both models continue a sequence they
    # learned, so draft/target agreement is high — the regime speculative
    # decoding exists for (an unseen start would measure two models
    # disagreeing about noise: mean_emitted ~1, no draft signal)
    prompt = [int(t) for t in pattern(32, 64, seed=1)[0][0][:32]]

    def time_fn(fn):
        fn()  # compile + warmup
        rates = []
        for _ in range(legs):
            t0 = time.perf_counter()
            fn()
            rates.append(steps / (time.perf_counter() - t0))
        med = float(np.median(rates))
        spread = (
            round((max(rates) - min(rates)) / med, 4)
            if len(rates) > 1 else None
        )
        return med, spread

    plain, _ = time_fn(lambda: generate_fast(target, tp, prompt, steps))
    spec, spread = time_fn(lambda: generate_speculative(
        target, tp, draft, dp, prompt, steps, k=k
    ))

    # the same trained pair through the CONTINUOUS-BATCHING tier:
    # speculative Server vs plain Server on a queue of pattern prompts
    from mpit_tpu.models import Server

    x_rows, _ = pattern(8, 48, seed=1)
    q_prompts = [[int(t) for t in row[:24]] for row in x_rows]
    q_mn = min(steps, max_len - 24 - k - 1)

    def drain(srv_kw):
        # segment applies to the plain server only; the spec server's
        # granularity is its spec_rounds
        srv = Server(target, tp, max_batch=4, segment=16, **srv_kw)
        for q in q_prompts:
            srv.submit(q, q_mn)
        srv.drain()
        return len(q_prompts) * q_mn

    def time_drain(srv_kw):
        drain(srv_kw)  # compile + warmup
        rates = []
        for _ in range(legs):
            t0 = time.perf_counter()
            toks = drain(srv_kw)
            rates.append(toks / (time.perf_counter() - t0))
        return float(np.median(rates))

    serve_plain = time_drain({})
    serve_spec = time_drain(dict(
        draft_model=draft, draft_params=dp, spec_k=k,
        spec_rounds=4,
    ))
    toks, stats = generate_speculative(
        target, tp, draft, dp, prompt, steps, k=k, return_stats=True
    )
    # exactness is the feature's contract — assert it on the bench pair
    # so a published speedup can never come from a wrong decode
    assert toks == generate_fast(target, tp, prompt, steps)
    return {
        "tokens_per_sec": spec,
        "spread": spread,
        "variance_flagged": bool(spread is not None and spread > 0.10),
        "plain_tokens_per_sec": round(plain, 1),
        "speedup": round(spec / plain, 3) if plain else None,
        "k": k,
        "mean_emitted": round(stats["mean_emitted"], 2),
        "steps": steps,
        "serve_tokens_per_sec": round(serve_spec, 1),
        "serve_plain_tokens_per_sec": round(serve_plain, 1),
        "serve_speedup": (
            round(serve_spec / serve_plain, 3) if serve_plain else None
        ),
        "model": "512d-6L vs 128d-2L draft" if not cpu_smoke else "tiny",
    }


def bench_torch_cpu(
    batch: int = 256, steps: int = 12, target_seconds: float = 2.0
) -> float:
    """Reference-stack stand-in: the same LeNet trained with torch on CPU
    (the reference's ptest example ran Torch on CPU, BASELINE.json:7).
    ``steps`` is a floor; the timed leg extends until ``target_seconds``
    elapse so the denominator gets the same noise attenuation as the
    adaptive JAX numerator."""
    try:
        import torch
        import torch.nn as tnn
    except Exception:
        return float("nan")

    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Conv2d(1, 32, 5, padding=2), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Conv2d(32, 64, 5, padding=2), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Flatten(),
        tnn.Linear(64 * 7 * 7, 256), tnn.ReLU(),
        tnn.Linear(256, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
    loss_fn = tnn.CrossEntropyLoss()
    x = torch.rand(batch, 1, 28, 28)
    y = torch.randint(0, 10, (batch,))
    # warmup
    for _ in range(2):
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    done = 0
    t0 = time.perf_counter()
    while done < steps or time.perf_counter() - t0 < target_seconds:
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
        done += 1
    dt = time.perf_counter() - t0
    return batch * done / dt


def main():
    # the container pins JAX_PLATFORMS to the hardware plugin (axon), so
    # "env var set" does NOT mean "cpu requested" — probe unless cpu is
    # explicitly the platform
    env_platform = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env_platform != "cpu" and not _backend_alive():
        # Dead hardware backend: `import jax` ITSELF hangs in this state
        # (the sitecustomize-registered plugin blocks at import while the
        # tunnel is down — observed 2026-07-29), so no in-process fallback
        # can work. Re-exec with JAX_PLATFORMS=cpu set from process start
        # (which demonstrably avoids the hang) for a CPU smoke run — a
        # wiring number with a note beats a benchmark that emits nothing.
        os.execve(
            sys.executable,
            [sys.executable] + sys.argv,
            dict(
                os.environ,
                JAX_PLATFORMS="cpu",
                MPIT_BENCH_PLATFORM_NOTE=(
                    "hardware backend unreachable (probe timed out); "
                    "cpu smoke numbers, not a benchmark"
                ),
                # probe cost survives the re-exec: the fallback's JSON
                # line must show what the dead-tunnel detour cost
                MPIT_BENCH_PROBE_SECONDS=str(
                    _PROBE_CACHE.get("seconds", "")
                ),
            ),
        )
    _honor_platform_env()
    platform_note = os.environ.get("MPIT_BENCH_PLATFORM_NOTE")
    import jax

    from mpit_tpu.utils.profiling import trace

    cpu = jax.devices()[0].platform == "cpu"

    def flag_arg(flag):
        """Value of `flag <arg>` from argv; usage-errors via SystemExit(2)
        when the argument is missing or another flag."""
        if flag not in sys.argv:
            return None
        i = sys.argv.index(flag) + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            print(f"{flag} requires an argument", file=sys.stderr)
            raise SystemExit(2)
        return sys.argv[i]

    profile_dir = flag_arg("--profile")
    profiled = {"profiled": True} if profile_dir else {}
    input_dtype = flag_arg("--input-dtype") or "float32"
    from mpit_tpu.data import INPUT_DTYPES

    if input_dtype not in INPUT_DTYPES:  # fail at flag parse, not mid-run
        print(
            f"--input-dtype must be one of {INPUT_DTYPES}, "
            f"got {input_dtype!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)
    dtype_tag = (
        {"input_dtype": input_dtype} if input_dtype != "float32" else {}
    )

    def emit_tokens_metric(
        metric, key, res, fields, opt_fields, latest_extra=()
    ):
        """THE reporting contract every tokens/sec bench shares
        (--decode, --serve): variance-gated LATEST.json admission, the
        dead-tunnel evidence trail, one JSON line. A change to the
        recording rules lands here once."""
        if not cpu and not profile_dir and not res.get("variance_flagged"):
            update_latest_measurement(key, {
                "tokens_per_sec": round(res["tokens_per_sec"], 1),
                **{k: round(res[k], 3) for k in latest_extra},
                **({"spread": res["spread"]}
                   if res.get("spread") is not None else {}),
                "source": f"bench.py {metric}",
            })
        last = last_tpu_measurement(key) if platform_note else None
        print(json.dumps({
            "metric": metric,
            "value": round(res["tokens_per_sec"], 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": None,  # the reference cannot sample at all
            **{k: res[k] for k in fields},
            **{k: res[k] for k in opt_fields if res.get(k) is not None},
            **({"platform_note": platform_note} if platform_note else {}),
            **({"last_tpu_measurement": last} if last else {}),
            **_probe_tag(),
            **profiled,
        }))

    def weights_dtype_flag():
        wd = flag_arg("--weights-dtype")
        if wd is not None and wd != "bf16":
            print("--weights-dtype supports: bf16", file=sys.stderr)
            raise SystemExit(2)
        return wd

    if "--serve" in sys.argv:
        wd = weights_dtype_flag()
        burst = "--burst" in sys.argv
        plen = int(flag_arg("--prefix-len") or 0)
        with trace(profile_dir):
            res = bench_serve(cpu_smoke=cpu, weights_dtype=wd, burst=burst,
                              prefix_len=plen)
        emit_tokens_metric(
            "serve_tokens_per_sec",
            "serve" + ("-bf16" if wd else "") + ("-burst" if burst else "")
            + (f"-prefix{plen}" if plen else ""),
            res,
            ("requests", "max_batch", "segment", "segments_per_drain",
             "model"),
            ("weights_dtype", "spread", "admission", "prefix_len"),
        )
        return

    if "--load" in sys.argv:
        seed = int(flag_arg("--seed") or 0)
        fleet = flag_arg("--fleet")
        if fleet is not None:
            n = int(fleet)
            if n < 1:
                print("--fleet requires N >= 1", file=sys.stderr)
                raise SystemExit(2)
            policy = flag_arg("--policy") or "p2c"
            with trace(profile_dir):
                res = bench_fleet_load(
                    cpu_smoke=cpu, seed=seed, n_replicas=n,
                    policy=policy,
                )
            emit_tokens_metric(
                "serve_load_tokens_per_sec", f"serve-load-fleet{n}", res,
                ("requests", "rate", "seed", "max_batch", "segment",
                 "replica_count", "router_policy", "finished",
                 "unfinished", "lost", "audit_ok", "model"),
                ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                 "e2e_p99_ms", "goodput"),
            )
            return
        with trace(profile_dir):
            res = bench_load(cpu_smoke=cpu, seed=seed)
        emit_tokens_metric(
            "serve_load_tokens_per_sec", "serve-load", res,
            ("requests", "rate", "seed", "max_batch", "segment",
             "finished", "unfinished", "model"),
            ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms", "e2e_p99_ms",
             "goodput"),
        )
        return

    if "--spec" in sys.argv:
        with trace(profile_dir):
            res = bench_spec(cpu_smoke=cpu)
        emit_tokens_metric(
            "spec_tokens_per_sec", "spec", res,
            ("plain_tokens_per_sec", "speedup", "k", "mean_emitted",
             "steps", "serve_tokens_per_sec",
             "serve_plain_tokens_per_sec", "serve_speedup", "model"),
            ("spread",),
        )
        return

    if "--decode" in sys.argv:
        wd = weights_dtype_flag()
        mixed = "--mixed" in sys.argv
        with trace(profile_dir):
            res = bench_decode(cpu_smoke=cpu, weights_dtype=wd, mixed=mixed)
        emit_tokens_metric(
            "decode_tokens_per_sec",
            "decode" + ("-bf16" if wd else "") + ("-mixed" if mixed else ""),
            res,
            ("batch", "prompt_len", "steps", "per_token_ms", "model"),
            ("weights_dtype", "spread", "mixed_prompt_lens"),
            latest_extra=("per_token_ms",),
        )
        return

    if "--wire" in sys.argv:
        with trace(profile_dir):
            res = bench_wire(cpu_smoke=cpu)
        print(json.dumps({
            "metric": "wire_codec_throughput",
            "value": round(res["framed_mb_per_sec"], 1),
            "unit": "MB/sec",
            "vs_baseline": None,  # pickle_*_ms columns ARE the baseline
            **{k: v for k, v in res.items() if k != "framed_mb_per_sec"},
            **({"platform_note": platform_note} if platform_note else {}),
            **_probe_tag(),
            **profiled,
        }))
        return

    if "--dp" in sys.argv:
        qmode = flag_arg("--quant") or "int8"
        bb = flag_arg("--bucket-bytes")
        try:
            with trace(profile_dir):
                res = bench_dp(
                    cpu_smoke=cpu, quant=qmode,
                    bucket_bytes=int(bb) if bb else None,
                )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(json.dumps({
            "metric": "sync_dp_exchange_throughput",
            "value": round(res["samples_per_sec_per_chip"], 1),
            "unit": "samples/sec/chip",
            # the A/B IS the baseline: quantized vs raw staged exchange
            "vs_baseline": res["vs_raw"],
            "baseline": "raw f32 staged exchange, same bucket plan/seed",
            **{
                k: res[k]
                for k in ("chips", "platform", "dp_quant",
                          "dp_bucket_bytes", "dp_overlap", "buckets",
                          "per_worker_batch", "timed_steps",
                          "raw_samples_per_sec", "wire_bytes_per_step",
                          "raw_wire_bytes_per_step", "wire_bytes_ratio",
                          "phases", "raw_phases", "phase_source",
                          "dynamics")
            },
            **({"platform_note": platform_note} if platform_note else {}),
            **_probe_tag(),
            **profiled,
        }))
        return

    name = flag_arg("--preset")
    if name is not None:
        try:
            with trace(profile_dir):
                res = bench_preset(
                    name, cpu_smoke=cpu, input_dtype=input_dtype,
                    repeats=1 if cpu else 3,
                )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        last = last_tpu_measurement(name) if platform_note else None
        print(json.dumps({
            "metric": f"{name}_throughput",
            "value": round(res["samples_per_sec_per_chip"], 1),
            "unit": "samples/sec/chip",
            "vs_baseline": None,  # only the headline config has a baseline
            **{k: res[k] for k in ("chips", "algo", "model")},
            **{
                k: res[k]
                for k in ("mfu", "spread", "phases", "phase_source",
                          "live", "dynamics", "ps_transport",
                          "wire_format", "wire_quant", "wire_detail",
                          "wire_bytes_total")
                if k in res
            },
            **({"platform_note": platform_note} if platform_note else {}),
            **({"last_tpu_measurement": last} if last else {}),
            **_probe_tag(),
            **profiled,
            **dtype_tag,
        }))
        return

    # smoke-run sizing on cpu: a CPU mesh shares one host's cores AND the
    # CPU backend's conv compile time grows steeply with batch size (>200s
    # at 64/worker); keep the smoke run tiny — the number it prints is
    # wiring validation, not a benchmark. On hardware: adaptive timed leg,
    # completion-proven.
    pwb, rounds = (8, 3) if cpu else (1024, None)
    configs = None
    with trace(profile_dir):  # covers the headline AND (with --all) every
        jax_res = bench_jax(  # preset
            per_worker_batch=pwb, rounds=rounds, input_dtype=input_dtype,
            repeats=1 if cpu else 3,
        )
        if "--all" in sys.argv:
            configs = {
                name: round(
                    bench_preset(
                        name, cpu_smoke=cpu, input_dtype=input_dtype,
                        repeats=1 if cpu else 3,  # same variance rule as
                    )["samples_per_sec_per_chip"],  # every other leg
                    1,
                )
                for name in ALL_BENCH_PRESETS
                if name != "mnist-easgd"  # the headline metric above
            }
    scaling = measure_scaling_efficiency(jax_res)
    # baseline at the SAME per-worker batch as the numerator (a 1024-batch
    # TPU rate over a 256-batch CPU rate would not be apples-to-apples)
    torch_sps = bench_torch_cpu(batch=pwb, steps=3)
    value = jax_res["samples_per_sec_per_chip"]
    # no torch -> no baseline measurement; report null, not fake parity
    vs = round(value / torch_sps, 2) if np.isfinite(torch_sps) else None
    # same admission rule as measure_presets.archive(): a variance-flagged
    # row must not become the evidence trail
    if (not cpu and not profile_dir and "mfu" in jax_res
            and not jax_res.get("variance_flagged")):
        update_latest_measurement("mnist-easgd", {
            "samples_per_sec_per_chip": round(value, 1),
            "mfu": jax_res["mfu"],
            **({"spread": jax_res["spread"]}
               if jax_res.get("spread") is not None else {}),
            "source": "bench.py headline",
        })
    # a dead tunnel must not bury the evidence: the fallback JSON carries
    # the latest ARCHIVED hardware number (date + caveat) so the driver
    # record is never just smoke throughput (VERDICT r3 weak-item 1)
    last = last_tpu_measurement("mnist-easgd") if platform_note else None
    out = {
        "metric": "easgd_mnist_lenet_throughput",
        "value": round(value, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": vs,
        "baseline": "torch-cpu LeNet train step (reference ran Torch on CPU)",
        "baseline_samples_per_sec": round(torch_sps, 1)
        if np.isfinite(torch_sps)
        else None,
        "chips": jax_res["chips"],
        "platform": jax_res["platform"],
        **{
            k: jax_res[k]
            for k in ("mfu", "model_flops_per_sec_per_chip", "timed_seconds",
                      "timed_rounds", "spread", "phases", "phase_source")
            if k in jax_res and jax_res[k] is not None
        },
        **scaling,
        **({"platform_note": platform_note} if platform_note else {}),
        **({"last_tpu_measurement": last} if last else {}),
        **_probe_tag(),
        **profiled,
        **dtype_tag,
    }
    if configs is not None:
        out["configs"] = configs
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
