"""Multi-process sync-DP training — the multi-host path, runnable anywhere.

    python -m mpit_tpu.launch -n 2 --jax-distributed \
        examples/multihost_sync.py --local-devices 2

Each rank boots ``jax.distributed`` (coordinator wired by the launcher),
contributes its local devices to ONE global mesh, and the ``lax.pmean``
inside the jitted step crosses process boundaries — gloo between CPU
processes here, ICI/DCN between hosts of a real TPU slice. This is the
TPU-native analogue of the reference's ``mpirun -n N`` + CUDA-aware
``MPI_Allreduce`` path (SURVEY.md §3(a),(d)): same launch shape, same
collective semantics, no MPI.
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--algo", choices=("sync", "zero", "easgd", "downpour"),
        default="sync",
    )
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument(
        "--local-devices", type=int, default=0,
        help="force an n-device virtual CPU backend in each rank "
             "(simulate a multi-host slice without TPU hardware)",
    )
    ap.add_argument(
        "--out", default="",
        help="write final metrics JSON to <out>.rank<i>.json",
    )
    ap.add_argument(
        "--ckpt-dir", default="",
        help="save + restore a checkpoint at the end (exercises the "
             "multi-host gather of non-addressable sharded leaves: only "
             "process 0 writes, every process restores)",
    )
    ns = ap.parse_args()
    if ns.local_devices:
        from mpit_tpu.utils.vmesh import force_virtual_devices

        force_virtual_devices(ns.local_devices)

    import jax
    import numpy as np
    import optax

    import mpit_tpu
    from mpit_tpu.data import load_mnist
    from mpit_tpu.models import MLP
    from mpit_tpu.parallel import DataParallelTrainer

    topo = mpit_tpu.init()
    w = topo.num_workers
    print(
        f"[rank {topo.process_index}/{topo.process_count}] "
        f"local={len(topo.local_devices)} global_workers={w}",
        flush=True,
    )

    # every process feeds the SAME global batch stream (deterministic
    # seeds); jit shards it onto the global mesh, each process transferring
    # only its addressable slice
    x, y, *_ = load_mnist(synthetic_train=2048)
    model = MLP(hidden=(64,), compute_dtype=np.float32)
    if ns.algo == "sync":
        trainer = DataParallelTrainer(model, optax.sgd(0.2), topo)
    elif ns.algo == "zero":
        # ZeRO-1 across PROCESSES: each rank's optimizer shards are
        # non-addressable to the others — the strongest multi-host case
        # for the psum_scatter/all_gather pair and the checkpoint gather
        from mpit_tpu.parallel import ZeroDataParallelTrainer

        trainer = ZeroDataParallelTrainer(
            model, optax.adam(1e-3), topo
        )
    elif ns.algo == "easgd":
        from mpit_tpu.parallel import EASGDTrainer

        trainer = EASGDTrainer(
            model, optax.sgd(0.2, momentum=0.9), topo, tau=4
        )
    else:
        from mpit_tpu.parallel import DownpourTrainer

        trainer = DownpourTrainer(model, optax.sgd(0.2), topo, tau=4)
    state = trainer.init_state(jax.random.key(0), x[: max(2, w)])
    gb = 16 * w
    tau = getattr(trainer, "tau", 1)
    first = last = None
    for step in range(ns.steps):
        idx = np.random.default_rng(step).integers(0, len(x), tau * gb)
        if ns.algo in ("sync", "zero"):
            state, m = trainer.step(state, x[idx], y[idx])
        else:  # one whole tau-round per step (local scan + exchange: EASGD's
            # elastic psum, or Downpour's update push / stale center pull)
            state, m = trainer.step(
                state,
                x[idx].reshape(tau, gb, *x.shape[1:]),
                y[idx].reshape(tau, gb),
            )
        loss = float(m["loss"])
        if first is None:
            first = loss
        last = loss
    print(
        f"[rank {topo.process_index}] loss {first:.4f} -> {last:.4f}",
        flush=True,
    )
    ckpt_roundtrip = None
    if ns.ckpt_dir:
        from mpit_tpu.utils import restore_checkpoint, save_checkpoint

        # collective gather of worker-sharded leaves happens on EVERY
        # process; only process 0 writes (checkpoint.py's contract)
        save_checkpoint(ns.ckpt_dir, state, step=ns.steps)
        shardings = jax.tree.map(lambda a: a.sharding, state)
        restored, step = restore_checkpoint(
            ns.ckpt_dir, state, shardings=shardings
        )
        assert step == ns.steps
        # the restored state must reproduce the trained one bit-exactly;
        # compare a worker-sharded leaf via a collective-free local check
        a = jax.tree.leaves(state)[0]
        b = jax.tree.leaves(restored)[0]
        ckpt_roundtrip = bool(
            np.array_equal(
                np.asarray(a.addressable_data(0)),
                np.asarray(b.addressable_data(0)),
            )
        )
        print(
            f"[rank {topo.process_index}] checkpoint roundtrip "
            f"bit-exact={ckpt_roundtrip}",
            flush=True,
        )
    if ns.out:
        path = f"{ns.out}.rank{topo.process_index}.json"
        with open(path, "w") as f:
            json.dump(
                {
                    "rank": topo.process_index,
                    "process_count": topo.process_count,
                    "num_workers": w,
                    "first_loss": first,
                    "last_loss": last,
                    "ckpt_roundtrip": ckpt_roundtrip,
                },
                f,
            )
    mpit_tpu.finalize()


if __name__ == "__main__":
    main()
