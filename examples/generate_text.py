"""Train a small LM and decode from it six ways — the serving tour.

Runs anywhere (CPU included; forces the local backend so it cannot hang
on a dead hardware tunnel): trains a TransformerLM to memorize a
periodic token stream with the sync-DP trainer, then continues prompts
with each decoding recipe:

  1. generate       — exact fixed-buffer decoding (slides past max_len)
  2. generate_fast  — KV-cached, one compiled lax.scan
  3. generate_batch — N prompts through the same kernel
  4. beam_search    — best-scoring continuation with K beams
  5. generate_speculative — a smaller draft proposes, the target
     verifies; output identical to generate_fast for ANY draft
  6. Server         — continuous batching (requests arrive/finish at
     any time; results bit-equal to the solo calls)

Usage:  python examples/generate_text.py [--steps 150]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from mpit_tpu.utils.vmesh import repin_platform  # noqa: E402

repin_platform("cpu")  # the ONE copy of the sitecustomize workaround

import jax
import jax.numpy as jnp
import numpy as np
import optax

import mpit_tpu
from mpit_tpu.models import (
    Server,
    beam_search,
    generate,
    generate_batch,
    generate_fast,
    generate_speculative,
)
from mpit_tpu.models.transformer import TransformerLM
from mpit_tpu.parallel import DataParallelTrainer

V, T = 17, 32


def main():
    steps = 150
    if "--steps" in sys.argv:
        i = sys.argv.index("--steps") + 1
        if i >= len(sys.argv):
            print("--steps requires an argument", file=sys.stderr)
            raise SystemExit(2)
        steps = int(sys.argv[i])
        if steps < 1:
            print("--steps must be >= 1", file=sys.stderr)
            raise SystemExit(2)

    topo = mpit_tpu.init(num_workers=1)
    model = TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, num_heads=4, max_len=T,
        compute_dtype=jnp.float32,
    )
    trainer = DataParallelTrainer(
        model, optax.adam(3e-3), topo, donate_state=False
    )
    stream = np.arange(8 * T * 2, dtype=np.int32) % V
    x = stream.reshape(-1, T)[:8]
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = trainer.init_state(jax.random.key(1), x[:1])
    for i in range(steps):
        state, m = trainer.step(state, x, y)
    print(f"trained {steps} steps, final loss {float(m['loss']):.4f}")

    prompt = list(range(8))
    print("prompt:", prompt, "(the stream continues 8, 9, 10, ... mod 17)")
    print("generate       :", generate(model, state.params, prompt, 8))
    greedy = generate_fast(model, state.params, prompt, 8)
    print("generate_fast  :", greedy)
    print("sampled t=0.7  :", generate_fast(
        model, state.params, prompt, 8, temperature=0.7, top_k=4, seed=0))
    outs = generate_batch(
        model, state.params, [prompt, [3, 4, 5], [11, 12]], 6
    )
    for row in outs:
        print("batched row    :", row)
    seq, score = beam_search(model, state.params, prompt, 8, beam_size=4)
    print(f"beam (K=4)     : {seq}   logprob {score:.3f}")

    # speculative: train a half-size draft on the same stream, then let
    # it propose — the output is the generate_fast greedy decode exactly
    draft = TransformerLM(
        vocab_size=V, num_layers=1, d_model=16, num_heads=2, max_len=T,
        compute_dtype=jnp.float32,
    )
    d_tr = DataParallelTrainer(
        draft, optax.adam(3e-3), topo, donate_state=False
    )
    d_state = d_tr.init_state(jax.random.key(2), x[:1])
    for _ in range(steps):
        d_state, _ = d_tr.step(d_state, x, y)
    spec, stats = generate_speculative(
        model, state.params, draft, d_state.params, prompt, 8, k=4,
        return_stats=True,
    )
    print(f"speculative    : {spec}   "
          f"({stats['mean_emitted']:.1f} tokens/verify-chunk)")
    assert spec == greedy  # the exactness contract, live

    # continuous batching: three requests, one resident-cache server
    srv = Server(model, state.params, max_batch=2, segment=4)
    rids = [srv.submit(q, 6) for q in (prompt, [3, 4, 5], [11, 12])]
    served = srv.drain()
    for rid in rids:
        print("served         :", served[rid])
    mpit_tpu.finalize()


if __name__ == "__main__":
    main()
