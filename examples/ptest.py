"""ptest — the bundled end-to-end MNIST example.

Reference parity (SURVEY.md §2 comp. 6, BASELINE.json:7): the reference's
``asyncsgd/ptest.lua`` was launched as ``mpirun -n 3 th ptest.lua`` and split
ranks into 2 pclients + 1 pserver training LeNet on MNIST. Here there is no
mpirun and no rank split: the worker "processes" are the devices of the TPU
slice (or a CPU-simulated mesh), and the algorithm is chosen by flag. All
flags come from :class:`mpit_tpu.utils.TrainConfig` (see
``examples/train.py`` for the preset-driven superset CLI).

Run on the simulated mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python examples/ptest.py --algo easgd --epochs 3

Run on TPU hardware: python examples/ptest.py --algo easgd
The reference's literal shape: python examples/ptest.py --algo ps-easgd
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from mpit_tpu.utils.config import TrainConfig

    cfg = TrainConfig.from_args(description=__doc__)
    if cfg.preset is None and cfg.dataset != "mnist":
        raise SystemExit(
            "ptest is the MNIST example; use examples/train.py for other "
            "datasets"
        )

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from mpit_tpu.run import run

    r = run(cfg)
    if cfg.algo.startswith("ps-"):
        print(
            f"[ptest] {cfg.algo} ({r['clients']} pclients + {r['servers']} "
            f"pservers): test acc={r['accuracy']:.4f} "
            f"loss={r['final_loss']:.4f} wall={r['wall_s']:.1f}s "
            f"({r['samples_per_sec']:.0f} samples/sec) "
            f"server_counts={r['server_counts']}"
        )
    else:
        print(
            f"[ptest] {cfg.algo}: test acc={r['accuracy']:.4f} "
            f"loss={r['final_loss']:.4f} wall={r['wall_s']:.1f}s "
            f"({r['samples_per_sec']:.0f} samples/sec, "
            f"{r['samples_per_sec_per_chip']:.0f} per worker)"
        )


if __name__ == "__main__":
    main()
