"""ptest — the bundled end-to-end MNIST example.

Reference parity (SURVEY.md §2 comp. 6, BASELINE.json:7): the reference's
``asyncsgd/ptest.lua`` was launched as ``mpirun -n 3 th ptest.lua`` and split
ranks into 2 pclients + 1 pserver training LeNet on MNIST. Here there is no
mpirun and no rank split: the worker "processes" are the devices of the TPU
slice (or a CPU-simulated mesh), and the algorithm is chosen by flag.

Run on the simulated mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python examples/ptest.py --algo easgd --epochs 3

Run on TPU hardware: python examples/ptest.py --algo easgd
"""

import argparse
import os
import sys
import time

# allow running straight from a checkout: examples/.. is the package root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--algo",
                   choices=["easgd", "downpour", "sync",
                            "ps-easgd", "ps-downpour"],
                   default="easgd",
                   help="easgd/downpour/sync = collective trainers (fast "
                        "path); ps-* = host-async pserver/pclient fidelity "
                        "mode (the reference's literal 2-pclient+1-pserver "
                        "shape)")
    p.add_argument("--clients", type=int, default=2,
                   help="pclients (ps-* algos; reference default 2)")
    p.add_argument("--servers", type=int, default=1,
                   help="pservers (ps-* algos; reference default 1)")
    p.add_argument("--steps", type=int, default=200,
                   help="local steps per client (ps-* algos)")
    p.add_argument("--model", default="lenet")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--tau", type=int, default=4,
                   help="communication period (EASGD/Downpour)")
    p.add_argument("--alpha", type=float, default=None,
                   help="elastic coupling (default: 0.9/W per the paper)")
    p.add_argument("--staleness", type=int, default=0)
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--log-every", type=int, default=0)
    args = p.parse_args()

    import jax

    # honor an explicit JAX_PLATFORMS even when a sitecustomize pre-registered
    # a hardware backend at interpreter start (see tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import optax

    import mpit_tpu
    from mpit_tpu.data import Batches, load_mnist
    from mpit_tpu.models import get_model
    from mpit_tpu.parallel import (
        DataParallelTrainer,
        DownpourTrainer,
        EASGDTrainer,
    )

    topo = mpit_tpu.init()
    print(
        f"[ptest] world: {topo.num_workers} workers on {topo.platform} "
        f"(process {topo.process_index}/{topo.process_count})"
    )
    x_tr, y_tr, x_te, y_te = load_mnist(synthetic_train=args.train_size)
    model = get_model(args.model)
    opt = optax.sgd(args.lr, momentum=args.momentum)

    if args.algo.startswith("ps-"):
        from mpit_tpu.parallel import AsyncPSTrainer

        # same default coupling rule as the collective path: alpha = 0.9/W
        # with W = number of clients
        ps_alpha = (
            args.alpha if args.alpha is not None else 0.9 / args.clients
        )
        trainer = AsyncPSTrainer(
            model, opt,
            num_clients=args.clients, num_servers=args.servers,
            algo=args.algo.removeprefix("ps-"),
            alpha=ps_alpha,
            tau=args.tau,
        )
        per_client_batch = max(args.global_batch // args.clients, 1)
        t0 = time.perf_counter()
        center, stats = trainer.train(
            x_tr, y_tr, steps=args.steps, batch_size=per_client_batch
        )
        dt = time.perf_counter() - t0
        acc = trainer.evaluate(center, x_te, y_te)
        samples = args.steps * per_client_batch * args.clients
        print(
            f"[ptest] {args.algo} ({args.clients} pclients + "
            f"{args.servers} pservers): test acc={acc:.4f} "
            f"loss={stats['mean_final_loss']:.4f} wall={dt:.1f}s "
            f"({samples / dt:.0f} samples/sec) "
            f"server_counts={stats['server_counts']}"
        )
        return

    if args.algo == "easgd":
        trainer = EASGDTrainer(model, opt, topo, alpha=args.alpha,
                               tau=args.tau)
    elif args.algo == "downpour":
        trainer = DownpourTrainer(model, opt, topo, tau=args.tau,
                                  staleness=args.staleness)
    else:
        trainer = DataParallelTrainer(model, opt, topo)

    gb = max((args.global_batch // topo.num_workers), 1) * topo.num_workers
    if gb != args.global_batch:
        print(
            f"[ptest] global batch {args.global_batch} -> {gb} "
            f"(must divide across {topo.num_workers} workers)"
        )
    state = trainer.init_state(jax.random.key(0), x_tr[:2])
    batches = Batches(x_tr, y_tr, global_batch=gb, seed=0)

    t0 = time.perf_counter()
    state, metrics = trainer.fit(
        batches, state, epochs=args.epochs, log_every=args.log_every
    )
    dt = time.perf_counter() - t0

    if args.algo == "sync":
        acc, _ = trainer.evaluate(state, x_te, y_te)
        trained_steps = args.epochs * batches.steps_per_epoch()
    else:
        acc = trainer.evaluate(state, x_te, y_te)
        # round trainers drop the trailing < tau buffer; count what trained
        trained_steps = (
            args.epochs * batches.steps_per_epoch() // args.tau
        ) * args.tau
    samples = trained_steps * gb
    print(
        f"[ptest] {args.algo}: test acc={acc:.4f} "
        f"loss={float(metrics['loss']):.4f} wall={dt:.1f}s "
        f"({samples / dt:.0f} samples/sec, "
        f"{samples / dt / topo.num_workers:.0f} per worker)"
    )


if __name__ == "__main__":
    main()
