"""ptest_proc — the MNIST PS example in the reference's literal shape:
one OS process per rank, launched like mpirun (SURVEY.md §3(a)):

    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py --steps 100

Rank→role split happens here, exactly as the reference's ptest.lua did it
from its MPI rank: ranks [0, servers) are pservers, the rest pclients.
Messages ride :class:`mpit_tpu.transport.SocketTransport` (TCP), addresses
from ``MPIT_TRANSPORT_HOSTS`` (exported by the launcher; set it yourself
across real hosts). Initial model state: every rank builds identical
params from the shared seed — the deterministic-init equivalent of the
reference's rank-0-construct + bcast.

The protocol body is `mpit_tpu.parallel.ps_roles.client_train_loop` — the
same code the thread-mode AsyncPSTrainer runs, so both modes are
protocol-identical by construction.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from mpit_tpu.utils.config import TrainConfig

    cfg = TrainConfig.from_args(description=__doc__)

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import numpy as np
    import optax

    from mpit_tpu.data import load_mnist
    from mpit_tpu.data.datasets import shard_for_worker
    from mpit_tpu.models import get_model
    from mpit_tpu.obs import wrap_from_env, write_fault_log
    from mpit_tpu.parallel import ps_roles
    from mpit_tpu.parallel.pclient import PClient
    from mpit_tpu.parallel.pserver import PServer, partition_bounds
    from mpit_tpu.transport import (
        ChaosTransport,
        SocketTransport,
        config_from_env as chaos_config_from_env,
    )
    from mpit_tpu.utils.params import flatten_params, unflatten_params

    try:
        rank = int(os.environ["MPIT_RANK"])
        world = int(os.environ["MPIT_WORLD_SIZE"])
    except KeyError:
        raise SystemExit(
            "MPIT_RANK/MPIT_WORLD_SIZE not set — run under "
            "`python -m mpit_tpu.launch -n N examples/ptest_proc.py ...`"
        )
    num_servers = cfg.servers
    num_clients = world - num_servers
    if num_clients < 1:
        raise SystemExit(
            f"world of {world} with {num_servers} servers leaves no clients"
        )
    alpha = cfg.alpha if cfg.alpha is not None else 0.9 / num_clients

    x_tr, y_tr, x_te, y_te = load_mnist(synthetic_train=cfg.train_size)
    model = get_model(cfg.model)
    opt = optax.sgd(cfg.lr, momentum=cfg.momentum)
    # identical init on every rank from the shared seed (≡ rank-0 + bcast)
    params0 = model.init(jax.random.key(cfg.seed), jnp.asarray(x_tr[:2]))[
        "params"
    ]
    flat0, spec = flatten_params(params0)
    flat0 = np.asarray(flat0, np.float32)

    # chaos opt-in (docs/ROBUSTNESS.md): MPIT_CHAOS_* knobs wrap the
    # socket in the fault injector — same contract as thread mode, but
    # each process has its own FaultLog (faults are recorded sender-side,
    # so the per-rank union is the whole schedule)
    # MPIT_CONNECT_RETRY_S: how long a refused outbound connection is
    # retried. The 30s default absorbs startup skew, but it also hides a
    # dead peer — the sharded soak leg shrinks it so a killed server is
    # *seen* to be dead (and its shards rerouted) instead of every send
    # quietly waiting out the window
    base = SocketTransport(
        rank, world,
        connect_retry_s=float(os.environ.get("MPIT_CONNECT_RETRY_S", "30")),
    )
    chaos_cfg = chaos_config_from_env()
    fault_log = None
    if chaos_cfg is not None:
        base = ChaosTransport(base, chaos_cfg)
        fault_log = base.log
    # observability opt-in (docs/OBSERVABILITY.md): with any MPIT_OBS_*
    # knob set the transport is wrapped for tracing/telemetry — e.g.
    # MPIT_OBS_DIR=/tmp/run writes per-rank journals that
    # `python -m mpit_tpu.obs merge /tmp/run` turns into one Perfetto
    # timeline. Unset, this is the identity function. Telemetry wraps
    # OUTERMOST over chaos so its stream index stays in lockstep with
    # the chaos schedule (the fault-overlay join key).
    tp = wrap_from_env(base)
    if fault_log is not None:
        # ride the chaos schedule along every black-box dump: the
        # post-mortem then sees the injected faults inside the same
        # file as the final exchange rounds they explain
        from mpit_tpu.obs import box_for

        box = box_for(tp)
        if box is not None:
            box.add_source(
                "faults",
                lambda: [
                    {
                        "ev": "fault", "kind": e.kind, "src": e.src,
                        "dst": e.dst, "tag": e.tag, "n": e.n,
                    }
                    for e in fault_log.events()
                ],
            )
    server_ranks = list(range(num_servers))
    client_ranks = list(range(num_servers, world))
    bounds = partition_bounds(flat0.size, num_servers)

    # sharded ownership opt-in (docs/ROBUSTNESS.md "Shard ownership &
    # resharding"): MPIT_PS_SHARDS=N splits the flat vector into N ring-
    # placed shards so clients reassign a killed server's shards to the
    # survivors (live resharding) instead of skipping its range forever
    ps_shards = int(os.environ.get("MPIT_PS_SHARDS", "0"))
    shard_map = None
    if ps_shards > 0:
        from mpit_tpu.comm.topology import HashRing, ShardMap

        shard_map = ShardMap(HashRing(server_ranks), flat0.size, ps_shards)

    # elastic mode (docs/ROBUSTNESS.md): set by the supervising launcher
    # (MPIT_ELASTIC_RESPAWN=1) — clients announce themselves with JOIN so
    # a respawned replacement registers a fresh dedup epoch, servers
    # snapshot their shard for kill→restore recovery, and exchange
    # failures degrade to skipped rounds instead of killing the run.
    elastic = os.environ.get("MPIT_ELASTIC_RESPAWN", "0") not in ("", "0")
    ckpt_dir = os.environ.get("MPIT_ELASTIC_CKPT_DIR")
    # elastic implies the dead-client watchdog: a restored server whose
    # snapshot predates some client's STOP would otherwise wait forever
    # for a rank that already exited cleanly and will never speak again
    client_timeout = cfg.client_timeout
    if client_timeout is None and elastic:
        client_timeout = 15.0

    if rank < num_servers:
        start, end = bounds[rank]
        if shard_map is not None:
            pieces = [flat0[s:e] for _, s, e in shard_map.ranges_for(rank)]
            center0 = (
                np.concatenate(pieces) if pieces else np.zeros(0, np.float32)
            )
        else:
            center0 = flat0[start:end]
        server = PServer(
            tp, center0,
            num_clients=num_clients, alpha=alpha,
            client_ranks=client_ranks,
            client_timeout=client_timeout,
            ckpt_path=(
                os.path.join(ckpt_dir, f"shard_{rank}.msgpack")
                if ckpt_dir else None
            ),
            ckpt_every=int(os.environ.get("MPIT_ELASTIC_CKPT_EVERY", "5")),
            shard_map=shard_map,
        )
        server.start()  # blocks until every client stopped (or died)
        print(
            f"pserver rank {rank}: counts={server.counts} "
            f"dead_clients={sorted(server.dead_clients)}"
        )
    else:
        c = rank - num_servers
        hb = client_timeout / 3 if client_timeout else None
        client = PClient(
            tp, server_ranks, flat0.size, heartbeat_interval=hb,
            # elastic: a killed server respawns within seconds — waiting
            # the default 60s per attempt would stall its clients past
            # the soak budget; short attempts + skipped rounds instead.
            # The sharded soak leg overrides both knobs so a killed
            # server is declared dead (and its shards rerouted) within
            # seconds, not after the full retry ladder
            timeout=float(
                os.environ.get("MPIT_PS_TIMEOUT")
                or (15.0 if elastic else 60.0)
            ),
            max_retries=int(os.environ.get("MPIT_PS_MAX_RETRIES", "3")),
            shard_map=shard_map,
        )
        xs = shard_for_worker(x_tr, c, num_clients)
        ys = shard_for_worker(y_tr, c, num_clients)
        local_step = ps_roles.make_local_step(model, opt)
        per_client = max(cfg.global_batch // num_clients, 1)
        losses = ps_roles.client_train_loop(
            client, local_step, opt, spec, xs, ys,
            steps=cfg.steps, batch_size=per_client, tau=cfg.tau,
            algo=cfg.resolved_algo().removeprefix("ps-")
            if cfg.algo.startswith("ps-") else "easgd",
            alpha=alpha, seed=cfg.seed + 1000 + c,
            join=elastic,
            max_exchange_failures=8 if elastic else None,
        )
        if c == 0:
            # final center fetch BEFORE stop (servers still serving)
            center = unflatten_params(spec, jnp.asarray(client.fetch()))
            apply = jax.jit(
                lambda p, xb: model.apply({"params": p}, xb)
            )
            correct = 0
            n = (len(x_te) // 512) * 512 or len(x_te)
            for i in range(0, n, 512):
                logits = apply(center, x_te[i : i + 512])
                correct += int(
                    np.sum(np.argmax(logits, -1) == y_te[i : i + 512])
                )
            print(
                f"pclient 0: test acc={correct / n:.4f} "
                f"final loss={losses[-1]:.4f}"
            )
        client.stop()
    obs_dir = os.environ.get("MPIT_OBS_DIR")
    if fault_log is not None and obs_dir:
        # per-rank fault log for the merger's --faults overlay (a
        # directory of faults_rank*.jsonl is accepted there)
        write_fault_log(
            fault_log.events(),
            os.path.join(obs_dir, f"faults_rank{rank}.jsonl"),
        )
    tp.close()


if __name__ == "__main__":
    main()
