"""train — unified CLI over every BASELINE workload config.

The reference shipped one example script per workload (SURVEY.md §2 comp. 6);
here one CLI + presets covers them all (BASELINE.md table):

  python examples/train.py --preset mnist-easgd        # config 1 (collective)
  python examples/train.py --preset mnist-ps           # config 1 (literal
                                                       #   2 pclient+1 pserver)
  python examples/train.py --preset cifar-vgg-sync     # config 2
  python examples/train.py --preset alexnet-downpour   # config 3
  python examples/train.py --preset resnet50-sync      # config 4
  python examples/train.py --preset ptb-lstm-easgd     # config 5

Any flag overrides its preset value (e.g. ``--epochs 10 --lr 0.1``). On the
CPU-simulated mesh, prefix with:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    # the CLI lives in the package (installed as `mpit-train`); this file
    # is the same entry run from a checkout
    from mpit_tpu.run import main as run_main

    run_main(description=__doc__)


if __name__ == "__main__":
    main()
