"""parallelism_tour — every parallelism axis of the framework, one step each.

A runnable, self-contained tour of the trainer API surface beyond the
reference's data-parallel scope (docs/PARITY.md "Beyond parity"): the same
tiny transformer LM trained one step under

  dp   sync allreduce data parallelism        (DataParallelTrainer)
  sp   ring-attention sequence parallelism    (SeqParallelTrainer)
  tp   GSPMD Megatron tensor parallelism      (TensorParallelTrainer)
  pp   pipeline parallelism, 3 schedules      (PipelineParallelTrainer)
  ep   expert-parallel mixture-of-experts     (MoEParallelTrainer)
  3-D  composed dp x tp x sp in one step      (ComposedParallelTrainer)

Run it anywhere — no TPU needed:

  python examples/parallelism_tour.py          # provisions 8 CPU devices

Each section prints the mesh it built and the first-step loss; every
trainer here is trajectory-proven against an unsharded reference in
tests/ (the tour shows the API, the tests prove the math).
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

# must precede jax backend init (a sitecustomize-registered hardware
# backend otherwise claims the platform)
from mpit_tpu.utils.vmesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import mpit_tpu  # noqa: E402
from mpit_tpu.models.transformer import TransformerLM  # noqa: E402
from mpit_tpu.parallel import (  # noqa: E402
    ComposedParallelTrainer,
    DataParallelTrainer,
    MoEParallelTrainer,
    PipelineParallelTrainer,
    SeqParallelTrainer,
    TensorParallelTrainer,
)

V, B, T = 31, 8, 32
rng = np.random.default_rng(0)
X = rng.integers(0, V, (B, T)).astype(np.int32)
Y = np.roll(X, -1, axis=1).astype(np.int32)


def lm(**kw):
    kw = {"num_heads": 4, **kw}
    return TransformerLM(
        vocab_size=V, num_layers=2, d_model=32, max_len=T,
        compute_dtype=jnp.float32, **kw,
    )


def show(tag, topo, loss):
    print(f"{tag:<28} mesh={dict(topo.mesh.shape)}  loss={loss:.4f}")


def fresh(axis_names=None, mesh_shape=None, **kw):
    mpit_tpu.finalize()
    if axis_names is None:
        return mpit_tpu.init(**kw)
    return mpit_tpu.init(axis_names=axis_names, mesh_shape=mesh_shape, **kw)


# dp — the reference's scope, one fused allreduce per step
topo = fresh()
tr = DataParallelTrainer(lm(), optax.adam(1e-3), topo, donate_state=False)
st = tr.init_state(jax.random.key(0), X[:2])
st, m = tr.step(st, X, Y)
show("dp (sync allreduce)", topo, float(m["loss"]))

# dp with gradient accumulation — same math, 1/4 the activation memory
# (needs a per-worker batch divisible by the accumulation factor)
tr = DataParallelTrainer(
    lm(), optax.adam(1e-3), topo, donate_state=False, accum_steps=4
)
st = tr.init_state(jax.random.key(0), X[:2])
st, m = tr.step(st, np.tile(X, (4, 1)), np.tile(Y, (4, 1)))
show("dp + grad accumulation x4", topo, float(m["loss"]))

# dp with ZeRO-1 — Adam's mu/nu sharded 1/8 per device, same trajectory
from mpit_tpu.parallel import ZeroDataParallelTrainer  # noqa: E402

tr = ZeroDataParallelTrainer(
    lm(), optax.adam(1e-3), topo, donate_state=False
)
st = tr.init_state(jax.random.key(0), X[:2])
st, m = tr.step(st, X, Y)
show("dp + ZeRO-1 optimizer shards", topo, float(m["loss"]))

# sp — the sequence sharded across devices, exact ring attention
topo = fresh(("dp", "sp"), (2, 4))
tr = SeqParallelTrainer(
    lm(seq_axis="sp"), optax.adam(1e-3), topo, donate_state=False
)
st = tr.init_state(jax.random.key(0), X[:2, : T // 4])
st, m = tr.step(st, X, Y)
show("sp (ring attention)", topo, float(m["loss"]))

# tp — Megatron shardings, collectives inserted by the partitioner
topo = fresh(("dp", "tp"), (2, 4))
tr = TensorParallelTrainer(
    lm(), optax.adam(1e-3), topo, donate_state=False
)
st = tr.init_state(jax.random.key(0), X[:2])
st, m = tr.step(st, X, Y)
show("tp (GSPMD Megatron)", topo, float(m["loss"]))

# pp — three schedules over the same mesh
topo = fresh(("dp", "pp"), (2, 4))
for sched, layers in (("gpipe", 4), ("1f1b", 4), ("interleaved", 8)):
    tr = PipelineParallelTrainer(
        vocab_size=V, num_layers=layers, d_model=32, num_heads=4,
        seq_len=T, topo=topo, n_micro=2, lr=0.1, schedule=sched,
    )
    st = tr.init_state(jax.random.key(0))
    st, m = tr.step(st, X, Y)
    show(f"pp ({sched}, {tr.ticks} ticks)", topo, float(m["loss"]))

# ep — experts sharded over the worker axis, all_to_all dispatch,
# top-2 routing with the balance loss in the objective
topo = fresh()
tr = MoEParallelTrainer(
    lm(moe_experts=8, moe_axis=topo.worker_axis, moe_top_k=2,
       moe_balance_weight=0.01, moe_capacity_factor=4.0),
    optax.adam(1e-3), topo, donate_state=False,
)
st = tr.init_state(jax.random.key(0), X[:1])
st, m = tr.step(st, X, Y)
show(
    f"ep (top-2 MoE, balance={float(m['moe_balance']):.3f})",
    topo, float(m["loss"]),
)

# 3-D — data, tensor, and sequence parallelism in ONE jitted step
topo = fresh(("dp", "tp", "sp"), (2, 2, 2))
tr = ComposedParallelTrainer(
    lm(seq_axis="sp", num_heads=8), optax.adam(1e-3), topo,
    donate_state=False,
)
st = tr.init_state(jax.random.key(0), X[:2, : T // 2])
st, m = tr.step(st, X, Y)
show("dp x tp x sp (composed)", topo, float(m["loss"]))

mpit_tpu.finalize()
print("tour complete — every axis trained a real step on this machine")
