"""goptim — distributed optimizer math (EASGD / EAMSGD / Downpour).

Reference parity (SURVEY.md §2 comp. 5): the reference's ``goptim`` provided
torch-optim-style functions ``geasgd`` / ``gdownpour`` that drove the
pclient push-pull every τ steps. Here the *math* lives in pure jittable
functions (this module) and the *orchestration* lives in the trainers
(``mpit_tpu.parallel.easgd`` / ``downpour``) — the split jax rewards: pure
update rules compose with jit/scan/shard_map, while the reference interleaved
math and MPI calls in one loop.

EASGD (Zhang, Choromanska, LeCun, NeurIPS 2015 — the paper the reference
implements; arXiv:1412.6651):

  every τ local SGD steps, with elastic coupling α and old center x̃_t:
    client:  x_i ← x_i − α (x_i − x̃_t)
    center:  x̃  ← x̃_t + α Σ_i (x_i − x̃_t)          (= x̃ + αW · mean_i diff)

EAMSGD = EASGD with momentum in the local steps (the local optimizer's
concern — pass ``optax.sgd(lr, momentum=m)``).

Downpour (Dean et al. 2012, as re-expressed by the EASGD paper's baselines):
workers run local steps, push accumulated updates to the center every τ
steps, and pull the (possibly stale) center back.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax import lax


def elastic_client_move(params: Any, center: Any, alpha: float) -> Any:
    """x_i ← x_i − α (x_i − x̃): pull the client toward the center."""
    return jax.tree.map(lambda p, c: p - alpha * (p - c), params, center)


def summed_client_diffs(
    params: Any,
    center: Any,
    axis_name: str,
    compress_dtype: Any = None,
) -> Any:
    """Σ_i (x_i − x̃) across the worker axis — the one collective of the
    EASGD exchange (shared by the plain and pallas paths).

    ``compress_dtype`` (e.g. ``jnp.bfloat16``) casts the diffs before the
    psum and back to the param dtype after — halving the bytes the
    collective moves over ICI/DCN (the quantized-allreduce idea of EQuARX,
    arXiv:2506.17615, in its simplest robust form). Sound for EASGD
    because the exchange transmits *differences* from the center, which
    are small and α-damped: quantization error enters as a bounded
    perturbation of an already-stochastic elastic move, not as
    accumulating drift of the master weights (which stay full precision).
    """
    diffs = jax.tree.map(lambda p, c: p - c, params, center)
    if compress_dtype is None:
        return lax.psum(diffs, axis_name)
    total = lax.psum(
        jax.tree.map(lambda d: d.astype(compress_dtype), diffs), axis_name
    )
    return jax.tree.map(
        lambda t, p: t.astype(p.dtype), total, params
    )


def elastic_center_move(
    center: Any, params: Any, alpha: float, axis_name: str,
    compress_dtype: Any = None,
) -> Any:
    """x̃ ← x̃ + α Σ_i (x_i − x̃): pull the center toward the clients.

    Must run inside SPMD over ``axis_name``; the sum over clients is one
    ``psum`` (this is exactly where the reference's pserver applied its
    per-message elastic update, SURVEY.md §3(c) — the collective form is the
    mathematically identical symmetric-round version, §5 item (i))."""
    total_diff = summed_client_diffs(
        params, center, axis_name, compress_dtype
    )
    return jax.tree.map(lambda c, d: c + alpha * d, center, total_diff)


def easgd_round(
    params: Any,
    center: Any,
    alpha: float,
    axis_name: str,
    use_pallas: bool = False,
    compress_dtype: Any = None,
) -> tuple[Any, Any]:
    """One synchronous elastic-averaging exchange; returns (params, center).

    Both moves use the *old* center, per the paper's update order.
    ``use_pallas`` routes the post-psum elementwise math through the fused
    kernel in :mod:`mpit_tpu.ops` (numerically identical; see its scope
    note). ``compress_dtype`` compresses the exchange collective (see
    :func:`summed_client_diffs`)."""
    if not use_pallas:
        new_params = elastic_client_move(params, center, alpha)
        new_center = elastic_center_move(
            center, params, alpha, axis_name, compress_dtype
        )
        return new_params, new_center

    from mpit_tpu import ops

    total_diff = summed_client_diffs(
        params, center, axis_name, compress_dtype
    )
    # flatten/unflatten by the params treedef (an is_leaf=tuple unzip would
    # misfire on pytrees whose CONTAINERS are tuples)
    leaves_p, treedef = jax.tree.flatten(params)
    leaves_c = jax.tree.leaves(center)
    leaves_d = jax.tree.leaves(total_diff)
    pairs = [
        ops.elastic_update(p, c, d, alpha, use_pallas=True)
        for p, c, d in zip(leaves_p, leaves_c, leaves_d)
    ]
    new_params = jax.tree.unflatten(treedef, [x for x, _ in pairs])
    new_center = jax.tree.unflatten(treedef, [c for _, c in pairs])
    return new_params, new_center


def downpour_push(
    center: Any, accumulated_updates: Any, axis_name: str, average: bool = True
) -> Any:
    """Server-side apply of pushed worker updates (one psum).

    ``average=True`` is the model-averaging flavor named by BASELINE.json:9;
    ``False`` sums raw updates (classic Downpour grad push)."""
    op = lax.pmean if average else lax.psum
    total = op(accumulated_updates, axis_name)
    return jax.tree.map(lambda c, u: c + u, center, total)


def downpour_pull(center: Any, stale_center: Optional[Any] = None) -> Any:
    """Worker pull: replace local params with the center (or a stale snapshot
    when emulating asynchrony — SURVEY.md §7 step 4's delay buffer)."""
    return stale_center if stale_center is not None else center
