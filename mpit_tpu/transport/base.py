"""Transport interface: mpiT's Send/Recv/Isend/Irecv/Probe surface."""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

ANY_SOURCE = -1
ANY_TAG = -1


class RecvTimeout(Exception):
    """recv()/probe() deadline expired (the reference would simply hang —
    SURVEY.md §5 failure detection: 'a dead rank hangs the job')."""


@dataclasses.dataclass
class Message:
    src: int
    dst: int
    tag: int
    payload: Any

    def matches(self, src: int, tag: int) -> bool:
        return (src == ANY_SOURCE or src == self.src) and (
            tag == ANY_TAG or tag == self.tag
        )


class SendHandle:
    """Handle returned by isend (completes immediately for queued local
    delivery; socket sends complete when written)."""

    def __init__(self):
        self._done = threading.Event()

    def set_done(self):
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._done.wait(timeout)
        if not ok:
            raise RecvTimeout("isend not complete before timeout")
        return True


class RecvHandle:
    """Handle returned by irecv; wait() yields the Message."""

    def __init__(self, fetch):
        self._fetch = fetch
        self._msg: Optional[Message] = None

    def wait(self, timeout: Optional[float] = None) -> Message:
        if self._msg is None:
            self._msg = self._fetch(timeout)
        return self._msg


class Transport:
    """Abstract tagged p2p transport for one rank.

    mpiT surface mapping: Send/Recv/Isend/Irecv/Wait/Probe with tags and
    ANY_SOURCE (SURVEY.md §2 L2 row). ``rank``/``size`` here are *transport*
    ranks (host actors: pservers + pclients), distinct from the device-mesh
    worker ids of the collective trainers.
    """

    rank: int
    size: int

    def send(self, dst: int, tag: int, payload: Any) -> None:
        raise NotImplementedError

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        raise NotImplementedError

    def isend(self, dst: int, tag: int, payload: Any) -> SendHandle:
        h = SendHandle()
        self.send(dst, tag, payload)
        h.set_done()
        return h

    def irecv(self, src: int = ANY_SOURCE, tag: int = ANY_TAG) -> RecvHandle:
        return RecvHandle(lambda timeout: self.recv(src, tag, timeout))

    def probe(
        self, src: int = ANY_SOURCE, tag: int = ANY_TAG
    ) -> bool:
        """Non-blocking: is a matching message waiting?"""
        raise NotImplementedError

    def close(self) -> None:
        pass
