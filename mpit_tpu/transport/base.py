"""Transport interface: mpiT's Send/Recv/Isend/Irecv/Probe surface."""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

ANY_SOURCE = -1
ANY_TAG = -1


class RecvTimeout(Exception):
    """recv()/probe() deadline expired (the reference would simply hang —
    SURVEY.md §5 failure detection: 'a dead rank hangs the job')."""


@dataclasses.dataclass
class Message:
    src: int
    dst: int
    tag: int
    payload: Any
    # exact on-wire byte count (length prefix + frame) stamped by byte-
    # counting transports (SocketTransport); None for reference-passing
    # transports, where obs telemetry falls back to its estimate
    wire_nbytes: Optional[int] = None

    def matches(self, src: int, tag: int) -> bool:
        return (src == ANY_SOURCE or src == self.src) and (
            tag == ANY_TAG or tag == self.tag
        )


class SendHandle:
    """Handle returned by isend (mpiT's ``Isend``/``Wait`` pair).

    Completes immediately for queued local delivery; socket isends complete
    when the frame is written by the background sender. A failed async send
    parks its exception here and re-raises it from :meth:`wait` — errors
    must reach the caller, not die in a worker thread."""

    def __init__(self):
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        # wire-phase wall-clock split (seconds), stamped by phase-aware
        # transports (SocketTransport: serialize / queue_wait / write)
        # BEFORE the handle completes; valid only once done() is true.
        # Transports without a phase breakdown leave it None.
        self.phases: Optional[dict] = None
        # exact bytes written for this send (length prefix included),
        # stamped alongside ``phases`` by byte-counting transports
        self.wire_nbytes: Optional[int] = None

    def set_done(self):
        self._done.set()

    def set_error(self, exc: BaseException):
        self._error = exc
        self._done.set()

    def done(self) -> bool:
        """Non-blocking completion check (MPI_Test parity)."""
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._done.wait(timeout)
        if not ok:
            raise RecvTimeout("isend not complete before timeout")
        if self._error is not None:
            raise self._error
        return True


class RecvHandle:
    """Handle returned by irecv; wait() yields the Message."""

    def __init__(self, fetch):
        self._fetch = fetch
        self._msg: Optional[Message] = None

    def wait(self, timeout: Optional[float] = None) -> Message:
        if self._msg is None:
            self._msg = self._fetch(timeout)
        return self._msg


class Transport:
    """Abstract tagged p2p transport for one rank.

    mpiT surface mapping: Send/Recv/Isend/Irecv/Wait/Probe with tags and
    ANY_SOURCE (SURVEY.md §2 L2 row). ``rank``/``size`` here are *transport*
    ranks (host actors: pservers + pclients), distinct from the device-mesh
    worker ids of the collective trainers.
    """

    rank: int
    size: int

    def send(self, dst: int, tag: int, payload: Any) -> None:
        raise NotImplementedError

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        raise NotImplementedError

    def isend(self, dst: int, tag: int, payload: Any) -> SendHandle:
        h = SendHandle()
        self.send(dst, tag, payload)
        h.set_done()
        return h

    def irecv(self, src: int = ANY_SOURCE, tag: int = ANY_TAG) -> RecvHandle:
        return RecvHandle(lambda timeout: self.recv(src, tag, timeout))

    def probe(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = 0,
    ) -> bool:
        """Is a matching message waiting (without consuming it)?

        ``timeout=0`` polls (MPI_Iprobe), ``timeout=None`` blocks until a
        match arrives (MPI_Probe), ``timeout>0`` waits at most that long.
        Returns False on expiry rather than raising — probing for absence
        is a legitimate outcome, unlike an expired recv."""
        raise NotImplementedError

    def close(self) -> None:
        pass
