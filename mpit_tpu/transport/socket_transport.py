"""TCP transport: ranks are processes, tagged delivery over sockets.

The DCN-style control plane for the host-async PS mode across hosts (the
reference's multi-node MPI case, SURVEY.md §2 distributed-backend row). Data
parallel *gradient* traffic should ride XLA collectives over ICI — this
transport is for the PS protocol's small, latency-tolerant messages.

Wire format: 8-byte big-endian length prefix, then ONE of two frame bodies,
distinguished per-frame by the first two bytes:

* **framed** (``transport/wire.py``, magic ``b"MW"``): a CRC-guarded binary
  header (src, tag, envelope scalars, dtype/shape) followed by raw ndarray
  bytes. The sender builds the frame from ``memoryview``s of the arrays —
  no copy, no pickle — and writes it with vectorized ``sendmsg``; the
  receiver reads the array bytes straight into a preallocated buffer with
  ``recv_into`` and wraps it zero-copy. Every frame writer must pin
  ``WIRE_FORMAT_VERSION`` by name (lint rule MPT007).
* **pickle** (``WIRE_PICKLE_PROTOCOL``, the canonical pin every pickle wire
  writer must name — lint rule MPT007) of (src, tag, payload). Pickle
  protocol ≥2 streams start ``b"\\x80"``, which can never collide with the
  framed magic. This is the fallback for payloads the binary codec cannot
  express and for mixed-version peers.

Negotiation: the *receiver* advertises — every accepted connection gets a
4-byte HELLO carrying the receiver's framed-format version before any
frames flow. The sender reads it (with a short timeout) right after
connect; no HELLO ⇒ pickle-only peer. Legacy receivers never send HELLO
(so new senders fall back), and legacy senders never read their outbound
socket (so the unread HELLO is harmless) — both mixed pairings keep
working. ``MPIT_WIRE_NEGOTIATE=0`` makes this transport behave like such a
legacy peer (no HELLO sent or awaited, pickle only).

Reconnect semantics: TCP gives FIFO within one connection; across a sender
reconnect, a straggler frame from the old connection could otherwise be
enqueued *after* frames of the new one and break per-(src,tag) FIFO. The
receiver therefore orders connections by accept sequence and, once a frame
from a src arrives on a newer connection, drops late frames from that src's
older connections — order is preserved at the cost of dropping stragglers,
which matches MPI's model (a broken connection loses in-flight traffic; a
dead rank is fatal, SURVEY.md §5 failure-detection row) rather than silently
reordering. The fence is entirely receiver-side accept ordering, so a fully
*restarted* sender (fresh transport object) keeps working — its new
connection is by construction newer than any it had before.

Rendezvous: ``MPIT_TRANSPORT_HOSTS="host0:port0,host1:port1,..."`` (index =
rank), or ``addresses=`` in the constructor; defaults to
``127.0.0.1:(base_port+rank)`` for single-host multi-process runs.
"""

from __future__ import annotations

import collections
import errno
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence

from mpit_tpu.analysis.runtime import make_condition, make_lock
from mpit_tpu.transport import wire
from mpit_tpu.transport.base import (
    ANY_SOURCE,
    ANY_TAG,
    Message,
    SendHandle,
    Transport,
)
from mpit_tpu.transport.chaos import CorruptedPayload
from mpit_tpu.transport.inproc import Broker
from mpit_tpu.transport.wire import WIRE_FORMAT_VERSION

_LEN = struct.Struct(">Q")

# The wire's ONE pickle protocol. Readers auto-detect (the id is embedded
# in the stream), but every WRITER must pin this — an unpinned dumps rides
# the interpreter default, which moves across Python versions, and a
# mixed-version peer then sees unparseable frames on an otherwise healthy
# socket. Every dumps feeding a frame (here and in mpit_tpu/native) must
# name this constant; the MPT007 lint rule enforces exactly that.
WIRE_PICKLE_PROTOCOL = 5

# sendmsg iovec count is bounded by IOV_MAX (1024 on Linux); a coalesced
# scatter frame stays far below this, but cap defensively anyway
_SENDMSG_MAX_BUFFERS = 512


def _addresses(size: int, base_port: int) -> list[tuple[str, int]]:
    env = os.environ.get("MPIT_TRANSPORT_HOSTS")
    if env:
        out = []
        for part in env.split(","):
            host, port = part.rsplit(":", 1)
            out.append((host, int(port)))
        if len(out) != size:
            raise ValueError(
                f"MPIT_TRANSPORT_HOSTS has {len(out)} entries, need {size}"
            )
        return out
    return [("127.0.0.1", base_port + r) for r in range(size)]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_into_exact(sock: socket.socket, buf: bytearray) -> None:
    """Fill ``buf`` completely from the socket — the zero-copy receive:
    bytes land directly in the buffer the decoded arrays will view."""
    view = memoryview(buf)
    got = 0
    while got < len(buf):
        n = sock.recv_into(view[got:])
        if n == 0:
            raise ConnectionError("peer closed")
        got += n


def _drain_exact(sock: socket.socket, n: int) -> None:
    """Consume and discard n bytes (skip the rest of an undecodable frame
    so the length-prefixed stream stays in sync)."""
    left = n
    while left > 0:
        chunk = sock.recv(min(left, 65536))
        if not chunk:
            raise ConnectionError("peer closed")
        left -= len(chunk)


class _OutMessage:
    """One queued outbound message, format-deferred.

    The framed buffers are built eagerly at isend time (zero-copy: they
    alias the caller's arrays, which MPI buffer semantics say are frozen
    until the send completes) — but whether the *framed* or *pickle* bytes
    actually hit the socket is decided by the drainer, after negotiation
    has revealed what the peer speaks. The pickle frame is built lazily and
    cached so an evict-retry does not re-serialize."""

    __slots__ = ("src", "tag", "payload", "buffers", "_pickled")

    def __init__(self, src: int, tag: int, payload: Any, buffers):
        self.src = src
        self.tag = tag
        self.payload = payload
        self.buffers = buffers  # list of buffers, or None (unencodable)
        self._pickled: Optional[bytes] = None

    def pickle_frame(self) -> bytes:
        if self._pickled is None:
            blob = pickle.dumps(
                (self.src, self.tag, self.payload),
                protocol=WIRE_PICKLE_PROTOCOL,
            )
            self._pickled = _LEN.pack(len(blob)) + blob
        return self._pickled

    def framed_buffers(self) -> list:
        """Length-prefixed buffer list for sendmsg. The prefix is fused
        onto the (small) header buffer; the array views ride untouched."""
        total = wire.frame_nbytes(self.buffers)
        return [_LEN.pack(total) + self.buffers[0], *self.buffers[1:]]


class SocketTransport(Transport):
    def __init__(
        self,
        rank: int,
        size: int,
        base_port: int = 29_500,
        addresses: Optional[Sequence[tuple[str, int]]] = None,
        connect_retry_s: float = 30.0,
        wire_format: Optional[str] = None,
    ):
        """``connect_retry_s``: window during which a refused outbound
        connection is retried — under a process launcher the peers come up
        at different times (mpirun gave the reference this for free).
        ``wire_format``: "framed" (default) or "pickle"; None reads
        ``MPIT_WIRE_FORMAT``."""
        self.rank = rank
        self.size = size
        self.connect_retry_s = float(connect_retry_s)
        self._addrs = (
            list(addresses) if addresses is not None else _addresses(size, base_port)
        )
        if wire_format is None:
            wire_format = wire.wire_format_from_env()
        elif wire_format not in ("framed", "pickle"):
            raise ValueError(f"wire_format must be framed|pickle, got {wire_format!r}")
        self._wire_format = wire_format
        self._negotiate = wire.negotiate_enabled_from_env()
        self._hello_timeout = wire.negotiate_timeout_from_env()
        # per-dst negotiation outcome: True once the peer's HELLO proved it
        # decodes framed; absent/False ⇒ pickle only
        self._peer_framed: dict[int, bool] = {}
        # local mailbox reuses the broker's matching logic (1 "rank" = me)
        self._mailbox = Broker(1)
        # reconnect fencing: newest accept-ordered connection seq per src
        self._accept_seq = 0
        self._src_seq: dict[int, int] = {}
        self._src_seq_lock = make_lock("SocketTransport._src_seq_lock")
        self._out: dict[int, socket.socket] = {}
        self._out_cache_lock = make_lock(
            "SocketTransport._out_cache_lock"
        )  # guards the dict only
        # per-destination lock: a slow connect/send to one rank must not
        # serialize traffic to healthy ranks
        self._dst_locks: dict[int, Any] = {}
        # per-destination outbound queues drained by lazily-created sender
        # threads: isend returns immediately, and because send() rides the
        # same queue, send/isend to one dst stay FIFO (the MPI order rule)
        self._send_queues: dict[int, "_SendQueue"] = {}
        # inbound wire-phase accounting per (src, tag): body-transfer and
        # deserialize seconds (the header wait is idle between messages and
        # deliberately NOT counted). Harvested by obs telemetry summaries.
        self._rx_phases: dict[tuple[int, int], dict] = {}
        self._rx_lock = make_lock("SocketTransport._rx_lock")
        # exact on-wire byte totals (length prefixes included), both
        # directions — ground truth the obs summaries are asserted against
        self._tx_wire_bytes = 0
        self._rx_wire_bytes = 0
        self._rx_corrupt_dropped = 0
        self._byte_lock = make_lock("SocketTransport._byte_lock")
        self._closing = threading.Event()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._listener.bind(self._addrs[rank])
        except OSError as e:
            raise OSError(
                f"rank {rank}: cannot bind {self._addrs[rank]} ({e}). "
                "If launched via mpit_tpu.launch, another process likely "
                "took the port between reservation and startup — relaunch."
            ) from e
        self._listener.listen(size)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # -- wire -------------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            if self._negotiate:
                # receiver-advertises: tell the peer what we decode before
                # any frames flow (legacy receivers skip this, so a new
                # sender's HELLO wait times out ⇒ pickle fallback)
                try:
                    conn.sendall(wire.encode_hello())
                except OSError:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
            with self._src_seq_lock:
                self._accept_seq += 1
                seq = self._accept_seq
            threading.Thread(
                target=self._read_loop, args=(conn, seq), daemon=True
            ).start()

    def _read_loop(self, conn: socket.socket, seq: int):
        try:
            while not self._closing.is_set():
                # phase split: the header wait is inter-message idle (the
                # reader blocks here between frames) and is NOT a phase;
                # body streaming is payload-transfer, decode is deserialize
                (length,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                t_h = time.perf_counter()
                msg = self._read_body(conn, length)
                with self._byte_lock:
                    self._rx_wire_bytes += _LEN.size + length
                if msg is None:
                    continue
                src, tag, payload, t_b, t_d = msg
                with self._rx_lock:
                    d = self._rx_phases.get((src, tag))
                    if d is None:
                        d = self._rx_phases[(src, tag)] = {
                            "transfer": 0.0, "deserialize": 0.0, "msgs": 0,
                        }
                    d["transfer"] += t_b - t_h
                    d["deserialize"] += t_d - t_b
                    d["msgs"] += 1
                with self._src_seq_lock:
                    latest = self._src_seq.get(src, 0)
                    if seq < latest:
                        continue  # straggler from before src's reconnect
                    self._src_seq[src] = seq
                self._mailbox.put(
                    Message(
                        src=src,
                        dst=0,
                        tag=tag,
                        payload=payload,
                        wire_nbytes=_LEN.size + length,
                    )
                )
        except (ConnectionError, OSError):
            return

    def _read_body(self, conn: socket.socket, length: int):
        """Read one frame body of ``length`` bytes; dispatch on magic.

        Returns (src, tag, payload, t_body_done, t_decode_done), or None
        for an undecodable framed body that was consumed and counted but
        yielded nothing deliverable (stream coordinates unknown)."""
        if length < wire.PREAMBLE_SIZE:
            body = _recv_exact(conn, length)
            t_b = time.perf_counter()
            src, tag, payload = pickle.loads(body)
            return src, tag, payload, t_b, time.perf_counter()
        head = _recv_exact(conn, wire.PREAMBLE_SIZE)
        if head[:2] != wire.MAGIC:
            body = head + _recv_exact(conn, length - wire.PREAMBLE_SIZE)
            t_b = time.perf_counter()
            src, tag, payload = pickle.loads(body)
            return src, tag, payload, t_b, time.perf_counter()
        consumed = wire.PREAMBLE_SIZE
        try:
            _version, flags, hlen, hcrc = wire.split_preamble(head)
            if wire.PREAMBLE_SIZE + hlen > length:
                raise wire.WireDecodeError("header length exceeds frame")
            header = _recv_exact(conn, hlen)
            consumed += hlen
            body = bytearray(length - consumed)
            _recv_into_exact(conn, body)
            consumed = length
            t_b = time.perf_counter()
            src, tag, payload = wire.decode_frame(flags, hcrc, header, body)
            return src, tag, payload, t_b, time.perf_counter()
        except wire.WireDecodeError as e:
            # a corrupted frame degrades exactly like a chaos `corrupt`
            # fault: deliver a CorruptedPayload marker so the receiving
            # role's malformed_dropped path absorbs it. Skip the rest of
            # the frame first — the stream must stay length-synced.
            if consumed < length:
                _drain_exact(conn, length - consumed)
            with self._byte_lock:
                self._rx_corrupt_dropped += 1
            t_b = time.perf_counter()
            src = e.src if e.src is not None else -1
            tag = e.tag if e.tag is not None else -1
            return src, tag, CorruptedPayload(src=src, tag=tag), t_b, t_b

    def _dst_lock(self, dst: int):
        with self._out_cache_lock:
            lock = self._dst_locks.get(dst)
            if lock is None:
                lock = self._dst_locks[dst] = make_lock(
                    f"SocketTransport._dst_locks[{dst}]"
                )
            return lock

    def _connection(self, dst: int) -> socket.socket:
        """Cached outbound socket; caller must hold the dst lock."""
        with self._out_cache_lock:
            sock = self._out.get(dst)
        if sock is None:
            sock = self._connect_with_retry(dst)
            framed_peer = False
            if self._wire_format == "framed" and self._negotiate:
                framed_peer = self._await_hello(sock)
            # back to blocking mode: a mid-frame timeout would desync the
            # length-prefixed stream for every later frame
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._out_cache_lock:
                self._out[dst] = sock
                self._peer_framed[dst] = framed_peer
        return sock

    def _await_hello(self, sock: socket.socket) -> bool:
        """Read the receiver's HELLO off a fresh outbound connection. A
        legacy peer sends nothing — the timeout is the negative signal —
        and nothing else ever arrives on this socket (frames only flow
        inbound→listener), so the read cannot swallow real traffic."""
        try:
            sock.settimeout(self._hello_timeout)
            data = _recv_exact(sock, wire.HELLO_SIZE)
        except (ConnectionError, OSError):
            return False
        peer_version = wire.decode_hello(data)
        return peer_version is not None and peer_version >= 1

    # transient connect failures retried within the window alongside a
    # clean refusal: real-DCN startup skew surfaces as timeouts and
    # unreachable-host/network errors while routes and peers come up,
    # not only as ECONNREFUSED
    _TRANSIENT_CONNECT_ERRNOS = frozenset(
        {errno.ETIMEDOUT, errno.EHOSTUNREACH, errno.ENETUNREACH}
    )

    def _connect_with_retry(self, dst: int) -> socket.socket:
        import time as _time

        deadline = _time.monotonic() + self.connect_retry_s
        while True:
            try:
                return socket.create_connection(self._addrs[dst], timeout=30)
            except OSError as e:
                transient = (
                    isinstance(e, (ConnectionRefusedError, TimeoutError))
                    or e.errno in self._TRANSIENT_CONNECT_ERRNOS
                )
                if (
                    not transient
                    or _time.monotonic() >= deadline
                    or self._closing.is_set()
                ):
                    raise
                _time.sleep(0.1)  # peer not reachable yet (startup skew)

    def _evict(self, dst: int) -> None:
        with self._out_cache_lock:
            sock = self._out.pop(dst, None)
            self._peer_framed.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _write_frame(self, dst: int, frame: bytes) -> None:
        """Write pre-serialized pickle bytes (legacy entry point)."""
        with self._dst_lock(dst):
            try:
                self._connection(dst).sendall(frame)
            except (ConnectionError, OSError):
                # stale cached socket (peer restarted): reconnect once. The
                # receiver's accept-order fence drops any stragglers still in
                # flight on the old connection. Whole-frame retry is safe —
                # the reader discards a connection on any partial frame.
                self._evict(dst)
                self._connection(dst).sendall(frame)
        with self._byte_lock:
            self._tx_wire_bytes += len(frame)

    def _write_msg(self, dst: int, item: _OutMessage) -> int:
        """Write one queued message in the best format the peer speaks;
        returns exact bytes written. Called only from the dst's drainer."""
        with self._dst_lock(dst):
            try:
                self._connection(dst)  # negotiates on a fresh connect
                n = self._send_item(dst, item)
            except (ConnectionError, OSError):
                # stale cached socket (peer restarted): reconnect once,
                # re-negotiating. Whole-message resend is safe — the
                # receiver discards a connection on any partial frame, and
                # the accept-order fence drops old-connection stragglers.
                self._evict(dst)
                self._connection(dst)
                n = self._send_item(dst, item)
        with self._byte_lock:
            self._tx_wire_bytes += n
        return n

    def _send_item(self, dst: int, item: _OutMessage) -> int:
        # under the dst lock the cached entries are stable, but the DICTS
        # are shared with close()/other drainers — reads take the cache
        # lock like every other access
        with self._out_cache_lock:
            sock = self._out[dst]
            peer_framed = self._peer_framed.get(dst)
        if item.buffers is not None and peer_framed:
            return self._sendmsg_all(sock, item.framed_buffers())
        frame = item.pickle_frame()
        sock.sendall(frame)
        return len(frame)

    @staticmethod
    def _sendmsg_all(sock: socket.socket, buffers: list) -> int:
        """Vectorized write of the framed buffer list (writev semantics):
        the kernel gathers header bytes + raw array views in one syscall
        per batch — the arrays are never copied into a Python-level frame."""
        bufs = [
            b if isinstance(b, memoryview) else memoryview(b) for b in buffers
        ]
        total = sum(b.nbytes for b in bufs)
        if not hasattr(sock, "sendmsg"):  # exotic platform fallback
            for b in bufs:
                sock.sendall(b)
            return total
        while bufs:
            sent = sock.sendmsg(bufs[:_SENDMSG_MAX_BUFFERS])
            while bufs and sent >= bufs[0].nbytes:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            if bufs and sent:
                bufs[0] = bufs[0][sent:]  # partial write: advance in place
        return total

    def _send_queue(self, dst: int) -> "_SendQueue":
        with self._out_cache_lock:
            q = self._send_queues.get(dst)
            if q is None:
                q = self._send_queues[dst] = _SendQueue(self, dst)
            return q

    # -- Transport API ----------------------------------------------------

    def send(self, dst: int, tag: int, payload: Any) -> None:
        self.isend(dst, tag, payload).wait()

    def isend(self, dst: int, tag: int, payload: Any) -> SendHandle:
        """Genuinely asynchronous: the frame (captured NOW — per MPI buffer
        semantics the payload must not be mutated until the send completes)
        is handed to the dst's sender thread; the handle completes when it
        is written, with its ``phases`` split (serialize / queue_wait /
        write) and exact ``wire_nbytes`` stamped. Framed encoding is
        zero-copy (the buffers alias the payload's arrays); payloads the
        codec cannot express — and all traffic to pickle-only peers — ride
        the pickle fallback."""
        t0 = time.perf_counter()
        buffers = None
        if self._wire_format == "framed":
            buffers = wire.encode_frame(
                self.rank, tag, payload, version=WIRE_FORMAT_VERSION
            )
        item = _OutMessage(self.rank, tag, payload, buffers)
        serialize_s = time.perf_counter() - t0
        return self._send_queue(dst).enqueue(item, serialize_s=serialize_s)

    def rx_phases(self) -> dict:
        """Snapshot of inbound phase seconds per ``"src:tag"`` stream
        (obs telemetry folds this into its summary)."""
        with self._rx_lock:
            return {
                f"{src}:{tag}": dict(v)
                for (src, tag), v in sorted(self._rx_phases.items())
            }

    def wire_byte_counts(self) -> dict:
        """Exact socket-level byte totals: {"tx", "rx", "rx_corrupt_dropped"}.
        Ground truth for the obs-summary == socket-bytes assertion."""
        with self._byte_lock:
            return {
                "tx": self._tx_wire_bytes,
                "rx": self._rx_wire_bytes,
                "rx_corrupt_dropped": self._rx_corrupt_dropped,
            }

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        msg = self._mailbox.get(0, src, tag, timeout)
        return Message(
            src=msg.src,
            dst=self.rank,
            tag=msg.tag,
            payload=msg.payload,
            wire_nbytes=msg.wire_nbytes,
        )

    def probe(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = 0,
    ) -> bool:
        if timeout == 0:
            return self._mailbox.peek(0, src, tag)
        return self._mailbox.peek_wait(0, src, tag, timeout)

    def close(self) -> None:
        self._closing.set()
        with self._out_cache_lock:
            queues = list(self._send_queues.values())
        for q in queues:
            q.shutdown()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_cache_lock:
            for sock in self._out.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._out.clear()


class _SendQueue:
    """One destination's outbound message queue + its sender thread.

    FIFO by construction (single drainer), which is what lets send() and
    isend() interleave without breaking MPI's per-(src, dst, tag) order
    guarantee. Write errors are parked on the message's SendHandle — a sync
    send() re-raises them from wait(); a fire-and-forget isend keeps them
    inspectable instead of crashing a daemon thread."""

    def __init__(self, transport: "SocketTransport", dst: int):
        self._transport = transport
        self._dst = dst
        self._cond = make_condition(f"socket._SendQueue.cond[{dst}]")
        # deque: the drainer pops from the front on every message — a list's
        # pop(0) is O(n) and melts under backlog (a slow peer + isend burst)
        # items are (msg, handle, enqueue perf_counter) — the timestamp
        # is what turns into the handle's queue_wait phase on dequeue
        self._items: collections.deque[tuple[_OutMessage, SendHandle, float]] = (
            collections.deque()
        )
        self._stopped = False
        self._thread = threading.Thread(
            target=self._drain,
            name=f"mpit-send-r{transport.rank}-d{dst}",
            daemon=True,
        )
        self._thread.start()

    def enqueue(self, item: _OutMessage, serialize_s: float = 0.0) -> SendHandle:
        h = SendHandle()
        h.phases = {"serialize": serialize_s}
        with self._cond:
            if self._stopped:
                h.set_error(ConnectionError("transport closed"))
                return h
            self._items.append((item, h, time.perf_counter()))
            self._cond.notify()
        return h

    def shutdown(self) -> None:
        with self._cond:
            self._stopped = True
            pending = self._items
            self._items = collections.deque()
            self._cond.notify()
        for _item, h, _enq_t in pending:
            h.set_error(ConnectionError("transport closed with send pending"))

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._items:
                    return
                item, h, enq_t = self._items.popleft()
            # queue_wait is the socket-wait phase a sync send() spends
            # behind earlier messages to the same dst; write is the payload
            # transfer into the kernel. Stamped BEFORE set_done so a
            # waiter observing done() always sees the full split.
            t_w = time.perf_counter()
            try:
                nbytes = self._transport._write_msg(self._dst, item)
            except BaseException as e:
                h.set_error(e)
            else:
                h.phases["queue_wait"] = t_w - enq_t
                h.phases["write"] = time.perf_counter() - t_w
                h.wire_nbytes = nbytes
                h.set_done()
