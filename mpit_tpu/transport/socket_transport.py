"""TCP transport: ranks are processes, tagged delivery over sockets.

The DCN-style control plane for the host-async PS mode across hosts (the
reference's multi-node MPI case, SURVEY.md §2 distributed-backend row). Data
parallel *gradient* traffic should ride XLA collectives over ICI — this
transport is for the PS protocol's small, latency-tolerant messages.

Wire format: 8-byte big-endian length + pickle (``WIRE_PICKLE_PROTOCOL``,
the canonical pin every wire writer must name — lint rule MPT007) of
(src, tag, payload). Each rank listens on one port; outbound connections are
cached per destination. A background acceptor/reader thread feeds a local
:class:`Broker` mailbox, so recv semantics (tags, ANY_SOURCE, per-(src,tag)
FIFO) are identical to :class:`InProcTransport`.

Reconnect semantics: TCP gives FIFO within one connection; across a sender
reconnect, a straggler frame from the old connection could otherwise be
enqueued *after* frames of the new one and break per-(src,tag) FIFO. The
receiver therefore orders connections by accept sequence and, once a frame
from a src arrives on a newer connection, drops late frames from that src's
older connections — order is preserved at the cost of dropping stragglers,
which matches MPI's model (a broken connection loses in-flight traffic; a
dead rank is fatal, SURVEY.md §5 failure-detection row) rather than silently
reordering. The fence is entirely receiver-side accept ordering, so a fully
*restarted* sender (fresh transport object) keeps working — its new
connection is by construction newer than any it had before.

Rendezvous: ``MPIT_TRANSPORT_HOSTS="host0:port0,host1:port1,..."`` (index =
rank), or ``addresses=`` in the constructor; defaults to
``127.0.0.1:(base_port+rank)`` for single-host multi-process runs.
"""

from __future__ import annotations

import collections
import errno
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence

from mpit_tpu.analysis.runtime import make_lock
from mpit_tpu.transport.base import (
    ANY_SOURCE,
    ANY_TAG,
    Message,
    SendHandle,
    Transport,
)
from mpit_tpu.transport.inproc import Broker

_LEN = struct.Struct(">Q")

# The wire's ONE pickle protocol. Readers auto-detect (the id is embedded
# in the stream), but every WRITER must pin this — an unpinned dumps rides
# the interpreter default, which moves across Python versions, and a
# mixed-version peer then sees unparseable frames on an otherwise healthy
# socket. Every dumps feeding a frame (here and in mpit_tpu/native) must
# name this constant; the MPT007 lint rule enforces exactly that.
WIRE_PICKLE_PROTOCOL = 5


def _addresses(size: int, base_port: int) -> list[tuple[str, int]]:
    env = os.environ.get("MPIT_TRANSPORT_HOSTS")
    if env:
        out = []
        for part in env.split(","):
            host, port = part.rsplit(":", 1)
            out.append((host, int(port)))
        if len(out) != size:
            raise ValueError(
                f"MPIT_TRANSPORT_HOSTS has {len(out)} entries, need {size}"
            )
        return out
    return [("127.0.0.1", base_port + r) for r in range(size)]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class SocketTransport(Transport):
    def __init__(
        self,
        rank: int,
        size: int,
        base_port: int = 29_500,
        addresses: Optional[Sequence[tuple[str, int]]] = None,
        connect_retry_s: float = 30.0,
    ):
        """``connect_retry_s``: window during which a refused outbound
        connection is retried — under a process launcher the peers come up
        at different times (mpirun gave the reference this for free)."""
        self.rank = rank
        self.size = size
        self.connect_retry_s = float(connect_retry_s)
        self._addrs = (
            list(addresses) if addresses is not None else _addresses(size, base_port)
        )
        # local mailbox reuses the broker's matching logic (1 "rank" = me)
        self._mailbox = Broker(1)
        # reconnect fencing: newest accept-ordered connection seq per src
        self._accept_seq = 0
        self._src_seq: dict[int, int] = {}
        self._src_seq_lock = make_lock("SocketTransport._src_seq_lock")
        self._out: dict[int, socket.socket] = {}
        self._out_cache_lock = make_lock(
            "SocketTransport._out_cache_lock"
        )  # guards the dict only
        # per-destination lock: a slow connect/send to one rank must not
        # serialize traffic to healthy ranks
        self._dst_locks: dict[int, Any] = {}
        # per-destination outbound queues drained by lazily-created sender
        # threads: isend returns immediately, and because send() rides the
        # same queue, send/isend to one dst stay FIFO (the MPI order rule)
        self._send_queues: dict[int, "_SendQueue"] = {}
        # inbound wire-phase accounting per (src, tag): body-transfer and
        # deserialize seconds (the header wait is idle between messages and
        # deliberately NOT counted). Harvested by obs telemetry summaries.
        self._rx_phases: dict[tuple[int, int], dict] = {}
        self._rx_lock = make_lock("SocketTransport._rx_lock")
        self._closing = threading.Event()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._listener.bind(self._addrs[rank])
        except OSError as e:
            raise OSError(
                f"rank {rank}: cannot bind {self._addrs[rank]} ({e}). "
                "If launched via mpit_tpu.launch, another process likely "
                "took the port between reservation and startup — relaunch."
            ) from e
        self._listener.listen(size)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # -- wire -------------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._src_seq_lock:
                self._accept_seq += 1
                seq = self._accept_seq
            threading.Thread(
                target=self._read_loop, args=(conn, seq), daemon=True
            ).start()

    def _read_loop(self, conn: socket.socket, seq: int):
        try:
            while not self._closing.is_set():
                # phase split: the header wait is inter-message idle (the
                # reader blocks here between frames) and is NOT a phase;
                # body streaming is payload-transfer, loads is deserialize
                (length,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                t_h = time.perf_counter()
                body = _recv_exact(conn, length)
                t_b = time.perf_counter()
                src, tag, payload = pickle.loads(body)
                t_d = time.perf_counter()
                with self._rx_lock:
                    d = self._rx_phases.get((src, tag))
                    if d is None:
                        d = self._rx_phases[(src, tag)] = {
                            "transfer": 0.0, "deserialize": 0.0, "msgs": 0,
                        }
                    d["transfer"] += t_b - t_h
                    d["deserialize"] += t_d - t_b
                    d["msgs"] += 1
                with self._src_seq_lock:
                    latest = self._src_seq.get(src, 0)
                    if seq < latest:
                        continue  # straggler from before src's reconnect
                    self._src_seq[src] = seq
                self._mailbox.put(
                    Message(src=src, dst=0, tag=tag, payload=payload)
                )
        except (ConnectionError, OSError):
            return

    def _dst_lock(self, dst: int):
        with self._out_cache_lock:
            lock = self._dst_locks.get(dst)
            if lock is None:
                lock = self._dst_locks[dst] = make_lock(
                    f"SocketTransport._dst_locks[{dst}]"
                )
            return lock

    def _connection(self, dst: int) -> socket.socket:
        """Cached outbound socket; caller must hold the dst lock."""
        with self._out_cache_lock:
            sock = self._out.get(dst)
        if sock is None:
            sock = self._connect_with_retry(dst)
            # back to blocking mode: a mid-frame timeout would desync the
            # length-prefixed stream for every later frame
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._out_cache_lock:
                self._out[dst] = sock
        return sock

    # transient connect failures retried within the window alongside a
    # clean refusal: real-DCN startup skew surfaces as timeouts and
    # unreachable-host/network errors while routes and peers come up,
    # not only as ECONNREFUSED
    _TRANSIENT_CONNECT_ERRNOS = frozenset(
        {errno.ETIMEDOUT, errno.EHOSTUNREACH, errno.ENETUNREACH}
    )

    def _connect_with_retry(self, dst: int) -> socket.socket:
        import time as _time

        deadline = _time.monotonic() + self.connect_retry_s
        while True:
            try:
                return socket.create_connection(self._addrs[dst], timeout=30)
            except OSError as e:
                transient = (
                    isinstance(e, (ConnectionRefusedError, TimeoutError))
                    or e.errno in self._TRANSIENT_CONNECT_ERRNOS
                )
                if (
                    not transient
                    or _time.monotonic() >= deadline
                    or self._closing.is_set()
                ):
                    raise
                _time.sleep(0.1)  # peer not reachable yet (startup skew)

    def _evict(self, dst: int) -> None:
        with self._out_cache_lock:
            sock = self._out.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _write_frame(self, dst: int, frame: bytes) -> None:
        with self._dst_lock(dst):
            try:
                self._connection(dst).sendall(frame)
            except (ConnectionError, OSError):
                # stale cached socket (peer restarted): reconnect once. The
                # receiver's accept-order fence drops any stragglers still in
                # flight on the old connection. Whole-frame retry is safe —
                # the reader discards a connection on any partial frame.
                self._evict(dst)
                self._connection(dst).sendall(frame)

    def _send_queue(self, dst: int) -> "_SendQueue":
        with self._out_cache_lock:
            q = self._send_queues.get(dst)
            if q is None:
                q = self._send_queues[dst] = _SendQueue(self, dst)
            return q

    # -- Transport API ----------------------------------------------------

    def send(self, dst: int, tag: int, payload: Any) -> None:
        self.isend(dst, tag, payload).wait()

    def isend(self, dst: int, tag: int, payload: Any) -> SendHandle:
        """Genuinely asynchronous: the frame (serialized NOW — the payload
        is captured at call time, per MPI buffer semantics) is handed to the
        dst's sender thread; the handle completes when it is written, with
        its ``phases`` split (serialize / queue_wait / write) stamped."""
        t0 = time.perf_counter()
        blob = pickle.dumps(
            (self.rank, tag, payload), protocol=WIRE_PICKLE_PROTOCOL
        )
        serialize_s = time.perf_counter() - t0
        frame = _LEN.pack(len(blob)) + blob
        return self._send_queue(dst).enqueue(frame, serialize_s=serialize_s)

    def rx_phases(self) -> dict:
        """Snapshot of inbound phase seconds per ``"src:tag"`` stream
        (obs telemetry folds this into its summary)."""
        with self._rx_lock:
            return {
                f"{src}:{tag}": dict(v)
                for (src, tag), v in sorted(self._rx_phases.items())
            }

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        msg = self._mailbox.get(0, src, tag, timeout)
        return Message(src=msg.src, dst=self.rank, tag=msg.tag, payload=msg.payload)

    def probe(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = 0,
    ) -> bool:
        if timeout == 0:
            return self._mailbox.peek(0, src, tag)
        return self._mailbox.peek_wait(0, src, tag, timeout)

    def close(self) -> None:
        self._closing.set()
        with self._out_cache_lock:
            queues = list(self._send_queues.values())
        for q in queues:
            q.shutdown()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_cache_lock:
            for sock in self._out.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._out.clear()


class _SendQueue:
    """One destination's outbound frame queue + its sender thread.

    FIFO by construction (single drainer), which is what lets send() and
    isend() interleave without breaking MPI's per-(src, dst, tag) order
    guarantee. Write errors are parked on the frame's SendHandle — a sync
    send() re-raises them from wait(); a fire-and-forget isend keeps them
    inspectable instead of crashing a daemon thread."""

    def __init__(self, transport: "SocketTransport", dst: int):
        self._transport = transport
        self._dst = dst
        self._cond = threading.Condition()
        # deque: the drainer pops from the front on every frame — a list's
        # pop(0) is O(n) and melts under backlog (a slow peer + isend burst)
        # items are (frame, handle, enqueue perf_counter) — the timestamp
        # is what turns into the handle's queue_wait phase on dequeue
        self._items: collections.deque[tuple[bytes, SendHandle, float]] = (
            collections.deque()
        )
        self._stopped = False
        self._thread = threading.Thread(
            target=self._drain,
            name=f"mpit-send-r{transport.rank}-d{dst}",
            daemon=True,
        )
        self._thread.start()

    def enqueue(self, frame: bytes, serialize_s: float = 0.0) -> SendHandle:
        h = SendHandle()
        h.phases = {"serialize": serialize_s}
        with self._cond:
            if self._stopped:
                h.set_error(ConnectionError("transport closed"))
                return h
            self._items.append((frame, h, time.perf_counter()))
            self._cond.notify()
        return h

    def shutdown(self) -> None:
        with self._cond:
            self._stopped = True
            pending = self._items
            self._items = collections.deque()
            self._cond.notify()
        for _frame, h, _enq_t in pending:
            h.set_error(ConnectionError("transport closed with send pending"))

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._items:
                    return
                frame, h, enq_t = self._items.popleft()
            # queue_wait is the socket-wait phase a sync send() spends
            # behind earlier frames to the same dst; write is the payload
            # transfer into the kernel. Stamped BEFORE set_done so a
            # waiter observing done() always sees the full split.
            t_w = time.perf_counter()
            try:
                self._transport._write_frame(self._dst, frame)
            except BaseException as e:
                h.set_error(e)
            else:
                h.phases["queue_wait"] = t_w - enq_t
                h.phases["write"] = time.perf_counter() - t_w
                h.set_done()
