"""Differential fuzz harness for the structural wire codec (stdlib PRNG).

Three properties, seeded and replay-stable (``python -m
mpit_tpu.analysis fuzz`` — lint gate 9):

1. **roundtrip**: every payload the structural grammar can produce
   encodes with :func:`~mpit_tpu.transport.wire.encode_frame` and
   decodes back bit-equal (floats compared by their IEEE bytes, so NaN
   payloads count as equal to themselves);
2. **differential**: the framed decode equals an independent
   pickle-roundtrip of the same ``(src, tag, payload)`` triple — the
   fast path and the fallback path must agree on every value either can
   carry;
3. **mutation**: corrupting a frame (preamble/header bit flips, CRC and
   length surgery, truncations, appends, future-version bumps) must
   land on :class:`~mpit_tpu.transport.wire.WireDecodeError` or decode
   to the *original* value (benign flips: an unused flag bit, a
   version LOWERING, swapping equal bytes) — never a different value, a
   crash, or a hang. Body *content* is deliberately never flipped: the
   CRC covers the header only (the body rides the TCP checksum, by
   documented design in ``wire.py``), so a body bit flip decoding to a
   different array is expected behavior, not a codec bug. Body
   *length* violations (truncate/append) are covered and must error.

The checked-in regression corpus (``tests/fixtures/wire_corpus/``)
freezes a sample of frames and mutations with their expected outcomes;
:func:`replay_corpus` re-verifies it deterministically so a codec
change that silently alters any verdict fails lint before it ships.

Everything is :mod:`random`-seeded stdlib — no hypothesis dependency on
the gate path (the optional property tests in ``tests/test_wire_fuzz.py``
use hypothesis only when it is installed).
"""

from __future__ import annotations

import dataclasses
import json
import pickle
import random
import struct
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from mpit_tpu.quant import QuantArray, quantize
from mpit_tpu.transport import wire
from mpit_tpu.transport.socket_transport import WIRE_PICKLE_PROTOCOL
from mpit_tpu.transport.wire import (
    PREAMBLE_SIZE,
    WIRE_FORMAT_VERSION,
    WireDecodeError,
)

#: dtypes the codec registers — the generator covers every one
_ARRAY_DTYPES = (
    np.float32,
    np.float64,
    np.int64,
    np.int32,
    np.int8,
    np.uint8,
    np.uint16,
    np.bool_,
    np.int16,
    np.uint32,
    np.uint64,
    np.float16,
)

#: preamble layout (">2sBBII"): magic 0:2, version 2, flags 3,
#: header-len 4:8, header-crc 8:12
_VERSION_OFF = 2
_HLEN_OFF = 4
_HCRC_OFF = 8
_U32 = struct.Struct(">I")


# ---------------------------------------------------------------------------
# framing helpers


def frame_bytes(src: int, tag: int, payload: Any) -> Optional[bytes]:
    """One contiguous wire frame, or None when the payload is not
    structural (the transport would pickle it)."""
    bufs = wire.encode_frame(
        src, tag, payload, version=WIRE_FORMAT_VERSION
    )
    if bufs is None:
        return None
    return b"".join(bytes(b) for b in bufs)


def decode_bytes(data: bytes) -> Tuple[int, int, Any]:
    """Decode one contiguous frame the way the transport does: split the
    preamble, slice the header, hand the rest over as the body. Any
    malformation raises :class:`WireDecodeError`."""
    if len(data) < PREAMBLE_SIZE:
        raise WireDecodeError("short preamble")
    version, flags, hlen, hcrc = wire.split_preamble(
        data[:PREAMBLE_SIZE]
    )
    header_end = PREAMBLE_SIZE + hlen
    if header_end > len(data):
        raise WireDecodeError("truncated header")
    header = data[PREAMBLE_SIZE:header_end]
    return wire.decode_frame(flags, hcrc, header, data[header_end:])


def deep_equal(a: Any, b: Any) -> bool:
    """Bit-exact structural equality: floats by their packed IEEE bytes
    (NaN equals NaN), arrays by dtype+shape+raw bytes, QuantArrays by
    mode + f32-packed scale (the wire stores f32; the pickle path keeps
    f64 — both pack to the same f32) + data."""
    if type(a) is not type(b):
        return False
    if isinstance(a, float):
        return struct.pack("!d", a) == struct.pack("!d", b)
    if isinstance(a, np.ndarray):
        return (
            a.dtype == b.dtype
            and a.shape == b.shape
            and a.tobytes() == b.tobytes()
        )
    if isinstance(a, QuantArray):
        return (
            a.mode == b.mode
            and struct.pack("!f", a.scale) == struct.pack("!f", b.scale)
            and deep_equal(a.data, b.data)
        )
    if isinstance(a, (tuple, list)):
        return len(a) == len(b) and all(
            deep_equal(x, y) for x, y in zip(a, b)
        )
    return a == b


# ---------------------------------------------------------------------------
# payload generation (seeded, stdlib random only)


def _gen_int(rng: random.Random) -> int:
    kind = rng.randrange(6)
    if kind == 0:
        return rng.randrange(-8, 64)
    if kind == 1:
        return rng.randrange(1 << 31, 1 << 32)
    if kind == 2:
        return (1 << 63) - rng.randrange(4)  # u64 boundary
    if kind == 3:
        return -(1 << 63) + rng.randrange(4)
    if kind == 4:
        return rng.getrandbits(100)  # wider than any machine word
    return -rng.getrandbits(80)


def _gen_float(rng: random.Random) -> float:
    return rng.choice(
        (
            0.0,
            -0.0,
            1.5,
            -2.25e300,
            float("inf"),
            float("-inf"),
            float("nan"),
            rng.random() * 1e6,
        )
    )


def _gen_str(rng: random.Random) -> str:
    out = []
    for _ in range(rng.randrange(12)):
        cp = rng.randrange(0x110000)
        if 0xD800 <= cp <= 0xDFFF:
            cp = 0x20  # lone surrogates don't utf-8 encode
        out.append(chr(cp))
    return "".join(out)


def _gen_array(rng: random.Random, max_elems: int = 32) -> np.ndarray:
    dtype = np.dtype(rng.choice(_ARRAY_DTYPES))
    ndim = rng.randrange(1, 4)
    shape = []
    elems = 1
    for _ in range(ndim):
        d = rng.randrange(0, 5)
        shape.append(d)
        elems *= d
    if elems > max_elems:
        shape = [rng.randrange(0, max_elems + 1)]
        elems = shape[0]
    raw = rng.randbytes(elems * dtype.itemsize)
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


def _gen_quant(rng: random.Random) -> QuantArray:
    n = rng.randrange(1, 17)
    # finite inputs only: int16 bytes widened to f32 (quantize of
    # NaN/inf would be numerically undefined, not a codec property)
    vals = np.frombuffer(rng.randbytes(2 * n), dtype=np.int16)
    return quantize(
        vals.astype(np.float32), rng.choice(("bf16", "int8"))
    )


def _gen_scalar(rng: random.Random) -> Any:
    kind = rng.randrange(8)
    if kind == 0:
        return None
    if kind == 1:
        return rng.choice((True, False))
    if kind == 2:
        return _gen_int(rng)
    if kind == 3:
        return _gen_float(rng)
    if kind == 4:
        return _gen_str(rng)
    if kind == 5:
        return rng.randbytes(rng.randrange(24))
    if kind == 6:
        return _gen_array(rng)
    return _gen_quant(rng)


def gen_payload(rng: random.Random, depth: int = 0) -> Any:
    """One payload from the structural grammar, weighted toward the
    protocol's real envelope shapes."""
    kind = rng.randrange(10)
    if kind < 4 or depth >= 2:
        return _gen_scalar(rng)
    if kind < 6:
        # the push/param envelope idiom: small int header + chunk
        chunk = _gen_quant(rng) if rng.randrange(2) else _gen_array(rng)
        n = rng.randrange(2, 5)
        return tuple(
            [rng.randrange(1 << 32) for _ in range(n - 1)] + [chunk]
        )
    if kind < 8:
        return tuple(
            gen_payload(rng, depth + 1)
            for _ in range(rng.randrange(0, 5))
        )
    return [_gen_scalar(rng) for _ in range(rng.randrange(0, 5))]


# ---------------------------------------------------------------------------
# mutations (preamble/header/length surgery — never body content: the
# CRC covers the header only, body bits ride the TCP checksum by design)


def _header_end(data: bytes) -> int:
    hlen = _U32.unpack_from(data, _HLEN_OFF)[0]
    return min(len(data), PREAMBLE_SIZE + hlen)


def _mut_truncate(data: bytes, rng: random.Random) -> bytes:
    return data[: rng.randrange(len(data))]


def _mut_append(data: bytes, rng: random.Random) -> bytes:
    return data + rng.randbytes(rng.randrange(1, 17))


def _mut_flip_preamble(data: bytes, rng: random.Random) -> bytes:
    i = rng.randrange(PREAMBLE_SIZE)
    out = bytearray(data)
    out[i] ^= 1 << rng.randrange(8)
    return bytes(out)


def _mut_flip_header(data: bytes, rng: random.Random) -> bytes:
    end = _header_end(data)
    if end <= PREAMBLE_SIZE:
        return _mut_flip_preamble(data, rng)  # headerless frame
    i = rng.randrange(PREAMBLE_SIZE, end)
    out = bytearray(data)
    out[i] ^= 1 << rng.randrange(8)
    return bytes(out)


def _mut_crc_xor(data: bytes, rng: random.Random) -> bytes:
    out = bytearray(data)
    out[_HCRC_OFF + rng.randrange(4)] ^= rng.randrange(1, 256)
    return bytes(out)


def _mut_version_bump(data: bytes, rng: random.Random) -> bytes:
    out = bytearray(data)
    out[_VERSION_OFF] = rng.randrange(WIRE_FORMAT_VERSION + 1, 256)
    return bytes(out)


def _mut_magic(data: bytes, rng: random.Random) -> bytes:
    out = bytearray(data)
    i = rng.randrange(2)
    out[i] = (out[i] + rng.randrange(1, 256)) % 256
    return bytes(out)


def _mut_hlen_tweak(data: bytes, rng: random.Random) -> bytes:
    hlen = _U32.unpack_from(data, _HLEN_OFF)[0]
    delta = rng.choice((-3, -2, -1, 1, 2, 3, 64, 4096))
    out = bytearray(data)
    _U32.pack_into(out, _HLEN_OFF, max(0, hlen + delta) & 0xFFFFFFFF)
    return bytes(out)


def _mut_swap(data: bytes, rng: random.Random) -> bytes:
    end = _header_end(data)
    if end < 2:
        return _mut_append(data, rng)
    i = rng.randrange(end)
    j = rng.randrange(end)
    out = bytearray(data)
    out[i], out[j] = out[j], out[i]
    return bytes(out)


MUTATIONS: List[Tuple[str, Callable]] = [
    ("truncate", _mut_truncate),
    ("append", _mut_append),
    ("flip_preamble", _mut_flip_preamble),
    ("flip_header", _mut_flip_header),
    ("crc_xor", _mut_crc_xor),
    ("version_bump", _mut_version_bump),
    ("magic", _mut_magic),
    ("hlen_tweak", _mut_hlen_tweak),
    ("swap", _mut_swap),
]


def classify_mutation(
    mutated: bytes, src: int, tag: int, payload: Any
) -> Tuple[str, str]:
    """("error"|"ok"|"wrong"|"crash", detail). The gate contract: a
    mutated frame must raise WireDecodeError or decode EXACTLY to the
    original triple (benign flips) — anything else is a codec bug."""
    try:
        msrc, mtag, mpayload = decode_bytes(mutated)
    except WireDecodeError:
        return "error", ""
    except Exception as e:  # an uncaught exception class IS the bug
        return "crash", repr(e)
    if msrc == src and mtag == tag and deep_equal(mpayload, payload):
        return "ok", ""
    return "wrong", (
        f"decoded ({msrc!r}, {mtag!r}, {type(mpayload).__name__}) "
        f"!= original ({src!r}, {tag!r}, {type(payload).__name__})"
    )


# ---------------------------------------------------------------------------
# the gate


@dataclasses.dataclass
class FuzzReport:
    seed: int = 0
    examples: int = 0
    roundtrip_ok: int = 0
    differential_ok: int = 0
    mutations_error: int = 0
    mutations_benign: int = 0
    corpus_clean: int = 0
    corpus_mutations: int = 0
    failures: List[str] = dataclasses.field(default_factory=list)

    def merge(self, other: "FuzzReport") -> None:
        self.examples += other.examples
        self.roundtrip_ok += other.roundtrip_ok
        self.differential_ok += other.differential_ok
        self.mutations_error += other.mutations_error
        self.mutations_benign += other.mutations_benign
        self.corpus_clean += other.corpus_clean
        self.corpus_mutations += other.corpus_mutations
        self.failures.extend(other.failures)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        status = "FAIL" if self.failures else "ok"
        return (
            f"fuzz gate {status}: {self.examples} example(s) "
            f"(seed {self.seed}): {self.roundtrip_ok} roundtrip, "
            f"{self.differential_ok} differential, "
            f"{self.mutations_error}+{self.mutations_benign} mutations "
            f"(error+benign), corpus {self.corpus_clean} clean / "
            f"{self.corpus_mutations} mutated, "
            f"{len(self.failures)} failure(s)"
        )


def run_fuzz(seed: int = 0, examples: int = 10000) -> FuzzReport:
    rng = random.Random(seed)
    report = FuzzReport(seed=seed, examples=examples)
    for i in range(examples):
        src = rng.randrange(64)
        tag = rng.randrange(1, 9)
        payload = gen_payload(rng)
        data = frame_bytes(src, tag, payload)
        if data is None:
            report.failures.append(
                f"example {i}: structural payload refused by "
                f"encode_frame ({type(payload).__name__})"
            )
            continue
        try:
            dsrc, dtag, dpayload = decode_bytes(data)
        except Exception as e:
            report.failures.append(
                f"example {i}: clean frame failed decode: {e!r}"
            )
            continue
        if not (
            dsrc == src and dtag == tag and deep_equal(dpayload, payload)
        ):
            report.failures.append(
                f"example {i}: roundtrip inequality "
                f"({type(payload).__name__})"
            )
            continue
        report.roundtrip_ok += 1
        blob = pickle.dumps(
            (src, tag, payload), protocol=WIRE_PICKLE_PROTOCOL
        )
        psrc, ptag, ppayload = pickle.loads(blob)
        if not (
            psrc == dsrc and ptag == dtag and deep_equal(dpayload, ppayload)
        ):
            report.failures.append(
                f"example {i}: framed decode != pickle decode "
                f"({type(payload).__name__})"
            )
            continue
        report.differential_ok += 1
        for _ in range(2):
            name, op = MUTATIONS[rng.randrange(len(MUTATIONS))]
            outcome, detail = classify_mutation(
                op(data, rng), src, tag, payload
            )
            if outcome == "error":
                report.mutations_error += 1
            elif outcome == "ok":
                report.mutations_benign += 1
            else:
                report.failures.append(
                    f"example {i}: mutation {name}: {outcome} {detail}"
                )
    return report


# ---------------------------------------------------------------------------
# regression corpus (checked in, replayed as lint gate 9)


def _corpus_payloads(rng: random.Random) -> List[Tuple[int, int, Any]]:
    """A fixed showcase of grammar corners plus generated envelopes."""
    fixed: List[Any] = [
        None,
        True,
        False,
        0,
        -1,
        (1 << 63) - 1,
        -(1 << 63),
        1 << 100,
        0.0,
        float("nan"),
        float("inf"),
        "",
        "päylöad ✓",
        b"",
        b"\x00\xffMW",
        (),
        (0, 1),
        [],
        [1, 2.5, "three", None],
        np.frombuffer(b"", dtype=np.float32),
        np.arange(6, dtype=np.int32).reshape(2, 3),
        quantize(np.arange(8, dtype=np.float32), "int8"),
        quantize(np.arange(8, dtype=np.float32) - 4.0, "bf16"),
        (7, 3, 1, np.ones(4, dtype=np.float32)),
    ]
    out = [
        (i % 8, 1 + i % 8, p) for i, p in enumerate(fixed)
    ]
    while len(out) < 40:
        out.append(
            (rng.randrange(8), rng.randrange(1, 9), gen_payload(rng))
        )
    return out


def build_corpus(seed: int = 0) -> List[dict]:
    rng = random.Random(seed)
    entries: List[dict] = []
    for p_i, (src, tag, payload) in enumerate(_corpus_payloads(rng)):
        data = frame_bytes(src, tag, payload)
        if data is None:
            raise AssertionError(
                f"corpus payload {p_i} is not structural"
            )
        blob = pickle.dumps(
            (src, tag, payload), protocol=WIRE_PICKLE_PROTOCOL
        )
        entries.append(
            {
                "id": f"clean-{p_i:03d}",
                "kind": "clean",
                "op": "",
                "frame": data.hex(),
                "expect": "ok",
                "pickle": blob.hex(),
            }
        )
        for name, op in MUTATIONS:
            mutated = op(data, rng)
            outcome, detail = classify_mutation(
                mutated, src, tag, payload
            )
            if outcome not in ("error", "ok"):
                raise AssertionError(
                    f"corpus payload {p_i} mutation {name}: {outcome} "
                    f"{detail}"
                )
            entries.append(
                {
                    "id": f"mut-{p_i:03d}-{name}",
                    "kind": "mutation",
                    "op": name,
                    "frame": mutated.hex(),
                    "expect": outcome,
                    "pickle": blob.hex(),
                }
            )
    return entries


def write_corpus(path, seed: int = 0) -> int:
    entries = build_corpus(seed=seed)
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e, sort_keys=True) + "\n")
    return len(entries)


def replay_corpus(path) -> FuzzReport:
    """Re-verify every checked-in frame against its recorded verdict.
    Any difference — a clean frame decoding differently, a mutation
    whose outcome changed in EITHER direction — is a failure: codec
    changes must regenerate the corpus consciously."""
    report = FuzzReport()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            e = json.loads(line)
            data = bytes.fromhex(e["frame"])
            src, tag, payload = pickle.loads(bytes.fromhex(e["pickle"]))
            if e["kind"] == "clean":
                try:
                    dsrc, dtag, dpayload = decode_bytes(data)
                except Exception as exc:
                    report.failures.append(
                        f"corpus {e['id']}: clean frame failed decode: "
                        f"{exc!r}"
                    )
                    continue
                if not (
                    dsrc == src
                    and dtag == tag
                    and deep_equal(dpayload, payload)
                ):
                    report.failures.append(
                        f"corpus {e['id']}: clean frame no longer "
                        "decodes to its recorded value"
                    )
                    continue
                report.corpus_clean += 1
            else:
                outcome, detail = classify_mutation(
                    data, src, tag, payload
                )
                if outcome != e["expect"]:
                    report.failures.append(
                        f"corpus {e['id']} ({e['op']}): expected "
                        f"{e['expect']}, got {outcome} {detail}"
                    )
                    continue
                report.corpus_mutations += 1
    return report
