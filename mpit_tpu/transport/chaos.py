"""ChaosTransport — seeded, deterministic fault injection over any Transport.

The permanent test substrate for the PS protocol's failure model
(docs/ROBUSTNESS.md): wrap any :class:`Transport` (inproc / socket /
native — anything with the send/recv surface) and every *send* is run
through a fault schedule derived purely from ``(seed, src, dst, tag, n)``
where ``n`` is the per-(dst, tag) message index on that stream. No
wall-clock, no global ``random`` state: the same seed replays the same
fault decisions byte-for-byte, which is what lets a failing chaos run be
re-run under a debugger with the identical schedule (the
``tests/test_chaos.py`` determinism pin).

Fault kinds (all sender-side — the receiver's mailbox semantics stay
untouched, so per-(src, tag) FIFO of *delivered* messages is preserved):

- ``drop``       message silently not delivered (lossy link)
- ``duplicate``  message delivered twice back-to-back (retransmit storm)
- ``delay``      blocking sleep before delivery (congested link; in-order)
- ``reset``      the send raises ``ConnectionError`` (peer RST — a
                 *visible* fault the caller's retry path must absorb)
- ``blackhole``  this and the next ``blackhole_len - 1`` messages on the
                 stream vanish silently (grey failure / dead NIC burst)
- ``jitter``     constant extra latency on every send from a slow rank
- ``kill_after`` rank goes silent after its N-th sent message (a dead
                 host doesn't fail cleanly; it just stops talking)
- ``corrupt``    the frame arrives but its payload is garbage — delivered
                 as a :class:`CorruptedPayload` marker (bit-rot / bad
                 deserialization). Receivers must drop it and let the
                 sender's retry/timeout machinery absorb the loss.
- ``truncate``   the frame is cut mid-stream: every array in the payload
                 arrives at half length (envelope scalars survive — a
                 length-prefixed read that stopped early). Payloads with
                 nothing array-like to cut degrade to ``CorruptedPayload``.

Determinism scope: per-stream decisions are always seed-determined. The
*total order* of the fault log is deterministic whenever each (dst, tag)
stream is fed from one thread (the log is sorted by stream, not by
wall-clock); ``kill_after`` counts sends across all streams of one rank,
so its trigger point is only reproducible for single-threaded senders
(e.g. heartbeats off).

Env knobs (read by :func:`config_from_env`; any set knob activates chaos):

  MPIT_CHAOS_SEED          int     schedule seed            (default 0)
  MPIT_CHAOS_DROP          float   P(drop)                  (default 0)
  MPIT_CHAOS_DUP           float   P(duplicate)             (default 0)
  MPIT_CHAOS_DELAY         float   P(delay)                 (default 0)
  MPIT_CHAOS_DELAY_S       float   max delay seconds        (default 0.01)
  MPIT_CHAOS_RESET         float   P(connection reset)      (default 0)
  MPIT_CHAOS_BLACKHOLE     float   P(blackhole burst start) (default 0)
  MPIT_CHAOS_BLACKHOLE_LEN int     burst length in messages (default 8)
  MPIT_CHAOS_JITTER_S      float   slow-rank extra latency  (default 0)
  MPIT_CHAOS_SLOW_RANKS    csv     ranks the jitter applies to
  MPIT_CHAOS_KILL_RANK     int     rank to kill
  MPIT_CHAOS_KILL_AFTER    int     ...after this many sent messages
  MPIT_CHAOS_CORRUPT       float   P(payload corruption)     (default 0)
  MPIT_CHAOS_TRUNCATE      float   P(frame truncation)       (default 0)
  MPIT_CHAOS_TAGS          csv     restrict faults to these tags (all)
  MPIT_CHAOS_<K>_TAGS      csv     narrow one kind further; K in DROP,
                                   DUP, DELAY, RESET, BLACKHOLE,
                                   CORRUPT, TRUNCATE
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Iterable, Mapping, Optional, Sequence

import numpy as np

from mpit_tpu.analysis.runtime import make_lock
from mpit_tpu.transport.base import Transport
from mpit_tpu.transport.wire import QuantArray

_MASK = (1 << 64) - 1


def _mix(*values: int) -> int:
    """Order-sensitive integer hash combine (boost-style), fully
    deterministic across runs and Python versions — ``hash()`` of str is
    randomized per process and tuples can't seed ``random.Random``."""
    h = 0x243F6A8885A308D3  # pi, nothing up the sleeve
    for v in values:
        v &= _MASK
        h ^= (v + 0x9E3779B97F4A7C15 + ((h << 6) & _MASK) + (h >> 2)) & _MASK
        h &= _MASK
    return h


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One logged fault decision (``n`` = per-(dst, tag) stream index)."""

    kind: str
    src: int
    dst: int
    tag: int
    n: int


@dataclasses.dataclass(frozen=True)
class CorruptedPayload:
    """What a ``corrupt`` fault delivers in place of the real payload (and
    what ``truncate`` degrades to when the payload has nothing array-like
    to cut): the frame-layer model of an unparseable frame. Receivers must
    treat it like garbage off the wire — drop the message and let the
    sender's retry/timeout path absorb the loss (docs/ROBUSTNESS.md);
    ``np.asarray`` on it raises, so an unhardened apply path fails loudly
    rather than silently training on junk. Carries its stream coordinates
    for debuggability only — protocol code must not dispatch on them."""

    src: int = -1
    dst: int = -1
    tag: int = -1
    n: int = -1


def _truncate_payload(payload: Any) -> Optional[Any]:
    """Payload with every ndarray cut to half length (a length-prefixed
    frame whose stream ended early: envelope scalars — epoch/seq/trace
    ids — decoded before the cut survive, the bulk array data did not).
    Returns None when nothing was truncatable (caller degrades to
    :class:`CorruptedPayload` — a cut tiny frame is just unparseable)."""
    if isinstance(payload, np.ndarray):
        if payload.ndim >= 1 and payload.shape[0] > 1:
            return payload[: payload.shape[0] // 2]
        return None
    if isinstance(payload, (tuple, list)):
        out, cut = [], False
        for item in payload:
            t = _truncate_payload(item)
            out.append(item if t is None else t)
            cut = cut or t is not None
        return type(payload)(out) if cut else None
    # quantized chunks carry their bulk bytes in .data — cut those, same
    # early-stream-end model as a raw ndarray (no extra RNG draws: the
    # fault schedule for old seeds is unchanged)
    if isinstance(payload, QuantArray):
        t = _truncate_payload(payload.data)
        if t is None:
            return None
        return dataclasses.replace(payload, data=t)
    return None


class FaultLog:
    """Thread-safe fault event collector, shared by a world's wrappers.

    ``events()`` returns the log sorted by (src, dst, tag, n): a total
    order derived from stream coordinates, not arrival time, so two runs
    of the same seed compare equal even when thread scheduling differs.
    """

    def __init__(self):
        self._lock = make_lock("chaos.FaultLog._lock")
        self._events: list[FaultEvent] = []

    def append(self, event: FaultEvent) -> None:
        with self._lock:
            self._events.append(event)

    def events(self) -> tuple[FaultEvent, ...]:
        with self._lock:
            return tuple(
                sorted(
                    self._events,
                    key=lambda e: (e.src, e.dst, e.tag, e.n, e.kind),
                )
            )

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.events():
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Fault schedule parameters. Frozen: one config is shared, lock-free,
    by every wrapper in the world; all mutable state lives per-transport.

    ``scripted`` pins exact faults for regression tests: a mapping from
    ``(src, dst, tag, n)`` to a fault kind (``"drop" | "duplicate" |
    "reset" | "corrupt" | "truncate"``) applied to exactly that message,
    ahead of any probability
    draw. ``tags``/``edges`` restrict the *probabilistic* faults (scripted
    entries already name their target precisely); the per-fault
    ``<kind>_tags`` fields narrow one fault kind further (None = inherit
    ``tags``) — e.g. drop only the retryable FETCH/PARAM path while
    duplicates and resets exercise the push dedup."""

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_s: float = 0.01
    reset: float = 0.0
    blackhole: float = 0.0
    blackhole_len: int = 8
    jitter_s: float = 0.0
    slow_ranks: tuple[int, ...] = ()
    kill_after: Mapping[int, int] = dataclasses.field(default_factory=dict)
    corrupt: float = 0.0
    truncate: float = 0.0
    tags: Optional[tuple[int, ...]] = None
    drop_tags: Optional[tuple[int, ...]] = None
    duplicate_tags: Optional[tuple[int, ...]] = None
    delay_tags: Optional[tuple[int, ...]] = None
    reset_tags: Optional[tuple[int, ...]] = None
    blackhole_tags: Optional[tuple[int, ...]] = None
    corrupt_tags: Optional[tuple[int, ...]] = None
    truncate_tags: Optional[tuple[int, ...]] = None
    edges: Optional[tuple[tuple[int, int], ...]] = None
    scripted: Mapping[tuple[int, int, int, int], str] = dataclasses.field(
        default_factory=dict
    )

    _KINDS = ("drop", "duplicate", "delay", "reset", "blackhole",
              "corrupt", "truncate")

    def __post_init__(self):
        for name in self._KINDS:
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be a probability, got {p}")
        if self.blackhole_len < 1:
            raise ValueError("blackhole_len must be >= 1")
        for key, kind in self.scripted.items():
            if kind not in ("drop", "duplicate", "reset", "corrupt",
                            "truncate"):
                raise ValueError(
                    f"scripted[{key}]: unknown fault kind {kind!r}"
                )
        if self.tags is not None:
            for name in self._KINDS:
                per = getattr(self, f"{name}_tags")
                if per is not None and not set(per) <= set(self.tags):
                    raise ValueError(
                        f"{name}_tags {per} must be a subset of tags "
                        f"{self.tags} (a tag outside `tags` never draws)"
                    )

    def applies(self, src: int, dst: int, tag: int) -> bool:
        """Do the *probabilistic* faults cover this message?"""
        if self.tags is not None and tag not in self.tags:
            return False
        if self.edges is not None and (src, dst) not in self.edges:
            return False
        return True

    def allows(self, kind: str, tag: int) -> bool:
        """Does fault ``kind`` cover ``tag``? (``applies`` already passed.)

        Gating is applied AFTER the probability draws, never instead of
        them: narrowing one kind's tags must not shift the other kinds'
        random streams, or per-kind filters would break seed replay."""
        per = getattr(self, f"{kind}_tags")
        return per is None or tag in per


_ENV_KNOBS = frozenset(
    "MPIT_CHAOS_" + k
    for k in (
        "SEED", "DROP", "DUP", "DELAY", "DELAY_S", "RESET", "BLACKHOLE",
        "BLACKHOLE_LEN", "JITTER_S", "SLOW_RANKS", "KILL_RANK",
        "KILL_AFTER", "CORRUPT", "TRUNCATE", "TAGS", "DROP_TAGS",
        "DUP_TAGS", "DELAY_TAGS", "RESET_TAGS", "BLACKHOLE_TAGS",
        "CORRUPT_TAGS", "TRUNCATE_TAGS",
    )
)


def config_from_env(env: Mapping[str, str] = os.environ) -> Optional[ChaosConfig]:
    """Build a config from ``MPIT_CHAOS_*`` knobs; None when none are set
    (chaos must never activate implicitly — only the RECOGNIZED knobs
    count, so e.g. the soak script's ``MPIT_CHAOS_SOAK_OFFSET`` doesn't
    arm an empty schedule)."""
    if not any(k in _ENV_KNOBS for k in env):
        return None

    def _f(name: str, default: float) -> float:
        return float(env.get(name, default))

    def _csv_ints(name: str) -> Optional[tuple[int, ...]]:
        raw = env.get(name)
        if raw is None or not raw.strip():
            return None
        return tuple(int(p) for p in raw.split(",") if p.strip())

    kill_after: dict[int, int] = {}
    if "MPIT_CHAOS_KILL_RANK" in env:
        kill_after[int(env["MPIT_CHAOS_KILL_RANK"])] = int(
            env.get("MPIT_CHAOS_KILL_AFTER", 0)
        )
    return ChaosConfig(
        seed=int(env.get("MPIT_CHAOS_SEED", 0)),
        drop=_f("MPIT_CHAOS_DROP", 0.0),
        duplicate=_f("MPIT_CHAOS_DUP", 0.0),
        delay=_f("MPIT_CHAOS_DELAY", 0.0),
        delay_s=_f("MPIT_CHAOS_DELAY_S", 0.01),
        reset=_f("MPIT_CHAOS_RESET", 0.0),
        blackhole=_f("MPIT_CHAOS_BLACKHOLE", 0.0),
        blackhole_len=int(env.get("MPIT_CHAOS_BLACKHOLE_LEN", 8)),
        jitter_s=_f("MPIT_CHAOS_JITTER_S", 0.0),
        slow_ranks=_csv_ints("MPIT_CHAOS_SLOW_RANKS") or (),
        kill_after=kill_after,
        corrupt=_f("MPIT_CHAOS_CORRUPT", 0.0),
        truncate=_f("MPIT_CHAOS_TRUNCATE", 0.0),
        tags=_csv_ints("MPIT_CHAOS_TAGS"),
        drop_tags=_csv_ints("MPIT_CHAOS_DROP_TAGS"),
        duplicate_tags=_csv_ints("MPIT_CHAOS_DUP_TAGS"),
        delay_tags=_csv_ints("MPIT_CHAOS_DELAY_TAGS"),
        reset_tags=_csv_ints("MPIT_CHAOS_RESET_TAGS"),
        blackhole_tags=_csv_ints("MPIT_CHAOS_BLACKHOLE_TAGS"),
        corrupt_tags=_csv_ints("MPIT_CHAOS_CORRUPT_TAGS"),
        truncate_tags=_csv_ints("MPIT_CHAOS_TRUNCATE_TAGS"),
    )


class ChaosTransport(Transport):
    """Fault-injecting wrapper: chaos on the send path, passthrough recv.

    The wrapped rank keeps its identity (``rank``/``size``); ``rng`` per
    message is derived from the stream coordinates, never shared or
    advanced across messages — see the module docstring's determinism
    contract. Inherited :meth:`Transport.isend` routes through
    :meth:`send`, so async sends see the same schedule.
    """

    def __init__(
        self,
        inner: Transport,
        config: ChaosConfig,
        log: Optional[FaultLog] = None,
    ):
        self.inner = inner
        self.rank = inner.rank
        self.size = inner.size
        self.config = config
        self.log = log if log is not None else FaultLog()
        self._lock = make_lock(f"chaos.ChaosTransport._lock[{inner.rank}]")
        self._stream_n: dict[tuple[int, int], int] = {}
        self._blackhole_until: dict[tuple[int, int], int] = {}
        self._sent_total = 0

    # -- schedule ---------------------------------------------------------

    def _next(self, dst: int, tag: int) -> tuple[int, int]:
        with self._lock:
            n = self._stream_n.get((dst, tag), 0)
            self._stream_n[(dst, tag)] = n + 1
            self._sent_total += 1
            return n, self._sent_total

    def _record(self, kind: str, dst: int, tag: int, n: int) -> None:
        self.log.append(FaultEvent(kind, self.rank, dst, tag, n))

    def send(self, dst: int, tag: int, payload: Any) -> None:
        cfg = self.config
        n, total = self._next(dst, tag)

        limit = cfg.kill_after.get(self.rank)
        if limit is not None and total > limit:
            # dead rank: silence, not an error — the layers above must
            # detect this via timeouts/watchdog, not a clean exception
            self._record("kill", dst, tag, n)
            return

        scripted = cfg.scripted.get((self.rank, dst, tag, n))
        if scripted == "drop":
            self._record("drop", dst, tag, n)
            return
        if scripted == "reset":
            self._record("reset", dst, tag, n)
            raise ConnectionError(
                f"chaos: scripted connection reset on "
                f"{self.rank}->{dst} tag {tag} msg {n}"
            )

        deliveries = 2 if scripted == "duplicate" else 1
        if scripted == "duplicate":
            self._record("duplicate", dst, tag, n)

        wire = payload  # what actually goes down; mangled by corrupt/truncate
        if scripted == "corrupt":
            self._record("corrupt", dst, tag, n)
            wire = CorruptedPayload(self.rank, dst, tag, n)
        elif scripted == "truncate":
            self._record("truncate", dst, tag, n)
            cut = _truncate_payload(payload)
            wire = (
                cut if cut is not None
                else CorruptedPayload(self.rank, dst, tag, n)
            )

        if cfg.applies(self.rank, dst, tag) and scripted is None:
            rng = random.Random(_mix(cfg.seed, self.rank, dst, tag, n))
            # fixed draw order — the replay contract; new kinds append
            # their draws at the END so old seeds replay old schedules
            r_drop = rng.random()
            r_dup = rng.random()
            r_delay = rng.random()
            delay_amount = rng.random() * cfg.delay_s
            r_reset = rng.random()
            r_black = rng.random()
            r_corrupt = rng.random()
            r_trunc = rng.random()

            with self._lock:
                in_hole = n < self._blackhole_until.get((dst, tag), 0)
                if (
                    not in_hole
                    and r_black < cfg.blackhole
                    and cfg.allows("blackhole", tag)
                ):
                    self._blackhole_until[(dst, tag)] = n + cfg.blackhole_len
                    in_hole = True
            if in_hole:
                self._record("blackhole", dst, tag, n)
                return
            if r_reset < cfg.reset and cfg.allows("reset", tag):
                self._record("reset", dst, tag, n)
                raise ConnectionError(
                    f"chaos: connection reset on {self.rank}->{dst} "
                    f"tag {tag} msg {n}"
                )
            if r_drop < cfg.drop and cfg.allows("drop", tag):
                self._record("drop", dst, tag, n)
                return
            # at most one mangle per message (elif): a frame is either
            # corrupted whole or cut short, and the draws above already
            # happened so the elif can't shift anyone's random stream
            if r_corrupt < cfg.corrupt and cfg.allows("corrupt", tag):
                self._record("corrupt", dst, tag, n)
                wire = CorruptedPayload(self.rank, dst, tag, n)
            elif r_trunc < cfg.truncate and cfg.allows("truncate", tag):
                self._record("truncate", dst, tag, n)
                cut = _truncate_payload(payload)
                wire = (
                    cut if cut is not None
                    else CorruptedPayload(self.rank, dst, tag, n)
                )
            if cfg.jitter_s > 0 and self.rank in cfg.slow_ranks:
                self._record("jitter", dst, tag, n)
                time.sleep(cfg.jitter_s)
            if r_delay < cfg.delay and cfg.allows("delay", tag):
                self._record("delay", dst, tag, n)
                time.sleep(delay_amount)
            if r_dup < cfg.duplicate and cfg.allows("duplicate", tag):
                self._record("duplicate", dst, tag, n)
                deliveries = 2

        for _ in range(deliveries):
            self.inner.send(dst, tag, wire)

    # -- passthrough ------------------------------------------------------

    def recv(self, src=-1, tag=-1, timeout=None):
        return self.inner.recv(src, tag, timeout)

    def probe(self, src=-1, tag=-1, timeout=0):
        return self.inner.probe(src, tag, timeout)

    def close(self) -> None:
        self.inner.close()


def wrap_transports(
    transports: Sequence[Transport],
    config: ChaosConfig,
    log: Optional[FaultLog] = None,
) -> tuple[list[ChaosTransport], FaultLog]:
    """Wrap a whole world's transports around one shared fault log."""
    log = log if log is not None else FaultLog()
    return [ChaosTransport(t, config, log) for t in transports], log


def iter_fault_lines(events: Iterable[FaultEvent]) -> Iterable[str]:
    """Stable text rendering of a fault log (soak-script output format)."""
    for e in events:
        yield f"{e.kind} {e.src}->{e.dst} tag={e.tag} n={e.n}"
