"""In-process transport: ranks are threads, delivery via a shared broker.

The TPU replacement for single-host ``mpirun -n N``: the reference simulated
a cluster with N co-located MPI processes (SURVEY.md §4); here N actors are
threads around one (or a few) accelerators, and the broker provides MPI-like
tagged mailboxes. Python threads are fine for this: clients spend their time
inside jit-compiled XLA computations (GIL released), and the protocol
messages are small.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Optional

from mpit_tpu.analysis import runtime as _rt
from mpit_tpu.transport.base import (
    ANY_SOURCE,
    ANY_TAG,
    Message,
    RecvTimeout,
    Transport,
)


class Broker:
    """Shared mailbox set for ``size`` ranks with MPI-like matching."""

    def __init__(self, size: int):
        self.size = size
        self._queues = [collections.deque() for _ in range(size)]
        self._conds = [
            _rt.make_condition(f"Broker.cond[{i}]") for i in range(size)
        ]

    def _note(self, dst: int) -> None:
        """RT103 annotation: every mailbox mutation is stamped into the
        vector-clock sanitizer when one is armed (no-op otherwise)."""
        _rt.note(f"Broker#{id(self)}.q{dst}", True)

    def put(self, msg: Message) -> None:
        if not 0 <= msg.dst < self.size:
            raise ValueError(f"dst {msg.dst} out of range (size {self.size})")
        cond = self._conds[msg.dst]
        with cond:
            self._note(msg.dst)
            self._queues[msg.dst].append(msg)
            cond.notify_all()

    def get(
        self,
        dst: int,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        cond = self._conds[dst]
        deadline = None if timeout is None else time.monotonic() + timeout
        # RT102 instrumentation: register this recv as a waiter so the
        # runtime checker can flag two protocol roles racing for one tag
        checker = _rt.active_checker()
        token = (
            checker.on_recv_enter(self, dst, src, tag)
            if checker is not None
            else None
        )
        try:
            with cond:
                while True:
                    q = self._queues[dst]
                    # scan in arrival order: preserves per-(src,tag) FIFO,
                    # and gives ANY_SOURCE the MPI arrival-order semantics
                    for i, msg in enumerate(q):
                        if msg.matches(src, tag):
                            self._note(dst)
                            del q[i]
                            return msg
                    if deadline is None:
                        cond.wait()
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or not cond.wait(remaining):
                            raise RecvTimeout(
                                f"rank {dst}: no message from src={src} "
                                f"tag={tag} within {timeout}s"
                            )
        finally:
            if token is not None:
                checker.on_recv_exit(token)

    def peek(self, dst: int, src: int = ANY_SOURCE, tag: int = ANY_TAG) -> bool:
        with self._conds[dst]:
            return any(m.matches(src, tag) for m in self._queues[dst])

    def peek_wait(
        self,
        dst: int,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> bool:
        """Blocking peek: wait (up to ``timeout``; None = forever) for a
        matching message WITHOUT consuming it. False on expiry."""
        cond = self._conds[dst]
        deadline = None if timeout is None else time.monotonic() + timeout
        with cond:
            while True:
                if any(m.matches(src, tag) for m in self._queues[dst]):
                    return True
                if deadline is None:
                    cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not cond.wait(remaining):
                        return False

    def transports(self) -> list["InProcTransport"]:
        return [InProcTransport(self, r) for r in range(self.size)]


class InProcTransport(Transport):
    def __init__(self, broker: Broker, rank: int):
        self.broker = broker
        self.rank = rank
        self.size = broker.size

    def send(self, dst: int, tag: int, payload: Any) -> None:
        self.broker.put(Message(src=self.rank, dst=dst, tag=tag, payload=payload))

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        return self.broker.get(self.rank, src, tag, timeout)

    def probe(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = 0,
    ) -> bool:
        if timeout == 0:
            return self.broker.peek(self.rank, src, tag)
        return self.broker.peek_wait(self.rank, src, tag, timeout)
