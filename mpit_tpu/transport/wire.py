"""wire — versioned zero-copy binary framing for the PS hot path.

The socket transport's historical wire format is ``length +
pickle.dumps((src, tag, payload))``: correct, but every FETCH/PARAM/push
envelope pays a full serialize on send and a full ``pickle.loads`` copy on
recv — exactly the serialize/deserialize seconds the roofline split
(docs/OBSERVABILITY.md) was built to expose. This module is the
replacement codec:

``frame body`` (what follows the transport's 8-byte length prefix)::

    magic "MW"  (2)   — cannot collide with pickle: a protocol>=2 pickle
                        stream always starts with 0x80
    version     (1)   — WIRE_FORMAT_VERSION; readers reject newer frames
    flags       (1)   — bit0: body byte order (1 = little-endian host)
    header_len  (4be)
    header_crc  (4be) — crc32 over the structural header ONLY (body
                        integrity rides the TCP checksum, the same trust
                        the pickle format extended)
    header      (header_len bytes, structural encoding below)
    body        (raw ndarray buffers, concatenated in header order)

The *structural header* is a tiny recursive type-code encoding of
``(src, tag, payload)`` covering everything the PS protocol and the obs
trace envelope actually send — None/bool/int/float/str/bytes/tuple/list,
plus two array kinds whose bulk data lives in the body at implicit
running offsets: raw ndarrays and :class:`QuantArray`. Ints are
length-prefixed big-endian magnitudes because client epochs are drawn
from ``os.urandom(8)`` and may exceed a signed 64-bit slot. Anything the
codec does not know (e.g. a chaos :class:`CorruptedPayload` marker)
makes :func:`encode_frame` return ``None`` and the caller falls back to
the pickle format for that message — receivers detect the format per
frame by the magic bytes, so pickle and framed messages interleave
freely on one connection.

Zero-copy contract: :func:`encode_frame` returns the header bytes plus
*memoryviews over the caller's arrays* — nothing is copied on the send
side, so the caller must not mutate those arrays until the frame is
written (the PS protocol replaces its flat vectors instead of mutating
them, and the socket transport's sync ``send`` blocks until the write
completes). On receive the socket reads the body straight into one
exactly-sized buffer (``recv_into``) and :func:`decode_frame` hands back
``np.frombuffer`` views into it — one allocation per message, zero
copies.

Version negotiation (docs/WIRE.md): a framed-capable *receiver* writes
:func:`encode_hello` on every accepted connection; the sender waits
briefly for it after connecting and falls back to pickle-only when no
hello arrives (a pickle-only peer never sends one, and a pickle-only
sender never reads its outbound socket, so the unsolicited hello is
harmless). Every frame writer must pin ``version=WIRE_FORMAT_VERSION``
by name — the MPT007 lint rule enforces it, same contract as the pickle
protocol pin.

Quantization (``MPIT_WIRE_QUANT={off,bf16,int8}``): :func:`quantize`
packs a float32 chunk into a :class:`QuantArray` (bf16 = round-to-
nearest-even high halves; int8 = symmetric per-chunk absmax scaling,
scale carried in the frame header as an f32). The PS client carries the
quantization residual into its next push (error feedback — see
docs/WIRE.md for the math), so the *accumulated* center drift stays
bounded while wire bytes drop ~2x (bf16) / ~4x (int8). The kernels
themselves live in :mod:`mpit_tpu.quant` (re-exported here so existing
imports keep working) — the quantized-collective path shares them, and
the host/device bit-equivalence contract is documented there.
"""

from __future__ import annotations

import os
import struct
import sys
import zlib
from typing import Any, Optional

import numpy as np

from mpit_tpu.quant import (  # noqa: F401  (re-exports: wire API surface)
    QUANT_MODES,
    QuantArray,
    dequantize,
    quantize,
)

# The wire format's ONE version number. Readers accept any frame at or
# below their own version; every frame WRITER must pin this constant by
# name in its encode_frame call — a literal would be silently stranded
# by a future bump (the MPT007 lint rule enforces the pin, exactly as it
# does for WIRE_PICKLE_PROTOCOL on the pickle path).
WIRE_FORMAT_VERSION = 1

MAGIC = b"MW"
_PREAMBLE = struct.Struct(">2sBBII")  # magic, version, flags, hlen, hcrc
PREAMBLE_SIZE = _PREAMBLE.size
_FLAG_LITTLE_ENDIAN = 0x01

_HELLO = struct.Struct(">2ssB")  # magic, "H", advertised version
HELLO_SIZE = _HELLO.size

_U32 = struct.Struct(">I")
_F64 = struct.Struct(">d")
_F32 = struct.Struct(">f")

# structural type codes
_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_TUPLE = 0x07
_T_LIST = 0x08
_T_NDARRAY = 0x09
_T_QUANT = 0x0A

# fixed dtype registry — codes are part of the wire format; append only
_DTYPE_CODES = {
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.uint8): 6,
    np.dtype(np.uint16): 7,
    np.dtype(np.bool_): 8,
    np.dtype(np.int16): 9,
    np.dtype(np.uint32): 10,
    np.dtype(np.uint64): 11,
    np.dtype(np.float16): 12,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}

_QUANT_MODE_CODES = {"bf16": 1, "int8": 2}
_CODE_QUANT_MODES = {v: k for k, v in _QUANT_MODE_CODES.items()}

_MAX_DIMS = 16
# header sanity bound: the structural part of a PS message is tiny (tens
# of bytes); a multi-megabyte header length is a corrupted preamble, not
# a real message — reject before allocating
MAX_HEADER_LEN = 1 << 20


class WireDecodeError(Exception):
    """A framed body failed its integrity checks (bad magic inside a
    declared-framed frame, header CRC mismatch, unknown type/dtype code,
    or declared-vs-actual body length disagreement). Carries the frame's
    ``src``/``tag`` when the header decoded far enough to know them, so
    the transport can still route the corruption marker to the right
    stream (None otherwise)."""

    def __init__(self, message: str, src: Optional[int] = None,
                 tag: Optional[int] = None):
        super().__init__(message)
        self.src = src
        self.tag = tag


# -- env knobs ------------------------------------------------------------


def wire_format_from_env(env=os.environ) -> str:
    """``MPIT_WIRE_FORMAT``: ``framed`` (default — the hot path) or
    ``pickle`` (the historical format; the before-side of the bench
    comparison, and a kill switch)."""
    fmt = env.get("MPIT_WIRE_FORMAT", "framed").strip().lower()
    if fmt not in ("framed", "pickle"):
        raise ValueError(
            f"MPIT_WIRE_FORMAT={fmt!r}: expected 'framed' or 'pickle'"
        )
    return fmt


def quant_mode_from_env(env=os.environ) -> str:
    """``MPIT_WIRE_QUANT``: ``off`` (default), ``bf16``, or ``int8``."""
    mode = env.get("MPIT_WIRE_QUANT", "off").strip().lower()
    if mode not in QUANT_MODES:
        raise ValueError(
            f"MPIT_WIRE_QUANT={mode!r}: expected one of {QUANT_MODES}"
        )
    return mode


def negotiate_enabled_from_env(env=os.environ) -> bool:
    """``MPIT_WIRE_NEGOTIATE=0`` disables the hello exchange entirely —
    the transport then behaves like a pickle-only peer on both sides
    (no hello sent on accept, none awaited after connect, nothing
    framed). This is the mixed-version test lever AND the emergency
    lever for a peer whose stack chokes on unexpected reverse-direction
    bytes."""
    return env.get("MPIT_WIRE_NEGOTIATE", "1").strip() != "0"


def negotiate_timeout_from_env(env=os.environ) -> float:
    """``MPIT_WIRE_NEGOTIATE_TIMEOUT_S``: how long a sender waits for
    the receiver's hello before concluding the peer is pickle-only
    (default 2s; paid once per connection, and only by mixed-version
    pairs — a framed receiver sends its hello at accept time, so the
    wait is one RTT in the common case)."""
    return float(env.get("MPIT_WIRE_NEGOTIATE_TIMEOUT_S", "2.0"))


# -- hello ----------------------------------------------------------------


def encode_hello(version: int = WIRE_FORMAT_VERSION) -> bytes:
    """The receiver-side capability advertisement written on every
    accepted connection."""
    return _HELLO.pack(MAGIC, b"H", version)


def decode_hello(data: bytes) -> Optional[int]:
    """Advertised wire version, or None when ``data`` is not a hello."""
    if len(data) != HELLO_SIZE:
        return None
    try:
        magic, h, version = _HELLO.unpack(data)
    except struct.error:
        return None
    if magic != MAGIC or h != b"H":
        return None
    return version


# -- encode ---------------------------------------------------------------


class _Unencodable(Exception):
    pass


def _encode_value(value: Any, header: bytearray, body: list) -> None:
    if value is None:
        header.append(_T_NONE)
    elif value is True:
        header.append(_T_TRUE)
    elif value is False:
        header.append(_T_FALSE)
    elif type(value) is int:
        mag = abs(value)
        raw = mag.to_bytes((mag.bit_length() + 7) // 8 or 1, "big")
        header.append(_T_INT)
        header.append(1 if value < 0 else 0)
        header += _U32.pack(len(raw))
        header += raw
    elif type(value) is float:
        header.append(_T_FLOAT)
        header += _F64.pack(value)
    elif type(value) is str:
        raw = value.encode("utf-8")
        header.append(_T_STR)
        header += _U32.pack(len(raw))
        header += raw
    elif type(value) is bytes:
        header.append(_T_BYTES)
        header += _U32.pack(len(value))
        header += value
    elif type(value) is tuple or type(value) is list:
        header.append(_T_TUPLE if type(value) is tuple else _T_LIST)
        header += _U32.pack(len(value))
        for item in value:
            _encode_value(item, header, body)
    elif type(value) is np.ndarray:
        code = _DTYPE_CODES.get(value.dtype)
        if code is None or value.ndim > _MAX_DIMS:
            raise _Unencodable
        a = np.ascontiguousarray(value)
        header.append(_T_NDARRAY)
        header.append(code)
        header.append(a.ndim)
        for dim in a.shape:
            header += _U32.pack(dim)
        # memoryview.cast rejects zero-in-shape views; an empty array's
        # body is empty regardless
        body.append(a.data.cast("B") if a.size else b"")
    elif type(value) is QuantArray:
        mode = _QUANT_MODE_CODES.get(value.mode)
        data = value.data
        if (
            mode is None
            or type(data) is not np.ndarray
            or data.ndim > _MAX_DIMS
        ):
            raise _Unencodable
        expected = np.uint16 if value.mode == "bf16" else np.int8
        a = np.ascontiguousarray(data, dtype=expected)
        header.append(_T_QUANT)
        header.append(mode)
        header += _F32.pack(value.scale)
        header.append(a.ndim)
        for dim in a.shape:
            header += _U32.pack(dim)
        body.append(a.data.cast("B") if a.size else b"")
    else:
        # numpy scalars, dataclasses (CorruptedPayload), arbitrary
        # objects: not this codec's business — the caller pickles them
        raise _Unencodable


def encode_frame(
    src: int, tag: int, payload: Any, *, version: int
) -> Optional[list]:
    """Zero-copy frame body for one message, as a buffer list
    ``[preamble+header bytes, array view, ...]`` ready for a vectorized
    write (``sendmsg``), or None when the payload contains something the
    structural codec cannot express (the caller falls back to pickle).

    ``version`` is keyword-required and must name
    :data:`WIRE_FORMAT_VERSION` at every call site (lint rule MPT007).
    """
    if not 0 <= version <= 255:
        raise ValueError(f"wire version {version} out of range")
    header = bytearray()
    body: list = []
    try:
        _encode_value(src, header, body)
        _encode_value(tag, header, body)
        _encode_value(payload, header, body)
    except _Unencodable:
        return None
    if len(header) > MAX_HEADER_LEN:
        return None  # degenerate payload (huge nesting): pickle handles it
    flags = _FLAG_LITTLE_ENDIAN if sys.byteorder == "little" else 0
    preamble = _PREAMBLE.pack(
        MAGIC, version, flags, len(header), zlib.crc32(bytes(header))
    )
    return [preamble + bytes(header), *body]


def frame_nbytes(buffers: list) -> int:
    """Total body length of an :func:`encode_frame` buffer list."""
    return sum(
        b.nbytes if isinstance(b, memoryview) else len(b) for b in buffers
    )


# -- decode ---------------------------------------------------------------


class _Decoder:
    def __init__(self, header: memoryview, body: memoryview):
        self.header = header
        self.h = 0
        self.body = body
        self.b = 0

    def _take(self, n: int) -> memoryview:
        if self.h + n > len(self.header):
            raise WireDecodeError("structural header truncated")
        out = self.header[self.h:self.h + n]
        self.h += n
        return out

    def _u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def _array_buffer(self, dtype: np.dtype, shape: tuple) -> np.ndarray:
        count = 1
        for dim in shape:
            count *= dim
        nbytes = count * dtype.itemsize
        if self.b + nbytes > len(self.body):
            raise WireDecodeError(
                "frame body shorter than its declared arrays"
            )
        arr = np.frombuffer(
            self.body, dtype=dtype, count=count, offset=self.b
        ).reshape(shape)
        self.b += nbytes
        return arr

    def value(self) -> Any:
        code = self._take(1)[0]
        if code == _T_NONE:
            return None
        if code == _T_TRUE:
            return True
        if code == _T_FALSE:
            return False
        if code == _T_INT:
            neg = self._take(1)[0]
            raw = self._take(self._u32())
            mag = int.from_bytes(raw, "big")
            return -mag if neg else mag
        if code == _T_FLOAT:
            return _F64.unpack(self._take(8))[0]
        if code == _T_STR:
            return str(self._take(self._u32()), "utf-8")
        if code == _T_BYTES:
            return bytes(self._take(self._u32()))
        if code in (_T_TUPLE, _T_LIST):
            n = self._u32()
            if n > len(self.header):  # cheap bound: each item is >= 1 byte
                raise WireDecodeError("container length exceeds header")
            items = [self.value() for _ in range(n)]
            return tuple(items) if code == _T_TUPLE else items
        if code == _T_NDARRAY:
            dtype = _CODE_DTYPES.get(self._take(1)[0])
            ndim = self._take(1)[0]
            if dtype is None or ndim > _MAX_DIMS:
                raise WireDecodeError("unknown dtype code or ndim")
            shape = tuple(self._u32() for _ in range(ndim))
            return self._array_buffer(dtype, shape)
        if code == _T_QUANT:
            mode = _CODE_QUANT_MODES.get(self._take(1)[0])
            if mode is None:
                raise WireDecodeError("unknown quantization mode code")
            scale = _F32.unpack(self._take(4))[0]
            ndim = self._take(1)[0]
            if ndim > _MAX_DIMS:
                raise WireDecodeError("quant array ndim out of range")
            shape = tuple(self._u32() for _ in range(ndim))
            dtype = np.dtype(np.uint16 if mode == "bf16" else np.int8)
            return QuantArray(mode, scale, self._array_buffer(dtype, shape))
        raise WireDecodeError(f"unknown structural type code 0x{code:02x}")


def split_preamble(preamble: bytes) -> tuple[int, int, int, int]:
    """(version, flags, header_len, header_crc) from a frame's first
    :data:`PREAMBLE_SIZE` bytes; raises :class:`WireDecodeError` on a
    non-framed or future-versioned preamble."""
    try:
        magic, version, flags, hlen, hcrc = _PREAMBLE.unpack(preamble)
    except struct.error as e:
        raise WireDecodeError(f"short preamble: {e}") from e
    if magic != MAGIC:
        raise WireDecodeError("bad magic in declared-framed frame")
    if version > WIRE_FORMAT_VERSION:
        raise WireDecodeError(
            f"frame version {version} is newer than this reader "
            f"({WIRE_FORMAT_VERSION})"
        )
    if hlen > MAX_HEADER_LEN:
        raise WireDecodeError(f"header length {hlen} exceeds sanity bound")
    return version, flags, hlen, hcrc


def decode_frame(
    flags: int, header_crc: int, header: bytes, body
) -> tuple[int, int, Any]:
    """(src, tag, payload) from a validated-preamble frame. ``body`` is
    any buffer (typically the transport's ``recv_into`` target); returned
    arrays are views into it. Integrity checks, in order: header CRC32,
    body byte order, structural decode, exact body-length consumption —
    any failure raises :class:`WireDecodeError` (with src/tag attached
    once known, so the caller can still route a corruption marker)."""
    if zlib.crc32(header) != header_crc:
        raise WireDecodeError("header CRC mismatch")
    little = bool(flags & _FLAG_LITTLE_ENDIAN)
    if little != (sys.byteorder == "little"):
        # a cross-endian peer would need byte-swapped views; no such host
        # exists in this deployment, so refuse rather than mis-decode
        raise WireDecodeError("frame byte order does not match this host")
    dec = _Decoder(memoryview(header), memoryview(body))
    src = tag = None
    try:
        src = dec.value()
        tag = dec.value()
        if type(src) is not int or type(tag) is not int:
            raise WireDecodeError("frame src/tag are not ints")
        payload = dec.value()
    except WireDecodeError as e:
        e.src = src if type(src) is int else None
        e.tag = tag if type(tag) is int else None
        raise
    if dec.h != len(dec.header):
        raise WireDecodeError(
            "structural header has trailing bytes", src=src, tag=tag
        )
    if dec.b != len(dec.body):
        raise WireDecodeError(
            f"frame body length mismatch: declared arrays consume "
            f"{dec.b} bytes, body holds {len(dec.body)}",
            src=src, tag=tag,
        )
    return src, tag, payload
