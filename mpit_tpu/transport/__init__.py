"""Tagged point-to-point message transport (host-side).

Reference parity (SURVEY.md §2 comp. 1, §3(b)-(c)): the reference's PS
protocol ran on ``MPI_Send/Recv/Isend/Irecv`` with message *tags* and
``ANY_SOURCE`` receives — semantics XLA collectives cannot express
(SURVEY.md §7 "hard parts": no tagged p2p on TPU). This package provides
those semantics on the host, where they belong on TPU: compute stays in
jit-compiled XLA programs, while the asynchronous parameter-server *protocol*
(small, latency-tolerant, order-sensitive) moves over host queues or TCP —
the same split the reference had between Torch compute and MPI transport.

Two implementations behind one interface:

- :class:`InProcTransport` — ranks are threads in one process, delivery via
  an in-memory broker. Used by the host-async PS trainer when all workers
  share one host (the reference's single-node ``mpirun -n N`` case).
- :class:`SocketTransport` — ranks are processes, delivery over TCP
  (DCN-style). Rendezvous via ``MPIT_TRANSPORT_HOSTS`` or localhost ports.

Ordering guarantee (matching MPI): messages between a fixed (src, dst) pair
with the same tag are received in send order; ANY_SOURCE/ANY_TAG receives
scan in arrival order.
"""

from mpit_tpu.transport.base import (  # noqa: F401
    ANY_SOURCE,
    ANY_TAG,
    Message,
    RecvTimeout,
    Transport,
)
from mpit_tpu.transport.chaos import (  # noqa: F401
    ChaosConfig,
    ChaosTransport,
    CorruptedPayload,
    FaultEvent,
    FaultLog,
    config_from_env,
    wrap_transports,
)
from mpit_tpu.transport.inproc import Broker, InProcTransport  # noqa: F401
from mpit_tpu.transport.socket_transport import (  # noqa: F401
    WIRE_PICKLE_PROTOCOL,
    SocketTransport,
)
from mpit_tpu.transport.wire import (  # noqa: F401
    WIRE_FORMAT_VERSION,
    QuantArray,
    WireDecodeError,
    dequantize,
    quantize,
)
